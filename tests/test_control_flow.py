"""Graph control flow: While / Cond / Scan.

Reference parity targets: AbstractSession.java:46-101 (frame-based
Enter/Exit/Switch/Merge execution), redesigned per the reference's own
ADR 0020 (invokable subgraphs) and lowered to lax.while_loop /
lax.cond / lax.scan. Covers: recording-API numerics, data-dependent
trip counts, gradients through scan and cond, a dynamic-iteration RNN,
serde round-trips of loop-bearing graphs, training through scan, and
TF2 functional StatelessWhile/StatelessIf import.
"""
import numpy as np
import pytest

from deeplearning4j_tpu.autodiff import SameDiff


def _while_double_until(sd, x, limit=100.0):
    """Double x until its sum exceeds ``limit``, counting iterations."""
    i0 = sd.constant(np.int32(0), "i0")

    def cond(s, xv, iv):
        return s.invoke("less",
                        [s.invoke("reduce_sum", [xv], name="sum"),
                         s.constant(np.float32(limit))], name="lt")

    def body(s, xv, iv):
        return [xv.mul(s.constant(np.float32(2.0))),
                s.invoke("add", [iv, s.constant(np.int32(1))], name="inc")]

    return sd.while_loop(cond, body, [x, i0], name="w")


class TestWhile:
    def test_data_dependent_trip_count(self):
        sd = SameDiff()
        x = sd.placeholder("x", shape=(3,))
        xf, it = _while_double_until(sd, x)
        out = sd.output({"x": np.ones(3, np.float32)},
                        outputs=[xf.name, it.name])
        # sum doubles from 3: 3*2^5 = 96 < 100 -> one more -> 192, stop;
        # each element then holds 2^6 = 64
        np.testing.assert_allclose(np.asarray(out[xf.name].data),
                                   np.full(3, 64.0))
        assert int(out[it.name].data) == 6
        # a different input takes a different number of iterations —
        # the trip count is data, not structure
        out2 = sd.output({"x": np.full(3, 30.0, np.float32)},
                         outputs=[xf.name, it.name])
        assert int(out2[it.name].data) == 1

    def test_captures_pass_through(self):
        sd = SameDiff()
        x = sd.placeholder("x", shape=())
        k = sd.var("k", value=np.float32(3.0))

        def cond(s, xv, kv):
            return s.invoke("less", [xv, s.constant(np.float32(50.0))],
                            name="lt")

        def body(s, xv, kv):
            return [s.invoke("mul", [xv, kv], name="m")]

        (xf,) = [sd.while_loop(cond, body, [x], captures=[k], name="w")]
        out = sd.output({"x": np.float32(1.0)}, outputs=[xf.name])
        np.testing.assert_allclose(float(out[xf.name].data), 81.0)  # 3^4

    def test_serde_roundtrip(self, tmp_path):
        sd = SameDiff()
        x = sd.placeholder("x", shape=(3,))
        xf, it = _while_double_until(sd, x)
        p = tmp_path / "while.sdz"
        sd.save(str(p))
        sd2 = SameDiff.load(str(p))
        out = sd2.output({"x": np.ones(3, np.float32)},
                         outputs=[xf.name, it.name])
        np.testing.assert_allclose(np.asarray(out[xf.name].data),
                                   np.full(3, 64.0))
        assert int(out[it.name].data) == 6

    def test_body_arity_mismatch_raises(self):
        sd = SameDiff()
        x = sd.placeholder("x", shape=())
        with pytest.raises(ValueError, match="loop vars"):
            sd.while_loop(
                lambda s, v: s.invoke("less",
                                      [v, s.constant(np.float32(1.0))]),
                lambda s, v: [v, v], [x])


class TestCond:
    def _graph(self):
        sd = SameDiff()
        a = sd.placeholder("a", shape=(2,))
        p = sd.placeholder("p", shape=(), dtype="bool")
        r = sd.cond(p,
                    lambda s, v: s.invoke(
                        "mul", [v, s.constant(np.float32(10.0))]),
                    lambda s, v: s.invoke("neg", [v]),
                    [a], name="c")
        return sd, r

    def test_both_branches(self):
        sd, r = self._graph()
        a = np.array([1.0, 2.0], np.float32)
        hi = sd.output({"a": a, "p": np.bool_(True)}, outputs=[r.name])
        lo = sd.output({"a": a, "p": np.bool_(False)}, outputs=[r.name])
        np.testing.assert_allclose(np.asarray(hi[r.name].data), [10.0, 20.0])
        np.testing.assert_allclose(np.asarray(lo[r.name].data), [-1.0, -2.0])

    def test_gradient_through_cond(self):
        sd = SameDiff()
        w = sd.var("w", value=np.array([2.0, 3.0], np.float32))
        p = sd.placeholder("p", shape=(), dtype="bool")
        r = sd.cond(p,
                    lambda s, v: s.invoke("mul", [v, v]),       # w^2
                    lambda s, v: s.invoke(
                        "mul", [v, s.constant(np.float32(5.0))]),
                    [w], name="c")
        loss = sd.invoke("reduce_sum", [r], name="loss")
        sd.set_loss_variables([loss])
        g_true = sd.calculate_gradients({"p": np.bool_(True)})
        g_false = sd.calculate_gradients({"p": np.bool_(False)})
        np.testing.assert_allclose(np.asarray(g_true["w"].data), [4.0, 6.0])
        np.testing.assert_allclose(np.asarray(g_false["w"].data), [5.0, 5.0])


class TestScan:
    def test_rnn_trains_through_scan(self):
        """A tanh-RNN over a scan loop learns to output a target —
        gradients flow through lax.scan into the weight captures."""
        from deeplearning4j_tpu.autodiff import TrainingConfig
        from deeplearning4j_tpu.dataset import DeviceCachedIterator
        from deeplearning4j_tpu.learning.updaters import Adam

        rng = np.random.default_rng(0)
        T, B, D = 6, 8, 4
        sd = SameDiff()
        xs = sd.placeholder("xs", shape=(T, B, D))
        tgt = sd.placeholder("tgt", shape=(B, D))
        h0 = sd.constant(np.zeros((B, D), np.float32), "h0")
        w = sd.var("w", value=(rng.standard_normal((D, D)) * 0.4)
                   .astype(np.float32))

        def body(s, h, x, wv):
            nh = s.invoke("tanh", [s.invoke(
                "add", [s.invoke("matmul", [h, wv], name="hw"), x],
                name="pre")], name="nh")
            return [nh]

        (hf,) = [sd.scan(body, [h0], [xs], [w], name="rnn")]
        loss = sd.invoke("mean_sqerr_loss", [hf, tgt], name="loss")
        sd.set_loss_variables([loss])
        sd.training_config = TrainingConfig(
            updater=Adam(5e-2), data_set_feature_mapping=["xs"],
            data_set_label_mapping=["tgt"])
        # teacher-student: the target IS a reachable RNN output (made by
        # a hidden teacher weight matrix), so the student w can fit it
        X = rng.standard_normal((1, T, B, D)).astype(np.float32)
        w_teacher = (rng.standard_normal((D, D)) * 0.4).astype(np.float32)
        h = np.zeros((B, D), np.float32)
        for t in range(T):
            h = np.tanh(h @ w_teacher + X[0, t])
        Y = h[None]
        hist = sd.fit([([x], [y]) for x, y in zip(X, Y)], epochs=150)
        assert hist.loss_curve.losses[-1] < hist.loss_curve.losses[0] * 0.1

    def test_stacked_outputs(self):
        sd = SameDiff()
        c0 = sd.constant(np.float32(0.0), "c0")
        xs = sd.placeholder("xs", shape=(5,))

        def body(s, c, x):
            nc = s.invoke("add", [c, x], name="nc")
            return [nc, nc]            # carry + per-step output

        cf_, ys = sd.scan(body, [c0], [xs], name="cumsum")
        out = sd.output({"xs": np.arange(1, 6, dtype=np.float32)},
                        outputs=[cf_.name, ys.name])
        np.testing.assert_allclose(float(out[cf_.name].data), 15.0)
        np.testing.assert_allclose(np.asarray(out[ys.name].data),
                                   [1, 3, 6, 10, 15])


def test_random_ops_in_scan_body_get_fresh_keys_per_step():
    """Dropout inside a scan body must draw a DIFFERENT mask each
    timestep (the key is split per step, not replayed)."""
    sd = SameDiff()
    c0 = sd.constant(np.float32(0.0), "c0")
    xs = sd.placeholder("xs", shape=(8, 64))

    def body(s, c, x):
        d = s.invoke("dropout", [x], {"p": 0.5}, name="drop")
        nc = s.invoke("add", [c, s.invoke("reduce_sum", [d], name="sm")],
                      name="nc")
        return [nc, d]

    _, ys = sd.scan(body, [c0], [xs], name="s")
    out = sd.output({"xs": np.ones((8, 64), np.float32)}, outputs=[ys.name])
    masks = np.asarray(out[ys.name].data) != 0
    # all 8 step masks identical is astronomically unlikely (p ~ 2^-448)
    assert not all((masks[i] == masks[0]).all() for i in range(1, 8))


def test_registry_op_names():
    """The recording API lowers onto the registry's structural ops:
    while_loop, cond_branch, scan_loop (ledger EXERCISED pointers)."""
    from deeplearning4j_tpu.ops import registry
    assert registry.has_op("while_loop")
    assert registry.has_op("cond_branch")
    assert registry.has_op("scan_loop")
    sd = SameDiff()
    x = sd.placeholder("x", shape=(3,))
    _while_double_until(sd, x)
    p = sd.placeholder("p", shape=(), dtype="bool")
    sd.cond(p, lambda s, v: s.invoke("neg", [v]),
            lambda s, v: s.invoke("neg", [v]), [x])
    c0 = sd.constant(np.float32(0.0), "c0")
    sd.scan(lambda s, c, x_: [s.invoke("add", [c, x_])], [c0], [x])
    ops = {n.op for n in sd.ops()}
    assert {"while_loop", "cond_branch", "scan_loop"} <= ops


class TestTFImport:
    """TF2 functional control flow: StatelessWhile / StatelessIf nodes
    with FunctionDef library (the format tf.function emits; reference
    imports these through ImportGraph.kt's subgraph machinery)."""

    def _while_pb(self):
        import deeplearning4j_tpu.modelimport.tf_builder as tb
        g = tb.GraphDefBuilder()
        g.placeholder("x", shape=(2,), dtype=np.float32)
        # cond: sum(x) < 100
        cb = tb.GraphDefBuilder()
        cb.const("axes", np.array([0], np.int32))
        cb.node("Sum", "sum", "x", "axes")
        cb.const("limit", np.array(100.0, np.float32))
        cb.node("Less", "less", "sum:output:0", "limit")
        g.add_function(tb.function_def(
            "while_cond", [("x", np.float32)],
            [("ret", "less:z:0", np.bool_)], cb))
        # body: x * 2
        bb = tb.GraphDefBuilder()
        bb.const("two", np.array(2.0, np.float32))
        bb.node("Mul", "mul", "x", "two")
        g.add_function(tb.function_def(
            "while_body", [("x", np.float32)],
            [("ret", "mul:z:0", np.float32)], bb))
        g.node("StatelessWhile", "loop", "x",
               cond=("func", "while_cond"), body=("func", "while_body"))
        return g.build()

    def test_stateless_while(self):
        from deeplearning4j_tpu.modelimport.tf_import import import_tf_graph
        sd = import_tf_graph(self._while_pb())
        out = sd.output({"x": np.array([1.0, 1.0], np.float32)},
                        outputs=["loop"])
        np.testing.assert_allclose(np.asarray(out["loop"].data),
                                   [64.0, 64.0])

    def test_stateless_if(self):
        import deeplearning4j_tpu.modelimport.tf_builder as tb
        from deeplearning4j_tpu.modelimport.tf_import import import_tf_graph
        g = tb.GraphDefBuilder()
        g.placeholder("p", shape=(), dtype=np.bool_)
        g.placeholder("v", shape=(2,), dtype=np.float32)
        then_b = tb.GraphDefBuilder()
        then_b.const("ten", np.array(10.0, np.float32))
        then_b.node("Mul", "mul", "v", "ten")
        g.add_function(tb.function_def(
            "then_f", [("v", np.float32)],
            [("ret", "mul:z:0", np.float32)], then_b))
        else_b = tb.GraphDefBuilder()
        else_b.node("Neg", "neg", "v")
        g.add_function(tb.function_def(
            "else_f", [("v", np.float32)],
            [("ret", "neg:y:0", np.float32)], else_b))
        g.node("StatelessIf", "branch", "p", "v",
               then_branch=("func", "then_f"),
               else_branch=("func", "else_f"))
        sd = import_tf_graph(g.build())
        v = np.array([1.0, 2.0], np.float32)
        hi = sd.output({"p": np.bool_(True), "v": v}, outputs=["branch"])
        lo = sd.output({"p": np.bool_(False), "v": v}, outputs=["branch"])
        np.testing.assert_allclose(np.asarray(hi["branch"].data),
                                   [10.0, 20.0])
        np.testing.assert_allclose(np.asarray(lo["branch"].data),
                                   [-1.0, -2.0])

    def test_missing_function_is_actionable(self):
        import deeplearning4j_tpu.modelimport.tf_builder as tb
        from deeplearning4j_tpu.modelimport.tf_import import (
            TFImportError, import_tf_graph)
        g = tb.GraphDefBuilder()
        g.placeholder("x", shape=(2,), dtype=np.float32)
        g.node("StatelessWhile", "loop", "x",
               cond=("func", "nope"), body=("func", "nada"))
        with pytest.raises(TFImportError, match="nope"):
            import_tf_graph(g.build())
