"""Test harness configuration.

Reference parity: the reference runs its consolidated platform-tests module
against a backend selected by property (SURVEY.md §4). Here tests run on the
CPU backend with a virtual 8-device mesh so multi-chip sharding logic is
exercised without TPU hardware (XLA --xla_force_host_platform_device_count),
exactly how multi-device code must be CI-tested for TPU.
"""
import os

# Force CPU: the session environment pre-sets JAX_PLATFORMS to the TPU
# tunnel; unit tests must run on the virtual 8-device CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
# The reference treats DOUBLE/INT64 as first-class dtypes; enable 64-bit on
# the CPU test backend. TPU runs keep jax's 32-bit defaults (MXU-friendly).
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax

jax.config.update("jax_enable_x64", True)
# The env var alone does not displace the preinstalled TPU-tunnel plugin;
# the config update does.
jax.config.update("jax_platforms", "cpu")

import signal

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(12345)


CHAOS_DEFAULT_TIMEOUT = 120


@pytest.hookimpl(wrapper=True)
def pytest_runtest_call(item):
    """Individually timeout-guard @pytest.mark.chaos tests: fault
    injection that wedges a run (a retry loop that never converges, a
    signal handler that deadlocks) must fail ONE test, not hang tier-1.
    SIGALRM-based, so it interrupts even a blocked main thread; chaos
    tests run on the main thread (pytest default) as required."""
    marker = item.get_closest_marker("chaos")
    if marker is None or not hasattr(signal, "SIGALRM"):
        return (yield)
    timeout = int(marker.kwargs.get("timeout", CHAOS_DEFAULT_TIMEOUT))

    def _expired(signum, frame):
        raise TimeoutError(
            f"chaos test exceeded its {timeout}s timeout guard")

    prev = signal.signal(signal.SIGALRM, _expired)
    signal.alarm(timeout)
    try:
        return (yield)
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, prev)
