"""Wave-2 layers: finite-difference gradient checks, serde round-trips,
and end-to-end training (reference test strategy: gradientcheck/* +
IntegrationTestRunner overfit sanity)."""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.learning.updaters import Adam
from deeplearning4j_tpu.nn import (
    CapsuleLayer, CapsuleStrengthLayer, CenterLossOutputLayer, CnnLossLayer,
    ConvolutionLayer, Cropping1DLayer, DenseLayer, DepthToSpaceLayer,
    DotProductAttentionLayer, ElementWiseMultiplicationLayer, FrozenLayer,
    GravesLSTMLayer, GRULayer, InputType, LossLayer, MultiLayerNetwork,
    NeuralNetConfiguration, OutputLayer, PReLULayer, PrimaryCapsulesLayer,
    RecurrentAttentionLayer, RepeatVectorLayer, RnnLossLayer,
    SpaceToDepthLayer, Subsampling1DLayer, Upsampling1DLayer,
    Upsampling3DLayer, VariationalAutoencoderLayer, Yolo2OutputLayer,
    ZeroPadding1DLayer, ZeroPadding3DLayer)
from deeplearning4j_tpu.nn.layers import BaseLayer
from deeplearning4j_tpu.ops import registry


def _net(layers, itype, lr=1e-2, seed=0):
    b = NeuralNetConfiguration.builder().seed(seed).updater(Adam(lr)).list()
    for l in layers:
        b = b.layer(l)
    return MultiLayerNetwork(b.set_input_type(itype).build()).init()


def _numeric_grad(f, x, eps=1e-4):
    g = np.zeros_like(x)
    it = np.nditer(x, flags=["multi_index"])
    while not it.finished:
        i = it.multi_index
        xp = x.copy(); xp[i] += eps
        xm = x.copy(); xm[i] -= eps
        g[i] = (f(xp) - f(xm)) / (2 * eps)
        it.iternext()
    return g


# --- gradient checks on the new ops ----------------------------------------
def test_capsule_routing_grad_check():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 4, 3).astype(np.float64) * 0.5
    w = rng.randn(4, 3, 3, 2).astype(np.float64) * 0.5
    fn = registry.get_op("capsule_routing").fn

    def loss_w(wv):
        return float(jnp.sum(jnp.square(fn(jnp.asarray(x), jnp.asarray(wv),
                                           routings=3))))

    ana = np.asarray(jax.grad(
        lambda wv: jnp.sum(jnp.square(fn(jnp.asarray(x), wv, routings=3))))(
        jnp.asarray(w)))
    num = _numeric_grad(loss_w, w, eps=1e-5)
    np.testing.assert_allclose(ana, num, rtol=1e-4, atol=1e-6)


def test_graves_lstm_grad_check():
    rng = np.random.RandomState(1)
    u, n_in = 3, 2
    x = rng.randn(2, 4, n_in).astype(np.float64) * 0.5
    w_ih = rng.randn(n_in, 4 * u) * 0.3
    w_hh = rng.randn(u, 4 * u) * 0.3
    w_p = rng.randn(3, u) * 0.2
    b = np.zeros(4 * u)
    h0 = np.zeros((2, u)); c0 = np.zeros((2, u))
    fn = registry.get_op("graves_lstm_layer").fn

    def out_sum(wp):
        o, _, _ = fn(jnp.asarray(x), jnp.asarray(h0), jnp.asarray(c0),
                     jnp.asarray(w_ih), jnp.asarray(w_hh), wp,
                     jnp.asarray(b))
        return jnp.sum(jnp.square(o))

    ana = np.asarray(jax.grad(out_sum)(jnp.asarray(w_p)))
    num = _numeric_grad(lambda wp: float(out_sum(jnp.asarray(wp))), w_p,
                        eps=1e-5)
    np.testing.assert_allclose(ana, num, rtol=1e-4, atol=1e-6)
    # peepholes actually matter: zero vs nonzero peephole output differ
    o1, _, _ = fn(jnp.asarray(x), jnp.asarray(h0), jnp.asarray(c0),
                  jnp.asarray(w_ih), jnp.asarray(w_hh),
                  jnp.zeros_like(jnp.asarray(w_p)), jnp.asarray(b))
    o2, _, _ = fn(jnp.asarray(x), jnp.asarray(h0), jnp.asarray(c0),
                  jnp.asarray(w_ih), jnp.asarray(w_hh), jnp.asarray(w_p),
                  jnp.asarray(b))
    assert float(jnp.max(jnp.abs(o1 - o2))) > 1e-4


def test_yolo2_loss_grad_and_values():
    rng = np.random.RandomState(2)
    B, H, W, A, C = 2, 4, 4, 2, 3
    pred = rng.randn(B, H, W, A * (5 + C)).astype(np.float64) * 0.3
    labels = np.zeros((B, H, W, 4 + C))
    # one object in cell (1,2) of each batch elem, class 1
    labels[:, 1, 2, 0:4] = [2.0, 1.0, 3.0, 2.0]   # x1,y1,x2,y2 grid units
    labels[:, 1, 2, 4 + 1] = 1.0
    fn = registry.get_op("yolo2_loss").fn
    anchors = (1.0, 1.0, 2.0, 2.0)
    loss = float(fn(jnp.asarray(pred), jnp.asarray(labels), anchors=anchors))
    assert np.isfinite(loss) and loss > 0
    ana = np.asarray(jax.grad(
        lambda p: fn(p, jnp.asarray(labels), anchors=anchors))(
        jnp.asarray(pred)))
    assert np.isfinite(ana).all()
    # numeric spot-check on a few entries
    flat_idx = [(0, 1, 2, 3), (1, 1, 2, 7), (0, 0, 0, 4)]
    def f(p):
        return float(fn(jnp.asarray(p), jnp.asarray(labels), anchors=anchors))
    for idx in flat_idx:
        pp = pred.copy(); pp[idx] += 1e-5
        pm = pred.copy(); pm[idx] -= 1e-5
        num = (f(pp) - f(pm)) / 2e-5
        np.testing.assert_allclose(ana[idx], num, rtol=2e-3, atol=1e-7)


# --- training e2e -----------------------------------------------------------
def test_vae_trains_unsupervised():
    rng = np.random.RandomState(0)
    X = rng.randn(64, 8).astype(np.float32)
    net = _net([VariationalAutoencoderLayer(
        n_out=3, encoder_layer_sizes=(16,), decoder_layer_sizes=(16,),
        kl_weight=0.1)], InputType.feed_forward(8), lr=5e-3)
    Y = np.zeros((64, 3), np.float32)    # labels unused by the ELBO loss
    h = net.fit(X, Y, epochs=30, batch_size=32)
    losses = h.loss_curve.losses
    assert losses[-1] < losses[0] * 0.8, losses[::10]
    latent = np.asarray(net.output(X[:5]).data)
    assert latent.shape == (5, 3)


def test_capsnet_trains():
    rng = np.random.RandomState(0)
    X = rng.rand(32, 1, 8, 8).astype(np.float32)
    y = (X.mean((1, 2, 3)) > X.mean()).astype(int)
    Y = np.eye(2, dtype=np.float32)[y]
    net = _net([
        ConvolutionLayer(n_out=8, kernel_size=(3, 3), activation="relu",
                         convolution_mode="VALID"),
        PrimaryCapsulesLayer(capsules=4, capsule_dimensions=4,
                             kernel_size=(3, 3), stride=(2, 2)),
        CapsuleLayer(capsules=2, capsule_dimensions=4, routings=2),
        CapsuleStrengthLayer(),
        LossLayer(loss_function="MSE", activation="identity"),
    ], InputType.convolutional(8, 8, 1), lr=5e-3)
    h = net.fit(X, Y, epochs=25, batch_size=32)
    assert h.loss_curve.losses[-1] < h.loss_curve.losses[0]


def test_yolo2_output_layer_trains():
    rng = np.random.RandomState(0)
    B, H, W, A, C = 8, 4, 4, 2, 2
    X = rng.rand(B, 3, 16, 16).astype(np.float32)
    labels = np.zeros((B, 4 + C, H, W), np.float32)
    labels[:, 0:4, 2, 2] = np.array([1.5, 1.5, 2.5, 2.5], np.float32)
    labels[:, 4, 2, 2] = 1.0
    net = _net([
        ConvolutionLayer(n_out=16, kernel_size=(3, 3), stride=(2, 2),
                         activation="relu"),
        ConvolutionLayer(n_out=A * (5 + C), kernel_size=(3, 3),
                         stride=(2, 2)),
        Yolo2OutputLayer(anchors=(1.0, 1.0, 2.0, 2.0)),
    ], InputType.convolutional(16, 16, 3), lr=1e-3)
    h = net.fit(X, labels, epochs=20, batch_size=8)
    assert h.loss_curve.losses[-1] < h.loss_curve.losses[0]
    out = np.asarray(net.output(X[:2]).data)
    assert out.shape == (2, A * (5 + C), H, W)    # NCHW external contract


def test_attention_layers_train():
    rng = np.random.RandomState(0)
    X = rng.randn(32, 6, 5).astype(np.float32)    # (B, T, C)
    y = (X[:, :, 0].mean(1) > 0).astype(int)
    Y = np.eye(2, dtype=np.float32)[y]
    for layer in (DotProductAttentionLayer(n_out=8, n_heads=2),
                  RecurrentAttentionLayer(n_out=8)):
        net = _net([layer,
                    GRULayer(n_out=8, return_sequences=False),
                    OutputLayer(n_out=2, loss_function="MCXENT")],
                   InputType.recurrent(5, 6), lr=5e-3)
        h = net.fit(X, Y, epochs=15, batch_size=32)
        assert h.loss_curve.losses[-1] < h.loss_curve.losses[0], type(layer)


def test_graves_lstm_trains():
    rng = np.random.RandomState(0)
    X = rng.randn(32, 5, 4).astype(np.float32)
    Y = np.eye(2, dtype=np.float32)[(X.sum((1, 2)) > 0).astype(int)]
    net = _net([GravesLSTMLayer(n_out=8, return_sequences=False),
                OutputLayer(n_out=2, loss_function="MCXENT")],
               InputType.recurrent(4, 5), lr=1e-2)
    h = net.fit(X, Y, epochs=15, batch_size=32)
    assert h.loss_curve.losses[-1] < h.loss_curve.losses[0]


def test_center_loss_output_layer():
    rng = np.random.RandomState(0)
    X = rng.randn(32, 6).astype(np.float32)
    Y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 32)]
    net = _net([DenseLayer(n_out=8, activation="relu"),
                CenterLossOutputLayer(n_out=3, lambda_=0.1)],
               InputType.feed_forward(6), lr=1e-2)
    h = net.fit(X, Y, epochs=20, batch_size=32)
    assert h.loss_curve.losses[-1] < h.loss_curve.losses[0]
    # centers updated away from init
    sd = net.samediff
    centers = [n for n in sd.state_vars_map() if "centers" in n]
    assert centers and float(np.abs(
        np.asarray(sd.state_vars_map()[centers[0]])).sum()) > 0


def test_frozen_layer_freezes():
    rng = np.random.RandomState(0)
    X = rng.randn(16, 4).astype(np.float32)
    Y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 16)]
    net = _net([FrozenLayer(layer=DenseLayer(n_out=8, activation="relu")),
                OutputLayer(n_out=2, loss_function="MCXENT")],
               InputType.feed_forward(4))
    sd = net.samediff
    frozen = [n for n in sd._vars if "dense" in n and n.endswith("_W")]
    assert frozen
    before = np.asarray(sd.get_arr_for_var(frozen[0]).data).copy()
    assert frozen[0] not in sd.trainable_params()
    net.fit(X, Y, epochs=3, batch_size=16)
    after = np.asarray(net.samediff.get_arr_for_var(frozen[0]).data)
    np.testing.assert_array_equal(before, after)


# --- structural layers: shapes + loss flows ---------------------------------
def test_structural_shapes():
    rng = np.random.RandomState(0)
    # rnn family
    Xr = rng.randn(4, 6, 3).astype(np.float32)
    net = _net([ZeroPadding1DLayer(padding=(1, 2)),
                Cropping1DLayer(cropping=(1, 0)),
                Upsampling1DLayer(size=2),
                Subsampling1DLayer(kernel_size=2),
                GlobalP := __import__("deeplearning4j_tpu.nn",
                                      fromlist=["GlobalPoolingLayer"]
                                      ).GlobalPoolingLayer(),
                OutputLayer(n_out=2, loss_function="MCXENT")],
               InputType.recurrent(3, 6))
    out = np.asarray(net.output(Xr).data)
    assert out.shape == (4, 2)

    # cnn family: s2d -> d2s round-trips shape
    Xc = rng.randn(2, 4, 8, 8).astype(np.float32)
    net2 = _net([SpaceToDepthLayer(block_size=2),
                 DepthToSpaceLayer(block_size=2),
                 CnnLossLayer(loss_function="MSE")],
                InputType.convolutional(8, 8, 4))
    oc = np.asarray(net2.output(Xc).data)
    assert oc.shape == (2, 4, 8, 8)

    # ff family
    Xf = rng.randn(4, 5).astype(np.float32)
    net3 = _net([ElementWiseMultiplicationLayer(),
                 PReLULayer(),
                 RepeatVectorLayer(n=3),
                 RnnLossLayer(loss_function="MSE", activation="identity")],
                InputType.feed_forward(5))
    of = np.asarray(net3.output(Xf).data)
    assert of.shape == (4, 3, 5)

    # cnn3d family
    X3 = rng.randn(2, 1, 2, 4, 4).astype(np.float32)
    net4 = _net([Upsampling3DLayer(size=(2, 1, 1)),
                 ZeroPadding3DLayer(padding=(0, 0, 1, 1, 0, 0)),
                 __import__("deeplearning4j_tpu.nn",
                            fromlist=["GlobalPoolingLayer"]
                            ).GlobalPoolingLayer(),
                 OutputLayer(n_out=2, loss_function="MCXENT")],
                InputType.convolutional3d(2, 4, 4, 1))
    o3 = np.asarray(net4.output(X3).data)
    assert o3.shape == (2, 2)


def test_wave2_serde_roundtrip():
    layers = [
        VariationalAutoencoderLayer(n_out=3, encoder_layer_sizes=(8,),
                                    decoder_layer_sizes=(8,)),
        Yolo2OutputLayer(anchors=(1.0, 2.0, 3.0, 4.0), lambda_coord=3.0),
        PrimaryCapsulesLayer(capsules=4, capsule_dimensions=8),
        CapsuleLayer(capsules=10, capsule_dimensions=16, routings=2),
        CapsuleStrengthLayer(),
        DotProductAttentionLayer(n_out=8, n_heads=2),
        RecurrentAttentionLayer(n_out=8),
        GravesLSTMLayer(n_out=8, return_sequences=False),
        GRULayer(n_out=8),
        RepeatVectorLayer(n=4),
        PReLULayer(),
        ElementWiseMultiplicationLayer(activation="tanh"),
        Subsampling1DLayer(kernel_size=3, pooling_type="AVG"),
        ZeroPadding1DLayer(padding=(2, 0)),
        Cropping1DLayer(cropping=(1, 1)),
        Upsampling1DLayer(size=3),
        Upsampling3DLayer(size=(1, 2, 2)),
        ZeroPadding3DLayer(),
        SpaceToDepthLayer(block_size=4),
        DepthToSpaceLayer(block_size=2),
        CnnLossLayer(loss_function="L1"),
        RnnLossLayer(loss_function="MSE"),
        CenterLossOutputLayer(n_out=5, alpha=0.1, lambda_=0.3),
        FrozenLayer(layer=DenseLayer(n_out=7, activation="relu")),
    ]
    for l in layers:
        d = l.to_json()
        l2 = BaseLayer.from_json(d)
        assert type(l2) is type(l)
        if isinstance(l, FrozenLayer):
            assert type(l2.layer) is DenseLayer and l2.layer.n_out == 7
        else:
            for f in dataclasses.fields(l):
                assert getattr(l2, f.name) == getattr(l, f.name), \
                    (type(l).__name__, f.name)


def test_layer_config_count_target():
    """VERDICT round-4 target: >= 55 layer/vertex config types."""
    from deeplearning4j_tpu.nn.graph import VERTEX_TYPES
    from deeplearning4j_tpu.nn.layers import LAYER_TYPES
    assert len(LAYER_TYPES) + len(VERTEX_TYPES) >= 55, \
        (len(LAYER_TYPES), len(VERTEX_TYPES))
