"""GPT decoder zoo model + SameDiff remat_scope + fused SDPA op.

Covers the compute-dense flagship path benched as gpt_medium: the fused
scaled_dot_product_attention op against a numpy reference, remat-scope
gradient equivalence (checkpointing must change memory, never numerics),
serde round-trip of the remat group field, and GPT_TINY learning.
"""
import numpy as np
import pytest

from deeplearning4j_tpu.ops import registry


def _np_sdpa(q, k, v, causal=False, mask=None):
    d = q.shape[-1]
    s = q.astype(np.float64) @ np.swapaxes(k.astype(np.float64), -1, -2)
    s /= np.sqrt(d)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        cm = np.tril(np.ones((sq, sk), bool), k=sk - sq)
        s = np.where(cm, s, -np.inf)
    if mask is not None:
        s = np.where(mask.astype(bool), s, -np.inf)
    e = np.exp(s - s.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    return p @ v.astype(np.float64)


class TestSDPA:
    def setup_method(self):
        self.rng = np.random.default_rng(7)
        self.q = self.rng.standard_normal((2, 3, 5, 8)).astype(np.float32)
        self.k = self.rng.standard_normal((2, 3, 5, 8)).astype(np.float32)
        self.v = self.rng.standard_normal((2, 3, 5, 8)).astype(np.float32)

    def test_matches_numpy_plain(self):
        out = registry.exec_op("scaled_dot_product_attention",
                               self.q, self.k, self.v)
        np.testing.assert_allclose(np.asarray(out.data),
                                   _np_sdpa(self.q, self.k, self.v),
                                   rtol=1e-5, atol=1e-5)

    def test_matches_numpy_causal(self):
        out = registry.exec_op("scaled_dot_product_attention",
                               self.q, self.k, self.v, causal=True)
        np.testing.assert_allclose(
            np.asarray(out.data),
            _np_sdpa(self.q, self.k, self.v, causal=True),
            rtol=1e-5, atol=1e-5)

    def test_causal_first_row_attends_only_self(self):
        out = np.asarray(registry.exec_op(
            "scaled_dot_product_attention", self.q, self.k, self.v,
            causal=True).data)
        np.testing.assert_allclose(out[..., 0, :], self.v[..., 0, :],
                                   rtol=1e-5, atol=1e-5)

    def test_padding_mask(self):
        mask = np.ones((2, 1, 1, 5), np.float32)
        mask[..., 3:] = 0          # keys 3,4 masked out
        out = registry.exec_op("scaled_dot_product_attention",
                               self.q, self.k, self.v, mask=mask)
        np.testing.assert_allclose(
            np.asarray(out.data),
            _np_sdpa(self.q, self.k, self.v, mask=mask),
            rtol=1e-5, atol=1e-5)

    def test_bf16_inputs_finite_and_close(self):
        import jax.numpy as jnp
        qb = jnp.asarray(self.q, jnp.bfloat16)
        kb = jnp.asarray(self.k, jnp.bfloat16)
        vb = jnp.asarray(self.v, jnp.bfloat16)
        out = np.asarray(registry.get_op("scaled_dot_product_attention")
                         (qb, kb, vb, causal=True), np.float32)
        ref = _np_sdpa(self.q, self.k, self.v, causal=True)
        assert np.isfinite(out).all()
        np.testing.assert_allclose(out, ref, rtol=0.1, atol=0.1)


class TestRematScope:
    def _mlp(self, remat):
        from deeplearning4j_tpu.autodiff import SameDiff
        rng = np.random.default_rng(3)
        sd = SameDiff()
        x = sd.placeholder("x", shape=(4, 8))
        cur, n_in = x, 8
        for i in range(3):
            ctx = sd.remat_scope(f"blk{i}") if remat else _null()
            with ctx:
                w = sd.var(f"w{i}", value=rng.standard_normal(
                    (n_in, 8)).astype(np.float32) * 0.3)
                cur = sd.nn.relu(cur.mmul(w), name=f"h{i}")
        loss = sd.invoke("reduce_sum", [cur.mul(cur)], name="loss")
        sd.set_loss_variables([loss])
        return sd

    def test_grads_identical_with_and_without_remat(self):
        x = np.random.default_rng(0).standard_normal((4, 8)).astype(np.float32)
        g_plain = self._mlp(False).calculate_gradients({"x": x})
        g_remat = self._mlp(True).calculate_gradients({"x": x})
        assert set(g_plain) == set(g_remat)
        for n in g_plain:
            np.testing.assert_allclose(np.asarray(g_plain[n].data),
                                       np.asarray(g_remat[n].data),
                                       rtol=1e-6, atol=1e-6,
                                       err_msg=n)

    def test_forward_identical(self):
        x = np.random.default_rng(0).standard_normal((4, 8)).astype(np.float32)
        o1 = self._mlp(False).output({"x": x}, outputs=["loss"])
        o2 = self._mlp(True).output({"x": x}, outputs=["loss"])
        np.testing.assert_allclose(float(o1["loss"].data),
                                   float(o2["loss"].data), rtol=1e-6)

    def test_group_serde_roundtrip(self, tmp_path):
        sd = self._mlp(True)
        groups = [n.group for n in sd.ops()]
        assert any(g is not None for g in groups)
        p = tmp_path / "remat.sdz"
        sd.save(str(p))
        from deeplearning4j_tpu.autodiff import SameDiff
        sd2 = SameDiff.load(str(p))
        assert [n.group for n in sd2.ops()] == groups
        x = np.random.default_rng(0).standard_normal((4, 8)).astype(np.float32)
        np.testing.assert_allclose(
            float(sd.output({"x": x}, outputs=["loss"])["loss"].data),
            float(sd2.output({"x": x}, outputs=["loss"])["loss"].data),
            rtol=1e-6)

    def test_remat_with_random_op_deterministic_per_trace(self):
        """Dropout inside a remat scope: forward and recomputed-backward
        must see the SAME mask (jax.checkpoint replays the fold_in key)."""
        from deeplearning4j_tpu.autodiff import SameDiff
        rng = np.random.default_rng(1)
        sd = SameDiff()
        x = sd.placeholder("x", shape=(32, 16))
        with sd.remat_scope("blk"):
            w = sd.var("w", value=rng.standard_normal(
                (16, 16)).astype(np.float32) * 0.3)
            h = sd.invoke("dropout", [x.mmul(w)], {"p": 0.5}, name="drop")
        loss = sd.invoke("reduce_sum", [h.mul(h)], name="loss")
        sd.set_loss_variables([loss])
        xv = rng.standard_normal((32, 16)).astype(np.float32)
        g = sd.calculate_gradients({"x": xv})
        assert np.isfinite(np.asarray(g["w"].data)).all()


class TestGPT:
    def test_tiny_overfits(self):
        from deeplearning4j_tpu.autodiff import TrainingConfig
        from deeplearning4j_tpu.dataset import DeviceCachedIterator
        from deeplearning4j_tpu.learning.updaters import Adam
        from deeplearning4j_tpu.zoo.gpt import GPT_TINY, build_gpt

        sd = build_gpt(GPT_TINY, batch=4, seq_len=16)
        sd.training_config = TrainingConfig(
            updater=Adam(1e-3),
            data_set_feature_mapping=["input_ids"],
            data_set_label_mapping=["targets"])
        rng = np.random.default_rng(0)
        ids = rng.integers(0, GPT_TINY.vocab_size, (8, 16)).astype(np.int32)
        tgt = rng.integers(0, GPT_TINY.vocab_size, (8, 16)).astype(np.int32)
        it = DeviceCachedIterator([ids], [tgt], batch_size=4)
        h = sd.fit(it, epochs=120)
        assert h.loss_curve.losses[-1] < h.loss_curve.losses[0] * 0.2

    def test_logits_shape_and_causality(self):
        """Changing a future token must not change past logits (the
        causal-mask end-to-end check)."""
        from deeplearning4j_tpu.zoo.gpt import GPT_TINY, build_gpt
        sd = build_gpt(GPT_TINY, batch=2, seq_len=8)
        rng = np.random.default_rng(0)
        ids = rng.integers(0, GPT_TINY.vocab_size, (2, 8)).astype(np.int32)
        tgt = np.zeros((2, 8), np.int32)
        base = np.asarray(sd.output({"input_ids": ids, "targets": tgt},
                                    outputs=["logits"])["logits"].data)
        ids2 = ids.copy()
        ids2[:, -1] = (ids2[:, -1] + 1) % GPT_TINY.vocab_size
        pert = np.asarray(sd.output({"input_ids": ids2, "targets": tgt},
                                    outputs=["logits"])["logits"].data)
        np.testing.assert_allclose(base[:, :-1], pert[:, :-1],
                                   rtol=1e-5, atol=1e-5)
        assert base.shape == (2, 8, GPT_TINY.vocab_size)

    def test_weight_tying(self):
        from deeplearning4j_tpu.zoo.gpt import GPT_TINY, build_gpt
        sd = build_gpt(GPT_TINY, batch=2, seq_len=8)
        names = [v.name for v in sd.variables()]
        assert "wte" in names and "lm_head" not in names


def _null():
    import contextlib
    return contextlib.nullcontext()
