"""Registry reachability + ADVICE-fix regression tests.

Guards against the round-3 failure mode where a whole op module
(ops/tf_compat.py) was merged but never imported by
registry._ensure_loaded(), leaving its ops unreachable.
"""
import importlib
import pathlib
import pkgutil

import numpy as np
import pytest

from deeplearning4j_tpu.ops import registry

OPS_DIR = pathlib.Path(registry.__file__).parent


def test_every_ops_module_is_loaded_by_registry():
    """Every module under deeplearning4j_tpu/ops that registers ops must be
    imported by _ensure_loaded() — i.e. after get-op machinery runs, each
    module's @op-decorated functions are reachable by name."""
    registry._ensure_loaded()
    loaded_names = set(registry.op_names())
    for info in pkgutil.iter_modules([str(OPS_DIR)]):
        if info.name in ("registry",):
            continue
        mod = importlib.import_module(f"deeplearning4j_tpu.ops.{info.name}")
        # find names registered by this module's source
        src = pathlib.Path(mod.__file__).read_text()
        import re
        declared = re.findall(r'@op\(\s*"([^"]+)"', src)
        missing = [d for d in declared if not registry.has_op(d)]
        assert not missing, (
            f"ops module {info.name!r} declares ops not reachable via the "
            f"registry (is it missing from _ensure_loaded()?): {missing}")


def test_tf_compat_category_present():
    cats = registry.ops_by_category()
    assert "compat" in cats
    assert "tf_reshape" in cats["compat"]
    assert registry.has_op("tf_reshape")


def test_tf_reduce_empty_axes_is_identity():
    """TF semantics: empty reduction_indices tensor => identity."""
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    out = registry.exec_op("tf_reduce", x, np.array([], dtype=np.int32),
                           reduction="mean")
    assert out.shape == (2, 3, 4)
    np.testing.assert_allclose(np.asarray(out.data), x)
    # scalar 0-d axes tensor still means that axis
    out2 = registry.exec_op("tf_reduce", x, np.array(0, dtype=np.int32),
                            reduction="sum")
    assert out2.shape == (3, 4)


def test_tf_gather_negative_axis():
    p = np.arange(12, dtype=np.float32).reshape(3, 4)
    idx = np.array([0, 2], dtype=np.int32)
    out = registry.exec_op("tf_gather", p, idx, np.array(-1))
    assert out.shape == (3, 2)
    np.testing.assert_allclose(np.asarray(out.data), p[:, [0, 2]])


def test_protowire_truncation_raises():
    from deeplearning4j_tpu.modelimport.protowire import Fields
    # field 1, wire type 2 (bytes), declared length 100, only 2 bytes present
    data = bytes([0x0A, 100, 0x01, 0x02])
    with pytest.raises(ValueError, match="truncated"):
        Fields(data)


def test_attrvalue_empty_list_has_all_keys():
    from deeplearning4j_tpu.modelimport.protowire import Fields
    from deeplearning4j_tpu.modelimport.tf_pb import AttrValue
    av = AttrValue(Fields(b""))
    lst = av.list
    assert set(lst.keys()) >= {"s", "i", "f", "b", "type", "shape"}


def test_op_trace_toggle_list_print_replay():
    """(reference: NativeOps toggleOpTrace/listOpTraces/printOpTrace +
    ADR 0024 'replayable as a SameDiff graph')"""
    import numpy as np
    from deeplearning4j_tpu.ops import (
        exec_op, list_op_traces, print_op_trace, purge_op_trace,
        replay_op_trace_as_graph, toggle_op_trace)
    purge_op_trace()
    toggle_op_trace(True)
    try:
        a = np.ones((2, 3), np.float32)
        exec_op("add", a, a)
        exec_op("reduce_sum", a, axis=(1,))
    finally:
        toggle_op_trace(False)
    traces = list_op_traces()
    assert [t.op for t in traces] == ["add", "reduce_sum"]
    assert traces[0].input_shapes == ((2, 3), (2, 3))
    lines = []
    print_op_trace(print_fn=lines.append)
    assert len(lines) == 2 and "add" in lines[0]
    # replay as a graph and execute it
    sd, outs = replay_op_trace_as_graph()
    res = sd.output({"t0_in0": a, "t0_in1": a, "t1_in0": a},
                    [outs[0].name, outs[1].name])
    np.testing.assert_allclose(np.asarray(res[outs[0].name]), 2.0)
    purge_op_trace()
    # disabled -> nothing recorded
    exec_op("add", a, a)
    assert list_op_traces() == []


def test_op_trace_scalar_literals_replay():
    """Regression: scalar positional args are recorded as literals and
    survive replay."""
    import numpy as np
    from deeplearning4j_tpu.ops import (
        exec_op, purge_op_trace, replay_op_trace_as_graph, toggle_op_trace)
    purge_op_trace()
    toggle_op_trace(True)
    try:
        exec_op("add", np.ones((2, 3), np.float32), 2.0)
    finally:
        toggle_op_trace(False)
    sd, outs = replay_op_trace_as_graph()
    res = sd.output({"t0_in0": np.ones((2, 3), np.float32)},
                    [outs[0].name])
    np.testing.assert_allclose(np.asarray(res[outs[0].name]), 3.0)
    purge_op_trace()
