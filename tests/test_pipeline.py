"""Pipeline parallelism: GPipe schedule numerics vs sequential baseline.

Runs on the 8-device CPU mesh (conftest sets
--xla_force_host_platform_device_count=8).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.parallel.mesh import DeviceMesh
from deeplearning4j_tpu.parallel.pipeline import (
    merge_microbatches, pipeline_forward, pipeline_train_step,
    place_stage_params, sequential_forward, split_microbatches)

F = 16   # feature width


def _stage_fn(params, x):
    w, b = params["w"], params["b"]
    return jnp.tanh(x @ w + b)


def _make_params(S, rng):
    return {"w": jnp.asarray(rng.normal(0, 0.5, (S, F, F)), jnp.float32),
            "b": jnp.asarray(rng.normal(0, 0.1, (S, F)), jnp.float32)}


def test_pipeline_forward_matches_sequential():
    S, M, mb = 4, 8, 4
    mesh = DeviceMesh.create(jax.devices()[:4], pipe=4)
    rng = np.random.RandomState(0)
    params = place_stage_params(mesh, _make_params(S, rng))
    x = jnp.asarray(rng.normal(size=(M, mb, F)), jnp.float32)

    fwd = jax.jit(pipeline_forward(_stage_fn, mesh))
    got = np.asarray(fwd(params, x))
    want = np.asarray(sequential_forward(_stage_fn, params, x))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_pipeline_gradients_match_sequential():
    S, M, mb = 4, 8, 2
    mesh = DeviceMesh.create(jax.devices()[:4], pipe=4)
    rng = np.random.RandomState(1)
    params = place_stage_params(mesh, _make_params(S, rng))
    x = jnp.asarray(rng.normal(size=(M, mb, F)), jnp.float32)

    fwd = pipeline_forward(_stage_fn, mesh)

    def loss_pp(p):
        return jnp.sum(jnp.square(fwd(p, x)))

    def loss_seq(p):
        return jnp.sum(jnp.square(sequential_forward(_stage_fn, p, x)))

    g_pp = jax.jit(jax.grad(loss_pp))(params)
    g_seq = jax.jit(jax.grad(loss_seq))(params)
    for k in g_pp:
        np.testing.assert_allclose(np.asarray(g_pp[k]), np.asarray(g_seq[k]),
                                   rtol=1e-4, atol=1e-5)


def test_pipeline_train_step_learns():
    S, n_micro, B = 2, 4, 16
    mesh = DeviceMesh.create(jax.devices()[:2], pipe=2)
    rng = np.random.RandomState(2)
    stage_params = place_stage_params(mesh, _make_params(S, rng))
    head = {"w": jnp.asarray(rng.normal(0, 0.5, (F, 1)), jnp.float32)}

    def loss_fn(y, head_params, labels):
        pred = y @ head_params["w"]
        return jnp.mean(jnp.square(pred - labels))

    step = pipeline_train_step(_stage_fn, loss_fn, mesh, n_micro)
    X = rng.normal(size=(B, F)).astype(np.float32)
    W_true = rng.normal(size=(F, 1)).astype(np.float32)
    Y = np.tanh(X) @ W_true
    losses = []
    for _ in range(30):
        stage_params, head, loss = step(stage_params, head,
                                        jnp.asarray(X), jnp.asarray(Y))
    losses.append(float(loss))
    first = float(step(place_stage_params(mesh, _make_params(S, np.random.RandomState(2))),
                       {"w": jnp.asarray(np.random.RandomState(2).normal(0, 0.5, (F, 1)), jnp.float32)},
                       jnp.asarray(X), jnp.asarray(Y))[2])
    assert losses[-1] < first, (losses[-1], first)


def test_pipeline_composes_with_data_axis():
    """PP x DP: 2 stages x 2 data columns on 4 devices; numerics equal to
    the sequential single-device run."""
    mesh = DeviceMesh.create(jax.devices()[:4], pipe=2, data=2)
    S, M, mb = 2, 4, 4
    rng = np.random.RandomState(3)
    params = place_stage_params(mesh, _make_params(S, rng))
    x = jnp.asarray(rng.normal(size=(M, mb, F)), jnp.float32)
    fwd = jax.jit(pipeline_forward(_stage_fn, mesh))
    got = np.asarray(fwd(params, x))
    want = np.asarray(sequential_forward(_stage_fn, params, x))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_split_merge_microbatches():
    x = jnp.arange(24.0).reshape(12, 2)
    mbs = split_microbatches(x, 3)
    assert mbs.shape == (3, 4, 2)
    np.testing.assert_allclose(np.asarray(merge_microbatches(mbs)),
                               np.asarray(x))
    with pytest.raises(ValueError):
        split_microbatches(x, 5)


class TestPipelineModelTrainStep:
    """Non-homogeneous embed -> trunk -> head pipelining (round-4
    Weak #8) + Megatron TP inside stages via param_specs."""

    def _run(self, model_size):
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from deeplearning4j_tpu.parallel import (
            DeviceMesh, pipeline_model_train_step, sequential_forward)

        devices = jax.devices()[:8] if model_size > 1 else jax.devices()[:4]
        mesh = (DeviceMesh.create(devices=devices, pipe=2, data=2, model=2)
                if model_size > 1 else
                DeviceMesh.create(devices=devices, pipe=2, data=2))
        V, H, F, S, B = 32, 8, 16, 6, 8
        rng = np.random.default_rng(3)
        f32 = lambda *s: jnp.asarray(rng.normal(0, 0.1, s), jnp.float32)
        embed_p = {"wte": f32(V, H)}
        stage_p = {"w": f32(2, H, F), "w2": f32(2, F, H)}
        head_p = {"w_out": f32(H, V)}

        def block(p, x):
            h = jnp.tanh(x @ p["w"])
            y = h @ p["w2"]
            if model_size > 1:
                y = lax.psum(y, "model")
            return x + y

        def block_1dev(p, x):
            return x + jnp.tanh(x @ p["w"]) @ p["w2"]

        def embed_fn(ep, ids):
            return ep["wte"][ids]

        def head_loss(hp, h, labels):
            logits = h @ hp["w_out"]
            logp = jax.nn.log_softmax(logits, -1)
            return -jnp.mean(jnp.take_along_axis(
                logp, labels[..., None], -1))

        specs = ({"w": P("pipe", None, "model"),
                  "w2": P("pipe", "model", None)}
                 if model_size > 1 else
                 {"w": P("pipe"), "w2": P("pipe")})
        placed = {k: jax.device_put(v, NamedSharding(mesh.mesh, specs[k]))
                  for k, v in stage_p.items()}
        ids = jnp.asarray(rng.integers(0, V, (B, S)), jnp.int32)
        labels = jnp.asarray(rng.integers(0, V, (B, S)), jnp.int32)
        step = pipeline_model_train_step(embed_fn, block, head_loss, mesh,
                                         n_micro=2,
                                         stage_param_specs=specs)
        (ne, ns, nh), loss = step((embed_p, placed, head_p),
                                  (ids,), (labels,))
        ref = float(head_loss(
            head_p, sequential_forward(block_1dev, stage_p,
                                       embed_fn(embed_p, ids)), labels))
        np.testing.assert_allclose(float(loss), ref, rtol=1e-4)
        assert not np.allclose(np.asarray(ns["w"]),
                               np.asarray(stage_p["w"]))
        # embed and head get gradients too (whole model trains)
        assert not np.allclose(np.asarray(ne["wte"]),
                               np.asarray(embed_p["wte"]))
        assert not np.allclose(np.asarray(nh["w_out"]),
                               np.asarray(head_p["w_out"]))

    def test_pp_dp(self):
        self._run(model_size=1)

    def test_pp_dp_tp(self):
        self._run(model_size=2)
