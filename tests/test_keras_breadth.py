"""Keras import breadth: wave-2 layer mappers against numpy references.

TF is unavailable in this environment, so fixtures are constructed as
real legacy-H5 keras files via h5py (same on-disk format tf.keras
model.save produces: model_config JSON attr + model_weights groups with
weight_names) and golden outputs are computed with independent numpy
implementations of the exact Keras semantics.
"""
import json
import os

import h5py
import numpy as np
import pytest

from deeplearning4j_tpu.modelimport import (
    import_keras_sequential_model_and_weights)

rng = np.random.RandomState(42)


def _write_h5(path, layers, weights):
    """layers: list of (class_name, config); weights: {layer_name:
    [(weight_name, array), ...]}."""
    cfg = {"class_name": "Sequential",
           "config": {"name": "seq",
                      "layers": [{"class_name": c, "config": k}
                                 for c, k in layers]}}
    with h5py.File(path, "w") as f:
        f.attrs["model_config"] = json.dumps(cfg)
        mw = f.create_group("model_weights")
        for lname, ws in weights.items():
            g = mw.create_group(lname)
            names = []
            for wn, arr in ws:
                full = f"{lname}/{wn}:0"
                mw.create_dataset(full, data=np.asarray(arr, np.float32))
                names.append(full.encode())
            g.attrs["weight_names"] = names


def _input(shape, dtype="float32"):
    return ("InputLayer", {"batch_input_shape": [None] + list(shape),
                           "dtype": dtype, "name": "input"})


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def test_gru_import_matches_numpy(tmp_path):
    T, C, U = 5, 3, 4
    kernel = rng.randn(C, 3 * U).astype(np.float32) * 0.5   # [z, r, h]
    rec = rng.randn(U, 3 * U).astype(np.float32) * 0.5
    bias = rng.randn(2, 3 * U).astype(np.float32) * 0.1      # reset_after
    path = tmp_path / "gru.h5"
    _write_h5(path, [
        _input([T, C]),
        ("GRU", {"name": "gru", "units": U, "activation": "tanh",
                 "recurrent_activation": "sigmoid", "use_bias": True,
                 "reset_after": True, "return_sequences": True,
                 "go_backwards": False}),
    ], {"gru": [("kernel", kernel), ("recurrent_kernel", rec),
                ("bias", bias)]})
    net = import_keras_sequential_model_and_weights(str(path))

    x = rng.randn(2, T, C).astype(np.float32)
    got = np.asarray(net.output(x).data)

    # numpy reference: keras GRU v3 (reset_after=True), gates [z, r, h]
    def ref(x):
        h = np.zeros((x.shape[0], U), np.float32)
        outs = []
        for t in range(T):
            gi = x[:, t] @ kernel + bias[0]
            gh = h @ rec + bias[1]
            z = _sigmoid(gi[:, :U] + gh[:, :U])
            r = _sigmoid(gi[:, U:2 * U] + gh[:, U:2 * U])
            hh = np.tanh(gi[:, 2 * U:] + r * gh[:, 2 * U:])
            h = z * h + (1 - z) * hh
            outs.append(h)
        return np.stack(outs, 1)

    np.testing.assert_allclose(got, ref(x), rtol=1e-4, atol=1e-5)


def test_layer_norm_prelu_elu_import(tmp_path):
    C = 6
    gamma = (rng.rand(C) + 0.5).astype(np.float32)
    beta = rng.randn(C).astype(np.float32)
    alpha = (rng.rand(C) * 0.5).astype(np.float32)
    path = tmp_path / "ln.h5"
    _write_h5(path, [
        _input([C]),
        ("LayerNormalization", {"name": "ln", "axis": [-1],
                                "epsilon": 1e-3}),
        ("PReLU", {"name": "prelu"}),
        ("ELU", {"name": "elu", "alpha": 1.0}),
    ], {"ln": [("gamma", gamma), ("beta", beta)],
        "prelu": [("alpha", alpha)]})
    net = import_keras_sequential_model_and_weights(str(path))
    x = rng.randn(4, C).astype(np.float32)
    got = np.asarray(net.output(x).data)

    m = x.mean(-1, keepdims=True)
    v = x.var(-1, keepdims=True)
    h = (x - m) / np.sqrt(v + 1e-3) * gamma + beta
    h = np.where(h >= 0, h, alpha * h)
    want = np.where(h >= 0, h, np.exp(h) - 1)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_leaky_relu_keras_default_slope(tmp_path):
    path = tmp_path / "leaky.h5"
    _write_h5(path, [
        _input([4]),
        ("LeakyReLU", {"name": "leaky"}),    # keras default alpha=0.3
    ], {})
    net = import_keras_sequential_model_and_weights(str(path))
    x = np.array([[-1.0, -2.0, 1.0, 3.0]], np.float32)
    got = np.asarray(net.output(x).data)
    np.testing.assert_allclose(got, [[-0.3, -0.6, 1.0, 3.0]], rtol=1e-5)


def test_reshape_permute_repeat_import(tmp_path):
    path = tmp_path / "shape.h5"
    _write_h5(path, [
        _input([6]),
        ("RepeatVector", {"name": "rv", "n": 4}),        # (B,4,6)
        ("Permute", {"name": "perm", "dims": [2, 1]}),   # (B,6,4)
        ("Reshape", {"name": "rs", "target_shape": [24]}),
    ], {})
    net = import_keras_sequential_model_and_weights(str(path))
    x = rng.randn(3, 6).astype(np.float32)
    got = np.asarray(net.output(x).data)
    want = np.transpose(np.repeat(x[:, None, :], 4, 1), (0, 2, 1)
                        ).reshape(3, 24)
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_time_distributed_dense_and_pool1d(tmp_path):
    T, C, U = 6, 4, 3
    k = rng.randn(C, U).astype(np.float32)
    b = rng.randn(U).astype(np.float32)
    path = tmp_path / "td.h5"
    _write_h5(path, [
        _input([T, C]),
        ("TimeDistributed", {"name": "td", "layer": {
            "class_name": "Dense",
            "config": {"name": "inner", "units": U, "activation": "relu",
                       "use_bias": True}}}),
        ("MaxPooling1D", {"name": "mp", "pool_size": 2, "strides": 2,
                          "padding": "valid"}),
    ], {"td": [("kernel", k), ("bias", b)]})
    net = import_keras_sequential_model_and_weights(str(path))
    x = rng.randn(2, T, C).astype(np.float32)
    got = np.asarray(net.output(x).data)
    h = np.maximum(x @ k + b, 0)                      # (2, 6, 3)
    want = h.reshape(2, 3, 2, U).max(2)               # pool_size 2
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_multi_head_attention_import(tmp_path):
    T, D, H, DK = 4, 6, 2, 3
    wq = rng.randn(D, H, DK).astype(np.float32) * 0.5
    bq = rng.randn(H, DK).astype(np.float32) * 0.1
    wk = rng.randn(D, H, DK).astype(np.float32) * 0.5
    bk = rng.randn(H, DK).astype(np.float32) * 0.1
    wv = rng.randn(D, H, DK).astype(np.float32) * 0.5
    bv = rng.randn(H, DK).astype(np.float32) * 0.1
    wo = rng.randn(H, DK, D).astype(np.float32) * 0.5
    bo = rng.randn(D).astype(np.float32) * 0.1
    path = tmp_path / "mha.h5"
    _write_h5(path, [
        _input([T, D]),
        ("MultiHeadAttention", {"name": "mha", "num_heads": H,
                                "key_dim": DK, "use_bias": True}),
    ], {"mha": [("query/kernel", wq), ("query/bias", bq),
                ("key/kernel", wk), ("key/bias", bk),
                ("value/kernel", wv), ("value/bias", bv),
                ("attention_output/kernel", wo),
                ("attention_output/bias", bo)]})
    net = import_keras_sequential_model_and_weights(str(path))
    x = rng.randn(2, T, D).astype(np.float32)
    got = np.asarray(net.output(x).data)

    # numpy reference: keras self-MHA
    q = np.einsum("btd,dhk->bhtk", x, wq) + bq[None, :, None, :]
    k = np.einsum("btd,dhk->bhtk", x, wk) + bk[None, :, None, :]
    v = np.einsum("btd,dhk->bhtk", x, wv) + bv[None, :, None, :]
    s = np.einsum("bhqk,bhtk->bhqt", q, k) / np.sqrt(DK)
    e = np.exp(s - s.max(-1, keepdims=True))
    a = e / e.sum(-1, keepdims=True)
    ctxv = np.einsum("bhqt,bhtk->bhqk", a, v)
    want = np.einsum("bhqk,hkd->bqd", ctxv, wo) + bo
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
