"""Continuous-batching generative serving (serving/generative.py,
ISSUE 15 / ROADMAP item 1).

Pinned contracts:
- greedy tokens from the continuous-batching server are IDENTICAL to
  :func:`greedy_decode` (the unbatched single-request reference) for
  every request in a mixed-length concurrent run;
- slot lifecycle: a slot is freed exactly once on each retirement path
  (EOS / max_new_tokens / deadline expiry / cancel / capacity), and a
  retired slot's cache — even poisoned with NaNs — cannot influence its
  successor (bit-identical to a fresh server);
- a crashed decode worker's in-flight generations requeue at prefill
  EXACTLY once and complete with the same tokens; a twice-lost request
  fails typed;
- compiles stay ≤ log2(max_seq)+O(1): ONE decode program + one prefill
  program per pow2 bucket, all AOT-warmable (0 traffic compiles);
- continuous batching does ≥2x the tokens-per-decode-step of static
  wait-for-full-batch batching on the same skewed trace.

ISSUE 18 (fast decode) grows the contract:
- draft-model speculation NEVER changes tokens: temp-0 output is
  bit-identical to the non-speculative server and to greedy_decode —
  the draft only sets how many tokens land per verify round;
- seeded sampling replays exactly per (seed, absolute token index):
  same request, same tokens — whatever shares the batch, whatever the
  admission order, and across a crash-requeue re-entry;
- AOT warmup with speculation + int8 weights still leaves 0 traffic
  compiles (7 plain + verify + draft decode + 6 draft prefill = 15).
"""
import threading
import time
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.serving.generative import (
    GenerationCancelled, GenerativeMetrics, GenerativeServer,
    GenerativeSpec, SlotAllocator, greedy_decode)
from deeplearning4j_tpu.serving.loadgen import GenerativeLoadGenerator
from deeplearning4j_tpu.serving.metrics import LatencyHistogram
from deeplearning4j_tpu.serving.queue import (RequestTimeoutError,
                                              ServerClosedError,
                                              ServerOverloadedError,
                                              ServingError,
                                              ServingTimeoutError)
from deeplearning4j_tpu.serving.resilience import ResilienceConfig
from deeplearning4j_tpu.zoo.gpt import (GPTConfig, build_gpt,
                                        gpt_generative_spec,
                                        gpt_param_names)

CFG = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
                intermediate_size=64, max_seq_len=32)
DRAFT_CFG = GPTConfig(vocab_size=64, hidden_size=16, num_layers=1,
                      num_heads=2, intermediate_size=32, max_seq_len=32)
MSL = 32


@pytest.fixture(scope="module")
def gpt_sd():
    return build_gpt(CFG, batch=2, seq_len=8, seed=0)


@pytest.fixture(scope="module")
def spec(gpt_sd):
    # one spec for the whole module: the jitted decode/prefill programs
    # are memoized on it, so every server here shares one compile set
    return gpt_generative_spec(gpt_sd, CFG)


@pytest.fixture(scope="module")
def draft_spec():
    # an independently-trained smaller model over the SAME vocab: low
    # acceptance (it disagrees with the target a lot) is the point —
    # the rejection/rollback path gets exercised hard
    dsd = build_gpt(DRAFT_CFG, batch=2, seq_len=8, seed=1)
    return gpt_generative_spec(dsd, DRAFT_CFG)


def make_server(spec, **kw):
    kw.setdefault("max_slots", 4)
    kw.setdefault("max_seq_len", MSL)
    kw.setdefault("warmup", False)
    return GenerativeServer(spec, **kw)


def ref_tokens(spec, prompt, n, eos_id=None):
    return greedy_decode(spec, prompt, n, eos_id=eos_id, max_seq_len=MSL)


def mixed_prompts(n=6, seed=0, max_len=12):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, CFG.vocab_size,
                         int(rng.integers(1, max_len + 1)))
            .astype(np.int32) for _ in range(n)]


# ----------------------------------------------------------------------
class TestSlotAllocator:
    def test_alloc_free_cycle(self):
        a = SlotAllocator(3)
        s = [a.alloc() for _ in range(3)]
        assert sorted(s) == [0, 1, 2]
        assert a.free_count() == 0
        with pytest.raises(RuntimeError):
            a.alloc()
        for x in s:
            a.free(x)
        assert a.free_count() == 3

    def test_double_free_raises(self):
        a = SlotAllocator(2)
        s = a.alloc()
        a.free(s)
        with pytest.raises(RuntimeError, match="twice"):
            a.free(s)

    def test_free_unallocated_raises(self):
        a = SlotAllocator(2)
        with pytest.raises(RuntimeError):
            a.free(1)

    def test_reset(self):
        a = SlotAllocator(2)
        a.alloc()
        a.reset()
        assert a.free_count() == 2


# ----------------------------------------------------------------------
class TestMetricsGuards:
    """ISSUE 15 satellite: NaN-free zeros on empty/degenerate inputs +
    the low-sample percentile flag."""

    def test_empty_percentile_is_zero(self):
        h = LatencyHistogram()
        for p in (0, 50, 99, 100):
            v = h.percentile(p)
            assert v == 0.0 and np.isfinite(v)
        assert h.mean() == 0.0
        s = h.summary()
        assert s["count"] == 0 and s["low_sample"] is True
        assert all(np.isfinite(v) for k, v in s.items()
                   if isinstance(v, (int, float)))

    def test_nonfinite_sample_records_as_zero(self):
        h = LatencyHistogram()
        h.record(float("nan"))
        h.record(float("inf"))
        s = h.summary()
        assert s["count"] == 2
        assert np.isfinite(s["mean"]) and s["mean"] == 0.0
        assert np.isfinite(s["p99"])

    def test_observe_batch_zero_rows_nan_free(self):
        m = GenerativeMetrics(max_slots=4)
        m.observe_batch(rows=0, padding=0, exec_ms=float("nan"))
        m.observe_batch(rows=-3, padding=-1, exec_ms=1.0)
        rec = m.to_record()
        assert rec["batch"]["mean_size"] == 0.0
        assert rec["batch"]["padding_waste"] == 0.0
        flat = [rec["batch"]["mean_size"], rec["batch"]["padding_waste"],
                *(rec["latency_ms"]["exec"][k]
                  for k in ("mean", "p50", "p99", "max"))]
        assert all(np.isfinite(v) for v in flat)
        assert m.padding_waste() == 0.0 and m.mean_batch_size() == 0.0

    def test_low_sample_flag_clears_at_32(self):
        h = LatencyHistogram()
        for _ in range(31):
            h.record(1.0)
        assert h.summary()["low_sample"] is True
        h.record(1.0)
        assert h.summary()["low_sample"] is False


# ----------------------------------------------------------------------
class TestDecodeMath:
    def test_param_names_cover_graph(self, gpt_sd):
        for n in gpt_param_names(CFG):
            assert n in gpt_sd._arrays, n

    def test_prefill_matches_full_forward(self, gpt_sd, spec):
        """The decode-mode prefill reproduces the training graph's
        logits at the last prompt position — the decode math is the
        same model, not a lookalike."""
        import jax.numpy as jnp
        prompt = np.asarray([5, 17, 40, 2, 33], np.int32)
        L = prompt.size
        # training graph: full forward at the prompt's own length
        sd_full = build_gpt(CFG, batch=1, seq_len=L, seed=0)
        out = sd_full.output({"input_ids": prompt[None],
                              "targets": np.zeros((1, L), np.int32)},
                             ["logits"])
        full_logits = np.asarray(out["logits"].to_numpy())[0, L - 1]
        # decode-mode prefill at the pow2 bucket (8 > 5: padded)
        kc = jnp.zeros(spec.kv_shape(1, MSL), jnp.float32)
        vc = jnp.zeros(spec.kv_shape(1, MSL), jnp.float32)
        padded = np.zeros(8, np.int32)
        padded[:L] = prompt
        _, _, nxt, logits = spec.prefill(
            dict(spec.params()), kc, vc,
            {"tokens": padded, "length": np.int32(L),
             "slot": np.int32(0)})
        np.testing.assert_allclose(np.asarray(logits), full_logits,
                                   rtol=1e-4, atol=1e-5)
        assert int(nxt) == int(np.argmax(full_logits))

    def test_greedy_decode_deterministic(self, spec):
        p = np.asarray([3, 9, 1], np.int32)
        assert ref_tokens(spec, p, 8) == ref_tokens(spec, p, 8)

    def test_greedy_decode_eos_stops(self, spec):
        p = np.asarray([3, 9, 1], np.int32)
        full = ref_tokens(spec, p, 8)
        eos = full[2]
        got = ref_tokens(spec, p, 8, eos_id=eos)
        # stops at the FIRST occurrence of eos (an untrained model may
        # repeat tokens, so that can be earlier than index 2)
        assert got == full[:full.index(eos) + 1]


# ----------------------------------------------------------------------
class TestServer:
    def test_mixed_run_bit_identical_to_unbatched(self, spec):
        """THE acceptance pin: every request in a mixed-length
        concurrent run decodes the same greedy tokens as the unbatched
        single-request reference."""
        prompts = mixed_prompts(8, seed=1)
        with make_server(spec, max_slots=4) as srv:
            handles = [srv.submit(p, max_new_tokens=6 + i % 5)
                       for i, p in enumerate(prompts)]
            results = [h.result(timeout=120) for h in handles]
        for i, (p, got) in enumerate(zip(prompts, results)):
            assert got == ref_tokens(spec, p, 6 + i % 5), f"request {i}"

    def test_streaming_matches_future(self, spec):
        p = np.asarray([1, 2, 3], np.int32)
        with make_server(spec) as srv:
            h = srv.submit(p, max_new_tokens=7)
            streamed = list(h.tokens(timeout=120))
            assert streamed == h.result(timeout=5)
            assert len(streamed) == 7

    def test_on_token_callback(self, spec):
        seen = []
        with make_server(spec) as srv:
            toks = srv.submit(np.asarray([4], np.int32), max_new_tokens=5,
                              on_token=seen.append).result(timeout=120)
        assert seen == toks

    def test_eos_retires_slot_immediately(self, spec):
        p = np.asarray([7, 7], np.int32)
        full = ref_tokens(spec, p, 10)
        eos = full[3]
        with make_server(spec) as srv:
            got = srv.generate(p, max_new_tokens=10)
            # submit with eos -> stops at its FIRST occurrence, slot
            # freed (the follow-up generate proves it)
            got_eos = srv.submit(p, max_new_tokens=10,
                                 eos_id=eos).result(timeout=120)
            assert srv._slots.free_count() == srv.max_slots
        assert got == full
        assert got_eos == full[:full.index(eos) + 1]

    def test_sequence_capacity_retires(self, spec):
        # prompt of MSL-1 leaves exactly one decode position
        p = np.arange(MSL - 1, dtype=np.int32) % CFG.vocab_size
        with make_server(spec) as srv:
            got = srv.generate(p, max_new_tokens=50)
        assert got == ref_tokens(spec, p, 50)
        assert 1 <= len(got) <= 2

    def test_slot_freed_exactly_once_all_paths(self, spec):
        """EOS, max_new_tokens, deadline expiry and cancel each free
        the slot exactly once (SlotAllocator raises on double free, so
        surviving the run IS the invariant; the counter makes it
        explicit)."""
        frees = []
        with make_server(spec, max_slots=2) as srv:
            orig_free = srv._slots.free

            def counting_free(s):
                frees.append(s)
                return orig_free(s)

            srv._slots.free = counting_free
            # max_new_tokens path
            srv.generate(np.asarray([1], np.int32), max_new_tokens=3)
            # eos path
            full = ref_tokens(spec, np.asarray([2], np.int32), 6)
            srv.submit(np.asarray([2], np.int32), max_new_tokens=6,
                       eos_id=full[1]).result(timeout=120)
            # deadline-expiry path (slow consumer via on_token)
            h = srv.submit(np.asarray([3], np.int32), max_new_tokens=50,
                           timeout_ms=150,
                           on_token=lambda t: time.sleep(0.05))
            with pytest.raises(ServingTimeoutError):
                h.result(timeout=120)
            # cancel path
            h2 = srv.submit(np.asarray([4], np.int32), max_new_tokens=400,
                            on_token=lambda t: time.sleep(0.02))
            time.sleep(0.06)
            h2.cancel()
            h2.result(timeout=120)
            deadline = time.monotonic() + 5
            while srv._slots.free_count() < 2 and \
                    time.monotonic() < deadline:
                time.sleep(0.01)
            assert srv._slots.free_count() == 2
        assert len(frees) == 4
        assert sorted(set(frees)) == sorted(frees) or len(frees) == 4

    def test_deadline_mid_generation_typed_with_partial(self, spec):
        with make_server(spec) as srv:
            h = srv.submit(np.asarray([9], np.int32), max_new_tokens=50,
                           timeout_ms=150,
                           on_token=lambda t: time.sleep(0.05))
            with pytest.raises(ServingTimeoutError) as ei:
                h.result(timeout=120)
            assert len(ei.value.tokens) >= 1      # partial tokens attached
            assert ei.value.tokens == h.partial()
            # the stream surfaces the same failure
            with pytest.raises(ServingTimeoutError):
                list(h.tokens(timeout=5))
        assert srv.metrics.counters["requests_timed_out"] >= 1

    def test_cancel_resolves_partial_and_clean_stream(self, spec):
        with make_server(spec) as srv:
            h = srv.submit(np.asarray([8], np.int32), max_new_tokens=400,
                           on_token=lambda t: time.sleep(0.02))
            time.sleep(0.08)
            h.cancel()
            got = h.result(timeout=120)
            assert 1 <= len(got) < 400
            streamed = list(h.tokens(timeout=5))   # ends cleanly, no raise
            assert streamed == got

    def test_queued_deadline_expires_before_prefill(self, spec):
        srv = make_server(spec, start=False)
        try:
            h = srv.submit(np.asarray([5], np.int32), max_new_tokens=4,
                           timeout_ms=1)
            time.sleep(0.05)
            srv.start()
            with pytest.raises(RequestTimeoutError):
                h.result(timeout=60)
            with pytest.raises(RequestTimeoutError):
                list(h.tokens(timeout=5))
        finally:
            srv.shutdown()

    def test_kv_poison_no_bleed_on_slot_reuse(self, spec):
        """Retire a generation, poison the ENTIRE slab with NaNs, then
        serve a new request: its tokens must be bit-identical to a
        fresh server's — the masked-V decode makes slot reuse provably
        independent of retired-cache contents."""
        p2 = np.asarray([11, 3, 7], np.int32)
        with make_server(spec, max_slots=2) as srv:
            srv.generate(np.asarray([1, 2, 3, 4, 5], np.int32),
                         max_new_tokens=8)
            # worker idle at a step boundary: poison between requests
            time.sleep(0.05)
            with srv._exec_lock:
                import jax.numpy as jnp
                srv._kc = jnp.full_like(srv._kc, jnp.nan)
                srv._vc = jnp.full_like(srv._vc, jnp.nan)
            got = srv.generate(p2, max_new_tokens=8)
        with make_server(spec, max_slots=2) as fresh:
            want = fresh.generate(p2, max_new_tokens=8)
        assert got == want
        assert got == ref_tokens(spec, p2, 8)

    def test_compile_budget_and_warm_traffic(self, gpt_sd):
        """ONE decode program + ≤ log2(max_seq)+1 prefill buckets;
        after warmup, mixed traffic compiles NOTHING new."""
        fresh_spec = gpt_generative_spec(gpt_sd, CFG)    # empty compile memo
        with make_server(fresh_spec, max_slots=4, warmup=True) as srv:
            assert srv.warmup_report["prefill_buckets"] == \
                [1, 2, 4, 8, 16, 32]
            assert srv.metrics.counters["warmup_compiles"] == 7
            for i, p in enumerate(mixed_prompts(8, seed=3, max_len=20)):
                srv.generate(p, max_new_tokens=3 + i % 4)
            assert srv.metrics.counters["compiles"] == 0
        # log2(32) + 1 prefill shapes + 1 decode shape
        assert len(srv.warmup_report["prefill_buckets"]) <= \
            int(np.log2(MSL)) + 1

    def test_admission_sheds_typed_on_estimated_ttft(self, spec):
        cfg = ResilienceConfig(min_exec_samples=4, percentile=99.0)
        srv = make_server(spec, resilience=cfg, start=False,
                          max_queue_len=64)
        try:
            for _ in range(8):
                srv.admission.observe(50.0)     # p99 step = 50 ms
            srv.submit(np.asarray([1], np.int32), 4)   # no deadline: kept
            with pytest.raises(ServerOverloadedError) as ei:
                srv.submit(np.asarray([2], np.int32), 4, timeout_ms=20.0)
            assert ei.value.retry_after_s is not None
            assert ei.value.retry_after_s > 0
            assert srv.metrics.counters["requests_shed"] == 1
        finally:
            srv.shutdown(drain=False)

    def test_queue_full_rejects_typed(self, spec):
        srv = make_server(spec, max_queue_len=2, start=False,
                          resilience=False)
        try:
            srv.submit(np.asarray([1], np.int32), 2)
            srv.submit(np.asarray([2], np.int32), 2)
            with pytest.raises(ServerOverloadedError):
                srv.submit(np.asarray([3], np.int32), 2)
            assert srv.metrics.counters["requests_rejected"] == 1
        finally:
            srv.shutdown(drain=False)

    def test_submit_validation(self, spec):
        with make_server(spec, start=False) as srv:
            with pytest.raises(ValueError):
                srv.submit(np.asarray([], np.int32), 4)
            with pytest.raises(ValueError):
                srv.submit(np.arange(MSL, dtype=np.int32), 4)
            with pytest.raises(ValueError):
                srv.submit(np.asarray([CFG.vocab_size], np.int32), 4)
            with pytest.raises(ValueError):
                srv.submit(np.asarray([1], np.int32), 0)
        with pytest.raises(ServerClosedError):
            srv.submit(np.asarray([1], np.int32), 4)

    def test_update_model_serves_new_params(self, spec, gpt_sd):
        import jax.numpy as jnp
        p = np.asarray([6, 6, 6], np.int32)
        with make_server(spec) as srv:
            before = srv.generate(p, max_new_tokens=6)
            old = gpt_sd._arrays["wte"]
            try:
                gpt_sd._arrays["wte"] = old + jnp.asarray(0.5)
                srv.update_model()
                after = srv.generate(p, max_new_tokens=6)
                want = ref_tokens(spec, p, 6)
            finally:
                gpt_sd._arrays["wte"] = old
                srv.update_model()
            assert after == want        # reference reads live params too
            assert srv.generate(p, max_new_tokens=6) == before
        assert before != after or before == after  # smoke: both defined


# ----------------------------------------------------------------------
class TestCrashRecovery:
    @pytest.mark.chaos
    def test_worker_crash_requeues_at_prefill_exactly_once(self, spec):
        """Kill the decode worker mid-generation: in-flight requests
        requeue at the FRONT exactly once, re-enter at prefill with
        prompt+generated-so-far, and finish with the SAME tokens."""
        prompts = mixed_prompts(3, seed=7)
        srv = make_server(spec, max_slots=2, start=False,
                          resilience=ResilienceConfig(
                              worker_backoff_base_s=0.01,
                              worker_backoff_max_s=0.05))
        real = srv._decode_disp
        state = {"calls": 0, "fired": False}

        class CrashOnce:
            def __call__(self, *args):
                state["calls"] += 1
                if not state["fired"] and state["calls"] > 2:
                    state["fired"] = True
                    raise RuntimeError("chaos: decode worker dies")
                return real(*args)

        srv._decode_disp = CrashOnce()
        try:
            srv.start()
            handles = [srv.submit(p, max_new_tokens=8) for p in prompts]
            results = [h.result(timeout=120) for h in handles]
        finally:
            srv.shutdown()
        assert state["fired"]
        for p, got in zip(prompts, results):
            assert got == ref_tokens(spec, p, 8)
        assert srv.metrics.counters["worker_restarts"] >= 1
        assert srv.metrics.counters["requests_requeued"] >= 1
        # streams saw each token exactly once: results == full greedy
        # sequences, nothing duplicated or dropped

    @pytest.mark.chaos
    def test_twice_lost_request_fails_typed(self, spec):
        srv = make_server(spec, max_slots=2, start=False,
                          resilience=ResilienceConfig(
                              worker_backoff_base_s=0.01,
                              worker_backoff_max_s=0.05))
        real = srv._decode_disp

        class AlwaysCrash:
            def __call__(self, *args):
                raise RuntimeError("chaos: decode always dies")

        srv._decode_disp = AlwaysCrash()
        try:
            srv.start()
            h = srv.submit(np.asarray([1, 2], np.int32), max_new_tokens=8)
            with pytest.raises(ServingError, match="twice"):
                h.result(timeout=120)
        finally:
            srv._decode_disp = real
            srv.shutdown(drain=False)

    def test_unsupervised_crash_fails_inflight(self, spec):
        srv = make_server(spec, max_slots=2, start=False, resilience=False)

        class Crash:
            def __call__(self, *args):
                raise RuntimeError("decode crash, no supervisor")

        srv._decode_disp = Crash()
        try:
            srv.start()
            h = srv.submit(np.asarray([1], np.int32), max_new_tokens=8)
            with pytest.raises(RuntimeError, match="no supervisor"):
                h.result(timeout=60)
        finally:
            srv.shutdown(drain=False)


# ----------------------------------------------------------------------
class TestSpeculative:
    """ISSUE 18 tentpole: the draft never changes tokens — it only
    changes how many land per verify dispatch."""

    def test_temp0_bit_identical_to_plain_and_reference(self, spec,
                                                        draft_spec):
        prompts = mixed_prompts(8, seed=11)
        budgets = [6 + i % 5 for i in range(8)]
        with make_server(spec, draft_spec=draft_spec,
                         speculate_k=4) as srv:
            hs = [srv.submit(p, n) for p, n in zip(prompts, budgets)]
            got = [h.result(timeout=120) for h in hs]
            rec = srv.metrics.to_record()["generative"]
        with make_server(spec) as plain:
            want = [plain.submit(p, n).result(timeout=120)
                    for p, n in zip(prompts, budgets)]
        assert got == want
        for p, n, g in zip(prompts, budgets, got):
            assert g == ref_tokens(spec, p, n)
        assert rec["spec_rounds"] >= 1          # speculation actually ran

    def test_metrics_count_tokens_exactly_once(self, spec, draft_spec):
        """Accepted draft tokens and the verify-corrected token land in
        tokens_generated exactly once; the draft ledger balances."""
        with make_server(spec, draft_spec=draft_spec,
                         speculate_k=4) as srv:
            outs = [srv.generate(p, max_new_tokens=6)
                    for p in mixed_prompts(4, seed=13)]
            rec = srv.metrics.to_record()["generative"]
        assert rec["tokens_generated"] == sum(len(o) for o in outs)
        assert rec["draft_tokens"] == \
            rec["draft_accepted"] + rec["draft_rejected"]
        assert rec["draft_tokens"] > 0
        assert 0.0 <= rec["draft_acceptance_rate"] <= 1.0

    def test_acceptance_lane_folds_and_renders(self, spec, draft_spec):
        from deeplearning4j_tpu.monitor.registry import MetricsRegistry
        from deeplearning4j_tpu.ui.report import render_report
        from deeplearning4j_tpu.ui.stats import StatsStorage
        storage = StatsStorage()
        with make_server(spec, draft_spec=draft_spec, speculate_k=4,
                         stats_storage=storage) as srv:
            srv.generate(np.asarray([1, 2, 3], np.int32),
                         max_new_tokens=6)
            rec = srv.metrics.to_record()
        reg = MetricsRegistry()
        reg.fold_serving(rec)
        text = reg.to_prometheus_text()
        assert "dl4j_serving_draft_acceptance_rate" in text
        assert "dl4j_serving_draft_tokens_rejected_total" in text
        html = render_report(storage)
        assert "speculative:" in html
        assert "draft tokens accepted" in html
        # a non-speculative record must NOT grow the lane
        with make_server(spec) as plain:
            plain.generate(np.asarray([1], np.int32), max_new_tokens=3)
            rec2 = plain.metrics.to_record()
        reg2 = MetricsRegistry()
        reg2.fold_serving(rec2)
        assert "draft_acceptance" not in reg2.to_prometheus_text()

    def test_warmup_covers_draft_and_verify_quantized(self, gpt_sd):
        """AOT warmup with speculation AND int8 weights enabled leaves
        0 traffic compiles: 7 plain programs + verify + draft decode +
        6 draft prefill buckets = 15."""
        fresh = gpt_generative_spec(gpt_sd, CFG, quantize_weights=True)
        d_sd = build_gpt(DRAFT_CFG, batch=2, seq_len=8, seed=4)
        fresh_draft = gpt_generative_spec(d_sd, DRAFT_CFG)
        with make_server(fresh, draft_spec=fresh_draft, speculate_k=4,
                         warmup=True) as srv:
            assert srv.warmup_report["speculative"] is True
            assert srv.metrics.counters["warmup_compiles"] == 15
            for i, p in enumerate(mixed_prompts(6, seed=17, max_len=20)):
                srv.generate(p, max_new_tokens=3 + i % 4)
            assert srv.metrics.counters["compiles"] == 0

    def test_pairing_validation(self, spec, draft_spec):
        bad_cfg = GPTConfig(vocab_size=48, hidden_size=16, num_layers=1,
                            num_heads=2, intermediate_size=32,
                            max_seq_len=32)
        bad = gpt_generative_spec(
            build_gpt(bad_cfg, batch=2, seq_len=8, seed=2), bad_cfg)
        with pytest.raises(ValueError, match="vocab"):
            make_server(spec, draft_spec=bad)
        short_cfg = GPTConfig(vocab_size=64, hidden_size=16,
                              num_layers=1, num_heads=2,
                              intermediate_size=32, max_seq_len=16)
        short = gpt_generative_spec(
            build_gpt(short_cfg, batch=2, seq_len=8, seed=2), short_cfg)
        with pytest.raises(ValueError, match="max_seq_len"):
            make_server(spec, draft_spec=short)
        with pytest.raises(ValueError, match="speculate_k"):
            make_server(spec, draft_spec=draft_spec, speculate_k=1)


# ----------------------------------------------------------------------
class TestSeededSampling:
    def test_sample_token_contract(self):
        from deeplearning4j_tpu.serving import sample_token
        r = np.random.default_rng(21)
        logits = r.normal(size=64).astype(np.float32)
        # temp 0 = exact greedy
        assert sample_token(logits, temperature=0.0) == \
            int(np.argmax(logits))
        # pure in (seed, index)
        a = sample_token(logits, temperature=0.8, seed=5, index=3)
        assert a == sample_token(logits, temperature=0.8, seed=5,
                                 index=3)
        assert 0 <= a < 64
        # top-k truncation: the draw is one of the k largest
        t = sample_token(logits, temperature=1.0, top_k=4, seed=9,
                         index=0)
        assert t in set(int(i) for i in np.argsort(logits)[-4:])
        # a vanishing top-p nucleus keeps (at least) the argmax
        assert sample_token(logits, temperature=1.0, top_p=1e-9,
                            seed=11, index=0) == int(np.argmax(logits))
        # NaN-safe: non-finite logits still yield a valid id
        bad = logits.copy()
        bad[::3] = np.nan
        assert 0 <= sample_token(bad, temperature=1.0, seed=1,
                                 index=1) < 64

    def test_sampled_deterministic_under_cobatching(self, spec):
        p = np.asarray([3, 7, 1], np.int32)
        with make_server(spec, max_slots=4) as srv:
            solo = srv.submit(p, max_new_tokens=8, temperature=0.9,
                              seed=7).result(timeout=120)
        with make_server(spec, max_slots=4) as srv:
            # different co-batch mix AND admission order this time
            others = [srv.submit(q, max_new_tokens=10, temperature=0.7,
                                 seed=100 + i)
                      for i, q in enumerate(mixed_prompts(3, seed=23))]
            h = srv.submit(p, max_new_tokens=8, temperature=0.9, seed=7)
            twin = srv.submit(p, max_new_tokens=8, temperature=0.9,
                              seed=7)
            got = h.result(timeout=120)
            assert got == twin.result(timeout=120)
            for o in others:
                o.result(timeout=120)
        assert got == solo
        # a different seed decouples the stream
        with make_server(spec) as srv:
            other = srv.submit(p, max_new_tokens=8, temperature=0.9,
                               seed=8).result(timeout=120)
        assert other != solo

    def test_sampled_identical_with_and_without_speculation(
            self, spec, draft_spec):
        """The emitted token is ALWAYS the target's sample at that
        (seed, index) — the draft cannot perturb a sampled stream."""
        p = np.asarray([5, 9], np.int32)
        with make_server(spec) as plain:
            want = plain.submit(p, max_new_tokens=8, temperature=0.8,
                                seed=42).result(timeout=120)
        with make_server(spec, draft_spec=draft_spec,
                         speculate_k=4) as srv:
            got = srv.submit(p, max_new_tokens=8, temperature=0.8,
                             seed=42).result(timeout=120)
        assert got == want

    @pytest.mark.chaos
    def test_sampled_crash_requeue_replays_identically(self, spec):
        """The (seed, absolute index) fold survives the requeue
        re-entry: prompt+generated-so-far re-prefills, the continuation
        draws land on the SAME indices, the stream is unchanged."""
        p = np.asarray([2, 4, 6], np.int32)
        with make_server(spec) as clean:
            want = clean.submit(p, max_new_tokens=8, temperature=0.9,
                                seed=13).result(timeout=120)
        srv = make_server(spec, max_slots=2, start=False,
                          resilience=ResilienceConfig(
                              worker_backoff_base_s=0.01,
                              worker_backoff_max_s=0.05))
        real = srv._decode_disp
        state = {"calls": 0, "fired": False}

        class CrashOnce:
            def __call__(self, *args):
                state["calls"] += 1
                if not state["fired"] and state["calls"] > 2:
                    state["fired"] = True
                    raise RuntimeError("chaos: decode worker dies")
                return real(*args)

        srv._decode_disp = CrashOnce()
        try:
            srv.start()
            got = srv.submit(p, max_new_tokens=8, temperature=0.9,
                             seed=13).result(timeout=120)
        finally:
            srv.shutdown()
        assert state["fired"]
        assert got == want
        assert srv.metrics.counters["requests_requeued"] >= 1

    def test_sampling_validation(self, spec):
        with make_server(spec, start=False) as srv:
            with pytest.raises(ValueError, match="temperature"):
                srv.submit(np.asarray([1], np.int32), 4,
                           temperature=-0.5)
            with pytest.raises(ValueError, match="temperature"):
                srv.submit(np.asarray([1], np.int32), 4,
                           temperature=float("nan"))
            with pytest.raises(ValueError, match="top_k"):
                srv.submit(np.asarray([1], np.int32), 4,
                           temperature=0.5, top_k=0)
            with pytest.raises(ValueError, match="top_p"):
                srv.submit(np.asarray([1], np.int32), 4,
                           temperature=0.5, top_p=0.0)


# ----------------------------------------------------------------------
class TestContinuousVsStatic:
    def test_continuous_2x_tokens_per_step_on_skewed_trace(self, spec):
        """The perf mechanism, pinned deterministically: on a trace of
        mostly-short generations with a long tail, continuous batching
        produces ≥2x the tokens per decode step of wait-for-full-batch
        static batching (wall-clock tokens/sec follows step count —
        bench.py generative measures it; CPU smoke showed 2.0x)."""
        budgets = [2, 2, 2, 24] * 3
        prompts = mixed_prompts(len(budgets), seed=5, max_len=6)
        stats = {}
        for mode in ("continuous", "static"):
            srv = make_server(spec, max_slots=4, admit=mode, start=False,
                              max_queue_len=64)
            try:
                hs = [srv.submit(p, n) for p, n in zip(prompts, budgets)]
                srv.start()
                results = [h.result(timeout=120) for h in hs]
            finally:
                srv.shutdown()
            rec = srv.metrics.to_record()["generative"]
            stats[mode] = (rec["tokens_generated"], rec["decode_steps"],
                           rec["slot_occupancy"], results)
        assert stats["continuous"][3] == stats["static"][3]  # same tokens
        tok_per_step = {m: stats[m][0] / max(1, stats[m][1])
                        for m in stats}
        assert tok_per_step["continuous"] >= \
            1.9 * tok_per_step["static"], stats
        assert stats["continuous"][2] > stats["static"][2]

    def test_loadgen_trace_shared_between_modes(self, spec):
        with make_server(spec, start=False) as srv:
            lg1 = GenerativeLoadGenerator(srv, seed=3, prompt_len=(1, 8),
                                          new_tokens=(2, 6))
            lg2 = GenerativeLoadGenerator(srv, seed=3, prompt_len=(1, 8),
                                          new_tokens=(2, 6))
            for i in range(10):
                p1, n1, d1, t1, s1 = lg1.request(i)
                p2, n2, d2, t2, s2 = lg2.request(i)
                assert np.array_equal(p1, p2) and n1 == n2 and d1 == d2
                assert t1 == t2 and s1 == s2


# ----------------------------------------------------------------------
class TestLoadgenGenerative:
    def test_closed_loop_records_token_percentiles(self, spec):
        with make_server(spec, max_slots=4) as srv:
            lg = GenerativeLoadGenerator(srv, seed=2, prompt_len=(1, 10),
                                         new_tokens=(2, 8))
            res = lg.run_closed(n_requests=12, concurrency=4)
        assert res.n_ok == 12
        assert res.tokens_total > 0
        assert len(res.ttft_ms) == 12
        assert len(res.intertoken_ms) == res.tokens_total - 12
        assert res.ttft_percentile(50) > 0
        assert res.tokens_per_sec > 0
        assert "TTFT" in res.stats()

    def test_open_loop_with_deadlines(self, spec):
        with make_server(spec, max_slots=2) as srv:
            lg = GenerativeLoadGenerator(srv, seed=4, prompt_len=(1, 6),
                                         new_tokens=(2, 6),
                                         deadline_ms=(5000, 8000))
            res = lg.run_open(n_requests=8, rate_rps=200.0)
        assert res.n_issued == 8
        assert res.n_ok + res.n_timed_out + res.n_rejected \
            + res.n_failed == 8
        assert res.n_ok >= 6            # generous SLO: most complete

    def test_callable_length_sampler(self, spec):
        with make_server(spec, start=False) as srv:
            lg = GenerativeLoadGenerator(
                srv, seed=1,
                prompt_len=lambda rng: 3,
                new_tokens=lambda rng: 2 + int(rng.integers(0, 3)))
            for i in range(5):
                p, n, _, _, _ = lg.request(i)
                assert p.size == 3 and 2 <= n <= 4

    def test_request_carries_pure_sampling_fields(self, spec):
        with make_server(spec, start=False) as srv:
            lg = GenerativeLoadGenerator(srv, seed=6, prompt_len=(1, 6),
                                         new_tokens=(2, 4),
                                         temperature=(0.5, 1.0))
            a = lg.request(3)
            lg.request(7)               # interleaved draw
            b = lg.request(3)           # same i -> same tuple regardless
            assert np.array_equal(a[0], b[0]) and a[1:] == b[1:]
            assert 0.5 <= a[3] <= 1.0
            assert isinstance(a[4], int)
            # default stays greedy: the pre-ISSUE-18 trace unchanged
            lg0 = GenerativeLoadGenerator(srv, seed=6, prompt_len=(1, 6),
                                          new_tokens=(2, 4))
            assert lg0.request(0)[3] == 0.0

    def test_closed_loop_sampled(self, spec):
        with make_server(spec, max_slots=2) as srv:
            lg = GenerativeLoadGenerator(srv, seed=5, prompt_len=(1, 6),
                                         new_tokens=(2, 4),
                                         temperature=0.8)
            res = lg.run_closed(n_requests=6, concurrency=2)
        assert res.n_ok == 6 and res.tokens_total > 0


# ----------------------------------------------------------------------
class TestObservability:
    def test_metrics_record_fold_and_prometheus(self, spec):
        from deeplearning4j_tpu.monitor.registry import MetricsRegistry
        with make_server(spec, max_slots=2) as srv:
            srv.generate(np.asarray([1, 2], np.int32), max_new_tokens=5)
            rec = srv.metrics.to_record()
        assert rec["type"] == "serving"
        g = rec["generative"]
        assert g["tokens_generated"] == 5 and g["prefills"] == 1
        assert 0 < g["slot_occupancy"] <= 1.0
        assert rec["latency_ms"]["ttft"]["count"] == 1
        assert rec["latency_ms"]["intertoken"]["count"] == 4
        reg = MetricsRegistry()
        reg.fold_serving(rec)
        text = reg.to_prometheus_text()
        for needle in ("dl4j_serving_tokens_generated_total",
                       "dl4j_serving_slot_occupancy_ratio",
                       "dl4j_serving_tokens_per_sec",
                       "dl4j_serving_latency_ms"):
            assert needle in text, needle

    def test_report_renders_generative_panel(self, spec):
        from deeplearning4j_tpu.ui.report import render_report
        from deeplearning4j_tpu.ui.stats import StatsStorage
        storage = StatsStorage()
        with make_server(spec, max_slots=2,
                         stats_storage=storage) as srv:
            srv.generate(np.asarray([3], np.int32), max_new_tokens=4)
        html = render_report(storage)
        assert "generative:" in html
        assert "ttft" in html and "intertoken" in html
        assert "slot occupancy" in html

    def test_kv_slab_bytes_tracked(self, spec):
        from deeplearning4j_tpu.monitor import memstats
        with make_server(spec, max_slots=2) as srv:
            rep = srv.memory_report()
            assert rep["kv_slab_bytes"] == srv.kv_slab_bytes > 0
            assert rep["kv_bytes_per_slot"] * 2 == rep["kv_slab_bytes"]
            rec = memstats.memory_record()
            assert rec["tracked"].get("kv_slab", 0) >= srv.kv_slab_bytes
        # released on shutdown
        rec2 = memstats.memory_record()
        assert rec2["tracked"].get("kv_slab", 0) < rep["kv_slab_bytes"] \
            or rec2["tracked"].get("kv_slab", 0) == 0

    def test_warmup_captures_memory_plans(self, gpt_sd):
        from deeplearning4j_tpu.compilecache.aot import ph_shape_sig
        from deeplearning4j_tpu.monitor import memstats
        import jax
        import jax.numpy as jnp
        fresh_spec = gpt_generative_spec(gpt_sd, CFG)
        with make_server(fresh_spec, max_slots=3, warmup=True) as srv:
            S = 3
            sig = ph_shape_sig(
                {"tokens": jax.ShapeDtypeStruct((S,), jnp.int32),
                 "positions": jax.ShapeDtypeStruct((S,), jnp.int32),
                 "active": jax.ShapeDtypeStruct((S,), jnp.bool_)})
            plan = memstats.PLANS.get(sig)
            assert plan is not None
            assert srv.warmup_report["seconds"] > 0

    def test_decode_spans_emitted(self, spec):
        from deeplearning4j_tpu.monitor.trace import TRACER
        was = TRACER.enabled
        TRACER.enabled = True
        try:
            with make_server(spec, max_slots=2) as srv:
                TRACER.drain()      # discard history
                srv.generate(np.asarray([2, 4], np.int32),
                             max_new_tokens=4)
                time.sleep(0.02)
                names = {s.name for s in TRACER.drain()[0]}
        finally:
            TRACER.enabled = was
        assert "serving.prefill" in names
        assert "serving.decode" in names
        assert "serving.enqueue" in names

    def test_telemetry_endpoint_exports_generative_gauges(self, spec):
        from deeplearning4j_tpu.ui.stats import StatsStorage
        storage = StatsStorage()
        with make_server(spec, max_slots=2, stats_storage=storage,
                         telemetry_port=0) as srv:
            srv.generate(np.asarray([5], np.int32), max_new_tokens=4)
            url = srv.telemetry.url
            with urllib.request.urlopen(url + "/metrics", timeout=5) as r:
                text = r.read().decode()
            with urllib.request.urlopen(url + "/healthz", timeout=5) as r:
                assert r.status == 200
        assert "dl4j_serving_tokens_generated_total" in text
        assert "dl4j_serving_slot_occupancy_ratio" in text

    def test_two_seq_lens_both_stay_warm(self, gpt_sd):
        """Review regression: AOT entries are keyed per (spec, slab
        shape) — a second server over the same spec with a different
        max_seq_len must get its own warmed programs, not silently
        fall off the first server's onto lazy traffic compiles."""
        fresh_spec = gpt_generative_spec(gpt_sd, CFG)
        p = np.asarray([5, 6], np.int32)
        with GenerativeServer(fresh_spec, max_slots=4, max_seq_len=16,
                              warmup=True) as s1:
            s1.generate(p, max_new_tokens=4)
            assert s1.metrics.counters["compiles"] == 0
        with GenerativeServer(fresh_spec, max_slots=4, max_seq_len=32,
                              warmup=True) as s2:
            s2.generate(p, max_new_tokens=4)
            assert s2.metrics.counters["compiles"] == 0

    def test_tokens_timeout_typed_and_resumable(self, spec):
        """Review regression: a per-token wait timeout raises the
        builtin TimeoutError (not a leaked queue.Empty), and the
        stream resumes afterwards."""
        srv = make_server(spec, start=False)
        try:
            h = srv.submit(np.asarray([1], np.int32), max_new_tokens=3)
            it = h.tokens(timeout=0.05)
            with pytest.raises(TimeoutError, match="still in flight"):
                next(it)
            srv.start()
            h.result(timeout=60)
            assert list(h.tokens(timeout=5)) == h.result()
        finally:
            srv.shutdown()

    def test_shutdown_never_started_fails_queued_typed(self, spec):
        """Review regression: shutdown of a start=False server has no
        worker to drain — queued futures fail typed instead of
        hanging their clients forever."""
        srv = make_server(spec, start=False)
        h = srv.submit(np.asarray([1], np.int32), max_new_tokens=3)
        srv.shutdown(drain=True, timeout=5)
        with pytest.raises(ServerClosedError):
            h.result(timeout=5)
        with pytest.raises(ServerClosedError):
            list(h.tokens(timeout=5))

    def test_cancel_counted_consistently(self, spec):
        """Review regression: a cancel is requests_cancelled whether it
        was still queued or already occupying a slot — never silently
        unaccounted, never counted as served."""
        with make_server(spec, max_slots=1) as srv:
            # slot-occupying cancel
            h1 = srv.submit(np.asarray([1], np.int32), max_new_tokens=400,
                            on_token=lambda t: time.sleep(0.02))
            # queued cancel (slot busy behind h1)
            h2 = srv.submit(np.asarray([2], np.int32), max_new_tokens=4)
            time.sleep(0.05)
            h1.cancel()
            h2.cancel()
            h1.result(timeout=60)
            h2.result(timeout=60)
            deadline = time.monotonic() + 5
            while srv.metrics.counters["requests_cancelled"] < 2 and \
                    time.monotonic() < deadline:
                time.sleep(0.01)
            c = srv.metrics.to_record()["counters"]
        assert c["requests_cancelled"] == 2
        assert c["requests_served"] + c["requests_cancelled"] \
            + c["requests_failed"] + c["requests_timed_out"] == 2

    def test_shutdown_drains_queued_generations(self, spec):
        srv = make_server(spec, max_slots=2, start=False)
        hs = [srv.submit(p, 4) for p in mixed_prompts(5, seed=9)]
        srv.start()
        srv.shutdown(drain=True, timeout=60)
        for h in hs:
            assert len(h.result(timeout=1)) == 4
