"""checkpoint/ subsystem: atomic commit protocol, async writer, retention,
bit-exact resume, torn-checkpoint recovery, preemption.

The two acceptance properties of ISSUE 2:
- resume-from-checkpoint reproduces the uninterrupted run bit-exactly
  (params, updater state, RNG, loss trajectory);
- a checkpoint directory killed mid-write is detected as uncommitted
  and skipped by restore_latest().
"""
import json
import os
import signal
import threading
import zipfile

import numpy as np
import pytest

from deeplearning4j_tpu.checkpoint import (
    CheckpointError, CheckpointListener, CheckpointManager,
    CheckpointModelSaver, Preempted, PreemptionHook, atomic_copy,
    atomic_output_file, atomic_write_bytes, capture_training_state,
    restore_training_state)
from deeplearning4j_tpu.checkpoint import manifest as ckpt_manifest
from deeplearning4j_tpu.learning.updaters import Adam
from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                   MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)


def _conf(dropout=None):
    b = (NeuralNetConfiguration.builder()
         .seed(7)
         .updater(Adam(learning_rate=0.05)))
    dense = DenseLayer(n_out=16, activation="tanh", **(
        {"dropout": dropout} if dropout else {}))
    return (b.list()
            .layer(dense)
            .layer(OutputLayer(n_out=2, loss_function="MCXENT"))
            .set_input_type(InputType.feed_forward(2))
            .build())


def _xor():
    X = np.tile(np.array([[0, 0], [0, 1], [1, 0], [1, 1]], np.float32),
                (16, 1))
    Y = np.eye(2, dtype=np.float32)[
        (X[:, 0].astype(int) ^ X[:, 1].astype(int))]
    return X, Y


def _net(dropout=None):
    return MultiLayerNetwork(_conf(dropout)).init()


# ---------------------------------------------------------------------------
# atomic primitives (satellites)

class TestAtomic:
    def test_write_bytes_publishes_complete_file(self, tmp_path):
        p = tmp_path / "x.bin"
        atomic_write_bytes(p, b"hello")
        assert p.read_bytes() == b"hello"
        assert not [f for f in os.listdir(tmp_path) if ".tmp" in f]

    def test_failed_write_preserves_previous_content(self, tmp_path):
        p = tmp_path / "x.bin"
        atomic_write_bytes(p, b"old complete artifact")
        with pytest.raises(RuntimeError):
            with atomic_output_file(p) as tmp:
                with open(tmp, "wb") as fh:
                    fh.write(b"partial garb")
                raise RuntimeError("simulated crash mid-write")
        assert p.read_bytes() == b"old complete artifact"
        assert not [f for f in os.listdir(tmp_path) if ".tmp" in f]

    def test_failed_write_leaves_no_target(self, tmp_path):
        p = tmp_path / "never.bin"
        with pytest.raises(RuntimeError):
            with atomic_output_file(p) as tmp:
                with open(tmp, "wb") as fh:
                    fh.write(b"part")
                raise RuntimeError("crash")
        assert not p.exists()

    def test_published_file_honors_umask(self, tmp_path):
        """mkstemp's 0600 must not leak onto published artifacts —
        shared checkpoint dirs need the same mode a plain open() gives."""
        p = tmp_path / "x.bin"
        atomic_write_bytes(p, b"data")
        umask = os.umask(0)
        os.umask(umask)
        assert (os.stat(p).st_mode & 0o777) == (0o666 & ~umask)

    def test_atomic_copy(self, tmp_path):
        src = tmp_path / "src.bin"
        src.write_bytes(b"artifact")
        dst = tmp_path / "cache" / "dst.bin"
        atomic_copy(src, dst)
        assert dst.read_bytes() == b"artifact"


def test_save_net_zip_is_crash_safe(tmp_path, monkeypatch):
    """A save that dies mid-serialization must not tear an existing zip."""
    net = _net()
    X, Y = _xor()
    net.fit(X, Y, epochs=1, batch_size=16)
    path = tmp_path / "model.zip"
    net.save(path)
    before = path.read_bytes()
    # crash inside the serializer, after the zip is partially written
    import deeplearning4j_tpu.nn.model_serde as ms
    real_savez = np.savez

    def boom(*a, **k):
        raise OSError("simulated disk failure")
    monkeypatch.setattr(ms.np, "savez", boom)
    with pytest.raises(OSError):
        net.save(path)
    monkeypatch.setattr(ms.np, "savez", real_savez)
    assert path.read_bytes() == before          # old artifact intact
    assert MultiLayerNetwork.load(path) is not None
    assert not [f for f in os.listdir(tmp_path) if ".tmp" in f]


def test_hub_add_atomic(tmp_path, monkeypatch):
    from deeplearning4j_tpu.hub.cache import ModelHub
    hub = ModelHub(cache_dir=str(tmp_path / "hub"))
    src = tmp_path / "weights.h5"
    src.write_bytes(b"w" * 4096)
    hub.add("weights.h5", str(src))
    assert hub.contains("weights.h5")
    # interrupted copy: entry must not become visible
    import deeplearning4j_tpu.checkpoint.atomic as at

    def boom(src_, dst_):
        with open(dst_, "wb") as fh:
            fh.write(b"half")
        raise OSError("copy died")
    monkeypatch.setattr(at.shutil, "copy2", boom)
    with pytest.raises(OSError):
        hub.add("other.h5", str(src))
    assert not hub.contains("other.h5")
    assert "other.h5" not in hub.list()


def test_earlystopping_saver_atomic(tmp_path):
    """LocalFileModelSaver best-model files survive a crash during an
    improvement save (routed through the atomic helper)."""
    from deeplearning4j_tpu.autodiff.earlystopping import LocalFileModelSaver
    net = _net()
    X, Y = _xor()
    net.fit(X, Y, epochs=1, batch_size=16)
    saver = LocalFileModelSaver(str(tmp_path))
    saver.save_best(net, 0, 0.5)
    before = open(saver.best_path, "rb").read()

    class CrashyModel:
        def save(self, path):
            with open(path, "wb") as fh:
                fh.write(b"torn")
            raise OSError("crash mid improvement save")

    with pytest.raises(OSError):
        saver.save_best(CrashyModel(), 1, 0.4)
    assert open(saver.best_path, "rb").read() == before
    with zipfile.ZipFile(saver.best_path) as zf:   # still a valid zip
        assert "configuration.json" in zf.namelist()


# ---------------------------------------------------------------------------
# manager: commit protocol + retention

class TestManagerBasics:
    def test_sync_roundtrip(self, tmp_path):
        net = _net()
        X, Y = _xor()
        net.fit(X, Y, epochs=2, batch_size=16)
        mgr = CheckpointManager(tmp_path, async_write=False)
        mgr.save(4, model=net, epoch=2)
        assert mgr.all_steps() == [4]
        net2 = _net()
        step, state = mgr.restore_latest(model=net2)
        assert step == 4
        for n, a in net.params().items():
            np.testing.assert_array_equal(a, net2.params()[n])
        assert state.iteration == net.samediff.training_config.iteration_count

    def test_commit_layout(self, tmp_path):
        mgr = CheckpointManager(tmp_path, async_write=False)
        net = _net()
        mgr.save(1, model=net)
        d = mgr.step_dir(1)
        names = set(os.listdir(d))
        assert {"COMMIT", "MANIFEST.json", "state.json",
                "arrays.npz"} <= names
        with open(os.path.join(d, "MANIFEST.json")) as fh:
            man = json.load(fh)["files"]
        assert "arrays.npz" in man
        assert set(man["arrays.npz"]) == {"size", "sha256"}
        assert ckpt_manifest.is_committed(d)

    def test_keep_last_n(self, tmp_path):
        mgr = CheckpointManager(tmp_path, keep_last_n=2, async_write=False)
        net = _net()
        for s in (1, 2, 3, 4):
            mgr.save(s, model=net)
        assert mgr.all_steps() == [3, 4]

    def test_keep_every_n_epochs(self, tmp_path):
        mgr = CheckpointManager(tmp_path, keep_last_n=1,
                                keep_every_n_epochs=2, async_write=False)
        net = _net()
        for s, e in [(1, 1), (2, 2), (3, 3), (4, 4), (5, 5)]:
            mgr.save(s, model=net, epoch=e)
        # epochs 2 and 4 kept permanently, plus last-1 (step 5)
        assert mgr.all_steps() == [2, 4, 5]

    def test_pin_best(self, tmp_path):
        mgr = CheckpointManager(tmp_path, keep_last_n=1,
                                pin_best_metric="loss", async_write=False)
        net = _net()
        for s, l in [(1, 0.9), (2, 0.2), (3, 0.5), (4, 0.6)]:
            mgr.save(s, model=net, metrics={"loss": l})
        assert mgr.best_step() == 2
        assert mgr.all_steps() == [2, 4]      # best pinned + last 1

    def test_explicit_pin(self, tmp_path):
        mgr = CheckpointManager(tmp_path, keep_last_n=1, async_write=False)
        net = _net()
        mgr.save(1, model=net, pin=True)
        for s in (2, 3):
            mgr.save(s, model=net)
        assert mgr.all_steps() == [1, 3]

    def test_resave_same_step(self, tmp_path):
        mgr = CheckpointManager(tmp_path, async_write=False)
        net = _net()
        mgr.save(1, model=net)
        mgr.save(1, model=net)        # e.g. restart re-saves its step
        assert mgr.all_steps() == [1]
        assert not [e for e in os.listdir(tmp_path)
                    if e.endswith((".tmp", ".old"))]

    def test_resave_crash_keeps_committed_step(self, tmp_path, monkeypatch):
        """A crash while RE-saving an existing step must not destroy the
        committed checkpoint — the old dir is only swapped aside across
        the rename, never deleted before the replacement is staged."""
        import deeplearning4j_tpu.checkpoint.manager as mg
        mgr = CheckpointManager(tmp_path, async_write=False)
        net = _net()
        mgr.save(1, model=net)
        want = mgr.restore(1).arrays

        def boom(*a, **k):
            raise OSError("killed during re-save staging")
        monkeypatch.setattr(mg, "write_state_files", boom)
        with pytest.raises(OSError):
            mgr.save(1, model=net)
        monkeypatch.undo()
        state = mgr.restore(1)         # original commit fully intact
        for n, a in want.items():
            np.testing.assert_array_equal(a, state.arrays[n])


class TestAsyncWriter:
    def test_no_tmp_entries_after_wait(self, tmp_path):
        """Required by ISSUE satellite: after wait_until_finished() the
        directory never contains .tmp entries."""
        mgr = CheckpointManager(tmp_path, keep_last_n=None)
        net = _net()
        X, Y = _xor()
        net.fit(X, Y, epochs=1, batch_size=16)
        for s in range(5):
            mgr.save(s, model=net, epoch=s)
        mgr.wait_until_finished()
        entries = os.listdir(tmp_path)
        assert not [e for e in entries if e.endswith(".tmp")], entries
        assert mgr.all_steps() == [0, 1, 2, 3, 4]
        mgr.close()

    def test_async_error_surfaces(self, tmp_path, monkeypatch):
        import deeplearning4j_tpu.checkpoint.manager as mg

        def boom(*a, **k):
            raise OSError("disk full")
        monkeypatch.setattr(mg, "write_state_files", boom)
        mgr = CheckpointManager(tmp_path)
        mgr.save(1, model=_net())
        with pytest.raises(CheckpointError, match="disk full"):
            mgr.wait_until_finished()
        assert mgr.all_steps() == []

    def test_async_error_surfaces_on_next_save(self, tmp_path, monkeypatch):
        import deeplearning4j_tpu.checkpoint.manager as mg
        real = mg.write_state_files
        calls = []

        def boom_once(*a, **k):
            calls.append(1)
            if len(calls) == 1:
                raise OSError("transient")
            return real(*a, **k)
        monkeypatch.setattr(mg, "write_state_files", boom_once)
        mgr = CheckpointManager(tmp_path)
        net = _net()
        mgr.save(1, model=net)
        # wait for the failure to land, then the NEXT save raises
        with mgr._cv:
            mgr._cv.wait_for(lambda: mgr._inflight == 0, timeout=30)
        with pytest.raises(CheckpointError, match="transient"):
            mgr.save(2, model=net)
        # error is cleared after raising; manager keeps working
        mgr.save(3, model=net)
        mgr.wait_until_finished()
        assert mgr.all_steps() == [3]


# ---------------------------------------------------------------------------
# torn-checkpoint detection (acceptance criterion)

class TestTornCheckpointRecovery:
    def _mgr_with_two(self, tmp_path):
        mgr = CheckpointManager(tmp_path, keep_last_n=None,
                                async_write=False)
        net = _net()
        X, Y = _xor()
        net.fit(X, Y, epochs=1, batch_size=16)
        mgr.save(10, model=net)
        net.fit(X, Y, epochs=1, batch_size=16)
        mgr.save(20, model=net)
        assert mgr.all_steps() == [10, 20]
        return mgr

    def test_truncated_payload_skipped(self, tmp_path):
        mgr = self._mgr_with_two(tmp_path)
        p = os.path.join(mgr.step_dir(20), "arrays.npz")
        with open(p, "r+b") as fh:
            fh.truncate(os.path.getsize(p) // 2)
        step, _ = mgr.restore_latest()
        assert step == 10

    def test_bitflip_payload_skipped(self, tmp_path):
        """Same size, corrupted content — only the sha256 catches it."""
        mgr = self._mgr_with_two(tmp_path)
        p = os.path.join(mgr.step_dir(20), "arrays.npz")
        data = bytearray(open(p, "rb").read())
        data[len(data) // 2] ^= 0xFF
        with open(p, "wb") as fh:
            fh.write(data)
        step, _ = mgr.restore_latest()
        assert step == 10

    def test_corrupt_manifest_skipped(self, tmp_path):
        mgr = self._mgr_with_two(tmp_path)
        with open(os.path.join(mgr.step_dir(20), "MANIFEST.json"),
                  "w") as fh:
            fh.write("{not json")
        step, _ = mgr.restore_latest()
        assert step == 10

    def test_missing_commit_marker_skipped(self, tmp_path):
        mgr = self._mgr_with_two(tmp_path)
        os.remove(os.path.join(mgr.step_dir(20), "COMMIT"))
        step, _ = mgr.restore_latest()
        assert step == 10

    def test_tmp_dir_from_killed_writer_skipped_and_gcd(self, tmp_path):
        mgr = self._mgr_with_two(tmp_path)
        torn = os.path.join(str(tmp_path), "step_00000030.tmp")
        os.makedirs(torn)
        with open(os.path.join(torn, "arrays.npz"), "wb") as fh:
            fh.write(b"half a checkpoint")
        step, _ = mgr.restore_latest()
        assert step == 20
        removed = mgr.gc_uncommitted()
        assert torn in removed
        assert not os.path.exists(torn)

    def test_interrupted_resave_swap_recovers_old_commit(self, tmp_path):
        """Crash between the two re-save renames leaves step_N.old (the
        committed old checkpoint) and no step_N — recovery renames it
        back rather than gc-ing committed data."""
        mgr = self._mgr_with_two(tmp_path)
        final = mgr.step_dir(20)
        os.rename(final, final + ".old")          # crash mid-swap
        step, _ = mgr.restore_latest()            # in-process recovery
        assert step == 20
        assert os.path.isdir(final)
        # and a fresh manager (process restart) also recovers
        os.rename(final, final + ".old")
        mgr2 = CheckpointManager(tmp_path, async_write=False)
        assert mgr2.latest_step() == 20
        assert mgr2.gc_uncommitted() == []

    def test_all_torn_returns_none(self, tmp_path):
        mgr = CheckpointManager(tmp_path, async_write=False)
        net = _net()
        mgr.save(5, model=net)
        os.remove(os.path.join(mgr.step_dir(5), "COMMIT"))
        assert mgr.restore_latest() is None

    def test_restore_specific_step_verifies(self, tmp_path):
        mgr = self._mgr_with_two(tmp_path)
        os.remove(os.path.join(mgr.step_dir(20), "COMMIT"))
        with pytest.raises(CheckpointError, match="COMMIT"):
            mgr.restore(20)


# ---------------------------------------------------------------------------
# bit-exact resume (THE acceptance criterion)

class TestBitExactResume:
    K, J = 6, 3        # epochs: straight K vs J + (K-J) resumed

    def _fit_losses(self, net, X, Y, epochs):
        h = net.fit(X, Y, epochs=epochs, batch_size=16)
        return list(h.loss_curve.losses)

    @pytest.mark.parametrize("dropout", [None, 0.8],
                             ids=["deterministic", "dropout_rng"])
    def test_resume_matches_uninterrupted(self, tmp_path, dropout):
        X, Y = _xor()
        # --- uninterrupted run -------------------------------------
        netA = _net(dropout)
        lossesA = self._fit_losses(netA, X, Y, self.K)
        # --- interrupted run: J epochs, checkpoint, "new process" --
        netB = _net(dropout)
        lossesB = self._fit_losses(netB, X, Y, self.J)
        mgr = CheckpointManager(tmp_path, async_write=False)
        mgr.save(self.J, model=netB, epoch=self.J)
        # fresh net = fresh process (same conf/seed, new arrays)
        netC = _net(dropout)
        step, state = mgr.restore_latest(model=netC)
        assert step == self.J
        lossesC = self._fit_losses(netC, X, Y, self.K - self.J)
        # --- loss trajectory identical -----------------------------
        np.testing.assert_array_equal(
            np.asarray(lossesA), np.asarray(lossesB + lossesC))
        # --- params bit-exact --------------------------------------
        pA, pC = netA.params(), netC.params()
        assert set(pA) == set(pC)
        for n in pA:
            np.testing.assert_array_equal(pA[n], pC[n], err_msg=n)
        # --- updater leaves bit-exact ------------------------------
        import jax
        lA = jax.tree_util.tree_leaves(netA.samediff._updater_state)
        lC = jax.tree_util.tree_leaves(netC.samediff._updater_state)
        assert len(lA) == len(lC) > 0
        for a, c in zip(lA, lC):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(c))
        # --- RNG + counters ----------------------------------------
        assert netC.samediff._fit_base_seed == netA.samediff._fit_base_seed
        assert (netC.samediff.training_config.iteration_count ==
                netA.samediff.training_config.iteration_count)

    def test_mid_epoch_listener_checkpoint_resumes_bit_exact(self, tmp_path):
        """Checkpoint taken by the listener MID-epoch (iteration cadence)
        carries updater state + iteration, so resume from it matches the
        uninterrupted run from that iteration on."""
        X, Y = _xor()                 # 64 rows = 4 batches of 16 / epoch
        netA = _net()
        netA.fit(X, Y, epochs=2, batch_size=16)     # iterations 0..7
        netB = _net()
        mgr = CheckpointManager(tmp_path, keep_last_n=None,
                                async_write=False)
        lst = CheckpointListener(mgr, every_n_iterations=3)
        netB.fit(X, Y, epochs=2, batch_size=16, listeners=[lst])
        steps = mgr.all_steps()
        assert 3 in steps             # fired mid-epoch after iteration 2
        state = mgr.restore(3)
        assert state.iteration == 3   # 3 steps done at snapshot time
        netC = _net()
        restore_training_state(netC, state)
        # finish the epoch the snapshot interrupted: batch 3 alone,
        # then the full second epoch — iterations 3, then 4..7
        netC.fit(X[48:64], Y[48:64], epochs=1, batch_size=16)
        netC.fit(X, Y, epochs=1, batch_size=16)
        pA, pC = netA.params(), netC.params()
        for n in pA:
            np.testing.assert_array_equal(pA[n], pC[n], err_msg=n)

    def test_normalizer_roundtrip(self, tmp_path):
        from deeplearning4j_tpu.dataset.normalizers import \
            NormalizerStandardize
        X, Y = _xor()
        norm = NormalizerStandardize().fit(X + np.float32(3.5))
        net = _net()
        mgr = CheckpointManager(tmp_path, async_write=False)
        mgr.save(0, model=net, normalizer=norm)
        _, state = mgr.restore_latest()
        norm2 = state.make_normalizer()
        assert isinstance(norm2, NormalizerStandardize)
        np.testing.assert_array_equal(norm.mean, norm2.mean)
        np.testing.assert_array_equal(norm.std, norm2.std)

    def test_strict_restore_rejects_mismatched_graph(self, tmp_path):
        net = _net()
        mgr = CheckpointManager(tmp_path, async_write=False)
        mgr.save(0, model=net)
        other = MultiLayerNetwork(
            (NeuralNetConfiguration.builder().seed(1)
             .updater(Adam(learning_rate=0.05)).list()
             .layer(DenseLayer(n_out=4, activation="relu"))
             .layer(DenseLayer(n_out=16, activation="tanh"))
             .layer(OutputLayer(n_out=2))
             .set_input_type(InputType.feed_forward(2)).build())).init()
        with pytest.raises(ValueError, match="does not cover"):
            mgr.restore_latest(model=other)
        # non-strict restores the intersection
        assert mgr.restore_latest(model=other, strict=False) is not None


# ---------------------------------------------------------------------------
# listener cadences + stats + savers + preemption

class TestCheckpointListener:
    def test_epoch_cadence(self, tmp_path):
        net = _net()
        X, Y = _xor()
        mgr = CheckpointManager(tmp_path, keep_last_n=None)
        lst = CheckpointListener(mgr, every_n_epochs=2)
        net.fit(X, Y, epochs=5, batch_size=16, listeners=[lst])
        # on_training_end waits, so commits are visible here
        assert len(mgr.all_steps()) == 2          # after epochs 2 and 4
        assert lst.last_checkpoint() == mgr.latest_step()
        assert not [e for e in os.listdir(tmp_path)
                    if e.endswith(".tmp")]

    def test_iteration_cadence_keep_last(self, tmp_path):
        net = _net()
        X, Y = _xor()
        X, Y = np.tile(X, (4, 1)), np.tile(Y, (4, 1))
        mgr = CheckpointManager(tmp_path, keep_last_n=2)
        lst = CheckpointListener(mgr, every_n_iterations=2)
        net.fit(X, Y, epochs=2, batch_size=16, listeners=[lst])
        steps = mgr.all_steps()
        assert len(steps) == 2                    # retention applied
        state = mgr.restore(steps[-1])
        assert state.iteration == steps[-1]       # step = iters completed

    def test_cadences_dedupe_same_step(self, tmp_path):
        """Iteration cadence firing at an epoch boundary must not commit
        the identical state twice (same step numbering across cadences)."""
        from deeplearning4j_tpu.ui.stats import StatsStorage
        storage = StatsStorage()
        net = _net()
        X, Y = _xor()                 # 4 batches of 16 per epoch
        mgr = CheckpointManager(tmp_path, keep_last_n=None,
                                stats_storage=storage)
        lst = CheckpointListener(mgr, every_n_iterations=4,
                                 every_n_epochs=1)
        net.fit(X, Y, epochs=2, batch_size=16, listeners=[lst])
        assert mgr.all_steps() == [4, 8]
        assert len(storage.of_type("checkpoint")) == 2   # no doubles

    def test_builder_parity(self, tmp_path):
        lst = (CheckpointListener.builder(str(tmp_path))
               .keep_last(5)
               .save_every_n_epochs(2)
               .build())
        assert lst.every_n_epochs == 2
        assert lst.manager.keep_last_n == 5

    def test_requires_cadence(self, tmp_path):
        with pytest.raises(ValueError, match="cadence"):
            CheckpointListener(str(tmp_path))

    def test_epoch_only_listener_stays_off_hot_path(self, tmp_path):
        """Epoch-only cadence must not force frequent mid-epoch flushes:
        needs_params makes every flush copy params + updater state."""
        lst = CheckpointListener(CheckpointManager(tmp_path),
                                 every_n_epochs=1)
        assert lst.frequency >= 10 ** 6

    def test_seconds_cadence_rejected_multihost(self, tmp_path):
        mgr = CheckpointManager(tmp_path, process_index=0, process_count=2,
                                barrier=lambda tag: None)
        with pytest.raises(ValueError, match="multihost"):
            CheckpointListener(mgr, every_n_seconds=10)

    def test_stats_records(self, tmp_path):
        from deeplearning4j_tpu.ui.stats import StatsStorage
        storage = StatsStorage()
        net = _net()
        X, Y = _xor()
        mgr = CheckpointManager(tmp_path, stats_storage=storage)
        lst = CheckpointListener(mgr, every_n_epochs=1)
        net.fit(X, Y, epochs=3, batch_size=16, listeners=[lst])
        recs = storage.of_type("checkpoint")
        assert len(recs) == 3
        for r in recs:
            assert r["bytes"] > 0
            assert r["commit_seconds"] >= 0
            assert r["async"] is True


def test_computation_graph_checkpoint_roundtrip(tmp_path):
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    conf = (NeuralNetConfiguration.builder()
            .seed(3).updater(Adam(learning_rate=0.05))
            .graph_builder()
            .add_inputs("in")
            .set_input_types(InputType.feed_forward(2))
            .add_layer("d", DenseLayer(n_out=8, activation="tanh"), "in")
            .add_layer("out", OutputLayer(n_out=2), "d")
            .set_outputs("out")
            .build())
    g = ComputationGraph(conf).init()
    X, Y = _xor()
    g.fit(X, Y, epochs=2, batch_size=16)
    mgr = CheckpointManager(tmp_path, async_write=False)
    mgr.save(0, state=g.capture_training_state(epoch=2))
    g2 = ComputationGraph(conf).init()
    _, state = mgr.restore_latest()
    g2.restore_training_state(state)
    for n, a in g.params().items():
        np.testing.assert_array_equal(a, g2.params()[n])


def test_checkpoint_model_saver_earlystopping(tmp_path):
    from deeplearning4j_tpu.autodiff.earlystopping import (
        EarlyStoppingConfiguration, EarlyStoppingTrainer,
        MaxEpochsTerminationCondition)
    net = _net()
    X, Y = _xor()
    saver = CheckpointModelSaver(str(tmp_path))
    cfg = (EarlyStoppingConfiguration.builder()
           .epoch_termination_conditions(MaxEpochsTerminationCondition(4))
           .model_saver(saver)
           .build())
    from deeplearning4j_tpu.nn.multilayer import _ArrayIterator
    result = EarlyStoppingTrainer(
        cfg, net, _ArrayIterator(X, Y, 16)).fit(max_epochs=10)
    assert result.best_model_epoch >= 0
    assert saver.best_step == result.best_model_epoch
    assert saver.manager.best_step() == saver.best_step
    # the best checkpoint survived retention and restores cleanly
    state = saver.manager.restore(saver.best_step)
    assert state.metadata["metrics"]["score"] == pytest.approx(
        result.best_model_score)


class TestPreemption:
    def test_sigterm_commits_final_checkpoint(self, tmp_path):
        net = _net()
        X, Y = _xor()
        net.fit(X, Y, epochs=2, batch_size=16)
        mgr = CheckpointManager(tmp_path)
        with pytest.raises(Preempted) as ei:
            with PreemptionHook(mgr, net,
                                epoch_provider=lambda: 2) as hook:
                PreemptionHook.simulate()       # scheduler sends SIGTERM
        assert ei.value.code == 128 + signal.SIGTERM
        assert hook.preempted
        it = net.samediff.training_config.iteration_count
        assert hook.final_step == it
        # committed, verified, and bit-exact restorable
        net2 = _net()
        step, state = mgr.restore_latest(model=net2)
        assert step == it and state.epoch == 2
        for n, a in net.params().items():
            np.testing.assert_array_equal(a, net2.params()[n])

    def test_handlers_restored_after_uninstall(self, tmp_path):
        prev = signal.getsignal(signal.SIGTERM)
        hook = PreemptionHook(CheckpointManager(tmp_path), _net(),
                              reraise=False)
        hook.install()
        assert signal.getsignal(signal.SIGTERM) is not prev
        hook.uninstall()
        assert signal.getsignal(signal.SIGTERM) is prev

    def test_no_reraise_mode_polls(self, tmp_path):
        net = _net()
        mgr = CheckpointManager(tmp_path)
        with PreemptionHook(mgr, net, reraise=False) as hook:
            PreemptionHook.simulate()
            assert hook.preempted               # caller decides when to exit
        assert mgr.restore_latest() is not None


# ---------------------------------------------------------------------------
# multihost sharding + heavier async churn (slow tier)

@pytest.mark.slow
def test_multihost_sharded_commit_with_barrier(tmp_path):
    """Two 'processes' write disjoint shards into the same staging dir;
    the barrier gates the manifest so the commit can never miss a shard;
    restore merges shards back into the full array set."""
    net = _net()
    X, Y = _xor()
    net.fit(X, Y, epochs=1, batch_size=16)
    state0 = capture_training_state(net, epoch=1)
    n_params = len(state0.arrays)
    assert n_params >= 4
    barrier = threading.Barrier(2, timeout=30)
    mgrs = [CheckpointManager(tmp_path, process_index=i, process_count=2,
                              barrier=lambda tag: barrier.wait(),
                              async_write=False)
            for i in range(2)]
    errs = []

    def run(i):
        try:
            mgrs[i].save(7, state=capture_training_state(net, epoch=1))
        except BaseException as e:
            errs.append(e)
    ts = [threading.Thread(target=run, args=(i,)) for i in range(2)]
    [t.start() for t in ts]
    [t.join(timeout=60) for t in ts]
    assert not errs
    d = mgrs[0].step_dir(7)
    names = sorted(os.listdir(d))
    shard_files = [n for n in names if n.startswith("arrays.shard")]
    assert shard_files == ["arrays.shard00000-of-00002.npz",
                           "arrays.shard00001-of-00002.npz"]
    # every shard is covered by the manifest process 0 committed
    with open(os.path.join(d, "MANIFEST.json")) as fh:
        man = json.load(fh)["files"]
    assert set(shard_files) <= set(man)
    net2 = _net()
    step, state = mgrs[0].restore_latest(model=net2)
    assert step == 7
    assert set(state.arrays) == set(state0.arrays)
    for n, a in net.params().items():
        np.testing.assert_array_equal(a, net2.params()[n])


@pytest.mark.slow
def test_async_churn_many_steps_retention_consistent(tmp_path):
    """Sustained async saves with aggressive retention: directory ends
    consistent (committed steps only, no .tmp, retention honored)."""
    net = _net()
    X, Y = _xor()
    net.fit(X, Y, epochs=1, batch_size=16)
    with CheckpointManager(tmp_path, keep_last_n=3) as mgr:
        for s in range(30):
            mgr.save(s, model=net, epoch=s)
        mgr.wait_until_finished()
        steps = mgr.all_steps(verify=True)
        assert steps == [27, 28, 29]
        assert not [e for e in os.listdir(tmp_path) if e.endswith(".tmp")]


def test_parallel_trainer_restore_latest(tmp_path):
    from deeplearning4j_tpu.parallel.trainer import ParallelTrainer
    net = _net()
    X, Y = _xor()
    net.fit(X, Y, epochs=1, batch_size=16)
    mgr = CheckpointManager(tmp_path, async_write=False)
    mgr.save(3, model=net, epoch=1)
    net2 = _net()
    pt = ParallelTrainer(net2)
    res = pt.restore_latest(mgr)
    assert res is not None and res[0] == 3
    for n, a in net.params().items():
        np.testing.assert_array_equal(a, net2.params()[n])
