"""BERT frozen-graph import: golden forward + fine-tune step.

The generated GraphDef (zoo/bert.build_bert_graphdef) is decoded twice:
once by the importer (graph under test) and once here to read the weight
constants for an independent numpy reference forward pass.
"""
import numpy as np
import pytest

from deeplearning4j_tpu.modelimport.tf_pb import GraphDef
from deeplearning4j_tpu.modelimport.tf_import import import_tf_graph
from deeplearning4j_tpu.zoo.bert import (
    BERT_TINY, BertConfig, bert_base, build_bert_graphdef)

B, S = 2, 16


@pytest.fixture(scope="module")
def tiny_pb():
    return build_bert_graphdef(BERT_TINY, batch=B, seq_len=S, seed=7)


@pytest.fixture(scope="module")
def weights(tiny_pb):
    g = GraphDef(tiny_pb)
    out = {}
    for n in g.nodes:
        if n.op == "Const":
            out[n.name] = n.attrs["value"].tensor
    return out


def _np_layer_norm(x, gamma, beta, eps):
    m = x.mean(-1, keepdims=True)
    v = ((x - m) ** 2).mean(-1, keepdims=True)
    return (x - m) / np.sqrt(v + eps) * gamma + beta


def _np_gelu(x):
    from scipy.special import erf
    return x * 0.5 * (1.0 + erf(x / np.sqrt(2.0)))


def _np_bert_forward(cfg: BertConfig, w, input_ids, input_mask,
                     token_type_ids):
    H, A, D = cfg.hidden_size, cfg.num_heads, cfg.head_size
    eps = cfg.layer_norm_eps
    emb = w["bert/embeddings/word_embeddings"][input_ids]
    oh = np.eye(cfg.type_vocab_size, dtype=np.float32)[token_type_ids]
    emb = emb + oh @ w["bert/embeddings/token_type_embeddings"]
    emb = emb + w["bert/embeddings/position_embeddings"][:S]
    x = _np_layer_norm(emb, w["bert/embeddings/LayerNorm/gamma"],
                       w["bert/embeddings/LayerNorm/beta"], eps)
    adder = (1.0 - input_mask.astype(np.float32))[:, None, None, :] * -10000.0
    x2 = x.reshape(B * S, H)

    def dense(scope, t):
        return t @ w[f"{scope}/kernel"] + w[f"{scope}/bias"]

    for i in range(cfg.num_layers):
        sc = f"bert/encoder/layer_{i}"
        q = dense(f"{sc}/attention/self/query", x2)
        k = dense(f"{sc}/attention/self/key", x2)
        v = dense(f"{sc}/attention/self/value", x2)

        def heads(t):
            return t.reshape(B, S, A, D).transpose(0, 2, 1, 3)

        qh, kh, vh = heads(q), heads(k), heads(v)
        scores = qh @ kh.transpose(0, 1, 3, 2) / np.sqrt(D) + adder
        e = np.exp(scores - scores.max(-1, keepdims=True))
        probs = e / e.sum(-1, keepdims=True)
        ctx = (probs @ vh).transpose(0, 2, 1, 3).reshape(B * S, H)
        attn = dense(f"{sc}/attention/output/dense", ctx) + x2
        attn = _np_layer_norm(attn, w[f"{sc}/attention/output/LayerNorm/gamma"],
                              w[f"{sc}/attention/output/LayerNorm/beta"], eps)
        inter = _np_gelu(dense(f"{sc}/intermediate/dense", attn))
        out = dense(f"{sc}/output/dense", inter) + attn
        x2 = _np_layer_norm(out, w[f"{sc}/output/LayerNorm/gamma"],
                            w[f"{sc}/output/LayerNorm/beta"], eps)
    seq = x2.reshape(B, S, H)
    pooled = np.tanh(dense("bert/pooler/dense", seq[:, 0]))
    return seq, pooled


def test_bert_tiny_forward_matches_numpy(tiny_pb, weights):
    rng = np.random.RandomState(0)
    ids = rng.randint(0, BERT_TINY.vocab_size, (B, S)).astype(np.int32)
    mask = np.ones((B, S), np.int32)
    mask[0, S // 2:] = 0   # ragged mask exercises the additive bias
    tt = np.zeros((B, S), np.int32)

    sd = import_tf_graph(tiny_pb)
    res = sd.output(
        placeholders={"input_ids": ids, "input_mask": mask,
                      "token_type_ids": tt},
        outputs=["bert/encoder/sequence_output", "bert/pooler/output"])
    got_seq = np.asarray(res["bert/encoder/sequence_output"].data)
    got_pooled = np.asarray(res["bert/pooler/output"].data)

    want_seq, want_pooled = _np_bert_forward(BERT_TINY, weights, ids, mask, tt)
    np.testing.assert_allclose(got_seq, want_seq, rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(got_pooled, want_pooled, rtol=1e-3, atol=1e-4)


def test_bert_tiny_finetune_step():
    from deeplearning4j_tpu.autodiff.training import TrainingConfig
    from deeplearning4j_tpu.learning.updaters import Adam
    sd = bert_base(BERT_TINY, batch=B, seq_len=S, num_labels=2, seed=7)
    n_params = len(sd.trainable_params())
    # 2 emb tables + pos + LN(g,b) + per-layer 16 + pooler 2 + classifier 2
    assert n_params > 10
    sd.training_config = TrainingConfig(
        updater=Adam(1e-3),
        data_set_feature_mapping=["input_ids", "input_mask",
                                  "token_type_ids"],
        data_set_label_mapping=["labels"])
    rng = np.random.RandomState(1)
    ids = rng.randint(0, BERT_TINY.vocab_size, (B, S)).astype(np.int32)
    mask = np.ones((B, S), np.int32)
    tt = np.zeros((B, S), np.int32)
    labels = np.eye(2, dtype=np.float32)[rng.randint(0, 2, B)]
    batch = ([ids, mask, tt], [labels])
    h = sd.fit([batch] * 8, epochs=2)
    losses = h.loss_curve.losses
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], f"fine-tune loss not decreasing: {losses}"
