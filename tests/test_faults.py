"""faults/ — divergence sentinels, rollback-and-retry, chaos harness.

The chaos-marked tests drive deterministic fault injection end-to-end:
NaN gradients inside compiled windows, loader exceptions mid-epoch, torn
checkpoint commits, SIGTERM mid-window — each must be detected with
step/epoch/batch provenance and healed (or cleanly aborted) by
FaultTolerantFit. Every chaos test is individually timeout-guarded by
conftest's SIGALRM hook.
"""
import os
import signal

import numpy as np
import pytest

from deeplearning4j_tpu.autodiff import (SameDiff, ScoreIterationListener,
                                         TrainingConfig)
from deeplearning4j_tpu.checkpoint import CheckpointManager
from deeplearning4j_tpu.dataset.iterators import (ArrayDataSetIterator,
                                                  DeviceCachedIterator)
from deeplearning4j_tpu.faults import (ChaosMonkey, DataPipelineError,
                                       FaultBudgetExhaustedError,
                                       FaultTolerantFit, LossSpikeWatcher,
                                       PlateauWatcher, RetryPolicy,
                                       RetryingIterator,
                                       TrainingDivergedError)
from deeplearning4j_tpu.learning.updaters import Adam
from deeplearning4j_tpu.ui.stats import StatsStorage


def _mlp(fused_steps=4, sentinel=False, accum_steps=1, lr=1e-2):
    rng = np.random.default_rng(0)
    sd = SameDiff()
    x = sd.placeholder("x", shape=(-1, 8))
    w0 = sd.var("w0", value=rng.normal(0, .1, (8, 16)).astype(np.float32))
    b0 = sd.var("b0", value=np.zeros(16, np.float32))
    h = sd.nn.relu(x.mmul(w0).add(b0))
    w1 = sd.var("w1", value=rng.normal(0, .1, (16, 2)).astype(np.float32))
    logits = h.mmul(w1)
    labels = sd.placeholder("labels", shape=(-1, 2))
    sd.loss.softmax_cross_entropy(logits, labels, name="loss")
    sd.set_loss_variables(["loss"])
    sd.training_config = TrainingConfig(
        updater=Adam(lr), data_set_feature_mapping=["x"],
        data_set_label_mapping=["labels"], fused_steps=fused_steps,
        accum_steps=accum_steps, sentinel=sentinel)
    return sd


def _data(n=128, seed=1):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 8)).astype(np.float32)
    Y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, n)]
    return X, Y


def _quiet():
    return ScoreIterationListener(print_every=10 ** 9,
                                  print_fn=lambda *a: None)


# ---------------------------------------------------------------------------
# device-side sentinel

class TestDeviceSentinel:
    @pytest.mark.chaos
    def test_windowed_divergence_named_at_exact_step(self):
        sd = _mlp(fused_steps=4, sentinel=True)
        X, Y = _data()
        chaos = ChaosMonkey(seed=0)
        with chaos.nan_gradients(sd, at_step=5):
            with pytest.raises(TrainingDivergedError) as ei:
                sd.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=2)
        e = ei.value
        assert e.step == 5 and e.epoch == 0 and e.batch_index == 5
        assert e.cause == "device_sentinel"

    @pytest.mark.chaos
    def test_windowed_divergence_with_listeners_before_delivery(self):
        """Poisoned losses must not reach listeners: the flush checks
        sentinel verdicts BEFORE delivering the burst."""
        seen = []

        class Recorder(ScoreIterationListener):
            def iteration_done(self, sd, epoch, iteration, loss):
                seen.append(iteration)

        sd = _mlp(fused_steps=4, sentinel=True)
        X, Y = _data()
        chaos = ChaosMonkey(seed=0)
        with chaos.nan_gradients(sd, at_step=6):
            with pytest.raises(TrainingDivergedError):
                sd.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=1,
                       listeners=[Recorder(print_every=4,
                                           print_fn=lambda *a: None)])
        assert all(i < 4 for i in seen)   # only the pre-fault flush

    @pytest.mark.chaos
    def test_per_step_tier_divergence(self):
        sd = _mlp(fused_steps=1, sentinel=True)
        X, Y = _data()
        chaos = ChaosMonkey(seed=0)
        with chaos.nan_gradients(sd, at_step=3):
            with pytest.raises(TrainingDivergedError) as ei:
                sd.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=1,
                       listeners=[_quiet()])
        assert ei.value.step == 3

    @pytest.mark.chaos
    def test_per_step_tier_no_listeners_divergence(self):
        sd = _mlp(fused_steps=1, sentinel=True)
        X, Y = _data()
        chaos = ChaosMonkey(seed=0)
        with chaos.nan_gradients(sd, at_step=2):
            with pytest.raises(TrainingDivergedError) as ei:
                sd.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=1)
        assert ei.value.step == 2

    @pytest.mark.chaos
    def test_scanned_tier_divergence(self):
        sd = _mlp(fused_steps=1, sentinel=True)
        X, Y = _data()
        chaos = ChaosMonkey(seed=0)
        with chaos.nan_gradients(sd, at_step=4):
            with pytest.raises(TrainingDivergedError) as ei:
                sd.fit(DeviceCachedIterator(X, Y, batch_size=16), epochs=2)
        assert ei.value.step == 4

    @pytest.mark.chaos
    def test_scanned_tier_divergence_in_later_epoch(self):
        """Epoch provenance on the scanned tier: a fault in epoch 1 of a
        multi-epoch fit names epoch 1, not the fit-start epoch."""
        sd = _mlp(fused_steps=1, sentinel=True)
        X, Y = _data()                               # 8 steps/epoch
        chaos = ChaosMonkey(seed=0)
        with chaos.nan_gradients(sd, at_step=10):
            with pytest.raises(TrainingDivergedError) as ei:
                sd.fit(DeviceCachedIterator(X, Y, batch_size=16), epochs=3)
        assert ei.value.step == 10 and ei.value.epoch == 1

    @pytest.mark.chaos
    def test_accum_windowed_divergence(self):
        sd = _mlp(fused_steps=4, sentinel=True, accum_steps=2)
        X, Y = _data()
        chaos = ChaosMonkey(seed=0)
        with chaos.nan_gradients(sd, at_step=5):
            with pytest.raises(TrainingDivergedError) as ei:
                sd.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=2)
        assert ei.value.step == 5     # the micro-step, not its cycle

    @pytest.mark.chaos
    def test_nan_input_finite_loss_still_detected(self):
        """A where-based relu launders all-NaN FEATURES into a finite
        loss (NaN > 0 is False -> 0 activations -> loss = log(2)) while
        the first weight's gradient x^T @ delta still goes NaN and
        silently kills that parameter. Only the global grad-norm term of
        the sentinel can see this — pinned so the sentinel never regresses
        to loss-only or sampled-leaf checks."""
        sd = _mlp(fused_steps=4, sentinel=True)
        X, Y = _data()
        chaos = ChaosMonkey(seed=0)
        it = chaos.poison_batches(
            ArrayDataSetIterator(X, Y, batch_size=16), at_step=3)
        with pytest.raises(TrainingDivergedError) as ei:
            sd.fit(it, epochs=1)
        assert ei.value.step == 3
        assert ei.value.cause == "device_sentinel"

    @pytest.mark.chaos
    def test_tbptt_path_honors_sentinel(self):
        """fit_tbptt builds its own graph + TrainingConfig; an armed
        sentinel must follow onto it, not silently go inert."""
        from deeplearning4j_tpu.nn import (InputType, LSTMLayer,
                                           NeuralNetConfiguration,
                                           RnnOutputLayer)
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        conf = (NeuralNetConfiguration.builder().seed(0)
                .updater(Adam(1e-2)).list()
                .layer(LSTMLayer(n_out=8))
                .layer(RnnOutputLayer(n_out=2, loss_function="MCXENT"))
                .set_input_type(InputType.recurrent(3, 12))
                .build())
        net = MultiLayerNetwork(conf).init()
        net._sd_train.training_config.sentinel = True
        rng = np.random.default_rng(1)
        X = rng.normal(size=(16, 12, 3)).astype(np.float32)
        Y = np.eye(2, dtype=np.float32)[
            rng.integers(0, 2, (16, 12))]
        h = net.fit_tbptt(X, Y, tbptt_length=4, epochs=1, batch_size=16)
        assert np.isfinite(h.final_loss())       # clean run unaffected
        # arm device chaos on the cached TBPTT graph and expect the rail
        tb_sd, _ = net._tbptt_graphs[("tbptt", 16)]
        chaos = ChaosMonkey(seed=0)
        with chaos.nan_gradients(tb_sd, at_step=4):
            with pytest.raises(TrainingDivergedError) as ei:
                net.fit_tbptt(X, Y, tbptt_length=4, epochs=2,
                              batch_size=16)
        assert ei.value.step == 4

    def test_sentinel_off_vs_on_bit_identical(self):
        """Acceptance bar: with injection disabled, sentinel-enabled
        fused-window training is bit-identical to sentinel-off."""
        X, Y = _data()
        results = {}
        for flag in (False, True):
            sd = _mlp(fused_steps=4, sentinel=flag)
            h = sd.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=3,
                       listeners=[_quiet()])
            results[flag] = ({n: np.asarray(a) for n, a in
                              sd.trainable_params().items()},
                             h.final_loss())
        for n, a in results[False][0].items():
            np.testing.assert_array_equal(a, results[True][0][n])
        assert results[False][1] == results[True][1]

    def test_sentinel_keeps_dispatch_count_and_stats(self):
        sd = _mlp(fused_steps=4, sentinel=True)
        X, Y = _data()
        sd.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=1,
               listeners=[_quiet()])
        st = sd.last_fit_stats
        assert st["dispatches_per_epoch"] == 2     # ceil(8 / 4)
        assert st["sentinel"] is True

    def test_sentinel_serde_roundtrip(self):
        tc = TrainingConfig.builder().updater(Adam(1e-3)) \
            .sentinel(True).build()
        assert TrainingConfig.from_json(tc.to_json()).sentinel is True


# ---------------------------------------------------------------------------
# host-side watchers

class TestWatchers:
    def test_loss_spike_raises_with_provenance(self):
        w = LossSpikeWatcher(spike_factor=5.0, warmup=3)
        w.iterations_done(None, 0, [0, 1, 2, 3], [1.0, 0.9, 0.8, 0.9])
        with pytest.raises(TrainingDivergedError) as ei:
            w.iterations_done(None, 1, [4, 5], [0.85, 50.0])
        assert ei.value.step == 5 and ei.value.epoch == 1
        assert ei.value.cause == "loss_spike" and ei.value.value == 50.0

    def test_loss_spike_non_finite(self):
        w = LossSpikeWatcher()
        with pytest.raises(TrainingDivergedError) as ei:
            w.iterations_done(None, 0, [0], [float("nan")])
        assert ei.value.cause == "non_finite_loss"

    def test_no_false_positive_on_decreasing_loss(self):
        w = LossSpikeWatcher(spike_factor=3.0, warmup=2)
        losses = list(np.linspace(2.0, 0.1, 50))
        w.iterations_done(None, 0, list(range(50)), losses)

    def test_plateau_watcher(self):
        w = PlateauWatcher(patience=2, min_delta=0.01)
        w.on_epoch_end(None, 0, 1.0)
        w.on_epoch_end(None, 1, 0.5)
        w.on_epoch_end(None, 2, 0.499)          # stale 1
        with pytest.raises(TrainingDivergedError) as ei:
            w.on_epoch_end(None, 3, 0.498)      # stale 2 = patience
        assert ei.value.cause == "plateau"


# ---------------------------------------------------------------------------
# data pipeline rail

class _FlakyOnce:
    """Raises once at a given batch index, then works on the retry."""

    def __init__(self, X, Y, batch, fail_at, times=1):
        self._it = ArrayDataSetIterator(X, Y, batch_size=batch)
        self.fail_at = fail_at
        self.times = times

    def reset(self):
        pass

    def __iter__(self):
        for i, b in enumerate(self._it):
            if i == self.fail_at and self.times > 0:
                self.times -= 1
                raise IOError("flaky shard")
            yield b


class TestRetryingIterator:
    def test_transient_failure_recovers_full_stream(self):
        X, Y = _data(64)
        rit = RetryingIterator(_FlakyOnce(X, Y, 16, fail_at=2),
                               max_retries=3)
        batches = list(rit)
        assert len(batches) == 4
        np.testing.assert_array_equal(batches[2][0], X[32:48])
        assert [e["event"] for e in rit.events] == ["loader_retry"]

    def test_budget_exhausted_raises_structured(self):
        X, Y = _data(64)
        rit = RetryingIterator(
            _FlakyOnce(X, Y, 16, fail_at=1, times=100),
            max_retries=2, max_consecutive_failures=5)
        with pytest.raises(DataPipelineError) as ei:
            list(rit)
        assert ei.value.batch_index == 1
        assert isinstance(ei.value.__cause__, IOError)

    def test_consecutive_failure_budget(self):
        X, Y = _data(64)
        rit = RetryingIterator(
            _FlakyOnce(X, Y, 16, fail_at=1, times=100),
            max_retries=100, max_consecutive_failures=2)
        with pytest.raises(DataPipelineError):
            list(rit)

    def test_quarantine_corrupt_batch(self):
        X, Y = _data(64)
        X[17] = np.nan                             # poisons batch 1 of 4
        rit = RetryingIterator(ArrayDataSetIterator(X, Y, batch_size=16))
        assert len(list(rit)) == 3
        assert rit.quarantined == {1}
        # second pass: skipped on sight, stream stays clean
        assert len(list(rit)) == 3
        kinds = [e["event"] for e in rit.events]
        assert "quarantine" in kinds and "quarantine_skip" in kinds

    def test_restart_failure_retries_instead_of_truncating(self):
        """A transient failure during the restart's fast-forward replay
        must trigger another restart — never a fall-back to the closed
        generator, whose next() is StopIteration (a silently short
        epoch)."""
        X, Y = _data(64)
        state = {"calls": 0}
        fail_calls = {3, 4}     # batch 2 of pass 1, then replay batch 0

        class FlakyByCall:
            def reset(self):
                pass

            def __iter__(self):
                for i in range(4):
                    state["calls"] += 1
                    if state["calls"] in fail_calls:
                        raise IOError(f"flaky fetch #{state['calls']}")
                    yield X[i * 16:(i + 1) * 16], Y[i * 16:(i + 1) * 16]

        rit = RetryingIterator(FlakyByCall(), max_retries=5,
                               max_consecutive_failures=5)
        batches = list(rit)
        assert len(batches) == 4               # nothing silently dropped
        np.testing.assert_array_equal(batches[3][0], X[48:])
        assert [e["event"] for e in rit.events] == \
            ["loader_retry", "loader_retry"]

    def test_source_shrank_during_retry_is_a_fault(self):
        """A source that comes back SHORTER after a retry reset must
        surface as a structured fault, not silently truncate the pass."""
        X, Y = _data(64)

        class Shrinking:
            passes = 0

            def reset(self):
                Shrinking.passes += 1

            def __iter__(self):
                n = 4 if Shrinking.passes <= 1 else 2
                for i in range(n):
                    if Shrinking.passes <= 1 and i == 3:
                        raise IOError("flaky")
                    yield X[i * 16:(i + 1) * 16], Y[i * 16:(i + 1) * 16]

        rit = RetryingIterator(Shrinking(), max_retries=3)
        with pytest.raises(DataPipelineError) as ei:
            list(rit)
        assert ei.value.cause == "source_shrank"

    def test_non_transient_propagates_immediately(self):
        class Bad:
            def reset(self):
                pass

            def __iter__(self):
                raise KeyboardInterrupt()
                yield  # pragma: no cover

        rit = RetryingIterator(Bad(), max_retries=5)
        with pytest.raises(KeyboardInterrupt):
            list(rit)


class TestAsyncPoison:
    def test_poisoned_sentinel_carries_batch_index(self):
        from deeplearning4j_tpu.dataset.iterators import AsyncDataSetIterator
        X, _ = _data(64)

        class Bad:
            def __iter__(self):
                yield X[:8], X[:8]
                yield X[8:16], X[8:16]
                raise ValueError("shard checksum mismatch")

        got = []
        with pytest.raises(DataPipelineError) as ei:
            for b in AsyncDataSetIterator(Bad(), queue_size=2):
                got.append(b)
        # the good prefix was delivered IN ORDER before the poison
        assert len(got) == 2
        np.testing.assert_array_equal(got[1][0], X[8:16])
        assert ei.value.batch_index == 2
        assert ei.value.cause == "async_worker"
        assert isinstance(ei.value.__cause__, ValueError)

    def test_retrying_iterator_wraps_async(self):
        """RetryingIterator on top of the async prefetch: the poisoned
        sentinel is a transient error, so the pass completes."""
        from deeplearning4j_tpu.dataset.iterators import AsyncDataSetIterator
        X, Y = _data(64)
        inner = _FlakyOnce(X, Y, 16, fail_at=3)
        rit = RetryingIterator(AsyncDataSetIterator(inner, queue_size=2),
                               max_retries=2)
        assert len(list(rit)) == 4


# ---------------------------------------------------------------------------
# preemption handler chaining

class TestPreemptionChaining:
    @pytest.mark.chaos
    def test_chains_to_previous_handler_after_commit(self, tmp_path):
        from deeplearning4j_tpu.checkpoint import Preempted, PreemptionHook
        sd = _mlp()
        calls = []

        def supervisor(signum, frame):
            # the outer supervisor must observe the committed checkpoint
            calls.append((signum, mgr.latest_step()))

        prev = signal.signal(signal.SIGTERM, supervisor)
        try:
            mgr = CheckpointManager(tmp_path, async_write=False)
            with pytest.raises(Preempted):
                with PreemptionHook(mgr, sd):
                    PreemptionHook.simulate()
            assert len(calls) == 1
            assert calls[0][0] == signal.SIGTERM
            assert calls[0][1] is not None      # commit BEFORE the chain
        finally:
            signal.signal(signal.SIGTERM, prev)

    @pytest.mark.chaos
    def test_no_chain_for_default_handler(self, tmp_path):
        from deeplearning4j_tpu.checkpoint import Preempted, PreemptionHook
        sd = _mlp()
        mgr = CheckpointManager(tmp_path, async_write=False)
        with pytest.raises(Preempted):
            with PreemptionHook(mgr, sd):
                PreemptionHook.simulate()
        assert mgr.latest_step() is not None


# ---------------------------------------------------------------------------
# torn checkpoints under injected storage faults

class TestTornCheckpoints:
    @pytest.mark.chaos
    def test_fsync_failure_torn_dir_skipped_gc_next_save_ok(self, tmp_path):
        from deeplearning4j_tpu.checkpoint.state import \
            capture_training_state
        sd = _mlp()
        mgr = CheckpointManager(tmp_path, async_write=False)
        mgr.save(1, capture_training_state(sd))
        chaos = ChaosMonkey(seed=0)
        with chaos.failing_fsync(times=1):
            with pytest.raises(OSError):
                mgr.save(2, capture_training_state(sd))
        # the torn staging dir is skipped by restore and reclaimed by gc
        assert mgr.all_steps() == [1]
        step, _ = mgr.restore_latest(model=sd)
        assert step == 1
        torn = mgr.uncommitted_dirs()
        assert len(torn) == 1 and torn[0].endswith(".tmp")
        assert mgr.gc_uncommitted() == torn
        assert mgr.uncommitted_dirs() == []
        mgr.save(2, capture_training_state(sd))      # next save succeeds
        assert mgr.all_steps() == [1, 2]

    @pytest.mark.chaos
    def test_replace_failure_fully_staged_dir_salvaged(self, tmp_path):
        """os.replace dying AFTER the manifest+COMMIT are staged leaves a
        fully-verifiable .tmp — _recover_aside salvages it instead of
        discarding a durable checkpoint."""
        from deeplearning4j_tpu.checkpoint.state import \
            capture_training_state
        sd = _mlp()
        mgr = CheckpointManager(tmp_path, async_write=False)
        mgr.save(1, capture_training_state(sd))
        chaos = ChaosMonkey(seed=0)
        with chaos.failing_os_replace(times=1):
            with pytest.raises(OSError):
                mgr.save(2, capture_training_state(sd))
        assert mgr.all_steps() == [1]
        step, _ = mgr.restore_latest(model=sd)       # salvage, then restore
        assert step == 2
        assert mgr.all_steps() == [1, 2]

    @pytest.mark.chaos
    def test_async_writer_fault_is_sticky(self, tmp_path):
        from deeplearning4j_tpu.checkpoint.manager import CheckpointError
        from deeplearning4j_tpu.checkpoint.state import \
            capture_training_state
        sd = _mlp()
        mgr = CheckpointManager(tmp_path, async_write=True)
        chaos = ChaosMonkey(seed=0)
        with chaos.failing_fsync(times=1):
            mgr.save(1, capture_training_state(sd))
            with pytest.raises(CheckpointError):
                mgr.wait_until_finished()
        mgr.gc_uncommitted()
        mgr.save(2, capture_training_state(sd), blocking=True)
        assert mgr.all_steps() == [2]
        mgr.close()


# ---------------------------------------------------------------------------
# FaultTolerantFit: the rollback-and-retry driver

class TestFaultTolerantFit:
    @pytest.mark.chaos
    def test_end_to_end_self_heal(self, tmp_path):
        """Acceptance: NaN injected into a mid-run step AND a loader
        exception mid-epoch — the run restores from the last committed
        checkpoint, resumes, and completes with a finite final loss."""
        sd = _mlp(fused_steps=4)
        X, Y = _data()
        chaos = ChaosMonkey(seed=7)
        it = ArrayDataSetIterator(X, Y, batch_size=16)     # 8 steps/epoch
        it = chaos.flaky_iterator(it, fail_at_batch=2)     # epoch 0 loader
        it = chaos.poison_batches(it, at_step=13)          # NaN mid-epoch-1
        storage = StatsStorage()
        mgr = CheckpointManager(tmp_path, keep_last_n=5)
        ftf = FaultTolerantFit(
            sd, mgr,
            policy=RetryPolicy(max_retries=2, backoff_base=0.0,
                               quarantine_corrupt=False),
            checkpoint_every_n_iterations=4, stats_storage=storage,
            sleep=lambda s: None)
        h = ftf.fit(it, epochs=4)
        assert np.isfinite(h.final_loss())
        assert sd.training_config.epoch_count == 4
        assert ftf.rollbacks >= 1
        for n, a in sd.trainable_params().items():
            assert np.isfinite(np.asarray(a)).all(), n
        events = [r["event"] for r in storage.of_type("faults")]
        assert "loader_retry" in events
        assert "fault" in events and "rollback" in events
        assert "recovered" in events
        mgr.close()

    @pytest.mark.chaos
    def test_epoch_budget_preserved_with_nonzero_start(self, tmp_path):
        """Checkpoints taken inside a retry attempt must carry the
        GLOBAL epoch count: a fit-local index would roll tc.epoch_count
        backwards on restore and inflate the remaining-epochs budget."""
        sd = _mlp(fused_steps=4)
        X, Y = _data()
        sd.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=2)
        assert sd.training_config.epoch_count == 2
        epochs_trained = []

        class Counter(ScoreIterationListener):
            def __init__(self):
                super().__init__(print_every=10 ** 9,
                                 print_fn=lambda *a: None)

            def on_epoch_end(self, sd, epoch, mean_loss):
                epochs_trained.append(epoch)

        chaos = ChaosMonkey(seed=3)
        it = chaos.poison_batches(
            ArrayDataSetIterator(X, Y, batch_size=16), at_step=4)
        mgr = CheckpointManager(tmp_path, keep_last_n=5)
        ftf = FaultTolerantFit(
            sd, mgr,
            policy=RetryPolicy(max_retries=2, backoff_base=0.0,
                               quarantine_corrupt=False),
            checkpoint_every_n_iterations=4, sleep=lambda s: None)
        h = ftf.fit(it, epochs=2, listeners=[Counter()])
        mgr.close()
        assert np.isfinite(h.final_loss())
        assert ftf.rollbacks == 1
        assert sd.training_config.epoch_count == 4     # 2 + exactly 2
        # the interrupted epoch replays once; nothing beyond the budget
        assert len(epochs_trained) == 2

    @pytest.mark.chaos
    def test_quarantine_heals_without_rollback(self, tmp_path):
        """Corrupt batches are the data rail's job: quarantined before
        they can become a divergence, no rollback needed."""
        sd = _mlp(fused_steps=4)
        X, Y = _data()
        chaos = ChaosMonkey(seed=3)
        it = chaos.poison_batches(
            ArrayDataSetIterator(X, Y, batch_size=16), at_step=2)
        storage = StatsStorage()
        mgr = CheckpointManager(tmp_path)
        ftf = FaultTolerantFit(sd, mgr, policy=RetryPolicy(backoff_base=0.0),
                               stats_storage=storage, sleep=lambda s: None)
        h = ftf.fit(it, epochs=2)
        assert np.isfinite(h.final_loss())
        assert ftf.rollbacks == 0
        assert "quarantine" in [r["event"] for r in storage.of_type("faults")]
        mgr.close()

    @pytest.mark.chaos
    def test_budget_exhausted_aborts_cleanly(self, tmp_path):
        """A permanent fault: rollback budget runs out, the model ends
        at the last good state and a pinned final checkpoint exists."""
        sd = _mlp(fused_steps=4)
        X, Y = _data()
        chaos = ChaosMonkey(seed=0)
        storage = StatsStorage()
        mgr = CheckpointManager(tmp_path, keep_last_n=3)
        ftf = FaultTolerantFit(
            sd, mgr, policy=RetryPolicy(max_retries=2, backoff_base=0.0),
            checkpoint_every_n_iterations=4, stats_storage=storage,
            sleep=lambda s: None)
        with chaos.nan_gradients(sd, at_step=6):   # re-injects every pass
            with pytest.raises(FaultBudgetExhaustedError) as ei:
                ftf.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=2)
        assert isinstance(ei.value.__cause__, TrainingDivergedError)
        for n, a in sd.trainable_params().items():
            assert np.isfinite(np.asarray(a)).all(), n
        events = [r["event"] for r in storage.of_type("faults")]
        assert "retry_exhausted" in events
        assert mgr.latest_step() is not None
        mgr.close()

    @pytest.mark.chaos
    def test_transient_device_error_retried(self, tmp_path):
        sd = _mlp(fused_steps=4)
        X, Y = _data()
        chaos = ChaosMonkey(seed=0)
        mgr = CheckpointManager(tmp_path)
        ftf = FaultTolerantFit(sd, mgr,
                               policy=RetryPolicy(max_retries=2,
                                                  backoff_base=0.0),
                               sleep=lambda s: None)
        with chaos.transient_device_error(sd):
            h = ftf.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=2)
        assert np.isfinite(h.final_loss())
        assert ftf.rollbacks == 1
        assert sd.training_config.epoch_count == 2
        mgr.close()

    @pytest.mark.chaos
    def test_lr_rescale_on_rollback(self, tmp_path):
        sd = _mlp(fused_steps=4, lr=1e-2)
        X, Y = _data()
        chaos = ChaosMonkey(seed=0)
        it = chaos.poison_batches(
            ArrayDataSetIterator(X, Y, batch_size=16), at_step=3)
        mgr = CheckpointManager(tmp_path)
        ftf = FaultTolerantFit(
            sd, mgr,
            policy=RetryPolicy(max_retries=2, backoff_base=0.0,
                               lr_rescale=0.5, quarantine_corrupt=False),
            checkpoint_every_n_iterations=2, sleep=lambda s: None)
        h = ftf.fit(it, epochs=2)
        assert np.isfinite(h.final_loss())
        assert ftf.rollbacks == 1
        assert sd.training_config.updater.learning_rate == \
            pytest.approx(5e-3)
        mgr.close()

    @pytest.mark.chaos
    def test_sigterm_mid_window_then_elastic_resume(self, tmp_path):
        """The preemption drill: SIGTERM mid-run commits a final
        checkpoint and raises Preempted; the relaunched run restores
        and finishes with finite loss."""
        from deeplearning4j_tpu.checkpoint import Preempted, PreemptionHook
        X, Y = _data()
        sd = _mlp(fused_steps=2)
        mgr = CheckpointManager(tmp_path, async_write=False)
        chaos = ChaosMonkey(seed=0)
        with pytest.raises(Preempted):
            with PreemptionHook(mgr, sd):
                sd.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=8,
                       listeners=[chaos.sigterm_listener(at_iteration=9)])
        # the final snapshot carries the last state the fit loop synced
        # into the graph (a window/epoch boundary at or before step 9)
        final = mgr.latest_step()
        assert final is not None and final >= 1
        # "relaunch": fresh process state, restore, finish the run
        sd2 = _mlp(fused_steps=2)
        mgr2 = CheckpointManager(tmp_path)
        step, _ = mgr2.restore_latest(model=sd2)
        assert step == final
        ftf = FaultTolerantFit(sd2, mgr2, sleep=lambda s: None)
        h = ftf.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=2)
        assert np.isfinite(h.final_loss())
        mgr2.close()

    def test_device_cached_source_keeps_fast_path(self, tmp_path):
        """A stacked_batches source must NOT be wrapped in
        RetryingIterator: the wrapper would hide the attribute the
        windowed tier's cached-windows path routes on, re-staging from
        host every epoch."""
        sd = _mlp(fused_steps=4)
        X, Y = _data(64)
        captured = {}
        orig_fit = sd.fit

        def spy(it, **kw):
            captured["it"] = it
            return orig_fit(it, **kw)

        sd.fit = spy
        mgr = CheckpointManager(tmp_path)
        ftf = FaultTolerantFit(sd, mgr, sleep=lambda s: None)
        h = ftf.fit(DeviceCachedIterator(X, Y, batch_size=16), epochs=2)
        assert np.isfinite(h.final_loss())
        assert hasattr(captured["it"], "stacked_batches")
        assert sd.last_fit_stats["tier"] == "windowed"
        mgr.close()

    def test_report_shape(self, tmp_path):
        sd = _mlp(fused_steps=2)
        X, Y = _data(64)
        mgr = CheckpointManager(tmp_path)
        ftf = FaultTolerantFit(sd, mgr, sleep=lambda s: None)
        ftf.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=1)
        rep = ftf.report()
        assert rep["rollbacks"] == 0 and rep["recovery_seconds"] == 0.0
        mgr.close()


# ---------------------------------------------------------------------------
# serving failure observability

class TestServingCauses:
    def test_record_failure_and_timeout_causes(self):
        from deeplearning4j_tpu.serving.metrics import ServingMetrics
        m = ServingMetrics()
        m.record_failure(ValueError("bad shape"))
        m.record_failure(RuntimeError("xla oom"), n=3)
        m.record_timeout("deadline")
        rec = m.to_record()
        assert rec["counters"]["requests_failed"] == 4
        assert rec["failure_causes"] == {"ValueError": 1, "RuntimeError": 3}
        assert rec["timeout_causes"] == {"deadline": 1}
        assert rec["last_error"]["kind"] == "timeout"
        assert "causes:" in m.stats() and "last_error:" in m.stats()

    def test_inference_failure_attributed(self):
        from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                           NeuralNetConfiguration,
                                           OutputLayer)
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.serving import InferenceMode, ParallelInference
        conf = (NeuralNetConfiguration.builder().seed(7)
                .updater(Adam(1e-3)).list()
                .layer(DenseLayer(n_out=8, activation="relu"))
                .layer(OutputLayer(n_out=2, loss_function="MCXENT"))
                .set_input_type(InputType.feed_forward(4))
                .build())
        net = MultiLayerNetwork(conf).init()
        pi = ParallelInference(net, mode=InferenceMode.INPLACE)

        def boom(*a, **kw):
            raise RuntimeError("injected model fault")

        pi._spec = pi._spec._replace(sd=type("X", (), {
            "output": staticmethod(boom), "_vars": pi._spec.sd._vars})())
        with pytest.raises(RuntimeError):
            pi.output(np.zeros((2, 4), np.float32))
        rec = pi.metrics.to_record()
        assert rec["failure_causes"] == {"RuntimeError": 1}
        assert rec["last_error"]["cause"] == "RuntimeError"
        pi.shutdown()


# ---------------------------------------------------------------------------
# chaos determinism

class TestChaosDeterminism:
    def test_seeded_draws_reproduce(self):
        a = ChaosMonkey(seed=42)
        b = ChaosMonkey(seed=42)
        assert [a.draw_step(0, 100) for _ in range(5)] == \
            [b.draw_step(0, 100) for _ in range(5)]

    def test_injections_are_logged(self):
        chaos = ChaosMonkey(seed=1)
        X, Y = _data(64)
        it = chaos.poison_batches(ArrayDataSetIterator(X, Y, batch_size=16),
                                  at_step=1)
        list(it)
        assert chaos.log[0]["event"] == "batch_poisoned"
