"""SameDiff-equivalent graph tests.

Reference test model: nd4j autodiff tests + GradCheckUtil/OpValidation
(autodiff/validation/OpValidation.java:110-453) — forward values checked
against an independent implementation (numpy), analytic gradients checked
against central finite differences, serde round-trips checked per case.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.autodiff import (
    SameDiff, SDVariable, VariableType, TrainingConfig,
    ScoreIterationListener, EarlyStoppingListener,
)
from deeplearning4j_tpu.learning.updaters import Adam, Sgd


def test_variable_creation_types():
    sd = SameDiff()
    v = sd.var("w", shape=(3, 4))
    c = sd.constant(np.eye(2), "c")
    p = sd.placeholder("x", shape=(-1, 3))
    assert v.var_type == VariableType.VARIABLE
    assert c.var_type == VariableType.CONSTANT
    assert p.var_type == VariableType.PLACEHOLDER
    assert v.shape == (3, 4)
    assert c.shape == (2, 2)
    assert sd.placeholders() == ["x"]


def test_unique_naming():
    sd = SameDiff()
    a = sd.var("w", shape=(2,))
    b = sd.var("w", shape=(2,))
    assert a.name == "w" and b.name == "w_1"


def test_forward_simple_arithmetic():
    sd = SameDiff()
    x = sd.placeholder("x", shape=(-1, 3))
    w = sd.var("w", value=np.full((3,), 2.0))
    y = (x * w + 1.0).sum()
    xv = np.arange(6, dtype=np.float64).reshape(2, 3)
    out = sd.output({"x": xv}, [y.name])[y.name].to_numpy()
    np.testing.assert_allclose(out, (xv * 2.0 + 1).sum())


def test_forward_mmul_chain():
    sd = SameDiff()
    rng = np.random.default_rng(0)
    a_np = rng.normal(size=(4, 5))
    b_np = rng.normal(size=(5, 6))
    a = sd.var("a", value=a_np)
    b = sd.var("b", value=b_np)
    c = a.mmul(b)
    out = c.eval().to_numpy()
    np.testing.assert_allclose(out, a_np @ b_np, rtol=1e-6)
    assert c.shape == (4, 6)


def test_namespace_ops():
    sd = SameDiff()
    x = sd.placeholder("x", shape=(-1, 4))
    h = sd.nn.softmax(x, axis=-1)
    xv = np.random.default_rng(1).normal(size=(3, 4))
    out = sd.output({"x": xv}, [h])[h.name].to_numpy()
    e = np.exp(xv - xv.max(-1, keepdims=True))
    np.testing.assert_allclose(out, e / e.sum(-1, keepdims=True), rtol=1e-6)


def test_namespace_scalar_lift():
    sd = SameDiff()
    x = sd.placeholder("x", shape=(2,))
    y = sd.math.subtract(10.0, x)
    out = sd.output({"x": np.array([1.0, 2.0])}, [y])[y.name].to_numpy()
    np.testing.assert_allclose(out, [9.0, 8.0])


def test_namespace_multi_output():
    sd = SameDiff()
    x = sd.placeholder("x", shape=(-1, 5))
    mean, var = sd.math.moments(x, axis=(0,))
    xv = np.random.default_rng(2).normal(size=(7, 5))
    outs = sd.output({"x": xv}, [mean, var])
    np.testing.assert_allclose(outs[mean.name].to_numpy(), xv.mean(0), rtol=1e-6)
    np.testing.assert_allclose(outs[var.name].to_numpy(), xv.var(0), rtol=1e-6)


def test_reductions_and_shape_methods():
    sd = SameDiff()
    x = sd.placeholder("x", shape=(-1, 6))
    s = x.reshape(-1, 2, 3).sum(dims=2).mean(dims=(0, 1))
    xv = np.arange(12, dtype=np.float64).reshape(2, 6)
    out = sd.output({"x": xv}, [s])[s.name].to_numpy()
    np.testing.assert_allclose(out, xv.reshape(2, 2, 3).sum(2).mean())


def test_shape_inference_with_batch_placeholder():
    sd = SameDiff()
    x = sd.placeholder("x", shape=(-1, 8))
    w = sd.var("w", shape=(8, 3))
    y = x.mmul(w)
    assert y.shape[-1] == 3


def test_gradients_match_finite_difference():
    sd = SameDiff()
    rng = np.random.default_rng(3)
    w_np = rng.normal(size=(4, 3))
    x_np = rng.normal(size=(5, 4))
    w = sd.var("w", value=w_np)
    x = sd.placeholder("x", shape=(-1, 4))
    loss = x.mmul(w).sigmoid().square().sum()
    loss.mark_as_loss()

    grads = sd.calculate_gradients({"x": x_np}, wrt=["w"])
    g = grads["w"].to_numpy()

    def f(wv):
        return float(np.sum((1 / (1 + np.exp(-(x_np @ wv)))) ** 2))

    eps = 1e-6
    num = np.zeros_like(w_np)
    for i in range(w_np.shape[0]):
        for j in range(w_np.shape[1]):
            wp = w_np.copy(); wp[i, j] += eps
            wm = w_np.copy(); wm[i, j] -= eps
            num[i, j] = (f(wp) - f(wm)) / (2 * eps)
    np.testing.assert_allclose(g, num, rtol=1e-4, atol=1e-6)


def test_gradient_wrt_subset():
    sd = SameDiff()
    a = sd.var("a", value=np.array([2.0]))
    b = sd.var("b", value=np.array([3.0]))
    loss = (a * b).sum()
    loss.mark_as_loss()
    grads = sd.calculate_gradients({}, wrt=["a"])
    assert set(grads.keys()) == {"a"}
    np.testing.assert_allclose(grads["a"].to_numpy(), [3.0])


def test_constants_get_no_gradient_path():
    sd = SameDiff()
    c = sd.constant(np.array([5.0]), "c")
    a = sd.var("a", value=np.array([2.0]))
    loss = (a * c).sum()
    loss.mark_as_loss()
    grads = sd.calculate_gradients({})
    assert set(grads.keys()) == {"a"}
    np.testing.assert_allclose(grads["a"].to_numpy(), [5.0])


class _ToyIterator:
    """Tiny in-memory DataSetIterator-alike."""

    def __init__(self, X, Y, batch: int):
        self.X, self.Y, self.batch = X, Y, batch

    def reset(self):
        pass

    def __iter__(self):
        for i in range(0, len(self.X), self.batch):
            yield self.X[i:i + self.batch], self.Y[i:i + self.batch]


def _xor_problem():
    X = np.array([[0, 0], [0, 1], [1, 0], [1, 1]], dtype=np.float32)
    X = np.tile(X, (16, 1))
    Y = (X[:, 0].astype(int) ^ X[:, 1].astype(int)).astype(np.int32)
    Y1h = np.eye(2, dtype=np.float32)[Y]
    return X, Y1h


def _build_mlp(sd, n_in=2, n_hidden=16, n_out=2):
    rng = np.random.default_rng(42)
    x = sd.placeholder("x", shape=(-1, n_in))
    labels = sd.placeholder("labels", shape=(-1, n_out))
    w0 = sd.var("w0", value=rng.normal(0, 0.5, size=(n_in, n_hidden)))
    b0 = sd.var("b0", shape=(n_hidden,))
    w1 = sd.var("w1", value=rng.normal(0, 0.5, size=(n_hidden, n_out)))
    b1 = sd.var("b1", shape=(n_out,))
    h = (x.mmul(w0) + b0).tanh()
    logits = h.mmul(w1) + b1
    probs = sd.nn.softmax(logits, name="out")
    loss = sd.loss.softmax_cross_entropy(logits, labels, name="loss")
    loss.mark_as_loss()
    return x, labels, probs, loss


def test_fit_learns_xor():
    sd = SameDiff()
    x, labels, probs, loss = _build_mlp(sd)
    sd.training_config = (TrainingConfig.builder()
                          .updater(Adam(learning_rate=0.05))
                          .data_set_feature_mapping("x")
                          .data_set_label_mapping("labels")
                          .build())
    X, Y = _xor_problem()
    hist = sd.fit(_ToyIterator(X, Y, batch=16), epochs=60)
    assert hist.final_loss() < 0.05
    preds = sd.output({"x": X[:4]}, ["out"])["out"].to_numpy()
    np.testing.assert_array_equal(preds.argmax(-1), [0, 1, 1, 0])


def test_fit_updater_state_persists_and_resumes(tmp_path):
    sd = SameDiff()
    _build_mlp(sd)
    sd.training_config = (TrainingConfig.builder()
                          .updater(Adam(learning_rate=0.01))
                          .data_set_feature_mapping("x")
                          .data_set_label_mapping("labels")
                          .build())
    X, Y = _xor_problem()
    sd.fit(_ToyIterator(X, Y, batch=32), epochs=2)
    assert sd._updater_state is not None
    assert sd.training_config.iteration_count == 4

    path = tmp_path / "model.zip"
    sd.save(path, include_updater_state=True)
    sd2 = SameDiff.load(path)
    assert sd2.training_config.iteration_count == 4
    # resumed updater state numerically identical
    l1 = jax.tree_util.tree_leaves(sd._updater_state)
    l2 = jax.tree_util.tree_leaves(sd2._updater_state)
    assert len(l1) == len(l2)
    for a, b in zip(l1, l2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    # training continues from the restored state
    h2 = sd2.fit(_ToyIterator(X, Y, batch=32), epochs=1)
    assert np.isfinite(h2.final_loss())


def test_serde_round_trip_preserves_outputs(tmp_path):
    sd = SameDiff()
    _build_mlp(sd)
    X, _ = _xor_problem()
    before = sd.output({"x": X[:8]}, ["out"])["out"].to_numpy()
    path = tmp_path / "m.zip"
    sd.save(path)
    sd2 = SameDiff.load(path)
    after = sd2.output({"x": X[:8]}, ["out"])["out"].to_numpy()
    np.testing.assert_allclose(before, after, rtol=1e-6)
    assert sd2.loss_variables == sd.loss_variables


def test_random_ops_keyed_and_reproducible():
    sd = SameDiff()
    u = sd.random.uniform(shape=(4, 4), name="u")
    k = jax.random.key(7)
    a = sd.output({}, [u], key=k)[u.name].to_numpy()
    b = sd.output({}, [u], key=k)[u.name].to_numpy()
    np.testing.assert_array_equal(a, b)
    c = sd.output({}, [u], key=jax.random.key(8))[u.name].to_numpy()
    assert not np.array_equal(a, c)
    assert (a >= 0).all() and (a < 1).all()


def test_early_stopping_listener():
    sd = SameDiff()
    _build_mlp(sd)
    sd.training_config = (TrainingConfig.builder()
                          .updater(Sgd(learning_rate=0.0))  # loss frozen
                          .data_set_feature_mapping("x")
                          .data_set_label_mapping("labels")
                          .build())
    X, Y = _xor_problem()
    es = EarlyStoppingListener(patience=2)
    hist = sd.fit(_ToyIterator(X, Y, batch=32), epochs=50, listeners=[es])
    assert es.stopped_epoch is not None and es.stopped_epoch < 49


def test_convert_variable_constant():
    sd = SameDiff()
    w = sd.var("w", value=np.ones(3))
    w.convert_to_constant()
    assert w.var_type == VariableType.CONSTANT
    assert "w" not in sd.trainable_params()
    w.convert_to_variable()
    assert "w" in sd.trainable_params()


def test_rename_variable_rewires_ops():
    sd = SameDiff()
    x = sd.placeholder("x", shape=(2,))
    y = x.exp()
    x.rename("input")
    out = sd.output({"input": np.zeros(2)}, [y])[y.name].to_numpy()
    np.testing.assert_allclose(out, np.ones(2))


def test_checkpoint_listener(tmp_path):
    from deeplearning4j_tpu.autodiff import CheckpointListener
    sd = SameDiff()
    _build_mlp(sd)
    sd.training_config = (TrainingConfig.builder()
                          .updater(Sgd(learning_rate=0.1))
                          .data_set_feature_mapping("x")
                          .data_set_label_mapping("labels")
                          .build())
    X, Y = _xor_problem()
    cl = CheckpointListener(tmp_path / "ckpts", every_n_epochs=1, keep_last=2)
    sd.fit(_ToyIterator(X, Y, batch=32), epochs=5, listeners=[cl])
    import os
    files = sorted(os.listdir(tmp_path / "ckpts"))
    assert len(files) == 2  # keep_last pruned older checkpoints
    restored = SameDiff.load(cl.last_checkpoint())
    assert "w0" in restored.trainable_params()


# ---- regression tests for review findings ----

def test_split_multi_output():
    sd = SameDiff()
    x = sd.placeholder("x", shape=(6, 2))
    parts = sd.shape.split(x, num_split=3, axis=0)
    assert isinstance(parts, list) and len(parts) == 3
    xv = np.arange(12, dtype=np.float64).reshape(6, 2)
    outs = sd.output({"x": xv}, parts)
    np.testing.assert_allclose(outs[parts[1].name].to_numpy(), xv[2:4])


def test_unstack_derives_output_count():
    sd = SameDiff()
    c = sd.constant(np.arange(6.0).reshape(3, 2), "c")
    rows = sd.shape.unstack(c, axis=0)
    assert len(rows) == 3
    np.testing.assert_allclose(rows[2].eval().to_numpy(), [4.0, 5.0])


def test_concat_requires_keyword_axis():
    sd = SameDiff()
    a = sd.constant(np.ones((2, 2)), "a")
    b = sd.constant(np.zeros((2, 2)), "b")
    y = sd.shape.concat(a, b, axis=0)
    assert y.eval().to_numpy().shape == (4, 2)
    with pytest.raises(TypeError, match="keyword"):
        sd.shape.concat(a, b, 0)


def test_mark_as_loss_idempotent():
    sd = SameDiff()
    a = sd.var("a", value=np.array([2.0]))
    loss = (a * a).sum()
    loss.mark_as_loss()
    loss.mark_as_loss()
    assert sd.loss_variables.count(loss.name) == 1
    g = sd.calculate_gradients({})["a"].to_numpy()
    np.testing.assert_allclose(g, [4.0])  # not doubled


def test_train_step_cached_across_fits():
    sd = SameDiff()
    _build_mlp(sd)
    sd.training_config = (TrainingConfig.builder()
                          .updater(Sgd(learning_rate=0.1))
                          .data_set_feature_mapping("x")
                          .data_set_label_mapping("labels")
                          .build())
    s1 = sd.make_train_step()
    s2 = sd.make_train_step()
    assert s1 is s2


def test_updater_state_reinit_after_graph_change():
    sd = SameDiff()
    _build_mlp(sd)
    sd.training_config = (TrainingConfig.builder()
                          .updater(Adam(learning_rate=0.01))
                          .data_set_feature_mapping("x")
                          .data_set_label_mapping("labels")
                          .build())
    X, Y = _xor_problem()
    sd.fit(_ToyIterator(X, Y, batch=32), epochs=1)
    sd.get_variable("b0").convert_to_constant()
    h = sd.fit(_ToyIterator(X, Y, batch=32), epochs=1)  # must not crash
    assert np.isfinite(h.final_loss())
    assert set(sd._updater_state.keys()) == set(sd.trainable_params().keys())


def test_fit_with_dict_batches():
    sd = SameDiff()
    _build_mlp(sd)
    sd.training_config = (TrainingConfig.builder()
                          .updater(Sgd(learning_rate=0.5))
                          .data_set_feature_mapping("x")
                          .data_set_label_mapping("labels")
                          .build())
    X, Y = _xor_problem()

    class DictIt:
        def reset(self): pass
        def __iter__(self):
            yield {"x": X, "labels": Y}

    h = sd.fit(DictIt(), epochs=2)
    assert np.isfinite(h.final_loss())


def test_performance_listener_autofills_batch_size():
    from deeplearning4j_tpu.autodiff import PerformanceListener
    sd = SameDiff()
    _build_mlp(sd)
    sd.training_config = (TrainingConfig.builder()
                          .updater(Sgd(learning_rate=0.1))
                          .data_set_feature_mapping("x")
                          .data_set_label_mapping("labels")
                          .build())
    X, Y = _xor_problem()
    pl = PerformanceListener(frequency=1, print_fn=lambda *a: None)
    sd.fit(_ToyIterator(X, Y, batch=16), epochs=1, listeners=[pl])
    assert pl.batch_size == 16
    assert np.isfinite(pl.samples_per_sec)


def test_new_training_config_invalidates_cached_step():
    # ADVICE r1: swapping training_config must not reuse the compiled step
    # that baked in the old hyperparameters.
    sd = SameDiff()
    _build_mlp(sd)
    sd.training_config = (TrainingConfig.builder()
                          .updater(Sgd(learning_rate=0.1))
                          .data_set_feature_mapping("x")
                          .data_set_label_mapping("labels")
                          .build())
    X, Y = _xor_problem()
    sd.fit(_ToyIterator(X, Y, batch=32), epochs=1)
    before = {k: np.asarray(v) for k, v in sd.trainable_params().items()}
    sd.training_config = (TrainingConfig.builder()
                          .updater(Sgd(learning_rate=0.0))
                          .data_set_feature_mapping("x")
                          .data_set_label_mapping("labels")
                          .build())
    sd.fit(_ToyIterator(X, Y, batch=32), epochs=1)
    after = sd.trainable_params()
    for k in before:
        np.testing.assert_allclose(np.asarray(after[k]), before[k],
                                   err_msg=f"lr=0 fit changed {k}")


def test_rename_variable_rewrites_state_tracking():
    import jax.numpy as jnp
    sd = SameDiff()
    x = sd.placeholder("x", shape=(-1, 3))
    s = sd.state_var("running", value=np.zeros((3,)))
    upd = s.add(x.mean(dims=0), name="upd")
    sd.update_state(s, upd)
    sd.rename_variable("running", "running2")
    assert "running2" in sd._state_var_names
    assert "running" not in sd._state_var_names
    assert sd._state_updates == {"running2": "upd"}
