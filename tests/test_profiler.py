"""Profiler subsystem: xplane decoding + op aggregation.

The xplane fixture is synthesized with the protowire-inverse encoder
(tf_builder's primitives target the same wire format), so decoding is
tested against real protobuf bytes without needing a TPU trace in CI.
"""
import numpy as np

from deeplearning4j_tpu.modelimport.tf_builder import (
    field_bytes, field_string, field_varint)
from deeplearning4j_tpu.profiler import (
    OpProfile, decode_xspace, device_op_times, step_times_ms)


def _xevent(metadata_id, offset_ps, duration_ps):
    return (field_varint(1, metadata_id) + field_varint(2, offset_ps)
            + field_varint(3, duration_ps))


def _xline(name, events):
    out = field_string(2, name)
    for e in events:
        out += field_bytes(4, e)
    return out


def _event_meta(mid, name):
    md = field_varint(1, mid) + field_string(2, name)   # XEventMetadata
    entry = field_varint(1, mid) + field_bytes(2, md)   # map entry k=1,v=2
    return field_bytes(4, entry)                        # XPlane field 4


def _xplane(name, lines, ev_meta):
    out = field_string(2, name)
    for m in ev_meta:
        out += m
    for l in lines:
        out += field_bytes(3, l)
    return out


def _make_space():
    meta = [
        _event_meta(1, "%fusion.1 = bf16[8,8] fusion(...)"),
        _event_meta(2, "%convolution.7 = bf16[8,8] convolution(...)"),
        _event_meta(3, "2"),
    ]
    # metadata entries are field 4 of XPlane; events reference them
    ops_line = _xline("XLA Ops", [
        _xevent(1, 0, 5_000_000_000), _xevent(2, 5_000_000_000, 2_000_000_000),
        _xevent(1, 8_000_000_000, 5_000_000_000)])
    async_line = _xline("Async XLA Ops", [_xevent(2, 0, 50_000_000_000)])
    steps_line = _xline("Steps", [_xevent(3, 0, 12_000_000_000)])
    plane = _xplane("/device:TPU:0", [ops_line, async_line, steps_line], meta)
    host_plane = _xplane("/host:CPU", [_xline("python", [_xevent(1, 0, 9)])],
                         meta)
    return field_bytes(1, plane) + field_bytes(1, host_plane)


def test_decode_and_aggregate():
    planes = decode_xspace(_make_space())
    assert [p.name for p in planes] == ["/device:TPU:0", "/host:CPU"]
    ops = device_op_times(planes)
    # host plane and async line excluded; 2 distinct ops
    assert len(ops) == 2
    top = ops[0]
    assert top.name.startswith("%fusion.1")
    assert top.count == 2
    assert abs(top.total_ms - 10.0) < 1e-9
    assert top.category == "fusion"
    assert ops[1].category == "convolution"


def test_async_line_opt_in():
    planes = decode_xspace(_make_space())
    ops = device_op_times(planes, include_async=True)
    names = [o.name for o in ops]
    assert any(n.startswith("async:") for n in names)


def test_step_times_and_report():
    planes = decode_xspace(_make_space())
    steps = step_times_ms(planes)
    assert steps == [12.0]
    prof = OpProfile(device_op_times(planes))
    rep = prof.report(top=5)
    assert "fusion" in rep and "ms" in rep
    assert abs(prof.total_ms() - 12.0) < 1e-9
    cats = prof.by_category()
    assert abs(cats["fusion"] - 10.0) < 1e-9
