"""Training UI stats pipeline: StatsListener -> StatsStorage -> HTML.

Reference parity: BaseStatsListener.java:58 collection families (score,
performance, histograms, update ratios, memory) and FileStatsStorage
persistence; the dashboard is a static HTML artifact instead of the
Vertx server (VertxUIServer.java:78).
"""
import json
import os

import numpy as np

from deeplearning4j_tpu.ui import (StatsListener, StatsStorage,
                                   render_report, write_report)


def _train_with_listener(tmp_path, epochs=4):
    from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig
    from deeplearning4j_tpu.learning.updaters import Adam

    rng = np.random.default_rng(0)
    sd = SameDiff()
    x = sd.placeholder("x", shape=(-1, 8))
    w = sd.var("w", value=rng.standard_normal((8, 4)).astype(np.float32))
    b = sd.var("b", value=np.zeros(4, np.float32))
    y = x.mmul(w).add(b, name="pred")
    t = sd.placeholder("t", shape=(-1, 4))
    loss = sd.invoke("mean_sqerr_loss", [y, t], name="loss")
    sd.set_loss_variables([loss])
    sd.training_config = TrainingConfig(
        updater=Adam(1e-2), data_set_feature_mapping=["x"],
        data_set_label_mapping=["t"])
    X = rng.standard_normal((64, 8)).astype(np.float32)
    W0 = rng.standard_normal((8, 4)).astype(np.float32)
    Y = X @ W0
    st = StatsStorage(str(tmp_path / "stats.jsonl"))
    lst = StatsListener(st, frequency=2)
    batches = [([X[i:i + 16]], [Y[i:i + 16]]) for i in range(0, 64, 16)]
    sd.fit(batches, epochs=epochs, listeners=[lst])
    st.close()
    return sd, st


class TestStatsPipeline:
    def test_collects_all_families(self, tmp_path):
        _, st = _train_with_listener(tmp_path)
        types = {r["type"] for r in st.records}
        assert {"meta", "score", "perf", "params", "end"} <= types
        scores = st.of_type("score")
        assert len(scores) == 16                    # 4 epochs x 4 batches
        assert scores[0]["loss"] > scores[-1]["loss"]

    def test_param_stats_and_update_ratio(self, tmp_path):
        _, st = _train_with_listener(tmp_path)
        params = st.of_type("params")
        assert len(params) == 4
        last = params[-1]["params"]
        assert set(last) == {"w", "b"}
        ent = last["w"]
        assert len(ent["hist"]) == 16
        assert ent["norm"] > 0
        # epochs after the first have update stats
        assert "update_ratio" in ent and ent["update_ratio"] > 0

    def test_jsonl_persistence_roundtrip(self, tmp_path):
        _, st = _train_with_listener(tmp_path)
        loaded = StatsStorage.load(str(tmp_path / "stats.jsonl"))
        assert len(loaded.records) == len(st.records)
        assert loaded.of_type("score")[0]["loss"] == \
            st.of_type("score")[0]["loss"]

    def test_html_report_artifact(self, tmp_path):
        _, st = _train_with_listener(tmp_path)
        out = write_report(st, str(tmp_path / "report.html"),
                           title="mlp run")
        html = open(out, encoding="utf-8").read()
        assert html.startswith("<!doctype html>")
        assert "score vs iteration" in html
        assert "Update : parameter ratios" in html
        assert html.count("<svg") >= 4     # score, perf, ratios, hists
        assert "mlp run" in html
        # every param appears in the stats table
        assert ">w<" in html and ">b<" in html

    def test_report_on_empty_storage(self):
        html = render_report(StatsStorage())
        assert "no data" in html

    def test_concurrent_writers_do_not_tear(self, tmp_path):
        """ISSUE-5 satellite: the async checkpoint writer, serving
        workers and the window stager publish concurrently — records
        must not drop and JSONL lines must not interleave."""
        import threading
        path = str(tmp_path / "concurrent.jsonl")
        st = StatsStorage(path)
        n_threads, n_puts = 8, 250

        def writer(tid):
            for i in range(n_puts):
                st.put({"type": "x", "writer": tid, "i": i,
                        "pad": "p" * 50})

        threads = [threading.Thread(target=writer, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        st.close()
        assert len(st.records) == n_threads * n_puts
        lines = [l for l in open(path, encoding="utf-8") if l.strip()]
        assert len(lines) == n_threads * n_puts
        seen = set()
        for line in lines:
            rec = json.loads(line)          # a torn line would not parse
            assert rec["pad"] == "p" * 50
            seen.add((rec["writer"], rec["i"]))
        assert len(seen) == n_threads * n_puts   # no record lost

    def test_load_keeps_persisting(self, tmp_path):
        """ISSUE-5 satellite: a loaded storage must keep appending to
        its source file — load() used to drop the path, silently
        turning persistence off after a restart."""
        path = str(tmp_path / "s.jsonl")
        st = StatsStorage(path)
        st.put({"type": "score", "iter": 0, "loss": 1.0})
        st.close()
        loaded = StatsStorage.load(path)
        assert loaded.path == path
        loaded.put({"type": "score", "iter": 1, "loss": 0.5})
        loaded.close()
        again = StatsStorage.load(path, persist=False)
        assert again.path is None           # explicit read-only opt-out
        assert [r["iter"] for r in again.of_type("score")] == [0, 1]


class TestZooModelReport:
    def test_lenet_training_produces_browsable_report(self, tmp_path):
        """VERDICT round-4 'done' criterion: training a zoo model
        produces a browsable report with PerformanceListener-style
        numbers in it."""
        from deeplearning4j_tpu.dataset import load_mnist
        from deeplearning4j_tpu.zoo import LeNet

        X, y = load_mnist(train=True, n_synthetic=128)
        Y = np.eye(10, dtype=np.float32)[y]
        net = LeNet(height=28, width=28, channels=1).build()
        st = StatsStorage(str(tmp_path / "lenet.jsonl"))
        lst = StatsListener(st, frequency=1)
        batches = [([X[i:i + 32]], [Y[i:i + 32]])
                   for i in range(0, 128, 32)]
        net.fit(batches, epochs=2, listeners=[lst])
        st.close()
        out = write_report(st, str(tmp_path / "lenet.html"))
        html = open(out, encoding="utf-8").read()
        assert "throughput" in html
        perf = st.of_type("perf")
        assert perf and perf[-1]["batches_per_sec"] > 0


class TestEpochStatsSingleTransfer:
    """Satellite (ISSUE 8): StatsListener.on_epoch_end computes its
    histograms/moments in float32 with ONE device→host copy per param
    — no float64 upcast doubling the epoch-boundary stall and peak
    host memory. The record schema is unchanged."""

    class _FakeSD:
        def __init__(self, params):
            self._params = params

        def trainable_params(self):
            return self._params

    def test_no_float64_upcast(self, monkeypatch):
        import jax.numpy as jnp

        seen_dtypes = []
        orig_hist = np.histogram

        def spy_hist(a, *args, **kw):
            seen_dtypes.append(np.asarray(a).dtype)
            return orig_hist(a, *args, **kw)

        monkeypatch.setattr(np, "histogram", spy_hist)
        st = StatsStorage()
        lst = StatsListener(st)
        sd = self._FakeSD({"w": jnp.arange(12, dtype=jnp.float32)})
        lst.on_epoch_end(sd, 0, 0.5)
        lst.on_epoch_end(sd, 1, 0.4)
        assert seen_dtypes and all(d == np.float32 for d in seen_dtypes)
        rec = st.of_type("params")[-1]["params"]["w"]
        # schema unchanged: plain floats + histogram + update stats
        assert isinstance(rec["mean"], float) and isinstance(
            rec["norm"], float)
        assert rec["update_norm"] == 0.0
        json.dumps(rec)

    def test_bfloat16_params_histogram(self):
        import jax.numpy as jnp

        st = StatsStorage()
        lst = StatsListener(st)
        sd = self._FakeSD(
            {"w": jnp.linspace(-1, 1, 64).astype(jnp.bfloat16)})
        lst.on_epoch_end(sd, 0, 0.1)
        ent = st.of_type("params")[-1]["params"]["w"]
        assert sum(ent["hist"]) == 64
        assert np.isfinite(ent["mean"])
