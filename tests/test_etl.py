"""ETL subsystem: schema, readers, transforms, and end-to-end training.

Mirrors the reference's datavec test strategy: unit tests per transform +
the two canonical e2e flows (CSV -> TransformProcess -> fit; image
directory -> CNN fit). Reference: TransformProcess.java:1,
RecordReaderDataSetIterator.java:54, ImageRecordReader.
"""
import numpy as np
import pytest

from deeplearning4j_tpu.etl import (
    CSVRecordReader, CollectionRecordReader, ImageRecordReader,
    ImageRecordReaderDataSetIterator, LineRecordReader,
    RecordReaderDataSetIterator, Schema, TransformProcess, analyze)

CSV = """sepal_l,sepal_w,species,junk
5.1,3.5,setosa,x
4.9,3.0,setosa,x
7.0,3.2,versicolor,x
6.4,3.2,versicolor,x
5.9,3.0,virginica,x
6.5,2.8,virginica,x
"""


def _schema():
    return (Schema.builder()
            .add_column_float("sepal_l")
            .add_column_float("sepal_w")
            .add_column_categorical("species", "setosa", "versicolor",
                                    "virginica")
            .add_column_string("junk")
            .build())


def test_csv_reader_and_schema():
    r = CSVRecordReader(text=CSV, skip_num_lines=1)
    rows = list(r)
    assert len(rows) == 6
    assert rows[0] == ["5.1", "3.5", "setosa", "x"]
    s = _schema()
    assert s.names() == ["sepal_l", "sepal_w", "species", "junk"]
    assert s.column("species").categories == ("setosa", "versicolor",
                                              "virginica")
    s2 = Schema.from_json(s.to_json())
    assert s2.names() == s.names()
    assert s2.column("species").categories == s.column("species").categories


def test_line_and_collection_readers():
    assert list(LineRecordReader(text="a\nb")) == [["a"], ["b"]]
    cr = CollectionRecordReader([[1, 2], [3, 4]])
    assert list(cr) == [[1, 2], [3, 4]]
    assert cr.num_records() == 2


def test_analyze():
    a = analyze(_schema(), CSVRecordReader(text=CSV, skip_num_lines=1))
    c = a.column("sepal_l")
    assert c.min == pytest.approx(4.9)
    assert c.max == pytest.approx(7.0)
    assert c.mean == pytest.approx(np.mean([5.1, 4.9, 7.0, 6.4, 5.9, 6.5]))
    assert a.column("species").categories == {"setosa": 2, "versicolor": 2,
                                              "virginica": 2}


def test_transform_process_chain():
    schema = _schema()
    analysis = analyze(schema, CSVRecordReader(text=CSV, skip_num_lines=1))
    tp = (TransformProcess.builder(schema)
          .remove_columns("junk")
          .normalize("sepal_l", "standardize", analysis)
          .normalize("sepal_w", "minmax", analysis)
          .filter_rows(lambda cols: cols["sepal_w"] > 0.1)
          .categorical_to_integer("species")
          .build())
    fs = tp.final_schema()
    assert fs.names() == ["sepal_l", "sepal_w", "species"]
    assert fs.column("species").ctype == "integer"
    cols = tp.execute_columnar(CSVRecordReader(text=CSV, skip_num_lines=1))
    assert cols["sepal_w"].min() > 0.1           # filtered
    assert cols["species"].dtype == np.int64
    assert abs(float(np.mean(
        tp.execute_columnar(CSVRecordReader(text=CSV, skip_num_lines=1))
        ["sepal_l"]))) < 2.0


def test_one_hot_and_rename_and_map():
    schema = _schema()
    tp = (TransformProcess.builder(schema)
          .remove_columns("junk")
          .rename_column("sepal_l", "sl")
          .map_column("sl", lambda v: v * 10.0)
          .categorical_to_one_hot("species")
          .build())
    fs = tp.final_schema()
    assert fs.names() == ["sl", "sepal_w", "species[setosa]",
                          "species[versicolor]", "species[virginica]"]
    cols = tp.execute_columnar(CSVRecordReader(text=CSV, skip_num_lines=1))
    assert cols["sl"][0] == pytest.approx(51.0)
    oh = np.stack([cols["species[setosa]"], cols["species[versicolor]"],
                   cols["species[virginica]"]], 1)
    np.testing.assert_allclose(oh.sum(1), 1.0)


def test_unknown_category_fails_loudly():
    schema = (Schema.builder()
              .add_column_categorical("c", "a", "b").build())
    tp = (TransformProcess.builder(schema)
          .categorical_to_integer("c").build())
    with pytest.raises(ValueError, match="not in categories"):
        tp.execute_columnar([["z"]])


def test_csv_to_training_e2e():
    """BASELINE-style e2e: CSV -> TransformProcess -> iterator -> fit()."""
    from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig
    from deeplearning4j_tpu.learning.updaters import Adam

    schema = _schema()
    analysis = analyze(schema, CSVRecordReader(text=CSV, skip_num_lines=1))
    tp = (TransformProcess.builder(schema)
          .remove_columns("junk")
          .normalize("sepal_l", "standardize", analysis)
          .normalize("sepal_w", "standardize", analysis)
          .categorical_to_integer("species")
          .build())
    it = RecordReaderDataSetIterator(
        CSVRecordReader(text=CSV, skip_num_lines=1), batch_size=3,
        label_column="species", num_classes=3, transform_process=tp)

    sd = SameDiff()
    x = sd.placeholder("x", shape=(-1, 2))
    y = sd.placeholder("y", shape=(-1, 3))
    rng = np.random.RandomState(0)
    w = sd.var("w", value=(rng.randn(2, 3) * 0.1).astype(np.float32))
    b = sd.var("b", value=np.zeros(3, np.float32))
    logits = x.mmul(w).add(b, name="logits")
    loss = sd.loss.softmax_cross_entropy(logits, y, name="loss")
    loss.mark_as_loss()
    sd.training_config = TrainingConfig(
        updater=Adam(0.1), data_set_feature_mapping=["x"],
        data_set_label_mapping=["y"])
    h = sd.fit(it, epochs=40)
    assert h.loss_curve.losses[-1] < h.loss_curve.losses[0] * 0.7


def test_image_folder_to_cnn_e2e(tmp_path):
    """image dir -> ImageRecordReader -> CNN fit() (reference:
    ImageRecordReader + ParentPathLabelGenerator flow)."""
    from PIL import Image
    rng = np.random.RandomState(0)
    # two classes with an obvious mean-intensity signal
    for label, base in (("dark", 40), ("bright", 200)):
        d = tmp_path / label
        d.mkdir()
        for i in range(8):
            arr = np.clip(rng.normal(base, 20, (10, 10)), 0, 255
                          ).astype(np.uint8)
            Image.fromarray(arr, mode="L").save(d / f"im{i}.png")

    reader = ImageRecordReader(10, 10, channels=1, root=str(tmp_path))
    assert reader.labels == ["bright", "dark"]
    assert reader.num_records() == 16
    it = ImageRecordReaderDataSetIterator(reader, batch_size=8, shuffle=True,
                                          seed=0)
    assert it.num_classes() == 2

    from deeplearning4j_tpu.learning.updaters import Adam
    from deeplearning4j_tpu.nn import (
        ConvolutionLayer, InputType, MultiLayerNetwork,
        NeuralNetConfiguration, OutputLayer, SubsamplingLayer)
    conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(5e-3))
            .list()
            .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                    activation="relu"))
            .layer(SubsamplingLayer(kernel_size=(2, 2)))
            .layer(OutputLayer(n_out=2, loss_function="MCXENT"))
            .set_input_type(InputType.convolutional(10, 10, 1))
            .build())
    net = MultiLayerNetwork(conf).init()
    h = net.fit(it, epochs=30)
    assert h.loss_curve.losses[-1] < h.loss_curve.losses[0] * 0.5
    # prediction sanity: brights vs darks separable
    X, Y = it._load_all()
    preds = np.asarray(net.output(X).data)
    acc = (preds.argmax(1) == Y.argmax(1)).mean()
    assert acc >= 0.9, acc


# ---- image transforms ------------------------------------------------------

def test_image_transforms_shapes_and_values():
    """(reference: datavec transform/* — flip/rotate/crop/resize/box)"""
    import numpy as np
    from deeplearning4j_tpu.etl import (
        BoxImageTransform, CropImageTransform, FlipImageTransform,
        PipelineImageTransform, RandomCropTransform, ResizeImageTransform,
        RotateImageTransform, ScaleImageTransform)
    rng = np.random.default_rng(0)
    img = np.arange(6 * 8 * 3, dtype=np.float32).reshape(6, 8, 3)
    np.testing.assert_array_equal(
        FlipImageTransform(1).transform(img, rng), img[:, ::-1])
    np.testing.assert_array_equal(
        FlipImageTransform(0).transform(img, rng), img[::-1])
    rot = RotateImageTransform(90).transform(img, rng)
    assert rot.shape == (8, 6, 3)
    crop = CropImageTransform(1).transform(img, rng)
    assert crop.shape == (4, 6, 3)
    rc = RandomCropTransform(4, 4).transform(img, rng)
    assert rc.shape == (4, 4, 3)
    rs = ResizeImageTransform(12, 16).transform(img, rng)
    assert rs.shape == (12, 16, 3)
    # bilinear resize preserves corners
    np.testing.assert_allclose(rs[0, 0], img[0, 0])
    np.testing.assert_allclose(rs[-1, -1], img[-1, -1])
    sc = ScaleImageTransform(scale=2.0, shift=1.0, clip=None)
    np.testing.assert_allclose(sc.transform(img, rng), img * 2 + 1)
    box = BoxImageTransform(10, 10, fill=-1.0).transform(img, rng)
    assert box.shape == (10, 10, 3) and box[0, 0, 0] == -1.0
    pipe = PipelineImageTransform(FlipImageTransform(1),
                                  (ScaleImageTransform(0.5, clip=None), 1.0))
    np.testing.assert_allclose(pipe(img, rng), img[:, ::-1] * 0.5)


def test_image_reader_applies_transform(tmp_path):
    import numpy as np
    from deeplearning4j_tpu.etl import (FlipImageTransform,
                                        ImageRecordReader)
    d = tmp_path / "cats"
    d.mkdir()
    img = np.arange(4 * 4, dtype=np.float32).reshape(4, 4)
    np.save(str(d / "a.npy"), img)
    rr = ImageRecordReader(4, 4, channels=1, root=str(tmp_path),
                           transform=FlipImageTransform(1))
    arr, label = next(iter(rr))
    assert label == "cats"
    np.testing.assert_array_equal(arr[:, :, 0], img[:, ::-1])


def test_quality_counts_ragged_rows_as_missing():
    """Regression: short rows count their absent cells as missing."""
    from deeplearning4j_tpu.etl import (CollectionRecordReader, Schema,
                                        analyze_quality)
    s = (Schema.builder().add_column_integer("a").add_column_float("b")
         .add_column_categorical("c", "x").build())
    qa = analyze_quality(s, CollectionRecordReader(
        [[1, 2.0, "x"], [1, 2.0]]))
    q = qa.column("c")
    assert (q.count_total, q.count_missing) == (2, 1)


def test_size_varying_transform_rejected_by_reader(tmp_path):
    """Regression: per-image varying output shapes raise a clear error
    naming the transform."""
    import numpy as np
    from deeplearning4j_tpu.etl import ImageRecordReader, RotateImageTransform
    d = tmp_path / "x"
    d.mkdir()
    np.save(str(d / "a.npy"), np.zeros((4, 6), np.float32))
    np.save(str(d / "b.npy"), np.zeros((4, 6), np.float32))

    class AlternatingRotate(RotateImageTransform):
        def __init__(self):
            super().__init__(None)
            self._n = 0

        def transform(self, img, rng):
            self._n += 1
            return np.rot90(img, k=self._n % 2, axes=(0, 1)).copy()

    rr = ImageRecordReader(4, 6, channels=1, root=str(tmp_path),
                           transform=AlternatingRotate())
    import pytest as _pytest
    with _pytest.raises(ValueError, match="AlternatingRotate"):
        list(rr)


def test_crop_margins_validated():
    import numpy as np
    import pytest as _pytest
    from deeplearning4j_tpu.etl import CropImageTransform
    img = np.zeros((6, 8, 3), np.float32)
    with _pytest.raises(ValueError, match="consume"):
        CropImageTransform(4).transform(img, np.random.default_rng(0))
