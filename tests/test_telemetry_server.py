"""Live telemetry HTTP endpoint (monitor/server.py).

Covers the route surface (/metrics /healthz /readyz /report /trace
/stats), health-state transitions driven by the fault rail, the
MonitorListener- and ParallelInference-hosted servers, and the
acceptance criteria: while a fit runs, /metrics serves parse-valid
Prometheus text containing ``dl4j_layer_*`` series, and /healthz goes
unhealthy during a chaos-injected rollback then recovers.
"""
import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig
from deeplearning4j_tpu.checkpoint import CheckpointManager
from deeplearning4j_tpu.dataset.iterators import ArrayDataSetIterator
from deeplearning4j_tpu.faults import (ChaosMonkey, FaultTolerantFit,
                                       RetryPolicy)
from deeplearning4j_tpu.learning.updaters import Adam
from deeplearning4j_tpu.monitor import (MetricsRegistry, MonitorListener,
                                        TensorStatsConfig, serve)
from deeplearning4j_tpu.monitor.server import health_snapshot
from deeplearning4j_tpu.ui.stats import StatsStorage


def _get(url, timeout=10):
    """(status, body) without raising on 4xx/5xx."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read().decode("utf-8")
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode("utf-8")


def _parse_prometheus(text):
    """Strict-enough exposition parse: {name{labels}: float}. Raises on
    malformed sample lines — the /metrics contract is machine-read."""
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name, _, value = line.rpartition(" ")
        assert name and value, f"malformed sample line: {line!r}"
        out[name] = float(value)
    return out


def _mlp(**tc_kw):
    rng = np.random.default_rng(0)
    sd = SameDiff()
    x = sd.placeholder("x", shape=(-1, 8))
    w0 = sd.var("w0", value=rng.normal(0, .1, (8, 16)).astype(np.float32))
    b0 = sd.var("b0", value=np.zeros(16, np.float32))
    h = sd.nn.relu(x.mmul(w0).add(b0))
    w1 = sd.var("w1", value=rng.normal(0, .1, (16, 2)).astype(np.float32))
    logits = h.mmul(w1)
    labels = sd.placeholder("labels", shape=(-1, 2))
    sd.loss.softmax_cross_entropy(logits, labels, name="loss")
    sd.set_loss_variables(["loss"])
    sd.training_config = TrainingConfig(
        updater=Adam(1e-2), data_set_feature_mapping=["x"],
        data_set_label_mapping=["labels"], **tc_kw)
    return sd


def _it(batch=8, n=64, seed=1):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 8)).astype(np.float32)
    Y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, n)]
    return ArrayDataSetIterator(X, Y, batch_size=batch)


@pytest.fixture
def server():
    st = StatsStorage()
    srv = serve(port=0, storage=st)
    try:
        yield srv, st
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# routes

class TestRoutes:
    def test_index_and_404(self, server):
        srv, _ = server
        code, body = _get(srv.url + "/")
        assert code == 200 and "/metrics" in body
        code, body = _get(srv.url + "/nope")
        assert code == 404 and "no route" in body

    def test_metrics_parse_valid_with_process_telemetry(self, server):
        srv, st = server
        st.put({"type": "checkpoint", "step": 3, "bytes": 100,
                "serialize_seconds": 0.01, "t": time.time()})
        code, text = _get(srv.url + "/metrics")
        assert code == 200
        samples = _parse_prometheus(text)
        assert samples["dl4j_process_uptime_seconds"] > 0
        assert samples["dl4j_checkpoint_commits_total"] == 1.0
        # Linux: RSS is available; elsewhere the series is absent
        rss = samples.get("dl4j_process_rss_bytes")
        if rss is not None:
            assert rss > 1 << 20

    def test_shared_registry_scrape_does_not_double_count(self):
        """Review regression: MonitorListener folds its own records AND
        a TelemetryServer sharing its registry folds the same storage
        on every scrape — counter-typed series must read 1x, not 2x
        (both paths go through the storage's shared fold mark)."""
        storage = StatsStorage()
        reg = MetricsRegistry()
        mon = MonitorListener(storage, registry=reg, frequency=4,
                              serve_port=0)
        sd = _mlp(fused_steps=4,
                  tensorstats=TensorStatsConfig(every_n=4))
        from deeplearning4j_tpu.monitor import enable_tracing, \
            disable_tracing
        enable_tracing(reset=True)
        try:
            sd.fit(_it(), epochs=1, listeners=[mon])
        finally:
            disable_tracing()
        try:
            samples = _parse_prometheus(
                _get(mon.server.url + "/metrics")[1])
            # scrape twice more — still no growth without new records
            samples2 = _parse_prometheus(
                _get(mon.server.url + "/metrics")[1])
            true_steps = sum(r["steps"]
                             for r in storage.of_type("steptime"))
            assert samples["dl4j_steptime_steps_total"] == true_steps
            assert samples2["dl4j_steptime_steps_total"] == true_steps
            n_ratio_obs = sum(
                len(r["layers"]) for r in storage.of_type("tensorstats"))
            assert samples2[
                'dl4j_layer_update_ratio_dist_bucket{le="+Inf"}'] \
                == n_ratio_obs
        finally:
            mon.server.close()

    def test_stats_nonpositive_n_returns_nothing(self, server):
        """Review regression: /stats?n=0 must not dump the whole
        storage (recs[-0:] would mean ALL)."""
        srv, st = server
        for i in range(5):
            st.put({"type": "score", "iter": i, "loss": 0.1})
        assert _get(srv.url + "/stats?n=0")[1] == ""
        assert _get(srv.url + "/stats?n=-3")[1] == ""
        assert st.tail(0) == [] and st.tail(-3) == []

    def test_metrics_scrape_is_incremental(self, server):
        srv, st = server
        st.put({"type": "checkpoint", "step": 1, "bytes": 10,
                "t": time.time()})
        _get(srv.url + "/metrics")
        _get(srv.url + "/metrics")           # re-scrape: no double count
        samples = _parse_prometheus(_get(srv.url + "/metrics")[1])
        assert samples["dl4j_checkpoint_commits_total"] == 1.0

    def test_stats_tail_and_type_filter(self, server):
        srv, st = server
        for i in range(5):
            st.put({"type": "score", "iter": i, "loss": 0.1})
        st.put({"type": "faults", "event": "fault", "t": 1.0})
        code, body = _get(srv.url + "/stats?n=2&type=score")
        lines = [json.loads(l) for l in body.splitlines()]
        assert [r["iter"] for r in lines] == [3, 4]
        code, body = _get(srv.url + "/stats?type=faults")
        assert len(body.splitlines()) == 1

    def test_report_and_trace(self, server):
        srv, st = server
        st.put({"type": "score", "iter": 0, "epoch": 0, "loss": 1.0,
                "t": 0.0})
        code, html = _get(srv.url + "/report")
        assert code == 200 and html.startswith("<!doctype html>")
        code, body = _get(srv.url + "/trace")
        assert code == 200 and "traceEvents" in json.loads(body)

    def test_no_storage_routes(self):
        srv = serve(port=0)
        try:
            assert _get(srv.url + "/report")[0] == 404
            assert _get(srv.url + "/stats")[0] == 404
            assert _get(srv.url + "/metrics")[0] == 200
            assert _get(srv.url + "/healthz")[0] == 200
        finally:
            srv.close()

    def test_close_stops_serving(self):
        srv = serve(port=0)
        url = srv.url
        srv.close()
        with pytest.raises(OSError):
            urllib.request.urlopen(url + "/healthz", timeout=2)


# ---------------------------------------------------------------------------
# health semantics

class TestHealth:
    def test_fault_rollback_recover_transitions(self, server):
        srv, st = server
        assert _get(srv.url + "/healthz")[0] == 200
        st.put({"type": "faults", "event": "fault", "t": time.time()})
        code, body = _get(srv.url + "/healthz")
        assert code == 503
        assert json.loads(body)["fault_state"] == "recovering"
        st.put({"type": "faults", "event": "rollback", "t": time.time()})
        assert _get(srv.url + "/healthz")[0] == 503
        assert _get(srv.url + "/readyz")[0] == 503   # unhealthy => unready
        st.put({"type": "faults", "event": "recovered", "t": time.time()})
        code, body = _get(srv.url + "/healthz")
        assert code == 200
        snap = json.loads(body)
        assert snap["fault_state"] == "ok" and snap["rollbacks"] == 1

    def test_retry_exhausted_is_sticky(self, server):
        srv, st = server
        st.put({"type": "faults", "event": "retry_exhausted", "t": 1.0})
        st.put({"type": "faults", "event": "recovered", "t": 2.0})
        code, body = _get(srv.url + "/healthz")
        assert code == 503 and json.loads(body)["fault_state"] == "failed"

    def test_readyz_staleness(self):
        st = StatsStorage()
        srv = serve(port=0, storage=st, stale_after_s=0.05)
        try:
            srv.add_health_provider(
                "train", lambda: {"last_step_t": time.time() - 10.0})
            code, body = _get(srv.url + "/readyz")
            assert code == 503
            snap = json.loads(body)
            assert snap["last_step_age_s"] >= 10.0
            assert snap["healthy"] is True       # stale != faulted
            assert _get(srv.url + "/healthz")[0] == 200
            srv.add_health_provider(
                "train", lambda: {"last_step_t": time.time()})
            assert _get(srv.url + "/readyz")[0] == 200
        finally:
            srv.close()

    def test_provider_error_reported_unhealthy(self, server):
        srv, _ = server

        def boom():
            raise RuntimeError("dead hook")

        srv.add_health_provider("broken", boom)
        code, body = _get(srv.url + "/healthz")
        assert code == 503
        assert "dead hook" in body

    def test_provider_ready_gate(self, server):
        srv, _ = server
        srv.add_health_provider("q", lambda: {"ready": False,
                                              "queue_depth": 9})
        assert _get(srv.url + "/healthz")[0] == 200
        code, body = _get(srv.url + "/readyz")
        assert code == 503
        assert json.loads(body)["providers"]["q"]["queue_depth"] == 9

    def test_snapshot_pure_function(self):
        st = StatsStorage()
        st.put({"type": "faults", "event": "rollback", "t": 1.0})
        snap = health_snapshot(st)
        assert snap["healthy"] is False and snap["rollbacks"] == 1
        st.put({"type": "faults", "event": "recovered", "t": 2.0})
        assert health_snapshot(st)["healthy"] is True


# ---------------------------------------------------------------------------
# hosted servers: MonitorListener + ParallelInference

class TestHostedServers:
    def test_live_metrics_during_fit(self):
        """Acceptance: while a fit is running, GET /metrics returns
        parse-valid Prometheus text containing dl4j_layer_* series."""
        storage = StatsStorage()
        mon = MonitorListener(storage, frequency=4, serve_port=0)
        sd = _mlp(fused_steps=4, sentinel=True,
                  tensorstats=TensorStatsConfig(every_n=2))
        seen = {}

        class MidFitProbe:
            frequency = 1_000_000_000
            def on_training_start(self, sd): ...
            def on_training_end(self, sd): ...
            def on_epoch_start(self, sd, epoch): ...
            def iterations_done(self, sd, epoch, iterations, losses): ...

            def on_epoch_end(self, probe_self, epoch, mean_loss=None):
                # mid-fit (between epochs): the server is live
                if epoch == 0 and mon.server is not None:
                    code, text = _get(mon.server.url + "/metrics")
                    seen["code"] = code
                    seen["samples"] = _parse_prometheus(text)
                    seen["health"] = _get(mon.server.url + "/healthz")[0]

        sd.fit(_it(), epochs=2, listeners=[mon, MidFitProbe()])
        try:
            assert seen["code"] == 200
            layer_series = [k for k in seen["samples"]
                            if k.startswith("dl4j_layer_")]
            assert any('dl4j_layer_grad_l2{layer="w0"}' == k
                       for k in layer_series)
            assert any("dl4j_layer_update_ratio" in k
                       for k in layer_series)
            assert seen["health"] == 200
            # heartbeat provider: last-step age tracked from flushes
            snap = json.loads(_get(mon.server.url + "/healthz")[1])
            assert snap["providers"]["training"]["last_iteration"] >= 8
            assert snap["last_step_age_s"] is not None
            # the report renders live too
            assert "Layer health" in _get(mon.server.url + "/report")[1]
        finally:
            mon.server.close()

    @pytest.mark.chaos
    def test_healthz_unhealthy_during_rollback_then_recovers(self,
                                                             tmp_path):
        """Acceptance: /healthz transitions to unhealthy during a
        chaos-injected rollback and recovers afterwards. The probe
        rides FaultTolerantFit's backoff sleep — a point strictly
        between the rollback record and the recovery."""
        storage = StatsStorage()
        srv = serve(port=0, storage=storage)
        codes = []

        def probing_sleep(_s):
            codes.append(_get(srv.url + "/healthz")[0])

        sd = _mlp(fused_steps=4, sentinel=True)
        chaos = ChaosMonkey(seed=0)
        it = chaos.poison_batches(_it(batch=16), at_step=5)
        mgr = CheckpointManager(tmp_path, keep_last_n=3)
        ftf = FaultTolerantFit(
            sd, mgr,
            policy=RetryPolicy(max_retries=2, backoff_base=0.01,
                               quarantine_corrupt=False),
            checkpoint_every_n_iterations=4, stats_storage=storage,
            sleep=probing_sleep)
        try:
            h = ftf.fit(it, epochs=3)
            assert np.isfinite(h.final_loss())
            assert ftf.rollbacks >= 1
            # mid-recovery: every backoff probe saw 503
            assert codes and all(c == 503 for c in codes)
            # recovered: healthy again, with the rollback on record
            code, body = _get(srv.url + "/healthz")
            assert code == 200
            snap = json.loads(body)
            assert snap["fault_state"] == "ok"
            assert snap["rollbacks"] >= 1
        finally:
            srv.close()
            mgr.close()

    def test_parallel_inference_telemetry(self):
        from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                           MultiLayerNetwork,
                                           NeuralNetConfiguration,
                                           OutputLayer)
        from deeplearning4j_tpu.serving import (InferenceMode,
                                                ParallelInference)
        conf = (NeuralNetConfiguration.builder().seed(7)
                .updater(Adam(1e-3)).list()
                .layer(DenseLayer(n_out=16, activation="tanh"))
                .layer(OutputLayer(n_out=3, loss_function="MCXENT"))
                .set_input_type(InputType.feed_forward(8))
                .build())
        net = MultiLayerNetwork(conf).init()
        pi = ParallelInference(net, mode=InferenceMode.INPLACE,
                               telemetry_port=0)
        try:
            x = np.random.default_rng(0).normal(size=(4, 8)) \
                .astype(np.float32)
            pi.output(x)
            code, text = _get(pi.telemetry.url + "/metrics")
            samples = _parse_prometheus(text)
            assert samples["dl4j_serving_requests_served_total"] >= 1
            code, body = _get(pi.telemetry.url + "/readyz")
            assert code == 200
            snap = json.loads(body)
            assert snap["providers"]["serving"]["queue_depth"] == 0
            assert snap["providers"]["serving"]["queue_capacity"] > 0
        finally:
            url = pi.telemetry.url
            pi.shutdown()
        with pytest.raises(OSError):
            urllib.request.urlopen(url + "/readyz", timeout=2)
