"""TF GraphDef import: golden-output tests.

Methodology mirrors the reference's framework-import conformance suite
(platform-tests .../frameworkimport/tensorflow — run imported graphs,
compare against recorded TF outputs): graphs are built as real serialized
GraphDef .pb bytes (via modelimport/tf_builder's wire encoder — TF itself
is not available in this environment), decoded + imported, executed, and
compared against numpy-computed golden values.
"""
import numpy as np
import pytest

from deeplearning4j_tpu.modelimport.tf_builder import GraphDefBuilder
from deeplearning4j_tpu.modelimport.tf_import import (
    TFImportError, import_tf_graph, supported_tf_ops)
from deeplearning4j_tpu.modelimport.tf_pb import GraphDef


def _run(pb_bytes, feeds, outputs, **kw):
    sd = import_tf_graph(pb_bytes, **kw)
    res = sd.output(placeholders=feeds, outputs=outputs)
    return {k: np.asarray(v.data) for k, v in res.items()}


def test_wire_roundtrip():
    b = GraphDefBuilder()
    b.const("c", np.arange(6, dtype=np.float32).reshape(2, 3))
    b.placeholder("x", shape=[-1, 3], dtype=np.float32)
    b.node("Add", "y", "x", "c")
    g = GraphDef(b.build())
    assert [n.name for n in g.nodes] == ["c", "x", "y"]
    assert g.nodes[2].op == "Add"
    assert g.nodes[2].inputs == ["x", "c"]


def test_mlp_matmul_bias_relu():
    rng = np.random.RandomState(0)
    W = rng.randn(4, 3).astype(np.float32)
    bias = rng.randn(3).astype(np.float32)
    x = rng.randn(2, 4).astype(np.float32)

    b = GraphDefBuilder()
    b.placeholder("x", shape=[-1, 4])
    b.const("W", W)
    b.const("b", bias)
    b.node("MatMul", "mm", "x", "W", transpose_a=False, transpose_b=False)
    b.node("BiasAdd", "ba", "mm", "b")
    b.node("Relu", "out", "ba")

    got = _run(b.build(), {"x": x}, ["out"])["out"]
    want = np.maximum(x @ W + bias, 0)
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_identity_and_control_deps():
    b = GraphDefBuilder()
    b.placeholder("x", shape=[2, 2])
    b.node("NoOp", "init")
    b.raw_node("y", "Identity", ["x", "^init"])
    b.node("Neg", "out", "y")
    x = np.ones((2, 2), np.float32)
    got = _run(b.build(), {"x": x}, ["out"])["out"]
    np.testing.assert_allclose(got, -x)


def test_shape_math_folds_to_reshape():
    """The frozen-graph idiom Shape -> StridedSlice -> Pack -> Reshape must
    fold away into a static reshape."""
    b = GraphDefBuilder()
    b.placeholder("x", shape=[2, 3, 4])
    b.node("Shape", "sh", "x")
    b.const("b0", np.array([0], np.int32))
    b.const("b1", np.array([1], np.int32))
    b.const("st", np.array([1], np.int32))
    b.raw_node("batch", "StridedSlice", ["sh", "b0", "b1", "st"],
               {"shrink_axis_mask": 1})
    b.const("rest", np.array(12, np.int32))
    b.node("Pack", "newshape", "batch", "rest", axis=0)
    b.node("Reshape", "out", "x", "newshape")
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    got = _run(b.build(), {"x": x}, ["out"])["out"]
    np.testing.assert_allclose(got, x.reshape(2, 12))


def test_reduce_and_softmax():
    b = GraphDefBuilder()
    b.placeholder("x", shape=[2, 5])
    b.const("axes", np.array([1], np.int32))
    b.node("Mean", "m", "x", "axes", keep_dims=True)
    b.node("Sub", "centered", "x", "m")
    b.node("Softmax", "out", "centered")
    x = np.random.RandomState(1).randn(2, 5).astype(np.float32)
    got = _run(b.build(), {"x": x}, ["out"])["out"]
    c = x - x.mean(1, keepdims=True)
    e = np.exp(c - c.max(1, keepdims=True))
    want = e / e.sum(1, keepdims=True)
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_conv_pool_fused_batchnorm():
    rng = np.random.RandomState(2)
    x = rng.randn(1, 8, 8, 3).astype(np.float32)
    k = rng.randn(3, 3, 3, 4).astype(np.float32)
    scale = rng.rand(4).astype(np.float32) + 0.5
    offset = rng.randn(4).astype(np.float32)
    mean = rng.randn(4).astype(np.float32)
    var = rng.rand(4).astype(np.float32) + 0.5

    b = GraphDefBuilder()
    b.placeholder("x", shape=[-1, 8, 8, 3])
    b.const("k", k)
    b.const("scale", scale)
    b.const("offset", offset)
    b.const("mean", mean)
    b.const("var", var)
    b.node("Conv2D", "conv", "x", "k", strides=[1, 1, 1, 1],
           padding=b"SAME", data_format=b"NHWC", dilations=[1, 1, 1, 1])
    b.node("FusedBatchNormV3", "bn", "conv", "scale", "offset", "mean",
           "var", epsilon=0.001, is_training=False, data_format=b"NHWC")
    b.raw_node("pool", "MaxPool", ["bn"],
               {"ksize": [1, 2, 2, 1], "strides": [1, 2, 2, 1],
                "padding": b"VALID", "data_format": b"NHWC"})
    got = _run(b.build(), {"x": x}, ["pool"])["pool"]

    # numpy golden
    from numpy.lib.stride_tricks import sliding_window_view
    xp = np.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    win = sliding_window_view(xp, (3, 3), axis=(1, 2))  # (1,8,8,3,3,3)
    conv = np.einsum("bhwcij,ijco->bhwo", win, k)
    bn = (conv - mean) / np.sqrt(var + 0.001) * scale + offset
    w2 = bn.reshape(1, 4, 2, 4, 2, 4)
    want = w2.max(axis=(2, 4))
    np.testing.assert_allclose(got, want.astype(np.float32), rtol=2e-4,
                               atol=2e-4)


def test_gather_one_hot_embedding():
    rng = np.random.RandomState(3)
    table = rng.randn(10, 6).astype(np.float32)
    ids = np.array([[1, 5, 3], [0, 2, 9]], np.int32)

    b = GraphDefBuilder()
    b.placeholder("ids", shape=[-1, 3], dtype=np.int32)
    b.const("table", table)
    b.const("axis", np.array(0, np.int32))
    b.node("GatherV2", "emb", "table", "ids", "axis")
    b.const("depth", np.array(10, np.int32))
    b.const("on", np.array(1.0, np.float32))
    b.const("off", np.array(0.0, np.float32))
    b.node("OneHot", "oh", "ids", "depth", "on", "off")
    got = _run(b.build(), {"ids": ids}, ["emb", "oh"])
    np.testing.assert_allclose(got["emb"], table[ids], rtol=1e-6)
    want_oh = np.eye(10, dtype=np.float32)[ids]
    np.testing.assert_allclose(got["oh"], want_oh)


def test_concat_split_pack_transpose():
    b = GraphDefBuilder()
    b.placeholder("x", shape=[2, 4])
    b.const("axis1", np.array(1, np.int32))
    b.node("ConcatV2", "cc", "x", "x", "axis1")
    b.const("axis0", np.array(0, np.int32))
    b.node("Split", "sp", "axis0", "cc", num_split=2)
    b.node("Pack", "pk", "sp:0", "sp:1", axis=0)
    b.const("perm", np.array([1, 0, 2], np.int32))
    b.node("Transpose", "out", "pk", "perm")
    x = np.arange(8, dtype=np.float32).reshape(2, 4)
    got = _run(b.build(), {"x": x}, ["out"])["out"]
    cc = np.concatenate([x, x], 1)
    sp = np.split(cc, 2, 0)
    want = np.stack(sp, 0).transpose(1, 0, 2)
    np.testing.assert_allclose(got, want)


def test_unmapped_op_reports_cleanly():
    b = GraphDefBuilder()
    b.placeholder("x", shape=[2])
    b.node("SomeExoticOp", "y", "x")
    with pytest.raises(TFImportError, match="unmapped TF op 'SomeExoticOp'"):
        import_tf_graph(b.build())


def test_data_dependent_structural_arg_reports_cleanly():
    b = GraphDefBuilder()
    b.placeholder("x", shape=[4])
    b.placeholder("shape", shape=[2], dtype=np.int32)
    b.node("Reshape", "y", "x", "shape")
    with pytest.raises(TFImportError, match="must be trace-time constant"):
        import_tf_graph(b.build())


def test_trainable_auto_splits_weights_from_structure():
    b = GraphDefBuilder()
    b.placeholder("x", shape=[-1, 4])
    b.const("W", np.ones((4, 2), np.float32))
    b.const("axes", np.array([1], np.int32))   # int structural const
    b.node("MatMul", "mm", "x", "W")
    b.node("Sum", "out", "mm", "axes")
    sd = import_tf_graph(b.build(), trainable="auto")
    params = sd.trainable_params()
    assert "W" in params
    assert len(params) == 1
    # and it trains: gradient flows to W
    grads = sd.calculate_gradients({"x": np.ones((3, 4), np.float32)},
                                   wrt=["W"], loss="out")
    assert np.asarray(grads["W"].data).shape == (4, 2)
    assert np.abs(np.asarray(grads["W"].data)).sum() > 0


def test_strided_slice_masks():
    b = GraphDefBuilder()
    b.placeholder("x", shape=[2, 3, 4])
    b.const("begin", np.array([0, 1], np.int32))
    b.const("end", np.array([0, 3], np.int32))
    b.const("strides", np.array([1, 1], np.int32))
    b.raw_node("y", "StridedSlice", ["x", "begin", "end", "strides"],
               {"begin_mask": 1, "end_mask": 1, "shrink_axis_mask": 0})
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    got = _run(b.build(), {"x": x}, ["y"])["y"]
    np.testing.assert_allclose(got, x[:, 1:])


def test_cast_argmax_select():
    b = GraphDefBuilder()
    b.placeholder("x", shape=[2, 3])
    b.const("dim", np.array(1, np.int32))
    b.node("ArgMax", "am", "x", "dim", output_type=3)
    b.node("Cast", "amf", "am", DstT=1)
    b.const("zeros", np.zeros((2, 3), np.float32))
    b.node("Greater", "gt", "x", "zeros")
    b.node("Select", "sel", "gt", "x", "zeros")
    x = np.array([[1., -2., 3.], [-1., 5., 2.]], np.float32)
    got = _run(b.build(), {"x": x}, ["amf", "sel"])
    np.testing.assert_allclose(got["amf"], [2., 1.])
    np.testing.assert_allclose(got["sel"], np.maximum(x, 0))


def test_supported_op_count():
    ops = supported_tf_ops()
    assert len(ops) >= 110, f"importer op coverage regressed: {len(ops)}"


def test_erf_gelu_pattern():
    """BERT's gelu: x * 0.5 * (1 + erf(x / sqrt(2)))."""
    b = GraphDefBuilder()
    b.placeholder("x", shape=[2, 4])
    b.const("sqrt2", np.array(np.sqrt(2.0), np.float32))
    b.node("RealDiv", "xd", "x", "sqrt2")
    b.node("Erf", "e", "xd")
    b.const("one", np.array(1.0, np.float32))
    b.node("AddV2", "e1", "e", "one")
    b.const("half", np.array(0.5, np.float32))
    b.node("Mul", "xh", "x", "half")
    b.node("Mul", "out", "xh", "e1")
    x = np.random.RandomState(4).randn(2, 4).astype(np.float32)
    got = _run(b.build(), {"x": x}, ["out"])["out"]
    from scipy.special import erf as sperf  # scipy ships with numpy stack
    want = x * 0.5 * (1 + sperf(x / np.sqrt(2)))
    np.testing.assert_allclose(got, want.astype(np.float32), rtol=1e-5)


def test_dtype_attrs_in_tf_native_encoding():
    """Real TF GraphDefs encode Cast DstT / ArgMax output_type as
    AttrValue.type (field 6), not as a plain int — both must import."""
    b = GraphDefBuilder()
    b.placeholder("x", shape=[2, 3])
    b.node("Cast", "xi", "x", DstT=("dtype", 3))          # -> int32
    b.const("dim", np.array(1, np.int32))
    b.node("ArgMax", "am", "x", "dim", output_type=("dtype", 3))
    x = np.array([[1.5, -2.0, 3.25], [0.5, 5.0, 2.0]], np.float32)
    got = _run(b.build(), {"x": x}, ["xi", "am"])
    assert got["xi"].dtype == np.int32
    np.testing.assert_allclose(got["xi"], x.astype(np.int32))
    assert got["am"].dtype == np.int32
    np.testing.assert_allclose(got["am"], [2, 1])


def test_placeholder_with_default_uses_const_default():
    b = GraphDefBuilder()
    b.placeholder("x", shape=[2, 2])
    b.const("kp_default", np.array(0.75, np.float32))
    b.raw_node("keep_prob", "PlaceholderWithDefault", ["kp_default"],
               {"dtype": ("dtype", 1), "shape": ("shape", [])})
    b.node("Mul", "out", "x", "keep_prob")
    x = np.ones((2, 2), np.float32)
    sd = import_tf_graph(b.build())
    # evaluates WITHOUT feeding keep_prob (TF default semantics)
    res = sd.output(placeholders={"x": x}, outputs=["out"])
    np.testing.assert_allclose(np.asarray(res["out"].data), x * 0.75)
