"""In-graph per-layer tensor statistics (monitor/tensorstats.py).

The DL4J ``BaseStatsListener`` parity rail computed inside the compiled
step: per-layer grad/update/param summaries sampled in-graph, folded
into the scan carry like the divergence sentinel, fetched at flush
boundaries, and published as ``{"type": "tensorstats"}`` records.

Composition coverage (the PR's satellite contract):
- a clean fused run with tensorstats AND the sentinel sharing the carry
  is bit-identical (params + losses) to both off;
- tensorstats under a ``ShardingSpec`` mesh reports the same norms as
  the unsharded run;
- ``SameDiff.precompile()`` covers the stats-enabled window signature
  (0 lazy window compiles).
"""
import json

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig
from deeplearning4j_tpu.autodiff.training import Listener
from deeplearning4j_tpu.dataset.iterators import ArrayDataSetIterator
from deeplearning4j_tpu.faults.errors import TrainingDivergedError
from deeplearning4j_tpu.learning.updaters import Adam, Sgd
from deeplearning4j_tpu.monitor import (LayerHealthWatcher, MetricsRegistry,
                                        MonitorListener, TensorStatsConfig)
from deeplearning4j_tpu.monitor.tensorstats import (FAMILY_PREFIX,
                                                    SCALAR_FIELDS,
                                                    build_record, normalize,
                                                    summarize_leaf)
from deeplearning4j_tpu.parallel import ShardingSpec
from deeplearning4j_tpu.ui.report import render_report
from deeplearning4j_tpu.ui.stats import StatsStorage


def _mlp(tensorstats=None, fused_steps=1, accum_steps=1, sentinel=False,
         sharding=None, lr=1e-2, updater=None):
    rng = np.random.default_rng(0)
    sd = SameDiff()
    x = sd.placeholder("x", shape=(-1, 8))
    w0 = sd.var("w0", value=rng.normal(0, .1, (8, 16)).astype(np.float32))
    b0 = sd.var("b0", value=np.zeros(16, np.float32))
    h = sd.nn.relu(x.mmul(w0).add(b0))
    w1 = sd.var("w1", value=rng.normal(0, .1, (16, 2)).astype(np.float32))
    logits = h.mmul(w1)
    labels = sd.placeholder("labels", shape=(-1, 2))
    sd.loss.softmax_cross_entropy(logits, labels, name="loss")
    sd.set_loss_variables(["loss"])
    sd.training_config = TrainingConfig(
        updater=updater or Adam(lr), data_set_feature_mapping=["x"],
        data_set_label_mapping=["labels"], fused_steps=fused_steps,
        accum_steps=accum_steps, sentinel=sentinel, sharding=sharding,
        tensorstats=tensorstats)
    return sd


def _data(n=64, seed=1):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 8)).astype(np.float32)
    Y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, n)]
    return X, Y


def _it(batch=8, n=64, seed=1):
    X, Y = _data(n, seed)
    return ArrayDataSetIterator(X, Y, batch_size=batch)


class Collector(Listener):
    """Burst + tensorstats collector with a configurable cadence ask."""

    def __init__(self, frequency=8):
        self.frequency = frequency
        self.losses = []
        self.records = []

    def iterations_done(self, sd, epoch, iterations, losses):
        self.losses.extend(float(v) for v in losses)

    def tensorstats_done(self, sd, epoch, records):
        self.records.extend(records)


# ---------------------------------------------------------------------------
# config

class TestConfig:
    def test_serde_roundtrip(self):
        cfg = TensorStatsConfig(every_n=7, families=("params", "grads"),
                                hist_bins=12, hist_min_exp=-8)
        back = TensorStatsConfig.from_json(cfg.to_json())
        assert back == cfg
        # families canonicalize to the fixed order regardless of input
        assert back.families == ("grads", "params")

    def test_rides_training_config_serde(self):
        sd = _mlp(tensorstats=TensorStatsConfig(every_n=3))
        tc2 = TrainingConfig.from_json(sd.training_config.to_json())
        assert tc2.tensorstats == sd.training_config.tensorstats
        assert TrainingConfig.from_json(
            _mlp().training_config.to_json()).tensorstats is None

    def test_true_means_defaults(self):
        sd = _mlp(tensorstats=True)
        assert sd.training_config.tensorstats == TensorStatsConfig()
        assert normalize(True) == TensorStatsConfig()
        assert normalize(None) is None

    def test_builder(self):
        tc = (TrainingConfig.builder().updater(Adam(1e-3))
              .tensorstats(TensorStatsConfig(every_n=2)).build())
        assert tc.tensorstats.every_n == 2

    def test_validation(self):
        with pytest.raises(ValueError):
            TensorStatsConfig(every_n=0)
        with pytest.raises(ValueError):
            TensorStatsConfig(families=("grads", "nope"))
        with pytest.raises(ValueError):
            TensorStatsConfig(families=())
        with pytest.raises(ValueError):
            TensorStatsConfig(hist_bins=0)
        with pytest.raises(TypeError):
            normalize("yes")

    def test_key_is_stable_identity(self):
        a = TensorStatsConfig(families=("params", "grads"))
        b = TensorStatsConfig(families=("grads", "params"))
        assert a.key() == b.key()
        assert a.key() != TensorStatsConfig(every_n=2).key()


# ---------------------------------------------------------------------------
# the traced summaries

class TestSummaries:
    def test_matches_numpy(self):
        rng = np.random.default_rng(3)
        x = rng.normal(0, 0.3, (9, 5)).astype(np.float32)
        x[0, 0] = 0.0
        cfg = TensorStatsConfig(hist_bins=24, hist_min_exp=-20)
        scalars, hist = jax.jit(
            lambda a: summarize_leaf(a, cfg))(x)
        scalars = np.asarray(scalars)
        got = dict(zip(SCALAR_FIELDS, scalars))
        assert got["l2"] == pytest.approx(np.linalg.norm(x), rel=1e-5)
        assert got["mean_abs"] == pytest.approx(np.abs(x).mean(), rel=1e-5)
        assert got["min"] == pytest.approx(x.min())
        assert got["max"] == pytest.approx(x.max())
        assert got["nonfinite"] == 0
        assert got["zeros"] == 1
        # histogram counts every finite nonzero entry exactly once
        hist = np.asarray(hist)
        assert hist.sum() == x.size - 1
        exps = np.floor(np.log2(np.abs(x[x != 0]))).astype(int)
        bins = np.clip(exps - cfg.hist_min_exp, 0, cfg.hist_bins - 1)
        expect = np.bincount(bins, minlength=cfg.hist_bins)
        np.testing.assert_array_equal(hist, expect)

    def test_nonfinite_counted_and_masked_from_moments(self):
        x = np.array([1.0, np.nan, np.inf, -2.0, 0.0], np.float32)
        cfg = TensorStatsConfig()
        scalars, hist = jax.jit(lambda a: summarize_leaf(a, cfg))(x)
        got = dict(zip(SCALAR_FIELDS, np.asarray(scalars)))
        assert got["nonfinite"] == 2
        assert got["zeros"] == 1
        # the exact norm accumulator propagates the poison — a NaN l2
        # IS the diagnostic for a poisoned layer
        assert np.isnan(got["l2"])
        # ... while the sampled moments mask nonfinites out
        assert got["min"] == -2.0 and got["max"] == 1.0
        assert got["mean_abs"] == pytest.approx(3.0 / 5, rel=1e-6)
        assert np.asarray(hist).sum() == 2          # 1.0 and -2.0

    def test_sample_cap_strided_subsample(self):
        # 1000 elements, cap 100 -> stride 10: sampled stats describe
        # x[::10]; l2 stays exact; a NaN at an UNSAMPLED index is still
        # detected through the norm accumulator (lower bound 1)
        x = np.linspace(0.1, 1.0, 1000).astype(np.float32)
        cfg = TensorStatsConfig(sample_cap=100)
        scalars, hist = jax.jit(lambda a: summarize_leaf(a, cfg))(x)
        got = dict(zip(SCALAR_FIELDS, np.asarray(scalars)))
        assert got["l2"] == pytest.approx(np.linalg.norm(x), rel=1e-5)
        assert got["mean_abs"] == pytest.approx(np.abs(x[::10]).mean(),
                                                rel=1e-5)
        assert np.asarray(hist).sum() == 100
        x[7] = np.nan                                # never sampled
        scalars, _ = jax.jit(lambda a: summarize_leaf(a, cfg))(x)
        got = dict(zip(SCALAR_FIELDS, np.asarray(scalars)))
        assert got["nonfinite"] == 1 and np.isnan(got["l2"])

    def test_sample_cap_zero_is_exact(self):
        rng = np.random.default_rng(5)
        x = rng.normal(size=(3000,)).astype(np.float32)
        cfg = TensorStatsConfig(sample_cap=0)
        scalars, hist = jax.jit(lambda a: summarize_leaf(a, cfg))(x)
        got = dict(zip(SCALAR_FIELDS, np.asarray(scalars)))
        assert got["mean_abs"] == pytest.approx(np.abs(x).mean(),
                                                rel=1e-5)
        assert np.asarray(hist).sum() == np.count_nonzero(x)

    def test_build_record_shape(self):
        cfg = TensorStatsConfig(hist_bins=4)
        stats = {"grads": (np.arange(12, dtype=np.float32).reshape(2, 6),
                           np.ones((2, 4), np.int32)),
                 "params": (np.ones((2, 6), np.float32),
                            np.zeros((2, 4), np.int32)),
                 "updates": (np.full((2, 6), 2.0, np.float32),
                             np.zeros((2, 4), np.int32))}
        rec = build_record(("a", "b"), stats, 40, 2, cfg)
        assert rec["type"] == "tensorstats" and rec["iter"] == 40
        ent = rec["layers"]["a"]
        for fam, pfx in FAMILY_PREFIX.items():
            assert f"{pfx}_l2" in ent and len(ent[f"{pfx}_hist"]) == 4
        assert ent["update_ratio"] == pytest.approx(2.0, rel=1e-6)
        assert isinstance(ent["grad_nonfinite"], int)
        json.dumps(rec)                              # JSONL-serializable


# ---------------------------------------------------------------------------
# fit integration

class TestFitIntegration:
    def test_fused_tier_publishes_at_cadence(self):
        col = Collector()
        sd = _mlp(tensorstats=TensorStatsConfig(every_n=4), fused_steps=4)
        sd.fit(_it(), epochs=2, listeners=[col])     # 8 steps/epoch
        assert [r["iter"] for r in col.records] == [0, 4, 8, 12]
        rec = col.records[-1]
        assert set(rec["layers"]) == {"w0", "b0", "w1"}
        ent = rec["layers"]["w0"]
        assert ent["grad_l2"] > 0 and ent["update_ratio"] > 0
        assert ent["grad_nonfinite"] == 0
        assert sum(ent["grad_hist"]) == 8 * 16       # every finite nonzero

    def test_per_step_tier_publishes(self):
        col = Collector()
        sd = _mlp(tensorstats=TensorStatsConfig(every_n=4), fused_steps=1)
        sd.fit(_it(), epochs=1, listeners=[col])
        assert [r["iter"] for r in col.records] == [0, 4]
        assert col.records[0]["layers"]["w1"]["param_l2"] > 0

    def test_listener_free_fit_skips_stats(self):
        # no listener rail -> the stats-free window dispatches; nothing
        # breaks, nothing is published
        sd = _mlp(tensorstats=TensorStatsConfig(every_n=2), fused_steps=4)
        h = sd.fit(_it(), epochs=1)
        assert np.isfinite(h.final_loss())

    def test_ragged_tail_windows_carry_stats(self):
        # 10 steps with K=4 -> windows of 4, 4, 2; the carry keeps the
        # LAST sampled step per window (one record per window at
        # every_n=1), and the pow2 tail window carries stats too
        col = Collector(frequency=1)
        sd = _mlp(tensorstats=TensorStatsConfig(every_n=1), fused_steps=4)
        sd.fit(_it(batch=8, n=80), epochs=1, listeners=[col])
        assert [r["iter"] for r in col.records] == [3, 7, 9]

    def test_accum_samples_on_apply_boundaries(self):
        # accum_steps=2, every_n=1: samples land where (it+1) % 2 == 0,
        # so the updates family always describes a real apply
        col = Collector(frequency=4)
        sd = _mlp(tensorstats=TensorStatsConfig(every_n=1), fused_steps=4,
                  accum_steps=2)
        sd.fit(_it(), epochs=1, listeners=[col])
        iters = [r["iter"] for r in col.records]
        # one record per window (last sample in the carry); every
        # sampled iteration is an apply boundary
        assert iters == [3, 7]
        assert all((it + 1) % 2 == 0 for it in iters)
        for r in col.records:
            assert r["layers"]["w0"]["update_l2"] > 0

    def test_bit_identical_with_sentinel_sharing_carry(self):
        """Satellite: tensorstats + sentinel share the scan carry; a
        clean fused run with BOTH on is bit-identical (params + losses)
        to both off."""
        on, off = Collector(), Collector()
        a = _mlp(tensorstats=TensorStatsConfig(every_n=2), fused_steps=4,
                 sentinel=True)
        a.fit(_it(), epochs=2, listeners=[on])
        b = _mlp(tensorstats=None, fused_steps=4, sentinel=False)
        b.fit(_it(), epochs=2, listeners=[off])
        assert on.losses == off.losses
        assert len(on.records) > 0 and len(off.records) == 0
        for n in a.trainable_params():
            np.testing.assert_array_equal(
                np.asarray(a.get_arr_for_var(n)),
                np.asarray(b.get_arr_for_var(n)), err_msg=n)

    def test_sharded_matches_unsharded_norms(self):
        """Satellite: tensorstats under a ShardingSpec mesh reports the
        same per-layer norms as the unsharded run."""
        cfg = TensorStatsConfig(every_n=2)
        sh, un = Collector(), Collector()
        a = _mlp(tensorstats=cfg, fused_steps=4,
                 sharding=ShardingSpec(axes={"data": -1}))
        a.fit(_it(batch=16), epochs=1, listeners=[sh])
        b = _mlp(tensorstats=cfg, fused_steps=4)
        b.fit(_it(batch=16), epochs=1, listeners=[un])
        assert [r["iter"] for r in sh.records] == \
            [r["iter"] for r in un.records]
        for ra, rb in zip(sh.records, un.records):
            for layer in ra["layers"]:
                for key in ("grad_l2", "update_l2", "param_l2",
                            "update_ratio"):
                    assert ra["layers"][layer][key] == pytest.approx(
                        rb["layers"][layer][key], rel=1e-4, abs=1e-7), \
                        (layer, key)

    def test_precompile_covers_stats_window_signature(self):
        """Satellite: precompile() with tensorstats configured builds
        the stats-enabled window signature — the monitored fit then
        reports 0 lazy window compiles."""
        sd = _mlp(tensorstats=TensorStatsConfig(every_n=2), fused_steps=4,
                  sentinel=True)
        info = sd.precompile(batch_size=8)
        assert info["compiled"] > 0
        col = Collector()
        sd.fit(_it(), epochs=1, listeners=[col])
        assert sd.last_fit_stats["window_compiles"] == 0
        assert len(col.records) > 0

    def test_nan_grads_counted_nonfinite(self):
        from deeplearning4j_tpu.faults import ChaosMonkey
        col = Collector(frequency=1)
        sd = _mlp(tensorstats=TensorStatsConfig(every_n=1), fused_steps=4)
        chaos = ChaosMonkey(seed=0)
        # inject at the window's LAST step — the one whose sample the
        # carry retains (every_n=1, K=4 -> records at iters 3, ...)
        with chaos.nan_gradients(sd, at_step=3):
            sd.fit(_it(batch=8, n=32), epochs=1, listeners=[col])
        rec = next(r for r in col.records if r["iter"] == 3)
        assert any(ent["grad_nonfinite"] > 0
                   for ent in rec["layers"].values())


# ---------------------------------------------------------------------------
# the listener rail: MonitorListener persistence + LayerHealthWatcher

class TestListenerRail:
    def test_monitor_listener_persists_and_folds(self):
        storage = StatsStorage()
        reg = MetricsRegistry()
        mon = MonitorListener(storage, registry=reg, frequency=4)
        sd = _mlp(tensorstats=TensorStatsConfig(every_n=4), fused_steps=4)
        sd.fit(_it(), epochs=1, listeners=[mon])
        recs = storage.of_type("tensorstats")
        assert [r["iter"] for r in recs] == [0, 4]
        text = reg.to_prometheus_text()
        assert 'dl4j_layer_grad_l2{layer="w0"}' in text
        assert 'dl4j_layer_update_ratio{layer="w1"}' in text
        assert "dl4j_layer_update_ratio_dist_bucket" in text

    def test_report_renders_layer_health_panel(self):
        storage = StatsStorage()
        mon = MonitorListener(storage, frequency=4)
        sd = _mlp(tensorstats=TensorStatsConfig(every_n=2), fused_steps=4)
        sd.fit(_it(), epochs=2, listeners=[mon])
        html = render_report(storage)
        assert "Layer health (device-side tensorstats)" in html
        assert "update:param (in-graph)" in html
        assert "gradient L2 norm per layer" in html
        # known type: must NOT appear in the forward-compat footer
        assert "unrendered record types: tensorstats" not in html

    def test_dead_layer_raises_after_patience(self):
        # lr=0: every update is exactly zero -> ratio 0 -> dead after
        # warmup + patience samples
        watcher = LayerHealthWatcher(patience=2, warmup=1)
        sd = _mlp(tensorstats=TensorStatsConfig(every_n=1), fused_steps=4,
                  updater=Sgd(0.0))
        with pytest.raises(TrainingDivergedError) as ei:
            sd.fit(_it(), epochs=2, listeners=[Collector(), watcher])
        assert ei.value.cause == "dead_layer"
        assert watcher.events and \
            watcher.events[-1]["cause"] == "dead_layer"

    def test_exploding_layer_raises(self):
        storage = StatsStorage()
        watcher = LayerHealthWatcher(explode_ratio=0.5, warmup=0,
                                     storage=storage)
        sd = _mlp(tensorstats=TensorStatsConfig(every_n=1), fused_steps=4,
                  updater=Sgd(500.0))
        with pytest.raises(TrainingDivergedError) as ei:
            sd.fit(_it(), epochs=1, listeners=[Collector(), watcher])
        assert ei.value.cause == "exploding_layer"
        evs = [r for r in storage.of_type("faults")
               if r.get("event") == "layer_health"]
        assert evs and evs[0]["cause"] == "exploding_layer"

    def test_watcher_reset_forgets_streaks(self):
        watcher = LayerHealthWatcher(patience=3, warmup=0)
        # params row: l2=1, clean counts (slots 4/5 = nonfinite/zeros
        # must be 0 or the poisoned-layer backstop fires first)
        prow = np.array([[1, 1, -1, 1, 0, 0]], np.float32)
        rec = build_record(
            ("w",), {"updates": (np.zeros((1, 6), np.float32),
                                 np.zeros((1, 4), np.int32)),
                     "params": (prow, np.zeros((1, 4), np.int32))},
            0, 0, TensorStatsConfig(hist_bins=4))
        watcher.tensorstats_done(None, 0, [rec, rec])    # streak = 2
        watcher.reset()
        watcher.tensorstats_done(None, 0, [rec, rec])    # fresh streak
        with pytest.raises(TrainingDivergedError):
            watcher.tensorstats_done(None, 0, [rec])

    def test_healthy_run_passes_watcher(self):
        watcher = LayerHealthWatcher(warmup=0)
        sd = _mlp(tensorstats=TensorStatsConfig(every_n=2), fused_steps=4)
        h = sd.fit(_it(), epochs=2, listeners=[Collector(), watcher])
        assert np.isfinite(h.final_loss())
        assert watcher.events == []


class TestReviewRegressions:
    def test_false_disables_like_sentinel(self):
        assert normalize(False) is None
        sd = _mlp(tensorstats=False)
        assert sd.training_config.tensorstats is None
        tc = TrainingConfig.from_json(
            {**sd.training_config.to_json(), "tensorstats": False})
        assert tc.tensorstats is None

    def test_report_panel_bounded_on_long_runs(self):
        # /report renders live per request: 5000 records must
        # downsample to a bounded column count, newest record kept
        storage = StatsStorage()
        cfg = TensorStatsConfig(hist_bins=4)
        base = {"updates": (np.ones((1, 6), np.float32) * 0.1,
                            np.zeros((1, 4), np.int32)),
                "params": (np.ones((1, 6), np.float32),
                           np.zeros((1, 4), np.int32)),
                "grads": (np.ones((1, 6), np.float32),
                          np.ones((1, 4), np.int32))}
        for i in range(5000):
            storage.put(build_record(("w",), base, i, 0, cfg))
        # the newest record (the one the health table reads) carries a
        # distinguishing grad L2 so its survival is observable
        marked = {**base, "grads": (np.full((1, 6), 7.125, np.float32),
                                    np.ones((1, 4), np.int32))}
        storage.put(build_record(("w",), marked, 5000, 0, cfg))
        html = render_report(storage)
        assert html.count('title>w[') <= 200      # heatmap cells bounded
        assert "5001 in-graph samples (" in html  # true total reported
        assert "7.125" in html                    # newest record survives

    def test_poisoned_layer_flagged_and_record_json_strict(self):
        """Review round-3 regressions: (a) a poisoned layer (NaN
        norms -> ratio None) must be FLAGGED by LayerHealthWatcher,
        not sail past the threshold comparisons; (b) the record
        serializes as strict RFC JSON (no NaN/Infinity tokens) — the
        non-finite floats become None, with the *_nonfinite counts
        carrying the signal."""
        cfg = TensorStatsConfig(hist_bins=4)
        # moments poisoned, counts finite (as in-graph: the count slots
        # are sums of bools and stay finite even for poisoned tensors)
        nanrow = np.full((1, 6), np.nan, np.float32)
        nanrow[0, 4] = 3.0                       # nonfinite count
        nanrow[0, 5] = 0.0                       # zeros count
        prow = np.array([[1, 1, -1, 1, 0, 0]], np.float32)
        stats = {"updates": (nanrow, np.zeros((1, 4), np.int32)),
                 "params": (prow, np.zeros((1, 4), np.int32)),
                 "grads": (nanrow, np.zeros((1, 4), np.int32))}
        rec = build_record(("w",), stats, 12, 0, cfg)
        ent = rec["layers"]["w"]
        assert ent["grad_l2"] is None and ent["update_ratio"] is None
        assert ent["grad_nonfinite"] == 3
        json.loads(json.dumps(rec, allow_nan=False))     # strict JSON
        # registry fold and report render tolerate the Nones
        reg = MetricsRegistry()
        reg.fold_tensorstats(rec)
        assert reg.get("layer_grad_l2", layer="w") is None
        assert reg.get("layer_param_l2", layer="w") == 1.0
        st = StatsStorage()
        st.put(rec)
        assert "Layer health" in render_report(st)
        # the watcher flags it immediately, warmup notwithstanding
        watcher = LayerHealthWatcher(warmup=100, storage=st)
        with pytest.raises(TrainingDivergedError) as ei:
            watcher.tensorstats_done(None, 0, [rec])
        assert ei.value.cause == "poisoned_layer"
        ev = [r for r in st.of_type("faults")
              if r.get("event") == "layer_health"][0]
        assert ev["ratio"] is None
        json.loads(json.dumps(ev, allow_nan=False))
