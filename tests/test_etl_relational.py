"""Join / Reducer / sequence ETL tests (reference: datavec TestJoin,
TestReduce, TestSequenceTransforms)."""
import numpy as np
import pytest

from deeplearning4j_tpu.etl import (
    FULL_OUTER, INNER, LEFT_OUTER, RIGHT_OUTER, Join, Reducer, Schema,
    columnar, convert_from_sequence, convert_to_sequence, offset_column,
    reduce_sequence_by_window, sequences_to_arrays, split_sequence_on_gap,
    trim_sequence)


def _customers():
    s = (Schema.builder().add_column_integer("cid")
         .add_column_string("name").build())
    cols = columnar(s, [[0, "alice"], [1, "bob"], [2, "carol"]])
    return s, cols


def _orders():
    s = (Schema.builder().add_column_integer("cid")
         .add_column_float("amount").build())
    cols = columnar(s, [[0, 10.0], [0, 20.0], [2, 5.0], [3, 7.0]])
    return s, cols


# ---- joins ----------------------------------------------------------------

def test_inner_join():
    ls, lc = _customers()
    rs, rc = _orders()
    j = Join(INNER, ["cid"], ls, rs)
    out = j.execute(lc, rc)
    assert out["cid"].tolist() == [0, 0, 2]
    assert out["name"].tolist() == ["alice", "alice", "carol"]
    assert out["amount"].tolist() == [10.0, 20.0, 5.0]
    assert j.output_schema().names() == ["cid", "name", "amount"]


def test_left_outer_join_fills_nan():
    ls, lc = _customers()
    rs, rc = _orders()
    out = Join(LEFT_OUTER, ["cid"], ls, rs).execute(lc, rc)
    # bob (cid 1) has no orders -> NaN amount
    assert out["cid"].tolist() == [0, 0, 1, 2]
    assert np.isnan(out["amount"][2])


def test_right_outer_join_keeps_unmatched_right():
    ls, lc = _customers()
    rs, rc = _orders()
    out = Join(RIGHT_OUTER, ["cid"], ls, rs).execute(lc, rc)
    # order cid=3 has no customer -> empty name
    assert out["cid"].tolist() == [0, 0, 2, 3]
    assert out["name"].tolist() == ["alice", "alice", "carol", ""]


def test_full_outer_join():
    ls, lc = _customers()
    rs, rc = _orders()
    out = Join(FULL_OUTER, ["cid"], ls, rs).execute(lc, rc)
    assert sorted(out["cid"].tolist()) == [0, 0, 1, 2, 3]


def test_join_rejects_overlapping_value_columns():
    s1 = (Schema.builder().add_column_integer("k")
          .add_column_float("x").build())
    with pytest.raises(ValueError):
        Join(INNER, ["k"], s1, s1)


# ---- reducer --------------------------------------------------------------

def _sales():
    s = (Schema.builder().add_column_string("region")
         .add_column_float("amount").add_column_integer("units").build())
    cols = columnar(s, [["w", 1.0, 2], ["e", 3.0, 4], ["w", 5.0, 6],
                        ["e", 7.0, 8], ["w", 9.0, 10]])
    return s, cols


def test_reducer_sum_mean_count():
    s, cols = _sales()
    r = (Reducer.builder(s).key_columns("region")
         .sum_columns("amount").mean_columns("units").build())
    out = r.execute(cols)
    assert out["region"].tolist() == ["w", "e"]   # first-appearance order
    assert out["sum(amount)"].tolist() == [15.0, 10.0]
    np.testing.assert_allclose(out["mean(units)"], [6.0, 6.0])
    names = r.output_schema().names()
    assert names == ["region", "sum(amount)", "mean(units)"]


def test_reducer_min_max_range_stdev_first_last():
    s, cols = _sales()
    r = Reducer(s, ["region"], {"amount": "stdev", "units": "range"})
    out = r.execute(cols)
    np.testing.assert_allclose(out["stdev(amount)"],
                               [np.std([1, 5, 9], ddof=1),
                                np.std([3, 7], ddof=1)], rtol=1e-6)
    assert out["range(units)"].tolist() == [8, 4]
    r2 = Reducer(s, ["region"], {"amount": "last", "units": "count"})
    out2 = r2.execute(cols)
    assert out2["last(amount)"].tolist() == [9.0, 7.0]
    assert out2["count(units)"].tolist() == [3, 2]


def test_reducer_count_unique_and_validation():
    s, cols = _sales()
    out = Reducer(s, ["region"], {"amount": "count_unique"}).execute(cols)
    assert out["count_unique(amount)"].tolist() == [3, 2]
    with pytest.raises(ValueError):
        Reducer(s, ["region"], {"region": "sum"})
    with pytest.raises(ValueError):
        Reducer(s, ["region"], {"amount": "bogus"})


def test_reducer_multi_key():
    s = (Schema.builder().add_column_string("a")
         .add_column_integer("b").add_column_float("v").build())
    cols = columnar(s, [["x", 0, 1.0], ["x", 1, 2.0], ["x", 0, 3.0]])
    out = Reducer(s, ["a", "b"], {"v": "sum"}).execute(cols)
    assert out["b"].tolist() == [0, 1]
    assert out["sum(v)"].tolist() == [4.0, 2.0]


# ---- sequences ------------------------------------------------------------

def _series():
    s = (Schema.builder().add_column_string("id").add_column_time("t")
         .add_column_float("v").build())
    rows = [["a", 3, 30.0], ["b", 1, 100.0], ["a", 1, 10.0],
            ["a", 2, 20.0], ["b", 2, 200.0]]
    return s, columnar(s, rows)


def test_convert_to_sequence_groups_and_sorts():
    s, cols = _series()
    keys, seqs = convert_to_sequence(s, cols, "id", time_column="t")
    assert keys == ["a", "b"]
    assert seqs[0]["v"].tolist() == [10.0, 20.0, 30.0]
    assert seqs[1]["t"].tolist() == [1, 2]
    flat = convert_from_sequence(seqs)
    assert flat["v"].tolist() == [10.0, 20.0, 30.0, 100.0, 200.0]


def test_offset_column_lag_and_trim():
    s, cols = _series()
    _, seqs = convert_to_sequence(s, cols, "id", time_column="t")
    lag = offset_column(seqs, "v", 1)
    # sequence a: rows for t=2,3 remain; lagged value = previous v
    assert lag[0]["v"].tolist() == [20.0, 30.0]
    assert lag[0]["v_offset(1)"].tolist() == [10.0, 20.0]
    # sequence b had 2 rows -> 1 remains
    assert lag[1]["v_offset(1)"].tolist() == [100.0]


def test_offset_lead_and_no_trim():
    s, cols = _series()
    _, seqs = convert_to_sequence(s, cols, "id", time_column="t")
    lead = offset_column(seqs, "v", -1, new_name="next_v", trim=False)
    assert lead[0]["next_v"].tolist() == [20.0, 30.0, 30.0]  # edge-filled


def test_trim_and_split():
    s, cols = _series()
    _, seqs = convert_to_sequence(s, cols, "id", time_column="t")
    trimmed = trim_sequence(seqs, 1)
    assert trimmed[0]["t"].tolist() == [2, 3]
    assert trimmed[1]["t"].tolist() == [2]
    big_gap = [{"t": np.array([1, 2, 10, 11]),
                "v": np.array([1.0, 2.0, 3.0, 4.0])}]
    parts = split_sequence_on_gap(big_gap, "t", max_gap=5)
    assert len(parts) == 2
    assert parts[0]["v"].tolist() == [1.0, 2.0]
    assert parts[1]["v"].tolist() == [3.0, 4.0]


def test_window_reduce():
    seqs = [{"t": np.arange(4), "v": np.array([1.0, 2.0, 3.0, 4.0])}]
    out = reduce_sequence_by_window(seqs, "v", window=2, op="mean")
    np.testing.assert_allclose(out[0]["mean(v,w=2)"], [1.5, 3.5])
    assert out[0]["t"].tolist() == [1, 3]   # last step of each window


def test_sequences_to_arrays_padding_and_mask():
    s, cols = _series()
    _, seqs = convert_to_sequence(s, cols, "id", time_column="t")
    feats, mask, labels = sequences_to_arrays(seqs, ["v"], label_column="t")
    assert feats.shape == (2, 3, 1) and mask.shape == (2, 3)
    assert mask.tolist() == [[1, 1, 1], [1, 1, 0]]
    assert feats[1, 2, 0] == 0.0                      # padded
    assert labels[0].tolist() == [1.0, 2.0, 3.0]


def test_sequence_pipeline_feeds_training_shapes():
    """End-to-end: raw rows -> sequences -> lag feature -> padded arrays."""
    rng = np.random.default_rng(0)
    s = (Schema.builder().add_column_integer("sensor")
         .add_column_time("t").add_column_float("x").build())
    rows = []
    for sid in range(3):
        for t in range(5 + sid):
            rows.append([sid, t, float(rng.normal())])
    cols = columnar(s, rows)
    _, seqs = convert_to_sequence(s, cols, "sensor", time_column="t")
    seqs = offset_column(seqs, "x", 1, new_name="x_prev")
    feats, mask, _ = sequences_to_arrays(seqs, ["x", "x_prev"])
    assert feats.shape == (3, 6, 2)
    assert mask.sum() == (4 + 5 + 6)


def test_outer_join_key_width_and_schema_promotion():
    """Regression: right-side key strings wider than left must not be
    truncated; nullable int columns are FLOAT in schema AND data."""
    ls = (Schema.builder().add_column_string("k")
          .add_column_integer("lv").build())
    rs = (Schema.builder().add_column_string("k")
          .add_column_integer("rv").build())
    lc = {"k": np.array(["x", "y"]), "lv": np.array([1, 2])}
    rc = {"k": np.array(["x", "longkey"]), "rv": np.array([7, 8])}
    j = Join(FULL_OUTER, ["k"], ls, rs)
    out = j.execute(lc, rc)
    assert "longkey" in out["k"].tolist()
    schema = j.output_schema()
    from deeplearning4j_tpu.etl import FLOAT
    assert schema.column("lv").ctype == FLOAT
    assert schema.column("rv").ctype == FLOAT
    assert out["lv"].dtype.kind == "f" and out["rv"].dtype.kind == "f"
    # inner joins keep ints in both schema and data
    ji = Join(INNER, ["k"], ls, rs)
    assert ji.output_schema().column("rv").ctype == "integer"
    assert ji.execute(lc, rc)["rv"].dtype.kind == "i"


def test_split_on_float_gap():
    """Regression: float time gaps must not be truncated before diffing."""
    seqs = [{"t": np.array([1.1, 2.9]), "v": np.array([1.0, 2.0])}]
    parts = split_sequence_on_gap(seqs, "t", max_gap=1)
    assert len(parts) == 2


def test_quality_analysis_counts():
    """(reference: datavec AnalyzeLocal.analyzeQuality)"""
    from deeplearning4j_tpu.etl import (CSVRecordReader, analyze_quality)
    s = (Schema.builder().add_column_integer("a").add_column_float("b")
         .add_column_categorical("c", "x", "y").build())
    text = "1,2.0,x\n,nan,z\nbad,inf,y\n2,,x\n"
    qa = analyze_quality(s, CSVRecordReader(text=text))
    a, b, c = qa.column("a"), qa.column("b"), qa.column("c")
    assert (a.count_total, a.count_valid, a.count_invalid,
            a.count_missing) == (4, 2, 1, 1)
    assert (b.count_valid, b.count_nan, b.count_infinite,
            b.count_missing) == (1, 1, 1, 1)
    assert (c.count_valid, c.count_invalid) == (3, 1)
    assert "data quality" in qa.report()
