"""ComputationGraph tests (reference: dl4jcore/nn/graph tests — multi-input
DAGs, vertex ops, serde round-trip)."""
import numpy as np
import pytest

from deeplearning4j_tpu.learning.updaters import Adam, Sgd
from deeplearning4j_tpu.nn import (
    ComputationGraph, ComputationGraphConfiguration, DenseLayer,
    ElementWiseVertex, InputType, L2NormalizeVertex, MergeVertex,
    NeuralNetConfiguration, OutputLayer, ScaleVertex, ShiftVertex,
    SubsetVertex)


def _two_input_graph():
    return (NeuralNetConfiguration.builder()
            .seed(7)
            .updater(Adam(learning_rate=0.05))
            .graph_builder()
            .add_inputs("inA", "inB")
            .set_input_types(InputType.feed_forward(3),
                             InputType.feed_forward(2))
            .add_layer("denseA", DenseLayer(n_out=8, activation="tanh"), "inA")
            .add_layer("denseB", DenseLayer(n_out=8, activation="tanh"), "inB")
            .add_vertex("merge", MergeVertex(), "denseA", "denseB")
            .add_layer("out", OutputLayer(n_out=2), "merge")
            .set_outputs("out")
            .build())


def test_graph_builds_and_outputs():
    net = ComputationGraph(_two_input_graph()).init()
    a = np.random.default_rng(0).normal(size=(4, 3)).astype(np.float32)
    b = np.random.default_rng(1).normal(size=(4, 2)).astype(np.float32)
    outs = net.output(a, b)
    assert len(outs) == 1
    assert outs[0].to_numpy().shape == (4, 2)
    np.testing.assert_allclose(outs[0].to_numpy().sum(-1), np.ones(4),
                               rtol=1e-5)


def test_graph_trains_two_inputs():
    rng = np.random.default_rng(3)
    A = rng.normal(size=(64, 3)).astype(np.float32)
    B = rng.normal(size=(64, 2)).astype(np.float32)
    y = ((A[:, 0] + B[:, 0]) > 0).astype(int)
    Y = np.eye(2, dtype=np.float32)[y]

    class It:
        def reset(self): ...
        def __iter__(self):
            for i in range(0, 64, 32):
                yield [A[i:i+32], B[i:i+32]], [Y[i:i+32]]

    net = ComputationGraph(_two_input_graph()).init()
    h = net.fit(It(), epochs=60)
    assert h.final_loss() < 0.2
    preds = net.output(A, B)[0].to_numpy().argmax(-1)
    assert (preds == y).mean() > 0.9


def test_elementwise_vertex_residual_block():
    conf = (NeuralNetConfiguration.builder()
            .seed(1).updater(Sgd(learning_rate=0.1))
            .graph_builder()
            .add_inputs("in")
            .set_input_types(InputType.feed_forward(8))
            .add_layer("d1", DenseLayer(n_out=8, activation="relu"), "in")
            .add_vertex("residual", ElementWiseVertex(op="Add"), "d1", "in")
            .add_layer("out", OutputLayer(n_out=2), "residual")
            .set_outputs("out").build())
    net = ComputationGraph(conf).init()
    x = np.random.default_rng(0).normal(size=(4, 8)).astype(np.float32)
    assert net.output(x)[0].to_numpy().shape == (4, 2)


@pytest.mark.parametrize("op,fn", [
    ("Add", lambda a, b: a + b),
    ("Subtract", lambda a, b: a - b),
    ("Product", lambda a, b: a * b),
    ("Average", lambda a, b: (a + b) / 2),
    ("Max", np.maximum),
])
def test_elementwise_vertex_math(op, fn):
    conf = (NeuralNetConfiguration.builder().seed(1)
            .graph_builder()
            .add_inputs("a", "b")
            .set_input_types(InputType.feed_forward(3),
                             InputType.feed_forward(3))
            .add_vertex("ew", ElementWiseVertex(op=op), "a", "b")
            .set_outputs("ew").build())
    net = ComputationGraph(conf).init()
    a = np.array([[1.0, 2.0, 3.0]], np.float32)
    b = np.array([[4.0, 0.5, -1.0]], np.float32)
    out = net.output(a, b)[0].to_numpy()
    np.testing.assert_allclose(out, fn(a, b), rtol=1e-6)


def test_scale_shift_subset_l2_vertices():
    conf = (NeuralNetConfiguration.builder().seed(1)
            .graph_builder()
            .add_inputs("in")
            .set_input_types(InputType.feed_forward(6))
            .add_vertex("sub", SubsetVertex(from_idx=1, to_idx=3), "in")
            .add_vertex("scaled", ScaleVertex(scale_factor=2.0), "sub")
            .add_vertex("shifted", ShiftVertex(shift_factor=1.0), "scaled")
            .add_vertex("l2", L2NormalizeVertex(), "shifted")
            .set_outputs("l2").build())
    net = ComputationGraph(conf).init()
    x = np.arange(6, dtype=np.float32)[None, :]
    out = net.output(x)[0].to_numpy()
    expected = x[:, 1:4] * 2 + 1
    expected = expected / np.linalg.norm(expected, axis=-1, keepdims=True)
    np.testing.assert_allclose(out, expected, rtol=1e-5)


def test_multi_output_graph():
    conf = (NeuralNetConfiguration.builder()
            .seed(2).updater(Adam(learning_rate=0.05))
            .graph_builder()
            .add_inputs("in")
            .set_input_types(InputType.feed_forward(4))
            .add_layer("shared", DenseLayer(n_out=16, activation="tanh"), "in")
            .add_layer("out1", OutputLayer(n_out=2), "shared")
            .add_layer("out2", OutputLayer(n_out=3), "shared")
            .set_outputs("out1", "out2").build())
    net = ComputationGraph(conf).init()
    rng = np.random.default_rng(5)
    X = rng.normal(size=(64, 4)).astype(np.float32)
    Y1 = np.eye(2, dtype=np.float32)[(X[:, 0] > 0).astype(int)]
    Y2 = np.eye(3, dtype=np.float32)[np.clip(X[:, 1].astype(int) + 1, 0, 2)]

    class It:
        def reset(self): ...
        def __iter__(self):
            yield [X], [Y1, Y2]

    h = net.fit(It(), epochs=50)
    assert np.isfinite(h.final_loss())
    o1, o2 = net.output(X)
    assert o1.to_numpy().shape == (64, 2)
    assert o2.to_numpy().shape == (64, 3)
    acc1 = (o1.to_numpy().argmax(-1) == Y1.argmax(-1)).mean()
    assert acc1 > 0.9


def test_graph_config_json_round_trip():
    conf = _two_input_graph()
    s = conf.to_json()
    conf2 = ComputationGraphConfiguration.from_json(s)
    assert conf2.to_json() == s
    assert [n.name for n in conf2.nodes] == ["denseA", "denseB", "merge", "out"]
    net = ComputationGraph(conf2).init()
    assert net.num_params() > 0


def test_graph_serde_round_trip(tmp_path):
    net = ComputationGraph(_two_input_graph()).init()
    a = np.random.default_rng(0).normal(size=(4, 3)).astype(np.float32)
    b = np.random.default_rng(1).normal(size=(4, 2)).astype(np.float32)
    before = net.output(a, b)[0].to_numpy()
    path = tmp_path / "graph.zip"
    net.save(path)
    net2 = ComputationGraph.load(path)
    np.testing.assert_allclose(net2.output(a, b)[0].to_numpy(), before,
                               rtol=1e-6)


def test_graph_rejects_unknown_input():
    with pytest.raises(ValueError, match="unknown"):
        (NeuralNetConfiguration.builder().graph_builder()
         .add_inputs("in")
         .set_input_types(InputType.feed_forward(2))
         .add_layer("d", DenseLayer(n_out=2), "missing")
         .set_outputs("d").build())


def test_graph_cnn_to_dense_preprocessor():
    from deeplearning4j_tpu.nn import ConvolutionLayer
    conf = (NeuralNetConfiguration.builder().seed(3)
            .updater(Sgd(learning_rate=0.1))
            .graph_builder()
            .add_inputs("img")
            .set_input_types(InputType.convolutional(8, 8, 1))
            .add_layer("conv", ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                                activation="relu"), "img")
            .add_layer("dense", DenseLayer(n_out=8), "conv")
            .add_layer("out", OutputLayer(n_out=2), "dense")
            .set_outputs("out").build())
    net = ComputationGraph(conf).init()
    x = np.zeros((2, 1, 8, 8), np.float32)
    assert net.output(x)[0].to_numpy().shape == (2, 2)


# ---- regression tests for review findings ----

def test_passthrough_node_does_not_corrupt_graph():
    from deeplearning4j_tpu.nn import DropoutLayer
    conf = (NeuralNetConfiguration.builder().seed(1)
            .updater(Sgd(learning_rate=0.1))
            .graph_builder()
            .add_inputs("in")
            .set_input_types(InputType.feed_forward(4))
            .add_layer("drop", DropoutLayer(dropout=0.5), "in")
            .add_layer("out", OutputLayer(n_out=2), "drop")
            .set_outputs("out").build())
    net = ComputationGraph(conf).init()
    x = np.zeros((2, 4), np.float32)
    # infer graph: dropout is identity — the input var must not be renamed
    assert net.output(x)[0].to_numpy().shape == (2, 2)
    net.fit(x, np.eye(2, dtype=np.float32)[[0, 1]], epochs=1, batch_size=2)


def test_layer_with_multiple_inputs_rejected():
    with pytest.raises(ValueError, match="MergeVertex"):
        (NeuralNetConfiguration.builder().graph_builder()
         .add_inputs("a", "b")
         .set_input_types(InputType.feed_forward(2), InputType.feed_forward(2))
         .add_layer("d", DenseLayer(n_out=2), "a", "b")
         .set_outputs("d").build())


def test_duplicate_node_name_rejected():
    with pytest.raises(ValueError, match="duplicate"):
        (NeuralNetConfiguration.builder().graph_builder()
         .add_inputs("in")
         .set_input_types(InputType.feed_forward(2))
         .add_layer("d", DenseLayer(n_out=2), "in")
         .add_layer("d", DenseLayer(n_out=2), "in")
         .set_outputs("d").build())


def test_label_mapping_follows_set_outputs_order():
    # loss heads declared in reverse of set_outputs order
    conf = (NeuralNetConfiguration.builder().seed(2)
            .updater(Sgd(learning_rate=0.1))
            .graph_builder()
            .add_inputs("in")
            .set_input_types(InputType.feed_forward(4))
            .add_layer("shared", DenseLayer(n_out=8), "in")
            .add_layer("out2", OutputLayer(n_out=3), "shared")
            .add_layer("out1", OutputLayer(n_out=2), "shared")
            .set_outputs("out1", "out2").build())
    net = ComputationGraph(conf).init()
    assert net._label_names == ["labels_out1", "labels_out2"]
    # fit with labels in set_outputs order: (B,2) then (B,3)
    X = np.zeros((4, 4), np.float32)
    Y1 = np.eye(2, dtype=np.float32)[[0, 1, 0, 1]]
    Y2 = np.eye(3, dtype=np.float32)[[0, 1, 2, 0]]

    class It:
        def reset(self): ...
        def __iter__(self):
            yield [X], [Y1, Y2]

    h = net.fit(It(), epochs=1)
    assert np.isfinite(h.final_loss())


def test_subset_vertex_on_rnn_slices_features():
    conf = (NeuralNetConfiguration.builder().seed(1)
            .graph_builder()
            .add_inputs("seq")
            .set_input_types(InputType.recurrent(5, 6))
            .add_vertex("sub", SubsetVertex(from_idx=1, to_idx=2), "seq")
            .set_outputs("sub").build())
    net = ComputationGraph(conf).init()
    x = np.random.default_rng(0).normal(size=(2, 6, 5)).astype(np.float32)
    out = net.output(x)[0].to_numpy()
    assert out.shape == (2, 6, 2)
    np.testing.assert_allclose(out, x[:, :, 1:3], rtol=1e-6)


def test_graph_save_load_preserves_iteration_count(tmp_path):
    conf = (NeuralNetConfiguration.builder()
            .seed(3).updater(Adam(learning_rate=0.01))
            .graph_builder()
            .add_inputs("in")
            .set_input_types(InputType.feed_forward(4))
            .add_layer("d", DenseLayer(n_out=8, activation="relu"), "in")
            .add_layer("out", OutputLayer(n_out=2), "d")
            .set_outputs("out").build())
    net = ComputationGraph(conf).init()
    X = np.random.default_rng(0).normal(size=(16, 4)).astype(np.float32)
    Y = np.eye(2, dtype=np.float32)[np.random.default_rng(1).integers(0, 2, 16)]
    net.fit([(X, Y)], epochs=3)
    it = net._sd_train.training_config.iteration_count
    assert it > 0
    p = tmp_path / "g.zip"
    net.save(p)
    net2 = ComputationGraph.load(p)
    assert net2._sd_train.training_config.iteration_count == it


def test_l2_normalize_vertex_cnn_all_nonbatch_dims():
    conf = (NeuralNetConfiguration.builder().seed(1)
            .graph_builder()
            .add_inputs("in")
            .set_input_types(InputType.convolutional(3, 3, 2))
            .add_vertex("l2", L2NormalizeVertex(), "in")
            .set_outputs("l2").build())
    net = ComputationGraph(conf).init()
    x = np.random.default_rng(5).normal(size=(2, 2, 3, 3)).astype(np.float32)
    out = net.output(x)[0].to_numpy()
    norm = np.sqrt((x ** 2).sum(axis=(1, 2, 3), keepdims=True))
    np.testing.assert_allclose(out, x / norm, rtol=1e-5)


def test_dot_product_vertex_ff_and_rnn():
    """DotProductVertex: ff feature-axis dot -> (B,1); rnn per-timestep
    dot -> (B,T,1); normalize gives cosine similarity."""
    import numpy as np
    from deeplearning4j_tpu.learning.updaters import Sgd
    from deeplearning4j_tpu.nn import (
        ComputationGraph, DenseLayer, DotProductVertex, InputType,
        NeuralNetConfiguration, OutputLayer)
    g = (NeuralNetConfiguration.builder().seed(0).updater(Sgd(0.1))
         .graph_builder().add_inputs("a", "b")
         .set_input_types(InputType.feed_forward(6),
                          InputType.feed_forward(6)))
    g.add_vertex("cos", DotProductVertex(normalize=True), "a", "b")
    g.add_layer("out", OutputLayer(n_out=2, loss_function="MCXENT"), "cos")
    net = ComputationGraph(g.set_outputs("out").build()).init()
    rng = np.random.default_rng(0)
    xa = rng.normal(size=(4, 6)).astype(np.float32)
    xb = rng.normal(size=(4, 6)).astype(np.float32)
    ff = net.feed_forward(xa, xb)
    cos = np.asarray(ff["cos"].data)
    want = (np.sum(xa * xb, 1)
            / (np.linalg.norm(xa, axis=1) * np.linalg.norm(xb, axis=1)))
    np.testing.assert_allclose(cos.ravel(), want, atol=1e-5)
    # rnn kind: per-timestep scalar sequence
    from deeplearning4j_tpu.nn import GraphVertex
    v = DotProductVertex()
    t = InputType.recurrent(5, 7)
    ot = v.output_type([t, t])
    assert ot.kind == "rnn" and ot.dims == (1, 7)


def test_graph_summary():
    """(reference: ComputationGraph.summary())"""
    from deeplearning4j_tpu.learning.updaters import Sgd
    from deeplearning4j_tpu.nn import (
        ComputationGraph, DenseLayer, ElementWiseVertex, InputType,
        NeuralNetConfiguration, OutputLayer)
    g = (NeuralNetConfiguration.builder().seed(0).updater(Sgd(0.1))
         .graph_builder().add_inputs("x")
         .set_input_types(InputType.feed_forward(4)))
    g.add_layer("d1", DenseLayer(n_out=8), "x")
    g.add_layer("d2", DenseLayer(n_out=8), "x")
    g.add_vertex("add", ElementWiseVertex(op="Add"), "d1", "d2")
    g.add_layer("out", OutputLayer(n_out=2, loss_function="MCXENT"), "add")
    net = ComputationGraph(g.set_outputs("out").build()).init()
    s = net.summary()
    assert "ComputationGraph" in s and "ElementWiseVertex" in s
    assert "<- d1, d2" in s and str(net.num_params()) in s
