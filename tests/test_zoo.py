"""Zoo model tests (reference test model: eclipse/deeplearning4j/zoo —
instantiation + forward-shape + brief training; heavyweight configs are
exercised at reduced input sizes)."""
import numpy as np
import pytest

from deeplearning4j_tpu.zoo import (
    AlexNet, LeNet, ResNet50, SimpleCNN, TextGenLSTM, TransformerEncoder,
    VGG16)

rng = np.random.default_rng(7)


def test_lenet_builds_and_trains():
    net = LeNet(height=28, width=28, channels=1, num_classes=10).build()
    x = rng.normal(size=(8, 1, 28, 28)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 8)]
    out = net.output(x).to_numpy()
    assert out.shape == (8, 10)
    np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-4)
    h = net.fit([(x, y)], epochs=2)
    assert np.isfinite(h.final_loss())


def test_simple_cnn_builds():
    net = SimpleCNN(height=48, width=48, channels=3, num_classes=5).build()
    x = rng.normal(size=(2, 3, 48, 48)).astype(np.float32)
    assert net.output(x).to_numpy().shape == (2, 5)


def test_alexnet_shapes_small():
    # reduced spatial size still exercises every layer incl. LRN
    net = AlexNet(height=67, width=67, channels=3, num_classes=10).build()
    x = rng.normal(size=(2, 3, 67, 67)).astype(np.float32)
    assert net.output(x).to_numpy().shape == (2, 10)


def test_vgg16_conf_structure():
    conf = VGG16(height=32, width=32, channels=3, num_classes=10).conf()
    from deeplearning4j_tpu.nn import ConvolutionLayer
    convs = [l for l in conf.layers if isinstance(l, ConvolutionLayer)]
    assert len(convs) == 13  # VGG16 = 13 conv + 3 dense


def test_resnet50_parameter_count_imagenet():
    # reference ResNet50 @1000 classes ≈ 25.6M params
    conf = ResNet50(height=224, width=224, channels=3,
                    num_classes=1000).conf()
    from deeplearning4j_tpu.nn import ComputationGraph
    net = ComputationGraph(conf).init()
    n = sum(int(np.prod(a.shape))
            for a in net._sd_train.trainable_params().values())
    assert 25_000_000 < n < 26_200_000, n


def test_resnet50_small_forward_and_train():
    net = ResNet50(height=32, width=32, channels=3, num_classes=4).build()
    x = rng.normal(size=(2, 3, 32, 32)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 2)]
    out = net.output(x)[0].to_numpy()
    assert out.shape == (2, 4)
    h = net.fit([(x, y)], epochs=1)
    assert np.isfinite(h.final_loss())


def test_textgen_lstm():
    net = TextGenLSTM(vocab_size=12, timesteps=6, units=8).build()
    x = rng.normal(size=(2, 6, 12)).astype(np.float32)
    out = net.output(x).to_numpy()
    assert out.shape == (2, 6, 12)
    np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-4)


def test_transformer_encoder_classifier():
    net = TransformerEncoder(vocab_size=50, max_len=8, d_model=16,
                             n_layers=2, n_heads=2, d_ff=32,
                             num_classes=3).build()
    ids = rng.integers(0, 50, size=(4, 8)).astype(np.int32)
    out = net.output(ids).to_numpy()
    assert out.shape == (4, 3)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 4)]
    h = net.fit([(ids, y)], epochs=2)
    assert np.isfinite(h.final_loss())
