"""Transfer learning builder + truncated BPTT.

Reference: TransferLearning.java:1 (freeze/replace/fine-tune),
MultiLayerNetwork.doTruncatedBPTT (MultiLayerNetwork.java:2083).
"""
import numpy as np
import pytest

from deeplearning4j_tpu.learning.updaters import Adam, Sgd
from deeplearning4j_tpu.nn import (
    ConvolutionLayer, DenseLayer, FineTuneConfiguration, InputType,
    LSTMLayer, MultiLayerNetwork, NeuralNetConfiguration, OutputLayer,
    RnnOutputLayer, SubsamplingLayer, TransferLearning)


def _base_cnn(seed=0):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
            .list()
            .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                    activation="relu"))
            .layer(SubsamplingLayer(kernel_size=(2, 2)))
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, loss_function="MCXENT"))
            .set_input_type(InputType.convolutional(8, 8, 1))
            .build())
    return MultiLayerNetwork(conf).init()


def test_transfer_freeze_and_replace_head():
    rng = np.random.RandomState(0)
    X = rng.rand(32, 1, 8, 8).astype(np.float32)
    Y3 = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 32)]
    base = _base_cnn()
    base.fit(X, Y3, epochs=3, batch_size=16)
    conv_w_name = [n for n in base.samediff._vars
                   if n.startswith("layer0_") and n.endswith("_W")][0]
    conv_w = np.asarray(base.samediff.get_arr_for_var(conv_w_name).data)

    # freeze features, swap head for a 5-class task
    new = (TransferLearning.builder(base)
           .fine_tune_configuration(FineTuneConfiguration(updater=Sgd(0.05)))
           .set_feature_extractor(2)          # freeze conv/pool/dense
           .remove_output_layer()
           .add_layer(OutputLayer(n_out=5, loss_function="MCXENT"))
           .build())
    sd = new.samediff
    # frozen params: present as constants, weights copied from the base
    got = np.asarray(sd.get_arr_for_var(conv_w_name).data)
    np.testing.assert_array_equal(got, conv_w)
    assert conv_w_name not in sd.trainable_params()
    # new head IS trainable
    head = [n for n in sd.trainable_params() if n.startswith("layer3_")]
    assert head

    Y5 = np.eye(5, dtype=np.float32)[rng.randint(0, 5, 32)]
    h = new.fit(X, Y5, epochs=10, batch_size=16)
    assert h.loss_curve.losses[-1] < h.loss_curve.losses[0]
    # frozen weights unchanged by fine-tuning
    after = np.asarray(new.samediff.get_arr_for_var(conv_w_name).data)
    np.testing.assert_array_equal(after, conv_w)
    assert np.asarray(new.output(X[:2]).data).shape == (2, 5)


def test_transfer_n_out_replace():
    base = _base_cnn()
    new = (TransferLearning.builder(base)
           .n_out_replace(2, 32)
           .remove_output_layer()
           .add_layer(OutputLayer(n_out=3, loss_function="MCXENT"))
           .build())
    assert new.conf.layers[2].n_out == 32
    out = new.output(np.zeros((2, 1, 8, 8), np.float32))
    assert np.asarray(out.data).shape == (2, 3)


def _rnn_net(tbptt=False, seed=0):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
            .list()
            .layer(LSTMLayer(n_out=8))
            .layer(RnnOutputLayer(n_out=2, loss_function="MCXENT"))
            .set_input_type(InputType.recurrent(3, 12))
            .build())
    return MultiLayerNetwork(conf).init()


def _seq_data(seed=1, B=16, T=12, C=3):
    rng = np.random.RandomState(seed)
    X = rng.randn(B, T, C).astype(np.float32)
    y = (np.cumsum(X[:, :, 0], axis=1) > 0).astype(int)
    Y = np.eye(2, dtype=np.float32)[y]
    return X, Y


def test_tbptt_full_length_equals_bptt():
    """tbptt_length >= T is exactly full BPTT: same loss trajectory as
    regular fit from the same seed."""
    X, Y = _seq_data()
    net_a = _rnn_net(seed=7)
    net_b = _rnn_net(seed=7)
    h_full = net_a.fit(X, Y, epochs=3, batch_size=16)
    h_tb = net_b.fit_tbptt(X, Y, tbptt_length=12, epochs=3, batch_size=16)
    np.testing.assert_allclose(h_tb.loss_curve.losses,
                               h_full.loss_curve.losses, rtol=2e-4,
                               atol=1e-5)


def test_tbptt_truncated_converges_and_carries_state():
    X, Y = _seq_data()
    net = _rnn_net(seed=3)
    h = net.fit_tbptt(X, Y, tbptt_length=4, epochs=12, batch_size=16)
    losses = h.loss_curve.losses
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses
    # truncation changes the gradients: trajectory differs from full BPTT
    net2 = _rnn_net(seed=3)
    h2 = net2.fit_tbptt(X, Y, tbptt_length=12, epochs=12, batch_size=16)
    assert abs(h.loss_curve.losses[-1] - h2.loss_curve.losses[-1]) > 1e-7


def test_tbptt_rejects_non_sequence():
    net = _rnn_net()
    with pytest.raises(ValueError, match="sequence features"):
        net.fit_tbptt(np.zeros((4, 3), np.float32),
                      np.zeros((4, 2), np.float32), tbptt_length=4)
