"""Elastic distributed training: declarative sharding strategies +
resharded resume across topology changes.

Covers PR 7's rail end-to-end on the virtual 8-device CPU mesh:

- ``ShardingSpec`` as a ``TrainingConfig`` citizen (serde round-trip,
  -1 fill-axis resolution, presets) driving sharded fits through every
  tier (scanned / fused windows / per-step) bit-exactly vs unsharded;
- checkpoint manifests recording mesh topology + per-array
  PartitionSpecs/global shapes, and the structured
  ``ShardCountMismatchError``/``TopologyChangedError`` restore raises
  when the runtime's process count differs from the manifest's;
- ``checkpoint.reshard.restore_resharded``: save on N processes,
  restore on M (N→M→N round-trip bit-exact), re-slice for the current
  mesh, ``{"type": "reshard"}`` observability;
- ``faults.FaultTolerantFit`` topology-change recovery: a chaos
  host-loss mid-fit resumes RESHARDED on the surviving mesh with the
  same loss trajectory; with topology unchanged, resume is bit-exact
  (params + losses) with the sentinel armed;
- the multi-process host-death drill (slow tier): one process of a
  2-host job dies via ``os._exit`` mid-run, the peer times out on the
  commit barrier, and the relaunched 1-process job resumes resharded.
"""
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.autodiff import (SameDiff, ScoreIterationListener,
                                         TrainingConfig)
from deeplearning4j_tpu.autodiff.training import Listener
from deeplearning4j_tpu.checkpoint import (CheckpointManager,
                                           ShardCountMismatchError,
                                           TopologyChangedError,
                                           capture_training_state,
                                           restore_resharded)
from deeplearning4j_tpu.dataset.iterators import (ArrayDataSetIterator,
                                                  DeviceCachedIterator)
from deeplearning4j_tpu.faults import (ChaosMonkey, FaultTolerantFit,
                                       RetryPolicy, TransientDeviceError,
                                       retryable_errors)
from deeplearning4j_tpu.learning.updaters import Adam
from deeplearning4j_tpu.parallel import (DeviceMesh, ParallelTrainer,
                                         ShardingRule, ShardingSpec,
                                         data_parallel)
from deeplearning4j_tpu.ui.stats import StatsStorage


def _mlp(sharding=None, fused_steps=1, sentinel=False, lr=1e-2):
    rng = np.random.default_rng(0)
    sd = SameDiff()
    x = sd.placeholder("x", shape=(-1, 8))
    w0 = sd.var("w0", value=rng.normal(0, .1, (8, 16)).astype(np.float32))
    b0 = sd.var("b0", value=np.zeros(16, np.float32))
    h = sd.nn.relu(x.mmul(w0).add(b0))
    w1 = sd.var("w1", value=rng.normal(0, .1, (16, 2)).astype(np.float32))
    logits = h.mmul(w1)
    labels = sd.placeholder("labels", shape=(-1, 2))
    sd.loss.softmax_cross_entropy(logits, labels, name="loss")
    sd.set_loss_variables(["loss"])
    sd.training_config = TrainingConfig(
        updater=Adam(lr), data_set_feature_mapping=["x"],
        data_set_label_mapping=["labels"], fused_steps=fused_steps,
        sentinel=sentinel, sharding=sharding)
    return sd


def _data(n=128, seed=1):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 8)).astype(np.float32)
    Y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, n)]
    return X, Y


def _quiet():
    return ScoreIterationListener(print_every=10 ** 9,
                                  print_fn=lambda *a: None)


def _full_mesh_strategy():
    return data_parallel(DeviceMesh.create(devices=jax.devices()))


def _sub_mesh_strategy(n=4):
    return data_parallel(DeviceMesh.create(devices=jax.devices()[:n]))


# ---------------------------------------------------------------------------
# ShardingSpec: the declarative TrainingConfig citizen

class TestShardingSpec:
    def test_serde_roundtrip(self):
        spec = ShardingSpec(
            axes={"data": -1, "model": 2}, preset="tensor_parallel",
            rules=[ShardingRule(r"_special_W$", (None, "model")),
                   ShardingRule(r"_mixed$", (("data", "model"), None))],
            batch_axes=("data",))
        d = spec.to_json()
        back = ShardingSpec.from_json(d)
        assert back.to_json() == d
        # tuple-valued PartitionSpec entries survive the list round-trip
        assert back.rules[1].spec == (("data", "model"), None)

    def test_rides_training_config_serde(self):
        sd = _mlp(sharding=ShardingSpec(axes={"data": -1}))
        d = sd.training_config.to_json()
        tc2 = TrainingConfig.from_json(d)
        assert tc2.sharding is not None
        assert tc2.sharding.to_json() == sd.training_config.sharding.to_json()
        # absent stays absent
        assert TrainingConfig.from_json(_mlp().training_config.to_json()) \
            .sharding is None

    def test_fill_axis_resolution(self):
        spec = ShardingSpec(axes={"data": -1, "model": 2})
        assert spec.resolve_axes(8) == {"data": 4, "model": 2}
        assert ShardingSpec(axes={"data": -1}).resolve_axes(8) == {"data": 8}
        with pytest.raises(ValueError, match="one -1"):
            ShardingSpec(axes={"data": -1, "model": -1}).resolve_axes(8)
        with pytest.raises(ValueError, match="multiple"):
            ShardingSpec(axes={"data": -1, "model": 3}).resolve_axes(8)

    def test_build_binds_to_devices(self):
        st = ShardingSpec(axes={"data": -1, "model": 2},
                          preset="tensor_parallel").build()
        assert dict(st.mesh.mesh.shape) == {"data": 4, "model": 2}
        # unknown preset is a loud error, not silent replication
        with pytest.raises(ValueError, match="preset"):
            ShardingSpec(preset="nope").build()

    def test_builder_hook(self):
        tc = (TrainingConfig.builder().updater(Adam(1e-3))
              .sharding(ShardingSpec(axes={"data": -1})).build())
        assert tc.sharding.axes == {"data": -1}


# ---------------------------------------------------------------------------
# sharded fit through every tier

class TestShardedFit:
    def test_fit_places_params_and_matches_unsharded(self):
        X, Y = _data()
        sharded = _mlp(sharding=ShardingSpec(axes={"data": -1}))
        h = sharded.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=2)
        plain = _mlp()
        h2 = plain.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=2)
        np.testing.assert_allclose(h.loss_curve.losses,
                                   h2.loss_curve.losses, rtol=1e-5)
        w0 = sharded.trainable_params()["w0"]
        assert len(w0.sharding.device_set) == len(jax.devices())

    def test_composes_with_fused_windows_and_sentinel(self):
        X, Y = _data()
        on = _mlp(sharding=ShardingSpec(axes={"data": -1}),
                  fused_steps=4, sentinel=True)
        h_on = on.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=2,
                      listeners=[_quiet()])
        assert on.last_fit_stats["tier"] == "windowed"
        off = _mlp(sharding=ShardingSpec(axes={"data": -1}), fused_steps=4)
        h_off = off.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=2,
                        listeners=[_quiet()])
        # sentinel on vs off stays bit-identical under the mesh
        np.testing.assert_array_equal(h_on.loss_curve.losses,
                                      h_off.loss_curve.losses)
        for n, a in on.trainable_params().items():
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(off.trainable_params()[n]), n)

    def test_scanned_tier_survives_the_wrap(self):
        """A device-cached source keeps the one-dispatch-per-epoch tier
        under TrainingConfig.sharding (the stacked_batches passthrough
        places (steps, batch, ...) stacks with the window sharding)."""
        X, Y = _data(n=64)
        sd = _mlp(sharding=ShardingSpec(axes={"data": -1}))
        h = sd.fit(DeviceCachedIterator(X, Y, batch_size=16), epochs=1)
        assert sd.last_fit_stats["tier"] == "scanned_epoch"
        plain = _mlp()
        h2 = plain.fit(DeviceCachedIterator(X, Y, batch_size=16), epochs=1)
        np.testing.assert_allclose(h.final_loss(), h2.final_loss(),
                                   rtol=1e-5)

    def test_parallel_trainer_adopts_config_spec(self):
        sd = _mlp(sharding=ShardingSpec(axes={"data": -1, "model": 2},
                                        preset="tensor_parallel"))
        trainer = ParallelTrainer(sd)
        assert dict(trainer.strategy.mesh.mesh.shape) == \
            {"data": 4, "model": 2}


# ---------------------------------------------------------------------------
# topology manifests + structured restore errors

class TestTopologyManifest:
    def test_capture_records_mesh_and_specs(self):
        X, Y = _data(n=64)
        sd = _mlp(sharding=ShardingSpec(axes={"data": -1}))
        sd.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=1)
        topo = capture_training_state(sd, epoch=1).metadata["topology"]
        assert topo["mesh_axes"] == {"data": len(jax.devices())}
        assert topo["device_count"] == len(jax.devices())
        assert set(topo["global_shapes"]) == set(sd.trainable_params())
        assert topo["global_shapes"]["w0"] == [8, 16]
        # every mesh-resident array records how it was sliced
        assert set(topo["partition_specs"]) == set(sd.trainable_params())

    def test_topology_roundtrips_through_commit(self, tmp_path):
        X, Y = _data(n=64)
        sd = _mlp(sharding=ShardingSpec(axes={"data": -1}))
        sd.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=1)
        with CheckpointManager(tmp_path, async_write=False) as mgr:
            mgr.save(3, model=sd, epoch=1)
            _, state = mgr.restore_latest()
        topo = state.metadata["topology"]
        assert topo["mesh_axes"] == {"data": len(jax.devices())}
        assert topo["global_shapes"]["w1"] == [16, 2]

    def test_shard_count_mismatch_is_structured(self, tmp_path):
        sd = _mlp()
        with CheckpointManager(tmp_path, async_write=False) as mgr:
            mgr.save(7, model=sd, epoch=0)
        mgr2 = CheckpointManager(tmp_path, process_index=0, process_count=2,
                                 barrier=lambda tag: None,
                                 async_write=False)
        with pytest.raises(ShardCountMismatchError) as ei:
            mgr2.restore_latest()
        err = ei.value
        assert err.manifest_count == 1 and err.runtime_count == 2
        assert err.step == 7
        assert isinstance(err, TopologyChangedError)
        # the rail treats it as retryable (CheckpointError family)
        assert isinstance(err, retryable_errors())
        with pytest.raises(ShardCountMismatchError):
            mgr2.restore(7)
        # the reshard path bypasses the check
        assert mgr2.restore_latest(allow_reshard=True)[0] == 7


# ---------------------------------------------------------------------------
# resharded restore: save on N, restore on M

def _save_two_process(tmp_path, sd, step=5, epoch=1):
    barrier = threading.Barrier(2, timeout=30)
    mgrs = [CheckpointManager(tmp_path, process_index=i, process_count=2,
                              barrier=lambda tag: barrier.wait(),
                              async_write=False)
            for i in range(2)]
    state = capture_training_state(sd, epoch=epoch)
    errs = []

    def run(i):
        try:
            mgrs[i].save(step, state=state)
        except BaseException as e:     # surfaced via the assert below
            errs.append(e)
    ts = [threading.Thread(target=run, args=(i,)) for i in range(2)]
    [t.start() for t in ts]
    [t.join(timeout=60) for t in ts]
    assert not errs, errs
    return mgrs


class TestReshardedRestore:
    @pytest.mark.slow
    def test_n_to_m_to_n_roundtrip_bit_exact(self, tmp_path):
        """Save on 2 processes → restore on 1 (resharded onto a
        4-device mesh) → save on 1 → restore on 2 (resharded again):
        the global params stay bit-exact through both crossings."""
        X, Y = _data(n=64)
        sd = _mlp(sharding=ShardingSpec(axes={"data": -1}))
        sd.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=1)
        _save_two_process(tmp_path, sd)

        mgr1 = CheckpointManager(tmp_path, process_index=0,
                                 process_count=1, async_write=False)
        storage = StatsStorage()
        sd2 = _mlp()
        trainer = ParallelTrainer(sd2, strategy=_sub_mesh_strategy(4))
        step, state = restore_resharded(mgr1, model=trainer,
                                        stats_storage=storage)
        assert step == 5
        info = state.metadata["reshard_info"]
        assert info["from_shards"] == 2 and info["to_processes"] == 1
        assert info["from_mesh"] == {"data": 8}
        assert info["to_mesh"] == {"data": 4}
        assert info["arrays"] == len(state.arrays) > 0
        for n, a in sd.trainable_params().items():
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(sd2.trainable_params()[n]), n)
        assert len(sd2.trainable_params()["w0"].sharding.device_set) == 4
        [rec] = storage.of_type("reshard")
        assert rec["bytes"] > 0

        # ... and back: 1-shard save, 2-process runtime reshards again
        mgr1.save(6, model=sd2, epoch=1, blocking=True)
        mgr2 = CheckpointManager(tmp_path, process_index=0,
                                 process_count=2,
                                 barrier=lambda tag: None,
                                 async_write=False)
        with pytest.raises(ShardCountMismatchError):
            mgr2.restore_latest()
        sd3 = _mlp()
        step, _ = restore_resharded(mgr2, model=sd3)
        assert step == 6
        for n, a in sd.trainable_params().items():
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(sd3.trainable_params()[n]), n)

    def test_restore_resharded_none_when_empty(self, tmp_path):
        mgr = CheckpointManager(tmp_path, async_write=False)
        assert restore_resharded(mgr, model=_mlp()) is None

    def test_trainer_restore_honors_strategy_override(self, tmp_path):
        """ParallelTrainer.restore_latest(strategy=...) reshards the
        restored state into a DIFFERENT sharding than construction
        time — restore-into-a-new-mesh works standalone."""
        X, Y = _data(n=64)
        sd = _mlp()
        trainer = ParallelTrainer(sd, strategy=_full_mesh_strategy())
        trainer.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=1)
        storage = StatsStorage()
        with CheckpointManager(tmp_path, async_write=False) as mgr:
            mgr.save(4, model=sd, epoch=1)
            sd2 = _mlp()
            t2 = ParallelTrainer(sd2, strategy=_full_mesh_strategy(),
                                 stats_storage=storage)
            res = t2.restore_latest(mgr, strategy=_sub_mesh_strategy(2))
        assert res is not None and res[0] == 4
        assert t2.strategy.mesh.n_devices == 2
        assert len(sd2.trainable_params()["w0"].sharding.device_set) == 2
        assert t2.last_reshard["from_mesh"] == {"data": 8}
        assert t2.last_reshard["to_mesh"] == {"data": 2}
        [rec] = storage.of_type("reshard")
        assert rec["to_devices"] == 2
        for n, a in sd.trainable_params().items():
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(sd2.trainable_params()[n]), n)

    def test_trainer_restore_same_topology_records_no_reshard(
            self, tmp_path):
        X, Y = _data(n=64)
        sd = _mlp()
        trainer = ParallelTrainer(sd, strategy=_full_mesh_strategy())
        trainer.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=1)
        with CheckpointManager(tmp_path, async_write=False) as mgr:
            mgr.save(4, model=sd, epoch=1)
            t2 = ParallelTrainer(_mlp(), strategy=_full_mesh_strategy())
            assert t2.restore_latest(mgr) is not None
        assert t2.last_reshard is None


# ---------------------------------------------------------------------------
# FaultTolerantFit: topology-change recovery

class TestElasticRecovery:
    @pytest.mark.chaos
    def test_host_loss_resumes_resharded_same_trajectory(self, tmp_path):
        """Acceptance e2e: a sharded fit survives a chaos host loss
        (mesh 8 → 4 mid-fit) by resuming RESHARDED on the surviving
        topology; the continued loss trajectory matches the
        uninterrupted full-mesh run."""
        X, Y = _data()
        ref = _mlp(fused_steps=4, sentinel=True)
        rt = ParallelTrainer(ref, strategy=_full_mesh_strategy())
        h_ref = rt.fit(ArrayDataSetIterator(X, Y, batch_size=16),
                       epochs=4, listeners=[_quiet()])

        sd = _mlp(fused_steps=4, sentinel=True)
        trainer = ParallelTrainer(sd, strategy=_full_mesh_strategy())
        chaos = ChaosMonkey(seed=7)
        injector = chaos.host_loss(trainer, _sub_mesh_strategy(4),
                                   at_iteration=17)
        storage = StatsStorage()
        mgr = CheckpointManager(tmp_path, keep_last_n=5)
        ftf = FaultTolerantFit(
            trainer, mgr,
            policy=RetryPolicy(max_retries=2, backoff_base=0.0),
            checkpoint_every_n_epochs=1, stats_storage=storage,
            sleep=lambda s: None)
        h = ftf.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=4,
                    listeners=[injector, _quiet()])
        mgr.close()
        assert injector.fired
        assert ftf.rollbacks == 1
        # resumed on the shrunken mesh
        assert len(sd.trainable_params()["w0"].sharding.device_set) == 4
        events = [r["event"] for r in storage.of_type("faults")]
        assert "fault" in events and "rollback" in events
        assert "reshard" in events and "recovered" in events
        reshard_ev = next(r for r in storage.of_type("faults")
                          if r["event"] == "reshard")
        assert reshard_ev["from_mesh"] == {"data": 8}
        assert reshard_ev["to_mesh"] == {"data": 4}
        assert chaos.log[0]["event"] == "host_loss"
        # trajectory: the final attempt's epochs match the uninterrupted
        # run's tail (rounding may differ across collective orders)
        tail = h_ref.loss_curve.losses[-len(h.loss_curve.losses):]
        np.testing.assert_allclose(h.loss_curve.losses, tail, rtol=1e-4)
        for n, a in sd.trainable_params().items():
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(ref.trainable_params()[n]),
                rtol=1e-4, atol=1e-6, err_msg=n)

    @pytest.mark.chaos
    def test_unchanged_topology_resume_bit_exact_sentinel_on(
            self, tmp_path):
        """With the topology unchanged, a fault-and-rollback resume is
        BIT-exact vs the uninterrupted run (params + losses), device
        sentinel armed throughout."""
        X, Y = _data()
        ref = _mlp(fused_steps=4, sentinel=True)
        rt = ParallelTrainer(ref, strategy=_full_mesh_strategy())
        h_ref = rt.fit(ArrayDataSetIterator(X, Y, batch_size=16),
                       epochs=4, listeners=[_quiet()])

        class Bomb(Listener):
            frequency = 1
            fired = False

            def iteration_done(self, s, e, it, loss):
                if not self.fired and it >= 17:
                    self.fired = True
                    raise TransientDeviceError("chaos: transient",
                                               step=it, cause="device")

        sd = _mlp(fused_steps=4, sentinel=True)
        trainer = ParallelTrainer(sd, strategy=_full_mesh_strategy())
        storage = StatsStorage()
        mgr = CheckpointManager(tmp_path, keep_last_n=5)
        ftf = FaultTolerantFit(
            trainer, mgr,
            policy=RetryPolicy(max_retries=2, backoff_base=0.0),
            checkpoint_every_n_epochs=1, stats_storage=storage,
            sleep=lambda s: None)
        h = ftf.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=4,
                    listeners=[Bomb(), _quiet()])
        mgr.close()
        assert ftf.rollbacks == 1
        assert sd.training_config.sentinel
        # no topology change → no reshard event
        events = [r["event"] for r in storage.of_type("faults")]
        assert "reshard" not in events
        np.testing.assert_array_equal(
            h.loss_curve.losses,
            h_ref.loss_curve.losses[-len(h.loss_curve.losses):])
        for n, a in sd.trainable_params().items():
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(ref.trainable_params()[n]), n)

    @pytest.mark.chaos
    def test_resume_latest_reshards_on_mismatch(self, tmp_path):
        """The restart half: a relaunched job with a different process
        count resumes through ftf.resume_latest() — plain restore
        raises ShardCountMismatchError, the rail reshards."""
        X, Y = _data(n=64)
        sd = _mlp(sharding=ShardingSpec(axes={"data": -1}))
        sd.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=1)
        _save_two_process(tmp_path, sd)
        mgr = CheckpointManager(tmp_path, process_count=1,
                                async_write=False)
        storage = StatsStorage()
        sd2 = _mlp(sharding=ShardingSpec(axes={"data": -1}))
        ftf = FaultTolerantFit(sd2, mgr, stats_storage=storage,
                               sleep=lambda s: None)
        res = ftf.resume_latest()
        assert res is not None and res[0] == 5
        events = [r["event"] for r in storage.of_type("faults")]
        assert "topology_changed" in events and "reshard" in events
        for n, a in sd.trainable_params().items():
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(sd2.trainable_params()[n]), n)
        # continue training on the current (1-process) topology
        h = ftf.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=1)
        assert np.isfinite(h.final_loss())
        mgr.close()


# ---------------------------------------------------------------------------
# observability

class TestReshardObservability:
    def _record(self):
        return {"type": "reshard", "step": 5, "arrays": 4,
                "bytes": 2048, "seconds": 0.01, "from_shards": 2,
                "from_mesh": {"data": 8}, "to_mesh": {"data": 4},
                "from_processes": 2, "to_processes": 1, "t": 0.0}

    def test_fold_reshard_metrics(self):
        from deeplearning4j_tpu.monitor.registry import MetricsRegistry
        reg = MetricsRegistry()
        storage = StatsStorage()
        storage.put(self._record())
        reg.fold_storage(storage)
        assert reg.get("reshard_events_total") == 1
        assert reg.get("reshard_arrays_resliced_total") == 4
        assert reg.get("reshard_bytes_gathered_total") == 2048
        assert reg.get("reshard_last_from_shards") == 2
        text = reg.to_prometheus_text()
        assert "dl4j_reshard_seconds" in text
        # idempotent over a growing storage
        reg.fold_storage(storage)
        assert reg.get("reshard_events_total") == 1

    def test_report_renders_reshards(self):
        from deeplearning4j_tpu.ui.report import render_report
        storage = StatsStorage()
        storage.put(self._record())
        html = render_report(storage)
        assert "Elastic reshards" in html
        assert "unrendered record types" not in html

    def test_reshard_emits_span(self, tmp_path):
        from deeplearning4j_tpu.monitor.trace import TRACER
        X, Y = _data(n=64)
        sd = _mlp()
        trainer = ParallelTrainer(sd, strategy=_full_mesh_strategy())
        trainer.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=1)
        with CheckpointManager(tmp_path, async_write=False) as mgr:
            mgr.save(2, model=sd, epoch=1)
            TRACER.enable()
            try:
                t2 = ParallelTrainer(_mlp(),
                                     strategy=_full_mesh_strategy())
                t2.restore_latest(mgr, strategy=_sub_mesh_strategy(2))
                spans, _, _ = TRACER.drain()
            finally:
                TRACER.disable()
        assert any(s.name == "checkpoint.reshard" for s in spans)


# ---------------------------------------------------------------------------
# multi-process host-death drill (slow tier: real processes, file barrier)

_WORKER_SCRIPT = r"""
import os, sys
sys.path.insert(0, {repo!r})
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + \
        " --xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig
from deeplearning4j_tpu.checkpoint import CheckpointListener, \
    CheckpointManager
from deeplearning4j_tpu.faults import FileBarrier, HostKiller
from deeplearning4j_tpu.learning.updaters import Adam

idx = int(sys.argv[1]); ckpt = sys.argv[2]; bdir = sys.argv[3]

rng = np.random.default_rng(0)
sd = SameDiff()
x = sd.placeholder("x", shape=(-1, 8))
w0 = sd.var("w0", value=rng.normal(0, .1, (8, 16)).astype(np.float32))
b0 = sd.var("b0", value=np.zeros(16, np.float32))
h = sd.nn.relu(x.mmul(w0).add(b0))
w1 = sd.var("w1", value=rng.normal(0, .1, (16, 2)).astype(np.float32))
labels = sd.placeholder("labels", shape=(-1, 2))
sd.loss.softmax_cross_entropy(h.mmul(w1), labels, name="loss")
sd.set_loss_variables(["loss"])
sd.training_config = TrainingConfig(
    updater=Adam(1e-2), data_set_feature_mapping=["x"],
    data_set_label_mapping=["labels"], fused_steps=2, sentinel=True)

drng = np.random.default_rng(1)
X = drng.normal(size=(64, 8)).astype(np.float32)
Y = np.eye(2, dtype=np.float32)[drng.integers(0, 2, 64)]

# each "host" trains the identical replica (pure DP, shared seed/data)
# and writes its name-shard of every checkpoint into the shared dir
mgr = CheckpointManager(ckpt, process_index=idx, process_count=2,
                        barrier=FileBarrier(bdir, idx, 2, timeout=20),
                        async_write=False)
listeners = [CheckpointListener(mgr, every_n_epochs=1)]
if idx == 1:
    listeners.append(HostKiller(at_iteration=9))   # dies inside epoch 2

from deeplearning4j_tpu.dataset.iterators import ArrayDataSetIterator
sd.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=4,
       listeners=listeners)
print("worker", idx, "finished")
"""


# ---------------------------------------------------------------------------
# review regressions

class TestReviewRegressions:
    def test_sub_mesh_trainer_restore_is_not_a_spurious_reshard(
            self, tmp_path):
        """A trainer on a SUB-mesh of the process's devices (4 of 8)
        restores a checkpoint saved on that same sub-mesh without
        flagging a reshard — the detector compares the saved mesh
        extent, not the process-wide device_count (which stays 8)."""
        X, Y = _data(n=64)
        sd = _mlp()
        trainer = ParallelTrainer(sd, strategy=_sub_mesh_strategy(4))
        trainer.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=1)
        with CheckpointManager(tmp_path, async_write=False) as mgr:
            mgr.save(3, model=sd, epoch=1)
            t2 = ParallelTrainer(_mlp(), strategy=_sub_mesh_strategy(4))
            assert t2.restore_latest(mgr) is not None
        assert t2.last_reshard is None

    def test_file_barrier_tag_reuse_requires_fresh_arrivals(self,
                                                            tmp_path):
        """Re-saving the same step re-uses barrier tags; stale markers
        from the first crossing must NOT satisfy the second (each
        recurrence gets its own generation)."""
        from deeplearning4j_tpu.faults import FileBarrier
        b0 = FileBarrier(tmp_path, 0, 2, timeout=0.3, poll=0.01)
        b1 = FileBarrier(tmp_path, 1, 2, timeout=5.0, poll=0.01)
        t = threading.Thread(target=b1, args=("step_5_staged",))
        t.start()
        b0("step_5_staged")            # first crossing completes
        t.join(timeout=10)
        assert not t.is_alive()
        with pytest.raises(TimeoutError):
            b0("step_5_staged")        # second: peer never re-arrives
        # a relaunched job (fresh run_id, same dir) must not be fed by
        # the dead job's markers either
        b_new = FileBarrier(tmp_path, 0, 2, timeout=0.3, poll=0.01,
                            run_id="r1")
        with pytest.raises(TimeoutError):
            b_new("step_5_staged")

    def test_restore_resharded_skips_corrupt_newest_step(self, tmp_path):
        """A bit-flipped newest step must not kill the reshard path —
        it falls back to the older intact checkpoint like
        restore_latest does."""
        sd = _mlp()
        with CheckpointManager(tmp_path, async_write=False) as mgr:
            mgr.save(1, model=sd, epoch=0)
            mgr.save(2, model=sd, epoch=0)
            d = mgr.step_dir(2)
            victim = next(os.path.join(d, f) for f in sorted(os.listdir(d))
                          if f.endswith(".npz"))
            data = bytearray(open(victim, "rb").read())
            data[len(data) // 2] ^= 0xFF        # same size, bad hash
            with open(victim, "wb") as fh:
                fh.write(data)
            res = restore_resharded(mgr, model=_mlp())
        assert res is not None and res[0] == 1

    def test_config_serde_accepts_live_strategy(self):
        """The fit path accepts a live ShardingStrategy on
        tc.sharding; to_json must serialize it (via its declarative
        spec) instead of crashing — and the emitted spec stays ELASTIC:
        the data axis round-trips as -1 so a relaunched job with fewer
        devices rebinds instead of failing on the frozen extent."""
        sd = _mlp()
        sd.training_config.sharding = _sub_mesh_strategy(4)
        d = sd.training_config.to_json()
        assert d["sharding"]["axes"] == {"data": -1}
        back = TrainingConfig.from_json(d)
        assert isinstance(back.sharding, ShardingSpec)
        # rebinds to whatever the relaunched process has
        assert back.sharding.build().mesh.n_devices == len(jax.devices())
        assert back.sharding.build(
            devices=jax.devices()[:2]).mesh.n_devices == 2

    def test_strategy_override_not_adopted_without_a_restore(
            self, tmp_path):
        """restore_latest(strategy=) on an empty manager returns None
        and must NOT swap the trainer's strategy — params are still
        placed under the old mesh, and a half-adopted override would
        make the next fit dispatch with incompatible devices."""
        t = ParallelTrainer(_mlp(), strategy=_full_mesh_strategy())
        with CheckpointManager(tmp_path, async_write=False) as mgr:
            assert t.restore_latest(mgr,
                                    strategy=_sub_mesh_strategy(2)) is None
        assert t.strategy.mesh.n_devices == len(jax.devices())

    def test_restore_resharded_lost_file_is_retryable(self, tmp_path,
                                                      monkeypatch):
        """A file vanishing between verification and read (retention
        race) surfaces as a retryable CheckpointError, not a raw
        FileNotFoundError that would abort the recovery rail."""
        from deeplearning4j_tpu.checkpoint import manager as mgr_mod
        from deeplearning4j_tpu.checkpoint import reshard as reshard_mod
        sd = _mlp()
        with CheckpointManager(tmp_path, async_write=False) as mgr:
            mgr.save(1, model=sd, epoch=0)
            def gone(d):
                raise FileNotFoundError("races with retention")
            monkeypatch.setattr(reshard_mod, "read_state_files", gone)
            with pytest.raises(mgr_mod.CheckpointError) as ei:
                restore_resharded(mgr, model=_mlp())
        assert not isinstance(ei.value, TopologyChangedError)
        assert isinstance(ei.value, retryable_errors())

    def test_report_renders_trainer_origin_reshards(self):
        """Trainer-origin reshard records carry device counts, not
        shard counts; the report must not render them as '? → ?'."""
        from deeplearning4j_tpu.ui.report import render_report
        storage = StatsStorage()
        storage.put({"type": "reshard", "step": 4, "arrays": 4,
                     "bytes": 1024, "seconds": 0.01,
                     "from_mesh": {"data": 8}, "to_mesh": {"data": 2},
                     "from_devices": 8, "to_devices": 2, "t": 0.0})
        html = render_report(storage)
        assert "Elastic reshards" in html
        assert "? → ?" not in html
        assert "8 → 2 dev" in html


@pytest.mark.slow
@pytest.mark.chaos(timeout=300)
def test_multihost_host_death_elastic_resume(tmp_path):
    """The full drill: a 2-process job (shared checkpoint dir, file
    barrier) loses one host to os._exit mid-window; the survivor times
    out on the commit barrier and the job dies. The relaunched
    1-process job restores RESHARDED from the 2-shard checkpoint and
    trains to completion."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ckpt = str(tmp_path / "ckpt")
    bdir = str(tmp_path / "barrier")
    script = tmp_path / "worker.py"
    script.write_text(_WORKER_SCRIPT.format(repo=repo))
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(i), ckpt, bdir],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i in range(2)]
    rcs = [p.wait(timeout=240) for p in procs]
    outs = [p.stdout.read().decode() for p in procs]
    # host 1 was killed (137); host 0 died on the barrier timeout — the
    # job did NOT complete
    assert rcs[1] == 137, outs[1]
    assert rcs[0] != 0, outs[0]
    assert "finished" not in outs[0]

    # the relaunched single-process job: ShardCountMismatch → reshard
    mgr = CheckpointManager(ckpt, process_count=1, async_write=False)
    assert mgr.latest_step() is not None
    with pytest.raises(ShardCountMismatchError):
        mgr.restore_latest()
    X, Y = _data(n=64)
    sd = _mlp(fused_steps=2, sentinel=True)
    storage = StatsStorage()
    ftf = FaultTolerantFit(sd, mgr, stats_storage=storage,
                           sleep=lambda s: None)
    res = ftf.resume_latest()
    assert res is not None
    step, state = res
    assert state.metadata["reshard_info"]["from_shards"] == 2
    h = ftf.fit(ArrayDataSetIterator(X, Y, batch_size=16),
                epochs=4 - sd.training_config.epoch_count)
    assert np.isfinite(h.final_loss())
    assert sd.training_config.epoch_count == 4
    events = [r["event"] for r in storage.of_type("faults")]
    assert "topology_changed" in events and "reshard" in events
    mgr.close()
