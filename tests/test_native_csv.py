"""Native fastcsv kernel tests (reference: datavec CSVRecordReader tests;
the native path mirrors datavec's native-IO record reading)."""
import numpy as np
import pytest

from deeplearning4j_tpu.etl import CSVRecordReader
from deeplearning4j_tpu.native import native_available, read_csv_f32
from deeplearning4j_tpu.native import build as native_build


def _write(tmp_path, text, name="data.csv"):
    p = tmp_path / name
    p.write_text(text)
    return str(p)


def test_native_kernel_builds():
    """The environment ships g++; the kernel must actually build here."""
    assert native_available("fastcsv"), \
        native_build.build_error("fastcsv")


def test_native_parse_matches_python(tmp_path):
    rng = np.random.default_rng(0)
    want = rng.normal(size=(200, 7)).astype(np.float32)
    text = "\n".join(",".join(f"{v:.6g}" for v in row) for row in want)
    p = _write(tmp_path, text + "\n")
    got = read_csv_f32(p)
    np.testing.assert_allclose(got, want, rtol=1e-5)
    # and through the record reader's fast path
    got2 = CSVRecordReader(p).as_matrix()
    np.testing.assert_allclose(got2, want, rtol=1e-5)


def test_skip_lines_and_delimiter(tmp_path):
    p = _write(tmp_path, "h1;h2\n1;2\n3;4\n")
    got = read_csv_f32(p, delimiter=";", skip_num_lines=1)
    np.testing.assert_array_equal(got, [[1, 2], [3, 4]])


def test_ragged_and_nonnumeric_rejected(tmp_path):
    ragged = _write(tmp_path, "1,2\n3,4,5\n", "ragged.csv")
    with pytest.raises(ValueError, match="ragged|could not|cannot"):
        read_csv_f32(ragged)
    bad = _write(tmp_path, "1,2\n3,abc\n", "bad.csv")
    with pytest.raises(ValueError):
        read_csv_f32(bad)


def test_python_fallback_matches(tmp_path, monkeypatch):
    p = _write(tmp_path, "1.5,2.5\n3.5,4.5\n")
    native = read_csv_f32(p)
    import deeplearning4j_tpu.native.fastcsv as fc
    monkeypatch.setattr(fc, "load", lambda name: None)
    fallback = fc.read_csv_f32(p)
    np.testing.assert_array_equal(native, fallback)


def test_native_is_faster_on_large_file(tmp_path):
    """Sanity: the point of the kernel is throughput; it must not be
    slower than numpy's text loader on a non-trivial file."""
    if not native_available("fastcsv"):
        pytest.skip("no toolchain")
    import time
    rng = np.random.default_rng(1)
    m = rng.normal(size=(20000, 20)).astype(np.float32)
    text = "\n".join(",".join(f"{v:.6g}" for v in row) for row in m)
    p = _write(tmp_path, text + "\n", "big.csv")
    t0 = time.perf_counter()
    a = read_csv_f32(p)
    t_native = time.perf_counter() - t0
    t0 = time.perf_counter()
    b = np.loadtxt(p, delimiter=",", dtype=np.float32, ndmin=2)
    t_numpy = time.perf_counter() - t0
    np.testing.assert_allclose(a, b, rtol=1e-5)
    assert t_native < t_numpy * 1.5, (t_native, t_numpy)


def test_empty_trailing_cell_rejected_not_stolen(tmp_path):
    """Regression: an empty trailing cell must raise, not pull its value
    across the newline from the next record."""
    p = _write(tmp_path, "1,\n2,3\n", "trail.csv")
    with pytest.raises(ValueError):
        read_csv_f32(p)


def test_tab_delimiter_native(tmp_path):
    """Regression: tab is a legal delimiter; the padding skip must not
    consume it."""
    p = _write(tmp_path, "1\t2\n3\t4\n", "tabs.csv")
    got = read_csv_f32(p, delimiter="\t")
    np.testing.assert_array_equal(got, [[1, 2], [3, 4]])
    got2 = CSVRecordReader(p, delimiter="\t").as_matrix()
    np.testing.assert_array_equal(got2, [[1, 2], [3, 4]])
