"""monitor/ — trace spans, metrics registry, step-time attribution.

Covers the ISSUE-5 acceptance criteria: a fused-window fit with tracing
enabled produces (a) a Perfetto-loadable chrome trace whose window spans
contain data-wait/dispatch/flush children, (b) {"type": "metrics"} and
{"type": "steptime"} records in StatsStorage, and (c) bit-identical
losses to the same fit with monitoring disabled; plus the tracer
overhead guard, the prometheus text parse check, and the report golden
render of the new sections.
"""
import json
import re
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.monitor import (MetricsRegistry, MonitorListener,
                                        RollingPercentiles,
                                        StragglerWatcher, TRACER,
                                        disable_tracing, enable_tracing,
                                        window_rows)
from deeplearning4j_tpu.ui.stats import StatsStorage


@pytest.fixture(autouse=True)
def _tracing_off_between_tests():
    """Each test opts in explicitly; nothing leaks across tests (the
    capacity reset matters: one test shrinks the shared ring)."""
    disable_tracing()
    TRACER.reset(capacity=65536)
    yield
    disable_tracing()
    TRACER.reset(capacity=65536)


def _build_mlp(fused_steps=4, seed=0):
    from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig
    from deeplearning4j_tpu.learning.updaters import Adam

    rng = np.random.default_rng(seed)
    sd = SameDiff()
    x = sd.placeholder("x", shape=(-1, 16))
    w0 = sd.var("w0", value=rng.normal(0, .1, (16, 32)).astype(np.float32))
    h = sd.nn.relu(x.mmul(w0))
    w1 = sd.var("w1", value=rng.normal(0, .1, (32, 4)).astype(np.float32))
    logits = h.mmul(w1)
    labels = sd.placeholder("labels", shape=(-1, 4))
    sd.loss.softmax_cross_entropy(logits, labels, name="loss")
    sd.set_loss_variables(["loss"])
    sd.training_config = TrainingConfig(
        updater=Adam(1e-2), data_set_feature_mapping=["x"],
        data_set_label_mapping=["labels"], fused_steps=fused_steps)
    return sd


def _data(n=128, seed=1):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 16)).astype(np.float32)
    Y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, n)]
    return X, Y


class TestTracer:
    def test_nested_spans_and_parents(self):
        tr = enable_tracing(reset=True)
        with tr.span("outer", cat="t") as o:
            with tr.span("inner"):
                pass
        spans = tr.spans()
        assert [s.name for s in spans] == ["inner", "outer"]
        inner, outer = spans
        assert inner.parent == outer.sid
        assert outer.parent == 0
        assert inner.t0 >= outer.t0
        assert inner.dur <= outer.dur

    def test_disabled_records_nothing_and_null_span_api(self):
        TRACER.reset()
        assert not TRACER.enabled
        with TRACER.span("x", k=1) as sp:
            sp.set(a=2)
            sp.discard()
        assert TRACER.spans() == []
        assert TRACER.mark() == 0

    def test_discard(self):
        tr = enable_tracing(reset=True)
        with tr.span("kept"):
            pass
        with tr.span("dropped") as sp:
            sp.discard()
        assert [s.name for s in tr.spans()] == ["kept"]

    def test_exception_records_span_with_error(self):
        tr = enable_tracing(reset=True)
        with pytest.raises(ValueError):
            with tr.span("boom"):
                raise ValueError("x")
        (sp,) = tr.spans()
        assert sp.args["error"] == "ValueError"

    def test_ring_eviction_and_drain_marks(self):
        tr = enable_tracing(reset=True)
        tr.reset(capacity=8)
        tr.enable()
        for i in range(5):
            with tr.span(f"s{i}"):
                pass
        spans, mark, dropped = tr.drain(0)
        assert [s.name for s in spans] == [f"s{i}" for i in range(5)]
        assert dropped == 0
        for i in range(5, 25):
            with tr.span(f"s{i}"):
                pass
        spans, mark2, dropped = tr.drain(mark)
        # 20 new spans, ring holds 8 — the drain reports the eviction
        assert dropped == 12
        assert [s.name for s in spans] == [f"s{i}" for i in range(17, 25)]
        assert tr.drain(mark2) == ([], mark2, 0)

    def test_thread_lanes_are_independent(self):
        tr = enable_tracing(reset=True)

        def worker():
            with tr.span("w_outer"):
                with tr.span("w_inner"):
                    time.sleep(0.002)

        with tr.span("main_outer"):
            t = threading.Thread(target=worker, name="lane2")
            t.start()
            t.join()
        by_name = {s.name: s for s in tr.spans()}
        # the worker's spans must NOT have picked up main_outer as
        # parent (per-thread stacks)
        assert by_name["w_outer"].parent == 0
        assert by_name["w_inner"].parent == by_name["w_outer"].sid
        assert by_name["w_outer"].tid != by_name["main_outer"].tid
        assert by_name["w_outer"].thread_name == "lane2"

    def test_traced_decorator(self):
        tr = enable_tracing(reset=True)

        @tr.traced(cat="test")
        def add(a, b):
            return a + b

        assert add(2, 3) == 5
        (sp,) = tr.spans()
        assert "add" in sp.name and sp.cat == "test"
        tr.disable()
        assert add(1, 1) == 2
        assert len(tr.spans()) == 1


class TestChromeTrace:
    def test_schema_and_monotonic_ts(self):
        tr = enable_tracing(reset=True)
        with tr.span("a", cat="x", k=3):
            with tr.span("b"):
                pass
        with tr.span("c"):
            pass
        doc = tr.to_chrome_trace()
        # must round-trip as plain JSON (Perfetto loads the file as-is)
        doc = json.loads(json.dumps(doc))
        assert "traceEvents" in doc
        events = doc["traceEvents"]
        xs = [e for e in events if e["ph"] == "X"]
        metas = [e for e in events if e["ph"] == "M"]
        assert metas and all(m["name"] == "thread_name" for m in metas)
        assert {e["name"] for e in xs} == {"a", "b", "c"}
        for e in xs:
            for key in ("name", "ph", "ts", "dur", "pid", "tid"):
                assert key in e, key
            assert e["dur"] >= 0 and e["ts"] >= 0
        assert [e["ts"] for e in xs] == sorted(e["ts"] for e in xs)
        a = next(e for e in xs if e["name"] == "a")
        assert a["args"]["k"] == 3 and a["cat"] == "x"

    def test_write_chrome_trace_file(self, tmp_path):
        tr = enable_tracing(reset=True)
        with tr.span("s"):
            pass
        p = tr.write_chrome_trace(str(tmp_path / "trace.json"))
        doc = json.load(open(p, encoding="utf-8"))
        assert any(e["name"] == "s" for e in doc["traceEvents"])


class TestFusedFitTracing:
    """The acceptance-criterion path: fused-window fit, tracing on."""

    def _run(self):
        from deeplearning4j_tpu.dataset.iterators import \
            ArrayDataSetIterator
        X, Y = _data()
        sd = _build_mlp(fused_steps=4)
        st = StatsStorage()
        mon = MonitorListener(st, frequency=10)
        hist = sd.fit(ArrayDataSetIterator(X, Y, batch_size=16),
                      epochs=2, listeners=[mon])
        return sd, st, hist

    def test_window_spans_have_stage_children(self):
        enable_tracing(reset=True)
        self._run()
        doc = TRACER.to_chrome_trace()
        xs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        wins = [e for e in xs if e["name"] == "window"]
        assert len(wins) == 4           # 2 epochs x ceil(8 steps / K=4)

        def children(w):
            return {e["name"] for e in xs
                    if e["tid"] == w["tid"] and e["name"] != "window"
                    and e["ts"] >= w["ts"] - 1e-3
                    and e["ts"] + e["dur"] <= w["ts"] + w["dur"] + 1e-3}
        union = set()
        for w in wins:
            ch = children(w)
            assert {"data_wait", "dispatch"} <= ch, ch
            union |= ch
        # the epoch-crossing cadence flush lands inside its window
        assert "flush" in union
        # the stager's H2D lane exists and is OFF the training thread
        h2d = [e for e in xs if e["name"] == "h2d_stage"]
        assert h2d and all(e["tid"] != wins[0]["tid"] for e in h2d)

    def test_steptime_and_metrics_records(self):
        enable_tracing(reset=True)
        sd, st, _ = self._run()
        stp = [r for r in st.of_type("steptime")
               if r.get("event") != "straggler"]
        assert stp
        total_steps = sum(r["steps"] for r in stp)
        assert total_steps == 16         # 2 epochs x 8 steps, all seen
        for r in stp:
            for key in ("data_wait_s", "dispatch_s", "flush_s", "other_s",
                        "wall_s", "step_ms_p50", "step_ms_p95"):
                assert key in r
            assert r["wall_s"] > 0 and r["dispatch_s"] > 0
        # flush time is attributed (the device sync happens somewhere)
        assert sum(r["flush_s"] for r in stp) > 0
        mets = st.of_type("metrics")
        assert mets
        flat = mets[-1]["metrics"]
        assert flat['dl4j_fit_steps_per_epoch{tier="windowed"}'] == 8
        assert flat["dl4j_steptime_steps_total"] == 16
        # trace dump for the report swimlane
        (tr_rec,) = st.of_type("trace")
        assert tr_rec["spans"] and all(
            set(s) >= {"name", "ts", "dur", "tid", "sid", "parent"}
            for s in tr_rec["spans"])

    def test_losses_bit_identical_monitoring_on_vs_off(self):
        from deeplearning4j_tpu.autodiff import ScoreIterationListener
        from deeplearning4j_tpu.dataset.iterators import \
            ArrayDataSetIterator
        X, Y = _data()
        enable_tracing(reset=True)
        sd1, st1, h1 = self._run()
        disable_tracing()
        sd2 = _build_mlp(fused_steps=4)
        # same listener cadence, no monitoring, no tracing
        silent = ScoreIterationListener(print_every=10 ** 9,
                                        print_fn=lambda *a: None)
        silent.frequency = 10
        h2 = sd2.fit(ArrayDataSetIterator(X, Y, batch_size=16),
                     epochs=2, listeners=[silent])
        np.testing.assert_array_equal(
            np.asarray(h1.loss_curve.losses),
            np.asarray(h2.loss_curve.losses))
        for n in ("w0", "w1"):
            np.testing.assert_array_equal(
                np.asarray(sd1.get_variable(n).get_arr()),
                np.asarray(sd2.get_variable(n).get_arr()))

    def test_per_step_tier_also_attributed(self):
        from deeplearning4j_tpu.dataset.iterators import \
            ArrayDataSetIterator
        X, Y = _data(64)
        sd = _build_mlp(fused_steps=1)
        enable_tracing(reset=True)
        st = StatsStorage()
        sd.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=1,
               listeners=[MonitorListener(st, frequency=2)])
        names = {s.name for s in TRACER.spans()}
        assert {"step", "data_wait", "dispatch", "flush"} <= names
        stp = [r for r in st.of_type("steptime")
               if r.get("event") != "straggler"]
        assert sum(r["steps"] for r in stp) == 4
        assert sum(r["flush_s"] for r in stp) > 0


class TestTracerOverhead:
    def test_disabled_span_cost_under_one_percent_of_step(self):
        """The always-on guard: the disabled tracer's per-span cost,
        times the spans-per-step the fused listener path emits, must be
        under 1% of the measured fused step time. Computed (not A/B
        timed) so the bound is deterministic on shared CI hardware; the
        real off-vs-on A/B lives in bench.py's tracer_overhead config."""
        from deeplearning4j_tpu.dataset.iterators import \
            ArrayDataSetIterator
        disable_tracing()
        n = 50_000
        t0 = time.perf_counter()
        for _ in range(n):
            with TRACER.span("x", cat="c", k=8):
                pass
        per_span_s = (time.perf_counter() - t0) / n
        assert TRACER.spans() == []     # truly recorded nothing
        # fused K=8 listener path: window + data_wait + dispatch +
        # (flush + h2d_stage amortized) ≈ 5 spans per 8 steps
        spans_per_step = 5.0 / 8.0
        X, Y = _data()
        sd = _build_mlp(fused_steps=8)
        it = ArrayDataSetIterator(X, Y, batch_size=16)
        mon = MonitorListener(StatsStorage())
        sd.fit(it, epochs=1, listeners=[mon])          # compile
        t0 = time.perf_counter()
        sd.fit(it, epochs=2, listeners=[mon])
        step_s = (time.perf_counter() - t0) / 16
        overhead = per_span_s * spans_per_step / step_s
        assert overhead < 0.01, (
            f"disabled tracer {1e9 * per_span_s:.0f} ns/span = "
            f"{100 * overhead:.3f}% of a {1e3 * step_s:.3f} ms step")


class TestRegistry:
    def test_counter_gauge_histogram_and_labels(self):
        reg = MetricsRegistry()
        reg.inc("requests_total", 2, help="reqs", mode="batched")
        reg.inc("requests_total", 3, mode="batched")
        reg.inc("requests_total", 1, mode="inplace")
        reg.set_gauge("depth", 7.5)
        reg.observe("latency_seconds", 0.02)
        reg.observe("latency_seconds", 4.0)
        assert reg.get("requests_total", mode="batched") == 5
        assert reg.get("requests_total", mode="inplace") == 1
        assert reg.get("absent") is None
        flat = reg.collect()
        assert flat['dl4j_requests_total{mode="batched"}'] == 5
        assert flat["dl4j_depth"] == 7.5
        assert flat["dl4j_latency_seconds_count"] == 2
        assert flat["dl4j_latency_seconds_sum"] == pytest.approx(4.02)

    def test_counter_cannot_decrease_or_change_kind(self):
        reg = MetricsRegistry()
        reg.inc("a", 1)
        with pytest.raises(ValueError):
            reg.inc("a", -1)
        with pytest.raises(ValueError):
            reg.set_gauge("a", 2)

    def test_prometheus_text_parses(self):
        reg = MetricsRegistry()
        reg.inc("events_total", 3, help='has "quotes" and\nnewline',
                event="rollback")
        reg.set_gauge("up", 1)
        reg.observe("commit_seconds", 0.5, stage="commit")
        text = reg.to_prometheus_text()
        sample_re = re.compile(
            r'^[a-zA-Z_:][a-zA-Z0-9_:]*'               # metric name
            r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'
            r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*\})?'
            r' -?[0-9.eE+-]+(\n|$)')
        seen_types = {}
        for line in text.strip().splitlines():
            if line.startswith("# TYPE"):
                _, _, name, kind = line.split()
                seen_types[name] = kind
                continue
            if line.startswith("# HELP"):
                assert "\n" not in line
                continue
            assert sample_re.match(line), line
        assert seen_types["dl4j_events_total"] == "counter"
        assert seen_types["dl4j_up"] == "gauge"
        assert seen_types["dl4j_commit_seconds"] == "histogram"
        # histogram exposes cumulative le buckets ending at +Inf
        bucket_lines = [l for l in text.splitlines()
                        if l.startswith("dl4j_commit_seconds_bucket")]
        assert bucket_lines and 'le="+Inf"' in bucket_lines[-1]
        counts = [int(l.rsplit(" ", 1)[1]) for l in bucket_lines]
        assert counts == sorted(counts)          # cumulative
        assert counts[-1] == 1

    def test_fold_serving_and_checkpoint_and_faults(self):
        from deeplearning4j_tpu.serving.metrics import ServingMetrics
        sm = ServingMetrics()
        sm.inc("requests_submitted", 4)
        sm.observe_batch(rows=6, padding=2, exec_ms=1.5)
        sm.observe_request(queue_wait_ms=0.3, e2e_ms=2.0)
        sm.record_failure(RuntimeError("x"))
        reg = MetricsRegistry()
        reg.fold_serving(sm)
        assert reg.get("serving_requests_submitted_total") == 4
        assert reg.get("serving_failures_by_cause_total",
                       cause="RuntimeError") == 1
        assert reg.get("serving_latency_ms", lane="e2e", stat="p50") > 0
        reg.fold_checkpoint({"type": "checkpoint", "step": 3, "bytes": 100,
                             "serialize_seconds": 0.1,
                             "commit_seconds": 0.2, "queue_seconds": 0.0})
        assert reg.get("checkpoint_commits_total") == 1
        assert reg.get("checkpoint_last_step") == 3
        reg.fold_faults([{"event": "rollback", "overhead_s": 0.4},
                         {"event": "retry"}])
        assert reg.get("faults_events_total", event="rollback") == 1
        text = reg.to_prometheus_text()
        assert "dl4j_serving_requests_submitted_total 4" in text

    def test_publish_record(self):
        st = StatsStorage()
        reg = MetricsRegistry()
        reg.inc("n", 1)
        rec = reg.publish(st)
        assert rec["type"] == "metrics"
        assert st.of_type("metrics")[0]["metrics"]["dl4j_n"] == 1

    def test_fold_storage_is_incremental_per_storage(self):
        """Review fix: re-folding a growing storage (the scrape-endpoint
        pattern) must not double-count counter-typed metrics."""
        st = StatsStorage()
        st.put({"type": "checkpoint", "step": 1, "bytes": 10,
                "commit_seconds": 0.1})
        st.put({"type": "faults", "event": "rollback", "overhead_s": 0.2})
        reg = MetricsRegistry()
        reg.fold_storage(st)
        reg.fold_storage(st)                     # same records again
        assert reg.get("checkpoint_commits_total") == 1
        assert reg.get("faults_events_total", event="rollback") == 1
        st.put({"type": "checkpoint", "step": 2, "bytes": 10,
                "commit_seconds": 0.1})
        reg.fold_storage(st)                     # only the new record
        assert reg.get("checkpoint_commits_total") == 2
        assert reg.get("checkpoint_last_step") == 2


class TestStepTime:
    def test_window_rows_groups_children(self):
        tr = enable_tracing(reset=True)
        with tr.span("window", k=4, iteration=0):
            with tr.span("data_wait"):
                pass
            with tr.span("dispatch"):
                pass
            with tr.span("flush"):
                pass
        with tr.span("window", k=2, iteration=4):
            with tr.span("dispatch"):
                pass
        rows = window_rows(tr.spans())
        assert [r["k"] for r in rows] == [4, 2]
        assert rows[0]["flush_s"] > 0 and rows[1]["flush_s"] == 0
        assert all(r["other_s"] >= 0 for r in rows)

    def test_rolling_percentiles(self):
        rp = RollingPercentiles(window=4)
        for v in (1.0, 2.0, 3.0, 4.0):
            rp.add(v)
        assert rp.percentile(0) == 1.0 and rp.percentile(100) == 4.0
        rp.add(100.0)                   # evicts 1.0
        assert rp.percentile(100) == 100.0
        assert rp.percentile(0) == 2.0
        assert len(rp) == 4

    def test_straggler_watcher_flags_spike_and_resets(self):
        st = StatsStorage()
        w = StragglerWatcher(threshold=3.0, alpha=0.5, warmup=3,
                             storage=st)
        for _ in range(6):
            assert w.observe(0.1) is None
        ev = w.observe(1.0, iteration=7, k=4)
        assert ev is not None and ev["ratio"] > 3
        assert st.of_type("steptime")[0]["event"] == "straggler"
        # the spike did not feed the EMA: a same-size spike still flags
        assert w.observe(1.0) is not None
        w.reset()
        assert w.observe(1.0) is None   # warmup restarts

    def test_straggler_threshold_validation(self):
        with pytest.raises(ValueError):
            StragglerWatcher(threshold=1.0)

    def test_flush_carrying_window_not_flagged_as_straggler(self):
        """Review fix: the flush child is a burst sync amortized over
        the whole cadence — the window that happens to carry it must
        not read as a step-time spike.

        Margins are sized for scheduler jitter on a loaded CI host
        (sleeps stretch): the base window sleeps 4 ms so a 1-2 ms
        hiccup stays well under the 6x threshold, while folding the
        80 ms flush in would read as ~5x the whole window — far past
        it — so the regression still trips the assert."""
        tr = enable_tracing(reset=True)
        st = StatsStorage()
        mon = MonitorListener(st, tracer=tr,
                              straggler=StragglerWatcher(
                                  threshold=6.0, warmup=2))
        mon.on_training_start(None)
        it = 0
        for burst in range(6):
            for w in range(4):
                with tr.span("window", k=4, iteration=it):
                    with tr.span("dispatch"):
                        time.sleep(0.004)
                    if w == 3:               # the cadence-crossing window
                        with tr.span("flush"):
                            time.sleep(0.08)  # 20x the dispatch time
                it += 4
            mon.iterations_done(None, 0, list(range(it - 16, it)), [0.0])
        assert mon.straggler.events == [], mon.straggler.events


class TestServingCheckpointSpans:
    def test_serving_lifecycle_spans(self):
        from deeplearning4j_tpu.learning.updaters import Adam
        from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                           MultiLayerNetwork,
                                           NeuralNetConfiguration,
                                           OutputLayer)
        from deeplearning4j_tpu.serving import (InferenceMode,
                                                ParallelInference)
        rng = np.random.default_rng(0)
        conf = (NeuralNetConfiguration.builder().seed(0)
                .updater(Adam(1e-3)).list()
                .layer(DenseLayer(n_out=8, activation="tanh"))
                .layer(OutputLayer(n_out=4, loss_function="MCXENT"))
                .set_input_type(InputType.feed_forward(8))
                .build())
        net = MultiLayerNetwork(conf).init()
        enable_tracing(reset=True)
        pi = ParallelInference(net, mode=InferenceMode.BATCHED,
                               max_batch_size=8, max_delay_ms=1.0)
        x = rng.normal(size=(4, 8)).astype(np.float32)
        y = pi.output(x)
        pi.shutdown()
        assert y.shape == (4, 4)
        names = {s.name for s in TRACER.spans()}
        assert {"serving.enqueue", "serving.batch", "serving.pad",
                "serving.exec", "serving.reply"} <= names
        # idle polls were discarded, not recorded
        batches = [s for s in TRACER.spans()
                   if s.name == "serving.batch"]
        assert all(s.args.get("requests") for s in batches)

    def test_checkpoint_commit_spans(self, tmp_path):
        from deeplearning4j_tpu.checkpoint import CheckpointManager
        sd = _build_mlp()
        enable_tracing(reset=True)
        with CheckpointManager(str(tmp_path), async_write=False) as mgr:
            mgr.save(0, model=sd, blocking=True)
        by_name = {}
        for s in TRACER.spans():
            by_name.setdefault(s.name, []).append(s)
        assert "checkpoint.capture" in by_name
        (commit,) = by_name["checkpoint.commit"]
        (serialize,) = by_name["checkpoint.serialize"]
        assert serialize.parent == commit.sid
        assert commit.args["step"] == 0
        assert commit.args["asynchronous"] is False


class TestReportRendering:
    def test_report_renders_observability_sections(self):
        """Golden render: timeline + breakdown + stragglers + metrics
        sections appear, and unknown record types land in the footer."""
        from deeplearning4j_tpu.ui.report import render_report
        from deeplearning4j_tpu.dataset.iterators import \
            ArrayDataSetIterator
        X, Y = _data()
        sd = _build_mlp(fused_steps=4)
        enable_tracing(reset=True)
        st = StatsStorage()
        sd.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=2,
               listeners=[MonitorListener(st, frequency=10)])
        st.put({"type": "steptime", "event": "straggler", "iteration": 3,
                "step_s": 0.5, "ema_s": 0.1, "ratio": 5.0, "t": 0.0})
        st.put({"type": "from_the_future", "payload": 1})
        html = render_report(st, title="monitored run")
        assert "Step-time breakdown" in html
        assert "Span timeline" in html
        assert "Stragglers (1)" in html
        assert "Metrics (last snapshot" in html
        assert "unrendered record types: from_the_future (1)" in html
        # the stacked chart legend names every stage
        for stage in ("data wait", "dispatch", "flush", "other"):
            assert stage in html
        # known observability types are NOT in the footer
        assert "steptime (" not in html and "metrics (" not in html

    def test_report_without_observability_records_unchanged(self):
        from deeplearning4j_tpu.ui.report import render_report
        html = render_report(StatsStorage())
        assert "Step-time breakdown" not in html
        assert "unrendered record types" not in html


class TestProfilerCorrelation:
    def test_correlate_spans_distributes_device_time(self):
        from deeplearning4j_tpu.profiler.session import (OpProfile,
                                                         ProfilerSession)
        from deeplearning4j_tpu.profiler.xplane import OpTime
        tr = enable_tracing(reset=True)
        sess = ProfilerSession.__new__(ProfilerSession)
        sess.log_dir = "/nonexistent"
        sess.t_start = time.perf_counter()
        with tr.span("window", k=4, iteration=0):
            time.sleep(0.004)
        with tr.span("window", k=4, iteration=4):
            time.sleep(0.004)
        sess.t_stop = time.perf_counter()
        with tr.span("window", k=4, iteration=8):   # outside the capture
            pass
        sess._profile = OpProfile([OpTime("fusion.1", 3, int(6e9),
                                          "fusion")])  # 6 ms device
        out = sess.correlate_spans(tracer=tr)
        assert out["device_total_ms"] == pytest.approx(6.0)
        assert len(out["windows"]) == 2          # capture-bounded
        est = sum(w["device_ms_est"] for w in out["windows"])
        assert est == pytest.approx(6.0, abs=1e-3)
        assert 0 < out["device_utilization"] < 1.5
        # the estimate is attached to the spans for the chrome trace
        spans = [s for s in tr.spans() if s.name == "window"]
        assert "device_ms_est" in spans[0].args
        assert "device_ms_est" not in spans[2].args


class TestProcessSelfTelemetry:
    def test_uptime_and_rss_in_exposition(self):
        reg = MetricsRegistry()
        text = reg.to_prometheus_text()
        m = re.search(r"^dl4j_process_uptime_seconds (\S+)$", text,
                      re.MULTILINE)
        assert m and float(m.group(1)) > 0
        assert "# TYPE dl4j_process_uptime_seconds gauge" in text
        # Linux exposes RSS via /proc; the series is optional elsewhere
        m = re.search(r"^dl4j_process_rss_bytes (\S+)$", text,
                      re.MULTILINE)
        if m is not None:
            assert float(m.group(1)) > 1 << 20
        # synthesized at scrape time, never stored as registry state
        assert reg.get("process_uptime_seconds") is None

    def test_uptime_monotonic_across_scrapes(self):
        reg = MetricsRegistry()

        def uptime():
            text = reg.to_prometheus_text()
            return float(re.search(
                r"^dl4j_process_uptime_seconds (\S+)$", text,
                re.MULTILINE).group(1))

        a = uptime()
        time.sleep(0.01)
        assert uptime() >= a


class TestHistogramInvariants:
    def test_inf_bucket_count_equals_count_for_every_histogram(self):
        """Satellite: for EVERY exported histogram the +Inf bucket's
        cumulative count equals its _count sample — the invariant
        Prometheus clients assume; a drift means observations leaked
        past the bucket ladder."""
        reg = MetricsRegistry()
        # several histogram families with different bucket ladders,
        # labels, and out-of-range observations
        for v in (1e-6, 0.02, 3.0, 500.0, 1e9):
            reg.observe("latency_seconds", v, lane="a")
            reg.observe("latency_seconds", v * 2, lane="b")
        reg.observe("ratio_dist", 1e-12, buckets=(0.1, 1.0))
        reg.observe("ratio_dist", 5.0, buckets=(0.1, 1.0))
        reg.inc("noise_total", 3)
        text = reg.to_prometheus_text()
        # parse every histogram series: {base{labels}: {le: cum}}
        bucket_re = re.compile(
            r'^(\w+)_bucket\{(.*?)le="([^"]+)"\} (\d+)$')
        count_re = re.compile(r"^(\w+)_count(\{.*\})? (\d+)$")
        buckets, counts = {}, {}
        for line in text.splitlines():
            mb = bucket_re.match(line)
            if mb:
                key = (mb.group(1), mb.group(2))
                buckets.setdefault(key, {})[mb.group(3)] = \
                    int(mb.group(4))
            mc = count_re.match(line)
            if mc:
                counts[(mc.group(1),
                        (mc.group(2) or "{}").strip("{}").rstrip(","))] \
                    = int(mc.group(3))
        assert buckets, "no histograms exported"
        for (name, labels), series in buckets.items():
            assert "+Inf" in series, (name, labels)
            ckey = (name, labels.rstrip(","))
            assert ckey in counts, (name, labels, sorted(counts))
            assert series["+Inf"] == counts[ckey], (name, labels)
            # cumulative le semantics: monotone nondecreasing
            ordered = [series[k] for k in series if k != "+Inf"]
            assert all(a <= b for a, b in zip(ordered, ordered[1:]))


# The PR-8 record-type lint moved to tests/test_static_lint.py (ISSUE
# 12 satellite), where it grew bare-except and traced-path-RNG lints
# alongside it.
