"""Zoo wave 2 + dataset fetchers + dynamic batching + megatron TP.

Overfit-sanity per zoo model mirrors the reference's
IntegrationTestRunner.java:84 methodology (train briefly on a tiny
separable set; loss must fall).
"""
import numpy as np
import pytest

from deeplearning4j_tpu.dataset import (Cifar10DataSetIterator,
                                        EmnistDataSetIterator, load_cifar10,
                                        load_emnist)
from deeplearning4j_tpu.learning.updaters import Adam
from deeplearning4j_tpu.zoo import (Darknet19, SqueezeNet, TinyYOLO, UNet,
                                    Xception)


def test_cifar10_loader_and_iterator():
    X, y = load_cifar10(train=True, n_synthetic=256)
    assert X.shape == (256, 3, 32, 32) and X.dtype == np.float32
    assert X.min() >= 0 and X.max() <= 1
    assert y.shape == (256,)
    it = Cifar10DataSetIterator(batch_size=64, n_synthetic=256)
    xb, yb = next(iter(it))
    assert xb.shape == (64, 3, 32, 32) and yb.shape == (64, 10)


def test_emnist_loader_splits():
    X, y = load_emnist("letters", n_synthetic=128)
    assert X.shape == (128, 1, 28, 28)
    assert y.max() < 26
    it = EmnistDataSetIterator("balanced", batch_size=32, n_synthetic=128)
    xb, yb = next(iter(it))
    assert yb.shape == (32, 47)
    with pytest.raises(ValueError, match="unknown EMNIST split"):
        load_emnist("nope")


def _overfit(net, X, Y, epochs, lr_msg=""):
    h = net.fit(X, Y, epochs=epochs, batch_size=len(X))
    losses = h.loss_curve.losses
    assert np.isfinite(losses).all(), lr_msg
    assert losses[-1] < losses[0], (lr_msg, losses[0], losses[-1])
    return h


def test_squeezenet_overfit_sanity():
    rng = np.random.RandomState(0)
    X = rng.rand(8, 3, 48, 48).astype(np.float32)
    Y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 8)]
    net = SqueezeNet(height=48, width=48, num_classes=3,
                     updater=Adam(3e-3)).build()
    _overfit(net, X, Y, epochs=8, lr_msg="squeezenet")


def test_unet_overfit_sanity():
    rng = np.random.RandomState(1)
    X = rng.rand(4, 1, 32, 32).astype(np.float32)
    Y = (X > 0.5).astype(np.float32)         # per-pixel target
    net = UNet(height=32, width=32, channels=1, features=4,
               updater=Adam(3e-3)).build()
    _overfit(net, X, Y, epochs=8, lr_msg="unet")
    out = net.output(X[:2])
    out = out[0] if isinstance(out, list) else out
    assert np.asarray(out.data).shape == (2, 1, 32, 32)


def test_xception_overfit_sanity():
    rng = np.random.RandomState(2)
    X = rng.rand(6, 3, 71, 71).astype(np.float32)
    Y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 6)]
    net = Xception(height=71, width=71, num_classes=2, middle_blocks=1,
                   updater=Adam(1e-3)).build()
    _overfit(net, X, Y, epochs=6, lr_msg="xception")


def test_darknet19_overfit_sanity():
    rng = np.random.RandomState(3)
    X = rng.rand(8, 3, 32, 32).astype(np.float32)
    Y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 8)]
    net = Darknet19(height=32, width=32, num_classes=3,
                    updater=Adam(3e-3)).build()
    _overfit(net, X, Y, epochs=8, lr_msg="darknet19")


# priced out of the tier-1 wall budget (ROADMAP tier-1 verify runs under timeout 870s); still pinned by the slow tier
@pytest.mark.slow
def test_tinyyolo_trains():
    rng = np.random.RandomState(4)
    B, C = 4, 2
    net = TinyYOLO(height=64, width=64, num_classes=C,
                   anchors=(1.0, 1.0, 2.0, 2.0), updater=Adam(3e-3)).build()
    X = rng.rand(B, 3, 64, 64).astype(np.float32)
    labels = np.zeros((B, 4 + C, 2, 2), np.float32)   # 64/32 = 2x2 grid
    labels[:, 0:4, 1, 1] = np.array([0.5, 0.5, 1.5, 1.5], np.float32)
    labels[:, 4, 1, 1] = 1.0
    # the exp(wh) term spikes in early epochs before settling — judge on
    # the settled tail, matching how detection training actually behaves
    h = net.fit(X, labels, epochs=15, batch_size=B)
    losses = h.loss_curve.losses
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_batched_parallel_inference():
    from concurrent.futures import wait
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.parallel import BatchedParallelInference
    net = MultiLayerNetwork(
        NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-3)).list()
        .layer(DenseLayer(n_out=8, activation="relu"))
        .layer(OutputLayer(n_out=3, loss_function="MCXENT"))
        .set_input_type(InputType.feed_forward(4)).build()).init()
    rng = np.random.RandomState(0)
    X = rng.randn(20, 4).astype(np.float32)
    want = np.asarray(net.output(X).data)

    srv = BatchedParallelInference(net, max_batch_size=16, max_wait_ms=20.0)
    try:
        futs = [srv.submit(X[i:i + 2]) for i in range(0, 20, 2)]
        wait(futs, timeout=30)
        got = np.concatenate([f.result() for f in futs])
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
        # coalescing happened: far fewer device batches than requests
        assert srv.batches_dispatched < len(futs)
        assert srv.requests_served == len(futs)
    finally:
        srv.close()


def test_megatron_tp_rules_alternate():
    import jax
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.parallel import (DeviceMesh,
                                             megatron_data_and_tensor_parallel)
    net = MultiLayerNetwork(
        NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-3)).list()
        .layer(DenseLayer(n_out=16, activation="relu"))
        .layer(DenseLayer(n_out=16, activation="relu"))
        .layer(OutputLayer(n_out=4, loss_function="MCXENT"))
        .set_input_type(InputType.feed_forward(8)).build()).init()
    mesh = DeviceMesh.create(jax.devices()[:4], data=2, model=2)
    st = megatron_data_and_tensor_parallel(mesh, net)
    from jax.sharding import PartitionSpec as P
    # layer0 column, layer1 row, layer2 (out) column again
    assert st.param_spec("layer0_dense_W", 2) == P(None, "model")
    assert st.param_spec("layer1_dense_W", 2) == P("model", None)
    assert st.param_spec("layer1_dense_b", 1) == P(None)
    assert st.param_spec("layer2_out_W", 2) == P(None, "model")
    # numerics equal to single-device under the sharded strategy
    from deeplearning4j_tpu.parallel import ParallelTrainer
    rng = np.random.RandomState(0)
    X = rng.randn(8, 8).astype(np.float32)
    Y = np.eye(4, dtype=np.float32)[rng.randint(0, 4, 8)]
    ref = MultiLayerNetwork(net.conf).init()
    h_ref = ref.fit(X, Y, epochs=3, batch_size=8)
    tr = ParallelTrainer(net, st)
    h_tp = tr.fit([(X, Y)], epochs=3)
    np.testing.assert_allclose(h_tp.loss_curve.losses,
                               h_ref.loss_curve.losses, rtol=1e-4,
                               atol=1e-6)
