"""Evaluation + dataset pipeline tests (reference: nd4j evaluation tests +
dataset iterator/normalizer tests)."""
import numpy as np
import pytest

from deeplearning4j_tpu.dataset import (
    ArrayDataSetIterator, AsyncDataSetIterator, BenchmarkDataSetIterator,
    DataSet, DeviceCachedIterator, EarlyTerminationIterator,
    ImagePreProcessingScaler, ListDataSetIterator, MnistDataSetIterator,
    MultipleEpochsIterator, NormalizerMinMaxScaler, NormalizerStandardize,
    SamplingDataSetIterator, synthetic_mnist)
from deeplearning4j_tpu.evaluation import (
    Evaluation, EvaluationBinary, ROC, ROCMultiClass, RegressionEvaluation)


# ---- evaluation -----------------------------------------------------------

def test_evaluation_accuracy_and_confusion():
    ev = Evaluation()
    labels = np.eye(3)[[0, 0, 1, 1, 2, 2]]
    preds = np.eye(3)[[0, 1, 1, 1, 2, 0]]  # 4/6 correct
    ev.eval(labels, preds)
    assert ev.accuracy() == pytest.approx(4 / 6)
    cm = ev.confusion_matrix()
    assert cm[0, 0] == 1 and cm[0, 1] == 1 and cm[2, 0] == 1
    assert "Accuracy" in ev.stats()


def test_evaluation_precision_recall_f1_per_class():
    ev = Evaluation()
    # class 0: tp=2 fp=1 fn=0 → precision 2/3, recall 1
    labels = np.eye(2)[[0, 0, 1, 1]]
    preds = np.eye(2)[[0, 0, 0, 1]]
    ev.eval(labels, preds)
    assert ev.precision(0) == pytest.approx(2 / 3)
    assert ev.recall(0) == pytest.approx(1.0)
    assert ev.f1(0) == pytest.approx(0.8)
    assert ev.recall(1) == pytest.approx(0.5)


def test_evaluation_accumulates_across_batches():
    ev = Evaluation()
    for _ in range(3):
        ev.eval(np.eye(2)[[0, 1]], np.eye(2)[[0, 1]])
    assert ev.accuracy() == 1.0
    assert ev._count == 6


def test_evaluation_int_labels_and_top_n():
    ev = Evaluation(top_n=2)
    scores = np.array([[0.5, 0.3, 0.2],
                       [0.1, 0.45, 0.45],
                       [0.2, 0.5, 0.3]])
    ev.eval(np.array([0, 2, 2]), scores)
    assert ev.accuracy() == pytest.approx(1 / 3)
    assert ev.top_n_accuracy() == pytest.approx(3 / 3)


def test_matthews_correlation_perfect_and_random():
    ev = Evaluation()
    ev.eval(np.eye(2)[[0, 1, 0, 1]], np.eye(2)[[0, 1, 0, 1]])
    assert ev.matthews_correlation() == pytest.approx(1.0)


def test_evaluation_binary():
    ev = EvaluationBinary()
    labels = np.array([[1], [1], [0], [0]])
    preds = np.array([[0.9], [0.4], [0.2], [0.7]])
    ev.eval(labels, preds)
    assert ev.accuracy() == pytest.approx(0.5)
    assert ev.precision() == pytest.approx(0.5)
    assert ev.recall() == pytest.approx(0.5)


def test_roc_auc_perfect_and_chance():
    roc = ROC()
    roc.eval(np.array([0, 0, 1, 1]), np.array([0.1, 0.2, 0.8, 0.9]))
    assert roc.auc() == pytest.approx(1.0)
    roc2 = ROC()
    rng = np.random.default_rng(0)
    y = rng.integers(0, 2, 2000)
    roc2.eval(y, rng.uniform(size=2000))
    assert abs(roc2.auc() - 0.5) < 0.05


def test_roc_multiclass():
    rng = np.random.default_rng(1)
    y = rng.integers(0, 3, 300)
    scores = np.eye(3)[y] * 2 + rng.normal(size=(300, 3))
    e = np.exp(scores)
    p = e / e.sum(-1, keepdims=True)
    roc = ROCMultiClass()
    roc.eval(y, p)
    assert roc.average_auc() > 0.8


def test_regression_evaluation():
    ev = RegressionEvaluation()
    y = np.array([[1.0], [2.0], [3.0]])
    p = np.array([[1.1], [2.1], [2.9]])
    ev.eval(y, p)
    assert ev.mean_squared_error(0) == pytest.approx(0.01, abs=1e-6)
    assert ev.mean_absolute_error(0) == pytest.approx(0.1, abs=1e-6)
    assert ev.r_squared(0) > 0.97
    assert ev.pearson_correlation(0) > 0.99
    assert "MSE" in ev.stats()


def test_network_evaluate_end_to_end():
    from deeplearning4j_tpu.learning.updaters import Adam
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration, OutputLayer)
    X = np.tile(np.array([[0, 0], [0, 1], [1, 0], [1, 1]], np.float32), (8, 1))
    Y = np.eye(2, dtype=np.float32)[
        (X[:, 0].astype(int) ^ X[:, 1].astype(int))]
    conf = (NeuralNetConfiguration.builder().seed(7)
            .updater(Adam(learning_rate=0.05)).list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=2))
            .set_input_type(InputType.feed_forward(2)).build())
    net = MultiLayerNetwork(conf).init()
    net.fit(X, Y, epochs=60, batch_size=16)
    ev = net.evaluate(X, Y)
    assert ev.accuracy() == 1.0
    assert ev.f1() == 1.0


# ---- dataset --------------------------------------------------------------

def test_dataset_shuffle_split_batch():
    X = np.arange(20).reshape(10, 2).astype(float)
    Y = np.arange(10)
    ds = DataSet(X, Y)
    tr, te = ds.split_test_and_train(0.8, seed=0)
    assert tr.num_examples() == 8 and te.num_examples() == 2
    sh = ds.shuffle(seed=1)
    assert not np.array_equal(sh.features, X)
    assert sorted(sh.labels.tolist()) == sorted(Y.tolist())
    batches = ds.batch_by(4)
    assert [b.num_examples() for b in batches] == [4, 4, 2]


def test_dataset_save_load(tmp_path):
    ds = DataSet(np.ones((4, 3)), np.zeros((4, 2)))
    path = tmp_path / "ds.npz"
    ds.save(path)
    ds2 = DataSet.load(path)
    np.testing.assert_array_equal(ds.features, ds2.features)


def test_array_iterator_shuffles_between_epochs():
    X = np.arange(16).reshape(8, 2).astype(float)
    Y = np.arange(8)
    it = ArrayDataSetIterator(X, Y, batch_size=4, shuffle=True, seed=0)
    e1 = np.concatenate([b[1] for b in it])
    e2 = np.concatenate([b[1] for b in it])
    assert sorted(e1.tolist()) == sorted(e2.tolist()) == list(range(8))
    assert not np.array_equal(e1, e2)


def test_device_cached_iterator_yields_device_slices():
    import jax
    X = np.random.default_rng(0).normal(size=(64, 4)).astype(np.float32)
    Y = np.eye(2, dtype=np.float32)[np.zeros(64, int)]
    it = DeviceCachedIterator(X, Y, batch_size=16)
    batches = list(it)
    assert len(batches) == 4
    assert isinstance(batches[0][0], jax.Array)
    np.testing.assert_allclose(np.asarray(batches[1][0]), X[16:32])


def test_device_cached_iterator_trains():
    from deeplearning4j_tpu.learning.updaters import Adam
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration, OutputLayer)
    X = np.tile(np.array([[0, 0], [0, 1], [1, 0], [1, 1]], np.float32), (8, 1))
    Y = np.eye(2, dtype=np.float32)[
        (X[:, 0].astype(int) ^ X[:, 1].astype(int))]
    conf = (NeuralNetConfiguration.builder().seed(7)
            .updater(Adam(learning_rate=0.05)).list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=2))
            .set_input_type(InputType.feed_forward(2)).build())
    net = MultiLayerNetwork(conf).init()
    h = net.fit(DeviceCachedIterator(X, Y, batch_size=16), epochs=50)
    assert h.final_loss() < 0.1


def test_async_iterator_matches_sync():
    X = np.arange(32).reshape(16, 2).astype(float)
    Y = np.arange(16)
    sync = ArrayDataSetIterator(X, Y, batch_size=4)
    out_sync = [b[1].tolist() for b in sync]
    out_async = [b[1].tolist() for b in AsyncDataSetIterator(
        ArrayDataSetIterator(X, Y, batch_size=4))]
    assert out_sync == out_async


def test_async_iterator_propagates_errors():
    class Bad:
        def __iter__(self):
            yield (np.zeros(1), np.zeros(1))
            raise RuntimeError("boom")

    with pytest.raises(RuntimeError, match="boom"):
        list(AsyncDataSetIterator(Bad()))


def test_utility_iterators():
    X = np.zeros((8, 2)); Y = np.zeros(8)
    base = ArrayDataSetIterator(X, Y, batch_size=4)
    assert len(list(MultipleEpochsIterator(base, 3))) == 6
    assert len(list(EarlyTerminationIterator(base, 1))) == 1
    bench = BenchmarkDataSetIterator((16, 3), 4, n_batches=5)
    batches = list(bench)
    assert len(batches) == 5 and batches[0][0].shape == (16, 3)
    ds = DataSet(np.arange(10.0).reshape(10, 1), np.arange(10))
    samp = list(SamplingDataSetIterator(ds, 4, 3, seed=0))
    assert len(samp) == 3 and samp[0][0].shape == (4, 1)


def test_normalizer_standardize_round_trip(tmp_path):
    rng = np.random.default_rng(0)
    X = rng.normal(5.0, 3.0, size=(100, 4)).astype(np.float32)
    norm = NormalizerStandardize().fit(X)
    t = norm.transform(X)
    assert abs(t.mean()) < 0.05 and abs(t.std() - 1) < 0.05
    np.testing.assert_allclose(norm.revert(t), X, rtol=1e-4, atol=1e-4)
    path = tmp_path / "norm.npz"
    norm.save(path)
    norm2 = NormalizerStandardize.load(path)
    np.testing.assert_allclose(norm2.transform(X), t, rtol=1e-6)


def test_normalizer_fits_from_iterator():
    X = np.random.default_rng(1).normal(2.0, 1.0, size=(64, 3))
    it = ArrayDataSetIterator(X, np.zeros(64), batch_size=16)
    norm = NormalizerStandardize().fit(it)
    np.testing.assert_allclose(norm.mean, X.mean(0), rtol=1e-6)


def test_min_max_scaler():
    X = np.array([[0.0, 10.0], [5.0, 20.0], [10.0, 30.0]])
    norm = NormalizerMinMaxScaler().fit(X)
    t = norm.transform(X)
    assert t.min() == 0.0 and t.max() == 1.0
    np.testing.assert_allclose(norm.revert(t), X, rtol=1e-6)


def test_image_scaler():
    X = np.array([[0, 127.5, 255]])
    s = ImagePreProcessingScaler()
    np.testing.assert_allclose(s.transform(X), [[0, 0.5, 1.0]])
    np.testing.assert_allclose(s.revert(s.transform(X)), X)


def test_mnist_iterator_synthetic_learnable():
    it = MnistDataSetIterator(batch_size=64, n_synthetic=256)
    f, l = next(iter(it))
    assert f.shape == (64, 1, 28, 28) and l.shape == (64, 10)
    assert f.min() >= 0 and f.max() <= 1
    # classes are visually distinct — a linear probe separates them
    X, y = synthetic_mnist(512)
    from deeplearning4j_tpu.learning.updaters import Adam
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration, OutputLayer)
    conf = (NeuralNetConfiguration.builder().seed(0)
            .updater(Adam(learning_rate=0.01)).list()
            .layer(OutputLayer(n_out=10))
            .set_input_type(InputType.convolutional(28, 28, 1)).build())
    net = MultiLayerNetwork(conf).init()
    net.fit(X, np.eye(10, dtype=np.float32)[y], epochs=30, batch_size=128)
    assert (net.predict(X) == y).mean() > 0.9
