"""analyze/ — pre-compile static analysis (docs/static_analysis.md).

Structure mirrors the acceptance contract:
- a seeded-defect corpus: one deliberately broken graph/config per
  cataloged rule, each caught with the RIGHT rule_id and variable/op
  provenance (and the corpus keys are asserted == the catalog, so a
  new rule without a seeded defect fails here);
- a zero-false-positive sweep over the zoo/bench model families
  (no error- or warn-severity findings on healthy models);
- strict mode raises GraphAnalysisError BEFORE any XLA compile
  (asserted via the compilecache COMPILE_STATS counters);
- integration: fit()/precompile() caching, ParallelInference, the CLI,
  the {"type": "analysis"} record (render + registry fold), and the
  PR-12 satellites (loss f32 accumulators, ShardingSpec.validate).
"""
import json
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.analyze import (RULES, AnalysisReport,
                                        GraphAnalysisError,
                                        GraphAnalysisWarning,
                                        analyze_inference,
                                        analyze_training)
from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig
from deeplearning4j_tpu.autodiff.training import MixedPrecision
from deeplearning4j_tpu.learning.updaters import Adam
from deeplearning4j_tpu.ops import registry as op_registry

rng = np.random.default_rng(0)


def _tc(**kw):
    kw.setdefault("updater", Adam(learning_rate=1e-3))
    kw.setdefault("data_set_feature_mapping", ["x"])
    kw.setdefault("data_set_label_mapping", ["labels"])
    return TrainingConfig(**kw)


def _mlp(sd=None, n_in=20, hidden=8, n_out=4, w0_rows=None,
         batch=(-1,)):
    """A small healthy MLP graph; ``w0_rows`` seeds a shape defect."""
    sd = sd or SameDiff()
    x = sd.placeholder("x", shape=tuple(batch) + (n_in,))
    w0 = sd.var("w0", value=rng.normal(
        0, 0.1, (w0_rows or n_in, hidden)).astype(np.float32))
    b0 = sd.var("b0", value=np.zeros(hidden, np.float32))
    h = sd.nn.relu(x.mmul(w0, name="h0_mm").add(b0), name="h0")
    w1 = sd.var("w1", value=rng.normal(
        0, 0.1, (hidden, n_out)).astype(np.float32))
    logits = h.mmul(w1, name="logits")
    labels = sd.placeholder("labels", shape=tuple(batch) + (n_out,))
    sd.loss.softmax_cross_entropy(logits, labels, name="loss")
    sd.set_loss_variables(["loss"])
    sd.training_config = _tc()
    return sd


class _lowp_loss_op:
    """Context manager registering a deliberately-broken loss op whose
    scalar accumulates in the input dtype (the defect the ops/loss.py
    satellite removed from the real loss ops) — and UNREGISTERING it
    after, so the op-coverage ledger (test_op_ledger) never sees a
    test-only op in the global registry."""

    NAME = "_test_lowp_accum_loss"

    def __enter__(self):
        if not op_registry.has_op(self.NAME):
            @op_registry.op(self.NAME, "loss")
            def _test_lowp_accum_loss(predictions, labels):
                return jnp.sum(jnp.abs(predictions - labels))
        return self.NAME

    def __exit__(self, *exc):
        op_registry._REGISTRY.pop(self.NAME, None)


# ---------------------------------------------------------------------------
# seeded-defect corpus: rule_id -> builder returning
# (report, expected-subject substring, expected-message substring)

def _seed_shape_mismatch():
    sd = _mlp(w0_rows=13)
    return analyze_training(sd), "h0_mm", "cannot compose"


def _seed_undefined_input():
    sd = _mlp()
    sd._ops["logits"].inputs[0] = "ghost"   # serde-corruption analogue
    return analyze_training(sd), "logits", "ghost"


def _seed_invalid_loss():
    sd = _mlp()
    sd.set_loss_variables(["not_a_var"])
    return analyze_training(sd), "not_a_var", "does not exist"


def _seed_unused_placeholder():
    sd = _mlp()
    sd.placeholder("extra_feature", shape=(-1, 3))
    return analyze_training(sd), "extra_feature", "not consumed"


def _seed_name_shadowing():
    sd = SameDiff()
    a = sd.placeholder("x", shape=(-1, 4))
    b = sd.placeholder("x", shape=(-1, 4))      # auto-renamed to x_1
    sd.loss.mean_sqerr_loss(a, b, name="loss")
    sd.set_loss_variables(["loss"])
    return analyze_training(sd), "x_1", "auto-renamed"


def _seed_dead_op():
    sd = _mlp()
    # a recorded penalty the user forgot to add to loss_variables
    sd.loss.l2_loss(sd.get_variable("w0"), name="l2_penalty")
    return analyze_training(sd), "l2_penalty", "trains nothing"


def _seed_state_alias():
    sd = _mlp()
    sv = sd.state_var("running_mean", np.zeros(8, np.float32))
    sd._state_updates[sv.name] = "missing_src"   # update_state analogue
    return analyze_training(sd), "running_mean", "does not exist"


def _seed_lowp_loss_accum():
    with _lowp_loss_op() as op_name:
        sd = SameDiff()
        p = sd.placeholder("x", shape=(-1, 16), dtype="bfloat16")
        l = sd.placeholder("labels", shape=(-1, 16), dtype="bfloat16")
        sd.invoke(op_name, [p, l], name="loss")
        sd.set_loss_variables(["loss"])
        return analyze_training(sd), "loss", "scalar"


def _seed_lowp_reduction():
    sd = SameDiff()
    x = sd.placeholder("x", shape=(4, 8192), dtype="bfloat16")
    s = x.sum(dims=(1,), name="big_sum")
    s.mean(name="loss")
    sd.set_loss_variables(["loss"])
    return analyze_training(sd), "big_sum", "8192"


def _seed_unguarded_log():
    sd = SameDiff()
    x = sd.placeholder("x", shape=(-1, 4))
    x.log(name="raw_log").mean(name="loss")
    sd.set_loss_variables(["loss"])
    return analyze_training(sd), "raw_log", "positivity"


def _seed_unguarded_div():
    sd = SameDiff()
    x = sd.placeholder("x", shape=(-1, 4))
    d = sd.placeholder("denom", shape=(-1, 4))
    x.div(d, name="raw_div").mean(name="loss")
    sd.set_loss_variables(["loss"])
    return analyze_training(sd), "raw_div", "zero guard"


def _seed_ce_tail_f32():
    sd = _mlp()
    sd.training_config = _tc(mixed_precision=MixedPrecision())
    return analyze_training(sd), "loss", "f32 under bf16"


def _seed_mapping_unknown():
    sd = _mlp()
    sd.training_config = _tc(data_set_feature_mapping=["nope"])
    return analyze_training(sd), "nope", "not in the graph"


def _seed_mapping_incomplete():
    sd = _mlp()
    sd.training_config = _tc(data_set_feature_mapping=["x"],
                             data_set_label_mapping=[])
    return analyze_training(sd), "labels", "neither feature nor label"


def _seed_cadence_misalignment():
    sd = _mlp()
    sd.training_config = _tc(fused_steps=6, accum_steps=4)
    return analyze_training(sd), "fused_steps=6", "not a multiple"


def _seed_donation_conflict():
    sd = _mlp()
    sd.set_loss_variables(["w0"])
    return analyze_training(sd), "w0", "no gradient"


def _seed_sharding_invalid():
    from deeplearning4j_tpu.parallel.sharding import ShardingSpec
    sd = _mlp()
    sd.training_config = _tc(
        sharding=ShardingSpec(axes={"data": -1, "model": 5}))
    return (analyze_training(sd, device_count=8),
            "TrainingConfig.sharding", "multiple of 5")


def _seed_sharding_unmatched_rule():
    from deeplearning4j_tpu.parallel.sharding import (ShardingRule,
                                                      ShardingSpec)
    sd = _mlp()
    sd.training_config = _tc(sharding=ShardingSpec(
        axes={"data": -1},
        rules=[ShardingRule(r"^transformer_block_.*$", (None,))]))
    return (analyze_training(sd, device_count=1),
            "transformer_block", "zero")


def _seed_chaos_armed():
    from types import SimpleNamespace
    sd = _mlp()
    sd.training_config._chaos_spec = SimpleNamespace(nan_grads_at=5)
    return analyze_training(sd), "_chaos_spec", "chaos"


def _seed_tensorstats_unobserved():
    sd = _mlp()
    sd.training_config = _tc(tensorstats=True)
    return (analyze_training(sd, has_listeners=False),
            "tensorstats", "no listeners")


def _seed_dense_kv_exceeds_headroom():
    from deeplearning4j_tpu.analyze import analyze_generative_config
    from deeplearning4j_tpu.serving.generative import GenerativeSpec
    spec = GenerativeSpec(
        params=dict, prefill=None, decode=None,
        kv_shape=lambda slots, seq: (2, slots, 2, seq, 16),
        vocab_size=64, max_seq_len=4096)
    # 64 slots x 4096 positions of f32 KV = 128 MiB vs a 64 MiB budget
    rep = analyze_generative_config(spec, max_slots=64,
                                    headroom_bytes=64 * 2**20)
    assert rep.context == "serving_config" and rep.rules_run == 1
    # the same plan under a roomy budget is clean, and CPU (no device
    # limit -> headroom None) is a no-op like the construction guard
    assert not analyze_generative_config(
        spec, max_slots=64, headroom_bytes=1 << 40).findings
    f = [x for x in rep.findings
         if x.rule_id == "serving.dense_kv_exceeds_headroom"][0]
    assert "paged" in f.fix_hint         # the hint IS the point
    return rep, "kv_slab[64x4096]", "headroom guard"


def _seed_fleet_slo_unreachable():
    from deeplearning4j_tpu.analyze import analyze_fleet_config
    # 100 req/s x 16 tokens x 20ms step = 32 concurrent slots needed,
    # but 2 replicas x 4 slots = 8 -> saturated, queues diverge
    rep = analyze_fleet_config(replicas=2, max_slots=4,
                               p99_decode_step_ms=20.0,
                               ttft_slo_ms=200.0,
                               arrival_rate_rps=100.0)
    assert rep.context == "serving_config" and rep.rules_run == 1
    f = [x for x in rep.findings
         if x.rule_id == "serving.fleet_slo_unreachable"][0]
    assert "replicas" in f.fix_hint      # the hint IS the point
    # a feasible plan (8 replicas x 8 slots = 64 >= 32 needed) is clean
    assert not analyze_fleet_config(
        replicas=8, max_slots=8, p99_decode_step_ms=20.0,
        ttft_slo_ms=200.0, arrival_rate_rps=100.0).findings
    # the floor variant: one decode step longer than the whole SLO
    floor = analyze_fleet_config(replicas=64, max_slots=64,
                                 p99_decode_step_ms=250.0,
                                 ttft_slo_ms=200.0,
                                 arrival_rate_rps=1.0)
    assert any("no replica count" in x.message for x in floor.findings)
    return rep, "fleet[2x4]", "concurrent slots"


def _seed_speculation_misconfig():
    from deeplearning4j_tpu.analyze import analyze_speculation_config
    from deeplearning4j_tpu.serving.generative import GenerativeSpec

    def _fake(vocab, msl, n_params):
        return GenerativeSpec(
            params=lambda: {"w": np.zeros((n_params,), np.float32)},
            prefill=None, decode=None,
            kv_shape=lambda slots, seq: (2, slots, 2, seq, 16),
            vocab_size=vocab, max_seq_len=msl)

    target = _fake(64, 128, 1000)
    # vocab mismatch: the error variant (the server refuses the pairing
    # at construction; the lint names it without building anything)
    rep = analyze_speculation_config(target, _fake(48, 128, 10))
    assert rep.context == "serving_config" and rep.rules_run == 1
    # a too-short draft window is the other error variant
    short = analyze_speculation_config(target, _fake(64, 64, 10))
    assert any(x.severity == "error" and "max_seq_len" in x.subject
               for x in short.findings)
    # a draft as LARGE as its target constructs fine and still emits
    # the target's exact tokens -> DEMOTED to warn, hint names a
    # smaller config
    big = analyze_speculation_config(target, _fake(64, 128, 1000))
    f = [x for x in big.findings
         if x.rule_id == "serving.speculation_misconfig"][0]
    assert f.severity == "warn" and "smaller" in f.fix_hint
    assert not big.errors()
    # a sane pairing is clean
    assert not analyze_speculation_config(target,
                                          _fake(64, 128, 10)).findings
    return rep, "draft_spec.vocab_size", "embedding table"


CORPUS = {
    "graph.shape_mismatch": _seed_shape_mismatch,
    "graph.undefined_input": _seed_undefined_input,
    "graph.invalid_loss": _seed_invalid_loss,
    "graph.unused_placeholder": _seed_unused_placeholder,
    "graph.name_shadowing": _seed_name_shadowing,
    "graph.dead_op": _seed_dead_op,
    "graph.state_alias": _seed_state_alias,
    "numerics.lowp_loss_accum": _seed_lowp_loss_accum,
    "numerics.lowp_reduction": _seed_lowp_reduction,
    "numerics.unguarded_log": _seed_unguarded_log,
    "numerics.unguarded_div": _seed_unguarded_div,
    "numerics.ce_tail_f32": _seed_ce_tail_f32,
    "config.mapping_unknown": _seed_mapping_unknown,
    "config.mapping_incomplete": _seed_mapping_incomplete,
    "config.cadence_misalignment": _seed_cadence_misalignment,
    "config.donation_conflict": _seed_donation_conflict,
    "config.sharding_invalid": _seed_sharding_invalid,
    "config.sharding_unmatched_rule": _seed_sharding_unmatched_rule,
    "config.chaos_armed": _seed_chaos_armed,
    "config.tensorstats_unobserved": _seed_tensorstats_unobserved,
    "serving.dense_kv_exceeds_headroom": _seed_dense_kv_exceeds_headroom,
    "serving.fleet_slo_unreachable": _seed_fleet_slo_unreachable,
    "serving.speculation_misconfig": _seed_speculation_misconfig,
}


class TestSeededDefects:
    def test_corpus_covers_catalog(self):
        """Every cataloged rule has a seeded defect — a rule added
        without one fails HERE, not in production."""
        assert set(CORPUS) == set(RULES)

    @pytest.mark.parametrize("rule_id", sorted(CORPUS))
    def test_rule_catches_seeded_defect(self, rule_id):
        report, subject_sub, message_sub = CORPUS[rule_id]()
        hits = [f for f in report.findings if f.rule_id == rule_id]
        assert hits, (f"{rule_id} not raised; got "
                      f"{[f.rule_id for f in report.findings]}")
        f = hits[0]
        assert f.severity == RULES[rule_id].severity
        assert subject_sub in f.subject, (f.subject, subject_sub)
        assert message_sub in f.message, (f.message, message_sub)

    def test_severity_override_is_demote_only(self):
        """finding(severity=...) may demote a dual-severity rule's hit
        below the catalog, never escalate past it."""
        from deeplearning4j_tpu.analyze.findings import finding
        with pytest.raises(ValueError, match="bad severity"):
            finding("serving.speculation_misconfig", "s", "m",
                    severity="bogus")
        with pytest.raises(ValueError, match="escalates"):
            # the fleet rule is cataloged warn — error would escalate
            finding("serving.fleet_slo_unreachable", "s", "m",
                    severity="error")
        f = finding("serving.speculation_misconfig", "s", "m",
                    severity="warn")
        assert f.severity == "warn"

    def test_shape_mismatch_provenance_names_producers(self):
        report, _, _ = CORPUS["graph.shape_mismatch"]()
        f = [x for x in report.findings
             if x.rule_id == "graph.shape_mismatch"][0]
        prov = "\n".join(f.provenance)
        # the chain names the user's placeholder AND the bad kernel
        # with their inferred shapes — not an XLA frame in sight
        assert "x" in prov and "w0" in prov
        assert "PLACEHOLDER" in prov and "VARIABLE" in prov
        assert "(13, 8)" in prov

    def test_batch_dim_artifacts_are_suppressed(self):
        """A graph valid at ANY batch extent produces no
        shape findings even though -1 dims were substituted."""
        report = analyze_training(_mlp())
        assert not [f for f in report.findings
                    if f.rule_id == "graph.shape_mismatch"]

    def test_weak_typed_constants_do_not_promote(self):
        """Regression (found by the inception-resnet sweep under the
        suite's x64 mode): ``sd.constant(0.17)`` stores a WEAKLY-typed
        scalar that promotes to its partner's dtype at runtime — the
        abstract walk must preserve weak_type, or the scaled-residual
        pattern reports a phantom f64/f32 conv mismatch."""
        sd = SameDiff()
        x = sd.placeholder("x", shape=(-1, 8))
        w = sd.var("w", value=rng.normal(0, 0.1, (8, 8))
                   .astype(np.float32))
        h = x.mmul(w, name="h")
        scaled = h.mul(sd.constant(0.17, "scale_c"), name="scaled")
        res = x.add(scaled, name="residual")       # f32 + scaled
        sd.loss.mean_sqerr_loss(res, x, name="loss")
        sd.set_loss_variables(["loss"])
        report = analyze_training(sd)
        assert not report.errors(), [f.render() for f in report.errors()]


# ---------------------------------------------------------------------------
# zero-false-positive sweep

def _assert_clean(report: AnalysisReport, name: str):
    bad = report.errors() + report.warnings()
    assert not bad, (name, [f.render() for f in bad])


class TestModelSweep:
    """Healthy zoo/bench models must produce ZERO error- or
    warn-severity findings (info hints are allowed). The examples/
    sweep rides test_examples: every example runs with
    GraphAnalysisWarning escalated to an error."""

    def test_bench_mlp(self):
        _assert_clean(analyze_training(_mlp(), has_listeners=True),
                      "bench-style mlp")

    def test_bench_mlp_fused_sentinel_tensorstats(self):
        sd = _mlp()
        sd.training_config = _tc(fused_steps=8, accum_steps=2,
                                 sentinel=True, tensorstats=True)
        _assert_clean(analyze_training(sd, has_listeners=True),
                      "mlp fused+sentinel+tensorstats")

    def test_zoo_lenet(self):
        from deeplearning4j_tpu.zoo import LeNet
        net = LeNet(height=28, width=28, channels=1).build()
        _assert_clean(analyze_training(net.samediff,
                                       has_listeners=True), "lenet")

    def test_zoo_resnet50(self):
        from deeplearning4j_tpu.zoo import ResNet50
        net = ResNet50(height=32, width=32, channels=3,
                       num_classes=4).build()
        _assert_clean(analyze_training(net.samediff,
                                       has_listeners=True),
                      "resnet50 (small input)")

    def test_zoo_lstm_and_transformer(self):
        from deeplearning4j_tpu.zoo import TextGenLSTM, TransformerEncoder
        net = TextGenLSTM(vocab_size=12, timesteps=6, units=8).build()
        _assert_clean(analyze_training(net.samediff,
                                       has_listeners=True), "lstm")
        net = TransformerEncoder(vocab_size=50, max_len=8, d_model=16,
                                 n_layers=2, n_heads=2, d_ff=32,
                                 num_classes=3).build()
        _assert_clean(analyze_training(net.samediff,
                                       has_listeners=True),
                      "transformer encoder")

    def test_zoo_gpt(self):
        from deeplearning4j_tpu.zoo.gpt import GPT_TINY, build_gpt
        sd = build_gpt(GPT_TINY, batch=4, seq_len=16)
        sd.training_config = (
            TrainingConfig.builder().updater(Adam(1e-4))
            .data_set_feature_mapping("input_ids")
            .data_set_label_mapping("targets")
            .mixed_precision(MixedPrecision(softmax_dtype="bfloat16"))
            .build())
        _assert_clean(analyze_training(sd, has_listeners=True),
                      "gpt_tiny bf16")

    def test_zoo_bert(self):
        from deeplearning4j_tpu.zoo.bert import BERT_TINY, bert_base
        sd = bert_base(BERT_TINY, batch=2, seq_len=8, num_labels=2,
                       seed=7)
        _assert_clean(analyze_training(sd, has_listeners=True),
                      "bert_tiny classifier")

    @pytest.mark.slow
    def test_bench_flagship_models_full_size(self):
        """The BENCH-config architectures at their real parameter
        sizes: resnet50@224/1000, bert_base, gpt_medium."""
        from deeplearning4j_tpu.zoo import ResNet50
        from deeplearning4j_tpu.zoo.bert import BERT_BASE, bert_base
        from deeplearning4j_tpu.zoo.gpt import GPT_MEDIUM, build_gpt
        net = ResNet50(height=224, width=224, channels=3,
                       num_classes=1000).build()
        _assert_clean(analyze_training(net.samediff,
                                       has_listeners=True),
                      "resnet50 imagenet")
        sd = bert_base(BERT_BASE, batch=2, seq_len=32, num_labels=2)
        _assert_clean(analyze_training(sd, has_listeners=True),
                      "bert_base")
        sd = build_gpt(GPT_MEDIUM, batch=2, seq_len=64)
        sd.training_config = (
            TrainingConfig.builder().updater(Adam(1e-4))
            .data_set_feature_mapping("input_ids")
            .data_set_label_mapping("targets")
            .mixed_precision(MixedPrecision(softmax_dtype="bfloat16"))
            .build())
        _assert_clean(analyze_training(sd, has_listeners=True),
                      "gpt_medium")

    def test_serving_graph_sweep(self):
        from deeplearning4j_tpu.zoo import LeNet
        net = LeNet(height=28, width=28, channels=1).build()
        sd, ins, outs, sync = net.serving_spec()
        rep = analyze_inference(sd, outputs=outs, inputs=ins)
        _assert_clean(rep, "lenet serving graph")
        assert rep.context == "serving"
        # rules_run counts EXECUTED rules: no config/loss/CE-tail/
        # dead-loss checks on the serving path (review regression)
        from deeplearning4j_tpu.analyze import _INFERENCE_RULES
        assert rep.rules_run == len(_INFERENCE_RULES) == 9
        # ... and a config-less training analysis skips config rules
        # (and the serving-capacity rules, which only run under
        # analyze_generative_config / analyze_fleet_config)
        from deeplearning4j_tpu.analyze import _SERVING_RULES
        bare = SameDiff()
        p = bare.placeholder("p", shape=(-1, 4))
        p.mean(name="loss")
        bare.set_loss_variables(["loss"])
        assert (analyze_training(bare).rules_run
                == len(RULES) - 8 - len(_SERVING_RULES))
        assert len(_SERVING_RULES) == 3


# ---------------------------------------------------------------------------
# integration: fit / precompile / serving / CLI / records

def _iterator(sd, n=32, batch=8, n_in=20, n_out=4):
    X = rng.normal(size=(n, n_in)).astype(np.float32)
    Y = np.eye(n_out, dtype=np.float32)[rng.integers(0, n_out, n)]
    return [(X[i:i + batch], Y[i:i + batch])
            for i in range(0, n, batch)]


class TestFitIntegration:
    def test_strict_raises_before_any_compile(self):
        """The acceptance bar: strict=True fails with named
        diagnostics and ZERO backend compiles (PR-6 counters)."""
        from deeplearning4j_tpu.compilecache import (
            COMPILE_STATS, install_compile_watcher)
        install_compile_watcher()
        sd = _mlp(w0_rows=13)
        sd.training_config.analyze = "strict"
        it = _iterator(sd)
        # warm the tiny eager kernels analysis itself touches
        # (random key construction) so the delta isolates fit()
        analyze_training(_mlp())
        mark = COMPILE_STATS.mark()
        with pytest.raises(GraphAnalysisError) as ei:
            sd.fit(it, epochs=1)
        assert COMPILE_STATS.delta(mark)["backend_compiles"] == 0
        assert "graph.shape_mismatch" in str(ei.value)
        assert sd.last_analysis.errors()

    def test_precompile_strict_raises_before_any_compile(self):
        from deeplearning4j_tpu.compilecache import (
            COMPILE_STATS, install_compile_watcher)
        install_compile_watcher()
        sd = _mlp(w0_rows=13)
        sd.training_config.analyze = "strict"
        analyze_training(_mlp())
        mark = COMPILE_STATS.mark()
        with pytest.raises(GraphAnalysisError):
            sd.precompile(batch_size=8)
        assert COMPILE_STATS.delta(mark)["backend_compiles"] == 0
        # a precompile-triggered analysis stamps its entry point
        assert sd.last_analysis.context == "precompile"

    def test_default_mode_warns_and_proceeds(self):
        sd = _mlp(w0_rows=13)
        it = _iterator(sd)
        with pytest.warns(GraphAnalysisWarning, match="shape_mismatch"):
            with pytest.raises(Exception):
                sd.fit(it, epochs=1)      # XLA still fails, later

    def test_analyze_false_disables(self):
        sd = _mlp(w0_rows=13)
        sd.training_config.analyze = False
        it = _iterator(sd)
        with warnings.catch_warnings():
            warnings.simplefilter("error", GraphAnalysisWarning)
            with pytest.raises(Exception) as ei:
                sd.fit(it, epochs=1)
        assert not isinstance(ei.value, GraphAnalysisError)
        assert sd.last_analysis is None

    def test_analysis_cached_per_graph_version(self):
        """Warm fits pay a dict lookup, not a re-analysis — the
        bench.py analyze_overhead contract."""
        sd = _mlp()
        it = _iterator(sd)
        sd.fit(it, epochs=1)
        first = sd.last_analysis
        assert first is not None and not first.errors()
        sd.fit(it, epochs=1)
        assert sd.last_analysis is first       # same report object
        sd.constant(1.0, "poke")               # graph mutation
        sd.fit(it, epochs=1)
        assert sd.last_analysis is not first

    def test_strict_keeps_refusing_on_repeat_fits(self):
        """Review regression: the cached report must re-enforce
        strict mode — a retry loop around a broken graph cannot slip
        past analysis into the compile on its second attempt."""
        sd = _mlp(w0_rows=13)
        sd.training_config.analyze = "strict"
        it = _iterator(sd)
        with pytest.raises(GraphAnalysisError):
            sd.fit(it, epochs=1)
        first = sd.last_analysis
        with pytest.raises(GraphAnalysisError):
            sd.fit(it, epochs=1)          # cache hit, same refusal
        assert sd.last_analysis is first

    def test_config_mutation_invalidates_analysis_cache(self):
        """Review regression: in-place TrainingConfig mutation (the
        common pattern) must re-analyze — the key is a content
        fingerprint, not the config object's identity."""
        from deeplearning4j_tpu.parallel.sharding import ShardingSpec
        sd = _mlp()
        it = _iterator(sd)
        sd.fit(it, epochs=1)
        assert not sd.last_analysis.errors()
        sd.training_config.sharding = ShardingSpec(
            axes={"data": -1, "model": 5})      # cannot bind
        sd.training_config.analyze = "strict"
        with pytest.raises(GraphAnalysisError) as ei:
            sd.fit(it, epochs=1)
        assert any(f.rule_id == "config.sharding_invalid"
                   for f in ei.value.report.errors())
        # loss_variables changes don't bump the graph version either
        sd2 = _mlp()
        sd2.fit(_iterator(sd2), epochs=1)
        sd2.set_loss_variables(["w0"])
        sd2.training_config.analyze = "strict"
        with pytest.raises(GraphAnalysisError):
            sd2.fit(_iterator(sd2), epochs=1)

    def test_clean_fit_trains_and_is_clean(self):
        sd = _mlp()
        it = _iterator(sd)
        with warnings.catch_warnings():
            warnings.simplefilter("error", GraphAnalysisWarning)
            h = sd.fit(it, epochs=2)
        assert np.isfinite(h.final_loss())
        assert sd.last_analysis is not None
        assert not sd.last_analysis.errors()


class TestServingIntegration:
    def _net(self):
        from deeplearning4j_tpu.zoo import LeNet
        return LeNet(height=8, width=8, channels=1).build()

    def test_parallel_inference_runs_analyzer(self):
        from deeplearning4j_tpu.serving import ParallelInference
        from deeplearning4j_tpu.ui.stats import StatsStorage
        storage = StatsStorage()
        pi = ParallelInference(self._net(), stats_storage=storage,
                               workers=1)
        try:
            assert pi.analysis is not None
            assert not pi.analysis.errors()
            recs = storage.of_type("analysis")
            assert len(recs) == 1
            assert recs[0]["context"] == "serving"
        finally:
            pi.shutdown()

    def test_parallel_inference_strict_raises(self):
        from deeplearning4j_tpu.serving import InferenceMode, \
            ParallelInference

        broken = SameDiff()
        x = broken.placeholder("input", shape=(-1, 6))
        w = broken.var("w", value=np.zeros((5, 2), np.float32))
        x.mmul(w, name="output")

        class FakeModel:
            def serving_spec(self):
                return broken, ["input"], ["output"], lambda: None

        with pytest.raises(GraphAnalysisError):
            ParallelInference(FakeModel(), analyze="strict",
                              mode=InferenceMode.INPLACE)
        with pytest.warns(GraphAnalysisWarning):
            pi = ParallelInference(FakeModel(),
                                   mode=InferenceMode.INPLACE)
            pi.shutdown()


class TestCLI:
    def _save(self, sd, tmp_path, name):
        path = str(tmp_path / name)
        sd.save(path)
        return path

    def test_cli_clean_model_exits_zero(self, tmp_path, capsys):
        from deeplearning4j_tpu.analyze.__main__ import main
        rc = main([self._save(_mlp(), tmp_path, "clean.zip")])
        out = capsys.readouterr().out
        assert rc == 0
        assert "static analysis" in out

    def test_cli_broken_model_exits_one_with_named_finding(
            self, tmp_path, capsys):
        from deeplearning4j_tpu.analyze.__main__ import main
        rc = main([self._save(_mlp(w0_rows=13), tmp_path, "bad.zip")])
        out = capsys.readouterr().out
        assert rc == 1
        assert "graph.shape_mismatch" in out and "h0_mm" in out

    def test_cli_json_record(self, tmp_path, capsys):
        from deeplearning4j_tpu.analyze.__main__ import main
        rc = main([self._save(_mlp(w0_rows=13), tmp_path, "bad.zip"),
                   "--json"])
        rec = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert rec["type"] == "analysis" and rec["context"] == "cli"
        assert rec["counts"]["error"] >= 1
        assert any(f["rule_id"] == "graph.shape_mismatch"
                   for f in rec["findings"])

    def test_cli_strict_fails_on_warns(self, tmp_path):
        from deeplearning4j_tpu.analyze.__main__ import main
        sd = _mlp()
        sd.placeholder("extra", shape=(-1, 2))    # warn-severity only
        path = self._save(sd, tmp_path, "warn.zip")
        assert main([path]) == 0
        assert main([path, "--strict"]) == 1

    def test_cli_rules_catalog(self, capsys):
        from deeplearning4j_tpu.analyze.__main__ import main
        assert main(["--rules"]) == 0
        out = capsys.readouterr().out
        for rid in RULES:
            assert rid in out

    def test_cli_missing_model_usage_error(self, capsys):
        from deeplearning4j_tpu.analyze.__main__ import main
        assert main([]) == 2


class TestRecordsAndReport:
    def test_record_renders_no_footer_leak(self):
        from deeplearning4j_tpu.ui.report import render_report
        from deeplearning4j_tpu.ui.stats import StatsStorage
        report, _, _ = CORPUS["graph.shape_mismatch"]()
        storage = StatsStorage()
        storage.put(report.to_record())
        html = render_report(storage)
        assert "Static analysis" in html
        assert "graph.shape_mismatch" in html
        assert "unrendered record types" not in html

    def test_registry_fold(self):
        from deeplearning4j_tpu.monitor import MetricsRegistry
        report, _, _ = CORPUS["graph.shape_mismatch"]()
        reg = MetricsRegistry()
        reg.fold_analysis(report.to_record())
        text = reg.to_prometheus_text()
        assert 'dl4j_analysis_findings{severity="error"}' in text
        assert "dl4j_analysis_rules_run" in text

    def test_monitor_listener_publishes_once(self):
        from deeplearning4j_tpu.monitor import MonitorListener
        from deeplearning4j_tpu.ui.stats import StatsStorage
        sd = _mlp()
        sd.training_config.fused_steps = 4
        storage = StatsStorage()
        mon = MonitorListener(storage)
        it = _iterator(sd)
        sd.fit(it, epochs=1, listeners=[mon])
        assert len(storage.of_type("analysis")) == 1
        sd.fit(it, epochs=1, listeners=[mon])    # same graph version
        assert len(storage.of_type("analysis")) == 1
        assert 'severity="error"' in \
            mon.registry.to_prometheus_text().replace("'", '"')


class TestSatellites:
    def test_weighted_loss_reductions_f32_accumulator(self):
        """ops/loss.py satellite: the weighted-reduction tails force
        an f32 accumulator under bf16 inputs (PR 6 fixed only the
        dense softmax-CE vocab sum)."""
        from deeplearning4j_tpu.ops.loss import (absolute_difference_loss,
                                                 hinge_loss,
                                                 mean_sqerr_loss)
        p = jnp.linspace(0, 1, 512, dtype=jnp.bfloat16).reshape(64, 8)
        l = jnp.zeros((64, 8), jnp.bfloat16)
        for fn in (absolute_difference_loss, hinge_loss):
            for reduction in ("sum", "mean", "mean_by_weight"):
                out = fn(p, l, reduction=reduction)
                assert out.dtype == jnp.float32, (fn.__name__, reduction)
        # reference value: the f32 accumulation matches a full-f32 run
        # to bf16 input precision
        lo = absolute_difference_loss(p, l, reduction="sum")
        hi = absolute_difference_loss(p.astype(jnp.float32),
                                      l.astype(jnp.float32),
                                      reduction="sum")
        np.testing.assert_allclose(float(lo), float(hi), rtol=1e-2)
        # "none" stays per-element in the compute dtype
        assert absolute_difference_loss(
            p, l, reduction="none").dtype == jnp.bfloat16

    def test_analyzer_reports_builtin_losses_clean_under_bf16(self):
        """The satellite's acceptance: after the f32-accumulator fix,
        the numerics pass reports the real loss ops clean."""
        for loss_op in ("absolute_difference_loss", "mean_sqerr_loss",
                        "hinge_loss", "huber_loss",
                        "softmax_cross_entropy"):
            sd = SameDiff()
            p = sd.placeholder("x", shape=(-1, 16), dtype="bfloat16")
            l = sd.placeholder("labels", shape=(-1, 16),
                               dtype="bfloat16")
            sd.invoke(loss_op, [p, l], name="loss")
            sd.set_loss_variables(["loss"])
            rep = analyze_training(sd)
            assert not [f for f in rep.findings
                        if f.rule_id == "numerics.lowp_loss_accum"], \
                loss_op

    def test_sharding_validate_matches_build_errors(self):
        """ShardingSpec.validate raises the SAME errors build() does,
        without constructing a mesh."""
        from deeplearning4j_tpu.parallel.sharding import (ShardingRule,
                                                          ShardingSpec)
        spec = ShardingSpec(axes={"data": -1, "model": -1})
        with pytest.raises(ValueError, match="one -1"):
            spec.validate(device_count=8)
        with pytest.raises(ValueError, match="one -1"):
            spec.build()
        spec = ShardingSpec(axes={"data": 0})
        with pytest.raises(ValueError, match="positive"):
            spec.validate(device_count=8)
        spec = ShardingSpec(axes={"data": -1}, preset="warp_drive")
        with pytest.raises(ValueError, match="unknown sharding preset"):
            spec.validate()
        with pytest.raises(ValueError, match="unknown sharding preset"):
            spec.build()
        spec = ShardingSpec(axes={"data": -1, "model": 5})
        with pytest.raises(ValueError, match="multiple of 5"):
            spec.validate(device_count=8)
        spec = ShardingSpec(axes={"data": -1}, batch_axes=("warp",))
        with pytest.raises(ValueError, match="batch axis"):
            spec.validate(device_count=8)
        # review regression: a FIXED (fill-free) product exceeding the
        # device count raises DeviceMesh.create's error pre-mesh
        spec = ShardingSpec(axes={"data": 16}, batch_axes=("data",))
        with pytest.raises(ValueError, match="needs 16 devices"):
            spec.validate(device_count=8)
        spec.validate(device_count=16)    # enough devices: fine

    def test_sharding_validate_param_divisibility(self):
        from deeplearning4j_tpu.parallel.sharding import (ShardingRule,
                                                          ShardingSpec)
        spec = ShardingSpec(
            axes={"data": -1, "model": 4},
            rules=[ShardingRule(r"_dense_W$", (None, "model"))])
        # dim 8 % 4 == 0: fine
        spec.validate(params={"l0_dense_W": (16, 8)}, device_count=8)
        with pytest.raises(ValueError, match="not.*divisible|divisible"):
            spec.validate(params={"l0_dense_W": (16, 10)},
                          device_count=8)
        # unmatched params are never constrained
        spec.validate(params={"something_else": (7, 13)},
                      device_count=8)

    def test_docs_catalog_in_sync(self):
        """docs/static_analysis.md documents every cataloged rule."""
        import pathlib
        doc = (pathlib.Path(__file__).resolve().parents[1]
               / "docs" / "static_analysis.md").read_text()
        missing = [rid for rid in RULES if rid not in doc]
        assert not missing, missing
