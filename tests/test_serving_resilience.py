"""Serving resilience rail tests (serving/resilience.py + the
inference.py surgery): SLO admission shedding, circuit breaker with
pinned /healthz 200→503→200 transitions, supervised workers with
exactly-once crash requeue, bisecting poisoned-batch isolation
(bit-identical healthy co-batched answers), reply-time deadline
re-check, and checkpoint-driven hot reload with canary rollback.

The chaos e2e drills follow the PR-4 convention: seed-driven injectors
from faults/chaos.py, each test ``@pytest.mark.chaos`` so the conftest
SIGALRM guard bounds a wedged recovery loop to one failing test.
"""
import re
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import Future

import numpy as np
import pytest

from deeplearning4j_tpu.checkpoint import CheckpointManager
from deeplearning4j_tpu.faults import ChaosMonkey
from deeplearning4j_tpu.learning.updaters import Adam
from deeplearning4j_tpu.nn import (DenseLayer, InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.serving import (
    InferenceMode, InferenceRequest, LoadGenerator, ParallelInference,
    PoisonedRequestError, ReloadFailedError, RequestQueue,
    RequestTimeoutError, ResilienceConfig, ServerClosedError,
    ServerOverloadedError, ServingError, ServingMetrics,
    ServingTimeoutError)
from deeplearning4j_tpu.serving.resilience import (AdmissionController,
                                                   CircuitBreaker)
from deeplearning4j_tpu.ui.stats import StatsStorage

N_IN, N_OUT = 8, 3


def _net(seed=7):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(Adam(1e-3)).list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=N_OUT, loss_function="MCXENT"))
            .set_input_type(InputType.feed_forward(N_IN))
            .build())
    return MultiLayerNetwork(conf).init()


def _req(rows=1, deadline=None, seed=0):
    x = np.random.default_rng(seed).normal(size=(rows, N_IN)) \
        .astype(np.float32)
    return InferenceRequest(x=[x], future=Future(), rows=rows,
                            deadline=deadline)


def _wait_until(cond, timeout=10.0, interval=0.005):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


class _Die(BaseException):
    """Escapes the worker's Exception guard — SIGKILL-grade worker
    death for supervision drills."""


# ---------------------------------------------------------------------------
# circuit breaker unit


def test_breaker_state_machine_closed_open_half_open():
    clock = {"t": 0.0}
    transitions = []
    br = CircuitBreaker(failure_threshold=3, reset_timeout_s=1.0,
                        on_transition=lambda o, n: transitions.append((o, n)),
                        clock=lambda: clock["t"])
    assert br.state == "closed"
    br.on_failure()
    br.on_failure()
    assert br.state == "closed"
    br.on_success()                 # a success resets the streak
    br.on_failure()
    br.on_failure()
    br.on_failure()
    assert br.state == "open"
    assert br.reject_for() == pytest.approx(1.0)
    ok, wait = br.acquire()
    assert not ok and wait == pytest.approx(1.0)
    clock["t"] = 1.5                # probe window reached
    assert br.reject_for() is None  # submits admitted again
    ok, _ = br.acquire()            # first worker owns the probe
    assert ok and br.state == "half_open"
    ok2, _ = br.acquire()           # concurrent probe denied
    assert not ok2
    br.on_failure()                 # probe failed -> re-open
    assert br.state == "open"
    clock["t"] = 3.0
    ok, _ = br.acquire()
    assert ok
    br.on_success()                 # probe succeeded -> closed
    assert br.state == "closed"
    assert ("closed", "open") in transitions
    assert ("open", "half_open") in transitions
    assert ("half_open", "open") in transitions
    assert ("half_open", "closed") in transitions


def test_breaker_release_returns_unused_probe():
    clock = {"t": 0.0}
    br = CircuitBreaker(failure_threshold=1, reset_timeout_s=0.5,
                        clock=lambda: clock["t"])
    br.on_failure()
    clock["t"] = 1.0
    ok, _ = br.acquire()
    assert ok and br.state == "half_open"
    br.release()                    # dispatched nothing (empty poll)
    ok2, _ = br.acquire()           # the probe is available again
    assert ok2


# ---------------------------------------------------------------------------
# admission controller unit


def test_admission_estimate_math_and_cold_start():
    ac = AdmissionController(window=16, percentile=95.0, min_samples=4)
    assert ac.estimate_wait_ms(64, 32) is None       # cold: never sheds
    for _ in range(4):
        ac.observe(10.0)
    assert ac.estimate_wait_ms(64, 32) == pytest.approx(20.0)
    assert ac.estimate_wait_ms(1, 32) == pytest.approx(10.0)
    assert ac.estimate_wait_ms(0, 32) == pytest.approx(0.0)
    # sequential convention: one request per dispatch
    assert ac.estimate_wait_ms(3, 1) == pytest.approx(30.0)


def test_overloaded_error_carries_retry_after():
    assert ServerOverloadedError("x", retry_after_s=1.5).retry_after_s == 1.5
    assert ServerOverloadedError("y").retry_after_s is None
    # ServingTimeoutError stays catchable as RequestTimeoutError (the
    # loadgen/back-compat contract)
    assert issubclass(ServingTimeoutError, RequestTimeoutError)


def test_resilience_config_normalize():
    assert ResilienceConfig.normalize(None) is None
    assert ResilienceConfig.normalize(False) is None
    assert isinstance(ResilienceConfig.normalize(True), ResilienceConfig)
    cfg = ResilienceConfig(breaker_reset_s=9.0)
    assert ResilienceConfig.normalize(cfg) is cfg
    with pytest.raises(TypeError):
        ResilienceConfig.normalize("yes")


# ---------------------------------------------------------------------------
# queue: requeue + rows accounting + reply-time deadline


def test_queue_requeue_front_and_rows_accounting():
    q = RequestQueue(4)
    a, b = _req(rows=2, seed=0), _req(rows=3, seed=1)
    q.put(a)
    q.put(b)
    assert q.pending_rows() == 5
    got = q.take(max_rows=2, timeout=0)
    assert len(got) == 1 and got[0] is a
    assert q.pending_rows() == 3
    q.requeue(a)                    # crash recovery: back to the FRONT
    assert q.pending_rows() == 5
    got2 = q.take(max_rows=8, timeout=0)
    assert got2[0] is a and got2[1] is b
    assert q.pending_rows() == 0
    q.close(drain=True)
    q.requeue(a)                    # allowed mid-drain
    q2 = RequestQueue(2)
    q2.close(drain=False)
    with pytest.raises(ServerClosedError):
        q2.requeue(_req())


def test_complete_after_deadline_is_servingtimeout():
    req = _req(rows=1, deadline=time.monotonic() - 0.01)
    assert req.complete([np.zeros((1, N_OUT), np.float32)]) is False
    with pytest.raises(ServingTimeoutError):
        req.future.result(timeout=0)
    live = _req(rows=1, deadline=time.monotonic() + 60)
    assert live.complete([np.zeros((1, N_OUT), np.float32)]) is True
    assert live.future.result(timeout=0).shape == (1, N_OUT)


def test_deadline_expiring_during_exec_surfaces_timeout():
    """Satellite: a request that expires DURING exec must not complete
    as a stale success — its future gets ServingTimeoutError and the
    deadline timeout is recorded."""
    net = _net()
    pi = ParallelInference(net, mode=InferenceMode.BATCHED, workers=1,
                           max_batch_size=4, buckets=(4,), max_delay_ms=0.5)
    try:
        x = np.zeros((2, N_IN), np.float32)
        pi.output(x)                # precompile: the timed exec is fast
        orig = pi._execute
        pi._execute = lambda *a, **k: (time.sleep(0.12), orig(*a, **k))[1]
        fut = pi.submit(x, timeout_ms=50)
        with pytest.raises(ServingTimeoutError):
            fut.result(timeout=10)
        assert pi.metrics.counters["requests_timed_out"] == 1
        assert pi.metrics.timeout_causes.get("deadline") == 1
    finally:
        pi._execute = orig
        pi.shutdown()


# ---------------------------------------------------------------------------
# SLO admission shedding


def test_slo_admission_sheds_doomed_requests():
    net = _net()
    gate = threading.Event()
    pi = ParallelInference(net, mode=InferenceMode.BATCHED, workers=1,
                           max_batch_size=4, buckets=(4,), max_queue_len=64,
                           max_delay_ms=0.5, resilience=True)
    orig = pi._execute
    pi._execute = lambda *a, **k: (gate.wait(10), orig(*a, **k))[1]
    try:
        # warm the estimator: rolling p95 exec = 50 ms
        for _ in range(pi.admission.min_samples):
            pi.admission.observe(50.0)
        first = pi.submit(np.zeros((4, N_IN), np.float32))
        assert _wait_until(lambda: pi._queue.pending() == 0)
        filler = pi.submit(np.zeros((4, N_IN), np.float32))
        # 4 queued rows + 1 own row -> 2 dispatches x 50 ms = 100 ms
        # estimated wait > the 20 ms deadline: shed at submit, typed
        with pytest.raises(ServerOverloadedError) as ei:
            pi.submit(np.zeros((1, N_IN), np.float32), timeout_ms=20)
        assert ei.value.retry_after_s is not None
        assert ei.value.retry_after_s > 0
        assert pi.metrics.counters["requests_shed"] == 1
        # a deadline the estimate fits IS admitted; no-deadline requests
        # are never SLO-shed
        roomy = pi.submit(np.zeros((1, N_IN), np.float32),
                          timeout_ms=60_000)
        free = pi.submit(np.zeros((1, N_IN), np.float32))
        gate.set()
        for f in (first, filler, roomy, free):
            assert f.result(timeout=30) is not None
        assert pi.metrics.counters["requests_shed"] == 1
    finally:
        gate.set()
        pi.shutdown()


# ---------------------------------------------------------------------------
# poisoned-batch isolation


def test_poisoned_request_quarantined_healthy_bit_identical():
    net = _net()
    chaos = ChaosMonkey(seed=5)
    storage = StatsStorage()
    pi = ParallelInference(net, mode=InferenceMode.BATCHED, workers=1,
                           max_batch_size=8, max_delay_ms=25.0,
                           resilience=True, stats_storage=storage)
    try:
        rng = np.random.default_rng(4)
        xs = [rng.normal(size=(2, N_IN)).astype(np.float32)
              for _ in range(3)]
        direct = [net.output(x).to_numpy() for x in xs]
        futs = [pi.submit(x) for x in xs]
        pf = pi.submit(chaos.poison_request(xs[0]))
        with pytest.raises(PoisonedRequestError) as ei:
            pf.result(timeout=60)
        assert ei.value.request_id is not None
        for f, d in zip(futs, direct):
            out = f.result(timeout=60)
            assert np.array_equal(out, d), \
                "healthy co-batched request lost bit-identity"
        assert pi.metrics.counters["poisoned_quarantined"] == 1
        # the poison was co-batched (the coalescing window held all 4),
        # so isolation had to bisect
        assert pi.metrics.counters["bisect_splits"] >= 1
    finally:
        pi.shutdown()
    events = [r.get("event") for r in storage.of_type("faults")]
    assert "quarantine" in events


@pytest.mark.chaos
def test_transient_exec_faults_absorbed_zero_healthy_failures():
    """Satellite soak: deterministic transient exec failures under
    closed-loop load — every healthy request is served (the bisection
    retries absorb the faults), none fails or times out."""
    net = _net()
    chaos = ChaosMonkey(seed=11)
    pi = ParallelInference(net, mode=InferenceMode.BATCHED, workers=2,
                           max_batch_size=8, max_delay_ms=1.0,
                           max_queue_len=512, resilience=True)
    try:
        lg = LoadGenerator(
            pi, lambda rng, i: rng.normal(size=(2, N_IN))
            .astype(np.float32), seed=2)
        with chaos.failing_exec(pi, n=6, every=5) as state:
            res = lg.run_closed(n_requests=96, concurrency=4)
        assert state["left"] == 0, "injector never fired fully"
        assert res.n_failed == 0 and res.n_timed_out == 0 \
            and res.n_rejected == 0
        assert res.n_ok == 96
        assert pi.metrics.counters["exec_faults"] >= 6
        assert pi.metrics.counters["poisoned_quarantined"] == 0
    finally:
        pi.shutdown()


# ---------------------------------------------------------------------------
# circuit breaker e2e: /healthz 200 -> 503 -> 200 pinned


def _probe(url, route):
    try:
        with urllib.request.urlopen(url + route, timeout=5) as r:
            return r.status
    except urllib.error.HTTPError as e:
        return e.code


@pytest.mark.chaos
def test_breaker_opens_sheds_and_heals_healthz_pinned():
    net = _net()
    storage = StatsStorage()
    cfg = ResilienceConfig(breaker_failure_threshold=3,
                           breaker_reset_s=1.0, single_retries=0,
                           admission=False)
    pi = ParallelInference(net, mode=InferenceMode.BATCHED, workers=1,
                           max_batch_size=4, buckets=(4,),
                           max_delay_ms=0.5, resilience=cfg,
                           stats_storage=storage, telemetry_port=0)
    chaos = ChaosMonkey(seed=3)
    url = pi.telemetry.url
    try:
        assert _probe(url, "/healthz") == 200
        x = np.zeros((1, N_IN), np.float32)
        with chaos.failing_exec(pi, n=3, every=1):
            deadline = time.monotonic() + 20
            while pi.breaker.state != "open" and \
                    time.monotonic() < deadline:
                try:
                    f = pi.submit(x)
                except ServerOverloadedError:
                    break
                with pytest.raises(ServingError):
                    f.result(timeout=30)    # every admitted future typed
        assert pi.breaker.state == "open"
        assert _probe(url, "/healthz") == 503
        assert _probe(url, "/readyz") == 503
        with pytest.raises(ServerOverloadedError) as ei:
            pi.submit(x)                    # open: shed with backoff hint
        assert ei.value.retry_after_s is not None
        assert pi.metrics.counters["requests_shed"] >= 1
        assert pi.metrics.counters["breaker_opens"] == 1
        # injector exhausted: after the reset window a probe batch heals
        assert _wait_until(lambda: pi.breaker.reject_for() is None,
                           timeout=5)
        ok = pi.submit(x)
        assert ok.result(timeout=30) is not None
        assert _wait_until(lambda: pi.breaker.state == "closed", timeout=10)
        assert _probe(url, "/healthz") == 200
        assert _probe(url, "/readyz") == 200
        events = [(r.get("event"), r.get("cause"))
                  for r in storage.of_type("faults")]
        assert ("fault", "breaker_open") in events
        assert ("recovered", "breaker_closed") in events
    finally:
        pi.shutdown()


# ---------------------------------------------------------------------------
# worker supervision


@pytest.mark.chaos
def test_worker_crash_requeues_inflight_exactly_once():
    net = _net()
    storage = StatsStorage()
    cfg = ResilienceConfig(worker_backoff_base_s=0.01,
                           worker_backoff_max_s=0.05)
    pi = ParallelInference(net, mode=InferenceMode.BATCHED, workers=1,
                           max_batch_size=4, max_delay_ms=1.0,
                           resilience=cfg, stats_storage=storage)
    try:
        orig = pi._execute
        state = {"kills": 1}

        def killer(features, real_rows=None):
            if state["kills"] > 0:
                state["kills"] -= 1
                raise _Die("chaos: worker death mid-dispatch")
            return orig(features, real_rows=real_rows)

        pi._execute = killer
        x = np.random.default_rng(0).normal(size=(2, N_IN)) \
            .astype(np.float32)
        fut = pi.submit(x)
        out = fut.result(timeout=60)    # requeued + served post-restart
        assert np.array_equal(out, net.output(x).to_numpy())
        assert pi.metrics.counters["worker_restarts"] >= 1
        assert pi.metrics.counters["requests_requeued"] == 1
        events = [(r.get("event"), r.get("cause"))
                  for r in storage.of_type("faults")]
        assert ("fault", "worker_crash") in events
        assert ("recovered", "worker_restart") in events
    finally:
        pi._execute = orig
        pi.shutdown()


@pytest.mark.chaos
def test_request_lost_to_two_crashes_fails_typed():
    net = _net()
    cfg = ResilienceConfig(worker_backoff_base_s=0.01,
                           worker_backoff_max_s=0.05)
    pi = ParallelInference(net, mode=InferenceMode.BATCHED, workers=1,
                           max_batch_size=4, max_delay_ms=1.0,
                           resilience=cfg)
    try:
        orig = pi._execute
        state = {"kills": 2}

        def killer(features, real_rows=None):
            if state["kills"] > 0:
                state["kills"] -= 1
                raise _Die("chaos: worker death mid-dispatch")
            return orig(features, real_rows=real_rows)

        pi._execute = killer
        fut = pi.submit(np.zeros((2, N_IN), np.float32))
        with pytest.raises(ServingError, match="twice"):
            fut.result(timeout=60)      # exactly-once: no third dispatch
        assert pi.metrics.counters["worker_restarts"] >= 2
        assert pi.metrics.counters["requests_requeued"] == 1
        # the server still serves after healing
        x = np.zeros((2, N_IN), np.float32)
        assert np.array_equal(pi.output(x), net.output(x).to_numpy())
    finally:
        pi._execute = orig
        pi.shutdown()


@pytest.mark.chaos
def test_persistent_guard_errors_escalate_to_worker_restart():
    """Review regression: construction-time workers must read the
    die-after-N escalation from the CONFIG (the supervisor attribute is
    not yet assigned when they start) — a persistently failing worker
    loop gets the worker replaced, not retried forever."""
    net = _net()
    cfg = ResilienceConfig(worker_max_consecutive_errors=3,
                           worker_backoff_base_s=0.01,
                           worker_backoff_max_s=0.05)
    pi = ParallelInference(net, mode=InferenceMode.BATCHED, workers=1,
                           max_delay_ms=0.5, resilience=cfg)
    try:
        state = {"left": 4}
        orig = pi._batcher.next_batch

        def flaky(poll_timeout=0.1):
            if state["left"] > 0:
                state["left"] -= 1
                raise RuntimeError("chaos: persistent loop bug")
            return orig(poll_timeout=poll_timeout)

        pi._batcher.next_batch = flaky
        # worker 1 dies after 3 consecutive guard errors; its
        # replacement eats the 4th, then the injector is spent
        assert _wait_until(
            lambda: pi.metrics.counters["worker_restarts"] >= 1,
            timeout=20)
        x = np.zeros((2, N_IN), np.float32)
        assert np.array_equal(pi.output(x), net.output(x).to_numpy())
    finally:
        pi.shutdown()


@pytest.mark.chaos
def test_worker_crash_holding_half_open_probe_does_not_wedge():
    """Review regression: a worker that dies while owning the
    half-open probe must not leave _probe_inflight latched — the
    supervisor's crash handler releases it, so the next probe can
    dispatch and the breaker can heal."""
    net = _net()
    cfg = ResilienceConfig(breaker_failure_threshold=1,
                           breaker_reset_s=0.2, single_retries=0,
                           worker_backoff_base_s=0.01,
                           worker_backoff_max_s=0.05)
    pi = ParallelInference(net, mode=InferenceMode.BATCHED, workers=1,
                           max_batch_size=4, buckets=(4,),
                           max_delay_ms=0.5, resilience=cfg)
    chaos = ChaosMonkey(seed=7)
    try:
        x = np.zeros((1, N_IN), np.float32)
        with chaos.failing_exec(pi, n=1, every=1):
            f = pi.submit(x)
            with pytest.raises(ServingError):
                f.result(timeout=30)        # opens the breaker
        assert pi.breaker.state == "open"
        assert _wait_until(lambda: pi.breaker.reject_for() is None,
                           timeout=5)
        # the PROBE dispatch dies worker-and-all
        orig = pi._execute
        state = {"kills": 1}

        def killer(features, real_rows=None):
            if state["kills"] > 0:
                state["kills"] -= 1
                raise _Die("chaos: probe-owning worker death")
            return orig(features, real_rows=real_rows)

        pi._execute = killer
        probe_req = pi.submit(x)
        # supervisor releases the leaked probe + requeues; the next
        # probe serves the request and closes the breaker
        assert probe_req.result(timeout=60) is not None
        assert _wait_until(lambda: pi.breaker.state == "closed",
                           timeout=30)
    finally:
        pi._execute = orig
        pi.shutdown()


@pytest.mark.chaos
def test_guard_level_error_releases_half_open_probe():
    """Review regression: an exception the worker guard absorbs while
    the worker HOLDS the half-open probe (e.g. next_batch raising after
    acquire) must release the probe — a leaked probe would gate every
    worker's dispatch forever with no escalation path."""
    net = _net()
    cfg = ResilienceConfig(breaker_failure_threshold=1,
                           breaker_reset_s=0.2, single_retries=0)
    pi = ParallelInference(net, mode=InferenceMode.BATCHED, workers=1,
                           max_batch_size=4, buckets=(4,),
                           max_delay_ms=0.5, resilience=cfg)
    chaos = ChaosMonkey(seed=1)
    try:
        x = np.zeros((1, N_IN), np.float32)
        with chaos.failing_exec(pi, n=1, every=1):
            with pytest.raises(ServingError):
                pi.submit(x).result(timeout=30)     # opens the breaker
        assert pi.breaker.state == "open"
        state = {"left": 1}
        orig = pi._batcher.next_batch

        def flaky(poll_timeout=0.1):
            # fire exactly while this worker owns the half-open probe
            if state["left"] > 0 and pi.breaker.state == "half_open":
                state["left"] -= 1
                raise RuntimeError("chaos: guard error holding the probe")
            return orig(poll_timeout=poll_timeout)

        pi._batcher.next_batch = flaky
        assert _wait_until(lambda: pi.breaker.reject_for() is None,
                           timeout=5)
        # without the guard's release() this request is never dispatched
        assert pi.submit(x).result(timeout=30) is not None
        assert state["left"] == 0, "injector never fired"
        assert _wait_until(lambda: pi.breaker.state == "closed",
                           timeout=10)
    finally:
        pi.shutdown()


def test_bisection_of_one_poisoned_request_does_not_open_breaker():
    """Review regression: the bisection's internal retries of a single
    RAISING poisoned request must not count as consecutive breaker
    failures — only the top-level exec outcome feeds the breaker."""
    net = _net()
    cfg = ResilienceConfig(breaker_failure_threshold=3,
                           breaker_reset_s=60.0, single_retries=1)
    pi = ParallelInference(net, mode=InferenceMode.BATCHED, workers=1,
                           max_batch_size=8, max_delay_ms=25.0,
                           resilience=cfg)
    try:
        orig = pi._execute

        def nan_raises(features, real_rows=None):
            # a garbage request the device genuinely rejects
            if np.isnan(np.asarray(features[0])).any():
                raise RuntimeError("exec rejects this batch")
            return orig(features, real_rows=real_rows)

        pi._execute = nan_raises
        rng = np.random.default_rng(8)
        xs = [rng.normal(size=(1, N_IN)).astype(np.float32)
              for _ in range(3)]
        direct = [net.output(x).to_numpy() for x in xs]
        futs = [pi.submit(x) for x in xs]
        pf = pi.submit(np.full((1, N_IN), np.nan, np.float32))
        with pytest.raises(PoisonedRequestError):
            pf.result(timeout=60)
        for f, d in zip(futs, direct):
            assert np.array_equal(f.result(timeout=60), d)
        # the bisection issued several failing execs for the poison,
        # but the breaker saw only the ONE top-level failure
        assert pi.metrics.counters["bisect_splits"] >= 1
        assert pi.breaker.state == "closed"
        assert pi.metrics.counters["breaker_opens"] == 0
    finally:
        pi._execute = orig
        pi.shutdown()


def test_worker_guard_records_instead_of_silent_continue():
    """Satellite: the last-ditch guard must record the exception
    (metrics + fault-rail record), not swallow it silently."""
    net = _net()
    storage = StatsStorage()
    pi = ParallelInference(net, mode=InferenceMode.BATCHED, workers=1,
                           max_delay_ms=0.5, stats_storage=storage)
    try:
        state = {"left": 2}
        orig = pi._batcher.next_batch

        def flaky(poll_timeout=0.1):
            if state["left"] > 0:
                state["left"] -= 1
                raise RuntimeError("chaos: worker loop bug")
            return orig(poll_timeout=poll_timeout)

        pi._batcher.next_batch = flaky
        x = np.zeros((2, N_IN), np.float32)
        out = pi.output(x)              # still serves afterwards
        assert np.array_equal(out, net.output(x).to_numpy())
        assert _wait_until(
            lambda: pi.metrics.failure_causes.get("worker_guard", 0) >= 2)
        assert any(r.get("event") == "worker_error"
                   and r.get("cause") == "worker_guard"
                   for r in storage.of_type("faults"))
    finally:
        pi.shutdown()


# ---------------------------------------------------------------------------
# submit vs shutdown(drain=True) race


def test_concurrent_submit_vs_drain_shutdown_no_dropped_futures():
    """Satellite: every submit() that returns a future resolves it —
    drain serves the queue; a submit racing the close gets a typed
    error AT THE CALL SITE, never a silently-dropped future."""
    net = _net()
    pi = ParallelInference(net, mode=InferenceMode.BATCHED, workers=2,
                           max_batch_size=8, max_delay_ms=0.5,
                           max_queue_len=1024)
    x = np.random.default_rng(1).normal(size=(2, N_IN)).astype(np.float32)
    direct = net.output(x).to_numpy()
    accepted = []
    lock = threading.Lock()
    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            try:
                f = pi.submit(x)
            except (ServerClosedError, ServerOverloadedError):
                if pi._closed:
                    return
                continue
            with lock:
                accepted.append(f)

    threads = [threading.Thread(target=hammer, daemon=True)
               for _ in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.1)
    pi.shutdown(drain=True)
    stop.set()
    for t in threads:
        t.join(timeout=10)
    assert accepted, "race produced no admitted requests"
    for f in accepted:
        assert np.array_equal(f.result(timeout=30), direct)


# ---------------------------------------------------------------------------
# checkpoint-driven hot reload


def _ulp_equal(a, b, atol=1e-5):
    """Exact up to co-batching rounding noise: XLA CPU execution of
    TRAINED nets is value-dependently off by a few ulps vs a solo exec
    depending on batch composition (pre-existing plain-path property,
    recorded in .claude/skills/verify/SKILL.md) — the reload test
    streams hundreds of co-batched copies, so composition varies run
    to run. atol=1e-5 is ~100x the observed noise and ~100x below the
    distance between the two parameter regimes being distinguished."""
    return np.array_equal(a, b) or \
        (a.shape == b.shape and np.allclose(a, b, rtol=0.0, atol=atol))


def test_hot_reload_mid_traffic_drops_nothing(tmp_path):
    net = _net()
    rng = np.random.default_rng(0)
    X = rng.normal(size=(64, N_IN)).astype(np.float32)
    Y = np.eye(N_OUT, dtype=np.float32)[rng.integers(0, N_OUT, 64)]
    net.fit(X, Y, epochs=1, batch_size=32)
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(1, model=net, blocking=True)
    x = rng.normal(size=(2, N_IN)).astype(np.float32)
    ckpt_out = net.output(x).to_numpy()     # outputs at the snapshot
    net.fit(X, Y, epochs=2, batch_size=32)  # train PAST the snapshot
    live_out = net.output(x).to_numpy()
    # the two regimes must sit far outside the _ulp_equal noise bound,
    # or the regime checks below could not discriminate them
    assert float(np.max(np.abs(ckpt_out - live_out))) > 1e-3
    pi = ParallelInference(net, mode=InferenceMode.BATCHED, workers=2,
                           max_delay_ms=1.0, max_queue_len=1024,
                           resilience=True)
    try:
        assert np.array_equal(pi.output(x), live_out)
        results = []
        stop = threading.Event()

        def stream():
            while not stop.is_set():
                try:
                    results.append(pi.submit(x))
                except ServerOverloadedError:
                    time.sleep(0.001)

        t = threading.Thread(target=stream, daemon=True)
        t.start()
        time.sleep(0.03)
        report = pi.reload_from(mgr)        # hot swap, mid-traffic
        time.sleep(0.03)
        stop.set()
        t.join(timeout=10)
        assert report["step"] == 1 and report["arrays_swapped"] > 0
        assert report["rolled_back"] is False
        # the streamer may have filled the queue faster than workers
        # drain on a loaded machine — the probe backs off like any
        # well-behaved client instead of failing on the typed shed
        deadline = time.monotonic() + 30
        while True:
            try:
                probe = pi.output(x)
                break
            except ServerOverloadedError:
                assert time.monotonic() < deadline, "queue never drained"
                time.sleep(0.01)
        assert _ulp_equal(probe, ckpt_out)
        # zero dropped: every streamed request resolved with a real
        # answer (pre-swap params or post-swap params, nothing else)
        assert results
        for f in results:
            out = f.result(timeout=30)
            assert _ulp_equal(out, ckpt_out) or _ulp_equal(out, live_out)
        assert pi.metrics.counters["reloads"] == 1
        assert pi.metrics.resilience.get("last_reload_step") == 1
    finally:
        pi.shutdown()


def test_reload_canary_failure_rolls_back(tmp_path):
    from deeplearning4j_tpu.checkpoint.state import capture_training_state
    net = _net()
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    state = capture_training_state(net.samediff, epoch=0)
    state.arrays = {n: (np.full_like(a, np.nan)
                        if np.issubdtype(a.dtype, np.floating) else a)
                    for n, a in state.arrays.items()}
    mgr.save(7, state=state, blocking=True)     # a poisoned checkpoint
    storage = StatsStorage()
    pi = ParallelInference(net, mode=InferenceMode.BATCHED,
                           max_delay_ms=1.0, resilience=True,
                           stats_storage=storage)
    try:
        x = np.random.default_rng(2).normal(size=(2, N_IN)) \
            .astype(np.float32)
        before = pi.output(x)
        with pytest.raises(ReloadFailedError) as ei:
            pi.reload_from(mgr)
        assert ei.value.rolled_back
        assert "non-finite" in str(ei.value)
        assert pi.metrics.counters["reload_rollbacks"] == 1
        assert pi.metrics.counters["reloads"] == 0
        # previous params restored: serving is bit-identical to before
        assert np.array_equal(pi.output(x), before)
        assert any(r.get("event") == "reload" and r.get("rolled_back")
                   for r in storage.of_type("faults"))
    finally:
        pi.shutdown()


def test_reload_strict_rejects_shape_mismatch(tmp_path):
    """Review regression: strict reload must reject same-name arrays
    whose SHAPES changed (silently swapping the matching subset would
    serve a chimera of old and new parameters)."""
    from deeplearning4j_tpu.checkpoint.state import capture_training_state
    net = _net()
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    state = capture_training_state(net.samediff, epoch=0)
    name = sorted(state.arrays)[0]
    state.arrays[name] = np.zeros(
        tuple(d + 1 for d in np.shape(state.arrays[name])), np.float32)
    mgr.save(2, state=state, blocking=True)
    with ParallelInference(net, mode=InferenceMode.INPLACE,
                           resilience=True) as pi:
        with pytest.raises(ReloadFailedError, match="different shapes"):
            pi.reload_from(mgr)
        assert pi.metrics.counters["reloads"] == 0
        # non-strict swaps the matching subset (and says how many)
        report = pi.reload_from(mgr, strict=False)
        assert report["arrays_swapped"] == len(state.arrays) - 1


def test_reload_requires_committed_checkpoint(tmp_path):
    net = _net()
    mgr = CheckpointManager(str(tmp_path / "empty"))
    with ParallelInference(net, mode=InferenceMode.INPLACE) as pi:
        with pytest.raises(ReloadFailedError, match="no committed"):
            pi.reload_from(mgr)


# ---------------------------------------------------------------------------
# the acceptance e2e: transient faults + poison + hot reload, one run


@pytest.mark.chaos
def test_chaos_e2e_selfheal_serving(tmp_path):
    """ISSUE 9 acceptance: under injected transient exec failures plus
    one poisoned request, exactly the poisoned request is quarantined,
    every healthy request is served bit-identically to a fault-free
    run, and a mid-traffic hot reload drops zero requests."""
    net = _net()
    rng = np.random.default_rng(9)
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(3, model=net, blocking=True)       # reload target == live
    xs = [rng.normal(size=(int(rng.integers(1, 4)), N_IN))
          .astype(np.float32) for _ in range(24)]
    direct = [net.output(x).to_numpy() for x in xs]     # fault-free run
    chaos = ChaosMonkey(seed=13)
    storage = StatsStorage()
    pi = ParallelInference(net, mode=InferenceMode.BATCHED, workers=2,
                           max_batch_size=8, max_delay_ms=2.0,
                           max_queue_len=256, resilience=True,
                           stats_storage=storage)
    try:
        poison = chaos.poison_request(xs[0])
        with chaos.failing_exec(pi, n=4, every=5):
            futs = [pi.submit(x) for x in xs[:12]]
            pf = pi.submit(poison)
            report = pi.reload_from(mgr)        # mid-traffic hot swap
            futs += [pi.submit(x) for x in xs[12:]]
            outs = [f.result(timeout=60) for f in futs]
            with pytest.raises(PoisonedRequestError):
                pf.result(timeout=60)
        assert report["rolled_back"] is False
        for x, o, d in zip(xs, outs, direct):
            assert np.array_equal(o, d), \
                "healthy request not bit-identical to the fault-free run"
        assert pi.metrics.counters["poisoned_quarantined"] == 1
        assert pi.metrics.counters["exec_faults"] >= 1
        assert pi.metrics.counters["reloads"] == 1
        # futures resolve BEFORE the worker's observe_request accounting
        # — poll rather than race the last batch's metric update
        assert _wait_until(
            lambda: pi.metrics.counters["requests_served"] == len(xs))
    finally:
        pi.shutdown()
    events = [r.get("event") for r in storage.of_type("faults")]
    assert "quarantine" in events and "reload" in events


# ---------------------------------------------------------------------------
# observability wiring


def test_fold_serving_resilience_gauges_and_report_panel():
    from deeplearning4j_tpu.monitor.registry import MetricsRegistry
    from deeplearning4j_tpu.ui.report import render_report
    m = ServingMetrics()
    m.inc("requests_shed", 3)
    m.inc("worker_restarts")
    m.inc("reloads")
    m.set_resilience(breaker_state="open", last_reload_step=12,
                     last_reload_failed=False)
    reg = MetricsRegistry()
    reg.fold_serving(m)
    text = reg.to_prometheus_text()

    def gauge(name):
        mt = re.search(rf"^{name} (\S+)$", text, re.M)
        assert mt, f"{name} missing from exposition"
        return float(mt.group(1))

    assert gauge("dl4j_serving_requests_shed_total") == 3
    assert gauge("dl4j_serving_breaker_state") == 2          # open
    assert gauge("dl4j_serving_last_reload_step") == 12
    assert gauge("dl4j_serving_last_reload_failed") == 0
    assert "resilience:" in m.stats()
    st = StatsStorage()
    st.put(m.to_record())
    st.put({"type": "faults", "event": "quarantine", "origin": "serving",
            "cause": None, "t": time.time(), "request_id": 5})
    html = render_report(st)
    assert "Serving" in html
    assert "breaker" in html
    assert "quarantine" in html
    assert "unrendered record types" not in html


def test_breaker_state_surfaces_in_telemetry_provider():
    net = _net()
    pi = ParallelInference(net, mode=InferenceMode.BATCHED,
                           max_delay_ms=0.5, resilience=True)
    try:
        snap = pi._telemetry_health()
        assert snap["breaker_state"] == "closed"
        assert snap["healthy"] and snap["ready"]
    finally:
        pi.shutdown()
