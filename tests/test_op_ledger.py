"""Op coverage ledger (reference: autodiff/validation/OpValidation.java:110-453
— forward-value checks vs golden + coverage accounting; CI fails when a
registered op has no validation).

Every registered op must either have a LEDGER entry here (forward check
against a numpy/scipy reference on fixed inputs, plus a finite-difference
gradient check for differentiable entries) or appear in EXERCISED with a
pointer to the test file that covers it. test_all_ops_covered is the gate
that fails on any op registered without coverage — this is the check that
would have caught round 3's unregistered tf_compat module.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.special as sps

from deeplearning4j_tpu.ops import registry

R = np.random.RandomState(0)
A = R.randn(3, 4).astype(np.float64) * 0.8
B_ = R.randn(3, 4).astype(np.float64) * 0.8 + 0.1
P = np.abs(A) + 0.5                       # strictly positive
U = R.rand(3, 4).astype(np.float64) * 0.8 + 0.1   # in (0.1, 0.9)
I1 = R.randint(0, 4, (3, 4)).astype(np.int64)
I2 = R.randint(1, 5, (3, 4)).astype(np.int64)
BOOL = (A > 0)


def spec(inputs, ref, attrs=None, grad=None, rtol=1e-5, atol=1e-7):
    return {"inputs": inputs, "ref": ref, "attrs": attrs or {},
            "grad": grad, "rtol": rtol, "atol": atol}


def _softplus(x):
    return np.logaddexp(0, x)


# name -> spec. `ref` takes the SAME numpy inputs and returns the expected
# array(s). `grad`=True adds a finite-difference check on input 0.
LEDGER = {
    # --- elementwise unary ------------------------------------------------
    "abs": spec([A], np.abs, grad=True),
    "acos": spec([U], np.arccos, grad=True),
    "acosh": spec([P + 1], np.arccosh, grad=True),
    "asin": spec([U], np.arcsin, grad=True),
    "asinh": spec([A], np.arcsinh, grad=True),
    "atan": spec([A], np.arctan, grad=True),
    "atanh": spec([U * 0.9], np.arctanh, grad=True),
    "ceil": spec([A], np.ceil),
    "cos": spec([A], np.cos, grad=True),
    "cosh": spec([A], np.cosh, grad=True),
    "cube": spec([A], lambda x: x ** 3, grad=True),
    "digamma": spec([P], sps.digamma),
    "elu": spec([A], lambda x: np.where(x > 0, x, np.exp(x) - 1), grad=True),
    "erf": spec([A], sps.erf, grad=True),
    "erfc": spec([A], sps.erfc),
    "exp": spec([A], np.exp, grad=True),
    "expm1": spec([A], np.expm1, grad=True),
    "floor": spec([A], np.floor),
    "gelu": spec([A], lambda x: x * 0.5 * (1 + sps.erf(x / np.sqrt(2))),
                 attrs={"precise": True}, grad=True, rtol=1e-4),
    "hard_sigmoid": spec([A], lambda x: np.clip(0.2 * x + 0.5, 0, 1)),
    "hard_tanh": spec([A], lambda x: np.clip(x, -1, 1)),
    "identity": spec([A], lambda x: x, grad=True),
    "isfinite": spec([A], np.isfinite),
    "isinf": spec([A], np.isinf),
    "isnan": spec([A], np.isnan),
    "leaky_relu": spec([A], lambda x: np.where(x > 0, x, 0.01 * x)),
    "lgamma": spec([P], sps.gammaln, rtol=1e-4),
    "log": spec([P], np.log, grad=True),
    "log10": spec([P], np.log10),
    "log1p": spec([P], np.log1p, grad=True),
    "log2": spec([P], np.log2),
    "log_sigmoid": spec([A], lambda x: -_softplus(-x), grad=True),
    "log_softmax": spec([A], lambda x: x - np.log(
        np.exp(x).sum(-1, keepdims=True)), grad=True, rtol=1e-4),
    "mish": spec([A], lambda x: x * np.tanh(_softplus(x)), grad=True),
    "neg": spec([A], np.negative, grad=True),
    "not": spec([BOOL], np.logical_not),
    "oneminus": spec([A], lambda x: 1 - x, grad=True),
    "onesas": spec([A], np.ones_like),
    "reciprocal": spec([P], np.reciprocal, grad=True),
    "relu": spec([A], lambda x: np.maximum(x, 0), grad=True),
    "relu6": spec([A], lambda x: np.clip(x, 0, 6)),
    "rint": spec([A], np.rint),
    "round": spec([A], np.round),
    "rsqrt": spec([P], lambda x: 1 / np.sqrt(x), grad=True),
    "selu": spec([A], lambda x: 1.0507009873554805 * np.where(
        x > 0, x, 1.6732632423543772 * (np.exp(x) - 1)), rtol=1e-4),
    "sigmoid": spec([A], sps.expit, grad=True),
    "sign": spec([A], np.sign),
    "sin": spec([A], np.sin, grad=True),
    "sinh": spec([A], np.sinh, grad=True),
    "softmax": spec([A], lambda x: np.exp(x) / np.exp(x).sum(-1, keepdims=True),
                    grad=True, rtol=1e-4),
    "softplus": spec([A], _softplus, grad=True),
    "softsign": spec([A], lambda x: x / (1 + np.abs(x)), grad=True),
    "sqrt": spec([P], np.sqrt, grad=True),
    "square": spec([A], np.square, grad=True),
    "step": spec([A], lambda x: (x > 0).astype(np.float64)),
    "swish": spec([A], lambda x: x * sps.expit(x), grad=True),
    "tan": spec([A], np.tan, grad=True),
    "tanh": spec([A], np.tanh, grad=True),
    "trunc": spec([A], np.trunc),
    "zerosas": spec([A], np.zeros_like),
    "nan_to_num": spec([A], np.nan_to_num),
    "celu": spec([A], lambda x: np.where(x > 0, x, np.exp(x) - 1),
                 rtol=1e-4),
    "cast": spec([A], lambda x: x.astype(np.float32),
                 attrs={"dtype": "float32"}),
    "scalar_add": spec([A], lambda x: x + 2.5, attrs={"scalar": 2.5},
                       grad=True),
    "scalar_mul": spec([A], lambda x: x * 2.5, attrs={"scalar": 2.5},
                       grad=True),
    "scalar_max": spec([A], lambda x: np.maximum(x, 0.5),
                       attrs={"scalar": 0.5}),
    "scalar_min": spec([A], lambda x: np.minimum(x, 0.5),
                       attrs={"scalar": 0.5}),
    "clip_by_value": spec([A], lambda x: np.clip(x, -0.5, 0.5),
                          attrs={"clip_min": -0.5, "clip_max": 0.5}),
    "pow": spec([P], lambda x: x ** 2.5, attrs={"exponent": 2.5},
           grad=True),
    "cumsum": spec([A], lambda x: np.cumsum(x, 0), attrs={"axis": 0},
                   grad=True),
    "cumprod": spec([P], lambda x: np.cumprod(x, 0), attrs={"axis": 0}),
    # --- pairwise ---------------------------------------------------------
    "add": spec([A, B_], np.add, grad=True),
    "subtract": spec([A, B_], np.subtract, grad=True),
    "multiply": spec([A, B_], np.multiply, grad=True),
    "divide": spec([A, P], np.divide, grad=True),
    "maximum": spec([A, B_], np.maximum, grad=True),
    "minimum": spec([A, B_], np.minimum, grad=True),
    "floordiv": spec([A, P], np.floor_divide),
    "floormod": spec([A, P], np.mod),
    "fmod": spec([A, P], np.fmod),
    "mod": spec([A, P], np.mod),
    "atan2": spec([A, B_], np.arctan2, grad=True),
    "copysign": spec([A, B_], np.copysign),
    "hypot": spec([A, B_], np.hypot),
    "pow_pairwise": spec([P, B_], np.power, grad=True, rtol=1e-4),
    "squaredsubtract": spec([A, B_], lambda a, b: (a - b) ** 2, grad=True),
    "reversesubtract": spec([A, B_], lambda a, b: b - a),
    "reversedivide": spec([P, A], lambda a, b: b / a),
    "truncatediv": spec([A, P], lambda a, b: np.trunc(a / b)),
    "divide_no_nan": spec([A, P], np.divide),
    "igamma": spec([P, P], sps.gammainc, rtol=1e-4),
    "igammac": spec([P, P], sps.gammaincc, rtol=1e-4),
    "equals": spec([I1, I2], np.equal),
    "not_equals": spec([I1, I2], np.not_equal),
    "greater": spec([A, B_], np.greater),
    "greater_equal": spec([A, B_], np.greater_equal),
    "less": spec([A, B_], np.less),
    "less_equal": spec([A, B_], np.less_equal),
    "boolean_and": spec([BOOL, ~BOOL], np.logical_and),
    "boolean_or": spec([BOOL, ~BOOL], np.logical_or),
    "boolean_xor": spec([BOOL, ~BOOL], np.logical_xor),
    "axpy": spec([A, B_], lambda a, b: 2.0 * a + b, attrs={"alpha": 2.0}),
    # --- reductions -------------------------------------------------------
    "reduce_sum": spec([A], lambda x: x.sum(1), attrs={"axis": (1,)},
                       grad=True),
    "reduce_mean": spec([A], lambda x: x.mean(1), attrs={"axis": (1,)},
                        grad=True),
    "reduce_max": spec([A], lambda x: x.max(1), attrs={"axis": (1,)},
                       grad=True),
    "reduce_min": spec([A], lambda x: x.min(1), attrs={"axis": (1,)}),
    "reduce_prod": spec([P], lambda x: x.prod(1), attrs={"axis": (1,)}),
    "reduce_variance": spec([A], lambda x: x.var(1, ddof=1),
                            attrs={"axis": (1,)}, rtol=1e-4),
    "reduce_stdev": spec([A], lambda x: x.std(1, ddof=1),
                         attrs={"axis": (1,)}, rtol=1e-4),
    "reduce_norm1": spec([A], lambda x: np.abs(x).sum(1),
                         attrs={"axis": (1,)}),
    "reduce_norm2": spec([A], lambda x: np.sqrt((x ** 2).sum(1)),
                         attrs={"axis": (1,)}),
    "reduce_norm_max": spec([A], lambda x: np.abs(x).max(1),
                            attrs={"axis": (1,)}),
    "reduce_sqnorm": spec([A], lambda x: (x ** 2).sum(1),
                          attrs={"axis": (1,)}),
    "reduce_logsumexp": spec([A], lambda x: np.log(
        np.exp(x).sum(1)), attrs={"axis": (1,)}, rtol=1e-5),
    "reduce_all": spec([BOOL], lambda x: x.all(1), attrs={"axis": (1,)}),
    "reduce_any": spec([BOOL], lambda x: x.any(1), attrs={"axis": (1,)}),
    "argmax": spec([A], lambda x: x.argmax(1), attrs={"axis": 1}),
    "argmin": spec([A], lambda x: x.argmin(1), attrs={"axis": 1}),
    "argamax": spec([A], lambda x: np.abs(x).argmax(1), attrs={"axis": 1}),
    "argamin": spec([A], lambda x: np.abs(x).argmin(1), attrs={"axis": 1}),
    "count_nonzero": spec([I1], lambda x: np.count_nonzero(x, 1),
                          attrs={"axis": (1,)}),
    "count_zero": spec([I1], lambda x: (x == 0).sum(1), attrs={"axis": (1,)}),
    "zero_fraction": spec([I1], lambda x: (x == 0).mean()),
    "dot": spec([A, B_], lambda a, b: (a * b).sum()),
    "euclidean_distance": spec([A, B_],
                               lambda a, b: np.sqrt(((a - b) ** 2).sum())),
    "manhattan_distance": spec([A, B_],
                               lambda a, b: np.abs(a - b).sum()),
    "cosine_similarity": spec(
        [A.ravel(), B_.ravel()],
        lambda a, b: (a * b).sum() / (np.linalg.norm(a) * np.linalg.norm(b)),
        rtol=1e-5),
    "cosine_distance": spec(
        [A.ravel(), B_.ravel()],
        lambda a, b: 1 - (a * b).sum() / (np.linalg.norm(a) *
                                          np.linalg.norm(b)), rtol=1e-5),
    "hamming_distance": spec([I1, I2], lambda a, b: (a != b).sum()),
    "jaccard_distance": spec(
        [P, np.abs(B_) + 0.5],
        lambda a, b: 1 - np.minimum(a, b).sum() / np.maximum(a, b).sum(),
        rtol=1e-5),
    # --- shape ------------------------------------------------------------
    "reshape": spec([A], lambda x: x.reshape(4, 3),
                    attrs={"shape": (4, 3)}, grad=True),
    "permute": spec([A], lambda x: x.T, attrs={"axes": (1, 0)}, grad=True),
    "transpose": spec([A], lambda x: x.T),
    "expand_dims": spec([A], lambda x: x[:, None], attrs={"axis": 1}),
    "squeeze": spec([A[:, :1]], lambda x: x.squeeze(1),
                    attrs={"axis": (1,)}),
    "stack": spec([A, B_], lambda a, b: np.stack([a, b]), attrs={"axis": 0}),
    "concat": spec([A, B_], lambda a, b: np.concatenate([a, b], 1),
                   attrs={"axis": 1}, grad=True),
    "tile": spec([A], lambda x: np.tile(x, (2, 1)), attrs={"reps": (2, 1)}),
    "reverse": spec([A], lambda x: x[:, ::-1], attrs={"axis": (1,)}),
    "flatten_2d": spec([np.stack([A, B_])],
                       lambda x: x.reshape(x.shape[0], -1)),
    "slice": spec([A], lambda x: x[1:3, 0:2],
                  attrs={"begin": (1, 0), "size": (2, 2)}),
    "strided_slice": spec([A], lambda x: x[0:3:2, 1:4],
                          attrs={"begin": (0, 1), "end": (3, 4),
                                 "strides": (2, 1)}),
    "gather": spec([A, np.array([2, 0])], lambda x, i: x[i],
                   attrs={"axis": 0}),
    "gather_nd": spec([A, np.array([[0, 1], [2, 3]])],
                      lambda x, i: x[i[:, 0], i[:, 1]]),
    "one_hot": spec([np.array([0, 2, 1])],
                    lambda i: np.eye(4)[i].astype(np.float32),
                    attrs={"depth": 4}),
    "zeros_like": spec([A], np.zeros_like),
    "ones_like": spec([A], np.ones_like),
    "fill": spec([], lambda: np.full((2, 3), 1.5, np.float32),
                 attrs={"shape": (2, 3), "value": 1.5}),
    "shape_of": spec([A], lambda x: np.array(x.shape)),
    "rank": spec([A], lambda x: np.array(2)),
    "size": spec([A], lambda x: np.array(x.size)),
    "diag": spec([np.array([1.0, 2.0, 3.0])], np.diag),
    "diag_part": spec([np.diag([1.0, 2.0, 3.0])], np.diagonal),
    "eye_op": spec([], lambda: np.eye(3, dtype=np.float32),
                   attrs={"rows": 3}),
    "pad": spec([A], lambda x: np.pad(x, ((1, 1), (0, 2))),
                attrs={"paddings": ((1, 1), (0, 2))}),
    "repeat": spec([A], lambda x: np.repeat(x, 2, 1),
                   attrs={"repeats": 2, "axis": 1}),
    "broadcast_to": spec([A[0]], lambda x: np.broadcast_to(x, (3, 4)),
                         attrs={"shape": (3, 4)}),
    "split": spec([A], lambda x: tuple(np.split(x, 2, 1)),
                  attrs={"num_split": 2, "axis": 1}),
    "split_v": spec([A], lambda x: tuple(np.split(x, [1], 1)),
                    attrs={"sizes": (1, 3), "axis": 1}),
    "unstack": spec([A], lambda x: tuple(x), attrs={"axis": 0}),
    "cumsum_shape": None,   # placeholder cleanliness
    "linspace_op": spec([], lambda: np.linspace(0, 1, 5).astype(np.float32),
                        attrs={"start": 0.0, "stop": 1.0, "num": 5}),
    "range_op": spec([], lambda: np.arange(1, 7, 2).astype(np.int64),
                     attrs={"start": 1, "limit": 7, "delta": 2}),
    "bincount": spec([np.array([0, 1, 1, 3])],
                     lambda x: np.bincount(x, minlength=4),
                     attrs={"minlength": 4}),
    # --- linalg -----------------------------------------------------------
    "matmul": spec([A, B_.T], np.matmul, grad=True, rtol=1e-5),
    "outer": spec([A[0], B_[0]], np.outer),
    "trace": spec([A @ B_.T], np.trace),
    "norm": spec([A], np.linalg.norm, rtol=1e-5),
    "matrix_determinant": spec([A @ A.T + 3 * np.eye(3)], np.linalg.det,
                               rtol=1e-4),
    "matrix_inverse": spec([A @ A.T + 3 * np.eye(3)], np.linalg.inv,
                           rtol=1e-4),
    "cross": spec([A[:, :3], B_[:, :3]], lambda a, b: np.cross(a, b)),
    "l2_normalize": spec([A], lambda x: x / np.linalg.norm(
        x, axis=-1, keepdims=True), rtol=1e-5),
}
LEDGER.pop("cumsum_shape")

SEG_IDS = np.array([0, 0, 2])
IMG = R.rand(1, 4, 4, 3).astype(np.float64)
UINT = np.array([[0b1100, 0b1010], [1, 255]], np.uint8)
LBL = np.eye(4)[[0, 2, 1]].astype(np.float64)
PRED = (U[:3, :4] * 0.8 + 0.1)

LEDGER.update({
    # --- losses (all reduce to mean by default) ---------------------------
    "mean_sqerr_loss": spec([A, B_], lambda p, l: ((p - l) ** 2).mean(),
                            grad=True),
    "absolute_difference_loss": spec([A, B_],
                                     lambda p, l: np.abs(p - l).mean()),
    "log_loss": spec([PRED, LBL[:, :4][:3]], lambda p, l: -(
        l * np.log(p + 1e-7) + (1 - l) * np.log(1 - p + 1e-7)).mean(),
        rtol=1e-5),
    "hinge_loss": spec([A, LBL[:3, :4]], lambda p, l: np.maximum(
        0, 1 - (2 * l - 1) * p).mean()),
    "squared_hinge_loss": spec([A, LBL[:3, :4]], lambda p, l: (np.maximum(
        0, 1 - (2 * l - 1) * p) ** 2).mean()),
    "poisson_loss": spec([P, np.abs(B_)], lambda p, l: (p - l * np.log(p)
                                                        ).mean()),
    "kl_divergence_loss": spec(
        [PRED / PRED.sum(-1, keepdims=True),
         U / U.sum(-1, keepdims=True)],
        lambda p, l: (l * np.log(l / p)).sum(-1).mean(), rtol=1e-5),
    "l2_loss": spec([A], lambda x: (x ** 2).sum() / 2),
    "sigm_cross_entropy": spec([A, LBL[:3, :4]], lambda z, l: (
        np.maximum(z, 0) - z * l + np.log1p(np.exp(-np.abs(z)))).mean(),
        grad=True, rtol=1e-5),
    "huber_loss": spec([A, B_], lambda p, l: np.where(
        np.abs(p - l) <= 1.0, 0.5 * (p - l) ** 2,
        np.abs(p - l) - 0.5).mean()),

    # --- segment / scatter ------------------------------------------------
    "segment_sum": spec([A, SEG_IDS], lambda d, i: np.stack(
        [d[i == k].sum(0) for k in range(3)]), attrs={"num_segments": 3}),
    "segment_mean": spec([A, SEG_IDS], lambda d, i: np.stack(
        [d[i == k].mean(0) if (i == k).any() else np.zeros(d.shape[1])
         for k in range(3)]), attrs={"num_segments": 3}),
    "segment_max": spec([A, np.array([0, 0, 1])], lambda d, i: np.stack(
        [d[i == k].max(0) for k in range(2)]), attrs={"num_segments": 2}),
    "segment_min": spec([A, np.array([0, 0, 1])], lambda d, i: np.stack(
        [d[i == k].min(0) for k in range(2)]), attrs={"num_segments": 2}),
    "segment_prod": spec([A, np.array([0, 0, 1])], lambda d, i: np.stack(
        [d[i == k].prod(0) for k in range(2)]), attrs={"num_segments": 2}),
    "scatter_add": spec(
        [A.copy(), np.array([0, 2]), np.ones((2, 4))],
        lambda r, i, u: _scatter_ref(r, i, u, np.add)),
    "scatter_sub": spec(
        [A.copy(), np.array([0, 2]), np.ones((2, 4))],
        lambda r, i, u: _scatter_ref(r, i, u, np.subtract)),
    "scatter_mul": spec(
        [A.copy(), np.array([0, 2]), np.full((2, 4), 2.0)],
        lambda r, i, u: _scatter_ref(r, i, u, np.multiply)),
    "scatter_div": spec(
        [A.copy(), np.array([0, 2]), np.full((2, 4), 2.0)],
        lambda r, i, u: _scatter_ref(r, i, u, np.divide)),
    "scatter_max": spec(
        [A.copy(), np.array([0, 2]), np.zeros((2, 4))],
        lambda r, i, u: _scatter_ref(r, i, u, np.maximum)),
    "scatter_min": spec(
        [A.copy(), np.array([0, 2]), np.zeros((2, 4))],
        lambda r, i, u: _scatter_ref(r, i, u, np.minimum)),
    "scatter_update": spec(
        [A.copy(), np.array([0, 2]), np.ones((2, 4))],
        lambda r, i, u: _scatter_ref(r, i, u, lambda a, b: b)),
    "scatter_nd": spec(
        [np.array([[0], [2]]), np.ones((2, 4)), np.array([3, 4])],
        lambda i, u, sh: np.stack([np.ones(4), np.zeros(4), np.ones(4)])),
    "dynamic_partition": spec(
        [A, np.array([0, 1, 0])],
        # static-shape variant: zero-masked partitions, not gathered rows
        lambda x, p: (np.where((p == 0)[:, None], x, 0),
                      np.where((p == 1)[:, None], x, 0)),
        attrs={"num_partitions": 2}),
    "unique": spec([np.array([3, 1, 3, 2])],
                   lambda x: np.unique(x, return_inverse=True)),
    "in_top_k": spec([A, np.array([1, 0, 3])],
                     lambda p, t: np.array(
                         [t[i] in np.argsort(p[i])[-2:] for i in
                          range(len(t))]), attrs={"k": 2}),
    "where_op": spec([BOOL, A, B_], np.where),
    "top_k": spec([A], lambda x: (np.sort(x, 1)[:, ::-1][:, :2],
                                  np.argsort(x, 1)[:, ::-1][:, :2]),
                  attrs={"k": 2}),
    "reverse_sequence": spec(
        [A, np.array([2, 4, 1])],
        lambda x, sl: np.stack([np.concatenate([x[i, :sl[i]][::-1],
                                                x[i, sl[i]:]])
                                for i in range(len(sl))])),
    "assign_op": spec([A, B_], lambda x, y: y),
    "stop_gradient": spec([A], lambda x: x),
    "checknumerics": spec([A], lambda x: x),
    "thresholdedrelu": spec([A], lambda x: np.where(x > 1.0, x, 0.0)),
    "rationaltanh": spec([A], lambda x: np.asarray(
        registry.get_op("rationaltanh").fn(jnp.asarray(x))), rtol=0, atol=1),
    "rectifiedtanh": spec([A], lambda x: np.maximum(np.tanh(x), 0)),
    "clip_by_norm": spec([A], lambda x: x * min(
        1.0, 1.0 / np.linalg.norm(x)), attrs={"clip_norm": 1.0}, rtol=1e-5),
    # --- nn basics --------------------------------------------------------
    "bias_add": spec([A, np.arange(4.0)], lambda x, b: x + b),
    "linear_layer": spec([A, B_.T], lambda x, w: x @ w),
    "embedding_lookup": spec([A, np.array([2, 0, 1])],
                             lambda t, i: t[i]),
    "standardize": spec([A], lambda x: (x - x.mean(-1, keepdims=True)) /
                        x.std(-1, keepdims=True), rtol=1e-4),
    "global_avg_pool": spec([IMG], lambda x: x.mean((1, 2)),
                            attrs={"data_format": "NHWC"}),
    "global_max_pool": spec([IMG], lambda x: x.max((1, 2)),
                            attrs={"data_format": "NHWC"}),
    "upsampling2d": spec([IMG], lambda x: x.repeat(2, 1).repeat(2, 2),
                         attrs={"factor": (2, 2), "data_format": "NHWC"}),
    # --- image ------------------------------------------------------------
    "image_flip_lr": spec([IMG], lambda x: x[:, :, ::-1]),
    "image_flip_ud": spec([IMG], lambda x: x[:, ::-1]),
    "adjust_contrast": spec([IMG], lambda x: (x - x.mean((1, 2),
                                                        keepdims=True))
                            * 2.0 + x.mean((1, 2), keepdims=True),
                            attrs={"factor": 2.0}, rtol=1e-5),
    "rgb_to_yuv": spec([IMG], lambda x: np.stack([
        0.299 * x[..., 0] + 0.587 * x[..., 1] + 0.114 * x[..., 2],
        -0.14714119 * x[..., 0] - 0.28886916 * x[..., 1]
        + 0.43601035 * x[..., 2],
        0.61497538 * x[..., 0] - 0.51496512 * x[..., 1]
        - 0.10001026 * x[..., 2]], -1), rtol=1e-4, atol=1e-6),
    # --- bitwise ----------------------------------------------------------
    "bitwise_not": spec([UINT], np.invert),
    "shift_right": spec([UINT, np.full_like(UINT, 2)], np.right_shift),
    "toggle_bits": spec([UINT], np.invert),
    "bits_hamming_distance": spec(
        [UINT, np.zeros_like(UINT)],
        lambda a, b: np.array(sum(bin(int(v)).count("1")
                                  for v in (a ^ b).ravel()))),
    # --- linalg extras ----------------------------------------------------
    "gemm": spec([A, B_.T], lambda a, b: a @ b, grad=True),
    "tensordot": spec([A, B_.T, None, None][:2] + [(1,), (0,)],
                      lambda a, b, ax, bx: np.tensordot(a, b, (ax, bx)),
                      attrs={}),
    "log_matrix_determinant": spec(
        [A @ A.T + 3 * np.eye(3)],
        lambda x: np.linalg.slogdet(x).logabsdet, rtol=1e-4),
    "matrix_set_diag": spec(
        [A[:3, :3], np.array([9.0, 8.0, 7.0])],
        lambda x, d: x - np.diag(np.diag(x)) + np.diag(d)),
    "sufficient_statistics": spec(
        [A, None][:1] + [(0,)],
        lambda x, ax: (np.array(x.shape[0]), x.sum(0), (x ** 2).sum(0)),
        attrs={}),
    "normalize_moments": spec(
        [np.array(4.0), A[0] * 4, (A[0] ** 2) * 4],
        lambda c, m, v: (A[0], (A[0] ** 2) - A[0] ** 2 * 0
                         - np.zeros_like(A[0]))
        if False else (m / c, v / c - (m / c) ** 2), rtol=1e-5),
})


def _scatter_ref(ref, idx, upd, op):
    out = ref.copy()
    for j, i in enumerate(idx):
        out[i] = op(out[i], upd[j])
    return out


# --- round-5 breadth wave (ops/breadth.py) ---------------------------------
SEG_D = R.randn(6, 3)
SEG_I = np.array([0, 2, 0, 1, 2, 2])
ND_REF = R.randn(4, 3)
ND_IX = np.array([[0], [2]])
ND_UP = R.randn(2, 3)
U32 = np.array([1, 2, 0x80000001, 7], np.uint32)


def _useg(op_):
    def ref(d, i):
        out = np.zeros((3, d.shape[1]))
        cnt = np.zeros((3, d.shape[1]))
        init = {"max": -np.inf, "min": np.inf, "prod": 1.0}.get(op_, 0.0)
        out[:] = init
        for r, seg in enumerate(i):
            if op_ in ("sum", "mean", "sqrt_n"):
                out[seg] += d[r]
            elif op_ == "prod":
                out[seg] *= d[r]
            elif op_ == "max":
                out[seg] = np.maximum(out[seg], d[r])
            elif op_ == "min":
                out[seg] = np.minimum(out[seg], d[r])
            cnt[seg] += 1
        if op_ == "mean":
            out = out / np.maximum(cnt, 1)
        if op_ == "sqrt_n":
            out = out / np.sqrt(np.maximum(cnt, 1))
        if op_ in ("max", "min"):
            out[cnt == 0] = init    # jax fills empty segments w/ identity
        return out
    return ref


def _wce_ref(t, lo, w):
    lw = 1 + (w - 1) * t
    return np.mean((1 - t) * lo
                   + lw * (np.log1p(np.exp(-np.abs(lo)))
                           + np.maximum(-lo, 0)))


def _fq_ref(x, mn=-6.0, mx=6.0, bits=8):
    qmax = 2 ** bits - 1
    scale = (mx - mn) / qmax
    zp = -mn / scale
    return (np.round(np.clip(x / scale + zp, 0, qmax)) - zp) * scale


LEDGER.update({
    "logaddexp": spec([A, B_], np.logaddexp, grad=True),
    "xlogy": spec([U, P], sps.xlogy, grad=True),
    "sinc": spec([A], np.sinc, grad=True, rtol=1e-4),
    "entr": spec([U], sps.entr),
    "erfinv": spec([U], sps.erfinv, grad=True, rtol=1e-4),
    "heaviside": spec([A, U], np.heaviside),
    "nextafter": spec([A, B_], np.nextafter),
    "ldexp": spec([A, I1], lambda a, i: np.ldexp(a, i.astype(int))),
    "betainc": spec([U * 3 + 0.5, U.T.reshape(3, 4) * 2 + 0.5, U],
                    sps.betainc, rtol=1e-4),
    "polygamma": spec([np.abs(I2).astype(np.float64), P + 0.5],
                      lambda n, x: sps.polygamma(n.astype(int), x),
                      rtol=1e-3),
    "zeta": spec([P + 1.5, P + 0.5], sps.zeta, rtol=1e-4),
    "crelu": spec([A], lambda x: np.concatenate(
        [np.maximum(x, 0), np.maximum(-x, 0)], -1), grad=True),
    "realdiv": spec([A, P], lambda a, b: a / b, grad=True),
    "reduce_dot": spec([A, B_], lambda a, b: np.sum(a * b), grad=True),
    "percentile": spec([A], lambda x: np.percentile(x, 30.0),
                       attrs={"q": 30.0}),
    "roll": spec([A], lambda x: np.roll(x, 2), attrs={"shift": 2}),
    "triu_op": spec([A], np.triu, grad=True),
    "tril_op": spec([A], np.tril, grad=True),
    "nth_element": spec([A], lambda x: np.sort(x, -1)[..., 1],
                        attrs={"n": 1}),
    "sequence_mask": spec([np.array([1, 3, 0])],
                          lambda l: (np.arange(4)[None, :]
                                     < l[:, None]),
                          attrs={"maxlen": 4}),
    "invert_permutation": spec([np.array([2, 0, 1, 3])], np.argsort),
    "ismax": spec([A], lambda x: (x == x.max()).astype(x.dtype)),
    "merge_add": spec([A, B_], lambda a, b: a + b, grad=True),
    "merge_avg": spec([A, B_], lambda a, b: (a + b) / 2, grad=True),
    "merge_max": spec([A, B_], np.maximum, grad=True),
    "merge_max_idx": spec([A, B_],
                          lambda a, b: np.argmax(np.stack([a, b]), 0)),
    "mirror_pad": spec([A], lambda x: np.pad(x, [(1, 1), (2, 2)],
                                             mode="reflect"),
                       attrs={"paddings": np.array([[1, 1], [2, 2]])}),
    "histogram": spec([A], lambda x: np.histogram(x, bins=5)[0],
                      attrs={"num_bins": 5}),
    "histogram_fixed_width": spec(
        [U], lambda x: np.histogram(x, bins=4, range=(0.0, 1.0))[0],
        attrs={"value_range": (0.0, 1.0), "num_bins": 4}),
    "unsorted_segment_sum": spec([SEG_D, SEG_I], _useg("sum"),
                                 attrs={"num_segments": 3}),
    "unsorted_segment_mean": spec([SEG_D, SEG_I], _useg("mean"),
                                  attrs={"num_segments": 3}),
    "unsorted_segment_min": spec([SEG_D, SEG_I], _useg("min"),
                                 attrs={"num_segments": 3}),
    "unsorted_segment_max": spec([SEG_D, SEG_I], _useg("max"),
                                 attrs={"num_segments": 3}),
    "unsorted_segment_prod": spec([SEG_D, SEG_I], _useg("prod"),
                                  attrs={"num_segments": 3}),
    "unsorted_segment_sqrt_n": spec([SEG_D, SEG_I], _useg("sqrt_n"),
                                    attrs={"num_segments": 3}),
    "scatter_nd_update": spec(
        [ND_REF, ND_IX, ND_UP],
        lambda r, i, u: _scatter_ref(r, i[:, 0], u, lambda a, b: b)),
    "scatter_nd_add": spec(
        [ND_REF, ND_IX, ND_UP],
        lambda r, i, u: _scatter_ref(r, i[:, 0], u, lambda a, b: a + b)),
    "scatter_nd_sub": spec(
        [ND_REF, ND_IX, ND_UP],
        lambda r, i, u: _scatter_ref(r, i[:, 0], u, lambda a, b: a - b)),
    "clip_by_averaged_norm": spec(
        [A], lambda x: x * min(1.0, 0.5 / np.sqrt(np.mean(x * x))),
        attrs={"clip_norm": 0.5}),
    "fake_quant_with_min_max_vars": spec([A], _fq_ref),
    "reshape_as": spec([A, B_.reshape(4, 3)],
                       lambda x, t: x.reshape(4, 3), grad=True),
    "tile_to_shape": spec([A[0:1]], lambda x: np.broadcast_to(x, (3, 4)),
                          attrs={"shape": (3, 4)}),
    "relu_layer": spec([A, B_.T, np.zeros(3)],
                       lambda x, w, b: np.maximum(x @ w + b, 0),
                       grad=True),
    "upsampling3d": spec(
        [R.rand(1, 2, 2, 2, 1)],
        lambda x: x.repeat(2, 1).repeat(2, 2).repeat(2, 3)),
    "cyclic_shift": spec(
        [U32, np.array([1, 4, 1, 31], np.uint32)],
        lambda x, s: ((x << s) | (x >> (32 - s))).astype(np.uint32)),
    "cyclic_rshift": spec(
        [U32, np.array([1, 4, 1, 31], np.uint32)],
        lambda x, s: ((x >> s) | (x << (32 - s))).astype(np.uint32)),
    "log_poisson_loss": spec(
        [A, np.abs(B_)],
        lambda lo, t: np.mean(np.exp(lo) - t * lo), rtol=1e-5),
    "weighted_cross_entropy_with_logits": spec(
        [U, A, P], _wce_ref, rtol=1e-5),
})


# ops exercised by dedicated tests elsewhere (file noted); the gate only
# requires that every op is covered SOMEWHERE, mirrored after
# OpValidation.collectCoverageInformation
EXERCISED = {    # nn ops — test_nn / test_layer_breadth / test_layers_ext / test_ops
    # control flow — numerics + grads + serde in test_control_flow
    "while_loop": "test_control_flow",
    "cond_branch": "test_control_flow",
    "scan_loop": "test_control_flow",
    # nlp — numpy-reference checks in test_nlp (TestNlpOpsLedger)
    "skipgram_ns_loss": "test_nlp",
    "cbow_ns_loss": "test_nlp",
    "glove_loss": "test_nlp",
    "conv1d": "test_layer_breadth",
    "conv3d": "test_layer_breadth", 
    "batchnorm": "test_nn", 
    "layer_norm": "test_keras_breadth", "lrn": "test_layer_breadth", "graves_lstm_layer": "test_layers_ext",
    "capsule_routing": "test_layers_ext",
    "yolo2_loss": "test_layers_ext",
    # losses — test_nn/test_autodiff
    "softmax_cross_entropy": "test_autodiff",
    "sparse_softmax_cross_entropy": "test_ops",
    "huber_loss": "test_ops",
    "ctc_loss": "test_ops",
    # random — test_ops (statistical)
    "random_normal": "test_ops", "random_uniform": "test_ops",
    "random_bernoulli": "test_ops", 
    "dropout": "test_nn",
    # linalg heavy — test_ops
    "svd": "test_ops", "qr": "test_ops", "lu": "test_ops",
    "eig": "test_ops", "cholesky": "test_ops", "solve": "test_ops",
    "matrix_band_part": "test_ops", "matrix_diag": "test_ops",
    "moments": "test_ops", # segment/scatter/structure — test_ops
    "scatter_add": "test_ops", "confusion_matrix": "test_ops",
    "clip_by_norm": "test_ops", 
    "prelu": "test_keras_breadth", # image — test_ops
    "resize_bilinear": "test_ops", "resize_nearest_neighbor": "test_ops",
    "rgb_to_hsv": "test_ops", "hsv_to_rgb": "test_ops",
    "rgb_to_grs": "test_ops", # bitwise — test_ops
    "bitwise_and": "test_ops", "bitwise_or": "test_ops",
    "bitwise_xor": "test_ops", "shift_left": "test_ops", # tf compat — test_tf_import / test_registry_coverage
    "tf_reshape": "test_registry_coverage", 
    "tf_reduce": "test_registry_coverage",
    "tf_gather": "test_registry_coverage",
    # conv_lstm2d: golden numerics vs independent numpy ConvLSTM in
    # test_keras_3d_shared; init_state is its shape helper
    "conv_lstm2d": "test_keras_3d_shared",
    "conv_lstm2d_init_state": "test_keras_3d_shared",
    # channel-wise dropout: behavior pinned by the SpatialDropout layer
    # import + training tests
    "spatial_dropout": "test_keras_3d_shared",
}


def _np_sru(x, c0, w, b):
    """Numpy SRU reference (Lei et al. 2018) for the ledger."""
    d = x.shape[-1]
    sig = lambda v: 1.0 / (1.0 + np.exp(-v))
    c = c0.copy()
    outs = []
    for t in range(x.shape[1]):
        z = x[:, t] @ w
        xt, zf, zr = z[:, :d], z[:, d:2 * d], z[:, 2 * d:]
        f = sig(zf + b[:d])
        r = sig(zr + b[d:])
        c = f * c + (1 - f) * xt
        outs.append(r * np.tanh(c) + (1 - r) * x[:, t])
    return np.stack(outs, 1), c


def _np_rnn(x, h0, w, u, b):
    h = h0.copy()
    outs = []
    for t in range(x.shape[1]):
        h = np.tanh(x[:, t] @ w + h @ u + b)
        outs.append(h)
    return np.stack(outs, 1), h


_SEQ = R.randn(2, 3, 4).astype(np.float64) * 0.5
_C0 = np.zeros((2, 4))
_WSRU = R.randn(4, 12).astype(np.float64) * 0.4
_BSRU = R.randn(8).astype(np.float64) * 0.1
_WR = R.randn(4, 4) * 0.4
_UR = R.randn(4, 4) * 0.4
_BR = R.randn(4) * 0.1
_LOGITS = R.randn(3, 5) * 2.0
_ONEHOT = np.eye(5)[R.randint(0, 5, 3)]
_CLS = R.randint(0, 5, 3).astype(np.int64)
_GEMM_A = R.randn(2, 3, 4) * 0.5
_GEMM_B = R.randn(2, 4, 5) * 0.5
_GEMM_C = R.randn(2, 3, 5) * 0.5
_LSQ_A = R.randn(5, 3) + np.eye(5, 3) * 3.0   # well-conditioned
_LSQ_B = R.randn(5, 2)
_BITS = R.randn(2, 16)


def _np_softmax_xent(logits, labels):
    m = logits - logits.max(-1, keepdims=True)
    logp = m - np.log(np.exp(m).sum(-1, keepdims=True))
    return -(labels * logp).sum(-1)


LEDGER.update({
    # --- breadth2: creation / shape tail ---------------------------------
    "eye": spec([], lambda: np.eye(3, 5), attrs={"rows": 3, "cols": 5}),
    "range": spec([], lambda: np.arange(2, 10, 2),
                  attrs={"start": 2, "limit": 10, "delta": 2}),
    "lin_space": spec([], lambda: np.linspace(0.0, 1.0, 5,
                                              dtype=np.float32),
                      attrs={"start": 0.0, "stop": 1.0, "num": 5}),
    "create": spec([], lambda: np.zeros((2, 3), np.float32),
                   attrs={"shape": (2, 3)}),
    "ones_as": spec([A], np.ones_like),
    "zeros_as": spec([A], np.zeros_like),
    "fill_as": spec([A], lambda x: np.full_like(x, 2.5),
                    attrs={"value": 2.5}),
    "reshapeas": spec([A, A.reshape(4, 3)],
                      lambda x, y: x.reshape(4, 3)),
    "assign": spec([A, B_], lambda x, y: y, grad=False),
    "size_at": spec([A], lambda x: np.int64(4), attrs={"dim": 1}),
    "shapes_of": spec([A], lambda x: np.asarray([3, 4], np.int64)),
    "set_shape": spec([A], lambda x: x.reshape(2, 6),
                      attrs={"shape": (2, 6)}),
    "broadcast_dynamic_shape": spec(
        [np.asarray([3, 1]), np.asarray([1, 4])],
        lambda a, b: np.asarray([3, 4], np.int64)),
    "noop": spec([A], lambda x: np.int32(0)),
    "expose": spec([A], lambda x: x, grad=True),
    "where": spec([BOOL, A, B_], lambda c, x, y: np.where(c, x, y)),
    "unique_with_counts": spec(
        [I1.ravel()],
        lambda x: np.unique(x, return_inverse=True, return_counts=True)),
    # --- breadth2: scalar comparisons ------------------------------------
    "eq_scalar": spec([I1], lambda x: x == 2, attrs={"scalar": 2}),
    "neq_scalar": spec([I1], lambda x: x != 2, attrs={"scalar": 2}),
    "gt_scalar": spec([A], lambda x: x > 0.1, attrs={"scalar": 0.1}),
    "gte_scalar": spec([I1], lambda x: x >= 2, attrs={"scalar": 2}),
    "lt_scalar": spec([A], lambda x: x < 0.1, attrs={"scalar": 0.1}),
    "lte_scalar": spec([I1], lambda x: x <= 2, attrs={"scalar": 2}),
    # --- breadth2: math tail ---------------------------------------------
    "reversemod": spec([I2, I1], lambda x, y: np.mod(y, x)),
    "compare_and_bitpack": spec(
        [_BITS], lambda x: np.packbits((x > 0.0), axis=-1)),
    "clipbyavgnorm": spec(
        [A], lambda x: x * min(1.0, 0.05 / (np.linalg.norm(x) / x.size)),
        attrs={"clip_norm": 0.05}, grad=True),
    "check_numerics": spec([A], lambda x: x, grad=True),
    "is_numeric_tensor": spec([A], lambda x: np.bool_(True)),
    # --- breadth2: recurrent ---------------------------------------------
    "sru_cell": spec(
        [_SEQ[:, 0], _C0, _WSRU, _BSRU],
        lambda x, c, w, b: tuple(
            a[:, 0] if a.ndim == 3 else a
            for a in _np_sru(x[:, None], c, w, b)), rtol=1e-6),
    "sru": spec([_SEQ, _C0, _WSRU, _BSRU], _np_sru, rtol=1e-6),
    "sru_bi": spec(
        [_SEQ, _C0, _C0, _WSRU, _BSRU, _WSRU, _BSRU],
        lambda x, cf, cb, wf, bf, wb, bb: (
            np.concatenate([_np_sru(x, cf, wf, bf)[0],
                            _np_sru(x[:, ::-1], cb, wb, bb)[0][:, ::-1]],
                           axis=-1),
            _np_sru(x, cf, wf, bf)[1],
            _np_sru(x[:, ::-1], cb, wb, bb)[1]), rtol=1e-6),
    "static_rnn": spec([_SEQ, _C0, _WR, _UR, _BR], _np_rnn, rtol=1e-6),
    "dynamic_rnn": spec(
        [_SEQ, _C0, _WR, _UR, _BR, np.asarray([2, 3])],
        lambda x, h, w, u, b, sl: (
            _np_rnn(x, h, w, u, b)[0]
            * (np.arange(3)[None, :] < sl[:, None])[..., None],
            np.stack([_np_rnn(x, h, w, u, b)[0][i, sl[i] - 1]
                      for i in range(2)])), rtol=1e-6),
    "static_bidirectional_rnn": spec(
        [_SEQ, _C0, _C0, _WR, _UR, _BR, _WR, _UR, _BR],
        lambda x, hf, hb, wf, uf, bf, wb, ub, bb: (
            np.concatenate([_np_rnn(x, hf, wf, uf, bf)[0],
                            _np_rnn(x[:, ::-1], hb, wb, ub, bb)[0][:, ::-1]],
                           axis=-1),
            _np_rnn(x, hf, wf, uf, bf)[1],
            _np_rnn(x[:, ::-1], hb, wb, ub, bb)[1]), rtol=1e-6),
    # full-length case: equals static; the masked path is covered by the
    # dynamic_rnn entry above (same masking code path)
    "dynamic_bidirectional_rnn": spec(
        [_SEQ, _C0, _C0, _WR, _UR, _BR, _WR, _UR, _BR],
        lambda x, hf, hb, wf, uf, bf, wb, ub, bb: (
            np.concatenate([_np_rnn(x, hf, wf, uf, bf)[0],
                            _np_rnn(x[:, ::-1], hb, wb, ub, bb)[0][:, ::-1]],
                           axis=-1),
            _np_rnn(x, hf, wf, uf, bf)[1],
            _np_rnn(x[:, ::-1], hb, wb, ub, bb)[1]), rtol=1e-6),
    # --- breadth2: losses -------------------------------------------------
    "softmax_cross_entropy_loss_with_logits": spec(
        [_LOGITS, _ONEHOT], _np_softmax_xent, grad=True, rtol=1e-6),
    "sparse_softmax_cross_entropy_loss_with_logits": spec(
        [_CLS, _LOGITS],
        lambda y, lg: _np_softmax_xent(lg, np.eye(5)[y]), rtol=1e-6),
    # --- breadth2: linalg -------------------------------------------------
    "batched_gemm": spec(
        [_GEMM_A, _GEMM_B, _GEMM_C],
        lambda a, b, c: 2.0 * np.matmul(a, b) + 0.5 * c,
        attrs={"alpha": 2.0, "beta": 0.5}, rtol=1e-6),
    "solve_ls": spec(
        [_LSQ_A, _LSQ_B],
        lambda a, b: np.linalg.lstsq(a, b, rcond=None)[0], rtol=1e-4),
    # --- legacy opNum tail (legacy_ops.h families) ------------------------
    "amax": spec([A], lambda x: np.max(np.abs(x))),
    "amin": spec([A], lambda x: np.min(np.abs(x))),
    "amean": spec([A], lambda x: np.mean(np.abs(x)), grad=True),
    "asum": spec([A], lambda x: np.sum(np.abs(x))),
    "squared_norm": spec([A], lambda x: np.sum(x * x), grad=True),
    "norm_p": spec([A], lambda x: np.sum(np.abs(x) ** 3) ** (1 / 3),
                   attrs={"p": 3.0}, rtol=1e-6),
    "entropy": spec([U], lambda x: -np.sum(x * np.log(x)), grad=True,
                    rtol=1e-6),
    "shannon_entropy": spec([U], lambda x: -np.sum(x * np.log2(x)),
                            rtol=1e-6),
    "log_entropy": spec([U], lambda x: np.log(-np.sum(x * np.log(x))),
                        rtol=1e-6),
    # per-axis form; the no-dims form reduces the FLATTENED array to one
    # scalar like the sibling index reduces (checked by the second pair)
    "first_index": spec([np.asarray([[0.0, 2.0, 3.0], [0.0, 0.0, 0.0]])],
                        lambda x: np.asarray([1, -1]),
                        attrs={"condition": "gt", "value": 1.0,
                               "dims": 1}),
    "last_index": spec([np.asarray([[0.0, 2.0, 3.0], [0.0, 0.0, 0.0]])],
                       lambda x: np.asarray([2, -1]),
                       attrs={"condition": "gt", "value": 1.0,
                              "dims": 1}),
    "iamax": spec([np.asarray([1.0, -5.0, 3.0])], lambda x: np.int64(1)),
    "iamin": spec([np.asarray([1.0, -5.0, 3.0])], lambda x: np.int64(0)),
    "match_condition": spec([A], lambda x: np.sum(x > 0.1),
                            attrs={"condition": "gt", "value": 0.1}),
    "logical_and": spec([I1, I2], lambda x, y: (x != 0) & (y != 0)),
    "logical_or": spec([I1, I2], lambda x, y: (x != 0) | (y != 0)),
    "logical_xor": spec([I1, I2], lambda x, y: (x != 0) ^ (y != 0)),
    "logical_not": spec([I1], lambda x: x == 0),
    "compare_and_set": spec(
        [np.asarray([1.0, 2.0, 3.0])], lambda x: np.asarray([1.0, 9.0, 3.0]),
        attrs={"compare": 2.0, "set_value": 9.0, "condition": "eq"}),
    "compare_and_replace": spec(
        [A, B_], lambda x, y: np.where(x < 0.0, y, x),
        attrs={"compare": 0.0, "condition": "lt"}),
    "affine": spec([A], lambda x: 2.0 * x + 1.0,
                   attrs={"a": 2.0, "b": 1.0}, grad=True),
    "set_range": spec([A], lambda x: np.clip(x, -0.5, 0.5),
                      attrs={"min": -0.5, "max": 0.5}),
    "scaled_tanh": spec([A], lambda x: 1.7159 * np.tanh(2.0 / 3.0 * x),
                        grad=True, rtol=1e-6),
    "times_one_minus": spec([U], lambda x: x * (1 - x), grad=True),
    "safe_divide": spec(
        [A, np.asarray(I1, np.float64)],
        lambda x, y: np.where(y == 0, 0.0, x / np.where(y == 0, 1, y))),
    "relative_error": spec(
        [A, B_], lambda x, y: np.where(
            np.maximum(np.abs(x), np.abs(y)) == 0, 0.0,
            np.abs(x - y) / np.maximum(np.abs(x), np.abs(y))), rtol=1e-6),
    "stabilize": spec([A * 100], lambda x: np.clip(x * 2.0, -100, 100),
                      attrs={"k": 2.0, "cutoff": -100.0}),
    "lstm_clip": spec([A * 3], lambda x: np.clip(x, -1.5, 1.5),
                      attrs={"clip": 1.5}),
    "is_negative": spec([A], lambda x: x < 0),
    "is_positive": spec([A], lambda x: x > 0),
    "is_inf_or_nan": spec(
        [np.asarray([1.0, np.inf, np.nan, -np.inf])],
        lambda x: np.asarray([False, True, True, True])),
})


# ops exercised HERE with invariant/shape checks (conv/rnn/random/structural
# ops whose full numerics are covered by layer- and import-level golden
# tests; the smoke spec keeps them in the in-file ledger so the coverage
# gate stays executable, not a pointer)
IMG_N = R.rand(2, 5, 5, 3).astype(np.float32)
SMOKE = {
    "conv2d": lambda f: f(IMG_N, np.ones((1, 1, 3, 4), np.float32),
                          data_format="NHWC").shape == (2, 5, 5, 4),
    "deconv2d": lambda f: f(IMG_N, np.ones((2, 2, 4, 3), np.float32),
                            strides=(2, 2), data_format="NHWC"
                            ).shape == (2, 10, 10, 4),
    "depthwise_conv2d": lambda f: f(IMG_N,
                                    np.ones((3, 3, 3, 2), np.float32),
                                    data_format="NHWC"
                                    ).shape == (2, 5, 5, 6),
    "separable_conv2d": lambda f: f(IMG_N,
                                    np.ones((3, 3, 3, 1), np.float32),
                                    np.ones((1, 1, 3, 4), np.float32),
                                    data_format="NHWC"
                                    ).shape == (2, 5, 5, 4),
    "max_pool2d": lambda f: np.allclose(
        np.asarray(f(IMG_N, kernel=(5, 5), data_format="NHWC"))[:, 0, 0],
        IMG_N.max((1, 2))),
    "avg_pool2d": lambda f: np.allclose(
        np.asarray(f(IMG_N, kernel=(5, 5), data_format="NHWC"))[:, 0, 0],
        IMG_N.mean((1, 2)), atol=1e-6),
    "pnorm_pool2d": lambda f: f(IMG_N, kernel=(2, 2), data_format="NHWC"
                                ).shape == (2, 2, 2, 3),
    "max_pool3d": lambda f: f(R.rand(1, 4, 4, 4, 2).astype(np.float32),
                              kernel=(2, 2, 2), data_format="NDHWC"
                              ).shape == (1, 2, 2, 2, 2),
    "avg_pool3d": lambda f: f(R.rand(1, 4, 4, 4, 2).astype(np.float32),
                              kernel=(2, 2, 2), data_format="NDHWC"
                              ).shape == (1, 2, 2, 2, 2),
    "im2col": lambda f: f(R.rand(1, 2, 4, 4).astype(np.float32),
                          kernel=(2, 2)).ndim >= 3,
    "batchnorm_train": lambda f: all(np.isfinite(np.asarray(o)).all()
                                     for o in f(IMG_N, np.ones(3), np.zeros(3),
                                                np.zeros(3), np.ones(3),
                                                axis=3)),
    "lstm_cell": lambda f: f(A32(2, 3), A32(2, 4), A32(2, 4),
                             A32(3, 16), A32(4, 16), np.zeros(16, np.float32)
                             )[0].shape == (2, 4),
    "lstm_layer": lambda f: f(A32(2, 5, 3), np.zeros((2, 4), np.float32),
                              np.zeros((2, 4), np.float32), A32(3, 16),
                              A32(4, 16), np.zeros(16, np.float32)
                              )[0].shape == (2, 5, 4),
    "gru_cell": lambda f: f(A32(2, 3), A32(2, 4), A32(3, 12), A32(4, 12),
                            np.zeros(12, np.float32),
                            np.zeros(12, np.float32)).shape == (2, 4),
    "gru_layer": lambda f: f(A32(2, 5, 3), np.zeros((2, 4), np.float32),
                             A32(3, 12), A32(4, 12),
                             np.zeros(12, np.float32),
                             np.zeros(12, np.float32))[0].shape == (2, 5, 4),
    "simple_rnn_cell": lambda f: f(A32(2, 3), A32(2, 4), A32(3, 4),
                                   A32(4, 4), np.zeros(4, np.float32)
                                   ).shape == (2, 4),
    "simple_rnn_layer": lambda f: f(A32(2, 5, 3),
                                    np.zeros((2, 4), np.float32),
                                    A32(3, 4), A32(4, 4),
                                    np.zeros(4, np.float32)
                                    )[0].shape == (2, 5, 4),
    "rnn_init_state": lambda f: np.asarray(
        f(A32(2, 5, 3), units=7)).shape == (2, 7)
        and not np.asarray(f(A32(2, 5, 3), units=7)).any(),
    "graves_lstm_cell": lambda f: f(A32(2, 3), A32(2, 4), A32(2, 4),
                                    A32(3, 16), A32(4, 16),
                                    np.zeros((3, 4), np.float32),
                                    np.zeros(16, np.float32)
                                    )[0].shape == (2, 4),
    "capsule_squash": lambda f: float(jnp.linalg.norm(
        f(A32(2, 5) * 100), axis=-1).max()) <= 1.0 + 1e-5,
    "dot_product_attention": lambda f: f(A32(2, 4, 8), A32(2, 4, 8),
                                         A32(2, 4, 8)).shape == (2, 4, 8),
    "multi_head_dot_product_attention": lambda f: f(
        A32(2, 4, 8), A32(2, 4, 8), A32(2, 4, 8), A32(8, 8), A32(8, 8),
        A32(8, 8), A32(8, 8), nheads=2).shape == (2, 4, 8),
    # causal SDPA: row 0 may only attend to position 0 — equals plain
    # softmax(qk)v restricted to the first key (checked vs full numpy
    # reference in test_gpt_remat.py)
    "scaled_dot_product_attention": lambda f: f(
        A32(2, 2, 4, 8), A32(2, 2, 4, 8), A32(2, 2, 4, 8),
        causal=True).shape == (2, 2, 4, 8),
    "mean_pairwssqerr_loss": lambda f: float(
        f(A32(3, 4), A32(3, 4))) >= 0,
    "cosine_distance_loss": lambda f: np.isfinite(float(
        f(A32(3, 4), A32(3, 4)))),
    # random: deterministic under a key + correct moments (loose bounds)
    "random_exponential": lambda f: _stat(f(shape=(20000,), lam=2.0,
                                            seed=1), 0.5, 0.06),
    "random_binomial": lambda f: _stat(f(shape=(20000,), trials=10,
                                         prob=0.3, seed=1), 3.0, 0.1),
    "random_gamma": lambda f: _stat(f(shape=(20000,), alpha=2.0, seed=1),
                                    2.0, 0.1),
    "random_lognormal": lambda f: _stat(
        f(shape=(20000,), mean=0.0, stddev=0.25, seed=1),
        float(np.exp(0.03125)), 0.05),
    "random_poisson": lambda f: _stat(f(shape=(20000,), lam=4.0, seed=1),
                                      4.0, 0.15),
    "random_truncated_normal": lambda f: float(jnp.abs(
        f(shape=(20000,), seed=1)).max()) <= 2.0 + 1e-5,
    "random_multinomial": lambda f: np.asarray(
        f(np.log(np.ones((2, 5)) / 5), num_samples=7, seed=1)
        ).shape == (2, 7),
    "random_shuffle": lambda f: sorted(np.asarray(
        f(np.arange(10), seed=3)).tolist()) == list(range(10)),
    "alpha_dropout": lambda f: np.asarray(
        f(A32(50, 50), p=0.5, seed=1)).shape == (50, 50),
    "gaussian_dropout": lambda f: np.asarray(
        f(A32(50, 50), rate=0.5, seed=1)).shape == (50, 50),
    "gaussian_noise": lambda f: abs(float(jnp.std(
        f(np.zeros((300, 300), np.float32), stddev=0.5, seed=1))) - 0.5
        ) < 0.02,
    # linalg solvers: residual invariants
    "triangular_solve": lambda f: np.allclose(
        np.tril(TRI) @ np.asarray(f(np.tril(TRI), RHS, lower=True)), RHS,
        atol=1e-4),
    "lstsq": lambda f: np.asarray(f(A32(5, 3), A32(5, 1))).shape == (3, 1),
    "batched_matmul": lambda f: np.allclose(
        np.asarray(f(BM1, BM2)), BM1 @ BM2, atol=1e-5),
    "bf16_matmul": lambda f: np.asarray(f(A32(4, 8), A32(8, 4))
                                        ).shape == (4, 4),
    "einsum": lambda f: np.allclose(
        np.asarray(f(EIN1, EIN2, equation="ij,jk->ik")), EIN1 @ EIN2,
        atol=1e-5),
    "dynamic_stitch": lambda f: np.asarray(
        f(np.array([0, 2]), np.array([1]),
          np.stack([np.ones(3), 3 * np.ones(3)]), 2 * np.ones((1, 3)))
        ).shape == (3, 3),
    "meshgrid": lambda f: np.asarray(
        f(np.arange(3.0), np.arange(2.0))[0]).shape == (2, 3),
    "space_to_depth": lambda f: f(IMG_N[:, :4, :4], block_size=2,
                                  data_format="NHWC").shape == (2, 2, 2, 12),
    "depth_to_space": lambda f: f(R.rand(1, 2, 2, 12).astype(np.float32),
                                  block_size=2, data_format="NHWC"
                                  ).shape == (1, 4, 4, 3),
    "space_to_batch": lambda f: f(IMG_N[:, :4, :4],
                                  block_shape=np.array([2, 2]),
                                  paddings=np.zeros((2, 2), np.int64)
                                  ).shape == (8, 2, 2, 3),
    "batch_to_space": lambda f: f(R.rand(8, 2, 2, 3).astype(np.float32),
                                  block_shape=np.array([2, 2]),
                                  crops=np.zeros((2, 2), np.int64)
                                  ).shape == (2, 4, 4, 3),
    "clip_by_global_norm": lambda f: np.isfinite(np.asarray(
        f(A32(3, 3), A32(3, 3), clip_norm=1.0)[0])).all(),
    # image
    "resize_bicubic": lambda f: f(IMG_N, height=8, width=8
                                  ).shape == (2, 8, 8, 3),
    "crop_and_resize": lambda f: f(
        IMG_N, np.array([[0.0, 0.0, 1.0, 1.0]], np.float32),
        np.array([0]), crop_height=3, crop_width=3).shape == (1, 3, 3, 3),
    "non_max_suppression": lambda f: np.asarray(f(
        np.array([[0, 0, 1, 1], [0, 0, 1.05, 1.05], [2, 2, 3, 3]],
                 np.float32),
        np.array([0.9, 0.8, 0.7], np.float32),
        max_output_size=2)[0]).tolist() == [0, 2],
    "extract_image_patches": lambda f: f(
        IMG_N[:, :4, :4], ksizes=(2, 2), strides=(2, 2), rates=(1, 1)
        ).shape[0] == 2,
    "yuv_to_rgb": lambda f: np.allclose(
        np.asarray(f(registry.get_op("rgb_to_yuv").fn(IMG_N))), IMG_N,
        atol=1e-4),
    "adjust_hue": lambda f: f(IMG_N, delta=0.2).shape == IMG_N.shape,
    "adjust_saturation": lambda f: f(IMG_N, factor=1.5
                                     ).shape == IMG_N.shape,
    "cyclic_shift_left": lambda f: np.asarray(
        f(np.array([1], np.uint8), np.array([1], np.uint8))
        )[0] == 2,
    "cyclic_shift_right": lambda f: np.asarray(
        f(np.array([2], np.uint8), np.array([1], np.uint8)))[0] == 1,
    # tf compat structural ops (importer-emitted; direct calls here)
    "tf_fill": lambda f: np.asarray(f(np.array([2, 3]), 1.5)
                                    ).shape == (2, 3),
    "tf_range": lambda f: np.asarray(f(np.array(1), np.array(7),
                                       np.array(2))).tolist() == [1, 3, 5],
    "tf_broadcast_to": lambda f: f(np.ones(3, np.float32),
                                   np.array([2, 3])).shape == (2, 3),
    "tf_tile": lambda f: f(np.ones((1, 2), np.float32),
                           np.array([2, 1])).shape == (2, 2),
    "tf_expand_dims": lambda f: f(np.ones(3, np.float32),
                                  np.array(0)).shape == (1, 3),
    "tf_squeeze": lambda f: f(np.ones((1, 3, 1), np.float32)
                              ).shape == (3,),
    "tf_transpose": lambda f: f(np.ones((2, 3), np.float32),
                                np.array([1, 0])).shape == (3, 2),
    "tf_concat": lambda f: f(np.ones((2, 2), np.float32),
                             np.zeros((2, 2), np.float32),
                             np.array(1)).shape == (2, 4),
    "tf_slice": lambda f: np.allclose(np.asarray(
        f(np.arange(12.0).reshape(3, 4), np.array([1, 0]),
          np.array([2, 2]))), np.arange(12.0).reshape(3, 4)[1:3, 0:2]),
    "tf_strided_slice": lambda f: f(
        np.arange(12.0).reshape(3, 4), np.array([0, 1]), np.array([3, 4]),
        np.array([2, 1])).shape == (2, 3),
    "strided_slice_masked": lambda f: f(
        np.arange(12.0).reshape(3, 4), begin=(0, 1), end=(3, 4),
        strides=(1, 1)).shape == (3, 3),
    "gather_batch_dims": lambda f: f(
        np.arange(24.0).reshape(2, 3, 4),
        np.array([[0, 2], [1, 0]]), axis=1, batch_dims=1
        ).shape == (2, 2, 4),
    "tf_one_hot": lambda f: np.allclose(np.asarray(
        f(np.array([0, 2]), np.array(3), np.array(1.0, np.float32),
          np.array(0.0, np.float32))), np.eye(3)[[0, 2]]),
    "tf_split": lambda f: len(f(np.array(1), np.ones((2, 4), np.float32),
                                num_split=2)) == 2,
    "tf_split_v": lambda f: [np.asarray(t).shape[1] for t in f(
        np.ones((2, 4), np.float32), np.array([1, 3]),
        np.array(1))] == [1, 3],
    "tf_pad": lambda f: f(np.ones((2, 2), np.float32),
                          np.array([[1, 1], [0, 0]])).shape == (4, 2),
    "tf_cumsum": lambda f: np.allclose(np.asarray(
        f(np.arange(4.0), np.array(0))), np.cumsum(np.arange(4.0))),
    "tf_argmax": lambda f: np.asarray(f(np.array([[1.0, 3.0, 2.0]]),
                                        np.array(1))).tolist() == [1],
    "tf_argmin": lambda f: np.asarray(f(np.array([[1.0, 3.0, 2.0]]),
                                        np.array(1))).tolist() == [0],
    "tf_addn": lambda f: np.allclose(np.asarray(
        f(np.ones(3, np.float32), np.ones(3, np.float32))), 2.0),
    "tf_fused_batch_norm": lambda f: all(
        np.isfinite(np.asarray(o)).all()
        for o in f(IMG_N, np.ones(3, np.float32), np.zeros(3, np.float32),
                   np.zeros(3, np.float32), np.ones(3, np.float32))),
    # --- breadth2 nn/image tail ------------------------------------------
    "pointwise_conv2d": lambda f: np.allclose(
        np.asarray(f(IMG_N, np.ones((1, 1, 3, 2), np.float32))),
        IMG_N.sum(-1, keepdims=True).repeat(2, -1)),
    "sep_conv2d": lambda f: f(
        IMG_N, np.ones((3, 3, 3, 2), np.float32),
        np.ones((1, 1, 6, 4), np.float32)).shape == (2, 5, 5, 4),
    "deconv3d": lambda f: f(
        np.ones((1, 2, 2, 2, 3), np.float32),
        np.ones((2, 2, 2, 4, 3), np.float32),
        strides=(2, 2, 2)).shape == (1, 4, 4, 4, 4),
    "max_pool_with_argmax": lambda f: (
        np.asarray(f(np.arange(16.0).reshape(1, 4, 4, 1))[0]).ravel()
        .tolist() == [5.0, 7.0, 13.0, 15.0]
        and np.asarray(f(np.arange(16.0).reshape(1, 4, 4, 1))[1]).ravel()
        .tolist() == [5, 7, 13, 15]),
    "pnormpool2d": lambda f: np.allclose(
        np.asarray(f(np.ones((1, 4, 4, 1), np.float32), p=2.0)),
        2.0),     # sqrt(4 ones) per 2x2 window
    "fused_batch_norm": lambda f: (
        np.allclose(np.asarray(f(IMG_N, np.ones(3, np.float32),
                                 np.zeros(3, np.float32))[1]),
                    IMG_N.mean((0, 1, 2)), rtol=1e-5)
        and np.allclose(np.asarray(f(IMG_N, np.ones(3, np.float32),
                                     np.zeros(3, np.float32))[0])
                        .mean((0, 1, 2)), 0.0, atol=1e-5)),
    "non_max_suppression_overlaps": lambda f: (
        np.asarray(f(np.eye(3), np.asarray([0.9, 0.8, 0.7]), 3,
                     overlap_threshold=0.5)[0]).tolist() == [0, 1, 2]),
    "print_variable": lambda f: np.allclose(
        np.asarray(f(np.ones(3, np.float32))), 1.0),
}




def A32(*shape):
    return R.rand(*shape).astype(np.float32) - 0.5


TRI = (np.eye(4) * 3 + R.rand(4, 4) * 0.2).astype(np.float32)
EIN1 = R.rand(3, 4).astype(np.float32)
EIN2 = R.rand(4, 5).astype(np.float32)
RHS = R.rand(4, 2).astype(np.float32)
BM1 = R.rand(2, 3, 4).astype(np.float32)
BM2 = R.rand(2, 4, 5).astype(np.float32)


def _stat(sample, want_mean, tol):
    return abs(float(jnp.mean(sample)) - want_mean) <= tol * max(
        want_mean, 1.0)


def _np32(*shape):
    return R.randn(*shape).astype(np.float32)


_CLONE_IN = _np32(3, 2)
_RGB_IN = np.abs(_np32(1, 2, 2, 3)) + 0.1
_YIQ_M = np.array([[0.299, 0.587, 0.114],
                   [0.5959, -0.2746, -0.3213],
                   [0.2115, -0.5227, 0.3112]], np.float32)
_YIQ_IN = np.einsum("...c,yc->...y", _RGB_IN, _YIQ_M)
_SPD_C = _np32(3, 3)
_SPD = (_SPD_C @ _SPD_C.T + 3 * np.eye(3)).astype(np.float32)
_MPX = _np32(1, 4, 4, 2)
_BC_IN = _np32(2, 2)


SMOKE.update({
    # list family: write/read/scatter/gather round-trips on the stacked
    # representation (reference generic/list semantics)
    "create_list": lambda f: f(A32(2, 3), size=4).shape == (4, 2, 3),
    "write_list": lambda f: np.allclose(
        f(np.zeros((3, 2)), np.ones(2), index=1)[1], 1.0),
    "read_list": lambda f: np.allclose(
        f(np.arange(6).reshape(3, 2), index=2), [4, 5]),
    "gather_list": lambda f: f(np.arange(6).reshape(3, 2),
                               np.array([2, 0])).shape == (2, 2),
    "scatter_list": lambda f: np.allclose(
        f(np.zeros((3, 2)), np.array([1]), np.ones((1, 2)))[1], 1.0),
    "stack_list": lambda f: f(np.arange(6).reshape(3, 2)).shape == (3, 2),
    "unstack_list": lambda f: f(np.arange(6).reshape(3, 2)).shape == (3, 2),
    "split_list": lambda f: [x.shape[0] for x in
                             f(np.arange(10).reshape(5, 2),
                               sizes=(2, 3))] == [2, 3],
    "size_list": lambda f: int(f(np.zeros((7, 2)))) == 7,
    "pick_list": lambda f: f(np.arange(6).reshape(3, 2),
                             np.array([0, 2])).shape == (4,),
    "clone_list": lambda f: np.allclose(f(_CLONE_IN), _CLONE_IN),
    # dtype casts
    "to_double": lambda f: f(A32(2, 2)).dtype == np.float64,
    "to_float32": lambda f: f(A.astype(np.float64)).dtype == np.float32,
    "to_float16": lambda f: f(A32(2, 2)).dtype == np.float16,
    "to_int32": lambda f: f(A32(2, 2)).dtype == np.int32,
    "to_int64": lambda f: f(A32(2, 2)).dtype == np.int64,
    "to_uint32": lambda f: f(np.abs(A32(2, 2))).dtype == np.uint32,
    "to_uint64": lambda f: f(np.abs(A32(2, 2))).dtype == np.uint64,
    "bitcast": lambda f: np.array_equal(
        np.asarray(f(f(_BC_IN, dtype="int32"), dtype="float32")), _BC_IN),
    # math/structural
    "tri_op": lambda f: np.array_equal(np.asarray(f(n=3, m=3, k=0)),
                                       np.tri(3, 3)),
    "sqrtm": lambda f: np.allclose(
        (lambda s: s @ s)(np.asarray(f(_SPD))), _SPD, atol=1e-3),
    "is_non_decreasing": lambda f: bool(f(np.array([1.0, 2.0, 2.0]))) and
    not bool(f(np.array([2.0, 1.0]))),
    "is_strictly_increasing": lambda f: bool(f(np.array([1.0, 2.0, 3.0])))
    and not bool(f(np.array([1.0, 1.0]))),
    "listdiff": lambda f: np.array_equal(
        np.asarray(f(np.array([1, 2, 3, 4]), np.array([2, 4]))[0]),
        [1, 3]),
    "identity_n": lambda f: np.allclose(
        np.asarray(f(np.ones(2), np.zeros(2))[0]), 1.0),
    "fake_quant_with_min_max_vars_per_channel": lambda f: np.isfinite(
        np.asarray(f(A32(2, 3), np.full(3, -6.0, np.float32),
                     np.full(3, 6.0, np.float32)))).all(),
    # image tail
    "resize_area": lambda f: np.allclose(
        np.asarray(f(np.arange(16, dtype=np.float32)
                     .reshape(1, 4, 4, 1), height=2, width=2))
        .reshape(2, 2),
        np.arange(16, dtype=np.float32).reshape(4, 4)
        .reshape(2, 2, 2, 2).mean(axis=(1, 3))),
    "rgb_to_yiq": lambda f: f(A32(2, 4, 4, 3)).shape == (2, 4, 4, 3),
    "yiq_to_rgb": lambda f: np.allclose(np.asarray(f(_YIQ_IN)), _RGB_IN,
                                        atol=1e-4),
    "random_crop": lambda f: f(A32(1, 6, 6, 3),
                               size=(1, 4, 4, 3)).shape == (1, 4, 4, 3),
    "draw_bounding_boxes": lambda f: f(
        np.zeros((1, 8, 8, 3), np.float32),
        np.array([[[0.1, 0.1, 0.8, 0.8]]], np.float32)).sum() > 0,
    "dilation2d": lambda f: np.allclose(     # zero filter == max pool
        np.asarray(f(np.arange(16, dtype=np.float32)
                     .reshape(1, 4, 4, 1),
                     np.zeros((2, 2, 1), np.float32),
                     strides=(2, 2), padding="VALID")).reshape(2, 2),
        [[5, 7], [13, 15]]),
    "col2im": lambda f: float(np.asarray(f(
        np.ones((1, 1, 2, 2, 2, 2), np.float32), height=3, width=3,
        kernel=(2, 2), stride=(1, 1))).sum()) == 16.0,
    "maxpool_with_argmax": lambda f: (lambda res: np.allclose(
        np.asarray(res[0]).ravel(),
        _MPX.ravel()[np.asarray(res[1]).ravel()]))(
        f(_MPX, kernel=(2, 2))),
    "batch_to_space_nd": lambda f: f(
        np.arange(16, dtype=np.float32).reshape(4, 2, 2, 1),
        block_shape=np.array([2, 2]),
        crops=np.array([[0, 0], [0, 0]])).shape == (1, 4, 4, 1),
    "space_to_batch_nd": lambda f: f(
        np.arange(16, dtype=np.float32).reshape(1, 4, 4, 1),
        block_shape=np.array([2, 2]),
        paddings=np.array([[0, 0], [0, 0]])).shape == (4, 2, 2, 1),
    "multinomial": lambda f: (lambda s: s.shape == (2, 64)
                              and int(np.asarray(s).max()) <= 2)(
        f(np.log(np.full((2, 3), 1 / 3, np.float32)), num_samples=64,
          seed=0)),
})


@pytest.mark.parametrize("name", sorted(SMOKE))
def test_smoke_invariant(name):
    fn = registry.get_op(name).fn
    assert SMOKE[name](fn), name


def _as_jax(inputs):
    return [jnp.asarray(a) for a in inputs]


@pytest.mark.parametrize("name", sorted(LEDGER))
def test_forward_matches_reference(name):
    s = LEDGER[name]
    fn = registry.get_op(name).fn
    got = fn(*_as_jax(s["inputs"]), **s["attrs"])
    want = s["ref"](*s["inputs"])
    gots = got if isinstance(got, (tuple, list)) else [got]
    wants = want if isinstance(want, (tuple, list)) else [want]
    assert len(gots) == len(wants)
    for g, w in zip(gots, wants):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=s["rtol"], atol=s["atol"],
                                   err_msg=name)


@pytest.mark.parametrize("name", sorted(
    n for n, s in LEDGER.items() if s["grad"]))
def test_gradient_matches_finite_difference(name):
    s = LEDGER[name]
    fn = registry.get_op(name).fn
    x0 = np.asarray(s["inputs"][0], np.float64)
    rest = _as_jax(s["inputs"][1:])

    def scalar(x):
        out = fn(jnp.asarray(x), *rest, **s["attrs"])
        return jnp.sum(jnp.square(out))

    ana = np.asarray(jax.grad(scalar)(jnp.asarray(x0)))
    eps = 1e-6
    idxs = [(0, 0), (1, 2), (2, 3)] if x0.ndim == 2 else [(0,), (1,)]
    for idx in idxs:
        xp = x0.copy(); xp[idx] += eps
        xm = x0.copy(); xm[idx] -= eps
        num = (float(scalar(xp)) - float(scalar(xm))) / (2 * eps)
        np.testing.assert_allclose(ana[idx], num, rtol=5e-4, atol=1e-6,
                                   err_msg=f"{name} grad at {idx}")


def test_all_ops_covered():
    """THE GATE (reference: OpValidation.java:447
    collectCoverageInformation): every registered op name must appear in
    LEDGER, SMOKE or EXERCISED."""
    covered = set(LEDGER) | set(SMOKE) | set(EXERCISED)
    missing = sorted(set(registry.op_names()) - covered)
    assert not missing, (
        f"{len(missing)} registered ops have no coverage entry — add a "
        f"LEDGER spec or an EXERCISED pointer: {missing}")


def test_exercised_pointers_are_real():
    """Each EXERCISED pointer must name a test file that actually mentions
    the op — pointers can't rot into unverifiable claims."""
    import pathlib
    here = pathlib.Path(__file__).parent
    for op_name, f in EXERCISED.items():
        path = here / f"{f}.py"
        assert path.exists(), (op_name, f)
        assert op_name in path.read_text(), (
            f"EXERCISED claims {op_name!r} is covered by {f}.py but the op "
            f"name does not appear there")


def test_first_last_index_global_scalar_form():
    """No dims: one scalar index into the flattened array (-1 when no
    element matches), matching BooleanIndexing.firstIndex."""
    import jax.numpy as jnp
    fi = registry.get_op("first_index").fn
    li = registry.get_op("last_index").fn
    x = jnp.asarray([[0.0, 2.0], [3.0, 0.0]])
    assert int(fi(x, condition="gt", value=1.0)) == 1
    assert int(li(x, condition="gt", value=1.0)) == 2
    assert int(fi(x, condition="gt", value=99.0)) == -1
