"""NDArray core tests.

Reference parity model: platform-tests org.eclipse.deeplearning4j.nd4j.linalg
basic ndarray tests (views, in-place ops, dup, reductions, gemm).
"""
import numpy as np
import pytest

from deeplearning4j_tpu import DataType, NDArray, nd


class TestCreation:
    def test_create_from_list(self):
        a = nd.create([[1.0, 2.0], [3.0, 4.0]])
        assert a.shape == (2, 2)
        assert a.dtype == DataType.FLOAT
        np.testing.assert_allclose(a.to_numpy(), [[1, 2], [3, 4]])

    def test_zeros_ones(self):
        assert nd.zeros(2, 3).to_numpy().sum() == 0
        assert nd.ones((4, 5)).to_numpy().sum() == 20

    def test_dtypes(self):
        a = nd.create([1, 2, 3], dtype="int64")
        assert a.dtype == DataType.INT64
        b = a.cast_to(DataType.BFLOAT16)
        assert b.dtype == DataType.BFLOAT16

    def test_linspace_arange_eye(self):
        np.testing.assert_allclose(nd.linspace(0, 1, 5).to_numpy(), [0, 0.25, 0.5, 0.75, 1])
        np.testing.assert_array_equal(nd.arange(5, dtype="int32").to_numpy(), np.arange(5))
        assert nd.eye(3).to_numpy().trace() == 3

    def test_rand_seeded_reproducible(self):
        a = nd.rand(3, 3, seed=42)
        b = nd.rand(3, 3, seed=42)
        assert a.equals(b)

    def test_global_rng_seed(self):
        nd.get_random().set_seed(7)
        a = nd.randn(4)
        nd.get_random().set_seed(7)
        b = nd.randn(4)
        assert a.equals(b)

    def test_value_array_scalar(self):
        v = nd.value_array_of((2, 2), 3.5)
        assert float(v.to_numpy()[0, 0]) == 3.5
        s = nd.scalar(2.0)
        assert s.item() == 2.0


class TestViews:
    def test_slice_view_writes_through(self):
        a = nd.zeros(4, 4)
        row = a[1]
        row.addi(5.0)
        assert a.to_numpy()[1].sum() == 20
        assert a.to_numpy()[0].sum() == 0

    def test_nested_view_write_through(self):
        a = nd.zeros(4, 4)
        sub = a[1:3]
        subsub = sub[0, 2:4]
        subsub.assign(9.0)
        expected = np.zeros((4, 4), np.float32)
        expected[1, 2:4] = 9
        np.testing.assert_allclose(a.to_numpy(), expected)

    def test_reshape_view_write_through(self):
        a = nd.zeros(2, 6)
        v = a.reshape(3, 4)
        v[0] = 1.0
        assert a.to_numpy().sum() == 4

    def test_transpose_view_write_through(self):
        a = nd.zeros(2, 3)
        t = a.T
        t[0] = 1.0  # first row of transpose = first column of a
        np.testing.assert_allclose(a.to_numpy()[:, 0], [1, 1])
        assert a.to_numpy().sum() == 2

    def test_dup_detaches(self):
        a = nd.ones(3)
        b = a.dup()
        b.addi(1.0)
        assert a.to_numpy().sum() == 3
        assert b.to_numpy().sum() == 6

    def test_owner_update_visible_to_view(self):
        a = nd.zeros(3, 3)
        v = a[2]
        a.addi(1.0)
        np.testing.assert_allclose(v.to_numpy(), [1, 1, 1])

    def test_put_scalar_and_get(self):
        a = nd.zeros(2, 2)
        a.put_scalar((0, 1), 7.0)
        assert a.get_double(0, 1) == 7.0

    def test_setitem_broadcast(self):
        a = nd.zeros(3, 3)
        a[1:] = 2.0
        assert a.to_numpy().sum() == 12


class TestArithmetic:
    def test_binary_ops(self):
        a = nd.create([1.0, 2.0, 3.0])
        b = nd.create([4.0, 5.0, 6.0])
        np.testing.assert_allclose((a + b).to_numpy(), [5, 7, 9])
        np.testing.assert_allclose((a - b).to_numpy(), [-3, -3, -3])
        np.testing.assert_allclose((a * b).to_numpy(), [4, 10, 18])
        np.testing.assert_allclose((b / a).to_numpy(), [4, 2.5, 2])
        np.testing.assert_allclose(a.rsub(1.0).to_numpy(), [0, -1, -2])
        np.testing.assert_allclose(a.rdiv(6.0).to_numpy(), [6, 3, 2])

    def test_inplace_ops(self):
        a = nd.create([1.0, 2.0])
        a.addi(1.0).muli(3.0)
        np.testing.assert_allclose(a.to_numpy(), [6, 9])

    def test_broadcasting(self):
        a = nd.ones(3, 4)
        col = nd.create([[1.0], [2.0], [3.0]])
        np.testing.assert_allclose((a * col).to_numpy().sum(), 24)

    def test_comparisons(self):
        a = nd.create([1.0, 5.0, 3.0])
        assert (a > 2.0).to_numpy().tolist() == [False, True, True]
        assert (a.eq(5.0)).to_numpy().tolist() == [False, True, False]


class TestMatmul:
    def test_mmul(self):
        a = nd.create([[1.0, 2.0], [3.0, 4.0]])
        b = nd.eye(2)
        assert a.mmul(b).equals(a)

    def test_gemm_transpose(self):
        a = nd.rand(3, 4, seed=1)
        b = nd.rand(3, 5, seed=2)
        r = nd.gemm(a, b, transpose_a=True)
        np.testing.assert_allclose(
            r.to_numpy(), a.to_numpy().T @ b.to_numpy(), rtol=1e-5)

    def test_mmuli_out(self):
        a = nd.rand(2, 3, seed=3)
        w = nd.rand(3, 4, seed=4)
        out = nd.zeros(2, 4)
        a.mmuli(w, out)
        np.testing.assert_allclose(out.to_numpy(), a.to_numpy() @ w.to_numpy(), rtol=1e-5)

    def test_batched_matmul(self):
        a = nd.rand(5, 2, 3, seed=5)
        b = nd.rand(5, 3, 2, seed=6)
        assert a.mmul(b).shape == (5, 2, 2)


class TestReductions:
    def test_sum_axes(self):
        a = nd.ones(2, 3, 4)
        assert a.sum().item() == 24
        assert a.sum(0).shape == (3, 4)
        assert a.sum(1, 2).shape == (2,)
        assert a.sum(0, keep_dims=True).shape == (1, 3, 4)

    def test_mean_std_var(self):
        a = nd.create([1.0, 2.0, 3.0, 4.0])
        assert a.mean().item() == 2.5
        np.testing.assert_allclose(a.var().item(), np.var([1, 2, 3, 4], ddof=1))
        np.testing.assert_allclose(a.std(bias_corrected=False).item(), np.std([1, 2, 3, 4]))

    def test_norms(self):
        a = nd.create([-3.0, 4.0])
        assert a.norm1().item() == 7
        assert a.norm2().item() == 5
        assert a.normmax().item() == 4

    def test_argmax(self):
        a = nd.create([[1.0, 9.0], [8.0, 2.0]])
        assert a.argmax(1).to_numpy().tolist() == [1, 0]

    def test_cumsum(self):
        np.testing.assert_allclose(nd.create([1.0, 2.0, 3.0]).cumsum().to_numpy(), [1, 3, 6])


class TestShapeOps:
    def test_concat_stack(self):
        a, b = nd.ones(2, 3), nd.zeros(2, 3)
        assert nd.concat(0, a, b).shape == (4, 3)
        assert nd.concat(1, a, b).shape == (2, 6)
        assert nd.stack(0, a, b).shape == (2, 2, 3)
        assert nd.vstack(a, b).shape == (4, 3)
        assert nd.hstack(a, b).shape == (2, 6)

    def test_permute_reshape(self):
        a = nd.rand(2, 3, 4, seed=9)
        assert a.permute(2, 0, 1).shape == (4, 2, 3)
        assert a.reshape(6, 4).shape == (6, 4)
        assert a.ravel().shape == (24,)

    def test_squeeze_expand(self):
        a = nd.ones(1, 3, 1)
        assert a.squeeze().shape == (3,)
        assert a.expand_dims(0).shape == (1, 1, 3, 1)

    def test_split(self):
        parts = nd.split(nd.arange(12, dtype="float32").reshape(4, 3), 2, axis=0)
        assert len(parts) == 2 and parts[0].shape == (2, 3)

    def test_where_sort(self):
        a = nd.create([3.0, 1.0, 2.0])
        np.testing.assert_allclose(nd.sort(a).to_numpy(), [1, 2, 3])
        np.testing.assert_allclose(nd.sort(a, descending=True).to_numpy(), [3, 2, 1])
        np.testing.assert_allclose(nd.where(a > 1.5, a, 0.0).to_numpy(), [3, 0, 2])

    def test_rows_columns(self):
        a = nd.arange(6, dtype="float32").reshape(2, 3).dup()
        np.testing.assert_allclose(a.get_row(1).to_numpy(), [3, 4, 5])
        np.testing.assert_allclose(a.get_column(0).to_numpy(), [0, 3])
        a.put_row(0, nd.create([9.0, 9.0, 9.0]))
        assert a.to_numpy()[0].sum() == 27


class TestInterop:
    def test_numpy_roundtrip(self):
        x = np.random.default_rng(0).normal(size=(3, 3)).astype(np.float32)
        assert np.array_equal(nd.create(x).to_numpy(), x)

    def test_iteration(self):
        rows = list(nd.eye(3))
        assert len(rows) == 3
        np.testing.assert_allclose(rows[1].to_numpy(), [0, 1, 0])

    def test_scan_all(self):
        stats = nd.create([1.0, 2.0, 3.0]).scan_all()
        assert stats["mean"] == 2.0 and stats["nan"] == 0

    def test_camelcase_aliases(self):
        a = nd.create([[1.0, 2.0]])
        assert a.getDouble(0, 1) == 2.0
        assert a.isMatrix()
