"""Model hub + pretrained-weight loading.

Reference parity: ZooModel.initPretrained() (download-cache-restore;
here the cache is seed-only — zero egress) and KerasModelImport's h5
weight restore. Hermetic fixtures: h5 files in BOTH Keras layouts
(weights-only keras-applications style and full-model model_weights
style) synthesized to the zoo architecture's exact shapes.
"""
import os

import numpy as np
import pytest

from deeplearning4j_tpu.hub import (
    KNOWN_ARTIFACTS, ModelHub, init_pretrained, load_sequential_weights,
    read_h5_layer_weights)


def _write_keras_apps_h5(path, layers, full_model=False):
    """layers: [(name, [arrays])] in model order, keras-applications
    attr layout (layer_names / weight_names)."""
    import h5py
    with h5py.File(path, "w") as f:
        root = f.create_group("model_weights") if full_model else f
        root.attrs["layer_names"] = np.array(
            [ln.encode() for ln, _ in layers])
        for ln, arrs in layers:
            g = root.create_group(ln)
            wnames = []
            for i, a in enumerate(arrs):
                wn = f"{ln}/w_{i}:0"
                g.create_dataset(wn, data=a)
                wnames.append(wn.encode())
            g.attrs["weight_names"] = np.array(wnames)


class TestModelHub:
    def test_add_and_resolve(self, tmp_path):
        hub = ModelHub(cache_dir=str(tmp_path / "hub"))
        src = tmp_path / "weights.bin"
        src.write_bytes(b"abc123")
        hub.add("my_weights.h5", str(src))
        assert hub.contains("my_weights.h5")
        assert "my_weights.h5" in hub.list()
        assert open(hub.path("my_weights.h5"), "rb").read() == b"abc123"

    def test_known_artifact_missing_is_actionable(self, tmp_path):
        hub = ModelHub(cache_dir=str(tmp_path / "hub"))
        with pytest.raises(FileNotFoundError) as ei:
            hub.path("vgg16_keras")
        msg = str(ei.value)
        assert "vgg16_weights_tf_dim_ordering_tf_kernels.h5" in msg
        assert str(tmp_path / "hub") in msg

    def test_unknown_name_lists_known(self, tmp_path):
        hub = ModelHub(cache_dir=str(tmp_path / "hub"))
        with pytest.raises(FileNotFoundError, match="vgg16_keras"):
            hub.path("nope")

    def test_sha256(self, tmp_path):
        hub = ModelHub(cache_dir=str(tmp_path / "hub"))
        (tmp_path / "hub" / "a.bin").write_bytes(b"x")
        assert hub.sha256("a.bin") == (
            "2d711642b726b04401627ca9fbac32f5c8530fb1903cc4db02258717921a4881")


def _vgg_fixture_layers(net, rng, head_classes=None):
    """Synthesize h5 layer entries shaped exactly like the net's params
    (optionally with a different head width, keras-apps 1000-way)."""
    sd = net.samediff
    params = {n: np.asarray(a) for n, a in
              {**sd.trainable_params(), **sd.state_vars_map()}.items()}
    stems, by_stem = [], {}
    for n, a in params.items():
        stem = n.rsplit("_", 1)[0]
        if stem not in by_stem:
            by_stem[stem] = []
            stems.append(stem)
        by_stem[stem].append(a)
    layers = []
    for i, stem in enumerate(stems):
        arrs = [rng.standard_normal(a.shape).astype(np.float32) * 0.05
                for a in by_stem[stem]]
        if head_classes is not None and i == len(stems) - 1:
            w = by_stem[stem][0]
            arrs = [rng.standard_normal((w.shape[0], head_classes))
                    .astype(np.float32) * 0.05,
                    np.zeros(head_classes, np.float32)]
        layers.append((f"keras_layer_{i}", arrs))
    return layers


class TestSequentialLoad:
    @pytest.mark.parametrize("full_model", [False, True])
    def test_vgg16_weights_land_exactly(self, tmp_path, full_model):
        from deeplearning4j_tpu.zoo import VGG16
        net = VGG16(height=32, width=32, num_classes=10).build()
        rng = np.random.default_rng(0)
        layers = _vgg_fixture_layers(net, rng)
        p = str(tmp_path / "w.h5")
        _write_keras_apps_h5(p, layers, full_model=full_model)
        n = load_sequential_weights(net, p)
        assert n == sum(len(a) for _, a in layers)
        # every param now equals its h5 source array
        sd = net.samediff
        flat = [a for _, arrs in layers for a in arrs]
        got = list({**sd.trainable_params(),
                    **sd.state_vars_map()}.values())
        stems_sorted = []    # rebuild pairing as the loader does
        params = {k: np.asarray(v) for k, v in
                  {**sd.trainable_params(), **sd.state_vars_map()}.items()}
        by_stem = {}
        for k, v in params.items():
            by_stem.setdefault(k.rsplit("_", 1)[0], []).append(v)
        pos = 0
        for stem in by_stem:
            for v in by_stem[stem]:
                np.testing.assert_allclose(v, flat[pos], atol=0,
                                           err_msg=stem)
                pos += 1

    def test_forward_uses_loaded_weights(self, tmp_path):
        """End-to-end: load handcrafted weights, check the network's
        prediction against a numpy forward computation."""
        from deeplearning4j_tpu.nn import (
            DenseLayer, InputType, NeuralNetConfiguration, OutputLayer)
        from deeplearning4j_tpu.learning.updaters import Sgd
        conf = (NeuralNetConfiguration.builder().seed(0)
                .updater(Sgd(0.1)).list()
                .layer(DenseLayer(n_out=5, activation="relu"))
                .layer(OutputLayer(n_out=3, loss_function="MCXENT"))
                .set_input_type(InputType.feed_forward(4)).build())
        from deeplearning4j_tpu.nn import MultiLayerNetwork
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(1)
        w0 = rng.standard_normal((4, 5)).astype(np.float32)
        b0 = rng.standard_normal(5).astype(np.float32)
        w1 = rng.standard_normal((5, 3)).astype(np.float32)
        b1 = np.zeros(3, np.float32)
        p = str(tmp_path / "w.h5")
        _write_keras_apps_h5(p, [("dense", [w0, b0]), ("out", [w1, b1])])
        load_sequential_weights(net, p)
        x = rng.standard_normal((2, 4)).astype(np.float32)
        got = net.output(x)
        h = np.maximum(x @ w0 + b0, 0)
        logits = h @ w1 + b1
        want = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4,
                                   atol=1e-5)

    def test_skip_mismatched_head(self, tmp_path):
        """1000-class keras-apps weights into a 10-class net: body
        loads, head stays at its fresh init (ZooModel.initPretrained
        with custom num_classes)."""
        from deeplearning4j_tpu.zoo import VGG16
        net = VGG16(height=32, width=32, num_classes=10).build()
        rng = np.random.default_rng(0)
        layers = _vgg_fixture_layers(net, rng, head_classes=1000)
        p = str(tmp_path / "w.h5")
        _write_keras_apps_h5(p, layers)
        head_before = np.asarray(net.samediff.trainable_params()
                                 ["layer20_out_W"])
        net2 = init_pretrained(
            VGG16(height=32, width=32, num_classes=10), p)
        sd = net2.samediff
        # first conv loaded from h5
        np.testing.assert_allclose(
            np.asarray(sd.trainable_params()["layer0_conv_W"]),
            layers[0][1][0])
        # head kept its own (seeded) init, not the 1000-way h5 head
        assert np.asarray(sd.trainable_params()["layer20_out_W"]
                          ).shape == (4096, 10)

    def test_shape_mismatch_is_actionable(self, tmp_path):
        from deeplearning4j_tpu.zoo import VGG16
        net = VGG16(height=32, width=32, num_classes=10).build()
        rng = np.random.default_rng(0)
        layers = _vgg_fixture_layers(net, rng, head_classes=1000)
        p = str(tmp_path / "w.h5")
        _write_keras_apps_h5(p, layers)
        with pytest.raises(ValueError, match="skip_mismatched_head"):
            load_sequential_weights(net, p)

    def test_read_both_layouts_agree(self, tmp_path):
        rng = np.random.default_rng(2)
        layers = [("a", [rng.standard_normal((3, 3)).astype(np.float32)]),
                  ("b", [rng.standard_normal(4).astype(np.float32),
                         rng.standard_normal((4, 2)).astype(np.float32)])]
        p1, p2 = str(tmp_path / "w1.h5"), str(tmp_path / "w2.h5")
        _write_keras_apps_h5(p1, layers, full_model=False)
        _write_keras_apps_h5(p2, layers, full_model=True)
        r1 = read_h5_layer_weights(p1)
        r2 = read_h5_layer_weights(p2)
        assert [ln for ln, _ in r1] == [ln for ln, _ in r2] == ["a", "b"]
        for (_, a1), (_, a2) in zip(r1, r2):
            for x, y in zip(a1, a2):
                np.testing.assert_array_equal(x, y)
