"""Layer-based API tests (reference test model: deeplearning4j platform-tests
dl4jcore/nn — config serde round-trips, forward shapes, fit convergence,
ModelSerializer round-trip)."""
import numpy as np
import pytest

from deeplearning4j_tpu.learning.updaters import Adam, Sgd
from deeplearning4j_tpu.nn import (
    ActivationLayer, BatchNormalization, ConvolutionLayer, DenseLayer,
    DropoutLayer, EmbeddingLayer, GlobalPoolingLayer, InputType, LSTMLayer,
    MultiLayerConfiguration, MultiLayerNetwork, NeuralNetConfiguration,
    OutputLayer, SubsamplingLayer)


def _mlp_conf(updater=None, l2=0.0):
    b = (NeuralNetConfiguration.builder()
         .seed(7)
         .updater(updater or Adam(learning_rate=0.05)))
    if l2:
        b = b.l2(l2)
    return (b.list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=2, loss_function="MCXENT"))
            .set_input_type(InputType.feed_forward(2))
            .build())


def _xor():
    X = np.tile(np.array([[0, 0], [0, 1], [1, 0], [1, 1]], dtype=np.float32),
                (16, 1))
    Y = np.eye(2, dtype=np.float32)[
        (X[:, 0].astype(int) ^ X[:, 1].astype(int))]
    return X, Y


def test_builder_produces_config():
    conf = _mlp_conf(l2=1e-4)
    assert len(conf.layers) == 2
    assert conf.seed == 7
    assert conf.regularization[0].l2 == 1e-4


def test_config_json_round_trip():
    conf = (NeuralNetConfiguration.builder()
            .seed(99)
            .updater(Adam(learning_rate=0.001))
            .l2(5e-4)
            .list()
            .layer(ConvolutionLayer(n_out=8, kernel_size=(3, 3),
                                    activation="relu"))
            .layer(SubsamplingLayer(pooling_type="MAX", kernel_size=(2, 2)))
            .layer(BatchNormalization())
            .layer(DenseLayer(n_out=32, activation="relu", dropout=0.8))
            .layer(OutputLayer(n_out=10))
            .set_input_type(InputType.convolutional(28, 28, 1))
            .build())
    s = conf.to_json()
    conf2 = MultiLayerConfiguration.from_json(s)
    assert conf2.to_json() == s
    assert conf2.layers[0].kernel_size == (3, 3)
    assert conf2.updater == conf.updater
    assert conf2.input_type == conf.input_type


def test_mlp_fit_and_predict_xor():
    net = MultiLayerNetwork(_mlp_conf()).init()
    X, Y = _xor()
    hist = net.fit(X, Y, epochs=60, batch_size=16)
    assert hist.final_loss() < 0.05
    assert net.score() < 0.05
    preds = net.predict(X[:4])
    np.testing.assert_array_equal(preds, [0, 1, 1, 0])


def test_output_shape_and_probabilities():
    net = MultiLayerNetwork(_mlp_conf()).init()
    X, _ = _xor()
    out = net.output(X[:8]).to_numpy()
    assert out.shape == (8, 2)
    np.testing.assert_allclose(out.sum(-1), np.ones(8), rtol=1e-5)


def test_num_params():
    net = MultiLayerNetwork(_mlp_conf()).init()
    # dense 2*16+16, out 16*2+2
    assert net.num_params() == (2 * 16 + 16) + (16 * 2 + 2)


def test_cnn_shapes_lenet_style():
    conf = (NeuralNetConfiguration.builder()
            .seed(3)
            .updater(Adam(learning_rate=0.01))
            .list()
            .layer(ConvolutionLayer(n_out=6, kernel_size=(5, 5),
                                    convolution_mode="SAME",
                                    activation="relu"))
            .layer(SubsamplingLayer(kernel_size=(2, 2)))
            .layer(ConvolutionLayer(n_out=16, kernel_size=(5, 5),
                                    convolution_mode="VALID",
                                    activation="relu"))
            .layer(SubsamplingLayer(kernel_size=(2, 2)))
            .layer(DenseLayer(n_out=32, activation="relu"))
            .layer(OutputLayer(n_out=10))
            .set_input_type(InputType.convolutional(28, 28, 1))
            .build())
    net = MultiLayerNetwork(conf).init()
    x = np.random.default_rng(0).normal(size=(4, 1, 28, 28)).astype(np.float32)
    out = net.output(x).to_numpy()
    assert out.shape == (4, 10)
    # conv SAME 28->28, pool 14, conv VALID 10, pool 5 → flat 16*5*5=400
    assert "400" in net.summary() or net.num_params() > 0


def test_cnn_learns_synthetic():
    rng = np.random.default_rng(5)
    # class 0: bright top-left quadrant; class 1: bright bottom-right
    n = 64
    X = rng.normal(0, 0.1, size=(n, 1, 8, 8)).astype(np.float32)
    y = rng.integers(0, 2, n)
    X[y == 0, :, :4, :4] += 1.0
    X[y == 1, :, 4:, 4:] += 1.0
    Y = np.eye(2, dtype=np.float32)[y]
    conf = (NeuralNetConfiguration.builder()
            .seed(1)
            .updater(Adam(learning_rate=0.02))
            .list()
            .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                    activation="relu"))
            .layer(GlobalPoolingLayer(pooling_type="AVG"))
            .layer(OutputLayer(n_out=2))
            .set_input_type(InputType.convolutional(8, 8, 1))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.fit(X, Y, epochs=60, batch_size=32)
    acc = (net.predict(X) == y).mean()
    assert acc > 0.9


def test_batchnorm_trains_and_infers():
    X, Y = _xor()
    conf = (NeuralNetConfiguration.builder()
            .seed(11)
            .updater(Adam(learning_rate=0.05))
            .list()
            .layer(DenseLayer(n_out=16, activation="identity"))
            .layer(BatchNormalization())
            .layer(ActivationLayer(activation="tanh"))
            .layer(OutputLayer(n_out=2))
            .set_input_type(InputType.feed_forward(2))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.fit(X, Y, epochs=40, batch_size=16)
    # running stats were updated away from init
    p = net.params()
    mean_key = [k for k in p if k.endswith("_mean")][0]
    assert np.abs(p[mean_key]).sum() > 0
    # inference uses running stats and still classifies
    preds = net.predict(X[:4])
    np.testing.assert_array_equal(preds, [0, 1, 1, 0])


def test_dropout_only_in_training_graph():
    conf = (NeuralNetConfiguration.builder()
            .seed(2)
            .updater(Sgd(learning_rate=0.1))
            .list()
            .layer(DenseLayer(n_out=64, activation="relu", dropout=0.5))
            .layer(OutputLayer(n_out=2))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    x = np.random.default_rng(0).normal(size=(8, 4)).astype(np.float32)
    a = net.output(x).to_numpy()
    b = net.output(x).to_numpy()
    np.testing.assert_array_equal(a, b)  # inference deterministic
    t1 = net.output(x, training=True).to_numpy()
    t2 = net.output(x, training=True).to_numpy()
    assert not np.array_equal(t1, t2)    # dropout active in train graph


def test_lstm_classifier():
    rng = np.random.default_rng(8)
    # class = whether the sequence mean of feature 0 is positive
    X = rng.normal(size=(64, 10, 3)).astype(np.float32)
    y = (X[:, :, 0].mean(1) > 0).astype(int)
    Y = np.eye(2, dtype=np.float32)[y]
    conf = (NeuralNetConfiguration.builder()
            .seed(4)
            .updater(Adam(learning_rate=0.02))
            .list()
            .layer(LSTMLayer(n_out=16, return_sequences=False))
            .layer(OutputLayer(n_out=2))
            .set_input_type(InputType.recurrent(3, 10))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.fit(X, Y, epochs=40, batch_size=32)
    acc = (net.predict(X) == y).mean()
    assert acc > 0.85


def test_embedding_layer():
    rng = np.random.default_rng(9)
    ids = rng.integers(0, 10, size=(64, 1)).astype(np.float32)
    Y = np.eye(2, dtype=np.float32)[(ids[:, 0] % 2).astype(int)]
    conf = (NeuralNetConfiguration.builder()
            .seed(6)
            .updater(Adam(learning_rate=0.05))
            .list()
            .layer(EmbeddingLayer(n_in=10, n_out=8))
            .layer(OutputLayer(n_out=2))
            .set_input_type(InputType.feed_forward(1))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.fit(ids, Y, epochs=40, batch_size=32)
    acc = (net.predict(ids) == (ids[:, 0] % 2)).mean()
    assert acc > 0.95


def test_model_serializer_round_trip(tmp_path):
    net = MultiLayerNetwork(_mlp_conf()).init()
    X, Y = _xor()
    net.fit(X, Y, epochs=10, batch_size=16)
    before = net.output(X[:8]).to_numpy()
    path = tmp_path / "net.zip"
    net.save(path)
    net2 = MultiLayerNetwork.load(path)
    after = net2.output(X[:8]).to_numpy()
    np.testing.assert_allclose(before, after, rtol=1e-6)
    # training resumes (updater state restored)
    h = net2.fit(X, Y, epochs=2, batch_size=16)
    assert np.isfinite(h.final_loss())
    assert net2._sd_train.training_config.iteration_count > 0


def test_regularization_shrinks_weights():
    X, Y = _xor()
    net_plain = MultiLayerNetwork(_mlp_conf(Sgd(learning_rate=0.1))).init()
    net_l2 = MultiLayerNetwork(
        _mlp_conf(Sgd(learning_rate=0.1), l2=0.3)).init()
    net_plain.fit(X, Y, epochs=20, batch_size=16)
    net_l2.fit(X, Y, epochs=20, batch_size=16)
    w_plain = np.abs(net_plain.params()["layer0_dense_W"]).mean()
    w_l2 = np.abs(net_l2.params()["layer0_dense_W"]).mean()
    assert w_l2 < w_plain


def test_summary_lists_layers():
    net = MultiLayerNetwork(_mlp_conf()).init()
    s = net.summary()
    assert "DenseLayer" in s and "OutputLayer" in s


def test_uninitialized_raises():
    net = MultiLayerNetwork(_mlp_conf())
    with pytest.raises(RuntimeError, match="init"):
        net.output(np.zeros((1, 2), dtype=np.float32))


# ---- regression tests for review findings ----

def test_dilated_valid_conv_shape():
    conf = (NeuralNetConfiguration.builder()
            .seed(1).updater(Sgd(learning_rate=0.1))
            .list()
            .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                    dilation=(2, 2),
                                    convolution_mode="VALID"))
            .layer(DenseLayer(n_out=8))
            .layer(OutputLayer(n_out=2))
            .set_input_type(InputType.convolutional(8, 8, 1))
            .build())
    net = MultiLayerNetwork(conf).init()
    out = net.output(np.zeros((2, 1, 8, 8), dtype=np.float32)).to_numpy()
    assert out.shape == (2, 2)
    # effective kernel 5 → 8-5+1 = 4
    assert conf.layers[0].output_type(conf.input_type).dims == (4, 4, 4)


def test_batchnorm_on_rnn_sequences():
    conf = (NeuralNetConfiguration.builder()
            .seed(1).updater(Sgd(learning_rate=0.1))
            .list()
            .layer(LSTMLayer(n_out=6))
            .layer(BatchNormalization())
            .layer(GlobalPoolingLayer(pooling_type="AVG"))
            .layer(OutputLayer(n_out=2))
            .set_input_type(InputType.recurrent(3, 10))
            .build())
    net = MultiLayerNetwork(conf).init()
    X = np.random.default_rng(0).normal(size=(4, 10, 3)).astype(np.float32)
    Y = np.eye(2, dtype=np.float32)[[0, 1, 0, 1]]
    net.fit(X, Y, epochs=2, batch_size=4)
    assert net.output(X).to_numpy().shape == (4, 2)


def test_embedding_rejects_multicolumn_input():
    conf = (NeuralNetConfiguration.builder()
            .seed(1).updater(Sgd(learning_rate=0.1))
            .list()
            .layer(EmbeddingLayer(n_in=10, n_out=4))
            .layer(OutputLayer(n_out=2))
            .set_input_type(InputType.feed_forward(3))
            .build())
    with pytest.raises(ValueError, match="single index column"):
        MultiLayerNetwork(conf).init()


def test_infer_shape_through_state_vars():
    conf = (NeuralNetConfiguration.builder()
            .seed(1).updater(Sgd(learning_rate=0.1))
            .list()
            .layer(DenseLayer(n_out=8))
            .layer(BatchNormalization())
            .layer(OutputLayer(n_out=2))
            .set_input_type(InputType.feed_forward(4))
            .build())
    net = MultiLayerNetwork(conf).init()
    assert net._sd_train.get_variable("output").shape is not None


def test_collapsed_spatial_dim_raises_at_config_time():
    """Regression: a net whose pools collapse the input below 1 pixel
    must fail with layer math at build time (reference:
    DL4JInvalidConfigException from InputTypeUtil), not a zero-dim
    reshape error inside the compiled step."""
    from deeplearning4j_tpu.zoo import SimpleCNN
    with pytest.raises(ValueError, match="spatial size"):
        SimpleCNN(height=8, width=8, channels=1, num_classes=2).build()
