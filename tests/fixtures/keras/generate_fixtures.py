"""Regenerates the Keras .h5 import fixtures + expected outputs.

Run with tf.keras available:  python generate_fixtures.py
Each fixture saves the legacy-H5 model and an npz with a test input and
the Keras prediction on it; tests compare the imported model to 1e-5.
"""
import os

import numpy as np


def main():
    os.environ.setdefault("CUDA_VISIBLE_DEVICES", "-1")
    import tensorflow as tf
    tf.keras.utils.set_random_seed(7)
    out = os.path.dirname(os.path.abspath(__file__))
    L = tf.keras.layers

    m = tf.keras.Sequential([
        L.Input((20,)), L.Dense(32, activation="relu"),
        L.Dense(16, activation="tanh"), L.Dense(5, activation="softmax")])
    x = np.random.default_rng(0).normal(size=(6, 20)).astype(np.float32)
    np.savez(f"{out}/mlp_expected.npz", x=x, y=m.predict(x, verbose=0))
    m.save(f"{out}/mlp.h5")

    m = tf.keras.Sequential([
        L.Input((12, 12, 2)),
        L.Conv2D(8, 3, activation="relu", padding="same"),
        L.MaxPooling2D(2), L.BatchNormalization(),
        L.Conv2D(12, 3, activation="relu", padding="valid"),
        L.AveragePooling2D(2), L.Flatten(), L.Dropout(0.4),
        L.Dense(20, activation="relu"), L.Dense(4, activation="softmax")])
    xt = np.random.default_rng(1).normal(size=(64, 12, 12, 2)).astype(np.float32)
    yt = np.eye(4)[np.random.default_rng(2).integers(0, 4, 64)]
    m.compile(optimizer="adam", loss="categorical_crossentropy")
    m.fit(xt, yt, epochs=2, verbose=0)      # fold nontrivial BN stats
    x = np.random.default_rng(3).normal(size=(5, 12, 12, 2)).astype(np.float32)
    np.savez(f"{out}/cnn_expected.npz", x=x, y=m.predict(x, verbose=0))
    m.save(f"{out}/cnn.h5")

    m = tf.keras.Sequential([
        L.Input((9, 6)), L.LSTM(11, return_sequences=True), L.LSTM(7),
        L.Dense(3, activation="softmax")])
    x = np.random.default_rng(4).normal(size=(4, 9, 6)).astype(np.float32)
    np.savez(f"{out}/lstm_expected.npz", x=x, y=m.predict(x, verbose=0))
    m.save(f"{out}/lstm.h5")

    m = tf.keras.Sequential([
        L.Input((7,), dtype="int32"), L.Embedding(30, 8),
        L.Bidirectional(L.LSTM(5, return_sequences=True)),
        L.GlobalAveragePooling1D(), L.Dense(2, activation="softmax")])
    x = np.random.default_rng(5).integers(0, 30, size=(4, 7)).astype(np.int32)
    np.savez(f"{out}/embed_bilstm_expected.npz", x=x,
             y=m.predict(x, verbose=0))
    m.save(f"{out}/embed_bilstm.h5")

    inp = L.Input((10,))
    h = L.Dense(10, activation="relu")(inp)
    h2 = L.Dense(10, activation="relu")(h)
    s = L.Add()([h, h2])
    o = L.Dense(3, activation="softmax")(s)
    m = tf.keras.Model(inp, o)
    x = np.random.default_rng(6).normal(size=(5, 10)).astype(np.float32)
    np.savez(f"{out}/functional_expected.npz", x=x, y=m.predict(x, verbose=0))
    m.save(f"{out}/functional.h5")

    # two conv branches → Concatenate → Flatten → Dense: exercises the
    # merge-vertex wiring and the concat-then-flatten HWC→CHW permutation
    inp = L.Input((8, 8, 2))
    a = L.Conv2D(4, 3, padding="same", activation="relu")(inp)
    b = L.Conv2D(6, 3, padding="same", activation="tanh")(inp)
    cat = L.Concatenate()([a, b])
    o = L.Dense(3, activation="softmax")(L.Flatten()(cat))
    m = tf.keras.Model(inp, o)
    x = np.random.default_rng(7).normal(size=(3, 8, 8, 2)).astype(np.float32)
    np.savez(f"{out}/functional_concat_expected.npz", x=x,
             y=m.predict(x, verbose=0))
    m.save(f"{out}/functional_concat.h5")
    print("fixtures regenerated")


if __name__ == "__main__":
    main()
