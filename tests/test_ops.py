"""Op registry tests.

Reference parity model: OpValidation (nd4j autodiff/validation/OpValidation.java)
— forward-value checks against numpy golden values, plus coverage accounting
(test_registry_coverage is the coverage ledger gate).
"""
import numpy as np
import pytest
import jax.numpy as jnp

from deeplearning4j_tpu.ops import exec_op, get_op, has_op, op_names, ops_by_category
from deeplearning4j_tpu import nd


def a(*s, seed=0):
    return np.random.default_rng(seed).normal(size=s).astype(np.float32)


class TestRegistry:
    def test_coverage_floor(self):
        # coverage ledger: grows monotonically round over round
        names = op_names()
        assert len(names) >= 200, f"only {len(names)} ops registered"

    def test_categories(self):
        cats = ops_by_category()
        for expected in ["elementwise", "pairwise", "reduce", "shape", "random",
                         "linalg", "nn", "loss", "bitwise", "image"]:
            assert expected in cats, f"missing category {expected}"

    def test_unknown_op(self):
        with pytest.raises(KeyError):
            get_op("no_such_op_xyz")

    def test_aliases(self):
        assert get_op("mul") is get_op("multiply")
        assert has_op("sigmoid")


class TestElementwise:
    def test_transforms_golden(self):
        x = a(4, 5, seed=1)
        for name, ref in [
            ("exp", np.exp), ("log", lambda v: np.log(np.abs(v) + 1.0)),
            ("tanh", np.tanh), ("sqrt", lambda v: np.sqrt(np.abs(v))),
            ("abs", np.abs), ("floor", np.floor), ("ceil", np.ceil),
            ("sign", np.sign), ("erf", None),
        ]:
            inp = np.abs(x) + 1.0 if name in ("log", "sqrt") else x
            got = exec_op(name, inp).to_numpy()
            if ref is not None:
                expect = ref(x) if name not in ("log", "sqrt") else \
                    (np.log(inp) if name == "log" else np.sqrt(inp))
                np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)

    def test_activations(self):
        x = a(3, 4, seed=2)
        sig = exec_op("sigmoid", x).to_numpy()
        np.testing.assert_allclose(sig, 1 / (1 + np.exp(-x)), rtol=1e-5)
        r = exec_op("relu", x).to_numpy()
        np.testing.assert_allclose(r, np.maximum(x, 0), rtol=1e-6)
        lr = exec_op("leaky_relu", x, alpha=0.1).to_numpy()
        np.testing.assert_allclose(lr, np.where(x >= 0, x, 0.1 * x), rtol=1e-5)
        r6 = exec_op("relu6", x * 10).to_numpy()
        assert r6.max() <= 6.0 and r6.min() >= 0.0

    def test_softmax(self):
        x = a(2, 5, seed=3)
        s = exec_op("softmax", x).to_numpy()
        np.testing.assert_allclose(s.sum(-1), np.ones(2), rtol=1e-5)
        ls = exec_op("log_softmax", x).to_numpy()
        np.testing.assert_allclose(np.exp(ls), s, rtol=1e-5)

    def test_clip(self):
        x = a(10, seed=4) * 5
        c = exec_op("clip_by_value", x, clip_min=-1.0, clip_max=1.0).to_numpy()
        assert c.min() >= -1.0 and c.max() <= 1.0
        n = exec_op("clip_by_norm", x, clip_norm=1.0).to_numpy()
        assert np.linalg.norm(n) <= 1.0 + 1e-5

    def test_cumsum_modes(self):
        x = np.array([1.0, 2.0, 3.0], np.float32)
        np.testing.assert_allclose(exec_op("cumsum", x, axis=0).to_numpy(), [1, 3, 6])
        np.testing.assert_allclose(
            exec_op("cumsum", x, axis=0, exclusive=True).to_numpy(), [0, 1, 3])
        np.testing.assert_allclose(
            exec_op("cumsum", x, axis=0, reverse=True).to_numpy(), [6, 5, 3])


class TestPairwiseReduce:
    def test_pairwise(self):
        x, y = a(3, 3, seed=5), a(3, 3, seed=6)
        np.testing.assert_allclose(exec_op("add", x, y).to_numpy(), x + y, rtol=1e-6)
        np.testing.assert_allclose(exec_op("squaredsubtract", x, y).to_numpy(),
                                   (x - y) ** 2, rtol=1e-5)
        np.testing.assert_allclose(exec_op("maximum", x, y).to_numpy(),
                                   np.maximum(x, y))

    def test_reductions(self):
        x = a(4, 6, seed=7)
        np.testing.assert_allclose(exec_op("reduce_mean", x, axis=1).to_numpy(),
                                   x.mean(1), rtol=1e-5)
        np.testing.assert_allclose(exec_op("norm2", x).to_numpy(),
                                   np.linalg.norm(x), rtol=1e-5)
        np.testing.assert_allclose(
            exec_op("reduce_stdev", x, axis=0, bias_corrected=True).to_numpy(),
            x.std(0, ddof=1), rtol=1e-4)

    def test_reduce3(self):
        x, y = a(8, seed=8), a(8, seed=9)
        cos = exec_op("cosine_similarity", x, y).to_numpy()
        expect = (x * y).sum() / (np.linalg.norm(x) * np.linalg.norm(y))
        np.testing.assert_allclose(cos, expect, rtol=1e-5)
        np.testing.assert_allclose(exec_op("euclidean_distance", x, y).to_numpy(),
                                   np.linalg.norm(x - y), rtol=1e-5)

    def test_argmax_moments(self):
        x = a(3, 7, seed=10)
        np.testing.assert_array_equal(exec_op("argmax", x, axis=1).to_numpy(), x.argmax(1))
        m, v = exec_op("moments", x)
        np.testing.assert_allclose(m.to_numpy(), x.mean(), rtol=1e-5)
        np.testing.assert_allclose(v.to_numpy(), x.var(), rtol=1e-5)

    def test_segment_sum(self):
        data = np.array([1.0, 2.0, 3.0, 4.0], np.float32)
        ids = np.array([0, 0, 1, 1])
        out = exec_op("segment_sum", data, ids, num_segments=2).to_numpy()
        np.testing.assert_allclose(out, [3, 7])


class TestShapeOps:
    def test_gather_scatter(self):
        x = a(5, 3, seed=11)
        idx = np.array([0, 2, 4])
        np.testing.assert_allclose(exec_op("gather", x, idx, axis=0).to_numpy(), x[idx])
        z = np.zeros((5, 3), np.float32)
        s = exec_op("scatter_add", z, idx, x[idx]).to_numpy()
        np.testing.assert_allclose(s[idx], x[idx])
        np.testing.assert_allclose(s[[1, 3]], 0)

    def test_gather_nd(self):
        x = a(4, 5, seed=12)
        idx = np.array([[0, 1], [3, 4]])
        np.testing.assert_allclose(exec_op("gather_nd", x, idx).to_numpy(),
                                   x[[0, 3], [1, 4]])

    def test_one_hot(self):
        oh = exec_op("one_hot", np.array([0, 2]), depth=3).to_numpy()
        np.testing.assert_allclose(oh, [[1, 0, 0], [0, 0, 1]])

    def test_pad_reverse(self):
        x = a(2, 3, seed=13)
        p = exec_op("pad", x, paddings=[[1, 1], [0, 0]]).to_numpy()
        assert p.shape == (4, 3) and p[0].sum() == 0
        np.testing.assert_allclose(exec_op("reverse", x, axis=1).to_numpy(), x[:, ::-1])

    def test_space_depth_roundtrip(self):
        x = a(2, 4, 4, 8, seed=14)  # NHWC
        y = exec_op("space_to_depth", x, block_size=2, data_format="NHWC").to_numpy()
        assert y.shape == (2, 2, 2, 32)
        z = exec_op("depth_to_space", y, block_size=2, data_format="NHWC").to_numpy()
        np.testing.assert_allclose(z, x, rtol=1e-6)

    def test_strided_slice_split(self):
        x = a(6, 4, seed=15)
        np.testing.assert_allclose(
            exec_op("strided_slice", x, begin=[0, 1], end=[6, 4], strides=[2, 1]).to_numpy(),
            x[::2, 1:4])
        parts = exec_op("split", x, num_split=3, axis=0)
        assert len(parts) == 3 and parts[0].shape == (2, 4)

    def test_top_k(self):
        x = np.array([[1.0, 5.0, 3.0, 2.0]], np.float32)
        v, i = exec_op("top_k", x, k=2)
        np.testing.assert_allclose(v.to_numpy(), [[5, 3]])
        np.testing.assert_array_equal(i.to_numpy(), [[1, 2]])

    def test_matrix_diag(self):
        d = np.array([1.0, 2.0], np.float32)
        np.testing.assert_allclose(exec_op("matrix_diag", d).to_numpy(),
                                   [[1, 0], [0, 2]])
        x = a(3, 3, seed=16)
        np.testing.assert_allclose(exec_op("diag_part", x).to_numpy(), np.diagonal(x))

    def test_confusion_matrix(self):
        cm = exec_op("confusion_matrix", np.array([0, 1, 1]), np.array([0, 1, 0]),
                     num_classes=2).to_numpy()
        np.testing.assert_array_equal(cm, [[1, 0], [1, 1]])


class TestLinalg:
    def test_matmul_flags(self):
        x, y = a(3, 4, seed=17), a(3, 5, seed=18)
        np.testing.assert_allclose(
            exec_op("matmul", x, y, transpose_a=True).to_numpy(), x.T @ y, rtol=1e-5)

    def test_solve_cholesky_det(self):
        m = a(4, 4, seed=19)
        spd = m @ m.T + 4 * np.eye(4, dtype=np.float32)
        b = a(4, 2, seed=20)
        sol = exec_op("solve", spd, b).to_numpy()
        np.testing.assert_allclose(spd @ sol, b, atol=1e-4)
        L = exec_op("cholesky", spd).to_numpy()
        np.testing.assert_allclose(L @ L.T, spd, rtol=1e-4, atol=1e-4)
        det = exec_op("matrix_determinant", spd).to_numpy()
        np.testing.assert_allclose(det, np.linalg.det(spd), rtol=1e-3)

    def test_svd_reconstruct(self):
        m = a(5, 3, seed=21)
        s, u, v = exec_op("svd", m)
        recon = u.to_numpy() @ np.diag(s.to_numpy()) @ v.to_numpy().T
        np.testing.assert_allclose(recon, m, atol=1e-4)

    def test_inverse_band(self):
        m = a(3, 3, seed=22) + 3 * np.eye(3, dtype=np.float32)
        inv = exec_op("matrix_inverse", m).to_numpy()
        np.testing.assert_allclose(m @ inv, np.eye(3), atol=1e-4)
        x = np.ones((4, 4), np.float32)
        band = exec_op("matrix_band_part", x, num_lower=1, num_upper=0).to_numpy()
        assert band.sum() == 7  # diagonal 4 + subdiagonal 3


class TestNN:
    def test_conv2d_identity(self):
        # 1x1 identity-matrix kernel: output equals input
        x = a(1, 3, 5, 5, seed=23)  # NCHW
        w = np.zeros((1, 1, 3, 3), np.float32)  # HWIO
        w[0, 0, :, :] = np.eye(3)
        out = exec_op("conv2d", x, w, strides=(1, 1), padding="VALID").to_numpy()
        np.testing.assert_allclose(out, x, rtol=1e-5)

    def test_conv2d_box_filter(self):
        # 3x3 all-ones kernel on single channel = local 3x3 sums
        x = a(1, 1, 5, 5, seed=230)
        w = np.ones((3, 3, 1, 1), np.float32)
        out = exec_op("conv2d", x, w, padding="VALID").to_numpy()
        expect = np.array([[x[0, 0, i:i+3, j:j+3].sum() for j in range(3)]
                           for i in range(3)])
        np.testing.assert_allclose(out[0, 0], expect, rtol=1e-4)

    def test_conv2d_shapes(self):
        x = a(2, 3, 8, 8, seed=24)
        w = a(3, 3, 3, 16, seed=25) * 0.1
        assert exec_op("conv2d", x, w, padding="SAME").shape == (2, 16, 8, 8)
        assert exec_op("conv2d", x, w, padding="VALID").shape == (2, 16, 6, 6)
        assert exec_op("conv2d", x, w, strides=(2, 2), padding="SAME").shape == (2, 16, 4, 4)

    def test_depthwise_shapes(self):
        x = a(2, 4, 8, 8, seed=26)
        w = a(3, 3, 4, 2, seed=27) * 0.1
        assert exec_op("depthwise_conv2d", x, w, padding="SAME").shape == (2, 8, 8, 8)

    def test_pooling(self):
        x = a(1, 1, 4, 4, seed=28)
        mp = exec_op("max_pool2d", x, kernel=(2, 2)).to_numpy()
        expect = x[0, 0].reshape(2, 2, 2, 2).transpose(0, 2, 1, 3).reshape(2, 2, 4).max(-1)
        np.testing.assert_allclose(mp[0, 0], expect, rtol=1e-6)
        ap = exec_op("avg_pool2d", x, kernel=(2, 2)).to_numpy()
        expect_a = x[0, 0].reshape(2, 2, 2, 2).transpose(0, 2, 1, 3).reshape(2, 2, 4).mean(-1)
        np.testing.assert_allclose(ap[0, 0], expect_a, rtol=1e-6)

    def test_batchnorm_train_and_infer(self):
        x = a(8, 4, 3, 3, seed=29)
        gamma, beta = np.ones(4, np.float32), np.zeros(4, np.float32)
        rm, rv = np.zeros(4, np.float32), np.ones(4, np.float32)
        out, nm, nv = exec_op("batchnorm_train", x, gamma, beta, rm, rv,
                              momentum=0.9, epsilon=1e-5, axis=1)
        o = out.to_numpy()
        np.testing.assert_allclose(o.mean((0, 2, 3)), 0, atol=1e-5)
        np.testing.assert_allclose(o.std((0, 2, 3)), 1, atol=1e-2)
        infer = exec_op("batchnorm", x, x.mean((0, 2, 3)), x.var((0, 2, 3)),
                        gamma, beta, axis=1).to_numpy()
        np.testing.assert_allclose(infer, o, atol=1e-4)

    def test_layer_norm(self):
        x = a(4, 10, seed=30)
        out = exec_op("layer_norm", x, np.ones(10, np.float32), axis=-1).to_numpy()
        np.testing.assert_allclose(out.mean(-1), 0, atol=1e-5)

    def test_lstm_layer_shapes(self):
        B, T, I, U = 2, 5, 3, 4
        x = a(B, T, I, seed=31)
        h0 = np.zeros((B, U), np.float32)
        c0 = np.zeros((B, U), np.float32)
        w_ih = a(I, 4 * U, seed=32) * 0.1
        w_hh = a(U, 4 * U, seed=33) * 0.1
        b = np.zeros(4 * U, np.float32)
        out, hT, cT = exec_op("lstm_layer", x, h0, c0, w_ih, w_hh, b)
        assert out.shape == (B, T, U) and hT.shape == (B, U)
        np.testing.assert_allclose(out.to_numpy()[:, -1], hT.to_numpy(), rtol=1e-5)

    def test_attention(self):
        q = a(2, 4, 8, seed=34)
        out = exec_op("dot_product_attention", q, q, q).to_numpy()
        assert out.shape == (2, 4, 8)
        # uniform keys → attention output = mean of values
        ones = np.ones((1, 3, 4), np.float32)
        v = a(1, 3, 4, seed=35)
        out2 = exec_op("dot_product_attention", ones, ones, v).to_numpy()
        np.testing.assert_allclose(out2[0, 0], v[0].mean(0), rtol=1e-5)

    def test_embedding(self):
        table = a(10, 4, seed=36)
        out = exec_op("embedding_lookup", table, np.array([1, 5])).to_numpy()
        np.testing.assert_allclose(out, table[[1, 5]])

    def test_lrn(self):
        x = a(1, 8, 3, 3, seed=37)
        out = exec_op("lrn", x, depth=2, bias=1.0, alpha=1e-4, beta=0.75).to_numpy()
        assert out.shape == x.shape


class TestLoss:
    def test_softmax_ce(self):
        logits = a(4, 3, seed=38)
        labels = np.eye(3, dtype=np.float32)[[0, 1, 2, 0]]
        l = exec_op("softmax_cross_entropy", logits, labels).to_numpy()
        logp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
        expect = -(labels * logp).sum(-1).mean()
        np.testing.assert_allclose(l, expect, rtol=1e-5)
        sp = exec_op("sparse_softmax_cross_entropy", logits,
                     np.array([0, 1, 2, 0])).to_numpy()
        np.testing.assert_allclose(sp, expect, rtol=1e-5)

    def test_mse_huber(self):
        p, y = a(4, 3, seed=39), a(4, 3, seed=40)
        np.testing.assert_allclose(exec_op("mean_sqerr_loss", p, y).to_numpy(),
                                   ((p - y) ** 2).mean(), rtol=1e-5)
        h = exec_op("huber_loss", p, y, delta=1.0).to_numpy()
        err = np.abs(p - y)
        expect = np.where(err <= 1, 0.5 * err ** 2, err - 0.5).mean()
        np.testing.assert_allclose(h, expect, rtol=1e-5)

    def test_reduction_modes(self):
        p, y = a(4, 3, seed=41), a(4, 3, seed=42)
        none = exec_op("mean_sqerr_loss", p, y, reduction="none").to_numpy()
        assert none.shape == (4,)
        s = exec_op("mean_sqerr_loss", p, y, reduction="sum").to_numpy()
        np.testing.assert_allclose(s, none.sum(), rtol=1e-5)

    def test_ctc_loss_runs(self):
        B, T, C, S = 2, 10, 5, 3
        rng = np.random.default_rng(43)
        logits = rng.normal(size=(B, T, C)).astype(np.float32)
        logp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
        labels = rng.integers(1, C, size=(B, S))
        l = exec_op("ctc_loss", logp, labels, np.array([T, T]), np.array([S, S])).to_numpy()
        assert l.shape == (B,) and np.all(l > 0)


class TestRandomOps:
    def test_distributions_seeded(self):
        u = exec_op("random_uniform", shape=(1000,), seed=1).to_numpy()
        assert 0 <= u.min() and u.max() <= 1 and abs(u.mean() - 0.5) < 0.05
        g = exec_op("random_normal", shape=(1000,), mean=2.0, stddev=0.5, seed=2).to_numpy()
        assert abs(g.mean() - 2.0) < 0.1
        b = exec_op("random_bernoulli", shape=(1000,), prob=0.3, seed=3).to_numpy()
        assert abs(b.mean() - 0.3) < 0.1

    def test_dropout(self):
        x = np.ones((1000,), np.float32)
        d = exec_op("dropout", x, p=0.8, seed=4).to_numpy()
        # inverted dropout: E[out] == x
        assert abs(d.mean() - 1.0) < 0.1
        kept = (d != 0).mean()
        assert abs(kept - 0.8) < 0.1
        same = exec_op("dropout", x, p=0.8, training=False).to_numpy()
        np.testing.assert_allclose(same, x)


class TestBitwiseImage:
    def test_bitwise(self):
        x = np.array([0b1100], np.int32)
        y = np.array([0b1010], np.int32)
        assert exec_op("bitwise_and", x, y).to_numpy()[0] == 0b1000
        assert exec_op("bitwise_or", x, y).to_numpy()[0] == 0b1110
        assert exec_op("bitwise_xor", x, y).to_numpy()[0] == 0b0110
        assert exec_op("shift_left", x, np.array([1])).to_numpy()[0] == 0b11000

    def test_resize(self):
        img = a(1, 4, 4, 3, seed=44)
        out = exec_op("resize_bilinear", img, height=8, width=8).to_numpy()
        assert out.shape == (1, 8, 8, 3)
        nn_out = exec_op("resize_nearest_neighbor", img, height=2, width=2).to_numpy()
        assert nn_out.shape == (1, 2, 2, 3)

    def test_rgb_hsv_roundtrip(self):
        img = np.random.default_rng(45).uniform(0.1, 0.9, (2, 3, 3, 3)).astype(np.float32)
        hsv = exec_op("rgb_to_hsv", img)
        back = exec_op("hsv_to_rgb", hsv.data).to_numpy()
        np.testing.assert_allclose(back, img, atol=1e-4)

    def test_grayscale(self):
        img = np.ones((1, 2, 2, 3), np.float32)
        g = exec_op("rgb_to_grs", img).to_numpy()
        np.testing.assert_allclose(g, 0.9999, atol=1e-3)
