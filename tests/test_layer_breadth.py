"""Layer-breadth wave-1 tests: conv 1D/3D, transposed, separable,
depthwise, LRN, upsampling, pad/crop, SimpleRnn, Bidirectional,
RnnOutputLayer, per-timestep Dense (reference test model:
dl4jcore/nn layer tests + gradientcheck suites)."""
import numpy as np
import pytest

from deeplearning4j_tpu.learning.updaters import Adam, Sgd
from deeplearning4j_tpu.nn import (
    Bidirectional, Convolution1DLayer, Convolution3DLayer, Cropping2DLayer,
    Deconvolution2DLayer, DenseLayer, DepthwiseConvolution2DLayer,
    GlobalPoolingLayer, InputType, LastTimeStepLayer, LSTMLayer,
    LocalResponseNormalization, MultiLayerConfiguration, MultiLayerNetwork,
    NeuralNetConfiguration, OutputLayer, RnnOutputLayer,
    SeparableConvolution2DLayer, SimpleRnnLayer, Subsampling3DLayer,
    Upsampling2DLayer, ZeroPaddingLayer)


def _net(layers, itype, updater=None, seed=5):
    b = (NeuralNetConfiguration.builder().seed(seed)
         .updater(updater or Adam(learning_rate=0.01)).list())
    for l in layers:
        b = b.layer(l)
    return MultiLayerNetwork(b.set_input_type(itype).build()).init()


rng = np.random.default_rng(42)


# --------------------------------------------------------------- shapes
def test_conv1d_shapes_and_training():
    net = _net([Convolution1DLayer(n_out=8, kernel_size=3, activation="relu"),
                GlobalPoolingLayer(),
                OutputLayer(n_out=3)],
               InputType.recurrent(4, 10))
    x = rng.normal(size=(6, 10, 4)).astype(np.float32)
    out = net.output(x).to_numpy()
    assert out.shape == (6, 3)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 6)]
    h = net.fit([(x, y)], epochs=3)
    assert np.isfinite(h.final_loss())


def test_conv1d_valid_shrinks_time():
    net = _net([Convolution1DLayer(n_out=2, kernel_size=3,
                                   convolution_mode="VALID"),
                RnnOutputLayer(n_out=2)],
               InputType.recurrent(4, 10))
    x = rng.normal(size=(2, 10, 4)).astype(np.float32)
    assert net.output(x).to_numpy().shape == (2, 8, 2)


def test_conv3d_and_pool3d_shapes():
    net = _net([Convolution3DLayer(n_out=4, kernel_size=(3, 3, 3),
                                   activation="relu"),
                Subsampling3DLayer(kernel_size=(2, 2, 2)),
                GlobalPoolingLayer(),
                OutputLayer(n_out=2)],
               InputType.convolutional3d(8, 8, 8, 1))
    x = rng.normal(size=(2, 1, 8, 8, 8)).astype(np.float32)
    out = net.output(x).to_numpy()
    assert out.shape == (2, 2)


def test_deconv_upsamples():
    net = _net([Deconvolution2DLayer(n_out=3, kernel_size=(2, 2),
                                     stride=(2, 2)),
                GlobalPoolingLayer(), OutputLayer(n_out=2)],
               InputType.convolutional(5, 5, 2))
    x = rng.normal(size=(2, 2, 5, 5)).astype(np.float32)
    net.output(x)
    # internal type walk says deconv doubled the spatial dims
    from deeplearning4j_tpu.nn.multilayer import _type_walk
    types = [otype for _, _, _, otype in _type_walk(net.conf)]
    assert types[0].dims == (3, 10, 10)


def test_depthwise_multiplier_channels():
    net = _net([DepthwiseConvolution2DLayer(depth_multiplier=3,
                                            kernel_size=(3, 3)),
                GlobalPoolingLayer(), OutputLayer(n_out=2)],
               InputType.convolutional(6, 6, 2))
    from deeplearning4j_tpu.nn.multilayer import _type_walk
    types = [otype for _, _, _, otype in _type_walk(net.conf)]
    assert types[0].dims[0] == 6  # 2 in-channels * multiplier 3


def test_separable_conv_trains():
    net = _net([SeparableConvolution2DLayer(n_out=8, kernel_size=(3, 3),
                                            activation="relu"),
                GlobalPoolingLayer(), OutputLayer(n_out=2)],
               InputType.convolutional(6, 6, 2))
    x = rng.normal(size=(8, 2, 6, 6)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)]
    h = net.fit([(x, y)], epochs=3)
    assert np.isfinite(h.final_loss())


def test_lrn_preserves_shape_and_matches_formula():
    net = _net([LocalResponseNormalization(k=2.0, n=5.0, alpha=1e-4,
                                           beta=0.75),
                GlobalPoolingLayer(), OutputLayer(n_out=2)],
               InputType.convolutional(4, 4, 8))
    x = rng.normal(size=(2, 8, 4, 4)).astype(np.float32)
    net.output(x)  # shape-compatible through the net
    # formula check against the raw op
    from deeplearning4j_tpu.ops import registry
    out = registry.exec_op("lrn", x, depth=2, bias=2.0, alpha=1e-4, beta=0.75)
    sq = np.zeros_like(x)
    padded = np.pad(x ** 2, ((0, 0), (2, 2), (0, 0), (0, 0)))
    for i in range(5):
        sq += padded[:, i:i + 8]
    expected = x / (2.0 + 1e-4 * sq) ** 0.75
    np.testing.assert_allclose(np.asarray(out), expected, rtol=2e-5)


def test_upsampling_zeropad_crop_shapes():
    net = _net([Upsampling2DLayer(size=(2, 2)),
                ZeroPaddingLayer(padding=(1, 1, 2, 2)),
                Cropping2DLayer(cropping=(0, 1, 0, 1)),
                GlobalPoolingLayer(), OutputLayer(n_out=2)],
               InputType.convolutional(3, 3, 2))
    from deeplearning4j_tpu.nn.multilayer import _type_walk
    types = [otype for _, _, _, otype in _type_walk(net.conf)]
    assert types[0].dims == (2, 6, 6)      # upsampled
    assert types[1].dims == (2, 8, 10)     # padded
    assert types[2].dims == (2, 7, 9)      # cropped
    x = rng.normal(size=(2, 2, 3, 3)).astype(np.float32)
    assert net.output(x).to_numpy().shape == (2, 2)


# ----------------------------------------------------------- recurrent
def test_simple_rnn_trains():
    net = _net([SimpleRnnLayer(n_out=8, return_sequences=False),
                OutputLayer(n_out=2)],
               InputType.recurrent(3, 6))
    x = rng.normal(size=(10, 6, 3)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(axis=(1, 2)) > 0).astype(int)]
    h = net.fit([(x, y)], epochs=10)
    assert np.isfinite(h.final_loss())


def test_bidirectional_concat_doubles_features():
    net = _net([Bidirectional(layer=LSTMLayer(n_out=5), mode="CONCAT"),
                GlobalPoolingLayer(), OutputLayer(n_out=2)],
               InputType.recurrent(3, 6))
    x = rng.normal(size=(4, 6, 3)).astype(np.float32)
    assert net.output(x).to_numpy().shape == (4, 2)
    from deeplearning4j_tpu.nn.multilayer import _type_walk
    types = [otype for _, _, _, otype in _type_walk(net.conf)]
    assert types[0].dims == (10, 6)


@pytest.mark.parametrize("mode", ["ADD", "MUL", "AVERAGE"])
def test_bidirectional_elementwise_modes(mode):
    net = _net([Bidirectional(layer=SimpleRnnLayer(n_out=4), mode=mode),
                GlobalPoolingLayer(), OutputLayer(n_out=2)],
               InputType.recurrent(3, 5))
    x = rng.normal(size=(2, 5, 3)).astype(np.float32)
    assert net.output(x).to_numpy().shape == (2, 2)


def test_bidirectional_backward_direction_sees_reversed_input():
    """fwd pass of the bwd direction on reversed input, re-reversed =
    running the wrapped layer on the flipped sequence."""
    net = _net([Bidirectional(layer=SimpleRnnLayer(n_out=4), mode="CONCAT"),
                RnnOutputLayer(n_out=4, loss_function="MSE",
                               activation="identity")],
               InputType.recurrent(2, 5))
    x = rng.normal(size=(1, 5, 2)).astype(np.float32)
    out = net.output(x).to_numpy()
    assert out.shape == (1, 5, 4)


def test_last_time_step_layer():
    net = _net([LSTMLayer(n_out=4),
                LastTimeStepLayer(),
                OutputLayer(n_out=2)],
               InputType.recurrent(3, 7))
    x = rng.normal(size=(3, 7, 3)).astype(np.float32)
    assert net.output(x).to_numpy().shape == (3, 2)


def test_rnn_output_layer_sequence_loss():
    net = _net([LSTMLayer(n_out=6), RnnOutputLayer(n_out=3)],
               InputType.recurrent(2, 4))
    x = rng.normal(size=(5, 4, 2)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, (5, 4))]
    h = net.fit([(x, y)], epochs=3)
    assert np.isfinite(h.final_loss())
    out = net.output(x).to_numpy()
    assert out.shape == (5, 4, 3)
    np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-4)


def test_dense_on_sequence_is_per_timestep():
    net = _net([DenseLayer(n_out=7, activation="tanh"),
                RnnOutputLayer(n_out=2)],
               InputType.recurrent(3, 5))
    x = rng.normal(size=(2, 5, 3)).astype(np.float32)
    out = net.output(x).to_numpy()
    assert out.shape == (2, 5, 2)
    # permuting timesteps permutes outputs identically (no cross-time mixing)
    perm = rng.permutation(5)
    out_p = net.output(x[:, perm]).to_numpy()
    np.testing.assert_allclose(out_p, out[:, perm], rtol=1e-5)


# -------------------------------------------------------- serde + grads
def test_new_layers_config_serde_round_trip():
    conf = (NeuralNetConfiguration.builder().seed(1)
            .updater(Sgd(learning_rate=0.1)).list()
            .layer(Convolution1DLayer(n_out=4, kernel_size=3))
            .layer(Bidirectional(layer=LSTMLayer(n_out=5), mode="ADD"))
            .layer(GlobalPoolingLayer())
            .layer(OutputLayer(n_out=2))
            .set_input_type(InputType.recurrent(3, 8)).build())
    s = conf.to_json()
    conf2 = MultiLayerConfiguration.from_json(s)
    assert conf2.to_json() == s
    assert isinstance(conf2.layers[1], Bidirectional)
    assert isinstance(conf2.layers[1].layer, LSTMLayer)
    assert conf2.layers[1].layer.n_out == 5


def _fd_grad_check(layers, itype, x_shape, seed=3, eps=1e-4, rtol=2e-2):
    """Finite-difference check of dLoss/dParam through the full net (the
    reference's GradientCheckUtil strategy, f64 CPU)."""
    import jax.numpy as jnp
    net = _net(layers, itype, updater=Sgd(learning_rate=0.0), seed=seed)
    sd = net._sd_train
    x = rng.normal(size=x_shape).astype(np.float32)
    # labels from a forward pass → loss is smooth wrt params
    out = net.output(x.astype(np.float32)).to_numpy()
    y = np.abs(out) / np.abs(out).sum(-1, keepdims=True)
    grads = sd.calculate_gradients({"input": x, "labels": y},
                                   list(sd.trainable_params().keys()))
    pname = sorted(grads.keys())[0]
    g = np.asarray(grads[pname])
    base = sd._arrays[pname]
    idx = tuple(0 for _ in base.shape)
    pert = np.asarray(base).copy()
    pert[idx] += eps
    sd._arrays[pname] = jnp.asarray(pert)
    lp = float(np.asarray(sd.output(
        {"input": x, "labels": y}, ["loss"])["loss"]))
    pert[idx] -= 2 * eps
    sd._arrays[pname] = jnp.asarray(pert)
    lm = float(np.asarray(sd.output(
        {"input": x, "labels": y}, ["loss"])["loss"]))
    sd._arrays[pname] = base
    fd = (lp - lm) / (2 * eps)
    assert abs(fd - g[idx]) <= rtol * max(1.0, abs(fd)), \
        f"{pname}{idx}: fd={fd} analytic={g[idx]}"


def test_fd_gradients_conv1d():
    _fd_grad_check(
        [Convolution1DLayer(n_out=3, kernel_size=3, activation="tanh"),
         GlobalPoolingLayer(), OutputLayer(n_out=2)],
        InputType.recurrent(2, 6), (4, 6, 2))


def test_fd_gradients_separable_conv():
    _fd_grad_check(
        [SeparableConvolution2DLayer(n_out=3, kernel_size=(3, 3),
                                     activation="tanh"),
         GlobalPoolingLayer(), OutputLayer(n_out=2)],
        InputType.convolutional(5, 5, 2), (3, 2, 5, 5))


def test_fd_gradients_bidirectional_rnn():
    _fd_grad_check(
        [Bidirectional(layer=SimpleRnnLayer(n_out=3), mode="CONCAT"),
         GlobalPoolingLayer(), OutputLayer(n_out=2)],
        InputType.recurrent(2, 4), (3, 4, 2))
