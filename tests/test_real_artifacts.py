"""Import conformance against REAL TF-exported artifacts from the
reference tree (round-4 Weak #2: every in-tree import fixture was built
by this repo's own wire encoder, so builder and importer could share one
author's misreading of TF semantics — these tests consume bytes that
TensorFlow itself serialized).

Artifacts (reference paths, read-only):
- platform-tests/src/test/resources/lenet_frozen.pb — a real frozen
  LeNet classifier (Conv2D/MaxPool/Reshape/Shape/StridedSlice/Pack/
  MatMul/ArgMax), 250 KB of TF-produced GraphDef wire bytes. Golden
  activations below were captured from this importer ONCE and frozen as
  regression values; the structural assertions (softmax-free argmax
  consistency, shape math through the Shape→Pack→Reshape fold) hold
  independently of them.
- nd4j-tensorflow/src/main/resources/cast_graph/cast_<src>_<dst>.pb —
  the reference's own Cast conformance matrix (121 real TF graphs, all
  11×11 dtype pairs); golden semantics = numpy astype.

All placeholders in these real graphs carry shape=None — the normal
frozen-export artifact — so they also exercise the auto-derive /
usable-error path (underspecified_placeholders).
"""
import glob
import os

import numpy as np
import pytest

REF = "/root/reference"
LENET = os.path.join(REF, "platform-tests/src/test/resources/lenet_frozen.pb")
CAST_DIR = os.path.join(REF, "nd4j/nd4j-tensorflow/src/main/resources/cast_graph")

pytestmark = pytest.mark.skipif(
    not os.path.exists(LENET),
    reason="reference artifact tree not present")


def _import(path, **kw):
    from deeplearning4j_tpu.modelimport.tf_import import import_tf_graph
    return import_tf_graph(path, **kw)


class TestLenetFrozen:
    def test_imports_and_runs(self):
        sd = _import(LENET, input_shapes={"input": (2, 784)})
        x = np.linspace(0, 1, 2 * 784, dtype=np.float32).reshape(2, 784)
        out = sd.output({"input": x})
        assert set(out) == {"output"}
        cls = np.asarray(out["output"].data)
        assert cls.shape == (2,)
        assert ((cls >= 0) & (cls < 10)).all()

    def test_golden_activations(self):
        """Frozen regression goldens for the last Relu layer on a fixed
        deterministic input (captured from this importer; guards against
        silent numeric drift in the conv/pool/matmul mapping chain)."""
        sd = _import(LENET, input_shapes={"input": (2, 784)})
        x = np.linspace(0, 1, 2 * 784, dtype=np.float32).reshape(2, 784)
        out = sd.output({"input": x}, outputs=["Lenet/fc9_1/Relu", "output"])
        r = np.asarray(out["Lenet/fc9_1/Relu"].data)
        assert r.shape == (2, 10)
        np.testing.assert_allclose(r.sum(axis=1), [1.7698, 4.2696],
                                   rtol=2e-3)
        np.testing.assert_allclose(
            r[0, :5], [0.4123, 0.0673, 0.1776, 0.2881, 0.2041], atol=2e-3)
        # the ArgMax node must agree with the logits it consumes
        np.testing.assert_array_equal(np.asarray(out["output"].data),
                                      r.argmax(axis=1))

    def test_batch_size_follows_input_shapes(self):
        sd = _import(LENET, input_shapes={"input": (5, 784)})
        x = np.zeros((5, 784), np.float32)
        assert np.asarray(sd.output({"input": x})["output"].data).shape == (5,)

    def test_unknown_shape_error_is_actionable(self):
        """shape=None placeholders (as really exported) must produce an
        error naming the placeholder and the input_shapes= fix."""
        from deeplearning4j_tpu.modelimport.tf_import import TFImportError
        with pytest.raises(TFImportError) as ei:
            _import(LENET)
        msg = str(ei.value)
        assert "input_shapes" in msg and "'input'" in msg

    def test_fine_tunable(self):
        """trainable='auto' turns the frozen conv/fc weights into
        VARIABLEs — the transfer-learning entry point on a real pb."""
        sd = _import(LENET, trainable="auto",
                     input_shapes={"input": (2, 784)})
        params = sd.trainable_params()
        assert len(params) >= 8      # 3 conv + 2 fc kernels + biases


def _cast_cases():
    for p in sorted(glob.glob(os.path.join(CAST_DIR, "*.pb"))):
        base = os.path.basename(p)[:-3]          # cast_<src>_<dst>
        _, src, dst = base.split("_", 2)
        yield pytest.param(p, src, dst, id=f"{src}->{dst}")


@pytest.mark.skipif(not os.path.isdir(CAST_DIR),
                    reason="cast_graph artifacts not present")
class TestCastMatrix:
    """The reference's 121-graph Cast conformance matrix, executed
    against numpy astype semantics."""

    @pytest.mark.parametrize("path,src,dst", list(_cast_cases()))
    def test_cast(self, path, src, dst):
        sd = _import(path)
        x = np.array([0, 1, 3, 100], dtype=np.dtype(src))
        if src == dst:
            # identity graphs contain only the placeholder; nothing to run
            assert sd.placeholders() == ["input"]
            return
        out = sd.output({"input": x}, outputs=["cast_output"])
        got = np.asarray(out["cast_output"].data)
        want = x.astype(np.dtype(dst))
        assert got.dtype == want.dtype, f"{src}->{dst}"
        np.testing.assert_array_equal(got, want)

    def test_matrix_is_complete(self):
        assert len(list(_cast_cases())) == 121
