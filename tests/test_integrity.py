"""integrity/ — stall watchdog, silent-corruption fingerprints,
checkpoint scrubbing (the non-raising-failure rail).

Covers the PR-4/PR-8 clean-path discipline (fingerprints + watchdog
armed vs off are bit-identical on the fused, per-step and scanned
tiers), pins each chaos injector to its typed error, and drives the
composite chaos e2e: one FaultTolerantFit run survives a stalled
dispatch, a param bit-flip and a rotten newest checkpoint, finishing
bit-identical to an uninterrupted run.
"""
import json
import os
import subprocess
import sys
import time
import urllib.request

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.autodiff import (SameDiff, ScoreIterationListener,
                                         TrainingConfig)
from deeplearning4j_tpu.checkpoint import (CheckpointManager, Scrubber,
                                           capture_training_state)
from deeplearning4j_tpu.checkpoint import manifest as ckpt_manifest
from deeplearning4j_tpu.dataset.iterators import (ArrayDataSetIterator,
                                                  DeviceCachedIterator)
from deeplearning4j_tpu.faults import (ChaosMonkey, FaultTolerantFit,
                                       RetryPolicy, SilentCorruptionError,
                                       TrainingStalledError,
                                       retryable_errors)
from deeplearning4j_tpu.integrity import (StallWatchdog,
                                          check_replica_agreement,
                                          dump_all_stacks, np_fingerprint,
                                          np_leaf_fingerprint,
                                          state_fingerprint,
                                          tree_fingerprint,
                                          verify_state_stamp)
from deeplearning4j_tpu.learning.updaters import Adam
from deeplearning4j_tpu.ui.stats import StatsStorage


def _mlp(fused_steps=4, fingerprints=False, replay_every=0, lr=1e-2,
         accum_steps=1):
    rng = np.random.default_rng(0)
    sd = SameDiff()
    x = sd.placeholder("x", shape=(-1, 8))
    w0 = sd.var("w0", value=rng.normal(0, .1, (8, 16)).astype(np.float32))
    b0 = sd.var("b0", value=np.zeros(16, np.float32))
    h = sd.nn.relu(x.mmul(w0).add(b0))
    w1 = sd.var("w1", value=rng.normal(0, .1, (16, 2)).astype(np.float32))
    logits = h.mmul(w1)
    labels = sd.placeholder("labels", shape=(-1, 2))
    sd.loss.softmax_cross_entropy(logits, labels, name="loss")
    sd.set_loss_variables(["loss"])
    sd.training_config = TrainingConfig(
        updater=Adam(lr), data_set_feature_mapping=["x"],
        data_set_label_mapping=["labels"], fused_steps=fused_steps,
        accum_steps=accum_steps, fingerprints=fingerprints,
        fingerprint_replay_every=replay_every)
    return sd


def _data(n=128, seed=1):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 8)).astype(np.float32)
    Y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, n)]
    return X, Y


def _quiet():
    return ScoreIterationListener(print_every=10 ** 9,
                                  print_fn=lambda *a: None)


def _params(sd):
    return {n: np.asarray(a) for n, a in sd.trainable_params().items()}


def _fast_watchdog(**kw):
    kw.setdefault("k", 4.0)
    kw.setdefault("floor_s", 0.15)
    kw.setdefault("grace_s", 0.4)
    kw.setdefault("poll_s", 0.02)
    kw.setdefault("min_samples", 2)
    return StallWatchdog(**kw)


# ---------------------------------------------------------------------------
# the digest itself

class TestFingerprintDigest:
    def test_host_device_parity_across_dtypes(self, rng):
        arrs = [rng.normal(size=(5, 7)).astype(np.float32),
                rng.normal(size=(3,)).astype(np.float64),
                rng.integers(0, 255, (4, 4)).astype(np.uint8),
                rng.normal(size=(2, 3)).astype(np.float16),
                np.array([True, False, True]),
                rng.integers(-5, 5, (6,)).astype(np.int32),
                rng.integers(-5, 5, (2,)).astype(np.int64)]
        host = np_fingerprint(arrs)
        import jax.numpy as jnp
        dev = int(jax.device_get(
            tree_fingerprint([jnp.asarray(a) for a in arrs])))
        assert host == dev

    def test_single_bit_flip_always_changes_digest(self, rng):
        a = rng.normal(size=(4, 4)).astype(np.float32)
        base = np_leaf_fingerprint(a)
        flat = a.copy().view(np.uint8).reshape(-1)
        # a u32 word-sum mod 2^32 changes by ±2^b on ANY single-bit
        # flip — exhaustively true, spot-check a spread of positions
        for pos in (0, 7, 13, 31, 64, flat.size * 8 - 1):
            b = a.copy()
            v = b.view(np.uint8).reshape(-1)
            v[pos // 8] ^= np.uint8(1 << (pos % 8))
            assert np_leaf_fingerprint(b) != base, f"bit {pos} silent"

    def test_order_independence(self, rng):
        leaves = [rng.normal(size=(3, 3)).astype(np.float32)
                  for _ in range(5)]
        assert np_fingerprint(leaves) == np_fingerprint(leaves[::-1])

    def test_empty_and_scalar_leaves(self):
        assert np_fingerprint([np.empty((0,), np.float32)]) == 0
        s = np.float32(1.5)
        assert np_leaf_fingerprint(s) == \
            int(np.asarray(s).view(np.uint32))


# ---------------------------------------------------------------------------
# clean-path bit-identity (the PR-4/PR-8 discipline)

class TestCleanPathBitIdentity:
    def _run(self, tier, fingerprints, watchdog):
        sd = _mlp(fused_steps=4 if tier == "windowed" else 1,
                  fingerprints=fingerprints,
                  accum_steps=2 if tier == "accum" else 1)
        if tier == "accum":
            sd.training_config.fused_steps = 4
        X, Y = _data()
        it = DeviceCachedIterator(X, Y, batch_size=16) \
            if tier == "scanned" else ArrayDataSetIterator(X, Y,
                                                           batch_size=16)
        listeners = [] if tier == "scanned" else [_quiet()]
        if watchdog:
            with _fast_watchdog(grace_s=60.0, floor_s=60.0):
                h = sd.fit(it, epochs=2, listeners=listeners)
        else:
            h = sd.fit(it, epochs=2, listeners=listeners)
        return _params(sd), h, sd

    @pytest.mark.parametrize("tier", ["windowed", "per_step", "scanned",
                                      "accum"])
    def test_rail_on_is_bit_identical(self, tier):
        p_off, h_off, _ = self._run(tier, False, False)
        p_on, h_on, sd = self._run(tier, True, True)
        for n in p_off:
            assert np.array_equal(p_off[n], p_on[n]), n
        assert h_off.final_loss() == h_on.final_loss()
        if tier == "scanned":
            assert sd.last_fit_stats["tier"] == "scanned_epoch"
        # the rail actually ran: a boundary digest was produced
        assert sd._device_fingerprint is not None

    def test_all_tiers_agree_on_boundary_digest(self):
        """Fused, per-step and scanned tiers end at the same params —
        their device digests must agree bit-for-bit (cross-validates
        the in-window digest against the separate per-step program)."""
        fps = {}
        for tier in ("windowed", "per_step", "scanned"):
            _, _, sd = self._run(tier, True, False)
            fps[tier] = sd._device_fingerprint["fp"]
        assert len(set(fps.values())) == 1, fps

    def test_probe_windows_do_not_change_math(self):
        p_base, _, _ = self._run("windowed", True, False)
        sd = _mlp(fused_steps=4, fingerprints=True, replay_every=1)
        X, Y = _data()
        sd.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=2,
               listeners=[_quiet()])
        assert sd.last_fit_stats["replay_probes"] > 0
        for n, v in p_base.items():
            assert np.array_equal(v, _params(sd)[n]), n


# ---------------------------------------------------------------------------
# capture stamping + restore re-verification

class TestCaptureAndRestoreStamp:
    def _trained(self, tmp_path, fingerprints=True):
        sd = _mlp(fingerprints=fingerprints)
        X, Y = _data()
        sd.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=1,
               listeners=[_quiet()])
        mgr = CheckpointManager(tmp_path, keep_last_n=10,
                                async_write=False)
        return sd, mgr

    def test_capture_stamps_verified(self, tmp_path):
        sd, mgr = self._trained(tmp_path)
        mgr.save(8, model=sd, blocking=True)
        _, state = mgr.restore_latest()
        stamp = state.metadata["integrity"]
        assert stamp["verified"] is True
        assert stamp["fingerprint"] == stamp["device_fingerprint"] \
            == state_fingerprint(state)
        assert verify_state_stamp(state) is True
        mgr.close()

    def test_capture_mismatch_raises_typed(self, tmp_path):
        sd, mgr = self._trained(tmp_path)
        # corrupt the host-side state AFTER the device digest was taken
        # (what a bad D2H copy looks like)
        name = sorted(sd.trainable_params())[0]
        host = np.asarray(sd._arrays[name]).copy()
        host.view(np.uint8).reshape(-1)[3] ^= 1
        import jax.numpy as jnp
        sd._arrays[name] = jnp.asarray(host)
        with pytest.raises(SilentCorruptionError) as ei:
            capture_training_state(sd)
        assert ei.value.check == "capture"
        mgr.close()

    def test_unstamped_checkpoints_restore_as_before(self, tmp_path):
        sd, mgr = self._trained(tmp_path, fingerprints=False)
        mgr.save(8, model=sd, blocking=True)
        _, state = mgr.restore_latest()
        assert "integrity" not in state.metadata
        assert verify_state_stamp(state) is None
        mgr.close()

    def test_restore_reverifies_stamp(self, tmp_path):
        """Rot that the sha256 manifest can no longer witness (payload
        AND manifest rewritten) still fails typed at restore — and the
        verified-only walk lands on an older intact step."""
        sd, mgr = self._trained(tmp_path)
        mgr.save(8, model=sd, blocking=True)
        mgr.save(16, model=sd, blocking=True)
        d = mgr.step_dir(16)
        p = os.path.join(d, "arrays.npz")
        with np.load(p) as npz:
            arrays = {k: npz[k].copy() for k in npz.files}
        first = sorted(arrays)[0]
        arrays[first].view(np.uint8).reshape(-1)[3] ^= 1
        np.savez(p, **arrays)                  # valid npz, wrong bits
        ckpt_manifest.write_manifest(d)        # adversarial re-hash
        with pytest.raises(SilentCorruptionError):
            mgr.restore(16)
        with pytest.raises(SilentCorruptionError):
            mgr.restore_latest()
        step, _ = mgr.restore_latest(verified_only=True)
        assert step == 8
        assert mgr.latest_verified_step() == 8
        mgr.close()

    def test_retryable_taxonomy(self):
        types = retryable_errors()
        assert SilentCorruptionError in types
        assert TrainingStalledError in types


# ---------------------------------------------------------------------------
# replay probe + chaos corruption injectors

class TestReplayProbeAndBitflip:
    @pytest.mark.chaos
    def test_probe_catches_self_consistent_sdc(self):
        """refingerprint=True: device state and its digest agree but
        differ from a correct replay — only the probe can see it."""
        sd = _mlp(fingerprints=True, replay_every=1)
        X, Y = _data()
        chaos = ChaosMonkey(0)
        with chaos.bitflip_param(at_call=3):
            with pytest.raises(SilentCorruptionError) as ei:
                sd.fit(ArrayDataSetIterator(X, Y, batch_size=16),
                       epochs=1, listeners=[_quiet()])
        assert ei.value.check == "replay_probe"
        assert chaos.log[-1]["event"] == "param_bit_flipped"
        assert chaos.log[-1]["refingerprint"] is True

    @pytest.mark.chaos
    def test_capture_catches_transfer_corruption(self, tmp_path):
        """refingerprint=False: the in-program digest is intact, the
        returned bytes are not — the capture check sees it and the
        recovery driver rolls back to a VERIFIED checkpoint."""
        sd = _mlp(fingerprints=True)
        X, Y = _data()
        storage = StatsStorage()
        mgr = CheckpointManager(tmp_path, keep_last_n=10,
                                async_write=False)
        ftf = FaultTolerantFit(
            sd, mgr, policy=RetryPolicy(max_retries=2, backoff_base=0.0),
            checkpoint_every_n_iterations=4, stats_storage=storage,
            sleep=lambda s: None)
        chaos = ChaosMonkey(1)
        with chaos.bitflip_param(at_call=3, refingerprint=False):
            h = ftf.fit(ArrayDataSetIterator(X, Y, batch_size=16),
                        epochs=2)
        assert np.isfinite(h.final_loss())
        assert ftf.rollbacks >= 1
        rb = [r for r in storage.of_type("faults")
              if r["event"] == "rollback"]
        assert rb and all(r["verified_only"] for r in rb)
        fault = [r for r in storage.of_type("faults")
                 if r["event"] == "fault"][0]
        assert fault["cause"] == "silent_corruption"
        mgr.close()

    @pytest.mark.chaos
    def test_fingerprints_off_is_genuinely_silent(self):
        """The negative control: without the rail, the same bit flip
        trains through unnoticed — finite loss, corrupted timeline."""
        sd = _mlp(fingerprints=False)
        X, Y = _data()
        chaos = ChaosMonkey(0)
        with chaos.bitflip_param(at_call=1):
            h = sd.fit(ArrayDataSetIterator(X, Y, batch_size=16),
                       epochs=1, listeners=[_quiet()])
        assert np.isfinite(h.final_loss())      # nothing raised
        clean = _mlp(fingerprints=False)
        clean.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=1,
                  listeners=[_quiet()])
        assert any(not np.array_equal(_params(sd)[n], _params(clean)[n])
                   for n in _params(sd))        # but the bits diverged


class TestReplicaAgreement:
    def test_replicated_params_agree(self):
        from jax.sharding import (Mesh, NamedSharding,
                                  PartitionSpec as P)
        devs = jax.devices()[:4]
        repl = NamedSharding(Mesh(np.array(devs), ("dp",)), P())
        a = np.arange(12, dtype=np.float32).reshape(3, 4)
        assert check_replica_agreement(
            {"w": jax.device_put(a, repl)}) == []

    def test_desynced_replica_raises(self):
        from jax.sharding import (Mesh, NamedSharding,
                                  PartitionSpec as P)
        devs = jax.devices()[:4]
        repl = NamedSharding(Mesh(np.array(devs), ("dp",)), P())
        a = np.arange(12, dtype=np.float32).reshape(3, 4)
        parts = [jax.device_put(a.copy(), d) for d in devs]
        bad = a.copy()
        bad.view(np.uint8).reshape(-1)[5] ^= 1
        parts[2] = jax.device_put(bad, devs[2])
        arr = jax.make_array_from_single_device_arrays(a.shape, repl,
                                                       parts)
        with pytest.raises(SilentCorruptionError) as ei:
            check_replica_agreement({"w": arr})
        assert ei.value.check == "replica_agreement"
        detail = check_replica_agreement({"w": arr}, raise_=False)
        assert detail[0]["array"] == "w"

    def test_host_arrays_short_circuit(self):
        # un-sharded host values have no addressable shards: no-op
        assert check_replica_agreement({"w": np.ones(3)}) == []


# ---------------------------------------------------------------------------
# stall watchdog

class TestStallWatchdog:
    def test_noop_guard_when_uninstalled(self):
        from deeplearning4j_tpu.integrity.watchdog import guard
        with guard("window_dispatch"):
            pass                                # shared null context

    def test_adaptive_deadline_and_compile_grace(self):
        wd = _fast_watchdog(k=10.0, floor_s=0.01, grace_s=5.0,
                            min_samples=3)
        # under min_samples → grace
        assert wd.deadline_for("b") == 5.0
        for v in (0.1, 0.1, 0.1):
            wd._percentiles.setdefault(
                "b", __import__(
                    "deeplearning4j_tpu.monitor.steptime",
                    fromlist=["RollingPercentiles"]
                ).RollingPercentiles(8)).add(v)
        assert wd.deadline_for("b") == pytest.approx(1.0)
        # a first (compiling) dispatch always gets the grace
        assert wd.deadline_for("b", first=True) == 5.0

    @pytest.mark.chaos
    def test_stall_raises_typed_with_forensics(self):
        from deeplearning4j_tpu.integrity.watchdog import guard
        storage = StatsStorage()
        wd = _fast_watchdog(storage=storage, min_samples=1,
                            floor_s=0.05, k=2.0)
        with wd:
            with guard("x"):
                time.sleep(0.002)
            with pytest.raises(TrainingStalledError) as ei:
                with guard("x"):
                    time.sleep(0.5)
        e = ei.value
        assert e.boundary == "x" and e.waited_s > e.deadline_s
        assert any(s["name"] for s in e.forensics["stacks"])
        prov = e.provenance()
        assert prov["cause"] == "stall" and prov["boundary"] == "x"
        events = [r["event"] for r in storage.of_type("faults")]
        assert events.count("stall") == 1
        forens = storage.of_type("integrity")
        assert forens and forens[0]["event"] == "stall_forensics"

    def test_stall_flips_health_until_recovered(self):
        from deeplearning4j_tpu.monitor.server import health_snapshot
        storage = StatsStorage()
        storage.put({"type": "faults", "event": "stall", "t": time.time(),
                     "boundary": "window_dispatch"})
        snap = health_snapshot(storage)
        assert snap["healthy"] is False
        assert snap["fault_state"] == "recovering"
        storage.put({"type": "faults", "event": "recovered",
                     "t": time.time()})
        assert health_snapshot(storage)["healthy"] is True

    def test_in_flight_exception_not_masked(self):
        from deeplearning4j_tpu.integrity.watchdog import guard
        wd = _fast_watchdog(min_samples=1, floor_s=0.05, k=2.0,
                            forensics=False)
        with wd:
            with guard("y"):
                time.sleep(0.002)
            with pytest.raises(ValueError):
                with guard("y"):
                    time.sleep(0.3)
                    raise ValueError("the real failure")

    @pytest.mark.chaos
    def test_stalled_dispatch_recovered_by_ftf(self, tmp_path):
        sd = _mlp()
        X, Y = _data()
        chaos = ChaosMonkey(0)
        storage = StatsStorage()
        mgr = CheckpointManager(tmp_path, async_write=False)
        ftf = FaultTolerantFit(
            sd, mgr, policy=RetryPolicy(max_retries=2, backoff_base=0.0),
            checkpoint_every_n_iterations=4, stats_storage=storage,
            sleep=lambda s: None)
        with _fast_watchdog(storage=storage):
            ftf.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=1)
            with chaos.stalled_dispatch(delay_s=1.0, at_call=1):
                h = ftf.fit(ArrayDataSetIterator(X, Y, batch_size=16),
                            epochs=1)
        assert np.isfinite(h.final_loss())
        events = [r["event"] for r in storage.of_type("faults")]
        assert "stall" in events and "recovered" in events
        assert ftf.rollbacks == 1
        fault = [r for r in storage.of_type("faults")
                 if r["event"] == "fault"][0]
        assert fault["cause"] == "stall"
        mgr.close()


class TestStacksRoute:
    def test_dump_all_stacks_sees_this_thread(self):
        stacks = dump_all_stacks()
        me = [s for s in stacks if s["name"] == "MainThread"]
        assert me and any("dump_all_stacks" in ln or "test_" in ln
                          for ln in me[0]["stack"])

    def test_stacks_route_serves_json(self):
        from deeplearning4j_tpu.monitor.server import serve
        server = serve(storage=StatsStorage())
        try:
            body = json.loads(urllib.request.urlopen(
                server.url + "/stacks", timeout=10).read())
            assert body["threads"]
            index = urllib.request.urlopen(server.url + "/",
                                           timeout=10).read().decode()
            assert "/stacks" in index
        finally:
            server.close()


# ---------------------------------------------------------------------------
# checkpoint scrubber + restore-path memo

class TestScrubber:
    def _tree(self, tmp_path, steps=(4, 8, 12)):
        sd = _mlp(fingerprints=True)
        X, Y = _data()
        sd.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=1,
               listeners=[_quiet()])
        mgr = CheckpointManager(tmp_path, keep_last_n=10,
                                async_write=False)
        for s in steps:
            mgr.save(s, model=sd, blocking=True)
        return sd, mgr

    def test_scrub_clean_tree(self, tmp_path):
        _, mgr = self._tree(tmp_path)
        storage = StatsStorage()
        rep = Scrubber(mgr, storage=storage).scrub_once()
        assert rep["scanned"] == 3 and rep["rotten"] == 0
        assert storage.of_type("integrity")[-1]["event"] == "scrub"
        mgr.close()

    @pytest.mark.chaos
    def test_rot_quarantined_aside_with_typed_record(self, tmp_path):
        _, mgr = self._tree(tmp_path)
        ChaosMonkey(0).rot_checkpoint(tmp_path, step=8)
        storage = StatsStorage()
        rep = Scrubber(mgr, storage=storage).scrub_once()
        assert rep["rotten"] == 1 and rep["quarantined"] == [8]
        rotten_dir = os.path.join(str(tmp_path), "step_00000008.rotten")
        assert os.path.isdir(rotten_dir)
        with open(os.path.join(rotten_dir, "ROTTEN.json")) as fh:
            rec = json.load(fh)
        assert rec["step"] == 8 and rec["problems"]
        # the quarantined name is invisible to restore/retention/gc
        assert mgr.all_steps() == [4, 12]
        assert mgr.restore_latest()[0] == 12
        assert mgr.gc_uncommitted() == []
        ev = [r["event"] for r in storage.of_type("integrity")]
        assert "checkpoint_quarantined" in ev
        mgr.close()

    @pytest.mark.chaos
    def test_rotten_newest_never_lands_mid_recovery(self, tmp_path):
        """The acceptance property: after a scrub, a rollback cannot
        land on bit-rot — and even WITHOUT a scrub, restore_latest's
        own verification skips it."""
        _, mgr = self._tree(tmp_path)
        ChaosMonkey(0).rot_checkpoint(tmp_path)      # newest = 12
        step, _ = mgr.restore_latest()
        assert step == 8
        mgr.close()

    @pytest.mark.chaos
    def test_re_rot_keeps_first_forensics(self, tmp_path):
        """A step that rots again after a re-save quarantines to
        .rotten.2 — the first incident's evidence stays untouched."""
        sd, mgr = self._tree(tmp_path, steps=(8,))
        ChaosMonkey(0).rot_checkpoint(tmp_path, step=8)
        sc = Scrubber(mgr)
        sc.scrub_once()
        first = os.path.join(str(tmp_path), "step_00000008.rotten")
        with open(os.path.join(first, "ROTTEN.json")) as fh:
            t_first = json.load(fh)["quarantined_t"]
        mgr.save(8, model=sd, blocking=True)           # re-save
        ChaosMonkey(1).rot_checkpoint(tmp_path, step=8)
        sc.scrub_once()
        second = first + ".2"
        assert os.path.isdir(first) and os.path.isdir(second)
        with open(os.path.join(first, "ROTTEN.json")) as fh:
            assert json.load(fh)["quarantined_t"] == t_first
        mgr.close()

    def test_rate_limit_sleeps_off_surplus(self, tmp_path):
        _, mgr = self._tree(tmp_path)
        slept = []
        sc = Scrubber(mgr, max_mb_per_s=1e-3,          # absurdly slow
                      sleep=lambda s: slept.append(s))
        sc.scrub_once()
        assert slept and sum(slept) > 0
        mgr.close()

    def test_background_cycles(self, tmp_path):
        _, mgr = self._tree(tmp_path)
        sc = Scrubber(mgr, interval_s=0.01)
        with sc:
            deadline = time.monotonic() + 5
            while sc.cycles < 2 and time.monotonic() < deadline:
                time.sleep(0.01)
        assert sc.cycles >= 2
        mgr.close()

    def test_cli_exit_codes(self, tmp_path):
        from deeplearning4j_tpu.checkpoint.__main__ import main
        _, mgr = self._tree(tmp_path)
        mgr.close()
        assert main(["scrub", str(tmp_path)]) == 0
        ChaosMonkey(0).rot_checkpoint(tmp_path, step=8)
        assert main(["scrub", str(tmp_path)]) == 1
        assert main(["scrub", str(tmp_path / "nope")]) == 2
        assert main([]) == 2
        # --quarantine moves it aside; the tree is then clean again
        assert main(["scrub", str(tmp_path), "--quarantine"]) == 1
        assert main(["scrub", str(tmp_path)]) == 0

    def test_cli_subprocess_entrypoint(self, tmp_path):
        _, mgr = self._tree(tmp_path, steps=(4,))
        mgr.close()
        r = subprocess.run(
            [sys.executable, "-m", "deeplearning4j_tpu.checkpoint",
             "scrub", str(tmp_path), "--json"],
            capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert r.returncode == 0, r.stderr
        rep = json.loads(r.stdout)
        assert rep["type"] == "integrity" and rep["scanned"] == 1


class TestRestoreMemo:
    def _hash_counter(self, monkeypatch):
        calls = {"n": 0}
        orig = ckpt_manifest.sha256_file

        def counting(path, chunk=1 << 20):
            calls["n"] += 1
            return orig(path, chunk)

        monkeypatch.setattr(ckpt_manifest, "sha256_file", counting)
        return calls

    def test_repeat_restores_skip_rehash(self, tmp_path, monkeypatch):
        sd = _mlp()
        X, Y = _data()
        sd.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=1,
               listeners=[_quiet()])
        mgr = CheckpointManager(tmp_path, async_write=False)
        for s in (4, 8):
            mgr.save(s, model=sd, blocking=True)
        calls = self._hash_counter(monkeypatch)
        mgr.restore_latest()
        first = calls["n"]
        assert first > 0
        # the recovery-loop case: repeated rollbacks over unchanged
        # committed files must not re-hash on the critical path
        mgr.restore_latest()
        mgr.restore(8)
        assert calls["n"] == first
        mgr.close()

    def test_memo_expires_after_ttl(self, tmp_path, monkeypatch):
        """Media rot bypasses the filesystem (no mtime change), so
        memo entries expire: a restore after the TTL re-hashes even an
        unchanged dir."""
        sd = _mlp()
        X, Y = _data()
        sd.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=1,
               listeners=[_quiet()])
        mgr = CheckpointManager(tmp_path, async_write=False,
                                verify_memo_ttl_s=0.0)
        mgr.save(4, model=sd, blocking=True)
        mgr.restore_latest()
        calls = self._hash_counter(monkeypatch)
        mgr.restore_latest()            # TTL 0: always expired
        assert calls["n"] > 0
        mgr.close()

    def test_memo_invalidates_on_change(self, tmp_path, monkeypatch):
        sd = _mlp()
        X, Y = _data()
        sd.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=1,
               listeners=[_quiet()])
        mgr = CheckpointManager(tmp_path, async_write=False)
        mgr.save(4, model=sd, blocking=True)
        mgr.save(8, model=sd, blocking=True)
        mgr.restore_latest()
        calls = self._hash_counter(monkeypatch)
        ChaosMonkey(0).rot_checkpoint(tmp_path)        # newest = 8
        step, _ = mgr.restore_latest()
        assert step == 4                # re-hashed, caught, skipped
        assert calls["n"] > 0
        mgr.close()

    def test_scrubber_feeds_memo(self, tmp_path, monkeypatch):
        sd = _mlp()
        X, Y = _data()
        sd.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=1,
               listeners=[_quiet()])
        mgr = CheckpointManager(tmp_path, async_write=False)
        mgr.save(4, model=sd, blocking=True)
        Scrubber(mgr).scrub_once()
        calls = self._hash_counter(monkeypatch)
        mgr.restore_latest()            # scrub already verified it
        assert calls["n"] == 0
        mgr.close()


# ---------------------------------------------------------------------------
# observability plumbing

class TestIntegrityObservability:
    def test_fold_integrity_metrics(self):
        from deeplearning4j_tpu.monitor.registry import MetricsRegistry
        reg = MetricsRegistry()
        reg.fold_integrity({"type": "integrity", "event": "scrub",
                            "scanned": 3, "rotten": 1, "bytes": 1024,
                            "seconds": 0.5, "quarantined": [8]})
        reg.fold_integrity({"type": "integrity",
                            "event": "checkpoint_quarantined", "step": 8})
        reg.fold_integrity({"type": "integrity",
                            "event": "stall_forensics", "waited_s": 1.2})
        text = reg.to_prometheus_text()
        assert "integrity_scrub_cycles_total 1" in text
        assert "integrity_rotten_total 1" in text
        assert "integrity_quarantined_total 1" in text
        assert "integrity_stalls_total 1" in text
        assert "integrity_last_rotten_step 8" in text

    def test_report_renders_integrity_panel(self):
        from deeplearning4j_tpu.ui.report import render_report
        storage = StatsStorage()
        storage.put({"type": "faults", "event": "stall", "t": time.time(),
                     "boundary": "window_dispatch", "waited_s": 1.5,
                     "deadline_s": 0.5, "threads": 3})
        storage.put({"type": "integrity", "event": "scrub",
                     "t": time.time(), "scanned": 3, "rotten": 1,
                     "quarantined": [8], "bytes": 4096, "seconds": 0.1})
        storage.put({"type": "integrity",
                     "event": "checkpoint_quarantined", "t": time.time(),
                     "step": 8, "problems": ["arrays.npz: sha256 "
                                             "mismatch"],
                     "quarantined_to": "/x/step_00000008.rotten"})
        html = render_report(storage)
        assert "Integrity" in html and "window_dispatch" in html
        assert "checkpoint scrubber" in html
        assert "unrendered record types" not in html


# ---------------------------------------------------------------------------
# the composite chaos e2e (acceptance)

class TestIntegrityChaosE2E:
    @pytest.mark.chaos
    def test_survives_stall_bitflip_and_rotten_checkpoint(self, tmp_path):
        """ONE FaultTolerantFit run survives a stalled dispatch, a
        param bit-flip and a rotten NEWEST checkpoint — and finishes
        bit-identical (params and final loss) to an uninterrupted
        run."""
        X, Y = _data()

        clean = _mlp(fingerprints=False)
        h_clean = clean.fit(ArrayDataSetIterator(X, Y, batch_size=16),
                            epochs=4, listeners=[_quiet()])

        sd = _mlp(fingerprints=True)
        chaos = ChaosMonkey(7)
        storage = StatsStorage()
        mgr = CheckpointManager(tmp_path, keep_last_n=16,
                                async_write=False)
        # epoch-boundary checkpoints: a rollback target is always a
        # whole-epoch boundary, so every retry replays complete epochs
        # and the healed run is bit-identical to the uninterrupted one
        ftf = FaultTolerantFit(
            sd, mgr, policy=RetryPolicy(max_retries=2, backoff_base=0.0,
                                        quarantine_corrupt=False),
            checkpoint_every_n_epochs=1, stats_storage=storage,
            sleep=lambda s: None)
        it = ArrayDataSetIterator(X, Y, batch_size=16)   # 8 steps/epoch
        with _fast_watchdog(storage=storage):
            # epoch 0: clean (warms the watchdog's percentiles and
            # commits verified rollback targets)
            ftf.fit(it, epochs=1)
            # epoch 1: a wedged dispatch that eventually un-wedges
            with chaos.stalled_dispatch(delay_s=1.0, at_call=1):
                ftf.fit(it, epochs=1)
            # epoch 2: silent corruption of the dispatched params —
            # on the epoch's LAST window (at_call=2 of 2), the boundary
            # whose digest the epoch-end capture verifies; an earlier
            # flip trains through device-side and is the replay probe's
            # case, pinned in TestReplayProbeAndBitflip
            with chaos.bitflip_param(at_call=2, refingerprint=False):
                ftf.fit(it, epochs=1)
            # epoch 3: the newest committed checkpoint rots on disk;
            # a poisoned batch then forces a rollback that MUST skip it
            chaos.rot_checkpoint(tmp_path)
            poisoned = chaos.poison_batches(it, at_step=2)
            h = ftf.fit(poisoned, epochs=1)
        assert sd.training_config.epoch_count == 4
        assert ftf.rollbacks >= 3
        events = [r["event"] for r in storage.of_type("faults")]
        assert "stall" in events
        assert "recovered" in events
        causes = {r.get("cause") for r in storage.of_type("faults")
                  if r["event"] == "fault"}
        assert {"stall", "silent_corruption"} <= causes
        # bit-identical to the uninterrupted run
        assert h.final_loss() == h_clean.final_loss()
        for n, v in _params(clean).items():
            assert np.array_equal(v, _params(sd)[n]), n
        mgr.close()
