"""Environment flag catalog + memory observability tests (reference:
ND4JSystemProperties / Environment.h toggles; AllocationsTracker)."""
import os

import numpy as np
import pytest

from deeplearning4j_tpu import environment, memory
from deeplearning4j_tpu.environment import PROPERTIES, Environment
from deeplearning4j_tpu.memory import (
    AllocationsTracker, MemoryWatermark, device_memory_report, snapshot)


@pytest.fixture(autouse=True)
def _clean_env():
    env = environment()
    env.reset()
    saved = {s.key: os.environ.get(s.key) for s in PROPERTIES.values()}
    yield
    env.reset()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def test_catalog_defaults_and_describe():
    env = environment()
    assert env.is_verbose() is False
    assert env.default_dtype() == "float32"
    d = env.describe()
    for name in PROPERTIES:
        assert name in d
    assert env.platform() in ("cpu", "tpu", "axon", "gpu")
    assert env.device_count() >= 1


def test_env_var_resolution_and_override_precedence():
    os.environ["DL4J_TPU_VERBOSE"] = "true"
    env = environment()
    assert env.is_verbose() is True
    env.set("verbose", False)            # programmatic beats env var
    assert env.is_verbose() is False
    env.reset("verbose")
    assert env.is_verbose() is True


def test_unknown_property_rejected():
    with pytest.raises(KeyError):
        environment().get("bogus")
    with pytest.raises(KeyError):
        environment().set("bogus", 1)


def test_singleton_identity():
    assert environment() is Environment.get_instance()


def test_debug_flag_defaults_nan_panic():
    from deeplearning4j_tpu.autodiff import TrainingConfig
    from deeplearning4j_tpu.learning.updaters import Sgd
    assert TrainingConfig(updater=Sgd(0.1)).nan_panic is False
    environment().set("debug", True)
    try:
        assert TrainingConfig(updater=Sgd(0.1)).nan_panic is True
    finally:
        environment().reset("debug")


def test_memory_snapshot_and_report():
    import jax.numpy as jnp
    keep = jnp.ones((256, 256), jnp.float32) + 0     # live device buffer
    states = snapshot()
    assert states and all(s.bytes_in_use >= 0 for s in states)
    rpt = device_memory_report()
    assert "MiB in use" in rpt
    assert memory.live_array_count() > 0
    del keep


def test_memory_watermark_context():
    import jax.numpy as jnp
    with MemoryWatermark() as wm:
        x = jnp.zeros((512, 512), jnp.float32) + 1.0
        x.block_until_ready()
    assert wm.peak_bytes >= 0
    assert "watermark" in wm.report()


def test_allocations_tracker_accounting():
    t = AllocationsTracker.get_instance()
    t.reset()
    t.allocate("workspace", 1024)
    t.allocate("workspace", 1024)
    t.release("workspace", 512)
    assert t.bytes_tracked("workspace") == 1536
    assert t.totals() == {"workspace": 1536}
    t.reset()
    assert t.totals() == {}


def test_verbose_compile_logging(capsys):
    from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig
    from deeplearning4j_tpu.learning.updaters import Sgd
    environment().set("verbose", True)
    try:
        sd = SameDiff()
        x = sd.placeholder("x", shape=(None, 4))
        w = sd.var("w", value=np.ones((4, 2)))
        y = x.mmul(w, name="y")
        loss = y.square().mean(name="loss")
        loss.mark_as_loss()
        sd.training_config = TrainingConfig(
            updater=Sgd(0.01), data_set_feature_mapping=["x"],
            data_set_label_mapping=[])
        sd.make_train_step()
        out = capsys.readouterr().out
        assert "compiling train step" in out
    finally:
        environment().reset("verbose")
