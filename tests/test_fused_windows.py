"""Fused multi-step training windows (autodiff/window.py).

Covers the windowed-tier contract: dispatch count drops from ``steps``
to ``ceil(steps/K)`` (counted via a counting wrapper around the
compiled window fn), numerics match the per-step tier (to float
rounding — buffer donation changes the per-step program's codegen, see
docs/training_performance.md), same-tier runs and checkpoint resumes
are BIT-exact including dropout, ragged tails run through bounded
power-of-two buckets, gradient accumulation matches the equivalent
large batch, and the stager/async-iterator threads cannot leak.
"""
import math
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.autodiff import (SameDiff, ScoreIterationListener,
                                         TrainingConfig)
from deeplearning4j_tpu.autodiff.window import WindowStager, pow2_buckets
from deeplearning4j_tpu.learning.updaters import Adam, Sgd


def _mlp(seed=42, dropout=None, updater=None):
    sd = SameDiff()
    rng = np.random.default_rng(seed)
    x = sd.placeholder("x", shape=(-1, 2), dtype="float32")
    labels = sd.placeholder("labels", shape=(-1, 2), dtype="float32")
    w0 = sd.var("w0", value=rng.normal(0, 0.5, (2, 16)).astype(np.float32))
    b0 = sd.var("b0", shape=(16,))
    w1 = sd.var("w1", value=rng.normal(0, 0.5, (16, 2)).astype(np.float32))
    b1 = sd.var("b1", shape=(2,))
    h = (x.mmul(w0) + b0).tanh()
    if dropout is not None:
        h = sd.random.dropout(h, p=dropout)
    logits = h.mmul(w1) + b1
    loss = sd.loss.softmax_cross_entropy(logits, labels, name="loss")
    loss.mark_as_loss()
    sd.training_config = (
        TrainingConfig.builder()
        .updater(updater or Adam(learning_rate=0.05))
        .data_set_feature_mapping("x").data_set_label_mapping("labels")
        .build())
    return sd


def _xor(n_rows=192):
    X = np.tile(np.array([[0, 0], [0, 1], [1, 0], [1, 1]], np.float32),
                (n_rows // 4, 1))
    Y = np.eye(2, dtype=np.float32)[
        X[:, 0].astype(int) ^ X[:, 1].astype(int)]
    return X, Y


class _StreamIt:
    """Host-streaming iterator (no stacked_batches) — the production ETL
    shape the windowed tier must handle."""

    def __init__(self, X, Y, batch):
        self.X, self.Y, self.batch = X, Y, batch

    def reset(self):
        pass

    def __iter__(self):
        for i in range(0, len(self.X), self.batch):
            yield self.X[i:i + self.batch], self.Y[i:i + self.batch]


def _quiet_listener(every=10 ** 9):
    return ScoreIterationListener(print_every=every, print_fn=lambda *a: None)


def _params(sd):
    return {n: np.asarray(a) for n, a in sd.trainable_params().items()}


def _count_dispatches(sd):
    """Counting wrapper around the compiled window fn: every invocation
    of the wrapped callable = one compiled-step dispatch."""
    counts = []
    orig = sd.make_train_window

    def counting(*a, **k):
        fn = orig(*a, **k)

        def wrapped(*fa, **fk):
            # window length = leading dim of any stacked placeholder
            stacked = fa[-2]
            counts.append(next(iter(stacked.values())).shape[0])
            return fn(*fa, **fk)

        return wrapped

    sd.make_train_window = counting
    return counts


# ---------------------------------------------------------------------------
# bucketing

def test_pow2_buckets():
    assert pow2_buckets(0) == []
    assert pow2_buckets(1) == [1]
    assert pow2_buckets(4) == [4]
    assert pow2_buckets(13) == [8, 4, 1]
    for r in range(1, 64):
        bs = pow2_buckets(r)
        assert sum(bs) == r
        assert all(b & (b - 1) == 0 for b in bs)       # powers of two
        assert bs == sorted(bs, reverse=True)


# ---------------------------------------------------------------------------
# dispatch-count regression (THE windowed-tier contract)

def test_windowed_dispatch_count_and_params_match_per_step():
    """K=8 over 16 steps/epoch → exactly ceil(16/8)=2 dispatches per
    epoch, and final params match the per-step tier."""
    X, Y = _xor(256)                        # 16 batches of 16
    sd_ref = _mlp()
    sd_ref.fit(_StreamIt(X, Y, 16), epochs=2,
               listeners=[_quiet_listener()])
    assert sd_ref.last_fit_stats["tier"] == "per_step"
    assert sd_ref.last_fit_stats["dispatches_per_epoch"] == 16

    sd_win = _mlp()
    sd_win.training_config.fused_steps = 8
    counts = _count_dispatches(sd_win)
    sd_win.fit(_StreamIt(X, Y, 16), epochs=2, listeners=[_quiet_listener()])
    assert counts == [8, 8, 8, 8]           # ceil(16/8)=2 per epoch
    st = sd_win.last_fit_stats
    assert st["tier"] == "windowed"
    assert st["dispatches_per_epoch"] == math.ceil(16 / 8)
    assert st["steps_per_epoch"] == 16
    # same math, independently compiled programs (donation changes the
    # per-step tier's codegen): equal to float rounding
    p_ref, p_win = _params(sd_ref), _params(sd_win)
    for n in p_ref:
        np.testing.assert_allclose(p_win[n], p_ref[n], rtol=1e-5,
                                   atol=1e-6, err_msg=n)
    assert sd_win.training_config.iteration_count == \
        sd_ref.training_config.iteration_count == 32


def test_windowed_ragged_tail_pow2_buckets():
    """13 steps, K=8 → windows [8, 4, 1]: the tail stays fused through
    bounded pow2 buckets instead of falling back to per-step."""
    X, Y = _xor(13 * 16)
    sd_ref = _mlp()
    sd_ref.fit(_StreamIt(X, Y, 16), epochs=1, listeners=[_quiet_listener()])
    sd_win = _mlp()
    sd_win.training_config.fused_steps = 8
    counts = _count_dispatches(sd_win)
    sd_win.fit(_StreamIt(X, Y, 16), epochs=1, listeners=[_quiet_listener()])
    assert counts == [8, 4, 1]
    assert sd_win.last_fit_stats["window_sizes"] == {8: 1, 4: 1, 1: 1}
    p_ref, p_win = _params(sd_ref), _params(sd_win)
    for n in p_ref:
        np.testing.assert_allclose(p_win[n], p_ref[n], rtol=1e-5,
                                   atol=1e-6, err_msg=n)


def test_windowed_ragged_final_batch():
    """An iterator whose LAST batch has fewer rows (170 rows, batch 32 →
    32×5 + 10) must not crash the stacker: the odd-shaped batch forms
    its own window, exactly the extra compiled shape the per-step tier
    pays for it. Review regression: np.stack of mixed shapes raised."""
    rng = np.random.default_rng(5)
    X = rng.normal(size=(170, 2)).astype(np.float32)
    Y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 170)]
    sd_ref = _mlp()
    sd_ref.fit(_StreamIt(X, Y, 32), epochs=1, listeners=[_quiet_listener()])
    sd_win = _mlp()
    sd_win.training_config.fused_steps = 4
    counts = _count_dispatches(sd_win)
    sd_win.fit(_StreamIt(X, Y, 32), epochs=1, listeners=[_quiet_listener()])
    # b0-b3 → one full window; b4 (32 rows) flushed alone when the
    # 10-row b5 arrives; b5 → its own window
    assert counts == [4, 1, 1]
    p_ref, p_win = _params(sd_ref), _params(sd_win)
    for n in p_ref:
        np.testing.assert_allclose(p_win[n], p_ref[n], rtol=1e-5,
                                   atol=1e-6, err_msg=n)


def test_windowed_device_cached_windows_built_once():
    """A stacked_batches source (DeviceCachedIterator) reuses one
    device-resident window list across epochs — no stager thread, no
    per-epoch re-stack — and matches the streaming windowed run."""
    from deeplearning4j_tpu.dataset import DeviceCachedIterator
    X, Y = _xor(192)
    sd_dev = _mlp()
    sd_dev.training_config.fused_steps = 8
    n_before = threading.active_count()
    counts = _count_dispatches(sd_dev)
    sd_dev.fit(DeviceCachedIterator(X, Y, 16), epochs=3,
               listeners=[_quiet_listener()])
    assert threading.active_count() == n_before      # no stager spawned
    assert counts == [8, 4] * 3
    sd_str = _mlp()
    sd_str.training_config.fused_steps = 8
    sd_str.fit(_StreamIt(X, Y, 16), epochs=3, listeners=[_quiet_listener()])
    p_dev, p_str = _params(sd_dev), _params(sd_str)
    for n in p_dev:
        np.testing.assert_allclose(p_dev[n], p_str[n], rtol=1e-5,
                                   atol=1e-6, err_msg=n)


def test_windowed_bit_identical_rerun_with_dropout():
    """Same tier + same seed → BIT-identical params, dropout included
    (per-step RNG keys fold the absolute iteration)."""
    X, Y = _xor(192)
    results = []
    for _ in range(2):
        sd = _mlp(dropout=0.8)
        sd.training_config.fused_steps = 8
        sd.fit(_StreamIt(X, Y, 16), epochs=2, listeners=[_quiet_listener()])
        results.append(_params(sd))
    for n in results[0]:
        np.testing.assert_array_equal(results[0][n], results[1][n],
                                      err_msg=n)


def test_windowed_matches_per_step_with_dropout():
    """Dropout key schedule is iteration-folded, so the windowed tier
    consumes the exact key sequence of the per-step tier."""
    X, Y = _xor(192)
    sd_ref = _mlp(dropout=0.8)
    sd_ref.fit(_StreamIt(X, Y, 16), epochs=2, listeners=[_quiet_listener()])
    sd_win = _mlp(dropout=0.8)
    sd_win.training_config.fused_steps = 8
    sd_win.fit(_StreamIt(X, Y, 16), epochs=2, listeners=[_quiet_listener()])
    p_ref, p_win = _params(sd_ref), _params(sd_win)
    for n in p_ref:
        np.testing.assert_allclose(p_win[n], p_ref[n], rtol=1e-5,
                                   atol=1e-6, err_msg=n)


def test_windowed_no_listeners_streaming():
    """fused_steps>1 + streaming iterator + zero listeners: windowed
    tier (not per-step), deferred loss fetch, learning happens."""
    X, Y = _xor(192)
    sd = _mlp()
    sd.training_config.fused_steps = 4
    h = sd.fit(_StreamIt(X, Y, 16), epochs=30)
    assert sd.last_fit_stats["tier"] == "windowed"
    assert sd.last_fit_stats["dispatches_per_epoch"] == 3
    assert h.loss_curve.losses[-1] < h.loss_curve.losses[0]


def test_windowed_accepts_sdvariable_keyed_dict_batches():
    """Per-step-tier parity: dict batches may be keyed by SDVariable
    objects, not just names (review regression: the stager's shape
    signature sort raised TypeError on unorderable keys)."""
    X, Y = _xor(64)
    sd = _mlp()
    sd.training_config.fused_steps = 2
    xv, lv = sd.get_variable("x"), sd.get_variable("labels")

    class VarKeyIt:
        def reset(self):
            pass

        def __iter__(self):
            for i in range(0, 64, 16):
                yield {xv: X[i:i + 16], lv: Y[i:i + 16]}

    h = sd.fit(VarKeyIt(), epochs=2, listeners=[_quiet_listener()])
    assert np.isfinite(h.final_loss())
    assert sd.last_fit_stats["dispatches_per_epoch"] == 2


def test_scanned_tier_still_preferred_without_listeners():
    from deeplearning4j_tpu.dataset import DeviceCachedIterator
    X, Y = _xor(192)
    sd = _mlp()
    sd.fit(DeviceCachedIterator(X, Y, 16), epochs=1)
    assert sd.last_fit_stats["tier"] == "scanned_epoch"
    assert sd.last_fit_stats["dispatches_per_epoch"] == 1


# ---------------------------------------------------------------------------
# mid-epoch checkpoint flush + bit-exact resume

def test_checkpoint_cadence_first_boundary_after_each_multiple(tmp_path):
    """every_n_iterations=10 with K=8 windows saves at the FIRST window
    boundary at-or-after each multiple of 10 (docs/checkpointing.md) —
    not only when a full 10 steps have buffered (review regression:
    sum-based flushing drifted the cadence to 16)."""
    from deeplearning4j_tpu.checkpoint import (CheckpointListener,
                                               CheckpointManager)
    X, Y = _xor(48 * 16)               # 48 batches of 16 per epoch
    sd = _mlp()
    sd.training_config.fused_steps = 8
    mgr = CheckpointManager(tmp_path, keep_last_n=None, async_write=False)
    lst = CheckpointListener(mgr, every_n_iterations=10)
    sd.fit(_StreamIt(X, Y, 16), epochs=1, listeners=[lst])
    # boundaries 8,16,24,32,40,48; multiples 10,20,30,40 → 16,24,32,40
    # (48 is the epoch-end flush: no multiple crossed since 40)
    assert mgr.all_steps() == [16, 24, 32, 40]


def test_windowed_mid_epoch_checkpoint_resumes_bit_exact(tmp_path):
    """A CheckpointListener firing MID-epoch under the windowed tier
    snapshots at a window boundary; resuming replays the identical
    window partition and matches the uninterrupted run bit-for-bit."""
    from deeplearning4j_tpu.checkpoint import (CheckpointListener,
                                               CheckpointManager,
                                               restore_training_state)
    X, Y = _xor(64)                     # 4 batches of 16 per epoch
    # --- uninterrupted windowed run (2 epochs, K=2 → windows [2,2]) --
    sd_a = _mlp()
    sd_a.training_config.fused_steps = 2
    sd_a.fit(_StreamIt(X, Y, 16), epochs=2, listeners=[_quiet_listener()])
    # --- run with a mid-epoch iteration-cadence checkpoint ----------
    sd_b = _mlp()
    sd_b.training_config.fused_steps = 2
    mgr = CheckpointManager(tmp_path, keep_last_n=None, async_write=False)
    lst = CheckpointListener(mgr, every_n_iterations=2)
    sd_b.fit(_StreamIt(X, Y, 16), epochs=2, listeners=[lst])
    steps = mgr.all_steps()
    assert 2 in steps                   # fired after the first window
    state = mgr.restore(2)
    assert state.iteration == 2         # a window boundary
    # --- fresh process resumes from the mid-epoch snapshot ----------
    sd_c = _mlp()
    sd_c.training_config.fused_steps = 2
    restore_training_state(sd_c, state)
    # finish the interrupted epoch: batches 2..3 = one window of 2
    sd_c.fit(_StreamIt(X[32:], Y[32:], 16), epochs=1,
             listeners=[_quiet_listener()])
    # the uninterrupted run keeps ONE base key across both epochs; a
    # resumed process replays it by re-pinning the restored seed
    sd_c._seed = state.rng_seed
    sd_c.fit(_StreamIt(X, Y, 16), epochs=1, listeners=[_quiet_listener()])
    p_a, p_c = _params(sd_a), _params(sd_c)
    for n in p_a:
        np.testing.assert_array_equal(p_a[n], p_c[n], err_msg=n)
    la = jax.tree_util.tree_leaves(sd_a._updater_state)
    lc = jax.tree_util.tree_leaves(sd_c._updater_state)
    assert len(la) == len(lc) > 0
    for a, c in zip(la, lc):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c))


# ---------------------------------------------------------------------------
# gradient accumulation

def test_accum_steps_match_large_batch():
    """accum_steps=2 at batch 16 == one update per 32-row batch (mean
    loss ⇒ averaged micro-grads ≡ full-batch grad) with plain SGD."""
    X, Y = _xor(192)
    sd_big = _mlp(updater=Sgd(learning_rate=0.2))
    sd_big.fit(_StreamIt(X, Y, 32), epochs=3, listeners=[_quiet_listener()])
    sd_acc = _mlp(updater=Sgd(learning_rate=0.2))
    sd_acc.training_config.fused_steps = 4
    sd_acc.training_config.accum_steps = 2
    sd_acc.fit(_StreamIt(X, Y, 16), epochs=3, listeners=[_quiet_listener()])
    p_big, p_acc = _params(sd_big), _params(sd_acc)
    for n in p_big:
        np.testing.assert_allclose(p_acc[n], p_big[n], rtol=1e-5,
                                   atol=1e-6, err_msg=n)


def test_accum_cycle_spans_window_boundary():
    """The accumulator carries BETWEEN window dispatches: K=3 with
    accum_steps=2 (cycle straddles the window edge) must equal one
    K=6 window of the same 6 micro-batches."""
    rng = np.random.default_rng(3)
    X = rng.normal(size=(96, 2)).astype(np.float32)
    Y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 96)]
    outs = []
    for k in (3, 6):
        sd = _mlp(updater=Sgd(learning_rate=0.2))
        sd.training_config.fused_steps = k
        sd.training_config.accum_steps = 2
        sd.fit(_StreamIt(X, Y, 16), epochs=2, listeners=[_quiet_listener()])
        outs.append(_params(sd))
    for n in outs[0]:
        np.testing.assert_allclose(outs[0][n], outs[1][n], rtol=1e-5,
                                   atol=1e-6, err_msg=n)


def test_accum_updater_steps_once_per_cycle():
    """With lr=0-equivalent NoOp-style freeze: accum must not change
    params between updates — probe that exactly floor(steps/accum)
    updates happen by comparing against per-update SGD math."""
    X, Y = _xor(64)                     # 4 micro-batches of 16
    sd = _mlp(updater=Sgd(learning_rate=0.5))
    sd.training_config.fused_steps = 4
    sd.training_config.accum_steps = 4
    sd.fit(_StreamIt(X, Y, 16), epochs=1, listeners=[_quiet_listener()])
    after = _params(sd)
    # one update from the mean grad over all 64 rows == full-batch SGD
    sd_ref = _mlp(updater=Sgd(learning_rate=0.5))
    sd_ref.fit(_StreamIt(X, Y, 64), epochs=1, listeners=[_quiet_listener()])
    p_ref = _params(sd_ref)
    for n in p_ref:
        np.testing.assert_allclose(after[n], p_ref[n], rtol=1e-5,
                                   atol=1e-6, err_msg=n)


def test_accum_carry_persists_across_fits():
    """A fit ending mid-accumulation-cycle must not drop its partial
    grads: two sequential 1-epoch fits (6 steps each, accum_steps=4 →
    each fit ends mid-cycle) equal one 2-epoch fit."""
    rng = np.random.default_rng(9)
    X = rng.normal(size=(96, 2)).astype(np.float32)   # 6 batches of 16
    Y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 96)]
    sd_one = _mlp(updater=Sgd(learning_rate=0.2))
    sd_one.training_config.fused_steps = 8
    sd_one.training_config.accum_steps = 4
    sd_one.fit(_StreamIt(X, Y, 16), epochs=2, listeners=[_quiet_listener()])
    sd_two = _mlp(updater=Sgd(learning_rate=0.2))
    sd_two.training_config.fused_steps = 8
    sd_two.training_config.accum_steps = 4
    for _ in range(2):
        sd_two.fit(_StreamIt(X, Y, 16), epochs=1,
                   listeners=[_quiet_listener()])
    p_one, p_two = _params(sd_one), _params(sd_two)
    for n in p_one:
        np.testing.assert_allclose(p_two[n], p_one[n], rtol=1e-5,
                                   atol=1e-6, err_msg=n)


# ---------------------------------------------------------------------------
# listener delivery + stats + networks

def test_windowed_listener_burst_delivery():
    """Every iteration's scalar arrives exactly once, in order, in
    window-boundary bursts."""
    X, Y = _xor(192)                    # 12 steps/epoch
    seen = []

    class Recorder(ScoreIterationListener):
        frequency = 5

        def __init__(self):
            super().__init__(print_every=10 ** 9, print_fn=lambda *a: None)
            self.frequency = 5

        def iterations_done(self, sd, epoch, iterations, losses):
            seen.append(list(iterations))
            assert len(iterations) == len(losses)
            assert all(np.isfinite(l) for l in losses)

    sd = _mlp()
    sd.training_config.fused_steps = 4
    sd.fit(_StreamIt(X, Y, 16), epochs=1, listeners=[Recorder()])
    flat = [i for burst in seen for i in burst]
    assert flat == list(range(12))
    # flush at the first window boundary at-or-after frequency=5 → 8
    assert seen[0] == list(range(8))


def test_windowed_stats_listener_dispatch_record():
    from deeplearning4j_tpu.ui.stats import StatsListener, StatsStorage
    X, Y = _xor(192)
    sd = _mlp()
    sd.training_config.fused_steps = 8
    storage = StatsStorage()
    sd.fit(_StreamIt(X, Y, 16), epochs=2, listeners=[StatsListener(storage)])
    recs = storage.of_type("dispatch")
    assert len(recs) == 2
    assert recs[0]["tier"] == "windowed"
    assert recs[0]["dispatches_per_epoch"] == 2     # ceil(12/8) = [8,4]
    assert recs[0]["fused_steps"] == 8


def test_multilayer_network_fused_steps_kwarg():
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration, OutputLayer)
    X, Y = _xor(192)
    net = MultiLayerNetwork(
        (NeuralNetConfiguration.builder().seed(0)
         .updater(Adam(learning_rate=0.05)).list()
         .layer(DenseLayer(n_out=16, activation="tanh"))
         .layer(OutputLayer(n_out=2))
         .set_input_type(InputType.feed_forward(2)).build())).init()
    h = net.fit(X, Y, epochs=20, batch_size=16,
                listeners=[_quiet_listener()], fused_steps=4)
    assert net.samediff.last_fit_stats["tier"] == "windowed"
    assert net.samediff.last_fit_stats["dispatches_per_epoch"] == 3
    assert h.loss_curve.losses[-1] < h.loss_curve.losses[0]


def test_parallel_trainer_windowed_fit():
    """Windows stack under the mesh shardings (window_sharding hook)."""
    from deeplearning4j_tpu.parallel import DeviceMesh, ParallelTrainer
    from deeplearning4j_tpu.parallel.sharding import data_parallel
    X, Y = _xor(192)
    sd = _mlp()
    sd.training_config.fused_steps = 4
    tr = ParallelTrainer(sd, strategy=data_parallel(DeviceMesh.create()))
    h = tr.fit(_StreamIt(X, Y, 16), epochs=2, listeners=[_quiet_listener()])
    assert sd.last_fit_stats["tier"] == "windowed"
    assert np.isfinite(h.final_loss())


def test_training_config_serde_roundtrip_fused_knobs():
    tc = (TrainingConfig.builder().updater(Adam(learning_rate=1e-3))
          .fused_steps(8).accum_steps(4).build())
    tc2 = TrainingConfig.from_json(tc.to_json())
    assert tc2.fused_steps == 8 and tc2.accum_steps == 4
    # defaults survive old-format JSON (no keys)
    d = tc.to_json()
    del d["fused_steps"], d["accum_steps"]
    tc3 = TrainingConfig.from_json(d)
    assert tc3.fused_steps == 1 and tc3.accum_steps == 1


# ---------------------------------------------------------------------------
# thread hygiene: stager + AsyncDataSetIterator

def test_window_stager_abandoned_consumer_no_leak():
    n_before = threading.active_count()
    stager = WindowStager(iter({"x": np.zeros((4, 2), np.float32)}
                               for _ in range(10000)), window=4, depth=2)
    it = iter(stager)
    next(it)
    it.close()                          # GeneratorExit → finally → close()
    assert not stager._thread.is_alive()
    assert threading.active_count() <= n_before + 1


def test_window_stager_propagates_source_error():
    def bad_source():
        yield {"x": np.zeros((4, 2), np.float32)}
        raise RuntimeError("etl failure")

    stager = WindowStager(bad_source(), window=1)
    with pytest.raises(RuntimeError, match="etl failure"):
        list(stager)


def test_async_iterator_abandoned_consumer_no_leak():
    from deeplearning4j_tpu.dataset.iterators import (ArrayDataSetIterator,
                                                      AsyncDataSetIterator)
    X = np.zeros((4096, 2), np.float32)
    wrapped = ArrayDataSetIterator(X, X, batch_size=1)   # 4096 batches
    ait = AsyncDataSetIterator(wrapped, queue_size=2)
    gen = iter(ait)
    next(gen)
    gen.close()             # abandon mid-epoch (the leak regression)
    t = ait._last_thread
    t.join(timeout=5)
    assert not t.is_alive()


def test_async_iterator_full_pass_and_error_propagation():
    from deeplearning4j_tpu.dataset.iterators import (ArrayDataSetIterator,
                                                      AsyncDataSetIterator)
    X = np.arange(64, dtype=np.float32).reshape(32, 2)
    ait = AsyncDataSetIterator(ArrayDataSetIterator(X, X, batch_size=8),
                               queue_size=2)
    got = list(ait)
    assert len(got) == 4
    np.testing.assert_array_equal(got[0][0], X[:8])

    class Bad:
        def __iter__(self):
            yield X[:8], X[:8]
            raise ValueError("reader died")

    # worker failure arrives as a poisoned sentinel: structured error
    # carrying the failing batch index, the original chained as __cause__
    from deeplearning4j_tpu.faults import DataPipelineError
    with pytest.raises(DataPipelineError, match="reader died") as ei:
        list(AsyncDataSetIterator(Bad(), queue_size=2))
    assert ei.value.batch_index == 1
    assert isinstance(ei.value.__cause__, ValueError)


def test_windowed_fit_through_async_iterator():
    """The windowed tier consumes a prefetching iterator end-to-end."""
    from deeplearning4j_tpu.dataset.iterators import (ArrayDataSetIterator,
                                                      AsyncDataSetIterator)
    X, Y = _xor(192)
    sd = _mlp()
    sd.training_config.fused_steps = 4
    ait = AsyncDataSetIterator(ArrayDataSetIterator(X, Y, batch_size=16),
                               queue_size=2)
    h = sd.fit(ait, epochs=2, listeners=[_quiet_listener()])
    assert np.isfinite(h.final_loss())
    assert sd.last_fit_stats["dispatches_per_epoch"] == 3


# ---------------------------------------------------------------------------
# BenchmarkDataSetIterator device-cached mode

def test_benchmark_iterator_device_cached_and_stacked():
    from deeplearning4j_tpu.dataset.iterators import BenchmarkDataSetIterator
    it = BenchmarkDataSetIterator((8, 4), 3, 5, device_cached=True)
    batches = list(it)
    assert len(batches) == 5
    assert isinstance(batches[0][0], jax.Array)
    # the SAME resident array every step — no per-step re-upload
    assert batches[0][0] is batches[1][0]
    fs, ls = it.stacked_batches()
    assert fs[0].shape == (5, 8, 4) and ls[0].shape == (5, 8, 3)
    # host mode keeps the legacy contract and no scanned-tier hook
    it2 = BenchmarkDataSetIterator((8, 4), 3, 5)
    assert not hasattr(it2, "stacked_batches")
    assert isinstance(next(iter(it2))[0], np.ndarray)


def test_benchmark_iterator_drives_scanned_tier():
    from deeplearning4j_tpu.dataset.iterators import BenchmarkDataSetIterator
    sd = _mlp()
    it = BenchmarkDataSetIterator((16, 2), 2, 6, device_cached=True)
    sd.fit(it, epochs=1)
    assert sd.last_fit_stats["tier"] == "scanned_epoch"
    assert sd.training_config.iteration_count == 6
