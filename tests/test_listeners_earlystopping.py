"""Listener breadth + early stopping suite tests (reference:
deeplearning4j-core TestEarlyStopping + listener tests)."""
import numpy as np
import pytest

from deeplearning4j_tpu.autodiff import (
    BestScoreEpochTerminationCondition, ClassificationScoreCalculator,
    DataSetLossCalculator, EarlyStoppingConfiguration, EarlyStoppingResult,
    EarlyStoppingTrainer, EvaluativeListener, InMemoryModelSaver,
    InvalidScoreTerminationCondition, LocalFileModelSaver,
    MaxEpochsTerminationCondition, MaxScoreTerminationCondition,
    MaxTimeTerminationCondition, ScoreImprovementEpochTerminationCondition,
    SleepyListener, TimeIterationListener)
from deeplearning4j_tpu.dataset import ArrayDataSetIterator
from deeplearning4j_tpu.learning.updaters import Adam, Sgd
from deeplearning4j_tpu.nn import (
    DenseLayer, InputType, MultiLayerNetwork, NeuralNetConfiguration,
    OutputLayer)


def _toy_net(lr=0.1, seed=0):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(lr))
            .list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, loss_function="MCXENT"))
            .set_input_type(InputType.feed_forward(8))
            .build())
    return MultiLayerNetwork(conf).init()


def _toy_data(n=96, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 8)).astype(np.float32)
    y = (X[:, 0] > 0).astype(int) + (X[:, 1] > 0).astype(int)
    Y = np.eye(3, dtype=np.float32)[y]
    return X, Y


# ---- listeners ------------------------------------------------------------

def test_evaluative_listener_epoch_end():
    net = _toy_net()
    X, Y = _toy_data()
    holdout = ArrayDataSetIterator(X[:32], Y[:32], batch_size=16,
                                   shuffle=False)
    lst = EvaluativeListener(net, holdout, frequency=2)
    net.fit(X, Y, epochs=4, batch_size=32, listeners=[lst])
    assert len(lst.results) == 2            # epochs 1 and 3
    assert lst.last_evaluation is not None
    assert 0.0 <= lst.last_evaluation.accuracy() <= 1.0


def test_time_iteration_listener_reports_eta():
    msgs = []
    net = _toy_net()
    X, Y = _toy_data()
    total = 3 * 3                            # 3 epochs x 3 batches
    lst = TimeIterationListener(total_iterations=total, frequency=2,
                                print_fn=msgs.append)
    net.fit(X, Y, epochs=3, batch_size=32, listeners=[lst])
    assert msgs and "remaining" in msgs[0]
    assert np.isfinite(lst.remaining_seconds)


def test_sleepy_listener_sleeps():
    net = _toy_net()
    X, Y = _toy_data(n=32)
    lst = SleepyListener(on_iteration_ms=1.0, on_epoch_end_ms=1.0)
    net.fit(X, Y, epochs=2, batch_size=32, listeners=[lst])
    assert lst.sleep_count == 4              # 2 iterations + 2 epoch ends


# ---- early stopping -------------------------------------------------------

def test_early_stopping_max_epochs():
    net = _toy_net()
    X, Y = _toy_data()
    it = ArrayDataSetIterator(X, Y, batch_size=32)
    cfg = (EarlyStoppingConfiguration.builder()
           .epoch_termination_conditions(MaxEpochsTerminationCondition(3))
           .build())
    res = EarlyStoppingTrainer(cfg, net, it).fit(max_epochs=50)
    assert res.total_epochs == 3
    assert res.termination_reason == EarlyStoppingResult.EPOCH_TERMINATION
    assert "MaxEpochs" in res.termination_details
    assert res.best_model is net             # in-memory restore


def test_early_stopping_score_improvement_patience():
    # lr=0 -> loss never improves after epoch 0 -> patience fires
    net = _toy_net(lr=0.0)
    X, Y = _toy_data()
    it = ArrayDataSetIterator(X, Y, batch_size=32, shuffle=False)
    cfg = (EarlyStoppingConfiguration.builder()
           .epoch_termination_conditions(
               ScoreImprovementEpochTerminationCondition(2))
           .build())
    res = EarlyStoppingTrainer(cfg, net, it).fit(max_epochs=50)
    assert res.total_epochs <= 5
    assert "ScoreImprovement" in res.termination_details
    assert res.best_model_epoch == 0


def test_early_stopping_invalid_score_aborts():
    net = _toy_net(lr=1e6)                   # diverges to NaN quickly
    X, Y = _toy_data()
    it = ArrayDataSetIterator(X, Y, batch_size=32)
    cfg = (EarlyStoppingConfiguration.builder()
           .iteration_termination_conditions(
               InvalidScoreTerminationCondition(),
               MaxScoreTerminationCondition(1e4))
           .epoch_termination_conditions(MaxEpochsTerminationCondition(30))
           .build())
    res = EarlyStoppingTrainer(cfg, net, it).fit(max_epochs=30)
    assert res.termination_reason == \
        EarlyStoppingResult.ITERATION_TERMINATION


def test_early_stopping_max_time():
    net = _toy_net()
    X, Y = _toy_data()
    it = ArrayDataSetIterator(X, Y, batch_size=32)
    cfg = (EarlyStoppingConfiguration.builder()
           .iteration_termination_conditions(
               MaxTimeTerminationCondition(0.0))
           .epoch_termination_conditions(MaxEpochsTerminationCondition(50))
           .build())
    res = EarlyStoppingTrainer(cfg, net, it).fit(max_epochs=50)
    assert res.total_epochs == 1
    assert "MaxTime" in res.termination_details


def test_early_stopping_holdout_calculator_and_best_restore():
    net = _toy_net(lr=0.2)
    X, Y = _toy_data(n=128)
    train = ArrayDataSetIterator(X[:96], Y[:96], batch_size=32)
    hold = ArrayDataSetIterator(X[96:], Y[96:], batch_size=32,
                                shuffle=False)
    saver = InMemoryModelSaver()
    cfg = (EarlyStoppingConfiguration.builder()
           .epoch_termination_conditions(MaxEpochsTerminationCondition(6))
           .score_calculator(DataSetLossCalculator(hold))
           .model_saver(saver).build())
    res = EarlyStoppingTrainer(cfg, net, train).fit(max_epochs=6)
    assert saver.best_params is not None
    assert res.best_model_score == min(res.score_vs_epoch.values())
    # restored best params: holdout score of the restored model equals
    # the recorded best (restore actually happened)
    again = DataSetLossCalculator(hold).calculate_score(res.best_model)
    assert again == pytest.approx(res.best_model_score, rel=1e-4)


def test_early_stopping_classification_calculator():
    net = _toy_net(lr=0.2)
    X, Y = _toy_data(n=128)
    train = ArrayDataSetIterator(X[:96], Y[:96], batch_size=32)
    hold = ArrayDataSetIterator(X[96:], Y[96:], batch_size=32,
                                shuffle=False)
    cfg = (EarlyStoppingConfiguration.builder()
           .epoch_termination_conditions(
               MaxEpochsTerminationCondition(4),
               BestScoreEpochTerminationCondition(0.0))
           .score_calculator(ClassificationScoreCalculator(hold))
           .build())
    res = EarlyStoppingTrainer(cfg, net, train).fit(max_epochs=4)
    assert 0.0 <= res.best_model_score <= 1.0


def test_local_file_model_saver(tmp_path):
    net = _toy_net(lr=0.2)
    X, Y = _toy_data()
    it = ArrayDataSetIterator(X, Y, batch_size=32)
    saver = LocalFileModelSaver(str(tmp_path))
    cfg = (EarlyStoppingConfiguration.builder()
           .epoch_termination_conditions(MaxEpochsTerminationCondition(2))
           .model_saver(saver).build())
    res = EarlyStoppingTrainer(cfg, net, it).fit(max_epochs=2)
    assert saver.best_path is not None
    out_a = res.best_model.output(X[:4]).to_numpy()
    assert out_a.shape == (4, 3)


def test_evaluative_listener_mid_epoch_sees_fresh_params():
    """Regression: iteration_end evaluation must see CURRENT weights, not
    the previous epoch boundary's (fit syncs params at each flush when a
    listener sets needs_params)."""
    net = _toy_net(lr=0.5)
    X, Y = _toy_data(n=256, seed=3)
    holdout = ArrayDataSetIterator(X[:64], Y[:64], batch_size=64,
                                   shuffle=False)
    lst = EvaluativeListener(net, holdout, frequency=4,
                             invocation="iteration_end")
    assert lst.needs_params is True
    net.fit(X, Y, epochs=1, batch_size=32, listeners=[lst])  # 8 iterations
    assert len(lst.results) >= 2
    # an un-synced eval would repeat the INITIAL accuracy at every point;
    # training at lr=0.5 moves accuracy between first and last mid-epoch
    # evals for this learnable task
    accs = [ev.accuracy() for _, ev in lst.results]
    assert accs[-1] != accs[0]


def test_evaluative_epoch_mode_does_not_force_small_bursts():
    lst = EvaluativeListener(_toy_net(), None, frequency=1)
    assert lst.frequency >= 10**6      # bus cadence stays unbounded


def test_time_listener_fires_with_misaligned_bursts():
    msgs = []
    lst = TimeIterationListener(total_iterations=100, frequency=5,
                                print_fn=msgs.append)
    lst.on_training_start(None)
    # bursts of 7 (another listener's cadence): 0-6, 7-13, ...
    for start in range(0, 28, 7):
        lst.iterations_done(None, 0, list(range(start, start + 7)), [0.0] * 7)
    assert msgs                        # 7-aligned bursts still print


def test_save_last_model_in_memory():
    net = _toy_net(lr=0.2)
    X, Y = _toy_data()
    it = ArrayDataSetIterator(X, Y, batch_size=32)
    saver = InMemoryModelSaver()
    cfg = (EarlyStoppingConfiguration.builder()
           .epoch_termination_conditions(MaxEpochsTerminationCondition(3))
           .model_saver(saver).save_last_model().build())
    EarlyStoppingTrainer(cfg, net, it).fit(max_epochs=3)
    assert saver.latest_params is not None
    assert saver.latest_epoch == 2


def test_environment_debug_enables_nan_check_at_fit_time():
    """Regression: debug set AFTER TrainingConfig construction still
    triggers loss checking."""
    from deeplearning4j_tpu import environment
    from deeplearning4j_tpu.autodiff.samediff import NumericsException
    net = _toy_net(lr=1e8, seed=1)       # diverges fast
    X, Y = _toy_data()
    environment().set("debug", True)
    try:
        with pytest.raises(NumericsException):
            net.fit(X, Y, epochs=30, batch_size=96)
    finally:
        environment().reset("debug")


def test_max_epochs_fires_despite_sparse_evaluation():
    """Regression: epoch conditions are checked every epoch, not only on
    the evaluate_every_n_epochs cadence."""
    net = _toy_net()
    X, Y = _toy_data()
    it = ArrayDataSetIterator(X, Y, batch_size=32)
    cfg = (EarlyStoppingConfiguration.builder()
           .epoch_termination_conditions(MaxEpochsTerminationCondition(3))
           .evaluate_every_n_epochs(5).build())
    res = EarlyStoppingTrainer(cfg, net, it).fit(max_epochs=50)
    assert res.total_epochs == 3


def test_score_improvement_min_threshold_and_reuse():
    """Regression: min_improvement gates what counts as improvement, and
    the condition resets between fit() calls."""
    cond = ScoreImprovementEpochTerminationCondition(2, min_improvement=0.1)
    cond.initialize()
    assert cond.terminate(0, 1.00, True) is False   # first score = best
    assert cond.terminate(1, 0.99, True) is False   # +1 (not >0.1 better)
    assert cond.terminate(2, 0.98, True) is False   # +2
    assert cond.terminate(3, 0.97, True) is True    # patience exceeded
    cond.initialize()                               # fresh fit
    assert cond.terminate(0, 5.0, True) is False    # streak reset
    # a REAL improvement (>0.1) resets the streak
    assert cond.terminate(1, 4.99, True) is False   # +1
    assert cond.terminate(2, 4.0, True) is False    # resets (1.0 > 0.1)
    assert cond.terminate(3, 3.99, True) is False   # +1 again


def test_startup_only_env_property_raises_late_unless_for_restart():
    """A startup-only property set after backend init cannot affect the
    running process: set() must REFUSE (not silently accept the write);
    for_restart=True opts into writing the env var for child
    processes."""
    import os
    from deeplearning4j_tpu import environment
    env = environment()
    saved = os.environ.get("XLA_PYTHON_CLIENT_MEM_FRACTION")
    try:
        with pytest.raises(RuntimeError, match="backend initialization"):
            env.set("mem_fraction", 0.5)     # backend already initialized
        assert os.environ.get("XLA_PYTHON_CLIENT_MEM_FRACTION") == saved
        env.set("mem_fraction", 0.5, for_restart=True)
        assert os.environ["XLA_PYTHON_CLIENT_MEM_FRACTION"] == "0.5"
    finally:
        env.reset("mem_fraction")
        if saved is None:
            os.environ.pop("XLA_PYTHON_CLIENT_MEM_FRACTION", None)
        else:
            os.environ["XLA_PYTHON_CLIENT_MEM_FRACTION"] = saved


def test_best_score_condition_never_judges_trainloss_standin():
    """Regression: before the first score-calculator run, threshold
    conditions must not fire on the train-loss stand-in."""
    net = _toy_net(lr=0.3)
    X, Y = _toy_data()
    train = ArrayDataSetIterator(X, Y, batch_size=32)
    hold = ArrayDataSetIterator(X[:32], Y[:32], batch_size=32,
                                shuffle=False)
    cfg = (EarlyStoppingConfiguration.builder()
           .epoch_termination_conditions(
               MaxEpochsTerminationCondition(6),
               # target below any plausible loss: would fire instantly if
               # judged against the train-loss stand-in at epochs 0-3
               BestScoreEpochTerminationCondition(-1.0))
           .score_calculator(DataSetLossCalculator(hold))
           .evaluate_every_n_epochs(5).build())
    res = EarlyStoppingTrainer(cfg, net, train).fit(max_epochs=6)
    # MaxEpochs(6) terminates; BestScore(-1.0) never fires
    assert res.total_epochs == 6
    assert "MaxEpochs" in res.termination_details


def test_environment_reset_restores_startup_only_envvar():
    import os
    from deeplearning4j_tpu import environment
    env = environment()
    saved = os.environ.get("XLA_PYTHON_CLIENT_MEM_FRACTION")
    try:
        env.set("mem_fraction", 0.5, for_restart=True)
        with pytest.raises(ValueError):
            # validated like others — and BEFORE the env-var write
            env.set("mem_fraction", "abc", for_restart=True)
        assert os.environ["XLA_PYTHON_CLIENT_MEM_FRACTION"] == "0.5"
        env.reset("mem_fraction")
        assert os.environ.get("XLA_PYTHON_CLIENT_MEM_FRACTION") == saved
    finally:
        if saved is None:
            os.environ.pop("XLA_PYTHON_CLIENT_MEM_FRACTION", None)
        else:
            os.environ["XLA_PYTHON_CLIENT_MEM_FRACTION"] = saved
