"""Zoo wave-3 + SVHN/TinyImageNet tests (reference: deeplearning4j-zoo
TestInstantiation + dataset iterator tests)."""
import numpy as np
import pytest

from deeplearning4j_tpu.dataset import (
    SvhnDataSetIterator, TinyImageNetDataSetIterator, load_svhn,
    load_tiny_imagenet)
from deeplearning4j_tpu.learning.updaters import Adam
from deeplearning4j_tpu.zoo import (
    FaceNet, InceptionResNetV1, NASNet, VGG19, YOLO2)


def _overfit(net, X, Y, epochs, msg=""):
    h = net.fit(X, Y, epochs=epochs, batch_size=len(X))
    losses = h.loss_curve.losses
    assert np.isfinite(losses).all(), (msg, losses)
    assert losses[-1] < losses[0], (msg, losses[0], losses[-1])
    return h


# priced out of the tier-1 wall budget (ROADMAP tier-1 verify runs under timeout 870s); still pinned by the slow tier
@pytest.mark.slow
def test_vgg19_conf_and_overfit():
    conf = VGG19().conf()
    # 16 conv + 5 pool + 2 dense + 1 output
    from deeplearning4j_tpu.nn import ConvolutionLayer
    convs = [l for l in conf.layers if isinstance(l, ConvolutionLayer)]
    assert len(convs) == 16
    rng = np.random.RandomState(0)
    X = rng.rand(4, 3, 32, 32).astype(np.float32)
    Y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 4)]
    net = VGG19(height=32, width=32, num_classes=2,
                updater=Adam(1e-3)).build()
    _overfit(net, X, Y, epochs=6, msg="vgg19")


# priced out of the tier-1 wall budget (ROADMAP tier-1 verify runs under timeout 870s); still pinned by the slow tier
@pytest.mark.slow
def test_inception_resnet_v1_overfit():
    rng = np.random.RandomState(1)
    X = rng.rand(4, 3, 64, 64).astype(np.float32)
    Y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 4)]
    net = InceptionResNetV1(height=64, width=64, num_classes=3,
                            blocks_a=1, blocks_b=1, blocks_c=1,
                            updater=Adam(3e-3)).build()
    _overfit(net, X, Y, epochs=8, msg="inception_resnet_v1")
    out = net.output(X[:2])
    out = out[0] if isinstance(out, list) else out
    assert np.asarray(out.data).shape == (2, 3)


def test_facenet_embedding_is_l2_normalized():
    rng = np.random.RandomState(2)
    X = rng.rand(4, 3, 64, 64).astype(np.float32)
    Y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 4)]
    net = FaceNet(height=64, width=64, num_classes=3, embedding_size=16,
                  blocks_a=1, blocks_b=1, blocks_c=1,
                  updater=Adam(3e-3)).build()
    _overfit(net, X, Y, epochs=6, msg="facenet")
    emb = net.feed_forward(X[:2])["embedding"]
    emb = np.asarray(emb.data if hasattr(emb, "data") else emb)
    assert emb.shape == (2, 16)
    np.testing.assert_allclose(np.linalg.norm(emb, axis=1), 1.0, atol=1e-3)


def test_nasnet_overfit():
    rng = np.random.RandomState(3)
    X = rng.rand(4, 3, 32, 32).astype(np.float32)
    Y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 4)]
    net = NASNet(height=32, width=32, num_classes=2, cells_per_stack=1,
                 filters=8, stem_filters=8, updater=Adam(3e-3)).build()
    _overfit(net, X, Y, epochs=8, msg="nasnet")


# priced out of the tier-1 wall budget (ROADMAP tier-1 verify runs under timeout 870s); still pinned by the slow tier
@pytest.mark.slow
def test_yolo2_trains_with_passthrough():
    rng = np.random.RandomState(4)
    B, C = 2, 2
    net = YOLO2(height=64, width=64, num_classes=C,
                anchors=(1.0, 1.0, 2.0, 2.0), updater=Adam(3e-3)).build()
    X = rng.rand(B, 3, 64, 64).astype(np.float32)
    labels = np.zeros((B, 4 + C, 2, 2), np.float32)   # 64/32 = 2x2 grid
    labels[:, 0:4, 1, 1] = np.array([0.5, 0.5, 1.5, 1.5], np.float32)
    labels[:, 4, 1, 1] = 1.0
    h = net.fit(X, labels, epochs=10, batch_size=B)
    losses = h.loss_curve.losses
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


# ---- datasets -------------------------------------------------------------

def test_svhn_loader_and_iterator():
    X, y = load_svhn(n_synthetic=256)
    assert X.shape == (256, 3, 32, 32) and y.shape == (256,)
    assert X.dtype == np.float32 and 0 <= y.min() and y.max() < 10
    it = SvhnDataSetIterator(batch_size=64, n_synthetic=256)
    xb, yb = next(iter(it))
    assert xb.shape == (64, 3, 32, 32) and yb.shape == (64, 10)


def test_tiny_imagenet_loader_and_iterator():
    X, y = load_tiny_imagenet(n_synthetic=128, n_classes=20)
    assert X.shape == (128, 3, 64, 64)
    assert y.max() < 20
    it = TinyImageNetDataSetIterator(batch_size=32, n_synthetic=128,
                                     n_classes=20)
    xb, yb = next(iter(it))
    assert xb.shape == (32, 3, 64, 64) and yb.shape == (32, 20)


def test_synthetic_svhn_learnable():
    """The hermetic fallback must be learnable (class signal present)."""
    from deeplearning4j_tpu.zoo import SimpleCNN
    X, y = load_svhn(n_synthetic=128)
    Y = np.eye(10, dtype=np.float32)[y]
    net = SimpleCNN(height=32, width=32, channels=3, num_classes=10,
                    updater=Adam(3e-3)).build()
    h = net.fit(X, Y, epochs=6, batch_size=64)
    assert h.loss_curve.losses[-1] < h.loss_curve.losses[0]
