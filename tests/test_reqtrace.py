"""Request-scoped distributed tracing + fleet SLO observability
(monitor/reqtrace.py, ISSUE 20).

Covers: TraceContext propagation + deterministic head sampling; the ONE
slo_attainment definition shared by SLOTracker and LoadResult; waterfall
assembly with proportional batch attribution; the RequestTracer's
head/tail keep policy and bounded LRU; concurrent Tracer drain (the
fleet collector's path — 8 writers, 1 drainer); the chaos drill (kill a
replica mid-stream → ONE trace_id whose waterfall shows the dead
segment and the resume segment) asserted in-process AND over the
/requesttrace route; /slo + registry fold + report panel; and the
router-level bit-identity of tracing on vs off.

Real-model trace tagging and bit-identity ride tests/test_fleet.py
(shared compile set); everything here runs on stubs — router logic,
not model math.
"""
import json
import threading
import time
import urllib.request
from types import SimpleNamespace

import numpy as np
import pytest

from deeplearning4j_tpu.monitor.registry import MetricsRegistry
from deeplearning4j_tpu.monitor.reqtrace import (RequestTracer, SLOTracker,
                                                 TraceContext, assemble,
                                                 head_sampled,
                                                 slo_attainment,
                                                 ttft_breakdown)
from deeplearning4j_tpu.monitor.server import TelemetryServer
from deeplearning4j_tpu.monitor.trace import (SPAN_CATALOG, TRACER, Tracer,
                                              disable_tracing,
                                              enable_tracing)
from deeplearning4j_tpu.serving.fleet.replica import FleetReplica
from deeplearning4j_tpu.serving.fleet.router import FleetRouter
from deeplearning4j_tpu.serving.loadgen import FleetLoadGenerator, LoadResult
from deeplearning4j_tpu.serving.queue import ServerClosedError
from deeplearning4j_tpu.ui.report import render_report
from deeplearning4j_tpu.ui.stats import StatsStorage


@pytest.fixture(autouse=True)
def _global_tracer_off():
    yield
    disable_tracing()


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, json.loads(r.read().decode("utf-8"))


# ----------------------------------------------------------------------
# stub fleet (the test_durable idiom): streams tokens, can die once
# mid-stream, resumes via submit_continuation — router logic only

class _Handle:
    def __init__(self, tokens, fail=None):
        self._tokens = tokens
        self._fail = fail

    def result(self, timeout=None):
        if self._fail is not None:
            raise self._fail
        return self._tokens


class StreamingStub:
    """Emits ``100 + i`` via ``on_token``; ``die_after=k`` fails the
    handle (once) after k tokens TOTAL have streamed."""

    def __init__(self, name="s", die_after=None, step_s=0.0):
        self.name = name
        self.block_size = 8
        self.telemetry = None
        self.die_after = die_after
        self.step_s = step_s
        self.traces_seen = []           # the trace= kwarg per submit
        self._queue = SimpleNamespace(pending=lambda: 0)

    def _n_active(self):
        return 0

    def _telemetry_health(self):
        return {"ready": True, "healthy": True,
                "load": {"queue_depth": 0, "slot_occupancy": 0.0,
                         "p99_decode_step_ms": 1.0}}

    def _run(self, start, n, on_token):
        for i in range(n):
            if self.die_after is not None and start + i >= self.die_after:
                self.die_after = None
                return _Handle(None, fail=ServerClosedError("crashed"))
            if self.step_s:
                time.sleep(self.step_s)
            if on_token is not None:
                on_token(100 + start + i)
        return _Handle([100 + start + i for i in range(n)])

    def submit(self, prompt, max_new_tokens=16, timeout_ms=None,
               on_token=None, trace=None, **kw):
        self.traces_seen.append(trace)
        return self._run(0, max_new_tokens, on_token)

    def submit_continuation(self, prompt, emitted, max_new_tokens=16,
                            timeout_ms=None, on_token=None, trace=None,
                            **kw):
        self.traces_seen.append(trace)
        return self._run(len(emitted), max_new_tokens - len(emitted),
                         on_token)

    def shutdown(self, drain=True, timeout=None):
        pass


def stub_fleet(servers, **router_kw):
    replicas = [FleetReplica(s.name, server=s) for s in servers]
    router_kw.setdefault("poll_interval_s", 0.0)
    router_kw.setdefault("affinity", False)
    router_kw.setdefault("sleep", lambda s: None)
    return FleetRouter(replicas, **router_kw), replicas


# ----------------------------------------------------------------------
class TestTraceContext:
    def test_segment_counter(self):
        ctx = TraceContext(7)
        assert ctx.trace_id == 7 and ctx.segment == 0
        assert ctx.segments_minted == 0
        assert ctx.next_segment() == 0
        assert ctx.next_segment() == 1
        assert ctx.segment == 1
        assert ctx.segments_minted == 2
        assert ctx.span_args() == {"trace_id": 7, "segment": 1}

    def test_head_sampling_is_deterministic_and_roughly_fair(self):
        assert all(head_sampled(i, 1.0) for i in range(50))
        assert not any(head_sampled(i, 0.0) for i in range(50))
        first = [head_sampled(i, 0.25) for i in range(2000)]
        assert first == [head_sampled(i, 0.25) for i in range(2000)]
        rate = sum(first) / len(first)
        assert 0.15 < rate < 0.35       # hash-fair, not exact

    def test_origin_marks_replays(self):
        assert TraceContext(1).origin == "live"
        assert TraceContext(1, origin="replay").origin == "replay"


class TestSloAttainmentDefinition:
    def test_one_definition(self):
        recs = [("ok", 100.0), ("ok", 900.0), ("ok", None),
                ("shed", None), ("failed:Boom", 50.0)]
        # ok-with-None excluded; non-ok always a miss
        assert slo_attainment(recs, 500.0) == pytest.approx(1 / 4)
        assert slo_attainment(recs, 1000.0) == pytest.approx(2 / 4)
        assert slo_attainment([], 1.0) == 1.0

    def test_loadgen_rows_and_tracker_agree(self):
        outcomes = [("ok", 10.0), ("ok", 5000.0), ("shed", None),
                    ("ok", 100.0)]
        tracker = SLOTracker(objectives={"ttft_ms": 1000.0},
                             error_budget=0.1)
        res = LoadResult()
        for status, ttft in outcomes:
            tracker.record(status, ttft_ms=ttft, e2e_ms=ttft, tokens=1)
            res.rows.append({"outcome": status if status != "ok"
                             else "ok", "ttft_ms": ttft})
        assert res.slo_attainment(1000.0) == \
            tracker.attainment("ttft_ms") == pytest.approx(2 / 4)


class TestSLOTracker:
    def test_attainment_burn_rate_and_record_shape(self):
        t = SLOTracker(objectives={"ttft_ms": 100.0}, window=64,
                       error_budget=0.1)
        for _ in range(9):
            t.record("ok", ttft_ms=50.0, e2e_ms=80.0, tokens=4,
                     replica="a")
        t.record("shed", tokens=0)
        assert t.attainment("ttft_ms") == pytest.approx(0.9)
        # 10% missing vs a 10% budget: burning exactly as provisioned
        assert t.burn_rate("ttft_ms") == pytest.approx(1.0)
        d = t.to_dict()
        assert d["window"] == 10 and d["total"] == 10
        assert d["outcomes"]["ok"] == 9 and d["outcomes"]["shed"] == 1
        obj = d["objectives"]["ttft_ms"]
        assert obj["target_ms"] == 100.0
        assert obj["attainment"] == pytest.approx(0.9)
        assert obj["burn_rate"] == pytest.approx(1.0)
        assert obj["p50_ms"] == 50.0

    def test_breached(self):
        t = SLOTracker(objectives={"ttft_ms": 100.0})
        assert t.breached({"status": "shed"})
        assert t.breached({"status": "ok", "ttft_ms": 101.0})
        assert not t.breached({"status": "ok", "ttft_ms": 99.0})
        assert not t.breached({"status": "ok", "ttft_ms": None})

    def test_worst_waterfalls_bounded_and_sorted(self):
        t = SLOTracker(worst_k=2)
        for i, ttft in enumerate([5.0, 50.0, 20.0]):
            t.note_waterfall({"trace_id": i, "ttft_ms": ttft,
                              "phases": {"queue_wait_ms": ttft / 2}})
        worst = t.to_dict()["worst_traces"]
        assert [w["trace_id"] for w in worst] == [1, 2]   # worst first
        assert worst[0]["breakdown"]["queue_wait_ms"] == 25.0


class TestAssemble:
    def _spans(self):
        t = Tracer(capacity=256, enabled=True)
        with t.span("fleet.attempt", cat="fleet", trace_id=5, segment=0,
                    kind="initial", outcome="ok"):
            with t.span("serving.enqueue", cat="serving", id=1,
                        trace_id=5, segment=0):
                time.sleep(0.002)
            time.sleep(0.002)           # the queue wait
            with t.span("serving.prefill", cat="serving", bucket=8,
                        slot=0, trace_id=5, segment=0):
                time.sleep(0.002)
            # two decode rounds shared with another request (slot 1)
            for _ in range(2):
                with t.span("serving.decode", cat="serving", active=2,
                            slots={0: 5, 1: 9}):
                    time.sleep(0.002)
            with t.span("serving.reply", cat="serving", id=1,
                        trace_id=5, segment=0):
                pass
        return t.spans()

    def test_waterfall_phases_and_proportional_attribution(self):
        spans = self._spans()
        wf = assemble(spans, 5, outcome={"status": "ok",
                                         "ttft_ms": 8.0, "e2e_ms": 12.0,
                                         "tokens": 2, "replica": "a",
                                         "retries": 0, "resumes": 0,
                                         "origin": "live"})
        ph = wf["phases"]
        assert ph["queue_wait_ms"] > 0.0
        assert ph["prefill_ms"] > 0.0
        assert ph["decode_rounds"] == 2
        # shared 2-slot dispatch: this request is attributed HALF
        raw_decode = sum(s.dur for s in spans
                         if s.name == "serving.decode") * 1000.0
        assert ph["decode_ms"] == pytest.approx(raw_decode / 2, rel=0.01)
        assert wf["segments"][0]["kind"] == "initial"
        assert wf["status"] == "ok" and wf["ttft_ms"] == 8.0
        shares = {ln["name"]: ln["share"] for ln in wf["spans"]}
        assert shares["serving.decode"] == 0.5
        assert shares["serving.prefill"] == 1.0
        # the OTHER occupant of the shared dispatch sees it too
        other = assemble(spans, 9)
        assert other["phases"]["decode_rounds"] == 2
        assert other["phases"]["prefill_ms"] == 0.0

    def test_every_assembled_span_name_is_cataloged(self):
        for s in self._spans():
            assert s.name in SPAN_CATALOG


class TestRequestTracer:
    def _rt(self, **kw):
        t = Tracer(capacity=512, enabled=True)
        kw.setdefault("tracer", t)
        return RequestTracer(**kw), t

    def _record_request(self, t, ctx, ok=True):
        with t.span("fleet.attempt", cat="fleet", kind="initial",
                    outcome="ok" if ok else None, **ctx.span_args()):
            pass

    def test_head_keep_and_get(self):
        rt, t = self._rt(sample=1.0)
        ctx = rt.begin(3)
        assert ctx.sampled
        self._record_request(t, ctx)
        wf = rt.finish(ctx, {"status": "ok", "ttft_ms": 1.0,
                             "e2e_ms": 2.0})
        assert wf is not None and wf["kept"] == "head"
        assert rt.get(3)["trace_id"] == 3
        assert rt.summaries()[0]["status"] == "ok"

    def test_unsampled_ok_is_dropped(self):
        rt, t = self._rt(sample=0.0)
        ctx = rt.begin(3)
        self._record_request(t, ctx)
        assert rt.finish(ctx, {"status": "ok", "ttft_ms": 1.0}) is None
        assert rt.get(3) is None

    def test_tail_keep_on_failure_retry_and_slo_breach(self):
        slo = SLOTracker(objectives={"ttft_ms": 10.0})
        rt, t = self._rt(sample=0.0, slo=slo)
        for tid, outcome in ((1, {"status": "shed"}),
                             (2, {"status": "ok", "retries": 2}),
                             (3, {"status": "ok", "resumes": 1}),
                             (4, {"status": "ok", "ttft_ms": 99.0})):
            ctx = rt.begin(tid)
            assert not ctx.sampled
            self._record_request(t, ctx)
            wf = rt.finish(ctx, outcome)
            assert wf is not None and wf["kept"] == "tail", outcome

    def test_lru_bound(self):
        rt, t = self._rt(sample=1.0, capacity=2)
        for tid in (1, 2, 3):
            ctx = rt.begin(tid)
            self._record_request(t, ctx)
            rt.finish(ctx, {"status": "ok"})
        assert rt.get(1) is None
        assert [w["trace_id"] for w in rt.waterfalls()] == [2, 3]

    def test_inert_while_tracer_disabled(self):
        t = Tracer(capacity=16, enabled=False)
        rt = RequestTracer(tracer=t, sample=1.0)
        assert not rt.active
        ctx = rt.begin(1)
        assert rt.finish(ctx, {"status": "ok"}) is None
        assert rt.waterfalls() == []

    def test_chrome_trace_is_lane_per_request(self):
        rt, t = self._rt(sample=1.0)
        for tid in (11, 12):
            ctx = rt.begin(tid)
            self._record_request(t, ctx)
            rt.finish(ctx, {"status": "ok"})
        out = rt.to_chrome_trace()
        meta = [e for e in out["traceEvents"] if e["ph"] == "M"]
        assert {m["args"]["name"] for m in meta} == \
            {"request 11", "request 12"}
        lanes = {e["tid"] for e in out["traceEvents"] if e["ph"] == "X"}
        assert lanes == {11, 12}


# ----------------------------------------------------------------------
# satellite: the ring under concurrent drain() + recording threads —
# the fleet collector polls a live tracer exactly like this

class TestTracerConcurrentDrain:
    N_WRITERS, PER_WRITER = 8, 300

    def _hammer(self, capacity):
        t = Tracer(capacity=capacity, enabled=True)
        drained, cursors, dropped_total = [], [], [0]
        stop = threading.Event()

        def writer(w):
            for i in range(self.PER_WRITER):
                with t.span("step", cat="train", w=w, i=i):
                    pass

        def drainer():
            mark = 0
            while True:
                spans, mark2, dropped = t.drain(mark)
                assert mark2 >= mark, "drain cursor went backwards"
                drained.extend(spans)
                dropped_total[0] += dropped
                cursors.append(mark2)
                mark = mark2
                if stop.is_set():
                    spans, mark, dropped = t.drain(mark)
                    drained.extend(spans)
                    dropped_total[0] += dropped
                    cursors.append(mark)
                    return
                time.sleep(0.0002)

        dt = threading.Thread(target=drainer)
        ws = [threading.Thread(target=writer, args=(w,))
              for w in range(self.N_WRITERS)]
        dt.start()
        for w in ws:
            w.start()
        for w in ws:
            w.join()
        stop.set()
        dt.join()
        return t, drained, cursors, dropped_total[0]

    def test_no_span_loss_below_capacity(self):
        total = self.N_WRITERS * self.PER_WRITER
        t, drained, cursors, dropped = self._hammer(capacity=total + 64)
        assert dropped == 0
        assert len(drained) == total
        # every (writer, i) arrived exactly once
        seen = {(s.args["w"], s.args["i"]) for s in drained}
        assert len(seen) == total
        # seq cursors monotonic; collected seqs strictly increasing
        assert cursors == sorted(cursors)
        seqs = [s.seq for s in drained]
        assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
        assert t.mark() == total

    def test_eviction_accounting_is_exact_under_concurrency(self):
        total = self.N_WRITERS * self.PER_WRITER
        t, drained, cursors, dropped = self._hammer(capacity=64)
        # conservation: every recorded span was either drained or
        # counted evicted — never both, never neither
        assert len(drained) + dropped == total
        seqs = [s.seq for s in drained]
        assert len(set(seqs)) == len(seqs)
        assert cursors == sorted(cursors)

    def test_eviction_accounting_exact_single_thread(self):
        t = Tracer(capacity=64, enabled=True)
        for i in range(500):
            with t.span("step", cat="train", i=i):
                pass
        spans, mark, dropped = t.drain(0)
        assert (len(spans), mark, dropped) == (64, 500, 436)
        assert [s.args["i"] for s in spans] == list(range(436, 500))
        spans, mark, dropped = t.drain(490)
        assert (len(spans), mark, dropped) == (10, 500, 0)


# ----------------------------------------------------------------------
# the chaos drill: kill a replica mid-stream -> ONE trace_id whose
# waterfall shows the dead segment + the resume segment (acceptance)

class TestChaosDrillTrace:
    def _drill(self):
        enable_tracing(reset=True)
        s1 = StreamingStub("a", die_after=3, step_s=0.001)
        s2 = StreamingStub("b", step_s=0.001)
        router, _ = stub_fleet([s1, s2])
        res = router.generate([1, 2, 3], max_new_tokens=6)
        return router, res, s1, s2

    def test_one_trace_id_dead_segment_then_resume(self):
        router, res, s1, s2 = self._drill()
        assert res.tokens == [100, 101, 102, 103, 104, 105]
        assert res.resumes == 1 and res.trace_id is not None
        # every hop saw the SAME context object/trace id
        hops = s1.traces_seen + s2.traces_seen
        assert all(h is not None and h.trace_id == res.trace_id
                   for h in hops)
        wf = router.reqtrace.get(res.trace_id)
        assert wf is not None
        segs = wf["segments"]
        assert len(segs) == 2
        assert segs[0]["error"] == "ServerClosedError"
        assert segs[0]["outcome"] is None
        assert segs[1]["kind"] == "resume"
        assert segs[1]["outcome"] == "ok"
        assert segs[1]["replica"] == "b"
        assert segs[0]["start_ms"] <= segs[1]["start_ms"]
        # correct total TTFT/e2e: the router's measurement is merged in
        assert wf["ttft_ms"] == pytest.approx(res.ttft_ms)
        assert wf["e2e_ms"] >= wf["ttft_ms"] > 0.0
        assert wf["resumes"] == 1
        # a failover is a tail-keep trigger even at 0% head sampling
        assert router.slo.to_dict()["outcomes"]["ok"] == 1

    def test_failover_tail_kept_at_one_percent_sampling(self):
        enable_tracing(reset=True)
        s1 = StreamingStub("a", die_after=3)
        s2 = StreamingStub("b")
        router, _ = stub_fleet([s1, s2], trace_sample=0.0)
        res = router.generate([1, 2, 3], max_new_tokens=6)
        wf = router.reqtrace.get(res.trace_id)
        assert wf is not None and wf["kept"] == "tail"

    def test_rendered_over_requesttrace_and_slo_routes(self):
        router, res, _, _ = self._drill()
        srv = TelemetryServer(storage=StatsStorage(), port=0)
        try:
            srv.attach_reqtrace(router.reqtrace)
            srv.attach_slo(router.slo)
            code, idx = _get(f"{srv.url}/requesttrace")
            assert code == 200
            assert [t["trace_id"] for t in idx["traces"]] == \
                [res.trace_id]
            code, wf = _get(
                f"{srv.url}/requesttrace?id={res.trace_id}")
            assert code == 200
            assert wf["segments"][0]["error"] == "ServerClosedError"
            assert wf["segments"][1]["kind"] == "resume"
            code, chrome = _get(
                f"{srv.url}/requesttrace?id={res.trace_id}&chrome=1")
            assert code == 200
            lanes = {e["tid"] for e in chrome["traceEvents"]
                     if e["ph"] == "X"}
            assert lanes == {res.trace_id}
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"{srv.url}/requesttrace?id=999999")
            assert ei.value.code == 404
            code, slo = _get(f"{srv.url}/slo")
            assert code == 200 and slo["source"] == "live"
            assert slo["slo"]["outcomes"]["ok"] == 1
        finally:
            srv.close()

    def test_slo_route_falls_back_to_storage(self):
        router, _, _, _ = self._drill()
        storage = StatsStorage()
        router.publish(storage)
        srv = TelemetryServer(storage=storage, port=0)
        try:
            code, slo = _get(f"{srv.url}/slo")
            assert code == 200 and slo["source"] == "storage"
            assert "ttft_ms" in slo["slo"]["objectives"]
        finally:
            srv.close()

    def test_replay_segments_reuse_the_trace_id(self, tmp_path):
        from deeplearning4j_tpu.serving.fleet.durable import \
            RequestJournal
        enable_tracing(reset=True)
        jn = RequestJournal(tmp_path)
        rid = jn.next_request_id()
        jn.log_submitted(rid, [1, 2], 4, None,
                         sampling={"temperature": 0.0})
        jn.append_token(rid, 2, 100)
        jn.flush(rid)
        router, _ = stub_fleet([StreamingStub("a")], journal=jn)
        results = router.recover()
        assert list(results) == [rid]
        wf = router.reqtrace.get(rid)
        assert wf is not None
        assert wf["origin"] == "replay"
        assert wf["segments"][0]["kind"] == "replay"
        jn.close()


# ----------------------------------------------------------------------
# the /trace?since= incremental drain satellite

class TestTraceSinceRoute:
    def test_incremental_drain_with_cursor(self):
        enable_tracing(reset=True)
        with TRACER.span("window", cat="train", k=1):
            pass
        srv = TelemetryServer(port=0)
        try:
            code, full = _get(f"{srv.url}/trace")
            assert code == 200
            cursor = full["otherData"]["next"]
            assert cursor == 1 and "dropped" not in full["otherData"]
            with TRACER.span("step", cat="train", k=1):
                pass
            code, inc = _get(f"{srv.url}/trace?since={cursor}")
            names = [e["name"] for e in inc["traceEvents"]
                     if e["ph"] == "X"]
            assert names == ["step"]    # old spans NOT re-downloaded
            assert inc["otherData"]["next"] == 2
            assert inc["otherData"]["dropped"] == 0
            code, empty = _get(f"{srv.url}/trace?since=2")
            assert [e for e in empty["traceEvents"]
                    if e["ph"] == "X"] == []
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"{srv.url}/trace?since=bogus")
            assert ei.value.code == 400
        finally:
            srv.close()


# ----------------------------------------------------------------------
# record / registry / report plumbing + the loadgen satellite

class TestSloRecordAndPanels:
    def _router_after_traffic(self):
        enable_tracing(reset=True)
        router, _ = stub_fleet([StreamingStub("a"), StreamingStub("b")])
        gen = FleetLoadGenerator(router.generate, vocab_size=64, seed=3,
                                 prompt_len=(1, 4), new_tokens=(2, 4))
        res = gen.run_closed(n_requests=8, concurrency=2)
        assert res.n_ok == 8
        return router, res

    def test_fleet_record_grows_slo_subdict(self):
        router, _ = self._router_after_traffic()
        rec = router.metrics.to_record()
        assert rec["type"] == "fleet"           # NO new record type
        slo = rec["slo"]
        assert slo["window"] == 8
        assert set(slo["objectives"]) == {"ttft_ms", "e2e_ms"}
        for obj in slo["objectives"].values():
            assert 0.0 <= obj["attainment"] <= 1.0
            assert obj["p99_ms"] >= obj["p50_ms"] >= 0.0

    def test_registry_folds_slo_gauges(self):
        router, _ = self._router_after_traffic()
        reg = MetricsRegistry()
        reg.fold_fleet(router.metrics)
        text = reg.to_prometheus_text()
        assert 'dl4j_fleet_slo_attainment{objective="ttft_ms"}' in text
        assert 'dl4j_fleet_slo_burn_rate{objective="e2e_ms"}' in text
        assert 'dl4j_fleet_slo_requests_total{outcome="ok"} 8' in text
        assert "dl4j_fleet_slo_p99_ms" in text

    def test_report_renders_slo_panel(self):
        router, _ = self._router_after_traffic()
        storage = StatsStorage()
        router.publish(storage)
        html = render_report(storage)
        assert "<h3>SLO</h3>" in html
        assert "burn rate" in html
        assert "worst sampled traces" in html
        assert "Request tracing" in html

    def test_loadgen_rows_carry_ttft_breakdown_when_sampled(self):
        _, res = self._router_after_traffic()
        ok = [r for r in res.rows if r["outcome"] == "ok"]
        assert ok and all(isinstance(r["ttft_breakdown"], dict)
                          for r in ok)
        assert set(ok[0]["ttft_breakdown"]) == \
            {"queue_wait_ms", "prefill_ms", "first_decode_ms"}
        assert res.slo_attainment(60000.0) == 1.0
        assert res.slo_attainment(60000.0, lane="e2e_ms") == 1.0

    def test_loadgen_breakdown_absent_when_tracing_off(self):
        disable_tracing()
        router, _ = stub_fleet([StreamingStub("a")])
        gen = FleetLoadGenerator(router.generate, vocab_size=64, seed=3,
                                 prompt_len=(1, 4), new_tokens=(2, 4))
        res = gen.run_closed(n_requests=4, concurrency=2)
        assert all(r["ttft_breakdown"] is None for r in res.rows)
        # ...but the SLO rail still records (host-side counters only)
        assert router.metrics.to_record()["slo"]["window"] == 4


# ----------------------------------------------------------------------
# the standing contract: tracing must never change the math

class TestBitIdentityOnOff:
    def _tokens(self, traced):
        if traced:
            enable_tracing(reset=True)
        else:
            disable_tracing()
        router, _ = stub_fleet([StreamingStub("a", die_after=3),
                                StreamingStub("b")])
        try:
            return router.generate([1, 2, 3], max_new_tokens=6).tokens
        finally:
            disable_tracing()

    def test_router_results_identical_tracing_on_vs_off(self):
        assert self._tokens(False) == self._tokens(True)

    def test_disabled_rail_is_fully_inert(self):
        disable_tracing()
        router, _ = stub_fleet([StreamingStub("a")],
                               slo=False, reqtrace=False)
        res = router.generate([1], max_new_tokens=2)
        assert res.tokens == [100, 101]
        assert res.ttft_breakdown is None
        assert router.reqtrace is None and router.slo is None
        assert "slo" not in router.metrics.to_record()
