"""Every example script must actually run (the dl4j-examples role: these
are the first thing a migrating user executes)."""
import os
import runpy
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = os.path.join(HERE, "examples")

SCRIPTS = ["mnist_mlp.py", "cnn_with_augmentation.py",
           "keras_import_finetune.py", "word2vec_text.py",
           "multi_device_training.py", "moe_expert_parallel.py",
           "early_stopping_holdout.py", "serving_mnist.py",
           "checkpoint_resume.py", "self_healing_fit.py",
           "observability_demo.py", "analyze_model.py",
           "streaming_fit.py", "generative_serving.py",
           # the fast-decode walkthrough trains a target AND a draft,
           # then compiles the speculative + paged-int8 tiers — priced
           # out of the tier-1 wall budget, still pinned by the slow
           # tier (its contracts also ride tests/test_generative.py
           # TestSpeculative/TestSeededSampling directly)
           pytest.param("speculative_serving.py",
                        marks=pytest.mark.slow),
           # the paged walkthrough compiles two serving tiers (dense
           # reference + paged, then a tp=2 mesh) — priced out of the
           # tier-1 wall budget, still pinned by the slow tier
           pytest.param("paged_serving.py", marks=pytest.mark.slow),
           # the fleet drill stands up three paged replicas and runs
           # kill + rolling-deploy chaos under open-loop load — slow
           # tier for the same wall-budget reason
           pytest.param("fleet_serving.py", marks=pytest.mark.slow)]


@pytest.mark.parametrize("script", SCRIPTS)
def test_example_runs(script):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "host_platform_device_count" not in f]
    flags.append("--xla_force_host_platform_device_count=8")
    env["XLA_FLAGS"] = " ".join(flags)
    env["PYTHONPATH"] = HERE + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, os.path.join(EXAMPLES, script)],
        env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, (script, proc.stdout[-1500:],
                                  proc.stderr[-1500:])
    assert proc.stdout.strip(), script
    # the examples double as the static analyzer's zero-false-positive
    # sweep (ISSUE 12): every fit runs analyze/ by default, and an
    # error-severity finding on a healthy example graph surfaces as a
    # GraphAnalysisWarning on stderr — a hard failure here. (A
    # PYTHONWARNINGS error:: filter cannot do this: dotted category
    # names are rejected at interpreter startup and silently dropped.)
    assert "GraphAnalysisWarning" not in proc.stderr, (
        script, proc.stderr[-1500:])
