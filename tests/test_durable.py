"""Durable generative requests (serving/fleet/durable.py, ISSUE 19).

Pinned contracts:

- the RequestJournal is a real WAL: per-record sha256, torn-tail
  truncation on the recovery scan (a crash mid-append drops exactly the
  torn bytes), :class:`JournalCorruptError` on a bad SEALED segment,
  compacting segment rotation through the atomic staging/commit
  discipline, request ids monotonic across reopen;
- StreamCursor delivers exactly once: duplicates absorb (counted),
  gaps raise, preloaded replay tokens never re-invoke the callback;
- ``FleetRouter.generate`` composes a caller ``on_token`` with its
  internals (the old duplicate-keyword TypeError), deducts elapsed
  time from the deadline per retry attempt (the old ``retry_budget ×
  timeout_ms`` hole → typed ``RequestTimeoutError``), and resumes a
  mid-stream death from the emitted prefix — same seed, decremented
  budget — instead of restarting;
- chaos drills: kill a replica mid-stream → the streamed sequence has
  zero duplicates/gaps and the final generation is bit-identical to an
  uninterrupted run, greedy AND sampled; kill-and-restart the router →
  ``recover(journal)`` replays every incomplete request exactly once
  (idempotent: completed entries skip, a second recover is a no-op);
- the paged server registers the GENERATED span's full blocks at clean
  retirement, so a continuation prefilling prompt + emitted hits the
  prefix cache beyond the prompt;
- every registered wire kind round-trips ``to_wire``/``from_wire``
  (FleetUnavailableError included), and the durability sub-dict flows
  fleet record → ``dl4j_fleet_durability_*`` gauges → report line.
"""
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from deeplearning4j_tpu.faults.chaos import ChaosMonkey
from deeplearning4j_tpu.serving.fleet import (FleetReplica, FleetRouter,
                                              FleetUnavailableError,
                                              JournalCorruptError,
                                              RequestJournal, StreamCursor)
from deeplearning4j_tpu.serving.fleet.durable import DurabilityMetrics
from deeplearning4j_tpu.serving.generative import greedy_decode
from deeplearning4j_tpu.serving.paged import PagedGenerativeServer
from deeplearning4j_tpu.serving.queue import (RequestTimeoutError,
                                              ServerClosedError,
                                              ServerOverloadedError)
from deeplearning4j_tpu.serving.resilience import (_WIRE_KINDS,
                                                   RetryableServingError)
from deeplearning4j_tpu.zoo.gpt import (GPTConfig, build_gpt,
                                        gpt_generative_spec,
                                        gpt_paged_spec)

CFG = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
                intermediate_size=64, max_seq_len=32)
MSL = 32
BS = 8


@pytest.fixture(scope="module")
def gpt_sd():
    return build_gpt(CFG, batch=2, seq_len=8, seed=0)


@pytest.fixture(scope="module")
def spec(gpt_sd):
    return gpt_paged_spec(gpt_sd, CFG)


@pytest.fixture(scope="module")
def dense_spec(gpt_sd):
    # greedy_decode's dense reference: paged vs dense is a memory-layout
    # change only, so it doubles as the bit-identity oracle here too
    return gpt_generative_spec(gpt_sd, CFG)


def make_server(spec, **kw):
    kw.setdefault("max_slots", 4)
    kw.setdefault("max_seq_len", MSL)
    kw.setdefault("block_size", BS)
    kw.setdefault("warmup", False)
    kw.setdefault("debug_leaks", True)
    return PagedGenerativeServer(spec, **kw)


def stop_all(replicas):
    for r in replicas:
        try:
            r.stop(drain=False)
        except Exception:   # noqa: BLE001 — already dead is fine here
            pass


# ----------------------------------------------------------------------
# stub surface: a server that streams tokens and can die mid-stream,
# with the continuation path the real server grew (router-logic tests)

class _Handle:
    def __init__(self, tokens, fail=None):
        self._tokens = tokens
        self._fail = fail

    def result(self, timeout=None):
        if self._fail is not None:
            raise self._fail
        return self._tokens


class StreamingStub:
    """Emits ``base + i`` tokens via ``on_token``; ``die_after=k``
    fails the handle (once) after k tokens TOTAL have streamed."""

    def __init__(self, name="s", die_after=None, submit_errors=()):
        self.name = name
        self.block_size = BS
        self.telemetry = None
        self.die_after = die_after
        self.submit_errors = list(submit_errors)
        self.submits = []           # (prompt, n, timeout_ms, seed)
        self.continuations = []     # (prompt, emitted, n, timeout_ms, seed)
        self._queue = SimpleNamespace(pending=lambda: 0)

    def _n_active(self):
        return 0

    def _telemetry_health(self):
        return {"ready": True, "healthy": True,
                "load": {"queue_depth": 0, "slot_occupancy": 0.0,
                         "p99_decode_step_ms": 1.0}}

    def _run(self, start, n, on_token):
        emitted = []
        for i in range(n):
            if self.die_after is not None and start + i >= self.die_after:
                self.die_after = None
                return _Handle(None, fail=ServerClosedError("crashed"))
            tok = 100 + start + i
            if on_token is not None:
                on_token(tok)
            emitted.append(tok)
        return _Handle(emitted)

    def submit(self, prompt, max_new_tokens=16, timeout_ms=None,
               on_token=None, **kw):
        if self.submit_errors:
            raise self.submit_errors.pop(0)
        self.submits.append((list(np.asarray(prompt).tolist()),
                             max_new_tokens, timeout_ms, kw.get("seed")))
        return self._run(0, max_new_tokens, on_token)

    def submit_continuation(self, prompt, emitted, max_new_tokens=16,
                            timeout_ms=None, on_token=None, **kw):
        self.continuations.append((list(np.asarray(prompt).tolist()),
                                   list(emitted), max_new_tokens,
                                   timeout_ms, kw.get("seed")))
        return self._run(len(emitted), max_new_tokens - len(emitted),
                         on_token)

    def shutdown(self, drain=True, timeout=None):
        pass


def stub_fleet(servers, **router_kw):
    replicas = [FleetReplica(s.name, server=s) for s in servers]
    router_kw.setdefault("poll_interval_s", 0.0)
    router_kw.setdefault("affinity", False)
    return FleetRouter(replicas, **router_kw), replicas


# ----------------------------------------------------------------------
class TestRequestJournal:
    def test_round_trip_and_monotonic_ids(self, tmp_path):
        j = RequestJournal(tmp_path)
        rid = j.next_request_id()
        j.log_submitted(rid, [1, 2, 3], 8, 500.0,
                        sampling={"temperature": 0.5, "seed": rid})
        for i, t in enumerate([9, 8, 7]):
            j.append_token(rid, 3 + i, t)
        j.flush(rid)
        done = j.next_request_id()
        j.log_submitted(done, [4], 2, None, sampling={})
        j.log_completed(done, 2)
        j.close()

        j2 = RequestJournal(tmp_path)
        inc = j2.incomplete()
        assert list(inc) == [rid]           # completed entry skipped
        assert inc[rid]["emitted"] == [9, 8, 7]
        assert inc[rid]["max_new_tokens"] == 8
        assert inc[rid]["timeout_ms"] == 500.0
        assert inc[rid]["sampling"]["seed"] == rid
        assert j2.next_request_id() == done + 1
        j2.close()

    def test_token_batching_flushes_every_n(self, tmp_path):
        m = DurabilityMetrics()
        j = RequestJournal(tmp_path, flush_every=4, metrics=m)
        rid = j.next_request_id()
        j.log_submitted(rid, [1], 8, None, sampling={})
        for i in range(3):                  # below the batch threshold
            j.append_token(rid, 1 + i, i)
        assert m.counters["journal_records"] == 1   # submitted only
        j.append_token(rid, 4, 3)                   # 4th token: batch out
        assert m.counters["journal_records"] == 2
        j.close()
        j2 = RequestJournal(tmp_path)
        assert j2.incomplete()[rid]["emitted"] == [0, 1, 2, 3]
        j2.close()

    def test_torn_tail_truncated(self, tmp_path):
        m = DurabilityMetrics()
        j = RequestJournal(tmp_path, metrics=m)
        rid = j.next_request_id()
        j.log_submitted(rid, [1, 2], 4, None, sampling={})
        j.append_token(rid, 2, 42)
        j.flush(rid)
        path = j._seg_path(j._seg_index)
        j.close()
        with open(path, "ab") as f:         # a crash mid-append
            f.write(b'{"rec":"tokens","rid":1,"at":3,"toks":[7],'
                    b'"sha":"forged"}\n')
            f.write(b'{"rec":"comp')
        j2 = RequestJournal(tmp_path, metrics=m)
        assert j2.incomplete()[rid]["emitted"] == [42]   # torn tail gone
        assert m.counters["journal_truncated_bytes"] > 0
        # the truncation is durable: a third open sees a clean file
        j2.close()
        j3 = RequestJournal(tmp_path)
        assert j3.incomplete()[rid]["emitted"] == [42]
        j3.close()

    def test_sealed_segment_corruption_raises(self, tmp_path):
        j = RequestJournal(tmp_path)
        rid = j.next_request_id()
        j.log_submitted(rid, [1], 4, None, sampling={})
        sealed = j._seg_path(j._seg_index)
        newer = j._seg_path(j._seg_index + 1)
        j.close()
        # a crash between rotation commit and old-segment unlink leaves
        # the sealed segment behind; sealed bytes were committed through
        # the atomic staging path, so bit-rot there is a storage lie —
        # no torn-tail forgiveness, the journal refuses to open
        open(newer, "wb").close()
        with open(sealed, "r+b") as f:
            f.seek(5)
            f.write(b"X")
        with pytest.raises(JournalCorruptError):
            RequestJournal(tmp_path)

    def test_rotation_compacts_and_drops_terminal(self, tmp_path):
        j = RequestJournal(tmp_path, segment_max_bytes=1)
        keep = j.next_request_id()
        j.log_submitted(keep, [5, 6], 8, None, sampling={"seed": keep})
        j.append_token(keep, 2, 11)
        j.flush(keep)
        gone = j.next_request_id()
        j.log_submitted(gone, [7], 2, None, sampling={})
        j.log_completed(gone, 2)
        segs = j._segments()
        assert len(segs) == 1               # old segments deleted
        j.close()
        j2 = RequestJournal(tmp_path)
        inc = j2.incomplete()
        assert list(inc) == [keep]          # terminal entry reclaimed
        assert inc[keep]["emitted"] == [11]
        assert inc[keep]["sampling"]["seed"] == keep
        assert j2.next_request_id() > gone  # ids survive compaction
        j2.close()

    def test_overlapping_token_replay_is_idempotent(self, tmp_path):
        j = RequestJournal(tmp_path)
        rid = j.next_request_id()
        j.log_submitted(rid, [1, 2], 8, None, sampling={})
        j.append_token(rid, 2, 10)
        j.flush(rid)
        # a batch overlapping what is already durable (e.g. a flush
        # raced by a failover) contributes only its fresh suffix
        with j._lock:
            j._append_locked({"rec": "tokens", "rid": rid, "at": 2,
                              "toks": [10, 11]})
        assert j.entry(rid)["emitted"] == [10, 11]
        j.close()


class TestStreamCursor:
    def test_exactly_once(self):
        m = DurabilityMetrics()
        got = []
        c = StreamCursor(got.append, metrics=m)
        assert c.deliver(0, 5) and c.deliver(1, 6)
        assert not c.deliver(0, 5)          # duplicate absorbed
        assert not c.deliver(1, 6)
        assert got == [5, 6] and c.delivered == [5, 6]
        assert m.counters["dedup_drops"] == 2

    def test_gap_raises(self):
        c = StreamCursor()
        c.deliver(0, 1)
        with pytest.raises(RuntimeError, match="stream gap"):
            c.deliver(2, 3)

    def test_preload_does_not_reinvoke_callback(self):
        got = []
        c = StreamCursor(got.append, preload=[1, 2, 3])
        assert got == []                    # replay: already delivered
        assert c.deliver(3, 4)
        assert got == [4] and c.delivered == [1, 2, 3, 4]


# ----------------------------------------------------------------------
class TestWireKinds:
    @pytest.mark.parametrize("kind", sorted(_WIRE_KINDS))
    def test_every_registered_kind_round_trips(self, kind):
        # FleetUnavailableError (and any future journal/continuation-
        # typed shed) must survive the process boundary with its class
        # and hint intact — the cross-replica retry contract
        cls = _WIRE_KINDS[kind]
        e = cls("gone away", retry_after_s=0.75)
        back = RetryableServingError.from_wire(e.to_wire())
        assert type(back) is cls
        assert back.retry_after_s == 0.75 and str(back) == "gone away"

    def test_fleet_unavailable_is_registered(self):
        assert _WIRE_KINDS["FleetUnavailableError"] is FleetUnavailableError


# ----------------------------------------------------------------------
class TestRouterComposition:
    def test_caller_on_token_composes_with_router_internals(self):
        # the satellite bug: on_token in **kw used to TypeError against
        # the router's internal TTFT lambda
        router, _ = stub_fleet([StreamingStub("a")])
        got = []
        res = router.generate([1, 2], max_new_tokens=4,
                              on_token=got.append)
        assert got == res.tokens == [100, 101, 102, 103]
        assert res.ttft_ms is not None      # internals still measured

    def test_submit_takes_on_token_explicitly(self):
        router, _ = stub_fleet([StreamingStub("a")])
        got = []
        handle, name, retries = router.submit([1, 2], max_new_tokens=3,
                                              on_token=got.append)
        assert handle.result() == got == [100, 101, 102]

    def test_retry_deadline_budget_is_total_not_per_attempt(self):
        t = [0.0]
        sleeps = []

        def clock():
            return t[0]

        def sleep(s):
            sleeps.append(s)
            t[0] += s

        shed = ServerOverloadedError("full", retry_after_s=2.0)
        stub = StreamingStub("a", submit_errors=[shed] * 10)
        router, _ = stub_fleet([stub], retry_budget=8, max_backoff_s=2.0,
                               clock=clock, sleep=sleep)
        with pytest.raises(RequestTimeoutError):
            router.generate([1], max_new_tokens=4, timeout_ms=5000.0)
        # 2 s per backoff against a 5 s budget: the third attempt finds
        # the deadline spent BEFORE touching a replica, not after 8
        # retries × 5 s each
        assert len(sleeps) == 3
        assert router.metrics.counters["requests_timed_out"] == 1
        assert len(stub.submit_errors) == 10 - 3

    def test_attempts_see_shrinking_timeout(self):
        t = [0.0]

        def clock():
            return t[0]

        def sleep(s):
            t[0] += s

        stub = StreamingStub(
            "a", submit_errors=[ServerOverloadedError("full",
                                                      retry_after_s=1.0)])
        router, _ = stub_fleet([stub], retry_budget=2, max_backoff_s=1.0,
                               clock=clock, sleep=sleep)
        router.generate([1], max_new_tokens=2, timeout_ms=10000.0)
        (_, _, timeout, _), = stub.submits
        assert timeout == pytest.approx(9000.0)     # 1 s backoff deducted

    def test_mid_stream_death_resumes_from_emitted_prefix(self):
        a = StreamingStub("a", die_after=3)
        b = StreamingStub("b")
        router, _ = stub_fleet([a, b])
        got = []
        res = router.generate([1, 2, 3, 4], max_new_tokens=8,
                              on_token=got.append, temperature=0.7)
        # exactly-once stream, no restart-induced duplicates
        assert got == res.tokens == [100 + i for i in range(8)]
        assert res.resumes == 1 and res.tokens_salvaged == 3
        assert res.retries == 1
        # the continuation carried the emitted prefix and the PINNED
        # seed (bit-identity across the hop needs the same draws)
        (prompt, emitted, n, _, seed), = b.continuations
        assert prompt == [1, 2, 3, 4] and emitted == [100, 101, 102]
        assert n == 8 and seed is not None
        assert a.submits[0][3] == seed      # same seed both attempts
        assert router.durability.counters["resumes"] == 1
        assert router.durability.counters["tokens_salvaged"] == 3
        assert router.durability.counters["dedup_drops"] == 0

    def test_journal_end_to_end_and_recover_idempotent(self, tmp_path):
        journal = RequestJournal(tmp_path, flush_every=2)
        # crash scenario: the only replica dies mid-stream and the
        # budget is 0 — generate gives up RETRYABLY, so the entry
        # stays open (a permanent failure would be journaled terminal)
        a = StreamingStub("a", die_after=3)
        router, _ = stub_fleet([a], retry_budget=0, journal=journal)
        with pytest.raises(FleetUnavailableError):
            router.generate([1, 2], max_new_tokens=6, temperature=0.5)
        inc = journal.incomplete()
        (rid,) = inc
        assert inc[rid]["emitted"] == [100, 101, 102]   # flushed at death
        seed = inc[rid]["sampling"]["seed"]
        assert seed is not None

        # "restart": a fresh router over a healthy replica replays it
        b = StreamingStub("b")
        router2, _ = stub_fleet([b], journal=journal)
        results = router2.recover()
        assert list(results) == [rid]
        assert results[rid].tokens == [100 + i for i in range(6)]
        (prompt, emitted, n, _, seed2), = b.continuations
        assert (prompt, emitted, n) == ([1, 2], [100, 101, 102], 6)
        assert seed2 == seed                # journal carried the pin
        assert journal.incomplete() == {}   # journaled completed
        assert router2.recover() == {}      # idempotent: nothing open
        assert router2.durability.counters["recovered_requests"] == 1
        assert router2.durability.counters["tokens_salvaged"] >= 3
        journal.close()

    def test_durability_rides_the_fleet_record(self):
        router, _ = stub_fleet([StreamingStub("a")])
        rec = router.metrics.to_record()
        assert rec["type"] == "fleet"
        dur = rec["durability"]
        assert set(dur) >= {"resumes", "tokens_salvaged", "dedup_drops",
                            "journal_fsync_ms"}

    def test_durability_folds_to_gauges_and_renders(self):
        from deeplearning4j_tpu.monitor.registry import MetricsRegistry
        from deeplearning4j_tpu.ui.report import render_report
        from deeplearning4j_tpu.ui.stats import StatsStorage
        router, _ = stub_fleet([StreamingStub("a", die_after=2),
                                StreamingStub("b")])
        router.generate([1], max_new_tokens=4)
        reg = MetricsRegistry()
        reg.fold_fleet(router.metrics.to_record())
        text = reg.to_prometheus_text()
        assert "dl4j_fleet_durability_resumes_total 1" in text
        assert "dl4j_fleet_durability_tokens_salvaged_total 2" in text
        assert "dl4j_fleet_durability_journal_fsync_ms_p99" in text
        storage = StatsStorage()
        router.publish(storage)
        html = render_report(storage)
        assert "durability:" in html and "salvaging" in html


# ----------------------------------------------------------------------
# real-model drills: the acceptance bar

class TestServerContinuation:
    def test_sampled_continuation_requires_seed(self, spec):
        server = make_server(spec)
        try:
            with pytest.raises(ValueError, match="seed"):
                server.submit_continuation([1, 2], [3], max_new_tokens=4,
                                           temperature=0.8)
        finally:
            server.shutdown(drain=False)

    def test_finished_continuation_resolves_without_a_slot(self, spec):
        server = make_server(spec)
        try:
            # budget already spent
            h = server.submit_continuation([1, 2], [5, 6], max_new_tokens=2)
            assert h.result(timeout=1) == []
            # EOS already emitted
            h = server.submit_continuation([1, 2], [5, 7], max_new_tokens=9,
                                           eos_id=7)
            assert h.result(timeout=1) == []
            assert server._n_active() == 0
        finally:
            server.shutdown(drain=False)

    def test_greedy_continuation_is_bit_identical(self, spec, dense_spec):
        ref = greedy_decode(dense_spec, [3, 1, 4, 1], 12, max_seq_len=MSL)
        server = make_server(spec)
        try:
            cut = 5
            out = server.submit_continuation(
                [3, 1, 4, 1], ref[:cut], max_new_tokens=12).result(timeout=60)
            assert ref[:cut] + out == ref
        finally:
            server.shutdown(drain=False)

    def test_continuation_hits_prefix_cache_over_generated_span(
            self, spec, dense_spec):
        server = make_server(spec)
        try:
            prompt = [2, 7, 2, 7]
            full = server.submit(prompt, max_new_tokens=20).result(timeout=60)
            # clean retirement registered the generated span's full
            # blocks: positions = 4 + 20 - 1 = 23 -> 2 full blocks
            before = int(server.metrics.counters["prefix_blocks_hit"])
            out = server.submit_continuation(
                prompt, full, max_new_tokens=24).result(timeout=60)
            hit = int(server.metrics.counters["prefix_blocks_hit"]) - before
            # prompt alone spans 0 full blocks — any hit is generated KV
            assert hit >= 2
            assert full + out == greedy_decode(dense_spec, prompt, 24,
                                               max_seq_len=MSL)
        finally:
            server.shutdown(drain=False)

    def test_abort_fails_inflight_typed(self, spec):
        server = make_server(spec, max_slots=1)
        try:
            first = threading.Event()
            h1 = server.submit([1, 2, 3], max_new_tokens=12,
                               on_token=lambda t: first.set())
            assert first.wait(timeout=60)
            h2 = server.submit([4, 5], max_new_tokens=4)    # queued
            server.abort(timeout=30)
            with pytest.raises(ServerClosedError):
                h1.result(timeout=30)
            with pytest.raises(ServerClosedError):
                h2.result(timeout=30)
            assert len(h1.partial()) >= 1   # emitted tokens stay emitted
        finally:
            server.shutdown(drain=False)


@pytest.mark.chaos
class TestChaosDrills:
    def _drill(self, spec, journal=None, **gen_kw):
        """Kill replica r0 after 5 streamed tokens of a 12-token
        generation; the router resumes on r1. Returns (result,
        streamed, router, replicas)."""
        replicas = [FleetReplica(f"r{i}", server=make_server(spec))
                    for i in range(2)]
        router = FleetRouter(replicas, retry_budget=3,
                             poll_interval_s=0.0, affinity=False,
                             journal=journal)
        chaos = ChaosMonkey(seed=7)
        chaos.kill_mid_stream(replicas[0], after_tokens=5)
        streamed = []
        try:
            res = router.generate([3, 1, 4, 1], max_new_tokens=12,
                                  on_token=streamed.append, **gen_kw)
        finally:
            stop_all(replicas)
        assert chaos.log and chaos.log[0]["event"] == "kill_mid_stream"
        return res, streamed, router

    def test_kill_mid_stream_greedy_bit_identical(self, spec, dense_spec):
        ref = greedy_decode(dense_spec, [3, 1, 4, 1], 12, max_seq_len=MSL)
        res, streamed, router = self._drill(spec)
        # exactly-once: the stream IS the result — no dup, no gap
        assert streamed == res.tokens == ref
        assert res.resumes >= 1 and res.tokens_salvaged >= 5
        assert res.replica == "r1"
        assert router.durability.counters["dedup_drops"] == 0

    def test_kill_mid_stream_sampled_bit_identical(self, spec):
        kw = dict(temperature=0.8, top_k=8, seed=20260807)
        baseline_server = make_server(spec)
        try:
            ref = baseline_server.submit([3, 1, 4, 1], max_new_tokens=12,
                                         **kw).result(timeout=60)
        finally:
            baseline_server.shutdown(drain=False)
        res, streamed, router = self._drill(spec, **kw)
        # the continuation redraws on the same (seed, absolute index)
        # stream — the cross-replica failover is invisible in the output
        assert streamed == res.tokens == ref
        assert res.resumes >= 1 and res.tokens_salvaged >= 5
        assert router.durability.counters["dedup_drops"] == 0

    def test_kill_and_restart_router_replays_journal(self, spec, dense_spec,
                                                     tmp_path):
        ref = greedy_decode(dense_spec, [3, 1, 4, 1], 12, max_seq_len=MSL)
        journal = RequestJournal(tmp_path, flush_every=2)
        # router 1: single replica, zero budget — the mid-stream kill
        # makes generate() give up retryably, which deliberately leaves
        # the journal entry OPEN (that is the router-crash analogue:
        # submitted + partial tokens on disk, no terminal record)
        r0 = FleetReplica("r0", server=make_server(spec))
        router1 = FleetRouter([r0], retry_budget=0, poll_interval_s=0.0,
                              affinity=False, journal=journal)
        chaos = ChaosMonkey(seed=7)
        killer = chaos.kill_mid_stream(r0, after_tokens=5)
        try:
            with pytest.raises(FleetUnavailableError):
                router1.generate([3, 1, 4, 1], max_new_tokens=12)
            assert killer.fired.wait(timeout=60)
        finally:
            stop_all([r0])
        (rid,) = journal.incomplete()
        salvaged = journal.incomplete()[rid]["emitted"]
        assert len(salvaged) >= 4           # flushed at the failover point
        assert salvaged == ref[:len(salvaged)]

        # "restart": a new router + replica adopt the journal and
        # replay the incomplete entry as a continuation, exactly once
        r1 = FleetReplica("r1", server=make_server(spec))
        router2 = FleetRouter([r1], poll_interval_s=0.0, affinity=False)
        try:
            results = router2.recover(journal)
            assert list(results) == [rid]
            assert results[rid].tokens == ref       # bit-identical
            assert router2.durability.counters["tokens_salvaged"] > 0
            assert journal.incomplete() == {}
            assert router2.recover() == {}          # idempotent
        finally:
            stop_all([r1])
        journal.close()
