"""compilecache/ tests: persistent compilation cache wiring, AOT
precompilation (train + serve), and compile observability
(docs/cold_start.md).

The acceptance bars from the subsystem issue:

- cache-hit regression: two fresh SameDiff graphs of the same model
  sharing a cache dir — the second compiles NOTHING (cache-miss count
  0) and its compile spans are marked ``cache_hit``;
- AOT: ``precompile()`` then ``fit`` triggers no new backend compile
  (all window shapes incl. pow2 tails prebuilt), and a warmed
  ``ParallelInference`` serves mixed-size traffic with a zero
  ``compiles`` counter;
- bit-exactness: precompiled and lazily-compiled paths produce
  identical parameters, losses and serving outputs.
"""
import os

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.autodiff import (MixedPrecision, SameDiff,
                                         ScoreIterationListener,
                                         TrainingConfig)
from deeplearning4j_tpu.compilecache import (COMPILE_STATS, AOTDispatch,
                                             install_compile_watcher,
                                             ph_shape_sig)
from deeplearning4j_tpu.environment import environment
from deeplearning4j_tpu.learning.updaters import Adam, Sgd
from deeplearning4j_tpu.monitor import TRACER, disable_tracing, \
    enable_tracing

install_compile_watcher()

N_IN, N_OUT = 16, 4


@pytest.fixture()
def cache_env(tmp_path):
    """A live persistent cache in a tmp dir, wired through Environment
    (exercising the programmatic-set path end to end), torn back down
    after the test."""
    env = environment()
    env.set("compilation_cache_dir", str(tmp_path / "xla_cache"))
    env.set("compilation_cache_min_entry_size", -1)
    env.set("compilation_cache_min_compile_time", 0.0)
    try:
        yield str(tmp_path / "xla_cache")
    finally:
        env.reset("compilation_cache_dir")
        env.reset("compilation_cache_min_entry_size")
        env.reset("compilation_cache_min_compile_time")


def _mlp(seed=0, fused_steps=1, accum_steps=1, sentinel=False, lr=1e-2):
    rng = np.random.default_rng(seed)
    sd = SameDiff()
    x = sd.placeholder("x", shape=(-1, N_IN))
    w0 = sd.var("w0", value=rng.normal(0, 0.1, (N_IN, 8))
                .astype(np.float32))
    b0 = sd.var("b0", value=np.zeros(8, np.float32))
    h = sd.nn.relu(x.mmul(w0).add(b0), name="h")
    w1 = sd.var("w1", value=rng.normal(0, 0.1, (8, N_OUT))
                .astype(np.float32))
    logits = h.mmul(w1, name="logits")
    labels = sd.placeholder("labels", shape=(-1, N_OUT))
    sd.loss.softmax_cross_entropy(logits, labels, name="loss")
    sd.set_loss_variables(["loss"])
    sd.training_config = (TrainingConfig.builder().updater(Adam(lr))
                          .data_set_feature_mapping("x")
                          .data_set_label_mapping("labels")
                          .fused_steps(fused_steps)
                          .accum_steps(accum_steps)
                          .sentinel(sentinel).build())
    return sd


def _data(n=112, batch=8, seed=1):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, N_IN)).astype(np.float32)
    Y = np.eye(N_OUT, dtype=np.float32)[rng.integers(0, N_OUT, n)]
    return [(X[i:i + batch], Y[i:i + batch]) for i in range(0, n, batch)]


def _quiet_listener():
    return ScoreIterationListener(print_every=10 ** 9,
                                  print_fn=lambda *a: None)


def _params(sd):
    return {n: np.asarray(a) for n, a in sd.trainable_params().items()}


# ---------------------------------------------------------------------------
# Environment wiring

def test_cache_dir_set_applies_live_and_reset_undoes(tmp_path):
    env = environment()
    d = str(tmp_path / "cc")
    before = jax.config.jax_compilation_cache_dir
    env.set("compilation_cache_dir", d)
    try:
        assert jax.config.jax_compilation_cache_dir == d
        assert env.compilation_cache_dir() == d
    finally:
        env.reset("compilation_cache_dir")
    assert jax.config.jax_compilation_cache_dir in (before, None)


def test_cache_admission_knobs_apply_live():
    env = environment()
    env.set("compilation_cache_min_entry_size", -1)
    env.set("compilation_cache_min_compile_time", 0.25)
    try:
        assert jax.config.jax_persistent_cache_min_entry_size_bytes == -1
        assert jax.config.jax_persistent_cache_min_compile_time_secs \
            == 0.25
    finally:
        env.reset("compilation_cache_min_entry_size")
        env.reset("compilation_cache_min_compile_time")
    assert jax.config.jax_persistent_cache_min_entry_size_bytes == 0
    assert jax.config.jax_persistent_cache_min_compile_time_secs == 1.0


def test_compile_stats_counts_backend_compiles():
    import jax.numpy as jnp
    mark = COMPILE_STATS.mark()

    @jax.jit
    def fresh(v):
        return jnp.sin(v) * jnp.float32(ord("q"))   # unique-ish program

    fresh(jnp.arange(7, dtype=jnp.float32)).block_until_ready()
    delta = COMPILE_STATS.delta(mark)
    assert delta["backend_compiles"] >= 1
    assert delta["backend_compile_seconds"] > 0.0


# ---------------------------------------------------------------------------
# cache-hit regression: a "restarted" graph recompiles nothing

def test_cache_hit_regression_second_graph_compiles_nothing(cache_env):
    data = _data()
    sd1 = _mlp(fused_steps=4)
    sd1.fit(data, epochs=1, listeners=[_quiet_listener()])

    # a fresh graph of the SAME model = a simulated process restart
    # (fresh jit closures, no in-process executable reuse)
    sd2 = _mlp(fused_steps=4)
    enable_tracing(reset=True)
    mark = COMPILE_STATS.mark()
    try:
        sd2.fit(data, epochs=1, listeners=[_quiet_listener()])
    finally:
        disable_tracing()
    delta = COMPILE_STATS.delta(mark)
    assert delta["cache_misses"] == 0, \
        f"warm restart recompiled: {delta}"
    assert delta["cache_hits"] >= 1
    hits = [s for s in TRACER.spans()
            if s.name == "compile.backend" and s.args.get("cache_hit")]
    assert hits, "no compile.backend span marked cache_hit"


# ---------------------------------------------------------------------------
# AOT precompile: train tiers

def test_precompile_then_windowed_fit_no_new_compiles():
    data = _data()                      # 14 batches: windows 4,4,4 + 2
    sd_warm = _mlp(fused_steps=4)       # warms the eager helper programs
    sd_warm.fit(data, epochs=1, listeners=[_quiet_listener()])

    sd = _mlp(fused_steps=4)
    info = sd.precompile(batch_size=8)
    # window K=4 plus pow2 tail buckets {2, 1} = log2(K)+1 shapes
    assert info["compiled"] == 3
    disp = sd.make_train_window(accum_steps=1)
    assert isinstance(disp, AOTDispatch) and len(disp.aot) == 3
    mark = COMPILE_STATS.mark()
    sd.fit(data, epochs=1, listeners=[_quiet_listener()])
    delta = COMPILE_STATS.delta(mark)
    assert delta["backend_compiles"] == 0, \
        f"fit compiled after precompile: {delta}"
    assert sd.last_fit_stats["window_compiles"] == 0


def test_precompile_non_pow2_window_covers_all_tail_buckets():
    """fused_steps=6, 11 batches → windows 6, then tail 5 = pow2
    buckets [4, 1]: k=4 is NOT in {6} ∪ halvings of 6, so the bucket
    set must be every pow2 ≤ K-1 (regression: the halving-only set
    missed it and the first tail window compiled lazily)."""
    data = _data(n=88, batch=8)         # 11 batches
    warm = _mlp(fused_steps=6)
    warm.fit(data, epochs=1, listeners=[_quiet_listener()])

    sd = _mlp(fused_steps=6)
    info = sd.precompile(batch_size=8)
    assert info["compiled"] == 4        # {6, 4, 2, 1}
    mark = COMPILE_STATS.mark()
    sd.fit(data, epochs=1, listeners=[_quiet_listener()])
    assert COMPILE_STATS.delta(mark)["backend_compiles"] == 0
    assert sd.last_fit_stats["window_compiles"] == 0
    assert sorted(sd.last_fit_stats["window_sizes"]) == [1, 4, 6]


def test_precompile_bit_exact_vs_lazy():
    data = _data()
    lazy = _mlp(fused_steps=4)
    h_lazy = lazy.fit(data, epochs=2, listeners=[_quiet_listener()])
    pre = _mlp(fused_steps=4)
    pre.precompile(batch_size=8)
    h_pre = pre.fit(data, epochs=2, listeners=[_quiet_listener()])
    pl, pp = _params(lazy), _params(pre)
    assert all(np.array_equal(pl[n], pp[n]) for n in pl)
    assert h_lazy.loss_curve.losses == h_pre.loss_curve.losses


def test_precompile_per_step_tier_no_new_compiles():
    data = _data(n=40, batch=8)
    warm = _mlp()
    # warms the eager helper programs (same epochs: the end-of-fit
    # deferred-mean stack shape depends on the epoch count)
    warm.fit(data, epochs=2)
    sd = _mlp()
    info = sd.precompile(batch_size=8)
    assert info["compiled"] == 1        # the per-step train fn
    mark = COMPILE_STATS.mark()
    sd.fit(data, epochs=2)
    assert COMPILE_STATS.delta(mark)["backend_compiles"] == 0


def test_precompile_scanned_epoch_tier():
    from deeplearning4j_tpu.dataset import DeviceCachedIterator
    rng = np.random.default_rng(3)
    n, batch = 32, 8
    X = rng.normal(size=(n, N_IN)).astype(np.float32)
    Y = np.eye(N_OUT, dtype=np.float32)[rng.integers(0, N_OUT, n)]
    it = DeviceCachedIterator(X, Y, batch_size=batch)

    lazy = _mlp()
    h_lazy = lazy.fit(it, epochs=2)

    pre = _mlp()
    info = pre.precompile(batch_size=batch, epoch_steps=n // batch)
    assert info["compiled"] >= 2        # step fn + scanned-epoch fn
    mark = COMPILE_STATS.mark()
    h_pre = pre.fit(it, epochs=2)
    assert COMPILE_STATS.delta(mark)["backend_compiles"] == 0
    pl, pp = _params(lazy), _params(pre)
    assert all(np.array_equal(pl[n_], pp[n_]) for n_ in pl)
    assert h_lazy.loss_curve.losses == h_pre.loss_curve.losses


def test_precompile_unpredicted_shape_falls_back_to_lazy():
    sd = _mlp(fused_steps=4)
    sd.precompile(batch_size=8)
    # a ragged final BATCH (3 rows) nobody precompiled: must still train
    data = _data(n=35, batch=8)         # 4 full batches + one of 3 rows
    h = sd.fit(data, epochs=1, listeners=[_quiet_listener()])
    assert len(h.loss_curve.losses) == 1
    assert np.isfinite(h.loss_curve.losses[0])


def test_aot_dispatch_sharding_mismatch_falls_back_to_lazy():
    # a jax Compiled raises ValueError (not TypeError) when called with
    # mesh-committed inputs against an executable lowered from unsharded
    # specs — the dispatch must degrade to lazy jit, not crash mid-fit
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    fn = jax.jit(lambda ph: {k: v * 2.0 for k, v in ph.items()})
    disp = AOTDispatch(fn, ph_arg=0)
    spec = {"x": jax.ShapeDtypeStruct((8, 4), jnp.float32)}
    disp.aot[ph_shape_sig(spec)] = disp.lower(spec).compile()
    mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
    sharded = jax.device_put(
        np.arange(32, dtype=np.float32).reshape(8, 4),
        NamedSharding(mesh, PartitionSpec("data", None)))
    out = disp({"x": sharded})          # must not raise
    assert np.array_equal(np.asarray(out["x"]),
                          np.arange(32, dtype=np.float32).reshape(8, 4) * 2)


def test_precompile_needs_resolvable_batch_dims():
    sd = _mlp(fused_steps=2)
    with pytest.raises(ValueError, match="batch"):
        sd.precompile()                 # -1 dims and no batch_size


def test_graph_mutation_invalidates_precompiled_programs():
    sd = _mlp(fused_steps=2)
    sd.precompile(batch_size=8)
    assert len(sd.make_train_window(accum_steps=1).aot) > 0
    sd.training_config = sd.training_config     # reassign = mutation
    assert len(sd.make_train_window(accum_steps=1).aot) == 0


# ---------------------------------------------------------------------------
# AOT precompile: serving warmup

def _net(seed=7):
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration,
                                       OutputLayer)
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(Adam(1e-3)).list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=N_OUT, loss_function="MCXENT"))
            .set_input_type(InputType.feed_forward(N_IN))
            .build())
    return MultiLayerNetwork(conf).init()


def test_serving_warmup_mixed_traffic_zero_compiles():
    from deeplearning4j_tpu.serving import InferenceMode, ParallelInference
    net = _net()
    pi = ParallelInference(net, mode=InferenceMode.BATCHED,
                           max_batch_size=8, max_delay_ms=1.0,
                           warmup_buckets=True)
    try:
        assert pi.warmup_report["buckets"] == [1, 2, 4, 8]
        assert pi.metrics.counters["warmup_compiles"] == 4
        rng = np.random.default_rng(0)
        for rows in (1, 3, 5, 8, 2, 7, 4, 6):
            x = rng.normal(size=(rows, N_IN)).astype(np.float32)
            got = np.asarray(pi.output(x))
            want = np.asarray(net.output(x).to_numpy())
            assert np.array_equal(got, want)    # bit-identical to lazy
        assert pi.metrics.counters["compiles"] == 0
        assert "(4 prewarmed)" in pi.metrics.stats()
    finally:
        pi.shutdown()


def test_serving_warmup_explicit_buckets_inplace_mode():
    from deeplearning4j_tpu.serving import InferenceMode, ParallelInference
    net = _net()
    pi = ParallelInference(net, mode=InferenceMode.INPLACE,
                           max_batch_size=16, warmup_buckets=(2, 16))
    assert pi.warmup_report["buckets"] == [2, 16]
    rng = np.random.default_rng(1)
    for rows in (2, 16):
        x = rng.normal(size=(rows, N_IN)).astype(np.float32)
        assert np.array_equal(np.asarray(pi.output(x)),
                              np.asarray(net.output(x).to_numpy()))
    assert pi.metrics.counters["compiles"] == 0
    pi.shutdown()


def test_precompile_output_idempotent():
    sd = _mlp()
    c1 = sd.precompile_output({"x": (4, N_IN)}, outputs=["logits"])
    c2 = sd.precompile_output({"x": (4, N_IN)}, outputs=["logits"])
    assert c1 is c2


# ---------------------------------------------------------------------------
# window executor satellite: sharding specs built once, not per window

def test_window_sharding_spec_construction_hoisted():
    calls = []
    spec = jax.sharding.SingleDeviceSharding(jax.devices()[0])

    class It:
        def window_sharding(self, ndim):
            calls.append(ndim)
            return spec

        def __iter__(self):
            return iter(_data(n=96, batch=8))   # 12 batches → 3 windows

        def reset(self):
            pass

    sd = _mlp(fused_steps=4)
    sd.fit(It(), epochs=2, listeners=[_quiet_listener()])
    # one construction per distinct rank (x is rank 2, labels rank 2 →
    # stacked rank 3), not windows × tensors × epochs
    assert len(calls) == 1, f"window_sharding called {len(calls)} times"


# ---------------------------------------------------------------------------
# faults rail: a retraced retry re-precompiles during recovery

def test_rollback_reprecompiles_after_lr_rescale(tmp_path):
    from deeplearning4j_tpu.checkpoint import CheckpointManager
    from deeplearning4j_tpu.faults import FaultTolerantFit, RetryPolicy
    sd = _mlp(fused_steps=2)
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    ftf = FaultTolerantFit(sd, mgr,
                           policy=RetryPolicy(lr_rescale=0.5,
                                              backoff_base=0.0))
    sd.precompile(batch_size=8)         # after FTF armed the sentinel
    mgr.save(0, model=sd, blocking=True)
    ftf._rollback(RuntimeError("injected"))
    assert any(e["event"] == "precompile" for e in ftf.events)
    # the retraced (rescaled-LR) dispatcher is AOT-warm again
    assert len(sd.make_train_window(accum_steps=1, sentinel=True).aot) > 0
    mgr.close() if hasattr(mgr, "close") else None


# ---------------------------------------------------------------------------
# observability plumbing

def test_compile_record_folds_and_renders():
    from deeplearning4j_tpu.monitor import MetricsRegistry
    from deeplearning4j_tpu.ui.report import render_report
    from deeplearning4j_tpu.ui.stats import StatsStorage
    storage = StatsStorage()
    rec = COMPILE_STATS.publish(storage)
    assert rec["type"] == "compile"
    assert rec["miss_compiles"] == max(
        0, rec["backend_compiles"] - rec["cache_hits"])
    reg = MetricsRegistry()
    reg.fold_storage(storage)
    assert reg.get("compile_backend_compiles_total") == \
        rec["backend_compiles"]
    text = reg.to_prometheus_text()
    assert "dl4j_compile_cache_hits_total" in text
    html = render_report(storage)
    assert "Compilation" in html
    assert "unrendered record types" not in html


def test_monitored_fit_publishes_compile_record():
    """A monitored run surfaces the cache-hit/miss split by itself:
    MonitorListener emits the ``{"type": "compile"}`` record and the
    ``compile_*`` gauges at its epoch cadence — no manual
    ``COMPILE_STATS.publish()`` required."""
    from deeplearning4j_tpu.monitor import MetricsRegistry, MonitorListener
    from deeplearning4j_tpu.ui.stats import StatsStorage
    storage = StatsStorage()
    reg = MetricsRegistry()
    sd = _mlp(fused_steps=4)
    sd.fit(_data(), epochs=1,
           listeners=[MonitorListener(storage, registry=reg),
                      _quiet_listener()])
    recs = storage.of_type("compile")
    assert recs, "monitored fit emitted no compile record"
    snap = COMPILE_STATS.snapshot()
    assert recs[-1]["backend_compiles"] <= snap["backend_compiles"]
    assert reg.get("compile_backend_compiles_total") == \
        recs[-1]["backend_compiles"]


def test_ph_shape_sig_matches_window_accounting():
    import jax.numpy as jnp
    ph = {"b": jnp.zeros((4, 2)), "a": jnp.zeros((4, 3))}
    assert ph_shape_sig(ph) == (("a", (4, 3)), ("b", (4, 2)))


# ---------------------------------------------------------------------------
# the real thing: a fresh-process warm restart (bench.py cold_start child)

@pytest.mark.slow
def test_cold_vs_warm_restart_subprocess(tmp_path):
    import json
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    bench = os.path.join(repo, "bench.py")
    cache_dir = str(tmp_path / "restart_cache")
    runs = {}
    for phase in ("cold", "warm"):
        proc = subprocess.run(
            [sys.executable, bench, "_cold_start_child", "samediff_mlp",
             cache_dir],
            capture_output=True, text=True, timeout=600, cwd=repo,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        assert proc.returncode == 0, proc.stderr[-800:]
        runs[phase] = json.loads(proc.stdout.strip().splitlines()[-1])
    assert runs["cold"]["cache_hits"] == 0
    assert runs["warm"]["cache_hits"] >= 1
    # a warm restart performs ZERO miss compiles — the acceptance bar
    # behind "warm-restart compile time ≈ 0"
    assert runs["warm"]["backend_compiles"] - runs["warm"]["cache_hits"] \
        == 0
    assert runs["warm"]["restart_to_first_step_s"] < \
        runs["cold"]["restart_to_first_step_s"]
