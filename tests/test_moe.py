"""Mixture-of-Experts + expert parallelism tests (new TPU-native
capability — no reference analogue; Switch/GShard recipe with static
capacity-based dispatch). Runs on the virtual 8-device CPU mesh."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.parallel import (
    EXPERT_AXIS, expert_parallel_specs, init_moe_params, moe_ffn,
    moe_train_step, switch_gating)


def _params(rng, d=8, f=16, e=4):
    return init_moe_params(rng, d, f, e)


def test_single_expert_equals_dense_ffn():
    """E=1 with ample capacity must reduce EXACTLY to gate*ffn(x)."""
    rng = np.random.default_rng(0)
    d, f = 8, 16
    p = _params(rng, d, f, e=1)
    x = jnp.asarray(rng.normal(size=(12, d)), jnp.float32)
    y, aux = moe_ffn(x, p["gate_w"], p["w_in"], p["w_out"],
                     capacity_factor=2.0)
    dense = jnp.matmul(jax.nn.gelu(jnp.matmul(x, p["w_in"][0])),
                       p["w_out"][0])
    # top-1 gate prob over a single expert is exactly 1
    np.testing.assert_allclose(np.asarray(y), np.asarray(dense),
                               rtol=1e-5, atol=1e-6)
    assert aux == pytest.approx(1.0)    # E * (1 * 1)


def test_routing_sends_tokens_to_argmax_expert():
    d, e = 4, 3
    gate_w = jnp.eye(d, e)              # token argmax dim -> expert
    x = jnp.asarray(np.eye(d, dtype=np.float32)[[0, 1, 2, 0]]) * 3.0
    dispatch, combine, aux = switch_gating(x, gate_w, capacity=4)
    assigned = np.asarray(dispatch.sum(axis=2).argmax(axis=1))
    np.testing.assert_array_equal(assigned, [0, 1, 2, 0])
    # second token routed to expert 0 takes slot 1
    assert float(dispatch[3, 0, 1]) == 1.0


def test_capacity_overflow_drops_tokens():
    d, e = 4, 2
    gate_w = jnp.zeros((d, e)).at[:, 0].set(1.0)   # everyone -> expert 0
    x = jnp.ones((6, d), jnp.float32)
    dispatch, combine, aux = switch_gating(x, gate_w, capacity=2)
    kept = float(dispatch.sum())
    assert kept == 2.0                  # capacity caps the queue
    # dropped tokens produce zero output rows
    rng = np.random.default_rng(1)
    p = _params(rng, d, 8, e)
    p["gate_w"] = gate_w
    y, _ = moe_ffn(x, p["gate_w"], p["w_in"], p["w_out"],
                   capacity_factor=2 * e / 6.0)    # capacity=2
    assert np.abs(np.asarray(y)[2:]).sum() < np.abs(np.asarray(y)[:2]).sum() \
        or np.allclose(np.asarray(y)[2:], 0)


def test_aux_loss_prefers_balance():
    d, e = 4, 2
    # positive tokens so the collapsed gate really routes EVERY token to
    # expert 0 (a linear gate has no bias; signed inputs would flip it)
    x = jnp.asarray(np.abs(np.random.default_rng(2).normal(size=(32, d))),
                    jnp.float32)
    balanced = jnp.asarray([[4.0, -4], [-4, 4], [4, -4], [-4, 4]],
                           jnp.float32)  # (d=4, e=2), splits tokens
    collapsed = jnp.zeros((d, e)).at[:, 0].set(4.0)
    *_, aux_b = switch_gating(x, balanced, capacity=32)
    *_, aux_c = switch_gating(x, collapsed, capacity=32)
    assert float(aux_c) > float(aux_b)


def test_expert_parallel_matches_single_device():
    """EP over the 8-device CPU mesh: sharded experts, GSPMD all-to-alls
    — numerics equal to the unsharded computation."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    rng = np.random.default_rng(3)
    d, f, e, n = 8, 16, 4, 32
    p = _params(rng, d, f, e)
    x = jnp.asarray(rng.normal(size=(n, d)), jnp.float32)
    y_ref, aux_ref = moe_ffn(x, p["gate_w"], p["w_in"], p["w_out"])

    devs = np.array(jax.devices()[:4]).reshape(4)
    mesh = Mesh(devs, (EXPERT_AXIS,))
    specs = expert_parallel_specs()
    with mesh:
        p_sharded = {
            k: jax.device_put(v, NamedSharding(mesh, specs[k]))
            for k, v in p.items()}
        fn = jax.jit(lambda pp, xx: moe_ffn(
            xx, pp["gate_w"], pp["w_in"], pp["w_out"],
            expert_sharded=True))
        y_ep, aux_ep = fn(p_sharded, x)
    np.testing.assert_allclose(np.asarray(y_ep), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-5)
    assert float(aux_ep) == pytest.approx(float(aux_ref), rel=1e-5)


def test_moe_training_learns_and_shards():
    """A data x expert mesh trains the MoE head; loss decreases and
    numerics match the single-device trajectory."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    rng = np.random.default_rng(4)
    d, f, e, n = 8, 16, 2, 64
    params = _params(rng, d, f, e)
    x = jnp.asarray(rng.normal(size=(n, d)), jnp.float32)
    tgt = jnp.asarray(np.tanh(np.asarray(x) @ rng.normal(size=(d, d))),
                      jnp.float32)

    # single-device trajectory
    p1 = jax.tree_util.tree_map(jnp.copy, params)
    losses = []
    for _ in range(5):
        p1, l = moe_train_step(p1, x, tgt)
        losses.append(float(l))
    assert losses[-1] < losses[0]

    devs = np.array(jax.devices()[:8]).reshape(4, 2)
    mesh = Mesh(devs, ("data", EXPERT_AXIS))
    specs = expert_parallel_specs()
    with mesh:
        p2 = {k: jax.device_put(jnp.copy(v), NamedSharding(mesh, specs[k]))
              for k, v in params.items()}
        xs = jax.device_put(x, NamedSharding(mesh, P("data", None)))
        ts = jax.device_put(tgt, NamedSharding(mesh, P("data", None)))
        step = jax.jit(lambda p, a, b: moe_train_step(
            p, a, b, expert_sharded=True))
        for i in range(5):
            p2, l2 = step(p2, xs, ts)
    assert float(l2) == pytest.approx(losses[-1], rel=1e-4)


def test_grouped_dispatch_matches_ungrouped_at_ample_capacity():
    """GShard-style grouping: with capacity ample enough that no group
    drops tokens, G>1 equals G=1 for a single expert, and runs with the
    (G,S,E,C) dispatch for many experts."""
    rng = np.random.default_rng(6)
    d, f = 8, 16
    p = _params(rng, d, f, e=1)
    x = jnp.asarray(rng.normal(size=(32, d)), jnp.float32)
    y1, _ = moe_ffn(x, p["gate_w"], p["w_in"], p["w_out"],
                    capacity_factor=2.0, n_groups=1)
    y4, _ = moe_ffn(x, p["gate_w"], p["w_in"], p["w_out"],
                    capacity_factor=2.0, n_groups=4)
    np.testing.assert_allclose(np.asarray(y4), np.asarray(y1),
                               rtol=1e-5, atol=1e-6)
    p8 = _params(rng, d, f, e=4)
    y8, aux = moe_ffn(x, p8["gate_w"], p8["w_in"], p8["w_out"],
                      capacity_factor=4.0, n_groups=4)
    assert y8.shape == (32, d) and np.isfinite(float(aux))
    with pytest.raises(ValueError, match="divisible"):
        moe_ffn(x, p["gate_w"], p["w_in"], p["w_out"], n_groups=5)
