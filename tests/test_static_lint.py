"""Repo-level static lint — the PR-8 record-type lint grown into its
own module (ISSUE 12 satellite), on the ``_KNOWN_TYPES`` pattern: every
ban has an explicit exemption table NAMING WHY each exception exists,
so a new violation fails with a decision to make, not a mystery.

Three lints:
1. record types: every ``{"type": ...}`` literal the package publishes
   must be rendered by ui/report (moved here from test_monitor);
2. ``except: pass`` (bare) is banned package-wide — it was the shape
   of the PR-6 silent-latch bugs;
3. traced step-body code paths (ops/, the in-graph tensorstats and
   sentinel builders) must not call wall clocks or unseeded NumPy RNG:
   a ``time.time()`` or ``np.random.*`` inside a traced body is frozen
   at TRACE time into the compiled program — it looks dynamic and is
   silently constant, and it breaks bit-exact resume.
"""
import ast
import pathlib
import re

import deeplearning4j_tpu
from deeplearning4j_tpu.ui import report as report_mod

PKG = pathlib.Path(deeplearning4j_tpu.__file__).resolve().parent


def _iter_sources():
    for py in sorted(PKG.rglob("*.py")):
        rel = str(py.relative_to(PKG))
        yield rel, py.read_text(encoding="utf-8")


# ---------------------------------------------------------------------------
# 1. record-type lint (grown from tests/test_monitor.py, PR 8)

class TestRecordTypeLint:
    def test_every_published_record_type_is_rendered(self):
        """The PR-6 round-5 dead-record bug, made structural: every
        ``{"type": ...}`` literal the package publishes must be a type
        ui/report renders (``_KNOWN_TYPES``) — or be explicitly
        exempted here with a reason, in which case the runtime footer
        still lists it instead of dropping it."""
        # types knowingly left to the forward-compat footer (none
        # today; add entries as "type": "why it is not rendered")
        footer_ok = {}
        published = {}
        pat = re.compile(r'"type":\s*"([a-z_]+)"')
        for rel, text in _iter_sources():
            for m in pat.finditer(text):
                published.setdefault(m.group(1), set()).add(rel)
        assert published, "lint walked no sources"
        # the walk sees both the oldest and the newest record types
        assert "tensorstats" in published
        assert "analysis" in published          # this PR's record
        dead = {t: sorted(files) for t, files in published.items()
                if t not in report_mod._KNOWN_TYPES
                and t not in footer_ok}
        assert not dead, (
            f"record types published but not rendered by ui/report "
            f"(add to _KNOWN_TYPES + a renderer, or exempt with a "
            f"reason): {dead}")


# ---------------------------------------------------------------------------
# 2. bare `except: pass`

#: "relpath::function": "why this bare swallow is acceptable" — none
#: today; every entry must name a reason
BARE_EXCEPT_EXEMPT = {}


def find_bare_except_pass(tree: ast.AST):
    """(funcname, lineno) of every bare ``except:`` whose body is only
    ``pass`` — the construct that silently eats KeyboardInterrupt and
    latch-failures alike."""
    hits = []

    class V(ast.NodeVisitor):
        def __init__(self):
            self.stack = ["<module>"]

        def _visit_func(self, node):
            self.stack.append(node.name)
            self.generic_visit(node)
            self.stack.pop()

        visit_FunctionDef = _visit_func
        visit_AsyncFunctionDef = _visit_func

        def visit_ExceptHandler(self, node):
            if node.type is None and len(node.body) == 1 and \
                    isinstance(node.body[0], ast.Pass):
                hits.append((self.stack[-1], node.lineno))
            self.generic_visit(node)

    V().visit(tree)
    return hits


class TestBareExceptLint:
    def test_no_bare_except_pass_in_package(self):
        violations = []
        n_files = 0
        for rel, text in _iter_sources():
            n_files += 1
            for func, lineno in find_bare_except_pass(ast.parse(text)):
                key = f"{rel}::{func}"
                if key not in BARE_EXCEPT_EXEMPT:
                    violations.append(f"{rel}:{lineno} in {func}")
        assert n_files > 100, "lint walked too few sources"
        assert not violations, (
            f"bare 'except: pass' swallows everything including "
            f"KeyboardInterrupt — catch a type, or exempt with a "
            f"reason in BARE_EXCEPT_EXEMPT: {violations}")

    def test_checker_catches_seeded_violation(self):
        tree = ast.parse(
            "def f():\n"
            "    try:\n"
            "        g()\n"
            "    except:\n"
            "        pass\n"
            "try:\n"
            "    h()\n"
            "except ValueError:\n"
            "    pass\n")
        hits = find_bare_except_pass(tree)
        assert hits == [("f", 4)]     # the typed handler is fine


# ---------------------------------------------------------------------------
# 3. wall clocks / unseeded RNG in traced step-body code paths

#: files whose function bodies are (partially) TRACED into compiled
#: programs: every ops/ body, the in-graph tensorstats summaries, and
#: the sentinel builders. Host-only helpers inside them go in the
#: exemption table below.
TRACED_FILES = ("ops/", "monitor/tensorstats.py", "faults/sentinels.py")

#: "relpath::function::call": "why this call is host-side, not traced"
TRACED_EXEMPT = {
    "monitor/tensorstats.py::build_record::time.time":
        "host-side record builder — runs at listener flush on fetched "
        "numpy values, never inside the traced step",
    "monitor/tensorstats.py::_flag::time.time":
        "LayerHealthWatcher event stamping — a host watcher consuming "
        "records, never traced",
}

_WALLCLOCK = {"time", "perf_counter", "monotonic", "time_ns"}


def find_traced_hazards(tree: ast.AST):
    """(funcname, call, lineno) for wall-clock reads, module-level
    ``np.random.*`` (the unseeded global RNG), and zero-arg
    ``np.random.default_rng()`` (unseeded)."""
    hits = []

    class V(ast.NodeVisitor):
        def __init__(self):
            self.stack = ["<module>"]

        def _visit_func(self, node):
            self.stack.append(node.name)
            self.generic_visit(node)
            self.stack.pop()

        visit_FunctionDef = _visit_func
        visit_AsyncFunctionDef = _visit_func

        def visit_Call(self, node):
            f = node.func
            if isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Name):
                mod, attr = f.value.id, f.attr
                if mod in ("time", "_time") and attr in _WALLCLOCK:
                    hits.append((self.stack[-1], f"time.{attr}",
                                 node.lineno))
            if isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Attribute) and \
                    isinstance(f.value.value, ast.Name) and \
                    f.value.value.id in ("np", "numpy") and \
                    f.value.attr == "random":
                if f.attr == "default_rng":
                    if not node.args and not node.keywords:
                        hits.append((self.stack[-1],
                                     "np.random.default_rng()",
                                     node.lineno))
                else:
                    hits.append((self.stack[-1],
                                 f"np.random.{f.attr}", node.lineno))
            self.generic_visit(node)

    V().visit(tree)
    return hits


class TestTracedPathLint:
    def test_no_wallclock_or_unseeded_rng_in_traced_paths(self):
        violations = []
        n_files = 0
        for rel, text in _iter_sources():
            if not any(rel.startswith(t) if t.endswith("/")
                       else rel == t for t in TRACED_FILES):
                continue
            n_files += 1
            for func, call, lineno in find_traced_hazards(
                    ast.parse(text)):
                key = f"{rel}::{func}::{call}"
                if key not in TRACED_EXEMPT:
                    violations.append(f"{rel}:{lineno} {call} in "
                                      f"{func}")
        assert n_files > 10, "lint walked too few traced sources"
        assert not violations, (
            f"wall clocks / unseeded RNG inside traced step-body code "
            f"freeze at trace time (silently constant in the compiled "
            f"program) and break bit-exact resume — thread a seeded "
            f"key, or exempt host-side helpers with a reason in "
            f"TRACED_EXEMPT: {violations}")

    def test_exemptions_still_exist(self):
        """Every exemption must still point at real code — a stale
        entry means the hazard it excused is gone and the table rots."""
        live = set()
        for rel, text in _iter_sources():
            for func, call, lineno in find_traced_hazards(
                    ast.parse(text)):
                live.add(f"{rel}::{func}::{call}")
        stale = [k for k in TRACED_EXEMPT if k not in live]
        assert not stale, f"stale TRACED_EXEMPT entries: {stale}"

    def test_checker_catches_seeded_violations(self):
        tree = ast.parse(
            "import time\nimport numpy as np\n"
            "def step(x):\n"
            "    t = time.time()\n"
            "    n = np.random.normal(size=3)\n"
            "    r = np.random.default_rng()\n"
            "    ok = np.random.default_rng(0)\n"       # seeded: fine
            "    return x + t + n + r.normal()\n")
        calls = {c for _, c, _ in find_traced_hazards(tree)}
        assert calls == {"time.time", "np.random.normal",
                         "np.random.default_rng()"}


# ---------------------------------------------------------------------------
# 4. span-name lint (ISSUE 20 satellite): every span the package emits
# must be in monitor.trace.SPAN_CATALOG — waterfall assembly
# (monitor/reqtrace.py) and the report's lanes key on these literals,
# so a silent rename would quietly drop a phase from every waterfall.

#: files the span walk skips, with the reason
SPAN_LINT_SKIP = {
    "monitor/trace.py":
        "the tracer machinery itself — SPAN_CATALOG literals and the "
        "module docstring's span() example, not emission sites",
}

#: emission shapes: context-manager spans, pre-timed completions, and
#: the serving tier's _dispatch(disp, io, "<span name>", ...) helper
#: which forwards its third argument to Tracer.span. ``[^,()]+`` keeps
#: each argument match inside one call; ``\s`` spans line breaks.
_SPAN_SITE_PATTERNS = (
    re.compile(r'\.span\(\s*"([a-z_][a-z_.0-9]*)"'),
    re.compile(r'record_completed\(\s*"([a-z_][a-z_.0-9]*)"'),
    re.compile(r'_dispatch\(\s*[^,()]+,\s*[^,()]+,'
               r'\s*"([a-z_][a-z_.0-9]*)"'),
)


def find_span_names(text: str):
    """(span_name, lineno) for every span-emission literal in source
    text, across all three emission shapes."""
    hits = []
    for pat in _SPAN_SITE_PATTERNS:
        for m in pat.finditer(text):
            hits.append((m.group(1), text[:m.start()].count("\n") + 1))
    return hits


class TestSpanNameLint:
    def test_every_emitted_span_is_cataloged(self):
        from deeplearning4j_tpu.monitor.trace import SPAN_CATALOG
        emitted = {}
        n_sites = 0
        for rel, text in _iter_sources():
            if rel in SPAN_LINT_SKIP:
                continue
            for name, lineno in find_span_names(text):
                n_sites += 1
                emitted.setdefault(name, []).append(f"{rel}:{lineno}")
        # the walk sees the oldest (train-tier) and the newest (fleet)
        # emission sites, through all three shapes
        assert n_sites > 25, f"span lint walked too few sites ({n_sites})"
        assert "window" in emitted
        assert "serving.decode" in emitted       # _dispatch shape
        assert "compile.backend" in emitted      # record_completed shape
        assert "fleet.attempt" in emitted        # this PR's span
        rogue = {n: sites for n, sites in emitted.items()
                 if n not in SPAN_CATALOG}
        assert not rogue, (
            f"span names emitted but missing from monitor.trace."
            f"SPAN_CATALOG — waterfall assembly and report lanes key on "
            f"the catalog, so add the name (+ category and arg keys) "
            f"or revert the rename: {rogue}")

    def test_every_cataloged_span_is_emitted(self):
        """The other direction: a catalog entry no source emits is a
        rename that left the catalog behind (assembly would wait for a
        span that never comes)."""
        from deeplearning4j_tpu.monitor.trace import SPAN_CATALOG
        emitted = set()
        for rel, text in _iter_sources():
            if rel in SPAN_LINT_SKIP:
                continue
            emitted.update(n for n, _ in find_span_names(text))
        stale = sorted(set(SPAN_CATALOG) - emitted)
        assert not stale, (
            f"SPAN_CATALOG entries no source emits (stale after a "
            f"rename?): {stale}")

    def test_skip_entries_still_exist(self):
        for rel in SPAN_LINT_SKIP:
            assert (PKG / rel).exists(), f"stale SPAN_LINT_SKIP: {rel}"

    def test_checker_catches_seeded_violation(self):
        text = (
            'with _tracer.span("serving.reply", cat="serving"):\n'
            "    pass\n"
            "_tracer.record_completed(\n"
            '    "compile.trace", cat="compile", dur=1.0)\n'
            "out = self._dispatch(self._decode_disp, io,\n"
            '                     "serving.decode", active=n)\n'
            'with _tracer.span("bogus.name", cat="x"):\n'
            "    pass\n")
        names = {n for n, _ in find_span_names(text)}
        assert names == {"serving.reply", "compile.trace",
                         "serving.decode", "bogus.name"}
        from deeplearning4j_tpu.monitor.trace import SPAN_CATALOG
        assert "bogus.name" not in SPAN_CATALOG
