"""Updater + schedule tests.

Reference parity model: nd4j UpdaterTest / UpdaterValidation (platform-tests)
— closed-form single-step checks per updater, convergence sanity, serde
round-trips.
"""
import numpy as np
import jax.numpy as jnp
import pytest

from deeplearning4j_tpu.learning import (
    Adam, AdaMax, AdaGrad, AdaDelta, AdaBelief, AMSGrad, Nadam, Nesterovs,
    NoOp, RmsProp, Sgd, IUpdater, UPDATERS,
    ExponentialSchedule, FixedSchedule, InverseSchedule, MapSchedule,
    PolySchedule, SigmoidSchedule, StepSchedule, CycleSchedule, RampSchedule,
    ISchedule, L1Regularization, L2Regularization, WeightDecay,
)


def params():
    return {"w": jnp.asarray(np.full((3,), 2.0, np.float32)),
            "b": jnp.asarray(np.full((2,), -1.0, np.float32))}


def grads():
    return {"w": jnp.asarray(np.full((3,), 0.5, np.float32)),
            "b": jnp.asarray(np.full((2,), -0.25, np.float32))}


class TestUpdaterMath:
    def test_sgd(self):
        u = Sgd(learning_rate=0.1)
        st = u.init(params())
        upd, _ = u.apply(grads(), st, 0)
        np.testing.assert_allclose(upd["w"], 0.05, rtol=1e-6)

    def test_noop(self):
        u = NoOp()
        upd, _ = u.apply(grads(), u.init(params()), 0)
        assert float(jnp.abs(upd["w"]).sum()) == 0

    def test_adam_first_step(self):
        # step 1: m=(1-b1)g, v=(1-b2)g^2, alphat=lr*sqrt(1-b2)/(1-b1)
        u = Adam(learning_rate=0.001)
        upd, st = u.apply(grads(), u.init(params()), 0)
        g = 0.5
        m = 0.1 * g
        v = 0.001 * g * g
        alphat = 0.001 * np.sqrt(1 - 0.999) / (1 - 0.9)
        expect = alphat * m / (np.sqrt(v) + 1e-8)
        np.testing.assert_allclose(upd["w"], expect, rtol=1e-5)

    def test_nesterovs_first_step(self):
        u = Nesterovs(learning_rate=0.1, momentum=0.9)
        upd, st = u.apply(grads(), u.init(params()), 0)
        # v' = -lr*g ; update = -(1+mu)*v'
        expect = (1 + 0.9) * 0.1 * 0.5
        np.testing.assert_allclose(upd["w"], expect, rtol=1e-6)

    def test_adagrad_first_step(self):
        u = AdaGrad(learning_rate=0.1)
        upd, _ = u.apply(grads(), u.init(params()), 0)
        expect = 0.1 * 0.5 / (np.sqrt(0.25) + 1e-6)
        np.testing.assert_allclose(upd["w"], expect, rtol=1e-5)

    def test_rmsprop_first_step(self):
        u = RmsProp(learning_rate=0.1)
        upd, _ = u.apply(grads(), u.init(params()), 0)
        r = 0.05 * 0.25
        expect = 0.1 * 0.5 / np.sqrt(r + 1e-8)
        np.testing.assert_allclose(upd["w"], expect, rtol=1e-5)

    def test_amsgrad_monotone_vhat(self):
        u = AMSGrad(learning_rate=0.01)
        st = u.init(params())
        _, st = u.apply(grads(), st, 0)
        big = {k: v * 10 for k, v in grads().items()}
        _, st2 = u.apply(big, st, 1)
        small = {k: v * 0 for k, v in grads().items()}
        _, st3 = u.apply(small, st2, 2)
        # v_hat never decreases
        assert float(st3["w"][2].min()) >= float(st2["w"][2].min()) * 0.999

    @pytest.mark.parametrize("cls", [Adam, AdaMax, Nadam, AMSGrad, AdaBelief,
                                     AdaGrad, RmsProp, Nesterovs, Sgd, AdaDelta])
    def test_convergence_quadratic(self, cls):
        # minimize f(x) = x^2 from x=5 — every updater must reduce |x|
        u = cls(learning_rate=0.1) if cls is not AdaDelta else AdaDelta(rho=0.9)
        x = jnp.asarray([5.0])
        st = u.init(x)
        for i in range(300):
            g = 2 * x
            upd, st = u.apply(g, st, i)
            x = x - upd
        # AdaGrad/AdaDelta are inherently slow from zero state; the gate is
        # monotone progress, not speed
        assert abs(float(x[0])) < 4.0, f"{cls.__name__} did not make progress: {x}"

    def test_state_shapes(self):
        for name, cls in UPDATERS.items():
            u = cls()
            st = u.init(params())
            upd, st2 = u.apply(grads(), st, 0)
            assert jnp.asarray(upd["w"]).shape == (3,), name


class TestSerde:
    def test_updater_roundtrip(self):
        for name, cls in UPDATERS.items():
            u = cls()
            j = u.to_json()
            u2 = IUpdater.from_json(j)
            assert u2 == u, name

    def test_updater_with_schedule_roundtrip(self):
        u = Adam(learning_rate=ExponentialSchedule(initial_value=0.01, gamma=0.9))
        u2 = IUpdater.from_json(u.to_json())
        assert isinstance(u2.learning_rate, ExponentialSchedule)
        assert u2 == u

    def test_schedule_roundtrip(self):
        for s in [FixedSchedule(0.1), ExponentialSchedule(0.1, 0.5),
                  InverseSchedule(0.1, 0.2, 2.0), PolySchedule(0.1, 2.0, 100),
                  SigmoidSchedule(0.1, 0.5, 10), StepSchedule(0.1, 0.5, 10),
                  MapSchedule({0: 0.1, 10: 0.01}),
                  CycleSchedule(1e-4, 1e-2, 100, 10)]:
            s2 = ISchedule.from_json(s.to_json())
            np.testing.assert_allclose(float(s2.value_at(5, 0)), float(s.value_at(5, 0)),
                                       rtol=1e-6)


class TestSchedules:
    def test_fixed(self):
        assert float(FixedSchedule(0.1).value_at(100, 5)) == pytest.approx(0.1)

    def test_exponential(self):
        s = ExponentialSchedule(initial_value=1.0, gamma=0.5)
        assert float(s.value_at(3, 0)) == pytest.approx(0.125)

    def test_step(self):
        s = StepSchedule(initial_value=1.0, decay_rate=0.1, step=10)
        assert float(s.value_at(5, 0)) == pytest.approx(1.0)
        assert float(s.value_at(15, 0)) == pytest.approx(0.1)
        assert float(s.value_at(25, 0)) == pytest.approx(0.01)

    def test_poly(self):
        s = PolySchedule(initial_value=1.0, power=1.0, max_iter=100)
        assert float(s.value_at(50, 0)) == pytest.approx(0.5)
        assert float(s.value_at(100, 0)) == pytest.approx(0.0)

    def test_map(self):
        s = MapSchedule(values={0: 1.0, 10: 0.1, 20: 0.01})
        assert float(s.value_at(0, 0)) == pytest.approx(1.0)
        assert float(s.value_at(12, 0)) == pytest.approx(0.1)
        assert float(s.value_at(30, 0)) == pytest.approx(0.01)

    def test_epoch_type(self):
        s = StepSchedule(initial_value=1.0, decay_rate=0.1, step=2,
                         schedule_type="EPOCH")
        assert float(s.value_at(1000, 1)) == pytest.approx(1.0)
        assert float(s.value_at(0, 3)) == pytest.approx(0.1)

    def test_ramp(self):
        s = RampSchedule(base=FixedSchedule(1.0), num_iter=10)
        assert float(s.value_at(0, 0)) == pytest.approx(0.1)
        assert float(s.value_at(9, 0)) == pytest.approx(1.0)
        assert float(s.value_at(99, 0)) == pytest.approx(1.0)

    def test_cycle_reference_form(self):
        # reference CycleSchedule: stepSize=(100-10)/2=45; annihilation is
        # exponential: initial * decay^(annealingLength-(cycleLength-pos))
        s = CycleSchedule(initial_lr=1e-3, max_lr=1e-2, cycle_length=100,
                          annealing_length=10, annealing_decay=0.1)
        assert float(s.value_at(0, 0)) == pytest.approx(1e-3)
        assert float(s.value_at(45, 0)) == pytest.approx(1e-2)
        assert float(s.value_at(90, 0)) == pytest.approx(1e-3)
        assert float(s.value_at(99, 0)) == pytest.approx(1e-3 * 0.1 ** 9, rel=1e-4)

    def test_map_requires_zero_key(self):
        with pytest.raises(ValueError):
            MapSchedule(values={10: 0.1})
        with pytest.raises(ValueError):
            RampSchedule(base=None)

    def test_updater_hashable(self):
        assert hash(Adam()) == hash(Adam())
        assert len({Adam(), Adam(), Sgd()}) == 2

    def test_schedule_in_updater(self):
        u = Sgd(learning_rate=StepSchedule(initial_value=1.0, decay_rate=0.5, step=10))
        upd0, _ = u.apply(grads(), u.init(params()), 0)
        upd1, _ = u.apply(grads(), u.init(params()), 15)
        np.testing.assert_allclose(upd1["w"], upd0["w"] * 0.5, rtol=1e-6)


class TestRegularization:
    def test_l2(self):
        r = L2Regularization(l2=0.1)
        g = r.apply(jnp.asarray([2.0]), jnp.asarray([0.5]), 0.1)
        np.testing.assert_allclose(g, [0.7], rtol=1e-6)

    def test_l1(self):
        r = L1Regularization(l1=0.1)
        g = r.apply(jnp.asarray([-2.0]), jnp.asarray([0.5]), 0.1)
        np.testing.assert_allclose(g, [0.4], rtol=1e-6)

    def test_weight_decay(self):
        r = WeightDecay(coeff=0.01, apply_lr=True)
        upd = r.apply(jnp.asarray([2.0]), jnp.asarray([0.5]), 0.1)
        np.testing.assert_allclose(upd, [0.502], rtol=1e-6)
