"""Keras import: Conv3D / pooling3D / ConvLSTM2D mappers and shared-layer
functional graphs, against independent numpy implementations of the Keras
semantics (fixtures written as legacy-H5 via h5py; TF unavailable here,
same policy as test_keras_breadth.py)."""
import json
import os

import h5py
import numpy as np
import pytest

from deeplearning4j_tpu.modelimport import (
    import_keras_model_and_weights, import_keras_sequential_model_and_weights)

rng = np.random.RandomState(7)


def _write_seq_h5(path, layers, weights):
    cfg = {"class_name": "Sequential",
           "config": {"name": "seq",
                      "layers": [{"class_name": c, "config": k}
                                 for c, k in layers]}}
    _write(path, cfg, weights)


def _write_func_h5(path, layers, inputs, outputs, weights):
    """layers: (class_name, config, inbound_nodes) with keras-2 style
    inbound [[name, node_idx, 0, {}], ...]."""
    cfg = {"class_name": "Functional",
           "config": {"name": "func",
                      "layers": [{"class_name": c, "config": k,
                                  "name": k["name"], "inbound_nodes": ib}
                                 for c, k, ib in layers],
                      "input_layers": [[n, 0, 0] for n in inputs],
                      "output_layers": [[n, i, 0] for n, i in outputs]}}
    _write(path, cfg, weights)


def _write(path, cfg, weights):
    with h5py.File(path, "w") as f:
        f.attrs["model_config"] = json.dumps(cfg)
        mw = f.create_group("model_weights")
        for lname, ws in weights.items():
            g = mw.create_group(lname)
            names = []
            for wn, arr in ws:
                full = f"{lname}/{wn}:0"
                mw.create_dataset(full, data=np.asarray(arr, np.float32))
                names.append(full.encode())
            g.attrs["weight_names"] = names


def _np_conv3d_valid(x, w, b):
    """x (B,D,H,W,Ci), w (kd,kh,kw,Ci,Co) — VALID, stride 1."""
    B, D, H, W, Ci = x.shape
    kd, kh, kw, _, Co = w.shape
    out = np.zeros((B, D - kd + 1, H - kh + 1, W - kw + 1, Co))
    for d in range(out.shape[1]):
        for i in range(out.shape[2]):
            for j in range(out.shape[3]):
                patch = x[:, d:d + kd, i:i + kh, j:j + kw, :]
                out[:, d, i, j, :] = np.tensordot(
                    patch, w, axes=([1, 2, 3, 4], [0, 1, 2, 3]))
    return out + b


def _np_conv2d_same(x, w):
    """x (B,H,W,Ci), w (kh,kw,Ci,Co) — SAME, stride 1, odd kernels."""
    kh, kw = w.shape[:2]
    ph, pw = kh // 2, kw // 2
    xp = np.pad(x, ((0, 0), (ph, ph), (pw, pw), (0, 0)))
    B, H, W, Ci = x.shape
    out = np.zeros((B, H, W, w.shape[3]))
    for i in range(H):
        for j in range(W):
            patch = xp[:, i:i + kh, j:j + kw, :]
            out[:, i, j, :] = np.tensordot(
                patch, w, axes=([1, 2, 3], [0, 1, 2]))
    return out


def _sigmoid(v):
    return 1.0 / (1.0 + np.exp(-v))


def test_conv3d_import_matches_numpy(tmp_path):
    p = str(tmp_path / "c3d.h5")
    w = rng.normal(size=(2, 2, 2, 2, 3)).astype(np.float32) * 0.3
    b = rng.normal(size=(3,)).astype(np.float32) * 0.1
    _write_seq_h5(p, [
        ("InputLayer", {"batch_input_shape": [None, 3, 5, 5, 2],
                        "dtype": "float32", "name": "input"}),
        ("Conv3D", {"name": "c3", "filters": 3, "kernel_size": [2, 2, 2],
                    "strides": [1, 1, 1], "padding": "valid",
                    "activation": "linear", "use_bias": True,
                    "data_format": "channels_last"}),
    ], {"c3": [("kernel", w), ("bias", b)]})
    net = import_keras_sequential_model_and_weights(p)
    x = rng.normal(size=(2, 3, 5, 5, 2)).astype(np.float32)
    want = _np_conv3d_valid(x, w, b)
    got = net.output(x.transpose(0, 4, 1, 2, 3)).to_numpy()   # NCDHW in
    np.testing.assert_allclose(got, want.transpose(0, 4, 1, 2, 3),
                               atol=1e-4)


def test_pool3d_and_upsampling3d_import(tmp_path):
    p = str(tmp_path / "p3d.h5")
    _write_seq_h5(p, [
        ("InputLayer", {"batch_input_shape": [None, 4, 4, 4, 1],
                        "dtype": "float32", "name": "input"}),
        ("MaxPooling3D", {"name": "mp", "pool_size": [2, 2, 2],
                          "strides": [2, 2, 2], "padding": "valid"}),
        ("UpSampling3D", {"name": "up", "size": [2, 2, 2]}),
    ], {})
    net = import_keras_sequential_model_and_weights(p)
    x = rng.normal(size=(2, 4, 4, 4, 1)).astype(np.float32)
    got = net.output(x.transpose(0, 4, 1, 2, 3)).to_numpy()
    # maxpool 2x2x2 then nearest upsample: every 2-cube holds its max
    blocks = x.reshape(2, 2, 2, 2, 2, 2, 2, 1).max(axis=(2, 4, 6))
    want = np.repeat(np.repeat(np.repeat(
        blocks, 2, axis=1), 2, axis=2), 2, axis=3)
    np.testing.assert_allclose(got, want.transpose(0, 4, 1, 2, 3),
                               atol=1e-5)


def test_conv_lstm2d_import_matches_numpy(tmp_path):
    B, T, H, W, Ci, F = 2, 3, 4, 4, 2, 3
    p = str(tmp_path / "clstm.h5")
    k = rng.normal(size=(3, 3, Ci, 4 * F)).astype(np.float32) * 0.3
    rk = rng.normal(size=(3, 3, F, 4 * F)).astype(np.float32) * 0.3
    b = rng.normal(size=(4 * F,)).astype(np.float32) * 0.1
    _write_seq_h5(p, [
        ("InputLayer", {"batch_input_shape": [None, T, H, W, Ci],
                        "dtype": "float32", "name": "input"}),
        ("ConvLSTM2D", {"name": "cl", "filters": F,
                        "kernel_size": [3, 3], "strides": [1, 1],
                        "padding": "same", "activation": "tanh",
                        "recurrent_activation": "sigmoid",
                        "return_sequences": True, "use_bias": True,
                        "data_format": "channels_last"}),
    ], {"cl": [("kernel", k), ("recurrent_kernel", rk), ("bias", b)]})
    net = import_keras_sequential_model_and_weights(p)
    x = rng.normal(size=(B, T, H, W, Ci)).astype(np.float32)

    # independent numpy ConvLSTM (keras gate order i, f, c, o)
    h = np.zeros((B, H, W, F))
    c = np.zeros((B, H, W, F))
    outs = []
    for t in range(T):
        z = _np_conv2d_same(x[:, t], k) + _np_conv2d_same(h, rk) + b
        i, f, g, o = np.split(z, 4, axis=-1)
        c = _sigmoid(f) * c + _sigmoid(i) * np.tanh(g)
        h = _sigmoid(o) * np.tanh(c)
        outs.append(h)
    want = np.stack(outs, axis=1)                     # (B,T,H,W,F)

    got = net.output(x.transpose(0, 4, 1, 2, 3)).to_numpy()  # NCDHW
    np.testing.assert_allclose(got, want.transpose(0, 4, 1, 2, 3),
                               atol=1e-4)


def test_shared_layer_functional_import(tmp_path):
    """One Dense called twice: h1 = d(x); h2 = d(h1); out = h1 + h2.
    Both call sites must carry the same imported weights."""
    p = str(tmp_path / "shared.h5")
    W = rng.normal(size=(6, 6)).astype(np.float32) * 0.4
    b = rng.normal(size=(6,)).astype(np.float32) * 0.1
    _write_func_h5(
        p,
        [("InputLayer", {"batch_input_shape": [None, 6],
                         "dtype": "float32", "name": "input"}, []),
         ("Dense", {"name": "shared", "units": 6, "activation": "relu",
                    "use_bias": True},
          [[["input", 0, 0, {}]], [["shared", 0, 0, {}]]]),
         ("Add", {"name": "add"},
          [[["shared", 0, 0, {}], ["shared", 1, 0, {}]]])],
        inputs=["input"], outputs=[("add", 0)],
        weights={"shared": [("kernel", W), ("bias", b)]})
    net = import_keras_model_and_weights(p)
    x = rng.normal(size=(3, 6)).astype(np.float32)
    h1 = np.maximum(x @ W + b, 0)
    h2 = np.maximum(h1 @ W + b, 0)
    got = net.output(x)[0].to_numpy()
    np.testing.assert_allclose(got, h1 + h2, atol=1e-5)


def test_shared_layer_into_two_heads(tmp_path):
    """Shared embedding trunk feeding two inputs (siamese pattern):
    out = d(x1) - d(x2) via Subtract."""
    p = str(tmp_path / "siamese.h5")
    W = rng.normal(size=(5, 4)).astype(np.float32) * 0.4
    b = np.zeros(4, np.float32)
    _write_func_h5(
        p,
        [("InputLayer", {"batch_input_shape": [None, 5],
                         "dtype": "float32", "name": "in_a"}, []),
         ("InputLayer", {"batch_input_shape": [None, 5],
                         "dtype": "float32", "name": "in_b"}, []),
         ("Dense", {"name": "emb", "units": 4, "activation": "linear",
                    "use_bias": True},
          [[["in_a", 0, 0, {}]], [["in_b", 0, 0, {}]]]),
         ("Subtract", {"name": "diff"},
          [[["emb", 0, 0, {}], ["emb", 1, 0, {}]]])],
        inputs=["in_a", "in_b"], outputs=[("diff", 0)],
        weights={"emb": [("kernel", W), ("bias", b)]})
    net = import_keras_model_and_weights(p)
    xa = rng.normal(size=(3, 5)).astype(np.float32)
    xb = rng.normal(size=(3, 5)).astype(np.float32)
    got = net.output(xa, xb)[0].to_numpy()
    np.testing.assert_allclose(got, xa @ W - xb @ W, atol=1e-5)


def test_conv_lstm2d_valid_padding_recurrent_same(tmp_path):
    """Regression: input conv VALID must not shrink the hidden state —
    the recurrent conv is always stride-1 SAME."""
    B, T, H, W, Ci, F = 1, 2, 5, 5, 1, 2
    p = str(tmp_path / "clstm_valid.h5")
    k = rng.normal(size=(3, 3, Ci, 4 * F)).astype(np.float32) * 0.3
    rk = rng.normal(size=(3, 3, F, 4 * F)).astype(np.float32) * 0.3
    b = np.zeros(4 * F, np.float32)
    _write_seq_h5(p, [
        ("InputLayer", {"batch_input_shape": [None, T, H, W, Ci],
                        "dtype": "float32", "name": "input"}),
        ("ConvLSTM2D", {"name": "cl", "filters": F,
                        "kernel_size": [3, 3], "strides": [1, 1],
                        "padding": "valid", "activation": "tanh",
                        "recurrent_activation": "sigmoid",
                        "return_sequences": True, "use_bias": True,
                        "data_format": "channels_last"}),
    ], {"cl": [("kernel", k), ("recurrent_kernel", rk), ("bias", b)]})
    net = import_keras_sequential_model_and_weights(p)
    x = rng.normal(size=(B, T, H, W, Ci)).astype(np.float32)
    got = net.output(x.transpose(0, 4, 1, 2, 3)).to_numpy()
    assert got.shape == (B, F, T, H - 2, W - 2)


def test_conv_lstm2d_rejects_dilation(tmp_path):
    p = str(tmp_path / "clstm_dil.h5")
    _write_seq_h5(p, [
        ("InputLayer", {"batch_input_shape": [None, 2, 4, 4, 1],
                        "dtype": "float32", "name": "input"}),
        ("ConvLSTM2D", {"name": "cl", "filters": 2, "kernel_size": [3, 3],
                        "padding": "same", "activation": "tanh",
                        "recurrent_activation": "sigmoid",
                        "dilation_rate": [2, 2], "use_bias": True,
                        "data_format": "channels_last"}),
    ], {"cl": []})
    with pytest.raises(ValueError, match="dilation_rate"):
        import_keras_sequential_model_and_weights(p)


def test_conv_lstm2d_op_direct():
    """Direct op-level exercise of conv_lstm2d + conv_lstm2d_init_state
    (the golden numerics above go through the layer; this pins the op
    names the ledger's EXERCISED pointers reference)."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.ops import registry
    clstm = registry.get_op("conv_lstm2d").fn
    init = registry.get_op("conv_lstm2d_init_state").fn
    x = jnp.asarray(rng.normal(size=(2, 3, 4, 4, 1)), jnp.float32)
    h0 = init(x, units=2, height=4, width=4)
    assert h0.shape == (2, 4, 4, 2)
    out, hT, cT = clstm(x, h0, h0,
                        jnp.ones((3, 3, 1, 8), jnp.float32) * 0.1,
                        jnp.ones((3, 3, 2, 8), jnp.float32) * 0.1,
                        jnp.zeros(8, jnp.float32))
    assert out.shape == (2, 3, 4, 4, 2) and hT.shape == (2, 4, 4, 2)
    np.testing.assert_allclose(np.asarray(out[:, -1]), np.asarray(hT))


def test_noise_layers_import_identity_at_inference(tmp_path):
    """GaussianNoise/GaussianDropout/AlphaDropout/SpatialDropout2D/
    Softmax import; inference output = softmax(x) exactly (noise layers
    are train-only)."""
    p = str(tmp_path / "noise.h5")
    _write_seq_h5(p, [
        ("InputLayer", {"batch_input_shape": [None, 6],
                        "dtype": "float32", "name": "input"}),
        ("GaussianNoise", {"name": "gn", "stddev": 0.5}),
        ("GaussianDropout", {"name": "gd", "rate": 0.3}),
        ("AlphaDropout", {"name": "ad", "rate": 0.1}),
        ("Softmax", {"name": "sm", "axis": -1}),
    ], {})
    net = import_keras_sequential_model_and_weights(p)
    x = rng.normal(size=(3, 6)).astype(np.float32)
    got = net.output(x).to_numpy()
    e = np.exp(x - x.max(1, keepdims=True))
    np.testing.assert_allclose(got, e / e.sum(1, keepdims=True), atol=1e-5)


def test_spatial_dropout_and_cropping3d_import(tmp_path):
    p = str(tmp_path / "sd3.h5")
    _write_seq_h5(p, [
        ("InputLayer", {"batch_input_shape": [None, 4, 6, 6, 1],
                        "dtype": "float32", "name": "input"}),
        ("SpatialDropout3D", {"name": "sd", "rate": 0.2}),
        ("Cropping3D", {"name": "cr", "cropping": [[1, 1], [2, 0],
                                                   [0, 2]]}),
    ], {})
    net = import_keras_sequential_model_and_weights(p)
    x = rng.normal(size=(2, 4, 6, 6, 1)).astype(np.float32)
    got = net.output(x.transpose(0, 4, 1, 2, 3)).to_numpy()   # NCDHW
    want = x[:, 1:3, 2:, :4, :].transpose(0, 4, 1, 2, 3)
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_thresholded_relu_import_and_theta_reject(tmp_path):
    p = str(tmp_path / "tr.h5")
    _write_seq_h5(p, [
        ("InputLayer", {"batch_input_shape": [None, 4],
                        "dtype": "float32", "name": "input"}),
        ("ThresholdedReLU", {"name": "tr", "theta": 1.0}),
    ], {})
    net = import_keras_sequential_model_and_weights(p)
    x = np.array([[0.5, 1.5, -2.0, 3.0]], np.float32)
    np.testing.assert_allclose(net.output(x).to_numpy(),
                               [[0.0, 1.5, 0.0, 3.0]], atol=1e-6)
    p2 = str(tmp_path / "tr2.h5")
    _write_seq_h5(p2, [
        ("InputLayer", {"batch_input_shape": [None, 4],
                        "dtype": "float32", "name": "input"}),
        ("ThresholdedReLU", {"name": "tr", "theta": 0.5}),
    ], {})
    with pytest.raises(ValueError, match="theta"):
        import_keras_sequential_model_and_weights(p2)


def test_noise_layers_active_in_training():
    """Train-time noise actually perturbs activations (train graph),
    while the inference graph passes through."""
    import jax
    from deeplearning4j_tpu.learning.updaters import Sgd
    from deeplearning4j_tpu.nn import (
        GaussianNoiseLayer, InputType, MultiLayerNetwork,
        NeuralNetConfiguration, OutputLayer)
    conf = (NeuralNetConfiguration.builder().seed(0).updater(Sgd(0.0))
            .list()
            .layer(GaussianNoiseLayer(stddev=1.0))
            .layer(OutputLayer(n_out=2, loss_function="MCXENT"))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    X = np.zeros((8, 4), np.float32)
    Y = np.eye(2, dtype=np.float32)[[0, 1] * 4]
    h1 = net.fit(X, Y, epochs=1, batch_size=8)
    h2 = net.fit(X, Y, epochs=1, batch_size=8)
    # lr=0: only the injected noise moves the loss between epochs
    assert h1.loss_curve.losses[0] != h2.loss_curve.losses[0]
    out = net.output(X[:2]).to_numpy()
    np.testing.assert_allclose(out, np.full((2, 2), 0.5), atol=1e-6)


def test_spatial_dropout_op_drops_whole_channels():
    """Direct numeric coverage for the spatial_dropout op (the ledger's
    EXERCISED pointer): whole channels drop together, kept channels
    rescale by 1/p, and training=False is the identity."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.ops import registry
    fn = registry.get_op("spatial_dropout").fn
    x = jnp.ones((2, 4, 4, 8), jnp.float32)
    y = np.asarray(fn(x, p=0.5, seed=0, channel_axis=-1))
    per_channel = y.reshape(2, 16, 8)
    for b in range(2):
        for c in range(8):
            vals = np.unique(per_channel[b, :, c])
            assert len(vals) == 1 and vals[0] in (0.0, 2.0), vals
    assert float(np.asarray(
        fn(x, p=0.5, seed=0, training=False)).sum()) == x.size


def test_dot_merge_import_cosine_similarity(tmp_path):
    """Keras Dot merge (normalize=True -> cosine similarity) imports to
    DotProductVertex and matches numpy."""
    p = str(tmp_path / "dot.h5")
    W = rng.normal(size=(5, 4)).astype(np.float32) * 0.5
    b = np.zeros(4, np.float32)
    _write_func_h5(
        p,
        [("InputLayer", {"batch_input_shape": [None, 5],
                         "dtype": "float32", "name": "in_a"}, []),
         ("InputLayer", {"batch_input_shape": [None, 5],
                         "dtype": "float32", "name": "in_b"}, []),
         ("Dense", {"name": "emb", "units": 4, "activation": "linear",
                    "use_bias": True},
          [[["in_a", 0, 0, {}]], [["in_b", 0, 0, {}]]]),
         ("Dot", {"name": "cos", "axes": -1, "normalize": True},
          [[["emb", 0, 0, {}], ["emb", 1, 0, {}]]])],
        inputs=["in_a", "in_b"], outputs=[("cos", 0)],
        weights={"emb": [("kernel", W), ("bias", b)]})
    net = import_keras_model_and_weights(p)
    xa = rng.normal(size=(3, 5)).astype(np.float32)
    xb = rng.normal(size=(3, 5)).astype(np.float32)
    ea, eb = xa @ W, xb @ W
    want = (np.sum(ea * eb, axis=1)
            / (np.linalg.norm(ea, axis=1) * np.linalg.norm(eb, axis=1)))
    got = net.output(xa, xb)[0].to_numpy()
    np.testing.assert_allclose(got.ravel(), want, atol=1e-5)
    # unsupported axes rejected loudly
    p2 = str(tmp_path / "dot2.h5")
    _write_func_h5(
        p2,
        [("InputLayer", {"batch_input_shape": [None, 5],
                         "dtype": "float32", "name": "in_a"}, []),
         ("InputLayer", {"batch_input_shape": [None, 5],
                         "dtype": "float32", "name": "in_b"}, []),
         ("Dot", {"name": "d", "axes": 0},
          [[["in_a", 0, 0, {}], ["in_b", 0, 0, {}]]])],
        inputs=["in_a", "in_b"], outputs=[("d", 0)], weights={})
    with pytest.raises(ValueError, match="axes"):
        import_keras_model_and_weights(p2)


def test_new_layer_types_serde_roundtrip(tmp_path):
    """Every round-5 layer/vertex type survives config JSON + model zip
    round-trips (LAYER_TYPES/VERTEX_TYPES registration is easy to forget
    and fails only at load time)."""
    import numpy as np
    from deeplearning4j_tpu.learning.updaters import Adam
    from deeplearning4j_tpu.nn import (
        AlphaDropoutLayer, ComputationGraph, Cropping3DLayer, DenseLayer,
        DotProductVertex, GaussianDropoutLayer, GaussianNoiseLayer,
        InputType, MultiLayerNetwork, NeuralNetConfiguration, OutputLayer,
        SpatialDropoutLayer)
    from deeplearning4j_tpu.nn.recurrent_layers import ConvLSTM2DLayer

    conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-3))
            .list()
            .layer(ConvLSTM2DLayer(n_out=2, kernel_size=(3, 3),
                                   return_sequences=True))
            .layer(Cropping3DLayer(cropping=(0, 0, 1, 1, 1, 1)))
            .layer(SpatialDropoutLayer(dropout=0.9))
            .layer(GaussianNoiseLayer(stddev=0.1))
            .layer(GaussianDropoutLayer(rate=0.1))
            .layer(AlphaDropoutLayer(dropout=0.95))
            .layer(OutputLayer(n_out=2, loss_function="MCXENT"))
            .set_input_type(InputType.convolutional3d(3, 6, 6, 1))
            .build())
    net = MultiLayerNetwork(conf).init()
    X = np.random.RandomState(0).rand(2, 1, 3, 6, 6).astype(np.float32)
    Y = np.eye(2, dtype=np.float32)[[0, 1]]
    net.fit(X, Y, epochs=1, batch_size=2)
    p = str(tmp_path / "m.zip")
    net.save(p)
    loaded = MultiLayerNetwork.load(p)
    np.testing.assert_allclose(loaded.output(X).to_numpy(),
                               net.output(X).to_numpy(), atol=1e-6)

    g = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-3))
         .graph_builder().add_inputs("a", "b")
         .set_input_types(InputType.feed_forward(4),
                          InputType.feed_forward(4)))
    g.add_layer("ea", DenseLayer(n_out=3), "a")
    g.add_layer("eb", DenseLayer(n_out=3), "b")
    g.add_vertex("dot", DotProductVertex(normalize=True), "ea", "eb")
    g.add_layer("out", OutputLayer(n_out=2, loss_function="MCXENT"), "dot")
    gnet = ComputationGraph(g.set_outputs("out").build()).init()
    Xa = np.random.RandomState(1).rand(2, 4).astype(np.float32)
    p2 = str(tmp_path / "g.zip")
    gnet.save(p2)
    gl = ComputationGraph.load(p2)
    np.testing.assert_allclose(
        np.asarray(gl.output(Xa, Xa)[0].data),
        np.asarray(gnet.output(Xa, Xa)[0].data), atol=1e-6)
