"""serving/ subsystem tests (reference test model: deeplearning4j
parallelwrapper ParallelInferenceTest — mode coverage, output parity
with the wrapped network, queue behavior under load) plus regression
tests for the satellite fixes that rode along with the subsystem.

The acceptance bar: BATCHED mode with bucketed padding serves 256
mixed-size requests with <= 4 jit compilations (counted by wrapping the
graph-compile entry point) and BIT-identical outputs vs per-request
``MultiLayerNetwork.output()``; overflow/timeout paths raise typed
errors instead of hanging.
"""
import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

from deeplearning4j_tpu.learning.updaters import Adam
from deeplearning4j_tpu.nn import (ComputationGraph, DenseLayer, InputType,
                                   MergeVertex, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.serving import (
    Batch, BucketSpec, DynamicBatcher, InferenceMode, InferenceRequest,
    LatencyHistogram, LoadGenerator, ParallelInference, RequestQueue,
    RequestTimeoutError, ServerClosedError, ServerOverloadedError,
    ServingMetrics, pad_to_bucket, pow2_buckets)
from deeplearning4j_tpu.ui.stats import StatsStorage

N_IN, N_OUT = 8, 3


def _net(seed=7):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(Adam(1e-3)).list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=N_OUT, loss_function="MCXENT"))
            .set_input_type(InputType.feed_forward(N_IN))
            .build())
    return MultiLayerNetwork(conf).init()


def _req(rows=1, deadline=None, seed=0):
    x = np.random.default_rng(seed).normal(size=(rows, N_IN)) \
        .astype(np.float32)
    return InferenceRequest(x=[x], future=Future(), rows=rows,
                           deadline=deadline)


class _CompileCounter:
    """Counting wrapper over the graph-compile entry point: SameDiff
    traces a python fn exactly once per compiled (outputs, shape)
    signature, so counting _trace_fn calls counts jit compilations."""

    def __enter__(self):
        from deeplearning4j_tpu.autodiff.samediff import SameDiff
        self._cls = SameDiff
        self._orig = SameDiff._trace_fn
        self.count = 0
        counter = self

        def wrapper(sd_self, *a, **k):
            counter.count += 1
            return counter._orig(sd_self, *a, **k)

        SameDiff._trace_fn = wrapper
        return self

    def __exit__(self, *exc):
        self._cls._trace_fn = self._orig
        return False


# ---------------------------------------------------------------------------
# acceptance: 256 mixed-size requests, <= 4 compiles, bit-identical


def test_batched_256_mixed_requests_4_compiles_bit_identical():
    net = _net()
    rng = np.random.default_rng(42)
    reqs = [rng.normal(size=(int(rng.integers(1, 9)), N_IN))
            .astype(np.float32) for _ in range(256)]
    pi = ParallelInference(net, mode=InferenceMode.BATCHED, workers=2,
                           max_batch_size=32, max_delay_ms=2.0,
                           max_queue_len=512)
    try:
        with _CompileCounter() as cc:
            futs = [pi.submit(x) for x in reqs]
            outs = [f.result(timeout=60) for f in futs]
        assert cc.count <= 4, f"{cc.count} compiles for 256 requests"
        assert pi.metrics.counters["compiles"] <= 4
        # bit-identical to the per-request direct path
        for x, served in zip(reqs, outs):
            direct = net.output(x).to_numpy()
            assert served.shape == direct.shape
            assert np.array_equal(served, direct), \
                "served output differs from direct output()"
        assert pi.metrics.counters["requests_served"] == 256
        assert pi.metrics.counters["rows_served"] == \
            sum(r.shape[0] for r in reqs)
    finally:
        pi.shutdown()


def test_sequential_mode_parity():
    net = _net()
    rng = np.random.default_rng(1)
    pi = ParallelInference(net, mode=InferenceMode.SEQUENTIAL, workers=2)
    try:
        xs = [rng.normal(size=(n, N_IN)).astype(np.float32)
              for n in (1, 5, 3)]
        outs = [pi.output(x) for x in xs]
        for x, o in zip(xs, outs):
            assert np.array_equal(o, net.output(x).to_numpy())
    finally:
        pi.shutdown()


def test_inplace_mode_parity_and_single_example():
    net = _net()
    x = np.random.default_rng(2).normal(size=(4, N_IN)).astype(np.float32)
    with ParallelInference(net, mode=InferenceMode.INPLACE) as pi:
        assert np.array_equal(pi.output(x), net.output(x).to_numpy())
        # unbatched single example: row dim added then squeezed back
        one = pi.output(x[0])
        assert one.shape == (N_OUT,)
        assert np.array_equal(one, net.output(x[:1]).to_numpy()[0])


def test_computation_graph_served():
    conf = (NeuralNetConfiguration.builder().seed(5)
            .updater(Adam(1e-3)).graph_builder()
            .add_inputs("inA", "inB")
            .set_input_types(InputType.feed_forward(3),
                             InputType.feed_forward(2))
            .add_layer("dA", DenseLayer(n_out=8, activation="tanh"), "inA")
            .add_layer("dB", DenseLayer(n_out=8, activation="tanh"), "inB")
            .add_vertex("merge", MergeVertex(), "dA", "dB")
            .add_layer("out", OutputLayer(n_out=2), "merge")
            .set_outputs("out").build())
    net = ComputationGraph(conf).init()
    rng = np.random.default_rng(0)
    a = rng.normal(size=(4, 3)).astype(np.float32)
    b = rng.normal(size=(4, 2)).astype(np.float32)
    # multi-input graphs serve in SEQUENTIAL mode (tuple submit)
    with ParallelInference(net, mode=InferenceMode.SEQUENTIAL) as pi:
        served = pi.output((a, b))
    direct = net.output(a, b)[0].to_numpy()
    assert np.array_equal(served, direct)
    # BATCHED refuses multi-input models with a clear error
    with pytest.raises(ValueError, match="single-input"):
        ParallelInference(net, mode=InferenceMode.BATCHED)


def test_inplace_rejects_timeout_and_uninit_graph_is_guarded():
    net = _net()
    with ParallelInference(net, mode=InferenceMode.INPLACE) as pi:
        with pytest.raises(ValueError, match="no queue"):
            pi.output(np.zeros((1, N_IN), np.float32), timeout_ms=5)
    with pytest.raises(ValueError, match="no queue wait"):
        ParallelInference(net, mode=InferenceMode.INPLACE,
                          default_timeout_ms=5)
    # serving an uninitialized network fails with a clear message
    conf = (NeuralNetConfiguration.builder().seed(5)
            .updater(Adam(1e-3)).graph_builder()
            .add_inputs("in")
            .set_input_types(InputType.feed_forward(3))
            .add_layer("out", OutputLayer(n_out=2), "in")
            .set_outputs("out").build())
    with pytest.raises(RuntimeError, match="init"):
        ParallelInference(ComputationGraph(conf))


def test_update_model_pulls_new_params():
    net = _net()
    rng = np.random.default_rng(3)
    x = rng.normal(size=(4, N_IN)).astype(np.float32)
    with ParallelInference(net, mode=InferenceMode.INPLACE) as pi:
        before = pi.output(x)
        X = rng.normal(size=(64, N_IN)).astype(np.float32)
        Y = np.eye(N_OUT, dtype=np.float32)[
            rng.integers(0, N_OUT, size=64)]
        net.fit(X, Y, epochs=1, batch_size=32)
        pi.update_model()
        after = pi.output(x)
        assert not np.array_equal(before, after)
        assert np.array_equal(after, net.output(x).to_numpy())


# ---------------------------------------------------------------------------
# queue: backpressure, deadlines, drain


def test_queue_backpressure_overflow_is_typed():
    q = RequestQueue(max_queue_len=2)
    q.put(_req())
    q.put(_req())
    with pytest.raises(ServerOverloadedError):
        q.put(_req())


def test_queue_take_budget_and_strict():
    q = RequestQueue(8)
    for s in (3, 3, 3):
        q.put(_req(rows=s))
    got = q.take(max_rows=8, timeout=0, strict=True)
    assert [r.rows for r in got] == [3, 3]       # third would overshoot
    # non-strict lets an oversize head through alone
    q2 = RequestQueue(8)
    q2.put(_req(rows=5))
    got = q2.take(max_rows=1, timeout=0)
    assert [r.rows for r in got] == [5]
    # strict never pops an oversize head
    q3 = RequestQueue(8)
    q3.put(_req(rows=5))
    assert q3.take(max_rows=2, timeout=0, strict=True) == []


def test_queue_deadline_expires_at_dispatch():
    q = RequestQueue(8)
    dead = _req(rows=1, deadline=time.monotonic() - 0.001)
    live = _req(rows=1)
    q.put(dead)
    q.put(live)
    got = q.take(max_rows=4, timeout=0)
    assert got == [live]
    with pytest.raises(RequestTimeoutError):
        dead.future.result(timeout=0)
    assert q.timed_out_count() == 1


def test_queue_close_without_drain_fails_pending():
    q = RequestQueue(8)
    r = _req()
    q.put(r)
    q.close(drain=False)
    with pytest.raises(ServerClosedError):
        r.future.result(timeout=0)
    with pytest.raises(ServerClosedError):
        q.put(_req())


def test_server_backpressure_rejection():
    net = _net()
    gate = threading.Event()
    pi = ParallelInference(net, mode=InferenceMode.BATCHED, workers=1,
                           max_batch_size=1, buckets=(1,), max_queue_len=2,
                           max_delay_ms=0.5)
    orig = pi._execute
    pi._execute = lambda *a, **k: (gate.wait(10), orig(*a, **k))[1]
    try:
        first = pi.submit(np.zeros((1, N_IN), np.float32))
        deadline = time.monotonic() + 5
        while pi._queue.pending() and time.monotonic() < deadline:
            time.sleep(0.005)        # worker picks up the first request
        pi.submit(np.zeros((1, N_IN), np.float32))
        pi.submit(np.zeros((1, N_IN), np.float32))
        with pytest.raises(ServerOverloadedError):
            pi.submit(np.zeros((1, N_IN), np.float32))
        assert pi.metrics.counters["requests_rejected"] == 1
    finally:
        gate.set()
        pi.shutdown()
    assert first.result(timeout=10) is not None


def test_server_deadline_expiry_typed_not_hanging():
    net = _net()
    gate = threading.Event()
    pi = ParallelInference(net, mode=InferenceMode.BATCHED, workers=1,
                           max_batch_size=1, buckets=(1,), max_queue_len=8,
                           max_delay_ms=0.5)
    orig = pi._execute
    pi._execute = lambda *a, **k: (gate.wait(10), orig(*a, **k))[1]
    try:
        pi.submit(np.zeros((1, N_IN), np.float32))      # occupies the worker
        deadline = time.monotonic() + 5
        while pi._queue.pending() and time.monotonic() < deadline:
            time.sleep(0.005)
        doomed = pi.submit(np.zeros((1, N_IN), np.float32), timeout_ms=20)
        time.sleep(0.05)                                # deadline passes
        gate.set()
        with pytest.raises(RequestTimeoutError):
            doomed.result(timeout=10)
        assert pi.metrics.counters["requests_timed_out"] == 1
    finally:
        gate.set()
        pi.shutdown()


def test_drain_on_shutdown_serves_queued_work():
    net = _net()
    rng = np.random.default_rng(9)
    pi = ParallelInference(net, mode=InferenceMode.BATCHED, workers=2,
                           max_batch_size=16, max_delay_ms=1.0,
                           max_queue_len=128)
    xs = [rng.normal(size=(2, N_IN)).astype(np.float32) for _ in range(40)]
    futs = [pi.submit(x) for x in xs]
    pi.shutdown(drain=True)
    for x, f in zip(xs, futs):
        assert np.array_equal(f.result(timeout=0), net.output(x).to_numpy())
    with pytest.raises(ServerClosedError):
        pi.submit(xs[0])


def test_shutdown_without_drain_fails_pending():
    net = _net()
    gate = threading.Event()
    pi = ParallelInference(net, mode=InferenceMode.BATCHED, workers=1,
                           max_batch_size=1, buckets=(1,), max_queue_len=8,
                           max_delay_ms=0.5)
    orig = pi._execute
    pi._execute = lambda *a, **k: (gate.wait(10), orig(*a, **k))[1]
    pi.submit(np.zeros((1, N_IN), np.float32))
    deadline = time.monotonic() + 5
    while pi._queue.pending() and time.monotonic() < deadline:
        time.sleep(0.005)
    pending = pi.submit(np.zeros((1, N_IN), np.float32))
    gate.set()
    pi.shutdown(drain=False)
    with pytest.raises(ServerClosedError):
        pending.result(timeout=10)


# ---------------------------------------------------------------------------
# batcher + buckets


def test_pow2_buckets():
    assert pow2_buckets(32) == (4, 8, 16, 32)
    assert pow2_buckets(8, n_buckets=2) == (4, 8)
    assert pow2_buckets(1) == (1,)


def test_bucket_spec_rounds_up():
    spec = BucketSpec((4, 8, 16, 32))
    assert spec.bucket_for(1) == 4
    assert spec.bucket_for(4) == 4
    assert spec.bucket_for(5) == 8
    assert spec.bucket_for(32) == 32
    with pytest.raises(ValueError):
        spec.bucket_for(33)


def test_pad_to_bucket_zero_pads():
    a = np.ones((3, 2), np.float32)
    b = np.full((2, 2), 2.0, np.float32)
    out = pad_to_bucket([a, b], 8)
    assert out.shape == (8, 2)
    np.testing.assert_array_equal(out[:3], a)
    np.testing.assert_array_equal(out[3:5], b)
    np.testing.assert_array_equal(out[5:], 0.0)


def test_batcher_coalesces_and_pads():
    q = RequestQueue(16)
    for i in range(5):
        q.put(_req(rows=3, seed=i))
    batcher = DynamicBatcher(q, max_batch_size=8, max_delay_ms=1.0,
                             buckets=(4, 8))
    batch = batcher.next_batch(poll_timeout=0.5)
    assert isinstance(batch, Batch)
    assert len(batch.requests) == 2         # 3+3 rows; a third overshoots
    assert batch.rows == 6
    assert batch.bucket == 8
    assert batch.padding == 2
    assert batch.features.shape == (8, N_IN)
    np.testing.assert_array_equal(batch.features[6:], 0.0)


def test_batch_resolve_scatters_rows():
    reqs = [_req(rows=2, seed=0), _req(rows=3, seed=1)]
    batch = Batch(requests=reqs,
                  features=np.zeros((8, N_IN), np.float32), rows=5,
                  bucket=8)
    out = np.arange(8 * N_OUT, dtype=np.float32).reshape(8, N_OUT)
    batch.resolve([out])
    np.testing.assert_array_equal(reqs[0].future.result(timeout=0), out[:2])
    np.testing.assert_array_equal(reqs[1].future.result(timeout=0), out[2:5])


# ---------------------------------------------------------------------------
# metrics


def test_latency_histogram_percentiles():
    h = LatencyHistogram()
    for ms in (1.0, 2.0, 3.0, 100.0):
        h.record(ms)
    assert h.count == 4
    assert h.percentile(50) <= h.percentile(95) <= h.percentile(99)
    assert h.percentile(99) <= h.max_ms
    assert h.mean() == pytest.approx(26.5)
    s = h.summary()
    assert set(s) == {"count", "low_sample", "mean", "p50", "p95", "p99",
                      "max"}
    assert s["low_sample"] is True      # 4 samples: tails are suspect


def test_metrics_record_through_stats_storage(tmp_path):
    net = _net()
    st = StatsStorage(str(tmp_path / "serving.jsonl"))
    pi = ParallelInference(net, mode=InferenceMode.BATCHED,
                           max_delay_ms=1.0, stats_storage=st)
    xs = np.random.default_rng(0).normal(size=(6, 4, N_IN)) \
        .astype(np.float32)
    for x in xs:
        pi.output(x)
    pi.shutdown()                   # publishes the final snapshot
    recs = st.of_type("serving")
    assert len(recs) == 1
    rec = recs[0]
    assert rec["counters"]["requests_served"] == 6
    assert rec["counters"]["rows_served"] == 24
    for fam in ("queue_wait", "e2e", "exec"):
        assert rec["latency_ms"][fam]["count"] > 0
        assert rec["latency_ms"][fam]["p99"] >= rec["latency_ms"][fam]["p50"]
    assert 0.0 <= rec["batch"]["padding_waste"] < 1.0
    # round-trips through the JSONL file like any other stats record
    loaded = StatsStorage.load(str(tmp_path / "serving.jsonl"))
    assert loaded.of_type("serving")[0]["counters"]["requests_served"] == 6
    assert "ServingMetrics" in pi.metrics.stats()


def test_padding_waste_accounting():
    m = ServingMetrics()
    m.observe_batch(rows=6, padding=2, exec_ms=1.0)
    m.observe_batch(rows=8, padding=0, exec_ms=1.0)
    assert m.padding_waste() == pytest.approx(2 / 16)
    assert m.mean_batch_size() == pytest.approx(7.0)


# ---------------------------------------------------------------------------
# load generator


def test_loadgen_closed_loop():
    net = _net()
    with ParallelInference(net, mode=InferenceMode.BATCHED,
                           max_delay_ms=1.0, max_queue_len=64) as pi:
        lg = LoadGenerator(
            pi, lambda rng, i: rng.normal(size=(2, N_IN))
            .astype(np.float32), seed=0)
        res = lg.run_closed(n_requests=24, concurrency=3)
    assert res.n_ok == 24 and res.n_issued == 24
    assert res.throughput_rps > 0
    assert len(res.latencies_ms) == 24
    assert res.percentile(50) <= res.percentile(99)
    assert "LoadResult" in res.stats()


def test_loadgen_open_loop():
    net = _net()
    with ParallelInference(net, mode=InferenceMode.BATCHED,
                           max_delay_ms=1.0, max_queue_len=64) as pi:
        lg = LoadGenerator(
            pi, lambda rng, i: rng.normal(size=(1, N_IN))
            .astype(np.float32), seed=1)
        res = lg.run_open(n_requests=16, rate_rps=400.0)
    assert res.n_ok + res.n_rejected + res.n_timed_out == 16
    assert res.n_ok > 0


# ---------------------------------------------------------------------------
# satellite regressions


def test_calibration_per_class_bins_only_label_column():
    """evaluation/calibration.py: residualPlotByLabelClass counts ONE
    entry per row (the label column), not C (satellite fix)."""
    from deeplearning4j_tpu.evaluation.calibration import (
        EvaluationCalibration)
    ec = EvaluationCalibration(histogram_bins=10)
    preds = np.array([[0.95, 0.05],      # label 0: residual col0 = 0.05
                      [0.30, 0.70],      # label 1: residual col1 = 0.30
                      [0.55, 0.45]])     # label 0: residual col0 = 0.45
    ec.eval(np.array([0, 1, 0]), preds)
    h0 = ec.residual_plot(0)
    assert h0.bin_counts.sum() == 2              # 2 rows labeled 0 -> 2
    assert h0.bin_counts[0] == 1                 # 0.05 -> bin 0
    assert h0.bin_counts[4] == 1                 # 0.45 -> bin 4
    h1 = ec.residual_plot(1)
    assert h1.bin_counts.sum() == 1
    assert h1.bin_counts[3] == 1                 # 0.30 -> bin 3
    p0 = ec.probability_histogram(0)
    assert p0.bin_counts.sum() == 2              # cols 0 of rows labeled 0
    assert p0.bin_counts[9] == 1                 # p=0.95
    assert p0.bin_counts[5] == 1                 # p=0.55
    # all-classes histograms still count every (row, class) entry
    assert ec.residual_plot_all_classes().bin_counts.sum() == 6


def test_fastcsv_io_vs_bad_cell_row0_disambiguated(tmp_path):
    """native/fastcsv: I/O failure (CSV_EIO) no longer collides with
    'bad cell at data row 0' (satellite fix)."""
    from deeplearning4j_tpu.native import native_available
    from deeplearning4j_tpu.native.fastcsv import CSV_EIO, read_csv_f32
    if not native_available("fastcsv"):
        pytest.skip("no C++ toolchain")
    p = tmp_path / "bad0.csv"
    p.write_text("oops,2\n3,4\n")
    with pytest.raises(ValueError, match="non-numeric cell at data row 0"):
        read_csv_f32(str(p))
    with pytest.raises(ValueError, match="cannot read"):
        read_csv_f32(str(tmp_path / "does_not_exist.csv"))
    # the raw ABI: bad cell at row r returns -(r+2), I/O returns INT_MIN
    import ctypes
    from deeplearning4j_tpu.native.build import load
    lib = load("fastcsv")
    out = np.empty((2, 2), np.float32)
    rc = lib.csv_parse_f32(str(p).encode(), b",", 0,
                           out.ctypes.data_as(
                               ctypes.POINTER(ctypes.c_float)), 2, 2)
    assert rc == -2                               # row 0 -> -(0+2)
    rc = lib.csv_parse_f32(b"/nonexistent/x.csv", b",", 0,
                           out.ctypes.data_as(
                               ctypes.POINTER(ctypes.c_float)), 2, 2)
    assert rc == CSV_EIO


def test_best_score_termination_is_strict():
    """autodiff/earlystopping: reaching the target exactly does NOT
    terminate; beating it does (satellite fix)."""
    from deeplearning4j_tpu.autodiff.earlystopping import (
        BestScoreEpochTerminationCondition)
    cond = BestScoreEpochTerminationCondition(0.5)
    assert not cond.terminate(0, 0.5, False)      # equal: keep training
    assert not cond.terminate(0, 0.6, False)
    assert cond.terminate(0, 0.499, True)         # strictly better: stop


def test_submit_rejects_wrong_feature_shape():
    """A mismatched request must die at admission with ValueError, not
    poison a coalesced batch (which would strand other futures)."""
    net = _net()
    with ParallelInference(net, mode=InferenceMode.BATCHED,
                           max_delay_ms=1.0) as pi:
        with pytest.raises(ValueError, match="expects shape"):
            pi.submit(np.zeros((2, N_IN + 1), np.float32))
        # well-formed traffic still serves afterwards
        x = np.zeros((2, N_IN), np.float32)
        assert np.array_equal(pi.output(x), net.output(x).to_numpy())


def test_timeout_callback_may_reenter_queue_without_deadlock():
    """Futures complete OUTSIDE the queue lock: a done-callback that
    re-submits (retry pattern) must not deadlock the worker."""
    net = _net()
    gate = threading.Event()
    pi = ParallelInference(net, mode=InferenceMode.BATCHED, workers=1,
                           max_batch_size=1, buckets=(1,), max_queue_len=8,
                           max_delay_ms=0.5)
    orig = pi._execute
    pi._execute = lambda *a, **k: (gate.wait(10), orig(*a, **k))[1]
    retried = []
    try:
        pi.submit(np.zeros((1, N_IN), np.float32))   # occupies the worker
        deadline = time.monotonic() + 5
        while pi._queue.pending() and time.monotonic() < deadline:
            time.sleep(0.005)
        doomed = pi.submit(np.zeros((1, N_IN), np.float32), timeout_ms=20)
        doomed.add_done_callback(
            lambda f: retried.append(
                pi.submit(np.zeros((1, N_IN), np.float32))))
        time.sleep(0.05)
        gate.set()
        with pytest.raises(RequestTimeoutError):
            doomed.result(timeout=10)
        assert len(retried) == 1
        assert retried[0].result(timeout=10) is not None
    finally:
        gate.set()
        pi.shutdown()


def test_switch_gating_positions_accumulate_in_int32():
    """parallel/moe: queue positions come from an int32 cumsum (exact at
    any token count), not float32 (satellite fix)."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.parallel import switch_gating
    x = jnp.zeros((16, 4), jnp.float32)
    w = jnp.zeros((4, 2), jnp.float32)
    jaxpr = str(jax.make_jaxpr(
        lambda x, w: switch_gating(x, w, capacity=4))(x, w))
    cumsum_lines = [ln for ln in jaxpr.splitlines() if "cumsum" in ln]
    assert cumsum_lines, "cumsum disappeared from switch_gating"
    assert all("f32" not in ln for ln in cumsum_lines), \
        f"float cumsum in switch_gating: {cumsum_lines}"
    # capacity enforcement stays exact: all tokens to one expert, cap 4
    gate_w = jnp.asarray(np.array([[10.0, -10.0]] * 4, np.float32))
    ones = jnp.asarray(np.ones((16, 4), np.float32))
    dispatch, combine, _ = switch_gating(ones, gate_w, capacity=4)
    assert float(jnp.sum(dispatch)) == 4.0        # first 4 kept, 12 dropped
    # kept tokens are the FIRST four in arrival order
    np.testing.assert_array_equal(
        np.asarray(jnp.sum(dispatch, axis=(1, 2))),
        np.array([1, 1, 1, 1] + [0] * 12, np.float32))
