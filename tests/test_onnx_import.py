"""ONNX import conformance: builder round-trip + numpy golden outputs.

Same methodology as the TF importer tests: real serialized ModelProto
bytes via the in-tree wire encoder (no onnx install needed), imported and
compared against independent numpy references.
"""
import numpy as np
import pytest

from deeplearning4j_tpu.modelimport.onnx_import import (
    OnnxImportError, import_onnx_model, supported_onnx_ops)
from deeplearning4j_tpu.modelimport.onnx_pb import OnnxModel, OnnxModelBuilder

rng = np.random.RandomState(0)


def _run(model_bytes, feeds, outputs, **kw):
    sd = import_onnx_model(model_bytes, **kw)
    res = sd.output(placeholders=feeds, outputs=outputs)
    return {k: np.asarray(v.data) for k, v in res.items()}


def test_wire_roundtrip():
    b = OnnxModelBuilder()
    b.input("x", [-1, 4])
    b.initializer("W", rng.randn(4, 3).astype(np.float32))
    b.node("MatMul", ["x", "W"], ["y"])
    b.output("y", [-1, 3])
    m = OnnxModel(b.build())
    assert [n.op_type for n in m.graph.nodes] == ["MatMul"]
    assert list(m.graph.initializers) == ["W"]
    assert m.graph.inputs[0][0] == "x"
    assert m.graph.inputs[0][2] == [-1, 4]


def test_mlp_gemm_relu_softmax():
    W1 = rng.randn(4, 8).astype(np.float32)
    b1 = rng.randn(8).astype(np.float32)
    W2 = rng.randn(8, 3).astype(np.float32)
    b2 = rng.randn(3).astype(np.float32)
    b = OnnxModelBuilder()
    b.input("x", [-1, 4])
    b.initializer("W1", W1).initializer("b1", b1)
    b.initializer("W2", W2).initializer("b2", b2)
    b.node("Gemm", ["x", "W1", "b1"], ["h"], alpha=1.0, beta=1.0)
    b.node("Relu", ["h"], ["hr"])
    b.node("Gemm", ["hr", "W2", "b2"], ["logits"])
    b.node("Softmax", ["logits"], ["probs"], axis=-1)
    b.output("probs", [-1, 3])

    x = rng.randn(5, 4).astype(np.float32)
    got = _run(b.build(), {"x": x}, ["probs"])["probs"]
    h = np.maximum(x @ W1 + b1, 0)
    logits = h @ W2 + b2
    e = np.exp(logits - logits.max(-1, keepdims=True))
    want = e / e.sum(-1, keepdims=True)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_conv_bn_pool_nchw():
    from numpy.lib.stride_tricks import sliding_window_view
    x = rng.randn(2, 3, 8, 8).astype(np.float32)
    k = rng.randn(4, 3, 3, 3).astype(np.float32)   # OIHW
    scale = (rng.rand(4) + 0.5).astype(np.float32)
    bias = rng.randn(4).astype(np.float32)
    mean = rng.randn(4).astype(np.float32)
    var = (rng.rand(4) + 0.5).astype(np.float32)

    b = OnnxModelBuilder()
    b.input("x", [-1, 3, 8, 8])
    b.initializer("k", k)
    for nm, v in (("scale", scale), ("bias", bias), ("mean", mean),
                  ("var", var)):
        b.initializer(nm, v)
    b.node("Conv", ["x", "k"], ["c"], kernel_shape=[3, 3],
           pads=[1, 1, 1, 1], strides=[1, 1])
    b.node("BatchNormalization", ["c", "scale", "bias", "mean", "var"],
           ["bn"], epsilon=1e-5)
    b.node("MaxPool", ["bn"], ["p"], kernel_shape=[2, 2], strides=[2, 2])
    b.node("GlobalAveragePool", ["p"], ["g"])
    b.node("Flatten", ["g"], ["out"], axis=1)
    b.output("out", [-1, 4])

    got = _run(b.build(), {"x": x}, ["out"])["out"]
    xp = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))
    win = sliding_window_view(xp, (3, 3), axis=(2, 3))   # (2,3,8,8,3,3)
    conv = np.einsum("bchwij,ocij->bohw", win, k)
    bn = ((conv - mean[:, None, None]) / np.sqrt(var + 1e-5)[:, None, None]
          * scale[:, None, None] + bias[:, None, None])
    pooled = bn.reshape(2, 4, 4, 2, 4, 2).max((3, 5))
    want = pooled.mean((2, 3))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_shape_ops_and_slicing():
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    b = OnnxModelBuilder()
    b.input("x", [2, 3, 4])
    b.node("Shape", ["x"], ["sh"])
    b.initializer("newshape", np.array([2, 12], np.int64))
    b.node("Reshape", ["x", "newshape"], ["r"])
    b.initializer("starts", np.array([2], np.int64))
    b.initializer("ends", np.array([8], np.int64))
    b.initializer("axes", np.array([1], np.int64))
    b.node("Slice", ["r", "starts", "ends", "axes"], ["s"])
    b.node("Transpose", ["s"], ["t"], perm=[1, 0])
    b.node("Concat", ["t", "t"], ["out"], axis=1)
    b.output("out", [6, 4])
    got = _run(b.build(), {"x": x}, ["out"])["out"]
    want0 = x.reshape(2, 12)[:, 2:8].T
    want = np.concatenate([want0, want0], 1)
    np.testing.assert_allclose(got, want)


def test_constant_folding_and_fold_ops():
    b = OnnxModelBuilder()
    b.input("x", [-1, 3])
    b.node("Constant", [], ["c"], value=np.full((3,), 2.0, np.float32))
    b.initializer("sh", np.array([2], np.int64))
    b.node("ConstantOfShape", ["sh"], ["z"],
           value=np.array([1.5], np.float32))
    b.node("Mul", ["x", "c"], ["xm"])
    b.node("ReduceSum", ["xm"], ["out"], axes=[1], keepdims=0)
    b.output("out", [-1])
    x = rng.randn(4, 3).astype(np.float32)
    got = _run(b.build(), {"x": x}, ["out"])["out"]
    np.testing.assert_allclose(got, (x * 2.0).sum(1), rtol=1e-6)


def test_gru_like_composite_ops():
    """Gather + Unsqueeze + Expand + Where + Cast chain."""
    table = rng.randn(10, 4).astype(np.float32)
    b = OnnxModelBuilder()
    b.input("ids", [2, 3], dtype=np.int64)
    b.initializer("table", table)
    b.node("Gather", ["table", "ids"], ["emb"], axis=0)
    b.node("ReduceMean", ["emb"], ["m"], axes=[2], keepdims=1)
    b.node("Greater", ["emb", "m"], ["g"])
    b.node("Cast", ["g"], ["gf"], to=1)
    b.node("Mul", ["emb", "gf"], ["out"])
    b.output("out", [2, 3, 4])
    ids = np.array([[1, 5, 3], [0, 2, 9]], np.int64)
    got = _run(b.build(), {"ids": ids}, ["out"])["out"]
    emb = table[ids]
    m = emb.mean(-1, keepdims=True)
    want = emb * (emb > m).astype(np.float32)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_trainable_auto_and_finetune():
    W = rng.randn(4, 2).astype(np.float32)
    b = OnnxModelBuilder()
    b.input("x", [-1, 4])
    b.initializer("W", W)
    b.node("MatMul", ["x", "W"], ["out"])
    b.output("out", [-1, 2])
    sd = import_onnx_model(b.build(), trainable="auto")
    assert "W" in sd.trainable_params()
    g = sd.calculate_gradients({"x": np.ones((3, 4), np.float32)},
                               wrt=["W"], loss="out")
    assert np.abs(np.asarray(g["W"].data)).sum() > 0


def test_unmapped_op_reports_cleanly():
    b = OnnxModelBuilder()
    b.input("x", [2])
    b.node("FancyCustomOp", ["x"], ["y"])
    b.output("y", [2])
    with pytest.raises(OnnxImportError, match="unmapped ONNX op"):
        import_onnx_model(b.build())


def test_supported_op_count():
    assert len(supported_onnx_ops()) >= 90, len(supported_onnx_ops())
