"""HBM memory observability (memory.py + monitor/memstats.py).

Covers: snapshot/watermark on the CPU live-array fallback, the
AllocationsTracker satellites (lock, clamp, counts, H2D/D2H wiring),
``{"type": "memory"}`` records at listener flush boundaries, compiled-
program memory plans (precompile + lazy promotion) and the live MFU
gauge, the /memory route, OOM forensics end-to-end via a chaos-injected
``RESOURCE_EXHAUSTED``, headroom-refused reload/warmup, and the
bit-identity of memory telemetry on vs off.
"""
import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu import memory
from deeplearning4j_tpu.autodiff import (SameDiff, ScoreIterationListener,
                                         TrainingConfig)
from deeplearning4j_tpu.checkpoint import CheckpointManager
from deeplearning4j_tpu.dataset.iterators import ArrayDataSetIterator
from deeplearning4j_tpu.faults import ChaosMonkey, FaultTolerantFit, \
    RetryPolicy
from deeplearning4j_tpu.learning.updaters import Adam
from deeplearning4j_tpu.memory import (AllocationsTracker,
                                       MemoryExhaustedError,
                                       MemoryHeadroomError)
from deeplearning4j_tpu.monitor import (MetricsRegistry, MonitorListener,
                                        memstats)
from deeplearning4j_tpu.monitor.server import health_snapshot
from deeplearning4j_tpu.ui.report import render_report
from deeplearning4j_tpu.ui.stats import StatsStorage


@pytest.fixture(autouse=True)
def _clean_memstats():
    """Plan capture and the tracker are process-global: every test
    starts from the off/empty state and leaves it that way."""
    memstats.disable_plan_capture()
    memstats.PLANS.reset()
    AllocationsTracker.get_instance().reset()
    yield
    memstats.disable_plan_capture()
    memstats.PLANS.reset()
    AllocationsTracker.get_instance().reset()


def _mlp(fused_steps=4, sentinel=False, seed=0):
    rng = np.random.default_rng(seed)
    sd = SameDiff()
    x = sd.placeholder("x", shape=(-1, 8))
    w0 = sd.var("w0", value=rng.normal(0, .1, (8, 16)).astype(np.float32))
    b0 = sd.var("b0", value=np.zeros(16, np.float32))
    h = sd.nn.relu(x.mmul(w0).add(b0))
    w1 = sd.var("w1", value=rng.normal(0, .1, (16, 2)).astype(np.float32))
    logits = h.mmul(w1)
    labels = sd.placeholder("labels", shape=(-1, 2))
    sd.loss.softmax_cross_entropy(logits, labels, name="loss")
    sd.set_loss_variables(["loss"])
    sd.training_config = TrainingConfig(
        updater=Adam(1e-2), data_set_feature_mapping=["x"],
        data_set_label_mapping=["labels"], fused_steps=fused_steps,
        sentinel=sentinel)
    return sd


def _it(batch=8, n=64, seed=1):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 8)).astype(np.float32)
    Y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, n)]
    return ArrayDataSetIterator(X, Y, batch_size=batch)


def _quiet():
    return ScoreIterationListener(print_every=10 ** 9,
                                  print_fn=lambda *a: None)


def _get(url, timeout=10):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read().decode("utf-8")
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode("utf-8")


# ---------------------------------------------------------------------------
# snapshot / watermark / census (CPU fallback path)

class TestSnapshotWatermark:
    def test_snapshot_total(self):
        import jax.numpy as jnp
        big = jnp.ones((256, 1024), jnp.float32)  # 1 MiB resident
        big.block_until_ready()
        states = memory.snapshot()
        assert states and all(s.source in ("pjrt", "live_arrays")
                              for s in states)
        assert memory.total_bytes_in_use() >= big.nbytes
        del big

    def test_watermark_reports_per_device_peaks(self):
        import jax.numpy as jnp
        with memory.MemoryWatermark() as wm:
            a = jnp.ones((128, 1024), jnp.float32)
            a.block_until_ready()
        rep = wm.report()
        # one "peak ... delta" line per device, not just the max
        for s in wm.after:
            assert s.device in rep
        assert "peak" in rep and "delta" in rep
        assert wm.peak_bytes > 0
        del a

    def test_live_census_top_sorted(self):
        import jax.numpy as jnp
        a = jnp.ones((64, 1024), jnp.float32)
        a.block_until_ready()
        census = memory.live_census(top_n=5)
        assert census["arrays"] >= 1
        assert census["total_bytes"] >= a.nbytes
        tops = [r["nbytes"] for r in census["top"]]
        assert tops == sorted(tops, reverse=True)
        del a

    def test_fallback_counts_unsizable_arrays(self, monkeypatch):
        """Satellite: a deleted array and a donated array (shard read
        raises) are SKIPPED AND COUNTED — the fallback total can no
        longer silently undercount."""
        class _Deleted:
            def is_deleted(self):
                return True

        class _Donated:
            def is_deleted(self):
                return False

            @property
            def addressable_shards(self):
                raise RuntimeError("Array has been deleted.")

        class _Shard:
            def __init__(self):
                self.device = "FakeDevice(0)"

                class _D:
                    nbytes = 128
                self.data = _D()

        class _Live:
            def is_deleted(self):
                return False

            @property
            def addressable_shards(self):
                return [_Shard()]

        import jax
        monkeypatch.setattr(jax, "live_arrays",
                            lambda: [_Deleted(), _Donated(), _Live()])
        by_dev, skipped = memory._live_array_bytes_by_device()
        assert skipped == 2
        assert by_dev == {"FakeDevice(0)": 128}


# ---------------------------------------------------------------------------
# AllocationsTracker satellites

class TestAllocationsTracker:
    def test_release_clamps_at_zero(self):
        t = AllocationsTracker.get_instance()
        t.allocate("tag", 100)
        t.release("tag", 500)
        assert t.bytes_tracked("tag") == 0
        t.allocate("tag", 40)
        assert t.bytes_tracked("tag") == 40  # not 40 - 400

    def test_counts(self):
        t = AllocationsTracker.get_instance()
        t.allocate("a", 10)
        t.allocate("a", 10)
        t.allocate("b", 1)
        assert t.counts() == {"a": 2, "b": 1}

    def test_thread_safety(self):
        t = AllocationsTracker.get_instance()

        def hammer():
            for _ in range(1000):
                t.allocate("hot", 1)
                t.release("cold", 1)

        threads = [threading.Thread(target=hammer) for _ in range(8)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert t.bytes_tracked("hot") == 8000
        assert t.counts()["hot"] == 8000
        assert t.bytes_tracked("cold") == 0

    def test_checkpoint_capture_tags_d2h_bytes(self):
        from deeplearning4j_tpu.checkpoint.state import \
            capture_training_state
        sd = _mlp()
        state = capture_training_state(sd)
        tracked = AllocationsTracker.get_instance().bytes_tracked(
            "checkpoint_d2h")
        assert tracked >= sum(a.nbytes for a in state.arrays.values())
        assert AllocationsTracker.get_instance().counts()[
            "checkpoint_d2h"] == 1

    def test_window_stager_tags_h2d_bytes(self):
        sd = _mlp(fused_steps=4)
        sd.fit(_it(), epochs=1, listeners=[_quiet()])
        t = AllocationsTracker.get_instance()
        # 64 rows x (8 feat + 2 label) x 4 bytes staged host-side
        assert t.bytes_tracked("h2d_stage") >= 64 * 10 * 4
        assert t.counts()["h2d_stage"] >= 1


# ---------------------------------------------------------------------------
# memory records at flush boundaries

class TestMemoryRecords:
    def test_records_at_flush_boundaries(self):
        sd = _mlp(fused_steps=4)
        storage = StatsStorage()
        mon = MonitorListener(storage, frequency=4)
        sd.fit(_it(), epochs=2, listeners=[mon])
        recs = storage.of_type("memory")
        # 64 rows / batch 8 = 8 steps/epoch, flush every 4 → ≥2/epoch
        assert len(recs) >= 4
        r = recs[-1]
        assert r["bytes_in_use"] >= 0 and "peak_bytes" in r
        assert r["devices"] and "device" in r["devices"][0]
        assert "iteration" in r
        assert "h2d_stage" in r["tracked"]

    def test_memory_off_publishes_nothing(self):
        sd = _mlp(fused_steps=4)
        storage = StatsStorage()
        sd.fit(_it(), epochs=1,
               listeners=[MonitorListener(storage, memory=False)])
        assert storage.of_type("memory") == []
        assert not memstats.plan_capture_enabled()

    def test_fold_memory_exports_hbm_gauges(self):
        reg = MetricsRegistry()
        reg.fold_memory({
            "type": "memory", "bytes_in_use": 100, "peak_bytes": 200,
            "bytes_limit": 1000, "headroom": 900,
            "devices": [{"device": "d0", "bytes_in_use": 100,
                         "peak_bytes": 200, "bytes_limit": 1000}],
            "tracked": {"h2d_stage": 42}})
        text = reg.to_prometheus_text()
        assert "dl4j_hbm_bytes_in_use 100" in text
        assert "dl4j_hbm_peak_bytes 200" in text
        assert "dl4j_hbm_bytes_limit 1000" in text
        assert "dl4j_hbm_headroom 900" in text
        assert 'dl4j_hbm_bytes_in_use{device="d0"} 100' in text
        assert 'dl4j_memory_tracked_bytes{tag="h2d_stage"} 42' in text

    def test_serving_batch_boundary_records(self):
        from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                           MultiLayerNetwork,
                                           NeuralNetConfiguration,
                                           OutputLayer)
        from deeplearning4j_tpu.serving import (InferenceMode,
                                                ParallelInference)
        conf = (NeuralNetConfiguration.builder().seed(7)
                .updater(Adam(1e-3)).list()
                .layer(DenseLayer(n_out=8, activation="tanh"))
                .layer(OutputLayer(n_out=2, loss_function="MCXENT"))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        st = StatsStorage()
        pi = ParallelInference(net, mode=InferenceMode.INPLACE,
                               stats_storage=st, memory_sample_every=2)
        try:
            x = np.ones((2, 4), np.float32)
            for _ in range(5):
                pi.output(x)
        finally:
            pi.shutdown()
        recs = st.of_type("memory")
        assert len(recs) >= 2
        assert all(r["source"] == "serving" for r in recs)


# ---------------------------------------------------------------------------
# memory plans + MFU

class TestMemoryPlans:
    def test_precompile_captures_window_plans(self):
        sd = _mlp(fused_steps=4)
        sd.precompile(batch_size=8)
        labels = {p.label for p in memstats.PLANS.plans()}
        assert {"window_k4", "window_k2", "window_k1"} <= labels
        plan = memstats.PLANS.find("window_k4")
        assert plan.steps == 4
        assert plan.argument_bytes is not None and plan.argument_bytes > 0
        assert plan.flops and plan.flops > 0
        assert plan.flops_per_step == plan.flops / 4
        assert plan.total_bytes > 0

    def test_lazy_promotion_captures_plan_and_is_bit_identical(self):
        X = np.random.default_rng(1).normal(size=(64, 8)) \
            .astype(np.float32)
        Y = np.eye(2, dtype=np.float32)[
            np.random.default_rng(2).integers(0, 2, 64)]

        def run(capture):
            memstats.PLANS.reset()
            if capture:
                memstats.enable_plan_capture()
            else:
                memstats.disable_plan_capture()
            sd = _mlp(fused_steps=4, seed=0)
            it = ArrayDataSetIterator(X, Y, batch_size=8)
            hist = sd.fit(it, epochs=2, listeners=[_quiet()])
            plans = {p.label for p in memstats.PLANS.plans()}
            return (hist.loss_curve.losses,
                    {n: np.asarray(a)
                     for n, a in sd.trainable_params().items()}, plans)

        losses_off, params_off, plans_off = run(False)
        losses_on, params_on, plans_on = run(True)
        assert plans_off == set()
        assert "window_k4" in plans_on       # lazy compile got a plan
        assert losses_on == losses_off       # bit-identical
        for n in params_off:
            np.testing.assert_array_equal(params_on[n], params_off[n])

    def test_serving_warmup_captures_bucket_plans(self):
        from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                           MultiLayerNetwork,
                                           NeuralNetConfiguration,
                                           OutputLayer)
        from deeplearning4j_tpu.serving import (InferenceMode,
                                                ParallelInference)
        conf = (NeuralNetConfiguration.builder().seed(7)
                .updater(Adam(1e-3)).list()
                .layer(DenseLayer(n_out=8, activation="tanh"))
                .layer(OutputLayer(n_out=2, loss_function="MCXENT"))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        pi = ParallelInference(net, mode=InferenceMode.BATCHED,
                               max_batch_size=8, warmup_buckets=True)
        try:
            labels = {p.label for p in memstats.PLANS.plans()}
            assert any(lb.startswith("output_b") for lb in labels)
            plan = next(p for p in memstats.PLANS.plans()
                        if p.label.startswith("output_b"))
            assert plan.output_bytes is not None
        finally:
            pi.shutdown()

    def test_mfu_gauge_mid_fit(self, monkeypatch):
        """Acceptance: /metrics exports dl4j_hbm_* gauges and a live
        MFU-estimate gauge MID-FIT (scraped from inside a listener
        flush while the fit is running)."""
        monkeypatch.setenv("DL4J_PEAK_FLOPS", "1e12")
        sd = _mlp(fused_steps=4)
        sd.precompile(batch_size=8)          # plans → MFU numerator
        storage = StatsStorage()
        mon = MonitorListener(storage, frequency=4, serve_port=0)
        scraped = {}

        from deeplearning4j_tpu.autodiff.training import Listener

        class _Probe(Listener):
            frequency = 4
            calls = 0

            def iterations_done(self, _sd, epoch, iters, losses):
                _Probe.calls += 1
                if _Probe.calls == 3 and not scraped:
                    code, text = _get(mon.server.url + "/metrics")
                    scraped["code"] = code
                    scraped["text"] = text

        try:
            # listener order: mon flushes (and samples memory) first,
            # then the probe scrapes — a genuine mid-fit scrape
            sd.fit(_it(n=128), epochs=3, listeners=[mon, _Probe()])
            assert scraped, "probe never scraped mid-fit"
            assert scraped["code"] == 200
            assert "dl4j_hbm_bytes_in_use" in scraped["text"]
            assert "dl4j_mfu_estimate" in scraped["text"]
            assert "dl4j_plan_flops_per_step" in scraped["text"]
            mfu = [float(line.rsplit(" ", 1)[1])
                   for line in scraped["text"].splitlines()
                   if line.startswith("dl4j_mfu_estimate")]
            assert mfu and mfu[0] > 0
        finally:
            if mon.server is not None:
                mon.server.close()

    def test_plan_records_published_and_rendered(self):
        sd = _mlp(fused_steps=4)
        sd.precompile(batch_size=8)
        storage = StatsStorage()
        sd.fit(_it(), epochs=1, listeners=[MonitorListener(storage)])
        plan_recs = storage.of_type("memory_plan")
        assert {r["program"] for r in plan_recs} >= {"window_k4"}
        html = render_report(storage)
        assert "compiled-program memory plans" in html
        assert "window_k4" in html
        # the forward-compat footer must NOT list memory/memory_plan
        assert "unrendered record types" not in html


class TestPlanScoping:
    def test_second_models_listener_does_not_republish_first_models_plans(
            self):
        """Review regression: the plan registry is process-global, but
        a later model's MonitorListener must publish only ITS graph's
        plans — not the earlier model's — into its storage/report."""
        sd_a = _mlp(fused_steps=4, seed=0)
        sd_a.precompile(batch_size=8)
        st_a = StatsStorage()
        sd_a.fit(_it(), epochs=1, listeners=[MonitorListener(st_a)])
        assert {r["program"] for r in st_a.of_type("memory_plan")} \
            >= {"window_k4"}

        sd_b = _mlp(fused_steps=2, seed=1)
        sd_b.precompile(batch_size=8)
        st_b = StatsStorage()
        sd_b.fit(_it(), epochs=1, listeners=[MonitorListener(st_b)])
        progs_b = {r["program"] for r in st_b.of_type("memory_plan")}
        assert "window_k2" in progs_b
        assert "window_k4" not in progs_b, \
            "model B's storage republished model A's plans"


class TestAcceptanceReportPlans:
    def test_gpt_tiny_window_and_serving_bucket_plans_in_report(self):
        """Acceptance: /report shows the per-executable memory plan for
        at least the gpt_tiny fused window and one serving bucket."""
        from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                           MultiLayerNetwork,
                                           NeuralNetConfiguration,
                                           OutputLayer)
        from deeplearning4j_tpu.serving import (InferenceMode,
                                                ParallelInference)
        from deeplearning4j_tpu.zoo.gpt import GPT_TINY, build_gpt
        sd = build_gpt(GPT_TINY, batch=2, seq_len=8)
        sd.training_config = TrainingConfig(
            updater=Adam(1e-3), data_set_feature_mapping=["input_ids"],
            data_set_label_mapping=["targets"], fused_steps=2)
        sd.precompile(batch_size=2)
        gpt_plan = memstats.PLANS.find("window_k2")
        assert gpt_plan is not None
        assert gpt_plan.flops and gpt_plan.flops > 0
        assert gpt_plan.argument_bytes > 0    # params + window batch

        conf = (NeuralNetConfiguration.builder().seed(7)
                .updater(Adam(1e-3)).list()
                .layer(DenseLayer(n_out=8, activation="tanh"))
                .layer(OutputLayer(n_out=2, loss_function="MCXENT"))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        pi = ParallelInference(net, mode=InferenceMode.INPLACE,
                               warmup_buckets=[4])
        try:
            storage = StatsStorage()
            for p in memstats.PLANS.plans():
                storage.put(p.to_record())
            html = render_report(storage)
            assert "compiled-program memory plans" in html
            assert "window_k2" in html            # the gpt_tiny window
            assert "output_b4" in html            # the serving bucket
        finally:
            pi.shutdown()


# ---------------------------------------------------------------------------
# /memory route

class TestMemoryRoute:
    def test_memory_route(self):
        from deeplearning4j_tpu.monitor import serve
        st = StatsStorage()
        st.put(memstats.memory_record(epoch=0, iteration=3))
        sd = _mlp(fused_steps=2)
        sd.precompile(batch_size=8)
        srv = serve(port=0, storage=st)
        try:
            code, body = _get(srv.url + "/memory")
            assert code == 200
            data = json.loads(body)
            assert data["type"] == "memory"
            assert data["devices"]
            assert any(p["program"] == "window_k2"
                       for p in data["plans"])
            assert data["last_record"]["iteration"] == 3
            code, body = _get(srv.url + "/")
            assert "/memory" in body
        finally:
            srv.close()


# ---------------------------------------------------------------------------
# OOM forensics

class TestOOMForensics:
    @pytest.mark.chaos
    def test_fit_converts_resource_exhausted(self):
        sd = _mlp(fused_steps=4)
        chaos = ChaosMonkey(seed=0)
        with chaos.resource_exhausted(at_call=2):
            with pytest.raises(MemoryExhaustedError) as ei:
                sd.fit(_it(), epochs=1, listeners=[_quiet()])
        err = ei.value
        assert err.program == "window_k4"
        assert err.snapshot, "no device snapshot attached"
        assert err.census is not None
        assert "RESOURCE_EXHAUSTED" in str(err.__cause__)
        # the rendered one-pager names usage per device
        assert "MiB in use" in str(err)

    @pytest.mark.chaos
    def test_oom_e2e_ftf_diagnoses_and_healthz_503(self, tmp_path):
        """Acceptance: injected OOM during a fit produces a
        MemoryExhaustedError naming the active program and per-device
        usage, an oom fault record, a rendered report panel, and a
        503-ing /healthz — instead of a raw backend crash. And FTF
        does NOT burn its retry budget on it."""
        from deeplearning4j_tpu.monitor import serve
        sd = _mlp(fused_steps=4, sentinel=True)
        storage = StatsStorage()
        mgr = CheckpointManager(tmp_path / "ckpt", keep_last_n=2)
        ftf = FaultTolerantFit(
            sd, mgr, policy=RetryPolicy(max_retries=3, backoff_base=0.0),
            checkpoint_every_n_epochs=1, stats_storage=storage)
        chaos = ChaosMonkey(seed=0)
        with chaos.resource_exhausted(at_call=3):
            with pytest.raises(MemoryExhaustedError):
                ftf.fit(_it(), epochs=2, listeners=[_quiet()])
        oom = [r for r in storage.of_type("faults")
               if r.get("event") == "oom"]
        assert len(oom) == 1
        assert oom[0]["program"] == "window_k4"
        assert oom[0]["devices"], "forensics lost the device usage"
        # non-retryable: no rollback was attempted for the OOM
        assert not [r for r in storage.of_type("faults")
                    if r.get("event") == "rollback"]
        # health: sticky failed
        snap = health_snapshot(storage)
        assert snap["healthy"] is False
        assert snap["last_fault_event"] == "oom"
        srv = serve(port=0, storage=storage)
        try:
            code, body = _get(srv.url + "/healthz")
            assert code == 503
            assert json.loads(body)["fault_state"] == "failed"
        finally:
            srv.close()
        html = render_report(storage)
        assert "OOM events" in html and "window_k4" in html

    @pytest.mark.chaos
    def test_serving_oom_structured_and_healthz(self):
        from deeplearning4j_tpu.monitor import serve
        from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                           MultiLayerNetwork,
                                           NeuralNetConfiguration,
                                           OutputLayer)
        from deeplearning4j_tpu.serving import (InferenceMode,
                                                ParallelInference)
        conf = (NeuralNetConfiguration.builder().seed(7)
                .updater(Adam(1e-3)).list()
                .layer(DenseLayer(n_out=8, activation="tanh"))
                .layer(OutputLayer(n_out=2, loss_function="MCXENT"))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        st = StatsStorage()
        pi = ParallelInference(net, mode=InferenceMode.INPLACE,
                               stats_storage=st)
        chaos = ChaosMonkey(seed=0)
        try:
            x = np.ones((2, 4), np.float32)
            pi.output(x)                         # healthy baseline
            with chaos.oom_serving(pi, at_call=1):
                with pytest.raises(MemoryExhaustedError) as ei:
                    pi.output(x)
            assert ei.value.program.startswith("serving_b")
            oom = [r for r in st.of_type("faults")
                   if r.get("event") == "oom"]
            assert oom and oom[0]["origin"] == "serving"
            srv = serve(port=0, storage=st)
            try:
                code, _ = _get(srv.url + "/healthz")
                assert code == 503
            finally:
                srv.close()
        finally:
            pi.shutdown()


# ---------------------------------------------------------------------------
# headroom guards

class TestHeadroomGuards:
    def _server_with_checkpoint(self, tmp_path):
        from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                           MultiLayerNetwork,
                                           NeuralNetConfiguration,
                                           OutputLayer)
        from deeplearning4j_tpu.serving import (InferenceMode,
                                                ParallelInference)
        conf = (NeuralNetConfiguration.builder().seed(7)
                .updater(Adam(1e-3)).list()
                .layer(DenseLayer(n_out=8, activation="tanh"))
                .layer(OutputLayer(n_out=2, loss_function="MCXENT"))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        mgr = CheckpointManager(tmp_path / "ckpt", keep_last_n=2)
        mgr.save(1, model=net, blocking=True)
        pi = ParallelInference(net, mode=InferenceMode.INPLACE)
        return pi, mgr

    def test_reload_refused_when_headroom_too_small(self, tmp_path,
                                                    monkeypatch):
        pi, mgr = self._server_with_checkpoint(tmp_path)
        try:
            x = np.ones((1, 4), np.float32)
            before = pi.output(x)
            monkeypatch.setattr(memstats, "projected_headroom",
                                lambda snap=None: 16)
            with pytest.raises(MemoryHeadroomError) as ei:
                pi.reload_from(mgr)
            assert ei.value.headroom_bytes == 16
            assert ei.value.required_bytes > 16
            assert pi.metrics.counters.get("reloads", 0) == 0
            # nothing was swapped: the server serves exactly what it
            # served before the refusal
            np.testing.assert_array_equal(pi.output(x), before)
        finally:
            pi.shutdown()

    def test_reload_ok_without_limits_and_with_guard_off(self, tmp_path,
                                                         monkeypatch):
        pi, mgr = self._server_with_checkpoint(tmp_path)
        try:
            # CPU: no bytes_limit → guard is a no-op, reload succeeds
            rep = pi.reload_from(mgr)
            assert rep["arrays_swapped"] > 0
            # guard off bypasses even a tiny headroom
            monkeypatch.setattr(memstats, "projected_headroom",
                                lambda snap=None: 1)
            rep = pi.reload_from(mgr, headroom_guard=False)
            assert rep["arrays_swapped"] > 0
        finally:
            pi.shutdown()

    def test_warmup_refused_when_headroom_too_small(self, monkeypatch):
        from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                           MultiLayerNetwork,
                                           NeuralNetConfiguration,
                                           OutputLayer)
        from deeplearning4j_tpu.serving import (InferenceMode,
                                                ParallelInference)
        conf = (NeuralNetConfiguration.builder().seed(7)
                .updater(Adam(1e-3)).list()
                .layer(DenseLayer(n_out=8, activation="tanh"))
                .layer(OutputLayer(n_out=2, loss_function="MCXENT"))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        pi = ParallelInference(net, mode=InferenceMode.SEQUENTIAL,
                               workers=1)
        try:
            monkeypatch.setattr(memstats, "projected_headroom",
                                lambda snap=None: 0)
            with pytest.raises(MemoryHeadroomError):
                pi.warmup([4])
        finally:
            pi.shutdown()


# ---------------------------------------------------------------------------
# bit-identity of the whole memory rail

class TestBitIdentity:
    def test_fused_run_bit_identical_memory_on_vs_off(self):
        X = np.random.default_rng(5).normal(size=(64, 8)) \
            .astype(np.float32)
        Y = np.eye(2, dtype=np.float32)[
            np.random.default_rng(6).integers(0, 2, 64)]

        def run(mem_on):
            memstats.PLANS.reset()
            memstats.disable_plan_capture()
            sd = _mlp(fused_steps=4, sentinel=True, seed=0)
            it = ArrayDataSetIterator(X, Y, batch_size=8)
            storage = StatsStorage()
            listeners = [_quiet(),
                         MonitorListener(storage, frequency=4,
                                         memory=mem_on)]
            hist = sd.fit(it, epochs=2, listeners=listeners)
            return (hist.loss_curve.losses,
                    {n: np.asarray(a)
                     for n, a in sd.trainable_params().items()},
                    storage)

        losses_off, params_off, st_off = run(False)
        losses_on, params_on, st_on = run(True)
        assert losses_on == losses_off
        for n in params_off:
            np.testing.assert_array_equal(params_on[n], params_off[n])
        assert st_on.of_type("memory") and not st_off.of_type("memory")
