"""Fault-tolerant streaming data plane (datapipe/).

Covers the ISSUE-13 contract: manifest commit/verify/torn-shard
detection, worker-crash exactly-once requeue + respawn, record-level
quarantine persisted across passes, multihost shard assignment
disjoint-and-total, disk-backed fit bit-exact vs in-memory, mid-epoch
seek-resume bit-exact (incl. shuffle RNG and dropout), RetryingIterator
seek-vs-fallback regression, datapipe telemetry (records / fold /
report), and the chaos self-heal e2e (torn shard + killed prefetch
worker + transient reads in ONE run, zero dropped/duplicated samples).
"""
import os
import threading

import numpy as np
import pytest

from deeplearning4j_tpu.autodiff import (SameDiff, ScoreIterationListener,
                                         TrainingConfig)
from deeplearning4j_tpu.datapipe import (PipelineState, ShardCorruptError,
                                         ShardedRecordReader,
                                         StreamingDataPipeline,
                                         find_pipeline, load_manifest,
                                         shard_assignment, verify_dataset,
                                         write_dataset)
from deeplearning4j_tpu.datapipe.manifest import SHARD_FMT
from deeplearning4j_tpu.faults import (ChaosMonkey, DataPipelineError,
                                       FaultTolerantFit, RetryPolicy,
                                       RetryingIterator,
                                       TransientDeviceError)
from deeplearning4j_tpu.learning.updaters import Adam


# ---------------------------------------------------------------------------
# helpers

def _data(n=96, width=8, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, width)).astype(np.float32)
    Y = np.eye(classes, dtype=np.float32)[np.arange(n) % classes]
    return X, Y


def _dataset(tmp_path, n=96, shard_size=16, seed=0):
    X, Y = _data(n=n, seed=seed)
    path = os.path.join(str(tmp_path), "ds")
    write_dataset(path, X, Y, shard_size=shard_size)
    return path, X, Y


def _mlp(seed=0, dropout=None, fused_steps=2, lr=1e-2):
    rng = np.random.default_rng(seed)
    sd = SameDiff()
    x = sd.placeholder("x", shape=(-1, 8))
    w0 = sd.var("w0", value=rng.normal(0, 0.3, (8, 16)).astype(np.float32))
    b0 = sd.var("b0", value=np.zeros(16, np.float32))
    h = sd.nn.relu(x.mmul(w0).add(b0))
    if dropout is not None:
        h = sd.random.dropout(h, p=dropout)
    w1 = sd.var("w1", value=rng.normal(0, 0.3, (16, 4)).astype(np.float32))
    b1 = sd.var("b1", value=np.zeros(4, np.float32))
    logits = h.mmul(w1).add(b1, name="logits")
    labels = sd.placeholder("labels", shape=(-1, 4))
    sd.loss.softmax_cross_entropy(logits, labels, name="loss")
    sd.set_loss_variables(["loss"])
    sd.training_config = (TrainingConfig.builder()
                          .updater(Adam(learning_rate=lr))
                          .data_set_feature_mapping("x")
                          .data_set_label_mapping("labels")
                          .fused_steps(fused_steps).build())
    sd._seed = 99
    return sd


def _quiet(every=10 ** 9):
    return ScoreIterationListener(print_every=every,
                                  print_fn=lambda *a: None)


def _params(sd):
    return {n: np.asarray(a) for n, a in sd.trainable_params().items()}


def _assert_params_equal(a, b, msg=""):
    for n in a:
        np.testing.assert_array_equal(a[n], b[n], err_msg=f"{msg}{n}")


class _FaultAt:
    """One-shot in-fit device fault at an absolute iteration — drives
    FaultTolerantFit's rollback while the pipeline is mid-pass."""

    frequency = 1

    def __init__(self, at):
        self.at, self.fired = int(at), False

    def on_training_start(self, sd):
        pass

    def on_epoch_start(self, sd, epoch):
        pass

    def iterations_done(self, sd, epoch, iterations, losses):
        if not self.fired and any(i >= self.at for i in iterations):
            self.fired = True
            raise TransientDeviceError(
                "chaos: injected device loss", step=max(iterations),
                cause="device")

    def on_epoch_end(self, sd, epoch, loss):
        pass

    def on_training_end(self, sd):
        pass


# ---------------------------------------------------------------------------
# manifest: staged commit + verification + torn-shard detection

class TestManifest:
    def test_write_verify_roundtrip(self, tmp_path):
        path, X, Y = _dataset(tmp_path, n=100, shard_size=16)
        m = load_manifest(path)
        assert m.record_count == 100
        assert len(m.shards) == 7               # six full + ragged tail
        assert [s.records for s in m.shards] == [16] * 6 + [4]
        # offsets form the global id space
        assert [s.offset for s in m.shards] == \
            [0, 16, 32, 48, 64, 80, 96]
        assert verify_dataset(path) == []

    def test_missing_commit_marker_is_typed(self, tmp_path):
        path, _, _ = _dataset(tmp_path)
        os.remove(os.path.join(path, "COMMIT"))
        with pytest.raises(ShardCorruptError, match="COMMIT"):
            load_manifest(path)

    def test_torn_manifest_is_typed(self, tmp_path):
        path, _, _ = _dataset(tmp_path)
        with open(os.path.join(path, "MANIFEST.json"), "w") as fh:
            fh.write("{not json")
        with pytest.raises(ShardCorruptError, match="manifest"):
            load_manifest(path)

    @pytest.mark.parametrize("mode", ["bitflip", "truncate"])
    def test_torn_shard_detected_with_provenance(self, tmp_path, mode):
        path, _, _ = _dataset(tmp_path)
        chaos = ChaosMonkey(seed=3)
        torn = chaos.torn_shard(path, shard_index=2, mode=mode)
        with torn:
            assert any("shard_00002" in p for p in verify_dataset(path))
            reader = ShardedRecordReader(path, read_retries=0,
                                         quarantine_budget=10)
            with pytest.raises(ShardCorruptError) as ei:
                reader.read_rows(np.arange(32, 40))
            # typed provenance: shard file + record offset, retryable
            assert ei.value.shard == SHARD_FMT.format(i=2)
            assert ei.value.offset == 32
            assert isinstance(ei.value, DataPipelineError)
        # healed on context exit
        assert verify_dataset(path) == []

    def test_overwrite_keeps_old_dataset_until_staged(self, tmp_path):
        """overwrite=True must not delete the committed dataset before
        the replacement is FULLY staged — a writer crashing mid-build
        leaves the OLD data, not nothing."""
        path, X, Y = _dataset(tmp_path)
        chaos = ChaosMonkey(seed=0)
        with chaos.failing_fsync(times=1):      # dies staging shard 0
            with pytest.raises(OSError):
                write_dataset(path, X, Y, shard_size=8, overwrite=True)
        assert verify_dataset(path) == []       # old dataset intact
        write_dataset(path, X, Y, shard_size=8, overwrite=True)
        assert verify_dataset(path) == []
        assert len(load_manifest(path).shards) == 12

    def test_staged_commit_never_publishes_half_dataset(self, tmp_path):
        X, Y = _data(n=32)
        path = os.path.join(str(tmp_path), "ds")
        chaos = ChaosMonkey(seed=0)
        with chaos.failing_os_replace(times=1, match="ds"):
            with pytest.raises(OSError):
                write_dataset(path, X, Y, shard_size=8)
        # nothing published; the staging dir is what's left
        assert not os.path.exists(path)
        # a later writer succeeds over the leftovers
        write_dataset(path, X, Y, shard_size=8)
        assert verify_dataset(path) == []


# ---------------------------------------------------------------------------
# reader: retry budget, shard quarantine, multihost assignment

class TestReader:
    def test_transient_read_error_retried(self, tmp_path):
        path, X, _ = _dataset(tmp_path)
        chaos = ChaosMonkey(seed=1)
        reader = ShardedRecordReader(path, read_retries=2)
        with chaos.flaky_read(times=1):
            rows = reader.read_rows(np.arange(0, 8))
        np.testing.assert_array_equal(rows["features"], X[:8])
        assert reader.read_retries_total == 1

    def test_persistent_corruption_quarantines_after_budget(self,
                                                            tmp_path):
        path, _, _ = _dataset(tmp_path)
        events = []
        reader = ShardedRecordReader(path, read_retries=1,
                                     quarantine_budget=2,
                                     on_event=events.append)
        chaos = ChaosMonkey(seed=1)
        torn = chaos.torn_shard(path, shard_index=0, mode="bitflip")
        torn.inject()
        try:
            for _ in range(2):                 # two exhausted budgets
                with pytest.raises(ShardCorruptError):
                    reader.read_rows(np.arange(0, 8))
        finally:
            torn.heal()
        assert 0 in reader.quarantined_shards
        assert any(e["event"] == "shard_quarantined" for e in events)
        # quarantined shard's records drop out of the id space, loudly
        ids = reader.record_ids()
        assert ids.min() == 16 and len(ids) == 96 - 16
        with pytest.raises(ShardCorruptError, match="quarantined"):
            reader.read_rows(np.arange(0, 8))

    def test_shard_assignment_disjoint_and_total(self):
        for n_shards in (1, 5, 8, 17):
            for host_count in (1, 2, 3, 8):
                parts = [shard_assignment(n_shards, h, host_count)
                         for h in range(host_count)]
                flat = [i for p in parts for i in p]
                assert sorted(flat) == list(range(n_shards))   # total
                assert len(flat) == len(set(flat))             # disjoint
        # the parallel/ convenience wraps the same partition for THIS
        # process (single-process test runtime: owns everything)
        from deeplearning4j_tpu.parallel.multihost import \
            host_shard_assignment
        assert host_shard_assignment(5) == [0, 1, 2, 3, 4]

    def test_multihost_pipelines_cover_all_records_disjointly(self,
                                                              tmp_path):
        path, X, _ = _dataset(tmp_path, n=96, shard_size=16)
        seen = []
        for h in range(3):
            pipe = StreamingDataPipeline(path, batch_size=8,
                                         shuffle=False, host_index=h,
                                         host_count=3, n_workers=1)
            for feats, _labels in pipe:
                seen.extend(feats[:, 0].tolist())
        assert sorted(seen) == sorted(X[:, 0].tolist())


# ---------------------------------------------------------------------------
# pipeline basics: ordering, determinism, transforms, state serde

class TestPipeline:
    def test_unshuffled_order_and_ragged_tail(self, tmp_path):
        path, X, Y = _dataset(tmp_path, n=100, shard_size=16)
        pipe = StreamingDataPipeline(path, batch_size=24, shuffle=False,
                                     n_workers=2)
        batches = list(pipe)
        assert [len(b[0]) for b in batches] == [24, 24, 24, 24, 4]
        np.testing.assert_array_equal(
            np.concatenate([b[0] for b in batches]), X)
        np.testing.assert_array_equal(
            np.concatenate([b[1] for b in batches]), Y)

    def test_shuffle_fresh_per_pass_and_reproducible(self, tmp_path):
        path, _, _ = _dataset(tmp_path)
        a = StreamingDataPipeline(path, batch_size=16, seed=9,
                                  n_workers=2)
        p0 = np.concatenate([b[0] for b in a])
        p1 = np.concatenate([b[0] for b in a])
        assert not np.array_equal(p0, p1)       # fresh order per pass
        b = StreamingDataPipeline(path, batch_size=16, seed=9,
                                  n_workers=2)
        np.testing.assert_array_equal(p0, np.concatenate(
            [bb[0] for bb in b]))               # same seed → same passes
        np.testing.assert_array_equal(p1, np.concatenate(
            [bb[0] for bb in b]))

    def test_vectorized_transform_runs_on_workers(self, tmp_path):
        path, X, Y = _dataset(tmp_path)
        tids = set()

        def xform(feats, labels):
            tids.add(threading.get_ident())
            return feats * 2.0, labels

        pipe = StreamingDataPipeline(path, batch_size=16, shuffle=False,
                                     transform=xform, n_workers=2)
        out = np.concatenate([b[0] for b in pipe])
        np.testing.assert_allclose(out, X * 2.0)
        assert threading.get_ident() not in tids   # ran off-thread

    def test_transform_process_columns_layout(self, tmp_path):
        from deeplearning4j_tpu.etl import (CATEGORICAL, FLOAT, ColumnMeta,
                                            Schema, TransformProcess)
        n = 48
        rng = np.random.default_rng(0)
        cols = {"a": rng.normal(size=n).astype(np.float32),
                "b": rng.normal(size=n).astype(np.float32),
                "label": np.asarray((np.arange(n) % 3), np.int64)}
        path = os.path.join(str(tmp_path), "cols")
        write_dataset(path, columns=cols, shard_size=16)
        schema = Schema([ColumnMeta("a", FLOAT), ColumnMeta("b", FLOAT),
                         ColumnMeta("label", FLOAT)])
        tp = (TransformProcess.builder(schema)
              .map_column("a", lambda v: v * 10.0)
              .build())
        pipe = StreamingDataPipeline(path, batch_size=16, shuffle=False,
                                     transform_process=tp,
                                     label_column="label", num_classes=3,
                                     n_workers=2)
        feats = np.concatenate([b[0] for b in pipe])
        labels = np.concatenate([b[1] for b in pipe])
        np.testing.assert_allclose(feats[:, 0], cols["a"] * 10.0,
                                   rtol=1e-6)
        assert labels.shape == (n, 3)
        assert (labels.argmax(axis=1) == cols["label"]).all()

    def test_filter_step_rejected_in_streaming(self, tmp_path):
        from deeplearning4j_tpu.etl import (FLOAT, ColumnMeta, Schema,
                                            TransformProcess)
        path = os.path.join(str(tmp_path), "cols")
        write_dataset(path, columns={
            "a": np.zeros(8, np.float32),
            "label": np.zeros(8, np.float32)}, shard_size=4)
        schema = Schema([ColumnMeta("a", FLOAT),
                         ColumnMeta("label", FLOAT)])
        tp = (TransformProcess.builder(schema)
              .filter_rows(lambda c: c["a"] > 0).build())
        with pytest.raises(ValueError, match="streamable"):
            StreamingDataPipeline(path, batch_size=4,
                                  transform_process=tp,
                                  label_column="label")

    def test_pipeline_state_serde_roundtrip(self):
        st = PipelineState(pass_index=3, cursor=7, yielded=6, seed=11,
                           passes_started=4,
                           quarantined_records=[5, 2],
                           pass_quarantine_base=[2],
                           quarantined_shards=[1])
        st2 = PipelineState.from_json(st.to_json())
        assert st2.to_json() == st.to_json()
        assert st2.quarantined_records == [2, 5]    # sorted

    def test_restore_state_rejects_seed_mismatch(self, tmp_path):
        path, _, _ = _dataset(tmp_path)
        pipe = StreamingDataPipeline(path, batch_size=16, seed=1)
        with pytest.raises(DataPipelineError, match="seed"):
            pipe.restore_state(PipelineState(seed=2))

    def test_restore_state_rejects_plan_config_mismatch(self, tmp_path):
        """The cursor is denominated in plan batches of the capturing
        configuration — a different batch_size/shuffle/host split must
        raise instead of silently seeking to different records."""
        path, _, _ = _dataset(tmp_path)
        pipe = StreamingDataPipeline(path, batch_size=16, seed=1)
        list(pipe)
        st = pipe.export_state()
        for other in (StreamingDataPipeline(path, batch_size=8, seed=1),
                      StreamingDataPipeline(path, batch_size=16, seed=1,
                                            shuffle=False)):
            with pytest.raises(DataPipelineError,
                               match="config_mismatch|uses"):
                other.restore_state(st)
        # old states without the config fields restore unchecked
        legacy = dict(st)
        for key in ("batch_size", "shuffle", "host_index", "host_count"):
            legacy.pop(key)
        StreamingDataPipeline(path, batch_size=8,
                              seed=1).restore_state(legacy)

    def test_mid_pass_shard_quarantine_does_not_replan_on_seek(
            self, tmp_path):
        """The pass permutation is computed over the PASS-START shard
        set: a shard quarantined mid-pass withholds its rows from the
        already-planned batches, and a seek back into the pass keeps
        that plan — re-planning over the shrunken id set would shift
        every later batch (duplicating/dropping healthy records)."""
        path, X, _ = _dataset(tmp_path, n=96, shard_size=16)
        pipe = StreamingDataPipeline(path, batch_size=10, shuffle=False,
                                     n_workers=1)
        it = iter(pipe)
        got = [next(it)[0] for _ in range(2)]        # batches 0, 1
        # shard 3 (ids 48..63) dies mid-pass
        pipe._reader.quarantined_shards.add(3)
        rest = [b[0] for b in pipe.seek_batches(2)]
        out = np.concatenate(got + rest)
        # frozen plan: original chunking, shard-3 rows withheld — NOT a
        # re-chunked permutation of the surviving ids
        keep = np.ones(96, bool)
        keep[48:64] = False
        np.testing.assert_array_equal(out, X[keep])
        sizes = [len(b) for b in rest]
        assert sizes == [10, 10, 8, 6, 10, 10, 6]    # 48/49, 60-63 holes

    def test_export_state_preserves_pending_seek(self, tmp_path):
        """A snapshot taken AFTER restore_state but BEFORE the next
        pass begins (FaultTolerantFit's step-0 rollback-target save in
        a relaunched job) must re-export the armed position, not a
        fresh next pass that would skip the interrupted one's rest."""
        path, _, _ = _dataset(tmp_path)
        pipe = StreamingDataPipeline(path, batch_size=16, seed=5)
        it = iter(pipe)
        for _ in range(3):
            next(it)
        st = pipe.export_state()
        fresh = StreamingDataPipeline(path, batch_size=16, seed=5)
        fresh.restore_state(st)
        st2 = fresh.export_state()              # pending, not consumed
        for key in ("pass_index", "cursor", "yielded",
                    "pass_quarantine_base", "pass_shard_base"):
            assert st2[key] == st[key], key

    def test_find_pipeline_unwraps_retrying_iterator(self, tmp_path):
        path, _, _ = _dataset(tmp_path)
        pipe = StreamingDataPipeline(path, batch_size=16)
        assert find_pipeline(pipe) is pipe
        assert find_pipeline(RetryingIterator(pipe)) is pipe
        assert find_pipeline(object()) is None


# ---------------------------------------------------------------------------
# supervised prefetch: crash requeue, respawn, stragglers

class TestPrefetchSupervision:
    @pytest.mark.chaos
    def test_worker_crash_requeued_exactly_once(self, tmp_path):
        path, X, _ = _dataset(tmp_path)
        chaos = ChaosMonkey(seed=2)
        pipe = StreamingDataPipeline(path, batch_size=16, shuffle=False,
                                     n_workers=2)
        with chaos.worker_killer(at_batch=3, times=1):
            batches = list(pipe)
        # every batch delivered exactly once, in order, despite the crash
        np.testing.assert_array_equal(
            np.concatenate([b[0] for b in batches]), X)
        st = pipe.stats()
        assert st["worker_restarts"] == 1
        assert st["requeues"] == 1
        kinds = {e["event"] for e in pipe.events}
        # (worker_restart fires after the respawn backoff; a short pass
        # can finish on the surviving worker first — crash + requeue
        # are the deterministic half of the episode)
        assert {"worker_crash", "prefetch_requeue"} <= kinds
        assert any(e["event"] == "worker_killed" for e in chaos.log)

    @pytest.mark.chaos
    def test_batch_lost_twice_fails_typed(self, tmp_path):
        path, _, _ = _dataset(tmp_path)
        chaos = ChaosMonkey(seed=2)
        pipe = StreamingDataPipeline(path, batch_size=16, shuffle=False,
                                     n_workers=2)
        with chaos.worker_killer(at_batch=3, times=2):
            with pytest.raises(DataPipelineError, match="twice"):
                list(pipe)

    @pytest.mark.chaos
    def test_slow_read_gets_backup_request(self, tmp_path):
        path, X, _ = _dataset(tmp_path, n=96, shard_size=16)
        chaos = ChaosMonkey(seed=2)
        pipe = StreamingDataPipeline(path, batch_size=16, shuffle=False,
                                     n_workers=2, read_timeout_s=0.15)
        with chaos.slow_reader(delay_s=1.0, times=1):
            batches = list(pipe)
        # the straggler read was hedged; content exact, nothing doubled
        np.testing.assert_array_equal(
            np.concatenate([b[0] for b in batches]), X)
        assert pipe.stats()["slow_reads"] >= 1
        assert any(e["event"] == "slow_read" for e in pipe.events)


# ---------------------------------------------------------------------------
# record-level quarantine

class TestRecordQuarantine:
    def _poisoned_dataset(self, tmp_path, bad_rows=(5, 23)):
        X, Y = _data(n=64)
        for r in bad_rows:
            X[r, 1] = np.nan
        path = os.path.join(str(tmp_path), "ds")
        write_dataset(path, X, Y, shard_size=16)
        return path, X, bad_rows

    def test_corrupt_rows_dropped_and_persisted_across_passes(
            self, tmp_path):
        path, X, bad_rows = self._poisoned_dataset(tmp_path)
        pipe = StreamingDataPipeline(path, batch_size=16, shuffle=False,
                                     n_workers=2)
        pass0 = np.concatenate([b[0] for b in pipe])
        assert len(pass0) == 64 - len(bad_rows)
        assert np.isfinite(pass0).all()
        assert pipe.quarantined_records == set(bad_rows)
        assert any(e["event"] == "record_quarantine" for e in pipe.events)
        # pass 2: quarantined ids excluded from the PLAN up front —
        # batch sizes are exact again (no mid-batch holes)
        sizes = [len(b[0]) for b in pipe]
        assert sizes == [16, 16, 16, 14]
        assert pipe.stats()["rows_quarantined"] == len(bad_rows)

    def test_quarantine_state_rides_pipeline_state(self, tmp_path):
        path, _, bad_rows = self._poisoned_dataset(tmp_path)
        pipe = StreamingDataPipeline(path, batch_size=16, shuffle=False)
        list(pipe)
        st = PipelineState.from_json(pipe.export_state())
        assert st.quarantined_records == sorted(bad_rows)
        # a FRESH pipeline restoring the boundary state first replays
        # the finished pass AT ITS END (empty — the form that absorbs a
        # not-yet-counted epoch, see export_state), then the next pass
        # excludes the quarantined ids up front
        pipe2 = StreamingDataPipeline(path, batch_size=16, shuffle=False)
        pipe2.restore_state(st)
        assert sum(len(b[0]) for b in pipe2) == 0
        assert sum(len(b[0]) for b in pipe2) == 64 - len(bad_rows)

    def test_composes_with_retrying_iterator_batch_semantics(
            self, tmp_path):
        # the pipeline's record-level quarantine feeds CLEAN batches to
        # RetryingIterator, whose batch-level corrupt scan then never
        # fires — the two rails compose instead of double-dropping
        path, _, bad_rows = self._poisoned_dataset(tmp_path)
        pipe = StreamingDataPipeline(path, batch_size=16, shuffle=False)
        wrapped = RetryingIterator(pipe)
        total = sum(len(b[0]) for b in wrapped)
        assert total == 64 - len(bad_rows)
        assert wrapped.quarantined == set()     # nothing left to catch


# ---------------------------------------------------------------------------
# RetryingIterator: seek path vs O(n) fallback (regression pins BOTH)

class TestRetryingIteratorSeek:
    def test_seekable_source_recovers_by_seeking(self, tmp_path):
        path, X, _ = _dataset(tmp_path, n=96, shard_size=16)
        pipe = StreamingDataPipeline(path, batch_size=16, shuffle=True,
                                     seed=4, n_workers=1)
        reference = [b[0] for b in
                     StreamingDataPipeline(path, batch_size=16,
                                           shuffle=True, seed=4,
                                           n_workers=1)]

        class FlakyOnce:
            """Transient failure surfaced to RetryingIterator at batch
            3 of the pass."""

            def __init__(self, wrapped):
                self._wrapped = wrapped
                self.fired = False

            def reset(self):
                self._wrapped.reset()

            def __iter__(self):
                for i, b in enumerate(self._wrapped):
                    if i == 3 and not self.fired:
                        self.fired = True
                        raise IOError("flake")
                    yield b

            def seek_batches(self, skip):
                # delegate: this wrapper is transparent to position
                return iter(self._seek_gen(skip))

            def _seek_gen(self, skip):
                it = self._wrapped.seek_batches(skip)
                for i, b in enumerate(it):
                    if i + skip == 3 and not self.fired:
                        self.fired = True
                        raise IOError("flake")
                    yield b

        flaky = FlakyOnce(pipe)
        out = [b[0] for b in RetryingIterator(flaky)]
        # recovered pass == the uninterrupted pass-0 permutation,
        # because the seek stayed INSIDE the same pass
        assert len(out) == len(reference)
        for a, b in zip(out, reference):
            np.testing.assert_array_equal(a, b)
        # the pipeline never replayed batches 0..2 (seek, not ffwd):
        # 6 plan batches + 1 re-delivery of the batch the flake ate
        # (an O(n) fallback would have re-pulled the whole prefix)
        assert pipe.stats()["batches"] == len(reference) + 1

    def test_second_recovery_in_one_pass_seeks_correctly(self, tmp_path):
        """RetryingIterator's per-pass batch index is ABSOLUTE and
        never resets across recoveries — the pipeline must anchor
        repeated seeks to the pass start, not to the previous seek's
        generator (double-counting raised a spurious source_shrank on
        the SECOND transient failure of a pass)."""
        path, X, _ = _dataset(tmp_path, n=96, shard_size=16)
        pipe = StreamingDataPipeline(path, batch_size=16, shuffle=False,
                                     n_workers=1)

        class FlakyTwice:
            def __init__(self, wrapped):
                self._wrapped = wrapped
                self.fail_at = {1, 4}            # two failures, one pass

            def reset(self):
                self._wrapped.reset()

            def __iter__(self):
                return self._gen(iter(self._wrapped), 0)

            def seek_batches(self, skip):
                return self._gen(self._wrapped.seek_batches(skip), skip)

            def _gen(self, it, base):
                for i, b in enumerate(it):
                    if base + i in self.fail_at:
                        self.fail_at.discard(base + i)
                        raise IOError("flake")
                    yield b

        out = [b[0] for b in RetryingIterator(FlakyTwice(pipe))]
        assert len(out) == 6
        np.testing.assert_array_equal(np.concatenate(out), X)

    def test_plain_iterator_keeps_on_fallback_path(self):
        """The O(n) reset+fast-forward fallback still recovers plain
        deterministic iterators (and re-pulls the already-delivered
        prefix, which is what makes it O(n))."""
        X = np.arange(40, dtype=np.float32).reshape(10, 4)
        pulls = {"n": 0}

        class Flaky:
            def __init__(self):
                self.fired = False

            def reset(self):
                pass

            def __iter__(self):
                for i in range(0, 10, 2):
                    pulls["n"] += 1
                    if i == 6 and not self.fired:
                        self.fired = True
                        raise IOError("flake")
                    yield X[i:i + 2], X[i:i + 2]

        out = list(RetryingIterator(Flaky()))
        assert len(out) == 5
        # 4 pulls to the failure + 3 replayed (fast-forward) + 2 rest
        assert pulls["n"] > 5                   # the O(n) replay happened
        np.testing.assert_array_equal(
            np.concatenate([b[0] for b in out]), X)


# ---------------------------------------------------------------------------
# fit integration: bit-exactness, checkpoints, seek-resume

class TestFitIntegration:
    def test_disk_backed_fit_bit_exact_vs_in_memory(self, tmp_path):
        from deeplearning4j_tpu.dataset import ArrayDataSetIterator
        path, X, Y = _dataset(tmp_path)
        sd_mem = _mlp()
        sd_mem.fit(ArrayDataSetIterator(X, Y, batch_size=16),
                   epochs=3, listeners=[_quiet()])
        sd_disk = _mlp()
        pipe = StreamingDataPipeline(path, batch_size=16, shuffle=False,
                                     n_workers=2)
        sd_disk.fit(pipe, epochs=3, listeners=[_quiet()])
        _assert_params_equal(_params(sd_mem), _params(sd_disk))
        # the per-step tier trains the same trajectory too
        sd_ps = _mlp(fused_steps=1)
        pipe_ps = StreamingDataPipeline(path, batch_size=16,
                                        shuffle=False, n_workers=2)
        sd_ps.fit(pipe_ps, epochs=3, listeners=[_quiet()])
        _assert_params_equal(_params(sd_mem), _params(sd_ps))

    def test_checkpoints_embed_pipeline_state(self, tmp_path):
        from deeplearning4j_tpu.checkpoint import (CheckpointListener,
                                                   CheckpointManager)
        path, _, _ = _dataset(tmp_path)
        sd = _mlp()
        pipe = StreamingDataPipeline(path, batch_size=16, seed=5)
        mgr = CheckpointManager(tmp_path / "ck", keep_last_n=None,
                                async_write=False)
        sd.fit(pipe, epochs=2,
               listeners=[CheckpointListener(mgr, every_n_iterations=2)])
        state = mgr.restore(4)                  # mid-epoch-0
        dp = state.metadata["datapipe"]
        assert dp["pass_index"] == 0 and dp["cursor"] == 4
        assert dp["seed"] == 5
        state8 = mgr.restore(8)                 # mid-epoch-1
        assert state8.metadata["datapipe"]["pass_index"] == 1
        assert state8.metadata["datapipe"]["cursor"] == 2

    def test_mid_epoch_seek_resume_bit_exact_incl_dropout(self, tmp_path):
        """The acceptance drill: restore a MID-EPOCH snapshot in a
        fresh process (fresh model + fresh pipeline), seek, finish —
        bit-exact vs uninterrupted including the shuffle RNG (seeded
        pass permutations) and dropout (iteration-folded keys)."""
        from deeplearning4j_tpu.checkpoint import (CheckpointListener,
                                                   CheckpointManager)
        from deeplearning4j_tpu.checkpoint.state import \
            restore_training_state
        path, _, _ = _dataset(tmp_path)
        sd_a = _mlp(dropout=0.3)
        sd_a.fit(StreamingDataPipeline(path, batch_size=16, seed=5),
                 epochs=2, listeners=[_quiet()])
        pa = _params(sd_a)
        sd_b = _mlp(dropout=0.3)
        mgr = CheckpointManager(tmp_path / "ck", keep_last_n=None,
                                async_write=False)
        sd_b.fit(StreamingDataPipeline(path, batch_size=16, seed=5),
                 epochs=2,
                 listeners=[CheckpointListener(mgr, every_n_iterations=2)])
        state = mgr.restore(4)                  # mid-epoch 0
        sd_c = _mlp(dropout=0.3)
        restore_training_state(sd_c, state)
        pipe_c = StreamingDataPipeline(path, batch_size=16, seed=5)
        pipe_c.restore_state(state.metadata["datapipe"])
        sd_c.fit(pipe_c, epochs=2, listeners=[_quiet()])
        _assert_params_equal(pa, _params(sd_c))

    @pytest.mark.chaos
    def test_rollback_seeks_instead_of_replaying(self, tmp_path):
        """A mid-fit fault rolls back to a mid-epoch snapshot and the
        pipeline SEEKS (datapipe_seek event) — final params bit-exact
        vs uninterrupted, across mid-epoch AND epoch-boundary
        snapshots."""
        from deeplearning4j_tpu.checkpoint import CheckpointManager
        path, _, _ = _dataset(tmp_path)
        sd_ref = _mlp()
        mgr_ref = CheckpointManager(tmp_path / "ckr", keep_last_n=None,
                                    async_write=False)
        FaultTolerantFit(sd_ref, mgr_ref,
                         checkpoint_every_n_iterations=2,
                         policy=RetryPolicy(backoff_base=0.0)).fit(
            StreamingDataPipeline(path, batch_size=16, seed=5),
            epochs=3)
        p_ref = _params(sd_ref)
        it_ref = sd_ref.training_config.iteration_count
        for fault_at in (7, 11):       # mid-epoch / epoch-boundary
            sd = _mlp()
            pipe = StreamingDataPipeline(path, batch_size=16, seed=5)
            mgr = CheckpointManager(tmp_path / f"ck{fault_at}",
                                    keep_last_n=None, async_write=False)
            ftf = FaultTolerantFit(sd, mgr,
                                   checkpoint_every_n_iterations=2,
                                   policy=RetryPolicy(backoff_base=0.0))
            ftf.fit(pipe, epochs=3, listeners=[_FaultAt(fault_at)])
            assert ftf.rollbacks == 1
            assert any(e["event"] == "datapipe_seek"
                       for e in ftf.events)
            assert sd.training_config.iteration_count == it_ref
            _assert_params_equal(p_ref, _params(sd),
                                 msg=f"fault@{fault_at}: ")

    def test_resume_latest_applies_pipeline_state_on_next_fit(
            self, tmp_path):
        """The relaunched-job path: resume_latest() BEFORE fit() sees
        the iterator — the pending PipelineState applies when fit
        registers the pipeline."""
        from deeplearning4j_tpu.checkpoint import (CheckpointListener,
                                                   CheckpointManager)
        path, _, _ = _dataset(tmp_path)
        sd_a = _mlp()
        sd_a.fit(StreamingDataPipeline(path, batch_size=16, seed=5),
                 epochs=2, listeners=[_quiet()])
        pa = _params(sd_a)
        sd_b = _mlp()
        mgr = CheckpointManager(tmp_path / "ck", keep_last_n=None,
                                async_write=False)
        # "interrupted" run: one epoch, single mid-epoch snapshot at
        # step 4 — the relaunch restores via resume_latest, then fit()
        # with a FRESH pipeline applies the pending PipelineState
        sd_b.fit(StreamingDataPipeline(path, batch_size=16, seed=5),
                 epochs=1,
                 listeners=[CheckpointListener(mgr,
                                               every_n_iterations=4)])
        assert mgr.latest_step() == 4
        sd_c = _mlp()
        ftf = FaultTolerantFit(sd_c, mgr,
                               checkpoint_every_n_iterations=4,
                               policy=RetryPolicy(backoff_base=0.0))
        assert ftf.resume_latest() is not None
        pipe_c = StreamingDataPipeline(path, batch_size=16, seed=5)
        ftf.fit(pipe_c, epochs=2)
        assert any(e["event"] == "datapipe_seek" for e in ftf.events)
        _assert_params_equal(pa, _params(sd_c))


# ---------------------------------------------------------------------------
# telemetry: records at flush boundaries, fold, report, /metrics

class TestTelemetry:
    def test_datapipe_records_fold_and_render(self, tmp_path):
        from deeplearning4j_tpu.monitor import (MonitorListener,
                                                disable_tracing,
                                                enable_tracing)
        from deeplearning4j_tpu.monitor.registry import MetricsRegistry
        from deeplearning4j_tpu.ui.report import render_report
        from deeplearning4j_tpu.ui.stats import StatsStorage
        path, _, _ = _dataset(tmp_path)
        sd = _mlp()
        pipe = StreamingDataPipeline(path, batch_size=16, seed=5,
                                     n_workers=2)
        storage = StatsStorage()
        enable_tracing(reset=True)
        try:
            mon = MonitorListener(storage, registry=MetricsRegistry(),
                                  frequency=2)
            sd.fit(pipe, epochs=2, listeners=[mon])
        finally:
            disable_tracing()
        recs = storage.of_type("datapipe")
        assert recs, "no datapipe records at flush boundaries"
        assert sum(r.get("records", 0) for r in recs) == 2 * 96
        assert any(r.get("records_per_sec") is not None for r in recs)
        assert any(r.get("data_wait_frac") is not None for r in recs)
        assert any(r.get("worker_utilization") for r in recs)
        prom = mon.registry.to_prometheus_text()
        assert "dl4j_datapipe_records_total 192" in prom
        assert "dl4j_datapipe_worker_utilization" in prom
        html = render_report(storage)
        assert "Data pipeline" in html
        # record-type lint contract: no forward-compat footer leak
        assert "unrendered record types" not in html

    def test_fold_datapipe_direct(self):
        from deeplearning4j_tpu.monitor.registry import MetricsRegistry
        reg = MetricsRegistry()
        reg.fold_datapipe({"type": "datapipe", "records": 128,
                           "read_retries": 2, "rows_quarantined": 1,
                           "records_per_sec": 5000.0,
                           "data_wait_frac": 0.25,
                           "worker_utilization": {"0": 0.8, "1": 0.4}})
        assert reg.get("datapipe_records_total") == 128
        assert reg.get("datapipe_read_retries_total") == 2
        assert reg.get("datapipe_data_wait_fraction") == 0.25
        assert reg.get("datapipe_worker_utilization", worker="0") == 0.8


# ---------------------------------------------------------------------------
# the acceptance e2e: one run survives torn shard + dead worker +
# transient reads, zero dropped/duplicated samples, bit-exact

class TestChaosE2E:
    @pytest.mark.chaos
    def test_self_heal_e2e_bit_exact(self, tmp_path):
        from deeplearning4j_tpu.checkpoint import CheckpointManager
        from deeplearning4j_tpu.ui.stats import StatsStorage
        path, _, _ = _dataset(tmp_path)
        # clean reference trajectory
        sd_ref = _mlp()
        mgr_ref = CheckpointManager(tmp_path / "ckr", keep_last_n=None,
                                    async_write=False)
        FaultTolerantFit(sd_ref, mgr_ref,
                         checkpoint_every_n_iterations=2,
                         policy=RetryPolicy(backoff_base=0.0)).fit(
            StreamingDataPipeline(path, batch_size=16, seed=5,
                                  n_workers=2), epochs=3)
        p_ref = _params(sd_ref)
        it_ref = sd_ref.training_config.iteration_count
        # chaos run: transient torn shard (heals after 2 failed reads)
        # + a killed prefetch worker + transient IO, all in ONE fit
        sd = _mlp()
        storage = StatsStorage()
        pipe = StreamingDataPipeline(path, batch_size=16, seed=5,
                                     n_workers=2, read_retries=3)
        mgr = CheckpointManager(tmp_path / "ck", keep_last_n=None,
                                async_write=False)
        ftf = FaultTolerantFit(sd, mgr, checkpoint_every_n_iterations=2,
                               policy=RetryPolicy(backoff_base=0.0),
                               stats_storage=storage)
        chaos = ChaosMonkey(seed=7)
        torn = chaos.torn_shard(path, shard_index=2,
                                heal_after_failures=2, pipeline=pipe)
        torn.inject()
        try:
            with chaos.worker_killer(at_batch=3, times=1):
                with chaos.flaky_read(times=2, every=3):
                    history = ftf.fit(pipe, epochs=3)
        finally:
            torn.heal()
        assert history is not None
        # zero dropped/duplicated samples: the strongest proof is the
        # bit-exact trajectory — any drop/dup would shift every later
        # update
        assert sd.training_config.iteration_count == it_ref
        _assert_params_equal(p_ref, _params(sd))
        st = pipe.stats()
        assert st["read_retries"] >= 2          # chaos really fired
        assert st["worker_restarts"] == 1
        assert st["rows_quarantined"] == 0      # transient, not dropped
        kinds = {e["event"] for e in pipe.events}
        assert {"read_retry", "worker_crash", "prefetch_requeue"} <= kinds
        assert {"shard_torn", "shard_healed", "worker_killed",
                "read_failed"} <= {e["event"] for e in chaos.log}
