"""EvaluationCalibration tests (reference: nd4j EvaluationCalibrationTest +
EvaluationCalibration.java:53-467)."""
import numpy as np
import pytest

from deeplearning4j_tpu.evaluation import (EvaluationCalibration,
                                           Histogram, channel_scales,
                                           histogram_quantile)


def test_reliability_diagram_hand_computed():
    ec = EvaluationCalibration(reliability_bins=5, exclude_empty_bins=False)
    # 4 examples, 2 classes. Class-1 probs: 0.1, 0.3, 0.7, 0.9;
    # class-1 labels: 0, 0, 1, 1.
    p1 = np.array([0.1, 0.3, 0.7, 0.9])
    preds = np.stack([1 - p1, p1], axis=1)
    labels = np.eye(2)[[0, 0, 1, 1]]
    ec.eval(labels, preds)
    rd = ec.reliability_diagram(1)
    # bins of width 0.2 -> probs land in bins 0,1,3,4; each one example.
    assert rd.bin_counts.tolist() == [1, 1, 0, 1, 1]
    assert rd.mean_predicted_value[0] == pytest.approx(0.1)
    assert rd.frac_positives[0] == 0.0
    assert rd.mean_predicted_value[4] == pytest.approx(0.9)
    assert rd.frac_positives[4] == 1.0


def test_reliability_diagram_excludes_empty_bins():
    ec = EvaluationCalibration(reliability_bins=5)
    p1 = np.array([0.1, 0.9])
    ec.eval(np.eye(2)[[0, 1]], np.stack([1 - p1, p1], axis=1))
    rd = ec.reliability_diagram(1)
    assert rd.bin_counts.tolist() == [1, 1]


def test_label_and_prediction_counts():
    ec = EvaluationCalibration()
    labels = np.eye(3)[[0, 0, 1, 2, 2, 2]]
    preds = np.eye(3)[[0, 1, 1, 2, 2, 0]] * 0.8 + 0.1 / 3
    ec.eval(labels, preds)
    assert ec.label_counts_each_class().tolist() == [2, 1, 3]
    assert ec.prediction_counts_each_class().tolist() == [2, 2, 2]


def test_class_index_labels_accepted():
    ec = EvaluationCalibration()
    preds = np.array([[0.9, 0.1], [0.2, 0.8]])
    ec.eval(np.array([0, 1]), preds)
    assert ec.label_counts_each_class().tolist() == [1, 1]


def test_residual_plot_counts():
    ec = EvaluationCalibration(histogram_bins=10)
    preds = np.array([[0.95, 0.05]])   # residuals 0.05, 0.05 -> bin 0
    ec.eval(np.array([[1.0, 0.0]]), preds)
    h = ec.residual_plot_all_classes()
    assert h.bin_counts[0] == 2 and h.bin_counts.sum() == 2
    h0 = ec.residual_plot(0)
    # per-class plots count ONLY the label column (i, c) of rows labeled
    # c (reference residualPlotByLabelClass): one entry for the one row
    assert h0.bin_counts.sum() == 1
    assert h0.bin_counts[0] == 1       # residual |1 - 0.95| -> bin 0
    assert ec.residual_plot(1).bin_counts.sum() == 0


def test_probability_histogram():
    ec = EvaluationCalibration(histogram_bins=4)
    preds = np.array([[0.1, 0.9], [0.6, 0.4]])
    ec.eval(np.array([1, 0]), preds)
    h = ec.probability_histogram_all_classes()
    # probs 0.1, 0.9, 0.6, 0.4 -> bins 0, 3, 2, 1
    assert h.bin_counts.tolist() == [1, 1, 1, 1]


def test_ece_perfectly_calibrated_is_zero():
    rng = np.random.default_rng(0)
    ec = EvaluationCalibration(reliability_bins=1)
    # With a single bin, conf = mean(p), acc = frac positives; make them
    # equal exactly: two examples at p=0.5, one positive.
    preds = np.array([[0.5, 0.5], [0.5, 0.5]])
    ec.eval(np.array([0, 1]), preds)
    assert ec.expected_calibration_error(1) == pytest.approx(0.0)


def test_ece_overconfident_detected():
    ec = EvaluationCalibration(reliability_bins=10)
    # Predict class 1 at 0.95 on 10 examples, only 5 actually positive.
    preds = np.tile([[0.05, 0.95]], (10, 1))
    labels = np.array([1, 1, 1, 1, 1, 0, 0, 0, 0, 0])
    ec.eval(labels, preds)
    assert ec.expected_calibration_error(1) == pytest.approx(0.45)


def test_batched_eval_equals_single_eval():
    rng = np.random.default_rng(1)
    logits = rng.normal(size=(64, 4))
    preds = np.exp(logits) / np.exp(logits).sum(1, keepdims=True)
    labels = rng.integers(0, 4, 64)
    a = EvaluationCalibration()
    a.eval(labels, preds)
    b = EvaluationCalibration()
    b.eval(labels[:30], preds[:30])
    b.eval(labels[30:], preds[30:])
    for i in range(4):
        ra, rb = a.reliability_diagram(i), b.reliability_diagram(i)
        np.testing.assert_array_equal(ra.bin_counts, rb.bin_counts)
        np.testing.assert_allclose(ra.mean_predicted_value,
                                   rb.mean_predicted_value)
    np.testing.assert_array_equal(a.residual_plot_all_classes().bin_counts,
                                  b.residual_plot_all_classes().bin_counts)


def test_merge_and_mask():
    a = EvaluationCalibration()
    b = EvaluationCalibration()
    preds = np.array([[0.9, 0.1], [0.3, 0.7], [0.5, 0.5]])
    labels = np.array([0, 1, 0])
    a.eval(labels, preds, mask=np.array([1, 1, 0]))  # drops last row
    b.eval(labels[2:], preds[2:])
    a.merge(b)
    assert a.label_counts_each_class().tolist() == [2, 1]
    assert "EvaluationCalibration" in a.stats()


def test_num_classes_mismatch_raises():
    ec = EvaluationCalibration()
    ec.eval(np.array([0]), np.array([[0.6, 0.4]]))
    with pytest.raises(ValueError):
        ec.eval(np.array([0]), np.array([[0.5, 0.3, 0.2]]))


def test_sequence_index_labels_with_mask():
    """Regression: [N,T] class-index labels + [N,T] mask (padded RNN
    batches) must accumulate like the flattened equivalent."""
    preds = np.array([[[0.9, 0.1], [0.2, 0.8], [0.5, 0.5]],
                      [[0.3, 0.7], [0.6, 0.4], [0.1, 0.9]]])
    labels = np.array([[0, 1, 0], [1, 0, 1]])
    mask = np.array([[1, 1, 0], [1, 1, 0]])
    a = EvaluationCalibration()
    a.eval(labels, preds, mask=mask)
    b = EvaluationCalibration()
    b.eval(np.array([0, 1, 1, 0]),
           preds.reshape(-1, 2)[[0, 1, 3, 4]])
    assert a.label_counts_each_class().tolist() == \
        b.label_counts_each_class().tolist()
    np.testing.assert_array_equal(
        a.residual_plot_all_classes().bin_counts,
        b.residual_plot_all_classes().bin_counts)


# ---------------------------------------------------------------------------
# channel_scales / histogram_quantile (ISSUE 18: the int8 weight/KV
# calibration rides this module's binning machinery)

def test_channel_scales_absmax_exact():
    x = np.array([[1.0, -2.0], [-4.0, 0.5]])
    s = channel_scales(x, qmax=127.0)
    np.testing.assert_allclose(s, [4.0 / 127.0, 2.0 / 127.0], rtol=1e-6)
    assert s.dtype == np.float32
    # leading axes flatten into observations: [B, T, C] == [B*T, C]
    y = np.arange(24, dtype=np.float64).reshape(2, 4, 3)
    np.testing.assert_allclose(channel_scales(y),
                               channel_scales(y.reshape(-1, 3)))


def test_channel_scales_all_zero_channel_is_identity():
    x = np.zeros((8, 3))
    x[:, 1] = 5.0
    s = channel_scales(x)
    # no positive mass -> scale 1.0: payload 0, dequant 0, never NaN
    assert s[0] == 1.0 and s[2] == 1.0
    assert s[1] == pytest.approx(5.0 / 127.0)


def test_channel_scales_nonfinite_masked():
    x = np.array([[np.nan, 1.0], [np.inf, -3.0], [-np.inf, np.nan]])
    s = channel_scales(x)
    assert np.all(np.isfinite(s))
    assert s[0] == 1.0                     # all-non-finite -> identity
    assert s[1] == pytest.approx(3.0 / 127.0)
    # quantile method is NaN-safe through the same mask
    sq = channel_scales(x, method="quantile", quantile=0.999)
    assert np.all(np.isfinite(sq)) and sq[0] == 1.0


def test_channel_scales_quantile_clips_outliers():
    rng = np.random.default_rng(5)
    x = rng.normal(0, 1, (4096, 2))
    x[0, 0] = 1000.0                       # one spike in channel 0
    s_abs = channel_scales(x, method="absmax")
    s_q = channel_scales(x, method="quantile", quantile=0.999)
    assert s_abs[0] == pytest.approx(1000.0 / 127.0)
    assert s_q[0] < 0.1 * s_abs[0]         # spike does not set the grid
    # without an outlier, quantile ~= absmax (within bin resolution)
    assert s_q[1] <= s_abs[1] * 1.01


def test_channel_scales_validation():
    with pytest.raises(ValueError):
        channel_scales(np.zeros((4, 2)), method="median")
    with pytest.raises(ValueError):
        channel_scales(np.zeros((4, 2)), method="quantile", quantile=0.0)
    with pytest.raises(ValueError):
        channel_scales(np.float64(3.0))    # scalar: no channel axis


def test_histogram_quantile_right_edge():
    h = Histogram("t", 0.0, 1.0, np.array([1, 1, 1, 1]))
    assert histogram_quantile(h, 0.5) == pytest.approx(0.5)
    assert histogram_quantile(h, 1.0) == pytest.approx(1.0)
    with pytest.raises(ValueError):
        histogram_quantile(h, 0.0)
