"""Interop runtime tests (reference: nd4j-tensorflow GraphRunnerTest —
load a foreign graph, feed/fetch by name, persistent session reuse)."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from deeplearning4j_tpu.interop import OnnxRuntimeRunner, TorchRunner


class _TwoHead(torch.nn.Module):
    def __init__(self):
        super().__init__()
        self.lin = torch.nn.Linear(4, 3)

    def forward(self, x):
        h = self.lin(x)
        return h, torch.softmax(h, dim=-1)


def test_run_module_named_outputs():
    m = _TwoHead()
    runner = TorchRunner(m, output_names=["logits", "probs"])
    x = np.random.RandomState(0).randn(5, 4).astype(np.float32)
    out = runner.run({"x": x})
    assert set(out) == {"logits", "probs"}
    np.testing.assert_allclose(out["probs"].sum(axis=1), 1.0, rtol=1e-5)
    # persistent session: second run, same model object
    out2 = runner.run({"x": x})
    np.testing.assert_array_equal(out["logits"], out2["logits"])


def test_zero_copy_numpy_feed():
    """numpy feeds share memory with the torch tensor (the zero-copy
    contract GraphRunner gets from TensorflowConversion)."""
    from deeplearning4j_tpu.interop.torch_runner import _to_torch
    a = np.ones((3, 3), np.float32)
    t = _to_torch(a, torch)
    t[0, 0] = 42.0
    assert a[0, 0] == 42.0            # same buffer


def test_multi_input_order_and_missing_key():
    class Add(torch.nn.Module):
        def forward(self, a, b):
            return a + 2 * b
    r = TorchRunner(Add(), input_order=["a", "b"])
    a = np.full((2, 2), 1.0, np.float32)
    b = np.full((2, 2), 3.0, np.float32)
    out = r.run({"a": a, "b": b})
    np.testing.assert_array_equal(out["output_0"], a + 2 * b)
    with pytest.raises(KeyError, match="missing"):
        r.run({"a": a})


def test_torchscript_file_roundtrip(tmp_path):
    m = torch.jit.script(torch.nn.Sequential(
        torch.nn.Linear(4, 8), torch.nn.ReLU(), torch.nn.Linear(8, 2)))
    p = str(tmp_path / "model.pt")
    torch.jit.save(m, p)
    runner = TorchRunner(p)
    x = np.random.RandomState(1).randn(3, 4).astype(np.float32)
    out = runner.run({"x": x})
    want = m(torch.from_numpy(x)).detach().numpy()
    np.testing.assert_allclose(out["output_0"], want, atol=1e-6)


def test_jax_array_feed_and_device_fetch():
    class Neg(torch.nn.Module):
        def forward(self, x):
            return -x
    import jax.numpy as jnp
    r = TorchRunner(Neg())
    x = jnp.arange(6, dtype=jnp.float32).reshape(2, 3)
    out = r.run_to_device({"x": x})
    import jax
    assert isinstance(out["output_0"], jax.Array)
    np.testing.assert_array_equal(np.asarray(out["output_0"]),
                                  -np.asarray(x))


def test_framework_pipeline_through_foreign_model():
    """The GraphRunner use case: a foreign torch feature extractor inside
    a framework training pipeline."""
    from deeplearning4j_tpu.learning.updaters import Sgd
    from deeplearning4j_tpu.nn import (
        DenseLayer, InputType, MultiLayerNetwork, NeuralNetConfiguration,
        OutputLayer)
    torch.manual_seed(0)
    extractor = TorchRunner(torch.nn.Sequential(
        torch.nn.Linear(8, 6), torch.nn.Tanh()))
    rng = np.random.RandomState(2)
    X = rng.randn(64, 8).astype(np.float32)
    y = (X[:, 0] > 0).astype(int)
    feats = extractor.run({"x": X})["output_0"]
    conf = (NeuralNetConfiguration.builder().seed(0).updater(Sgd(0.5))
            .list().layer(DenseLayer(n_out=8, activation="relu"))
            .layer(OutputLayer(n_out=2, loss_function="MCXENT"))
            .set_input_type(InputType.feed_forward(6)).build())
    net = MultiLayerNetwork(conf).init()
    h = net.fit(feats, np.eye(2, dtype=np.float32)[y], epochs=10,
                batch_size=32)
    assert h.loss_curve.losses[-1] < h.loss_curve.losses[0]


def test_closed_runner_rejects_and_onnx_gated():
    r = TorchRunner(torch.nn.Identity())
    r.close()
    with pytest.raises(RuntimeError, match="closed"):
        r.run({"x": np.zeros((1,), np.float32)})
    try:
        import onnxruntime  # noqa: F401
        have_ort = True
    except ImportError:
        have_ort = False
    if not have_ort:
        with pytest.raises(RuntimeError, match="onnxruntime"):
            OnnxRuntimeRunner("/nonexistent.onnx")
