"""Paged KV serving (serving/paged/, ISSUE 16).

Pinned contracts:

- allocator discipline: refcounted blocks freed exactly once (a second
  release raises), pool exhaustion under admission pressure sheds TYPED
  (:class:`PoolExhaustedError` with ``retry_after_s``) instead of
  crashing a worker, and the full accounting invariant (free + held +
  evictable == capacity, refcounts == live-table occurrences) holds
  after every scheduler step under ``debug_leaks=True`` — through
  completion, shed, cancel AND crash-recovery requeue;
- block tables grow on demand at decode-step boundaries across every
  pow2 prefill bucket;
- prefix caching: chain-hashed full blocks are shared by refcount,
  survive interleaved admit/complete churn, skip their prefill (a hit
  dispatches the small SUFFIX bucket, not the full-prompt bucket), and
  never change greedy output;
- hot reload: ``update_model()`` flushes the prefix cache (its blocks
  hold K/V computed with the superseded weights) before any later
  lookup — a repeated prompt after a reload re-prefills from scratch
  and matches the NEW model's reference;
- permanent errors stay permanent: an invalid request raises
  ValueError even with the pool fully committed (validation precedes
  the block commitment), never a retryable PoolExhaustedError;
- greedy tokens are IDENTICAL to the dense server's reference
  (:func:`greedy_decode`) — paged vs dense is a memory-layout change,
  not a numerics change — including under tensor parallelism (tp=2 on
  the virtual 8-device CPU mesh).
"""
import time

import numpy as np
import pytest

from deeplearning4j_tpu.serving.generative import greedy_decode
from deeplearning4j_tpu.serving.paged import (NULL_BLOCK, BlockPool,
                                              PagedGenerativeServer,
                                              PagedMetrics,
                                              PoolExhaustedError,
                                              blocks_for_tokens,
                                              prefix_block_hashes)
from deeplearning4j_tpu.serving.queue import ServerOverloadedError
from deeplearning4j_tpu.serving.resilience import ResilienceConfig
from deeplearning4j_tpu.zoo.gpt import (GPTConfig, build_gpt,
                                        gpt_generative_spec,
                                        gpt_paged_spec)

CFG = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
                intermediate_size=64, max_seq_len=32)
MSL = 32
BS = 8


@pytest.fixture(scope="module")
def gpt_sd():
    return build_gpt(CFG, batch=2, seq_len=8, seed=0)


@pytest.fixture(scope="module")
def spec(gpt_sd):
    # one spec for the whole module: the jitted programs are memoized
    # per (spec, geometry), so every server below shares one compile set
    return gpt_paged_spec(gpt_sd, CFG)


@pytest.fixture(scope="module")
def dense_spec(gpt_sd):
    return gpt_generative_spec(gpt_sd, CFG)


def make_server(spec, **kw):
    kw.setdefault("max_slots", 4)
    kw.setdefault("max_seq_len", MSL)
    kw.setdefault("block_size", BS)
    kw.setdefault("warmup", False)
    kw.setdefault("debug_leaks", True)
    return PagedGenerativeServer(spec, **kw)


def ref_tokens(dense_spec, prompt, n):
    return greedy_decode(dense_spec, prompt, n, max_seq_len=MSL)


def mixed_prompts(n=6, seed=0, max_len=12):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, CFG.vocab_size,
                         int(rng.integers(1, max_len + 1)))
            .astype(np.int32) for _ in range(n)]


def wait_uncommitted(srv, timeout=10.0):
    """Block-commitment release rides the request future's done
    callback, which CPython fires AFTER result() waiters wake — give
    the callbacks a moment before asserting on ``_committed``."""
    deadline = time.monotonic() + timeout
    while srv._committed and time.monotonic() < deadline:
        time.sleep(0.01)
    return srv._committed


# ----------------------------------------------------------------------
class TestBlockPool:
    def test_alloc_release_cycle(self):
        p = BlockPool(5, 4)
        assert p.capacity == 4 and p.free_count() == 4
        blocks = [p.alloc() for _ in range(4)]
        assert NULL_BLOCK not in blocks
        assert len(set(blocks)) == 4 and p.free_count() == 0
        with pytest.raises(PoolExhaustedError) as ei:
            p.alloc()
        assert ei.value.retry_after_s > 0
        assert isinstance(ei.value, ServerOverloadedError)
        for b in blocks:
            p.release(b)
        assert p.free_count() == 4
        p.check_invariant(tables=[])

    def test_double_free_raises(self):
        p = BlockPool(4, 2)
        b = p.alloc()
        p.release(b)
        with pytest.raises(RuntimeError, match="released twice"):
            p.release(b)

    def test_refcount_shared_block(self):
        p = BlockPool(4, 2)
        b = p.alloc()
        p.retain(b)
        p.release(b)
        assert p.held_count() == 1          # still held by one reader
        p.release(b)
        assert p.held_count() == 0 and p.free_count() == 3
        with pytest.raises(RuntimeError):
            p.retain(b)                      # retaining a free block

    def test_null_block_never_allocated(self):
        p = BlockPool(8, 2)
        got = {p.alloc() for _ in range(p.capacity)}
        assert NULL_BLOCK not in got
        with pytest.raises(ValueError):
            p.retain(NULL_BLOCK)

    def test_prefix_register_lookup_evict_lru(self):
        p = BlockPool(4, 2)                  # 3 usable blocks
        toks = np.arange(6, dtype=np.int32)
        hashes = prefix_block_hashes(toks, 2)
        assert len(hashes) == 3
        blocks = [p.alloc() for _ in range(3)]
        for h, b in zip(hashes, blocks):
            assert p.register(h, b)
        # a second registration of the same hash leaves the cache alone
        assert not p.register(hashes[0], blocks[1])
        for b in blocks:
            p.release(b)                     # refcount 0 -> evictable
        assert p.free_count() == 0 and p.usable_free_count() == 3
        hit = p.lookup(hashes)               # revives all three
        assert hit == blocks
        for b in hit:
            p.release(b)
        # pool pressure reclaims the LRU-released cached block first
        fresh = p.alloc()
        assert fresh == blocks[0] and p.evictions == 1
        # its hash is gone, and a chain lookup stops at the first miss
        assert p.lookup(hashes) == []
        p.release(fresh)
        p.check_invariant()

    def test_chain_hashes_depend_on_prefix(self):
        a = prefix_block_hashes(np.array([1, 2, 3, 4], np.int32), 2)
        b = prefix_block_hashes(np.array([9, 9, 3, 4], np.int32), 2)
        assert a[0] != b[0]
        assert a[1] != b[1]        # same block tokens, different prefix

    def test_partial_trailing_block_never_hashed(self):
        assert len(prefix_block_hashes(np.arange(7), 2)) == 3
        assert len(prefix_block_hashes(np.arange(1), 2)) == 0

    def test_flush_cache_drops_registrations_keeps_held(self):
        """The hot-reload flush: every registration drops (no future
        lookup reuses stale K/V), evictable blocks return to the free
        list, held shared blocks keep their refcounts for in-flight
        readers — and free straight to the free list on release."""
        p = BlockPool(6, 2)                  # 5 usable blocks
        h1, h2 = prefix_block_hashes(np.arange(4, dtype=np.int32), 2)
        held = p.alloc()
        p.register(h1, held)
        ev = p.alloc()
        p.register(h2, ev)
        p.release(ev)                        # refcount 0 -> evictable
        assert p.flush_cache() == 2
        assert p.cached_count() == 0
        assert p.lookup([h1, h2]) == []
        assert p.free_count() == 4           # the evictable one freed
        assert p.held_count() == 1           # in-flight reader intact
        p.check_invariant(tables=[[held]])
        p.release(held)                      # unregistered -> free, not
        assert p.free_count() == 5           # evictable
        p.check_invariant(tables=[])

    def test_reset_clears_everything(self):
        p = BlockPool(4, 2)
        b = p.alloc()
        p.register(prefix_block_hashes(np.arange(2), 2)[0], b)
        p.reset()
        assert p.free_count() == 3 and p.cached_count() == 0
        p.check_invariant(tables=[])

    def test_invariant_catches_seeded_leak(self):
        p = BlockPool(4, 2)
        b = p.alloc()
        with pytest.raises(AssertionError, match="diverge"):
            p.check_invariant(tables=[])     # held block in no table
        p.check_invariant(tables=[[b]])

    def test_blocks_for_tokens(self):
        assert blocks_for_tokens(1, 8) == 1
        assert blocks_for_tokens(8, 8) == 1
        assert blocks_for_tokens(9, 8) == 2


# ----------------------------------------------------------------------
class TestGreedyParity:
    @pytest.mark.slow
    def test_mixed_lengths_match_dense_reference(self, spec, dense_spec):
        # random mixed lengths + the degenerate/bucket-edge prompts the
        # tier-1 growth test drops for wall budget (1 token, exact
        # bucket edges 3 -> 4 and 16 -> 16)
        prompts = mixed_prompts(6) + [
            np.arange(L, dtype=np.int32) % CFG.vocab_size
            for L in (1, 3, 16)]
        with make_server(spec, num_blocks=64) as srv:
            handles = [srv.submit(p, max_new_tokens=8) for p in prompts]
            got = [h.result(timeout=60) for h in handles]
        assert got == [ref_tokens(dense_spec, p, 8) for p in prompts]

    def test_table_growth_across_buckets(self, spec, dense_spec):
        """Prompts landing in every pow2 prefill bucket, each decoding
        across at least one block boundary — growth at the step
        boundary keeps tokens identical to the dense reference.
        (The full per-bucket matrix incl. the degenerate 1-token and
        exact-bucket-edge prompts lives in the slow-tier mixed-lengths
        test; this keeps one spanning set inside the tier-1 budget.)"""
        lengths = [2, 5, 9, 17]                # buckets 2, 8, 16, 32
        prompts = [np.arange(L, dtype=np.int32) % CFG.vocab_size
                   for L in lengths]
        with make_server(spec, num_blocks=64) as srv:
            handles = [srv.submit(p, max_new_tokens=10) for p in prompts]
            got = [h.result(timeout=60) for h in handles]
        assert got == [ref_tokens(dense_spec, p, 10) for p in prompts]

    def test_pool_drains_clean_after_traffic(self, spec):
        srv = make_server(spec, num_blocks=64)
        hs = [srv.submit(p, max_new_tokens=6) for p in mixed_prompts(8)]
        for h in hs:
            h.result(timeout=60)
        srv.shutdown()
        st = srv.pool.stats()
        assert st["held"] == 0, st
        assert wait_uncommitted(srv) == 0
        srv.pool.check_invariant(tables=[])


# ----------------------------------------------------------------------
class TestPrefixCache:
    @pytest.mark.slow
    def test_repeat_prefix_hits_and_matches(self, spec, dense_spec):
        sys_prompt = (np.arange(17, dtype=np.int32) * 3) % CFG.vocab_size
        with make_server(spec) as srv:
            a = srv.submit(sys_prompt, max_new_tokens=6).result(timeout=60)
            b = srv.submit(sys_prompt, max_new_tokens=6).result(timeout=60)
        ref = ref_tokens(dense_spec, sys_prompt, 6)
        assert a == ref and b == ref
        rec = srv.metrics.to_record()["paged"]
        # 17 tokens = 2 full blocks of 8; the repeat reuses both (reuse
        # is capped at (L-1)//BS so >= 1 suffix token still prefills)
        assert rec["prefix_hit_rate"] > 0
        assert rec["prefix_blocks_hit"] == 2

    def test_hit_skips_prefill_to_suffix_bucket(self, spec):
        """A prefix hit dispatches the SUFFIX bucket (near-one-decode-
        step TTFT on repeats), not the full-prompt bucket — observable
        in the prefill shapes the server actually ran."""
        prompt = (np.arange(17, dtype=np.int32) * 5) % CFG.vocab_size
        with make_server(spec) as srv:
            srv.submit(prompt, max_new_tokens=2).result(timeout=60)
            before = set(srv._shapes_seen)
            srv.submit(prompt, max_new_tokens=2).result(timeout=60)
            new_shapes = srv._shapes_seen - before
        # 17 tokens cold runs bucket 32; the repeat reuses 2 blocks and
        # prefills only its 1-token suffix -> the ONLY new prefill
        # shape is bucket 1 ("hist" marks prefill signatures; shapes
        # are keyed (role, sig) since the speculative tier, because
        # draft and target share io signatures)
        new_buckets = {dict(sig)["tokens"][0]
                       for role, sig in new_shapes
                       if role == "target" and "hist" in dict(sig)}
        assert new_buckets == {1}

    @pytest.mark.slow
    def test_refcount_churn_interleaved_admit_complete(
            self, spec, dense_spec):
        """Many concurrent requests sharing one prefix, admitted and
        retired in interleaved waves through 3 slots: the shared
        blocks' refcounts drain to exactly zero, under the every-step
        invariant check."""
        shared = (np.arange(16, dtype=np.int32) * 7) % CFG.vocab_size
        rng = np.random.default_rng(3)
        prompts = [np.concatenate([shared,
                                   rng.integers(0, CFG.vocab_size,
                                                int(rng.integers(1, 6)))
                                   .astype(np.int32)])
                   for _ in range(10)]
        budgets = [int(rng.integers(1, 8)) for _ in prompts]
        with make_server(spec, max_slots=3, num_blocks=64) as srv:
            handles = [srv.submit(p, max_new_tokens=n)
                       for p, n in zip(prompts, budgets)]
            got = [h.result(timeout=120) for h in handles]
        assert got == [ref_tokens(dense_spec, p, n)
                       for p, n in zip(prompts, budgets)]
        st = srv.pool.stats()
        assert st["held"] == 0, st
        srv.pool.check_invariant(tables=[])

    def test_update_model_flushes_prefix_cache(self, spec, dense_spec,
                                               gpt_sd):
        """A hot reload must invalidate the prefix cache: the cached
        blocks' K/V were computed with the OLD weights, so a repeated
        prompt after update_model() re-prefills from scratch (zero
        hits) and its tokens match the NEW model's reference — no
        silent old/new mixing."""
        import jax.numpy as jnp
        prompt = (np.arange(17, dtype=np.int32) * 3) % CFG.vocab_size
        with make_server(spec) as srv:
            srv.submit(prompt, max_new_tokens=4).result(timeout=60)
            assert srv.pool.cached_count() > 0   # 2 full blocks cached
            old = gpt_sd._arrays["wte"]
            try:
                gpt_sd._arrays["wte"] = old + jnp.asarray(0.5)
                srv.update_model()
                after = srv.submit(prompt,
                                   max_new_tokens=4).result(timeout=60)
                want = ref_tokens(dense_spec, prompt, 4)
            finally:
                gpt_sd._arrays["wte"] = old
                srv.update_model()
        assert after == want        # the reference reads live params too
        rec = srv.metrics.to_record()["paged"]
        # the repeat ran AFTER the flush: nothing to hit
        assert rec["prefix_blocks_hit"] == 0
        assert srv.metrics.counters["prefix_cache_flushes"] >= 1
        srv.pool.check_invariant(tables=[])

    def test_disabled_cache_never_hits(self, spec):
        prompt = (np.arange(17, dtype=np.int32) * 3) % CFG.vocab_size
        with make_server(spec, prefix_cache=False) as srv:
            srv.submit(prompt, max_new_tokens=2).result(timeout=60)
            srv.submit(prompt, max_new_tokens=2).result(timeout=60)
        rec = srv.metrics.to_record()["paged"]
        assert rec["prefix_hit_rate"] == 0.0
        assert rec["cached_blocks"] == 0


# ----------------------------------------------------------------------
class TestPoolPressure:
    def test_exhaustion_sheds_typed_not_crash(self, spec):
        """A pool too small for the offered worst-case load sheds at
        SUBMIT with a retry_after_s hint — no worker crash — and the
        shed client's retry succeeds once completions release their
        commitment."""
        # capacity 8 blocks; each request commits ceil((12+8)/8) = 3
        # blocks worst-case -> two fit, the third sheds. start=False
        # keeps the accounting deterministic (nothing completes early)
        srv = make_server(spec, max_slots=4, num_blocks=9, start=False)
        try:
            p = np.arange(12, dtype=np.int32)
            h1 = srv.submit(p, max_new_tokens=8)
            h2 = srv.submit(p + 1, max_new_tokens=8)
            with pytest.raises(PoolExhaustedError) as ei:
                srv.submit(p + 2, max_new_tokens=8)
            assert ei.value.retry_after_s > 0
            srv.start()
            assert h1.result(timeout=60) and h2.result(timeout=60)
            # completions released their commitment: the retry now fits
            assert wait_uncommitted(srv) == 0
            h3 = srv.submit(p + 2, max_new_tokens=8)
            assert h3.result(timeout=60)
        finally:
            srv.shutdown()
        assert srv.metrics.counters["requests_shed"] >= 1
        assert wait_uncommitted(srv) == 0

    @pytest.mark.slow
    def test_shed_clients_retrying_all_complete(self, spec, dense_spec):
        """Admission-pressure end-to-end: 8 clients against a pool
        that holds ~3 requests' worst case, each retrying on typed
        shed with the server's own backoff hint — everything completes
        with reference tokens and the pool drains clean."""
        prompts = mixed_prompts(8, seed=5, max_len=8)
        with make_server(spec, max_slots=3, num_blocks=7) as srv:
            handles = []
            deadline = time.monotonic() + 120
            for p in prompts:
                while True:
                    assert time.monotonic() < deadline, "retry wedged"
                    try:
                        handles.append(srv.submit(p, max_new_tokens=4))
                        break
                    except PoolExhaustedError as e:
                        time.sleep(min(e.retry_after_s, 0.05))
            got = [h.result(timeout=120) for h in handles]
        assert got == [ref_tokens(dense_spec, p, 4) for p in prompts]
        assert srv.pool.stats()["held"] == 0
        assert wait_uncommitted(srv) == 0

    def test_failed_submit_rolls_back_commitment(self, spec):
        with make_server(spec, max_slots=4, num_blocks=9) as srv:
            with pytest.raises(ValueError):     # out-of-vocab prompt
                srv.submit(np.asarray([999]), max_new_tokens=4)
            assert srv._committed == 0

    def test_invalid_request_raises_valueerror_under_pressure(self, spec):
        """Permanent errors stay permanent under pool pressure: with
        the pool fully committed, an invalid request raises ValueError
        (validation runs BEFORE the block commitment) — not a
        retryable PoolExhaustedError telling the client to back off
        and resubmit something that can never run — and is not
        counted as shed."""
        srv = make_server(spec, max_slots=4, num_blocks=9, start=False)
        try:
            p = np.arange(12, dtype=np.int32)
            srv.submit(p, max_new_tokens=8)
            srv.submit(p + 1, max_new_tokens=8)     # 6 of 8 committed
            with pytest.raises(PoolExhaustedError):
                srv.submit(p + 2, max_new_tokens=8)  # valid -> typed shed
            shed = srv.metrics.counters["requests_shed"]
            with pytest.raises(ValueError):          # empty prompt
                srv.submit(np.asarray([], np.int32), 4)
            with pytest.raises(ValueError):          # out-of-vocab
                srv.submit(np.asarray([CFG.vocab_size], np.int32), 4)
            with pytest.raises(ValueError):          # zero budget
                srv.submit(np.asarray([1], np.int32), 0)
            with pytest.raises(ValueError):          # over-long prompt
                srv.submit(np.arange(MSL, dtype=np.int32) % CFG.vocab_size,
                           4)
            assert srv.metrics.counters["requests_shed"] == shed
            assert srv._committed == 6
        finally:
            srv.shutdown()


# ----------------------------------------------------------------------
class TestLifecycleRelease:
    def test_cancel_releases_blocks_once(self, spec):
        with make_server(spec) as srv:
            h = srv.submit(np.arange(9, dtype=np.int32),
                           max_new_tokens=30)
            next(iter(h.tokens(timeout=30)))      # it is in flight
            h.cancel()
            h.result(timeout=30)                  # partial token list
        assert srv.pool.stats()["held"] == 0
        assert wait_uncommitted(srv) == 0
        srv.pool.check_invariant(tables=[])

    def test_deadline_expiry_releases_blocks(self, spec):
        with make_server(spec) as srv:
            h = srv.submit(np.arange(6, dtype=np.int32),
                           max_new_tokens=25, timeout_ms=30.0)
            try:
                h.result(timeout=60)
            except Exception:
                pass     # timed out or not — either way nothing leaks
        assert srv.pool.stats()["held"] == 0
        assert wait_uncommitted(srv) == 0

    @pytest.mark.chaos
    def test_crash_requeue_releases_blocks_exactly_once(
            self, spec, dense_spec):
        """Kill the decode worker mid-generation: the pool hard-resets
        (every held block back exactly once, the prefix cache — which
        addresses now-garbage slab rows — dropped wholesale), the
        in-flight requests requeue at prefill exactly once, tokens
        still match the reference, and the accounting invariant holds
        on the respawned worker's every step."""
        prompts = mixed_prompts(4, seed=7)
        srv = make_server(spec, start=False,
                          resilience=ResilienceConfig(
                              worker_backoff_base_s=0.01,
                              worker_backoff_max_s=0.05))
        real = srv._decode_disp
        state = {"calls": 0, "fired": False}

        class CrashOnce:
            def __call__(self, *args):
                state["calls"] += 1
                if not state["fired"] and state["calls"] > 2:
                    state["fired"] = True
                    raise RuntimeError("chaos: decode worker dies")
                return real(*args)

        srv._decode_disp = CrashOnce()
        try:
            srv.start()
            handles = [srv.submit(p, max_new_tokens=8) for p in prompts]
            got = [h.result(timeout=120) for h in handles]
        finally:
            srv.shutdown()
        assert state["fired"]
        assert got == [ref_tokens(dense_spec, p, 8) for p in prompts]
        assert srv.metrics.counters["worker_restarts"] >= 1
        assert srv.metrics.counters["requests_requeued"] >= 1
        assert srv.pool.stats()["held"] == 0, srv.pool.stats()
        assert wait_uncommitted(srv) == 0
        srv.pool.check_invariant(tables=[])


# ----------------------------------------------------------------------
class TestTensorParallel:
    @pytest.mark.slow
    def test_tp2_bit_identical_greedy(self, spec, dense_spec):
        """gpt served with tp=2 over the virtual CPU mesh produces the
        dense single-chip reference tokens, with sharded params + KV
        slabs and ZERO traffic compiles after the sharded AOT warmup."""
        import jax
        if len(jax.devices()) < 2:
            pytest.skip("needs >= 2 devices")
        prompts = mixed_prompts(5, seed=9)
        with make_server(spec, tp=2, num_blocks=64, warmup=True) as srv:
            assert srv._strategy is not None
            handles = [srv.submit(p, max_new_tokens=8) for p in prompts]
            got = [h.result(timeout=120) for h in handles]
            assert srv.metrics.counters["compiles"] == 0
        assert got == [ref_tokens(dense_spec, p, 8) for p in prompts]

    def test_tp_must_divide_heads(self, spec):
        with pytest.raises(ValueError, match="num_heads"):
            make_server(spec, tp=3)            # 2 heads % 3 != 0


# ----------------------------------------------------------------------
class TestMetricsAndReports:
    def test_paged_record_cold_start_no_nans(self):
        rec = PagedMetrics(4, 16, 8).to_record()
        p = rec["paged"]
        for k, v in p.items():
            assert v == v, f"NaN in cold paged record: {k}"
        assert p["pool_occupancy"] == 0.0
        assert p["prefix_hit_rate"] == 0.0
        assert p["blocks_per_request"] == 0.0

    def test_fold_serving_exports_paged_and_low_sample(self):
        from deeplearning4j_tpu.monitor.registry import MetricsRegistry
        m = PagedMetrics(4, 16, 8)
        m.observe_pool(4, stats={"cached": 1, "evictions": 0})
        m.observe_prefix(True, 2)
        m.observe_ttft(5.0)                  # 1 sample -> low_sample
        reg = MetricsRegistry()
        reg.fold_serving(m)
        text = reg.to_prometheus_text()
        for needle in ("dl4j_serving_pool_blocks",
                       "dl4j_serving_pool_occupancy_ratio",
                       "dl4j_serving_prefix_hit_rate",
                       "dl4j_serving_blocks_per_request",
                       "dl4j_serving_pool_cached_blocks",
                       "dl4j_serving_latency_count",
                       "dl4j_serving_latency_low_sample"):
            assert needle in text, needle
        assert "nan" not in text.lower()

    def test_report_renders_paged_panel(self, spec):
        from deeplearning4j_tpu.ui.report import render_report
        from deeplearning4j_tpu.ui.stats import StatsStorage
        storage = StatsStorage()
        with make_server(spec, stats_storage=storage) as srv:
            srv.generate(np.arange(9, dtype=np.int32), max_new_tokens=4)
        html = render_report(storage)
        assert "paged KV" in html
        assert "prefix hit" in html

    @pytest.mark.slow
    def test_memory_report_block_accounting(self, spec):
        with make_server(spec, num_blocks=32) as srv:
            srv.submit(np.arange(9, dtype=np.int32),
                       max_new_tokens=2).result(timeout=60)
            rep = srv.memory_report()
        assert rep["num_blocks"] == 31
        assert rep["block_size"] == BS
        assert rep["kv_bytes_per_block"] > 0
        assert rep["blocks_free"] + rep["blocks_held"] \
            + rep["blocks_evictable"] == 31


# ----------------------------------------------------------------------
class TestSpeculativeAndQuant:
    """ISSUE 18 on the paged tier: speculation never changes greedy
    tokens (rejected tails roll back KV write positions without
    touching committed blocks — ``debug_leaks=True`` audits the pool
    invariant after every scheduler step), and int8 KV multiplies the
    block pool's token capacity at equal slab bytes."""

    def test_paged_speculation_bit_identical(self, spec, dense_spec):
        dcfg = GPTConfig(vocab_size=64, hidden_size=16, num_layers=1,
                         num_heads=2, intermediate_size=32,
                         max_seq_len=32)
        draft = gpt_generative_spec(
            build_gpt(dcfg, batch=2, seq_len=8, seed=3), dcfg)
        prompts = mixed_prompts(6, seed=31)
        budgets = [4 + i % 5 for i in range(6)]
        with make_server(spec, draft_spec=draft, speculate_k=4) as srv:
            hs = [srv.submit(p, n) for p, n in zip(prompts, budgets)]
            got = [h.result(timeout=120) for h in hs]
            rec = srv.metrics.to_record()["generative"]
            assert not wait_uncommitted(srv)    # every block released
        for p, n, g in zip(prompts, budgets, got):
            assert g == ref_tokens(dense_spec, p, n)
        assert rec["spec_rounds"] >= 1          # speculation actually ran

    def test_int8_kv_multiplies_pool_capacity_equal_bytes(self, gpt_sd,
                                                          dense_spec):
        budget = 1 << 20
        f32 = make_server(gpt_paged_spec(gpt_sd, CFG),
                          kv_hbm_bytes=budget)
        q = make_server(gpt_paged_spec(gpt_sd, CFG,
                                       quantize_weights=True,
                                       quantize_kv=True),
                        kv_hbm_bytes=budget)
        try:
            nf = f32.metrics.to_record()["paged"]["num_blocks"]
            nq = q.metrics.to_record()["paged"]["num_blocks"]
            assert nq >= 1.9 * nf, (nq, nf)     # the acceptance bar
            # the quantized tier still serves a full generation
            p = np.asarray([5, 9, 2], np.int32)
            got = q.submit(p, max_new_tokens=6).result(timeout=120)
            assert len(got) == 6
        finally:
            f32.shutdown()
            q.shutdown()
