"""Parallelism tests on the virtual 8-device CPU mesh (SURVEY.md §4:
multi-device behavior must be CI-testable without hardware; numerics must
match the single-device run)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig
from deeplearning4j_tpu.learning.updaters import Adam, Sgd
from deeplearning4j_tpu.nn import (
    DenseLayer, InputType, MultiLayerNetwork, NeuralNetConfiguration,
    OutputLayer)
from deeplearning4j_tpu.parallel import (
    DATA_AXIS, MODEL_AXIS, SEQ_AXIS, DeviceMesh, ParallelInference,
    ParallelTrainer, data_and_tensor_parallel, data_parallel,
    ring_attention, ulysses_attention)


def test_mesh_creation_and_axes():
    m = DeviceMesh.create(data=4, model=2)
    assert m.n_devices == 8
    assert m.axis_size("data") == 4
    assert m.axis_size("model") == 2
    assert m.axis_size("missing") == 1


def test_mesh_wrong_size_raises():
    with pytest.raises(ValueError, match="devices"):
        DeviceMesh.create(data=5)


def _net(seed=7):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed)
            .updater(Sgd(learning_rate=0.1))
            .list()
            .layer(DenseLayer(n_out=32, activation="tanh"))
            .layer(OutputLayer(n_out=4))
            .set_input_type(InputType.feed_forward(8))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(n=64, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 8)).astype(np.float32)
    y = (X[:, 0] > 0).astype(int) + 2 * (X[:, 1] > 0).astype(int)
    return X, np.eye(4, dtype=np.float32)[y], y


class _It:
    def __init__(self, X, Y, b):
        self.X, self.Y, self.b = X, Y, b

    def reset(self): ...

    def __iter__(self):
        for i in range(0, len(self.X), self.b):
            yield self.X[i:i + self.b], self.Y[i:i + self.b]


def test_data_parallel_matches_single_device():
    X, Y, _ = _data()
    net_sp = _net()
    net_dp = _net()
    h_sp = net_sp.fit(X, Y, epochs=3, batch_size=32)
    mesh = DeviceMesh.create(data=8)
    trainer = ParallelTrainer(net_dp, data_parallel(mesh))
    h_dp = trainer.fit(_It(X, Y, 32), epochs=3)
    # same data, same seed, same updater → numerically equal training
    np.testing.assert_allclose(h_sp.final_loss(), h_dp.final_loss(),
                               rtol=1e-5)
    for n, p in net_sp.params().items():
        np.testing.assert_allclose(p, net_dp.params()[n], rtol=1e-4,
                                   atol=1e-5, err_msg=n)


def test_data_parallel_params_replicated_batch_sharded():
    X, Y, _ = _data()
    net = _net()
    mesh = DeviceMesh.create(data=8)
    trainer = ParallelTrainer(net, data_parallel(mesh))
    trainer.shard_params()
    w = net.samediff._arrays["layer0_dense_W"]
    assert len(w.sharding.device_set) == 8
    assert w.sharding.is_fully_replicated


def test_tensor_parallel_training_matches_single_device():
    X, Y, _ = _data()
    net_sp = _net()
    net_tp = _net()
    h_sp = net_sp.fit(X, Y, epochs=3, batch_size=32)
    mesh = DeviceMesh.create(data=2, model=4)
    trainer = ParallelTrainer(net_tp, data_and_tensor_parallel(mesh))
    h_tp = trainer.fit(_It(X, Y, 32), epochs=3)
    np.testing.assert_allclose(h_sp.final_loss(), h_tp.final_loss(),
                               rtol=1e-4)
    for n, p in net_sp.params().items():
        np.testing.assert_allclose(p, net_tp.params()[n], rtol=1e-4,
                                   atol=1e-5, err_msg=n)


def test_tensor_parallel_weights_actually_sharded():
    net = _net()
    mesh = DeviceMesh.create(data=2, model=4)
    trainer = ParallelTrainer(net, data_and_tensor_parallel(mesh))
    trainer.shard_params()
    w = net.samediff._arrays["layer0_dense_W"]
    assert not w.sharding.is_fully_replicated
    # sharded over the model axis on the output dim
    shard_shape = w.sharding.shard_shape(w.shape)
    assert shard_shape == (8, 32 // 4)


def test_parallel_inference_matches_local():
    net = _net()
    X, Y, _ = _data(32)
    net.fit(X, Y, epochs=2, batch_size=32)
    local = net.output(X).to_numpy()
    mesh = DeviceMesh.create(data=8)
    pi = ParallelInference(net, data_parallel(mesh))
    dist = pi.output(X).to_numpy()
    np.testing.assert_allclose(local, dist, rtol=1e-5, atol=1e-6)


# ---- sequence parallelism -------------------------------------------------

def _qkv(b=2, t=32, h=4, d=8, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: rng.normal(size=(b, t, h, d)).astype(np.float32)
    return mk(), mk(), mk()


def _reference_attention(q, k, v, causal=False):
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = np.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        t = q.shape[1]
        mask = np.tril(np.ones((t, t), bool))
        s = np.where(mask[None, None], s, -np.inf)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_exact(causal):
    q, k, v = _qkv()
    mesh = DeviceMesh.create(seq=8)
    out = np.asarray(ring_attention(jnp.asarray(q), jnp.asarray(k),
                                    jnp.asarray(v), mesh, causal=causal))
    ref = _reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_exact(causal):
    q, k, v = _qkv(h=8)
    mesh = DeviceMesh.create(seq=8)
    out = np.asarray(ulysses_attention(jnp.asarray(q), jnp.asarray(k),
                                       jnp.asarray(v), mesh, causal=causal))
    ref = _reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


def test_ring_attention_output_stays_sharded():
    q, k, v = _qkv()
    mesh = DeviceMesh.create(seq=8)
    out = ring_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), mesh)
    assert not out.sharding.is_fully_replicated
    assert out.sharding.shard_shape(out.shape)[1] == q.shape[1] // 8


def test_ulysses_rejects_bad_head_count():
    q, k, v = _qkv(h=4)  # 4 heads on an 8-way axis
    mesh = DeviceMesh.create(seq=8)
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), mesh)


def test_collectives_inside_shard_map():
    from functools import partial
    try:
        from jax import shard_map
    except ImportError:                       # older jax
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    from deeplearning4j_tpu.parallel import collectives as C
    mesh = DeviceMesh.create(data=8)
    x = jnp.arange(8.0)

    @partial(shard_map, mesh=mesh.mesh, in_specs=P("data"), out_specs=P("data"))
    def f(x):
        return C.all_reduce_sum(x, "data")

    np.testing.assert_allclose(np.asarray(f(x)), np.full(8, 28.0))


# ---- regression tests for review findings ----

def test_ring_attention_bf16_accumulates_f32():
    q, k, v = _qkv(t=64)
    mesh = DeviceMesh.create(seq=8)
    qb, kb, vb = (jnp.asarray(a, jnp.bfloat16) for a in (q, k, v))
    out = ring_attention(qb, kb, vb, mesh)
    assert out.dtype == jnp.bfloat16
    ref = _reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out, np.float32), ref,
                               rtol=0.05, atol=0.05)


def test_parallel_inference_preserves_tp_sharding():
    net = _net()
    mesh = DeviceMesh.create(data=2, model=4)
    strategy = data_and_tensor_parallel(mesh)
    ParallelTrainer(net, strategy).shard_params()
    sd = net.samediff
    before = sd._arrays["layer0_dense_W"].sharding
    assert not before.is_fully_replicated
    pi = ParallelInference(net.samediff, strategy)
    X, _, _ = _data(16)
    pi.output(X, output_names=["output"])
    # TP sharding survives inference — params were NOT forcibly replicated
    assert sd._arrays["layer0_dense_W"].sharding == before


def test_global_pooling_rejects_ff_input():
    from deeplearning4j_tpu.nn import GlobalPoolingLayer
    conf = (NeuralNetConfiguration.builder()
            .seed(1).updater(Sgd(learning_rate=0.1))
            .list()
            .layer(DenseLayer(n_out=10))
            .layer(GlobalPoolingLayer())
            .layer(OutputLayer(n_out=5))
            .set_input_type(InputType.feed_forward(4))
            .build())
    with pytest.raises(ValueError, match="cnn or rnn"):
        MultiLayerNetwork(conf).init()


class TestTransformerTPRules:
    def test_gpt_naming_covered(self):
        """Round-4 Weak #5: attention qkv/proj + embeddings must get
        Megatron specs, not silent replication."""
        import jax
        from deeplearning4j_tpu.parallel import (
            DeviceMesh, megatron_data_and_tensor_parallel)
        from deeplearning4j_tpu.zoo.gpt import GPT_TINY, build_gpt
        sd = build_gpt(GPT_TINY, batch=2, seq_len=8)
        mesh = DeviceMesh.create(devices=jax.devices()[:4], data=2, model=2)
        st = megatron_data_and_tensor_parallel(mesh, sd)
        spec = lambda n: tuple(st.param_spec(
            n, len(np.shape(sd._arrays[n]))))
        assert spec("h0/attn/qkv/kernel") == (None, "model")
        assert spec("h0/attn/proj/kernel") == ("model", None)
        assert spec("h0/mlp/fc/kernel") == (None, "model")
        assert spec("h0/mlp/proj/kernel") == ("model", None)
        assert spec("wte") == ("model", None)
        assert spec("h0/ln_1/gamma") == ()        # replicated

    def test_gpt_tiny_trains_with_megatron_tp(self):
        """GPT through the GSPMD path with the full Megatron layout:
        numerics equal to single-device training."""
        import jax
        from deeplearning4j_tpu.autodiff import TrainingConfig
        from deeplearning4j_tpu.dataset import DeviceCachedIterator
        from deeplearning4j_tpu.learning.updaters import Sgd
        from deeplearning4j_tpu.parallel import (
            DeviceMesh, ParallelTrainer, megatron_data_and_tensor_parallel)
        from deeplearning4j_tpu.zoo.gpt import GPT_TINY, build_gpt

        def make():
            sd = build_gpt(GPT_TINY, batch=4, seq_len=8)
            sd.training_config = TrainingConfig(
                updater=Sgd(0.05),
                data_set_feature_mapping=["input_ids"],
                data_set_label_mapping=["targets"])
            return sd
        rng = np.random.default_rng(0)
        ids = rng.integers(0, GPT_TINY.vocab_size, (8, 8)).astype(np.int32)
        tgt = rng.integers(0, GPT_TINY.vocab_size, (8, 8)).astype(np.int32)

        sd1 = make()
        it = DeviceCachedIterator([ids], [tgt], batch_size=4)
        sd1.fit(it, epochs=2)
        w1 = np.asarray(sd1.get_arr_for_var("wte").data)

        sd2 = make()
        mesh = DeviceMesh.create(devices=jax.devices()[:4], data=2,
                                 model=2)
        tr = ParallelTrainer(sd2, megatron_data_and_tensor_parallel(
            mesh, sd2))
        it2 = DeviceCachedIterator([ids], [tgt], batch_size=4)
        tr.fit(it2, epochs=2)
        w2 = np.asarray(sd2.get_arr_for_var("wte").data)
        np.testing.assert_allclose(w1, w2, rtol=2e-4, atol=2e-5)


def test_batched_inference_oversized_submit_single_shape():
    """Regression (round-4 weak #7): a submit larger than max_batch_size
    must slice into fixed-shape dispatches, never produce a new padded
    shape on the serving hot path."""
    import numpy as np
    from deeplearning4j_tpu.parallel.trainer import BatchedParallelInference
    from deeplearning4j_tpu.learning.updaters import Sgd
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration, OutputLayer)
    conf = (NeuralNetConfiguration.builder().seed(0).updater(Sgd(0.1))
            .list().layer(DenseLayer(n_out=8, activation="relu"))
            .layer(OutputLayer(n_out=3, loss_function="MCXENT"))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()

    shapes_seen = set()
    bpi = BatchedParallelInference(net, max_batch_size=8, max_wait_ms=5)
    inner_output = bpi._inner.output

    def spy_output(x):
        shapes_seen.add(tuple(np.asarray(x).shape))
        return inner_output(x)

    bpi._inner.output = spy_output
    real_output = net.output
    try:
        x_big = np.random.RandomState(0).rand(21, 4).astype(np.float32)
        got = bpi.submit(x_big).result(timeout=30)
        assert got.shape == (21, 3)
        # direct single-model output for comparison
        want = np.asarray(real_output(x_big).data)
        np.testing.assert_allclose(got, want, atol=1e-5)
        # every dispatch had the ONE fixed shape
        assert shapes_seen == {(8, 4)}, shapes_seen
    finally:
        bpi.close()
