"""Multi-host runtime: initialize() no-op path + elastic restart.

The elastic test mirrors the reference's FailureTestingListener
methodology (inject a crash at a chosen point) combined with the missing
recovery half: a NEW trainer over the same checkpoint dir resumes from
the latest checkpoint and finishes; final params match an uninterrupted
run exactly (deterministic resume).
"""
import numpy as np
import pytest

from deeplearning4j_tpu.parallel import multihost


def test_initialize_noop_without_coordinator(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("COORDINATOR_ADDRESS", raising=False)
    multihost.initialize()          # must not raise / not try to connect
    assert multihost.process_count() == 1
    assert multihost.is_coordinator()
    multihost.sync_global_devices("t")   # no-op single-process


def _make_model(seed=0):
    from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig
    from deeplearning4j_tpu.learning.updaters import Adam
    rng = np.random.RandomState(seed)
    sd = SameDiff()
    x = sd.placeholder("x", shape=(-1, 6))
    y = sd.placeholder("y", shape=(-1, 1))
    w = sd.var("w", value=(rng.randn(6, 1) * 0.1).astype(np.float32))
    loss = ((x.mmul(w) - y).square()).mean()
    loss.mark_as_loss()
    sd.training_config = TrainingConfig(
        updater=Adam(0.05), data_set_feature_mapping=["x"],
        data_set_label_mapping=["y"])
    return sd


def _data(seed=1):
    rng = np.random.RandomState(seed)
    X = rng.randn(64, 6).astype(np.float32)
    Y = (X @ rng.randn(6, 1)).astype(np.float32)
    return [(X[i:i + 16], Y[i:i + 16]) for i in range(0, 64, 16)]


class _Boom(RuntimeError):
    pass


def test_elastic_restart_resumes_and_matches(tmp_path):
    batches = _data()
    total_epochs = 6

    # uninterrupted baseline
    sd_ref = _make_model()
    ref_tr = multihost.ElasticTrainer(sd_ref, str(tmp_path / "ref"),
                                      every_n_epochs=1)
    ref_tr.run(batches, epochs=total_epochs)
    ref_w = np.asarray(sd_ref.get_arr_for_var("w").data)

    # crash after epoch 2 (checkpoint for epoch 2 already written)
    ckdir = str(tmp_path / "elastic")
    sd1 = _make_model()
    tr1 = multihost.ElasticTrainer(sd1, ckdir, every_n_epochs=1)

    def fault(epoch):
        if epoch == 2:
            raise _Boom("injected slice failure")

    with pytest.raises(_Boom):
        tr1.run(batches, epochs=total_epochs, fault_hook=fault)
    path, done = tr1.latest()
    assert done == 2 and path is not None

    # "relaunch": fresh process state, same checkpoint dir
    sd2 = _make_model()
    tr2 = multihost.ElasticTrainer(sd2, ckdir, every_n_epochs=1)
    losses = tr2.run(batches, epochs=total_epochs)
    assert len(losses) == total_epochs - 3      # epochs 3..5 only
    got_w = np.asarray(sd2.get_arr_for_var("w").data)
    np.testing.assert_allclose(got_w, ref_w, rtol=1e-5, atol=1e-6)

    # keep_last pruning
    import glob, os
    assert len(glob.glob(os.path.join(ckdir, "elastic_epoch_*.zip"))) <= 3


def test_elastic_fresh_run_no_checkpoint(tmp_path):
    sd = _make_model()
    tr = multihost.ElasticTrainer(sd, str(tmp_path / "fresh"))
    losses = tr.run(_data(), epochs=2)
    assert len(losses) == 2
    assert np.isfinite(losses).all()
