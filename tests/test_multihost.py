"""Multi-host runtime: initialize() no-op path + elastic restart.

The elastic test mirrors the reference's FailureTestingListener
methodology (inject a crash at a chosen point) combined with the missing
recovery half: a NEW trainer over the same checkpoint dir resumes from
the latest checkpoint and finishes; final params match an uninterrupted
run exactly (deterministic resume).
"""
import numpy as np
import pytest

from deeplearning4j_tpu.parallel import multihost


def test_initialize_noop_without_coordinator(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("COORDINATOR_ADDRESS", raising=False)
    multihost.initialize()          # must not raise / not try to connect
    assert multihost.process_count() == 1
    assert multihost.is_coordinator()
    multihost.sync_global_devices("t")   # no-op single-process


def _make_model(seed=0):
    from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig
    from deeplearning4j_tpu.learning.updaters import Adam
    rng = np.random.RandomState(seed)
    sd = SameDiff()
    x = sd.placeholder("x", shape=(-1, 6))
    y = sd.placeholder("y", shape=(-1, 1))
    w = sd.var("w", value=(rng.randn(6, 1) * 0.1).astype(np.float32))
    loss = ((x.mmul(w) - y).square()).mean()
    loss.mark_as_loss()
    sd.training_config = TrainingConfig(
        updater=Adam(0.05), data_set_feature_mapping=["x"],
        data_set_label_mapping=["y"])
    return sd


def _data(seed=1):
    rng = np.random.RandomState(seed)
    X = rng.randn(64, 6).astype(np.float32)
    Y = (X @ rng.randn(6, 1)).astype(np.float32)
    return [(X[i:i + 16], Y[i:i + 16]) for i in range(0, 64, 16)]


class _Boom(RuntimeError):
    pass


def test_elastic_restart_resumes_and_matches(tmp_path):
    batches = _data()
    total_epochs = 6

    # uninterrupted baseline
    sd_ref = _make_model()
    ref_tr = multihost.ElasticTrainer(sd_ref, str(tmp_path / "ref"),
                                      every_n_epochs=1)
    ref_tr.run(batches, epochs=total_epochs)
    ref_w = np.asarray(sd_ref.get_arr_for_var("w").data)

    # crash after epoch 2 (checkpoint for epoch 2 already written)
    ckdir = str(tmp_path / "elastic")
    sd1 = _make_model()
    tr1 = multihost.ElasticTrainer(sd1, ckdir, every_n_epochs=1)

    def fault(epoch):
        if epoch == 2:
            raise _Boom("injected slice failure")

    with pytest.raises(_Boom):
        tr1.run(batches, epochs=total_epochs, fault_hook=fault)
    path, done = tr1.latest()
    assert done == 2 and path is not None

    # "relaunch": fresh process state, same checkpoint dir
    sd2 = _make_model()
    tr2 = multihost.ElasticTrainer(sd2, ckdir, every_n_epochs=1)
    losses = tr2.run(batches, epochs=total_epochs)
    assert len(losses) == total_epochs - 3      # epochs 3..5 only
    got_w = np.asarray(sd2.get_arr_for_var("w").data)
    np.testing.assert_allclose(got_w, ref_w, rtol=1e-5, atol=1e-6)

    # keep_last pruning
    import glob, os
    assert len(glob.glob(os.path.join(ckdir, "elastic_epoch_*.zip"))) <= 3


def test_elastic_fresh_run_no_checkpoint(tmp_path):
    sd = _make_model()
    tr = multihost.ElasticTrainer(sd, str(tmp_path / "fresh"))
    losses = tr.run(_data(), epochs=2)
    assert len(losses) == 2
    assert np.isfinite(losses).all()


def test_strict_restore_mismatch_raises(tmp_path):
    """Round-4 Weak #6: a checkpoint that doesn't cover the live
    graph's parameters (renamed layer) must raise, not silently resume
    the uncovered parameter from fresh init."""
    import pytest

    ckdir = str(tmp_path / "mismatch")
    sd = _make_model()
    tr = multihost.ElasticTrainer(sd, ckdir, every_n_epochs=1)
    tr.run(_data(), epochs=1)

    sd2 = _make_model()
    sd2.rename_variable("w", "w_renamed")
    tr2 = multihost.ElasticTrainer(sd2, ckdir, every_n_epochs=1)
    with pytest.raises(ValueError, match="w_renamed"):
        tr2.run(_data(), epochs=2)
    # explicit opt-out resumes the matching subset
    losses = tr2.run(_data(), epochs=2, strict_restore=False)
    assert len(losses) == 1


def test_barrier_with_timeout_detects_hang():
    """Liveness: a barrier that never completes (dead peer) raises
    HostFailureError instead of blocking forever."""
    import time

    import pytest

    def hung_sync(tag):
        time.sleep(30)

    t0 = time.perf_counter()
    with pytest.raises(multihost.HostFailureError, match="epoch_0"):
        multihost.barrier_with_timeout("epoch_0", timeout=0.3,
                                       _sync_fn=hung_sync)
    assert time.perf_counter() - t0 < 5


def test_barrier_with_timeout_propagates_peer_error():
    import pytest

    def failing_sync(tag):
        raise RuntimeError("peer went away")

    with pytest.raises(multihost.HostFailureError, match="peer went away"):
        multihost.barrier_with_timeout("b", timeout=5, _sync_fn=failing_sync)


def test_barrier_completes_normally():
    calls = []
    multihost.barrier_with_timeout("ok", timeout=5,
                                   _sync_fn=lambda tag: calls.append(tag))
    assert calls == ["ok"]


_TWO_PROC_WORKER = r"""
import os, sys, json
proc_id = int(sys.argv[1]); port = sys.argv[2]; out = sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
# initialize() must run before anything touches the XLA backend — the
# package __init__ builds mesh helpers that do, so initialize first
# through the same code path, importing only the multihost module
import importlib.util
spec = importlib.util.spec_from_file_location(
    "mh_standalone",
    os.path.join(os.environ["PYTHONPATH"],
                 "deeplearning4j_tpu/parallel/multihost.py"))
mh = importlib.util.module_from_spec(spec)
spec.loader.exec_module(mh)
mh.initialize(coordinator_address=f"127.0.0.1:{port}",
              num_processes=2, process_id=proc_id)
from deeplearning4j_tpu.parallel import multihost
assert jax.process_count() == 2
assert jax.device_count() == 4          # 2 hosts x 2 local devices
from jax.experimental import multihost_utils
import numpy as np
gathered = multihost_utils.process_allgather(
    np.asarray([multihost.process_index()], np.int32))
multihost.barrier_with_timeout("handshake", timeout=60)
assert mh.initialize is not multihost.initialize  # same file, two loads
with open(out, "w") as fh:
    json.dump({"pid": proc_id,
               "is_coord": multihost.is_coordinator(),
               "gathered": np.asarray(gathered).ravel().tolist()}, fh)
"""


def test_two_process_distributed_cpu(tmp_path):
    """An ACTUAL 2-process jax.distributed run on CPU (round-4 Weak #6:
    initialize() had never been exercised with >1 process): both
    processes join the coordinator, see the global device view
    (2 hosts x 2 devices), allgather each other's ranks, and pass a
    liveness-checked barrier."""
    import json
    import os
    import socket
    import subprocess
    import sys

    with socket.socket() as s:       # free port
        s.bind(("127.0.0.1", 0))
        port = str(s.getsockname()[1])
    script = tmp_path / "worker.py"
    script.write_text(_TWO_PROC_WORKER)
    outs = [str(tmp_path / f"out_{i}.json") for i in range(2)]
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env["PYTHONPATH"] = os.getcwd()
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(i), port, outs[i]],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i in range(2)]
    try:
        for p in procs:
            stdout, _ = p.communicate(timeout=180)
            text = stdout.decode()
            if p.returncode != 0 and \
                    "aren't implemented on the CPU backend" in text:
                pytest.skip("this jax build has no multiprocess CPU "
                            "collectives (coordinator join itself is "
                            "exercised up to the allgather)")
            assert p.returncode == 0, text[-2000:]
    finally:
        for p in procs:
            p.kill()
    results = [json.load(open(o)) for o in outs]
    assert results[0]["is_coord"] is True
    assert results[1]["is_coord"] is False
    for r in results:
        assert r["gathered"] == [0, 1]
